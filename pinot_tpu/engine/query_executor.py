"""In-process query executor: tables of segments → BrokerResponse.

The round-1 equivalent of the reference's in-process test harness topology
(BaseQueriesTest.getBrokerResponse, pinot-core/src/test/.../BaseQueriesTest.java:126-207
— plan maker → per-segment operators → combine → broker reduce, no
networking). The cluster layer (broker/server processes over gRPC) builds on
exactly these pieces.

Per segment, the TPU path is tried first; UnsupportedQueryError falls back to
the host engine — mirroring BASELINE.json's "CPU path remains the default"
backend selection, inverted: TPU is the default here, host is the safety net.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..query.context import QueryContext
from ..query.parser.sql import SqlParseError, parse_sql
from ..spi.metrics import SERVER_METRICS, ServerMeter, ServerTimer
from ..spi.trace import TRACING, ServerQueryPhase
from .scheduler import GLOBAL_ACCOUNTANT
from ..segment.loader import ImmutableSegment
from ..spi.data_types import Schema
from .aggregation import UnsupportedQueryError, semantics_for
from .combine import (combine_aggregation, combine_group_by,
                      combine_selection, trim_group_by)
from ..ops.kernels import PackedOuts, fetch_packed_batch, unpack_outputs
from .executor import (BatchFamilyMismatch, TpuSegmentExecutor,
                       batch_family_key, dispatch_counters,
                       reset_dispatch_counters)
from .host_executor import HostSegmentExecutor
from .oom import HbmExhaustedError, with_oom_retry
from .pruner import SegmentPrunerService
from .reduce import BrokerReducer
from .results import (
    AggIntermediate,
    BrokerResponse,
    GroupByIntermediate,
    SelectionIntermediate,
)


def _estimate_bytes(inter) -> int:
    """Rough intermediate footprint for the accountant (reference samples
    real allocations via ThreadMXBean; here: container-size heuristics)."""
    from .results import GroupArrays

    if isinstance(inter, GroupArrays):
        # size from the columns; do NOT touch .groups (materializing the
        # dict is exactly the per-group cost the columnar path avoids)
        return (sum(k.nbytes for k in inter.key_cols)
                + sum(c.nbytes for comps in inter.state_cols for c in comps)
                + 64)
    if isinstance(inter, GroupByIntermediate):
        width = 1 + max((len(v) for v in inter.groups.values()), default=0)
        return 64 * width * len(inter.groups)
    if isinstance(inter, SelectionIntermediate):
        width = max(1, len(inter.columns))
        return 32 * width * len(inter.rows)
    if isinstance(inter, AggIntermediate):
        return 64 * max(1, len(inter.states))
    return 64


@dataclass
class Table:
    name: str
    schema: Schema
    segments: list[ImmutableSegment] = field(default_factory=list)


class QueryExecutor:
    """Executes SQL over registered tables. backend: "tpu" | "host" | "auto"
    (auto = tpu with host fallback per query shape)."""

    def __init__(self, backend: str = "auto", num_threads: int = 1):
        self.backend = backend
        self.tables: dict[str, Table] = {}
        self.tpu = TpuSegmentExecutor()
        self.host = HostSegmentExecutor()
        self.pruner = SegmentPrunerService()
        self.use_star_tree = True  # reference: useStarTree query option default true
        # >1: host-path segments run on a worker pool, the reference's
        # combine-operator fan-out (GroupByCombineOperator.java:54 runs one
        # task per segment on a shared executor)
        self.num_threads = max(1, int(num_threads))
        self._pool = None
        # cross-query coalescing rendezvous (engine/coalesce.py): shared
        # by every concurrent query through this executor
        from .coalesce import QueryCoalescer

        self.coalescer = QueryCoalescer()

    def _host_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=self.num_threads)
        return self._pool

    def add_table(self, schema: Schema, segments: list[ImmutableSegment], name: Optional[str] = None):
        """``segments`` is held BY REFERENCE when it is a list: realtime data
        managers mutate it in place as segments commit/rotate and queries see
        the live view (snapshotted per query). Segments predating schema
        columns are backfilled with virtual default columns on registration
        (reference: on-load default-column update — schema evolution)."""
        if not isinstance(segments, list):
            segments = list(segments)  # before iterating: may be a generator
        for seg in segments:
            if hasattr(seg, "apply_schema"):
                seg.apply_schema(schema)
        self.tables[name or schema.schema_name] = Table(
            name or schema.schema_name, schema, segments)
        # compile-free cold starts: pre-warm the table's top persisted
        # family executables (engine/aot_cache.py) so the first queries
        # after a restart skip XLA compiles. No-op unless
        # PINOT_TPU_AOT_CACHE_DIR is set; refusals fall back silently.
        from .aot_cache import enabled as aot_enabled, prewarm_table

        if aot_enabled():
            prewarm_table(name or schema.schema_name)

    def add_dimension_table(self, schema: Schema, segments: list,
                            name: Optional[str] = None) -> None:
        """Register a queryable table that ALSO serves LOOKUP joins
        (reference: TableConfig.isDimTable + DimensionTableDataManager —
        dim tables replicate fully and back the LOOKUP transform). The
        schema must declare primaryKeyColumns (single key)."""
        import numpy as np

        from .dim_tables import register_dimension_table

        self.add_table(schema, segments, name)
        if len(schema.primary_key_columns) != 1:
            raise ValueError("dimension tables need exactly one primary key")
        segs = self.tables[name or schema.schema_name].segments
        cols = {}
        for c in schema.column_names():
            parts = [np.asarray(s.get_values(c)) for s in segs]
            cols[c] = parts[0] if len(parts) == 1 else np.concatenate(parts)
        register_dimension_table(name or schema.schema_name,
                                 schema.primary_key_columns[0], cols)

    def execute_sql(self, sql: str) -> BrokerResponse:
        """Engine selection mirrors the reference's
        BrokerRequestHandlerDelegate: V1 for single-table queries, V2 (MSE)
        for joins/subqueries/set-ops/windows or when the
        ``useMultistageEngine`` query option is set."""
        try:
            query = parse_sql(sql)
        except SqlParseError:
            return self.multistage.execute_sql(sql)
        if query.query_options.get("useMultistageEngine") in (True, "true", 1):
            return self.multistage.execute_sql(sql)
        resp = self.execute(query)
        if resp.exceptions and any("UnsupportedQueryError" in e for e in resp.exceptions):
            # shapes V1 rejects (e.g. ORDER BY on unselected columns) that
            # the MSE can plan — mirrors the reference's option to fall back
            # across engines per query
            mse = self.multistage.execute_sql(sql)
            if not mse.exceptions:
                return mse
        return resp

    @property
    def multistage(self):
        if not hasattr(self, "_multistage"):
            from ..mse.executor import MultistageExecutor

            self._multistage = MultistageExecutor(self)
        return self._multistage

    def execute(self, query: QueryContext, tracker=None) -> BrokerResponse:
        t0 = time.perf_counter()
        table = self.tables.get(query.table_name)
        if table is None:
            # tolerate _OFFLINE/_REALTIME suffixes (reference table name with type)
            base = query.table_name.rsplit("_", 1)[0]
            table = self.tables.get(base)
        if table is None:
            return BrokerResponse(exceptions=[f"table {query.table_name} not found"])

        if getattr(query, "explain", False) == "analyze":
            return self._execute_analyze(query, tracker=tracker)

        if getattr(query, "explain", False):
            from .explain import explain_plan

            try:
                rt = explain_plan(query, table, self.pruner,
                                  backend=self.backend,
                                  use_star_tree=self.use_star_tree)
                return BrokerResponse(
                    result_table=rt,
                    time_used_ms=(time.perf_counter() - t0) * 1000)
            except Exception as e:
                return BrokerResponse(exceptions=[f"{type(e).__name__}: {e}"])

        # own the trace only when nobody upstream (the MSE stage runner)
        # already started one — nested engine calls join the caller's span
        # tree and leave attaching trace_info to the owner
        trace = None
        owns_trace = False
        if query.query_options.get("trace") in (True, "true", 1):
            trace = TRACING.active_trace()
            if trace is None:
                trace = TRACING.start_trace(
                    f"{query.table_name}:{id(query):x}")
                owns_trace = True
        try:
            with TRACING.scope(ServerQueryPhase.QUERY_PLAN_EXECUTION):
                combined, stats = self.execute_segments(
                    query, list(table.segments), tracker=tracker)
            reducer = BrokerReducer(table.schema)
            with TRACING.scope("BROKER_REDUCE"):
                result = reducer.reduce(query, combined)
        except Exception as e:  # clean broker-style error (reference QueryException)
            SERVER_METRICS.add_meter(ServerMeter.QUERY_EXECUTION_EXCEPTIONS)
            if owns_trace:
                TRACING.end_trace()
            return BrokerResponse(
                exceptions=[f"{type(e).__name__}: {e}"],
                num_segments_queried=len(table.segments),
                time_used_ms=(time.perf_counter() - t0) * 1000,
            )
        resp = BrokerResponse(
            result_table=result,
            num_docs_scanned=getattr(combined, "num_docs_scanned", 0),
            total_docs=stats["total_docs"],
            num_segments_queried=len(table.segments),
            num_segments_processed=stats["num_segments_processed"],
            num_segments_pruned=stats["num_segments_pruned"],
            num_groups_limit_reached=getattr(combined, "groups_trimmed",
                                             False),
            num_device_dispatches=stats.get("num_device_dispatches", 0),
            num_compiles=stats.get("num_compiles", 0),
            num_segments_cache_hit=stats.get("num_segments_cache_hit", 0),
            num_segments_cache_miss=stats.get("num_segments_cache_miss", 0),
            num_coalesced_queries=stats.get("num_coalesced_queries", 0),
            coalesce_wait_ms=stats.get("coalesce_wait_ms", 0.0),
            time_used_ms=(time.perf_counter() - t0) * 1000,
        )
        if owns_trace:
            TRACING.end_trace()
            resp.trace_info = trace.to_json()
        return resp

    def _execute_analyze(self, query: QueryContext,
                         tracker=None) -> BrokerResponse:
        """EXPLAIN ANALYZE: run the query for real with an analyze-flagged
        trace (caches stay live) and return the span tree rendered as the
        annotated plan table, counters carried over from the actual run."""
        import copy

        from .explain import analyze_table

        sub = copy.copy(query)
        sub.explain = False
        sub.query_options = dict(query.query_options)
        sub.query_options["trace"] = True
        owns = TRACING.active_trace() is None
        if owns:
            trace = TRACING.start_trace(
                f"analyze:{query.table_name}", analyze=True)
        else:
            trace = TRACING.active_trace()
        try:
            resp = self.execute(sub, tracker=tracker)
        finally:
            if owns:
                TRACING.end_trace()
        if resp.exceptions:
            return resp
        trace_json = resp.trace_info if resp.trace_info is not None \
            else trace.to_json()
        out = copy.copy(resp)
        out.result_table = analyze_table(trace_json, resp,
                                         table_name=query.table_name)
        out.trace_info = trace_json
        return out

    def execute_selection_columnar(self, query: QueryContext):
        """Columnar leaf for MSE scan+filter stages: device filter mask →
        numpy column gather, skipping SelectionIntermediate's Python row
        materialization and the broker's row→column round trip. Returns
        (source-column arrays, stats) or None when the shape or backend
        doesn't qualify — the caller falls back to the row path, which owns
        ordering, deadlines, tracing and null handling."""
        import numpy as np

        if self.backend == "host":
            return None
        if (not query.is_selection or query.distinct
                or query.group_by_expressions or query.order_by_expressions
                or query.having_filter is not None or query.offset
                or query.null_handling
                or query.query_options.get("timeoutMs") is not None
                or query.query_options.get("trace") in (True, "true", 1)):
            return None
        if not query.select_expressions or not all(
                e.is_identifier and e.identifier != "*"
                for e in query.select_expressions):
            return None
        table = self.tables.get(query.table_name)
        if table is None:
            table = self.tables.get(query.table_name.rsplit("_", 1)[0])
        if table is None:
            return None
        # consuming segments join through a pinned snapshot; if the plan
        # can't lower on the realtime planner the except below falls back
        segments = [s.snapshot_view() if getattr(s, "is_mutable", False)
                    else s for s in table.segments]
        from ..query.optimizer import optimize_filter
        from ..segment.bitpack import unpack_bitmap

        names = [e.identifier for e in query.select_expressions]
        reset_dispatch_counters()
        try:
            query.filter = optimize_filter(query.filter)
            kept, _ = self.pruner.prune(query, segments)
            pending = []
            for seg in kept:
                plan = self.tpu.plan(query, seg)
                if plan.program.mode != "selection" or plan.selection_exprs:
                    return None
                outs = with_oom_retry(
                    lambda: self.tpu.dispatch_plan(seg, plan),
                    keep_segment=seg, cache=self.tpu.cache)
                pending.append((seg, outs))
            parts: dict[str, list] = {c: [] for c in names}
            scanned = 0
            remaining = max(0, int(query.limit))
            for seg, outs in pending:
                if remaining <= 0:
                    break
                mats = unpack_outputs(outs) if isinstance(outs, PackedOuts) \
                    else [np.asarray(o) for o in outs]
                bits = unpack_bitmap(np.asarray(mats[0]), seg.num_docs)
                doc_ids = np.nonzero(bits)[0]
                if len(doc_ids) > remaining:
                    doc_ids = doc_ids[:remaining]
                scanned += len(doc_ids)
                remaining -= len(doc_ids)
                for c in names:
                    parts[c].append(np.asarray(seg.get_values(c))[doc_ids])
        except Exception:
            # any planning/device hiccup: the row path re-runs the leaf
            # with identical semantics (and surfaces real failures)
            return None
        if any(getattr(s, "is_mutable", False) for s in kept):
            from ..realtime.device_plane import note_realtime_device_query

            note_realtime_device_query()
        cols: dict = {}
        for c, ps in parts.items():
            if not ps:
                cols[c] = np.empty(0)
            elif len(ps) == 1:
                cols[c] = ps[0]
            else:
                if any(p.dtype.kind == "O" for p in ps):
                    ps = [p.astype(object) for p in ps]
                cols[c] = np.concatenate(ps)
        num_dispatches, num_compiles = dispatch_counters()
        return cols, {"num_docs_scanned": scanned,
                      "total_docs": sum(s.num_docs for s in segments),
                      "num_device_dispatches": num_dispatches,
                      "num_compiles": num_compiles}

    def execute_segments(self, query: QueryContext, segments: list, tracker=None):
        """Server-side half of a query: prune → per-segment execute →
        combine. Returns (combined_intermediate, stats). This is what a
        cluster server runs for its assigned segments (reference:
        ServerQueryExecutorV1Impl.executeInternal without broker reduce);
        the in-process path and the cluster data plane share it.

        ``tracker`` (engine/scheduler.py QueryResourceTracker) enables
        cooperative cancellation + allocation accounting; the per-query
        deadline comes from the timeoutMs query option."""
        # filter canonicalization (query/optimizer.py — reference
        # QueryOptimizer runs once at the broker; here once per query on the
        # server path so every engine entry benefits). Idempotent, so a
        # re-dispatched QueryContext is safe to re-optimize.
        from ..query.optimizer import optimize_filter

        t_start = time.perf_counter()
        query.filter = optimize_filter(query.filter)
        # per-query dispatch/compile counters (engine/executor.py): every
        # device dispatch for this query happens on this thread
        reset_dispatch_counters()
        # table attribution for AOT-persisted executables + per-query
        # coalescing counters (both thread-local, like the counters above)
        from .aot_cache import set_current_table
        from .coalesce import reset_coalesce_stats

        set_current_table(query.table_name)
        reset_coalesce_stats()
        # snapshot: realtime tables mutate the live list concurrently;
        # consuming segments pin a consistent row-count view per query
        segments = [s.snapshot_view() if getattr(s, "is_mutable", False) else s
                    for s in segments]
        kept, num_pruned = self.pruner.prune(query, segments)
        total_docs = sum(s.num_docs for s in segments)
        deadline = None
        timeout_ms = query.query_options.get("timeoutMs")
        if timeout_ms is not None:
            deadline = time.perf_counter() + float(timeout_ms) / 1000
        cstats = {"hit": 0, "miss": 0}
        intermediates = self._run_segments(query, kept, tracker, deadline,
                                           timeout_ms, cstats)
        with TRACING.scope(ServerQueryPhase.SERVER_COMBINE):
            combined = self._combine(query, intermediates)
        num_dispatches, num_compiles = dispatch_counters()
        # the declared server-phase timer (reference ServerQueryPhase
        # QUERY_PROCESSING): wall time of the server-side half, into the
        # histogram that backs the /metrics p50/p95/p99
        SERVER_METRICS.update_timer(ServerTimer.QUERY_PROCESSING_TIME_MS,
                                    (time.perf_counter() - t_start) * 1000)
        SERVER_METRICS.add_meter(ServerMeter.QUERIES)
        SERVER_METRICS.add_table_meter(query.table_name, ServerMeter.QUERIES)
        SERVER_METRICS.add_meter(ServerMeter.NUM_DOCS_SCANNED,
                                 getattr(combined, "num_docs_scanned", 0))
        SERVER_METRICS.add_meter(ServerMeter.NUM_SEGMENTS_PROCESSED, len(kept))
        SERVER_METRICS.add_meter(ServerMeter.NUM_SEGMENTS_PRUNED, num_pruned)
        SERVER_METRICS.add_meter(ServerMeter.NUM_DEVICE_DISPATCHES,
                                 num_dispatches)
        SERVER_METRICS.add_meter(ServerMeter.NUM_COMPILES, num_compiles)
        SERVER_METRICS.add_meter(ServerMeter.SEGMENT_CACHE_HITS,
                                 cstats["hit"])
        SERVER_METRICS.add_meter(ServerMeter.SEGMENT_CACHE_MISSES,
                                 cstats["miss"])
        from .coalesce import coalesce_stats

        co_peers, co_wait_ms = coalesce_stats()
        return combined, {
            "total_docs": total_docs,
            "num_segments_processed": len(kept),
            "num_segments_pruned": num_pruned,
            "num_device_dispatches": num_dispatches,
            "num_compiles": num_compiles,
            "num_segments_cache_hit": cstats["hit"],
            "num_segments_cache_miss": cstats["miss"],
            "num_coalesced_queries": co_peers,
            "coalesce_wait_ms": co_wait_ms,
        }

    def _run_segments(self, query: QueryContext, kept: list, tracker,
                      deadline, timeout_ms, cstats=None) -> list:
        """Two-phase multi-segment execution: dispatch every device kernel
        first (async — the device queue fills and runs back-to-back), run
        host-fallback segments while the device works, then collect. This
        replaces the serial plan→dispatch→block loop the reference handles
        with a worker pool (GroupByCombineOperator.java:54); here the
        pipeline overlap comes from XLA's async dispatch instead of threads."""

        def check(done: int):
            if tracker is not None:
                tracker.check_cancel()
            if deadline is not None and time.perf_counter() > deadline:
                raise TimeoutError(
                    f"query exceeded timeoutMs={timeout_ms} "
                    f"({done}/{len(kept)} segments done)")

        if len(kept) > 1 and self.backend != "host":
            merged = self._try_sparse_device_combine(query, kept, tracker,
                                                     check, cstats)
            if merged is not None:
                return merged

        pending: list = []  # (idx, run_query, segment, rewrite, plan, token)
        host_work: list = []  # (idx, run_query, run_segment, rewrite)
        intermediates: list = [None] * len(kept)
        device_entries: list = []  # (idx, run_query, run_segment, rewrite, plan)
        for idx, segment in enumerate(kept):
            check(idx)
            run_query, run_segment, rewrite = self._segment_route(query, segment)
            if self.backend == "host":
                host_work.append((idx, run_query, run_segment, rewrite))
                continue
            try:
                # consuming-segment snapshots lower through the realtime
                # planner (realtime/device_plane.py) and join the device
                # path; unsupported shapes fall back per segment
                device_entries.append((idx, run_query, run_segment, rewrite,
                                       self.tpu.plan(run_query, run_segment)))
            except UnsupportedQueryError:
                if self.backend == "tpu" \
                        and not getattr(run_segment, "is_mutable", False):
                    # mutable snapshots stay best-effort even under the
                    # forced-device backend: realtime tables must answer
                    raise
                host_work.append((idx, run_query, run_segment, rewrite))

        # segment partial-result cache (cache/partial.py): a hit fills the
        # intermediate directly and the segment never reaches dispatch; a
        # miss is remembered so the collected result is inserted below.
        # Traced runs bypass — the dispatch spans ARE the observability
        # product and must describe real device work. EXPLAIN ANALYZE is
        # the exception: it must report the cache behaviour of a real run.
        cache_on = device_entries and self._segment_cache_enabled(query)
        if cache_on and TRACING.active_trace() is not None \
                and not TRACING.analyze_active():
            with TRACING.scope("SEGMENT_CACHE(bypass:trace)"):
                cache_on = False
        cache_inserts: list = []  # (idx, cache key, segment name)
        if cache_on:
            from ..cache.partial import GLOBAL_PARTIAL_CACHE

            uncached = []
            for e in device_entries:
                idx, run_query, run_segment, rewrite, plan = e
                key = self._partial_cache_key(run_query, run_segment,
                                              rewrite, plan)
                hit = None if key is None else GLOBAL_PARTIAL_CACHE.get(key)
                if hit is not None:
                    intermediates[idx] = hit
                    if cstats is not None:
                        cstats["hit"] += 1
                    continue
                if key is not None:
                    if cstats is not None:
                        cstats["miss"] += 1
                    cache_inserts.append(
                        (idx, key, getattr(run_segment, "name", "?")))
                uncached.append(e)
            device_entries = uncached

        # stacked segment batching: one vmapped dispatch per batch FAMILY
        # (equal host-side family key → identical plane shapes), single-
        # member families keep the per-segment path (incl. the fused
        # kernel). Tokens mark family members: (family key, row in batch).
        fam_packs: dict = {}    # fkey → batched PackedOuts
        fam_inputs: dict = {}   # fkey → (segments, plans) for re-dispatch
        fam_hosts: dict = {}    # fkey → HOST arrays from a coalesced group
        msig = self._mesh_sig(query)
        # cross-query coalescing (engine/coalesce.py): only armed when the
        # opt-in hold window is set AND the family has repeat traffic;
        # traced queries never coalesce (their spans must describe their
        # own device work)
        from .coalesce import coalesce_enabled
        from ..realtime.device_plane import (RealtimeUploadError,
                                             note_realtime_device_query)

        co_on = coalesce_enabled(query) and TRACING.active_trace() is None
        rt_device = False  # any consuming segment answered on device
        for fkey, positions in self._batch_families(
                query, [(e[2], e[4]) for e in device_entries], mesh=msig):
            entries = [device_entries[p] for p in positions]
            if fkey is not None and len(entries) > 1:
                segs_f = [e[2] for e in entries]
                plans_f = [e[4] for e in entries]
                fam_mutable = any(getattr(s, "is_mutable", False)
                                  for s in segs_f)
                # the coalescer's family key carries no snapshot
                # generation, so a held group could serve one generation's
                # stack to a later query — consuming families never join
                if co_on and not fam_mutable:
                    def _co_runner(segs_all, plans_all,
                                   _keep=segs_f[0], _m=msig):
                        pack = with_oom_retry(
                            lambda: self.tpu.dispatch_plan_batch(
                                segs_all, plans_all, mesh=_m),
                            keep_segment=_keep, cache=self.tpu.cache)
                        return fetch_packed_batch([pack])[0]

                    co = self.coalescer.offer(query.table_name, fkey,
                                              segs_f, plans_f, msig,
                                              _co_runner)
                    if co is not None:
                        # this query's S rows are zero-copy views of the
                        # group's fetched stack; tokens ride the normal
                        # family demux below
                        fam_hosts[fkey] = co.outs
                        for row, e in enumerate(entries):
                            pending.append(e + ((fkey, row),))
                        continue
                try:
                    # HBM pressure during plane upload/dispatch: evict cold
                    # cached segments once and retry (engine/oom.py — the
                    # DirectOOMHandler analogue). Relief drops whole stacks.
                    pack = with_oom_retry(
                        lambda: self.tpu.dispatch_plan_batch(segs_f, plans_f,
                                                             mesh=msig),
                        keep_segment=segs_f[0], cache=self.tpu.cache)
                except BatchFamilyMismatch:
                    pass  # host key over-grouped; per-segment is always valid
                except RealtimeUploadError:
                    pass  # per-segment path below host-falls the faulted one
                except HbmExhaustedError:
                    # the [S, N] stacks ~double the family's footprint, so a
                    # family that fits per-segment can OOM batched even after
                    # relief — fall back rather than fail a query the 1x
                    # per-segment path (below, with its own retry) completes
                    pass
                else:
                    if fam_mutable:
                        rt_device = True
                    fam_packs[fkey] = pack
                    fam_inputs[fkey] = (segs_f, plans_f)
                    for row, e in enumerate(entries):
                        pending.append(e + ((fkey, row),))
                    continue
            for e in entries:
                idx, run_query, run_segment, rewrite, plan = e
                try:
                    outs = with_oom_retry(
                        lambda: self.tpu.dispatch_plan(run_segment, plan),
                        keep_segment=run_segment, cache=self.tpu.cache)
                except RealtimeUploadError:
                    # delta upload faulted/overran its budget: THIS query
                    # answers on host (bit-identical); plane state is
                    # pre-fault-consistent or dropped for full re-upload
                    inter = self._account(
                        tracker, lambda rq=run_query, rs=run_segment:
                        self.host.execute(rq, rs), run_segment)
                    intermediates[idx] = (
                        self._remap_star_tree(rewrite, inter) if rewrite
                        else inter)
                    continue
                if getattr(run_segment, "is_mutable", False):
                    rt_device = True
                pending.append((idx, run_query, run_segment, rewrite, plan,
                                outs))

        done = 0
        if self.num_threads > 1 and len(host_work) > 1:
            caller_trace = TRACING.active_trace()
            caller_span = TRACING.current_span()

            def run_one(run_query, run_segment):
                # traces are thread-local; seed the caller's span so
                # worker scopes nest under QUERY_PLAN_EXECUTION
                TRACING.adopt(caller_trace, caller_span)
                try:
                    cpu0 = time.thread_time_ns()
                    with TRACING.scope(
                            f"segment:{getattr(run_segment, 'name', '?')}"):
                        inter = self.host.execute(run_query, run_segment)
                    return inter, time.thread_time_ns() - cpu0
                finally:
                    TRACING.adopt(None)

            futs = [
                (idx, rewrite, self._host_pool().submit(
                    run_one, run_query, run_segment))
                for idx, run_query, run_segment, rewrite in host_work]
            for idx, rewrite, fut in futs:
                check(done)
                inter, cpu_ns = fut.result()
                if tracker is not None:
                    tracker.add_cpu_ns(cpu_ns)
                    GLOBAL_ACCOUNTANT.on_allocation(
                        tracker, _estimate_bytes(inter))
                intermediates[idx] = (
                    self._remap_star_tree(rewrite, inter) if rewrite else inter)
                done += 1
            host_work = []
        for idx, run_query, run_segment, rewrite in host_work:
            check(done)
            inter = self._account(tracker, lambda: self.host.execute(
                run_query, run_segment), run_segment)
            intermediates[idx] = (
                self._remap_star_tree(rewrite, inter) if rewrite else inter)
            done += 1
        solo = [p for p in pending if isinstance(p[5], PackedOuts)]
        fam_keys = list(fam_packs)
        if fam_keys or fam_hosts or len(solo) > 1:
            # ONE device→host transfer for the whole multi-segment batch —
            # each batched family is already a single flat buffer, solo
            # packs of equal length concat with it (a tunneled device pays
            # a fixed round trip per fetch).
            # async dispatch means an in-flight OOM surfaces HERE on
            # error-poisoned buffers: the retry must RE-DISPATCH every
            # pending segment/family after eviction, not re-fetch the dead
            # outputs
            def _refetch():
                packs = [self.tpu.dispatch_plan(p[2], p[4]) for p in solo]
                packs += [self.tpu.dispatch_plan_batch(*fam_inputs[k],
                                                       mesh=msig)
                          for k in fam_keys]
                return fetch_packed_batch(packs)

            if solo or fam_keys:
                try:
                    fetched = with_oom_retry(
                        lambda: fetch_packed_batch(
                            [p[5] for p in solo]
                            + [fam_packs[k] for k in fam_keys]),
                        cache=self.tpu.cache, retry_fn=_refetch)
                except RealtimeUploadError:
                    # double fault: OOM relief dropped the realtime planes
                    # mid-query and the re-dispatch's re-upload faulted too.
                    # Upload faults must never fail a query — host-execute
                    # every still-pending segment instead.
                    for p in pending:
                        idx, run_query, run_segment, rewrite = p[:4]
                        inter = self._account(
                            tracker, lambda rq=run_query, rs=run_segment:
                            self.host.execute(rq, rs), run_segment)
                        intermediates[idx] = (
                            self._remap_star_tree(rewrite, inter)
                            if rewrite else inter)
                        done += 1
                    pending = []
                    fetched = []
                    solo, fam_keys, fam_hosts = [], [], {}
            else:
                fetched = []  # coalesced families arrive host-side already
            solo_outs = {id(p): raw for p, raw in zip(solo, fetched)}
            fam_outs = dict(zip(fam_keys, fetched[len(solo):]))
            fam_outs.update(fam_hosts)
            # vectorized family combine (engine/combine.py): dense and
            # un-grouped aggregation families decode all members in one
            # pass over the batched arrays; other modes slice per member
            # and ride the normal collect()
            from .combine import (combine_batched_aggregation,
                                  combine_batched_dense)

            precomputed: dict = {}
            for fkey in fam_outs:
                members = [p for p in pending
                           if not isinstance(p[5], PackedOuts)
                           and p[5][0] == fkey]
                plans_f = [p[4] for p in members]
                mode = plans_f[0].program.mode
                batched = None
                if mode == "group_by":
                    batched = combine_batched_dense(fam_outs[fkey], plans_f)
                elif mode == "aggregation":
                    batched = combine_batched_aggregation(
                        fam_outs[fkey], plans_f)
                if batched is not None:
                    for row, inter in enumerate(batched):
                        precomputed[(fkey, row)] = inter
            new_pending = []
            for p in pending:
                tok = p[5]
                if isinstance(tok, PackedOuts):
                    new_pending.append(p[:5] + (solo_outs[id(p)],))
                elif tok in precomputed:
                    new_pending.append(p[:5] + (precomputed[tok],))
                else:
                    fkey, row = tok
                    # zero-copy per-segment views of the batched [S, ...]
                    # host arrays; collect() consumes them unchanged
                    new_pending.append(
                        p[:5] + ([o[row] for o in fam_outs[fkey]],))
            pending = new_pending
        for idx, run_query, run_segment, rewrite, plan, outs in pending:
            check(done)
            if isinstance(outs, (AggIntermediate, GroupByIntermediate)):
                # vectorized family combine already decoded this member
                inter = self._account(tracker, lambda o=outs: o, run_segment)
                intermediates[idx] = (
                    self._remap_star_tree(rewrite, inter) if rewrite
                    else inter)
                done += 1
                continue

            def _recollect(run_query=run_query, run_segment=run_segment,
                           plan=plan):
                return self.tpu.collect(
                    run_query, run_segment, plan,
                    self.tpu.dispatch_plan(run_segment, plan))

            inter = self._account(
                tracker,
                lambda: with_oom_retry(
                    lambda: self.tpu.collect(
                        run_query, run_segment, plan, outs),
                    keep_segment=run_segment, cache=self.tpu.cache,
                    retry_fn=_recollect),
                run_segment)
            intermediates[idx] = (
                self._remap_star_tree(rewrite, inter) if rewrite else inter)
            done += 1
        if cache_inserts:
            from ..cache.partial import GLOBAL_PARTIAL_CACHE

            for idx, key, seg_name in cache_inserts:
                inter = intermediates[idx]
                # selections bypass (LIMIT makes row sets order-dependent
                # across segments and the payoff is row materialization,
                # not device work); agg/group partials are pure merges
                if isinstance(inter, (AggIntermediate, GroupByIntermediate)):
                    GLOBAL_PARTIAL_CACHE.put(key, inter, (seg_name,))
        if rt_device:
            note_realtime_device_query()
        return intermediates

    def _segment_cache_enabled(self, query: QueryContext) -> bool:
        """Segment partial-result caching is ON by default for the device
        path; ``SET segmentCache = false`` opts a query out and
        PINOT_TPU_SEGMENT_CACHE=0 disables it process-wide. The option is
        checked FIRST so opted-out queries never touch fingerprinting."""
        opt = query.query_options.get("segmentCache")
        if opt is not None and str(opt).lower() in ("false", "0", "off"):
            return False
        from ..cache.partial import partial_cache_enabled

        return partial_cache_enabled()

    def _partial_cache_key(self, run_query, run_segment, rewrite, plan):
        """(program_fp, segment_token) for one routed segment, or None when
        this segment can't participate: star-tree rewrites (the cached
        partial would be pre-remap against a derived view), crc-less
        immutable segments, mutable snapshots without a generation stamp,
        or plans with unfingerprintable state. Generation-stamped realtime
        snapshots DO participate — their token folds (rows, upsert_gen), so
        a new row or upsert flip mints a fresh key and stale partials are
        invalidated by name at commit."""
        if rewrite is not None:
            return None
        from ..cache.keys import program_fingerprint, segment_token

        token = segment_token(run_segment)
        if token is None:
            return None
        fp = program_fingerprint(plan, run_query)
        if fp is None:
            return None
        return (fp, token)

    def _segment_batch_enabled(self, query: QueryContext) -> bool:
        """Stacked segment batching is ON by default; SET segmentBatch =
        false opts a query out (same spelling family as deviceCombine)."""
        return str(query.query_options.get("segmentBatch")).lower() \
            not in ("false", "0", "off")

    def _mesh_enabled(self, query: QueryContext) -> bool:
        """Mesh execution (segment-axis sharding of batch families over the
        local devices) is ON by default when more than one device exists;
        ``SET meshExecution = false`` opts a query out and
        PINOT_TPU_MESH_DEVICES sizes/disables it process-wide."""
        return str(query.query_options.get("meshExecution")).lower() \
            not in ("false", "0", "off")

    def _mesh_sig(self, query: QueryContext) -> tuple:
        """Mesh shape for this query's family dispatches: (ndev,) when the
        sharded path is active, () for solo batching. Part of the batch
        family key so sharded and solo executables cache separately."""
        if self.backend == "host" or not self._mesh_enabled(query):
            return ()
        from ..parallel.mesh import mesh_device_count

        ndev = mesh_device_count()
        return (ndev,) if ndev > 1 else ()

    def _batch_families(self, query: QueryContext, pairs: list,
                        mesh: tuple = ()) -> list:
        """Group (segment, plan) pairs into batch families by the
        host-side family key (engine/executor.py:batch_family_key).
        Returns ordered (fkey, positions) groups; fkey is None for pairs
        that can't batch (unpredictable slot shapes, or batching disabled)
        — those take the per-segment path."""
        if len(pairs) < 2 or not self._segment_batch_enabled(query):
            return [(None, [i]) for i in range(len(pairs))]
        groups: dict = {}
        order: list = []
        for pos, (segment, plan) in enumerate(pairs):
            fkey = batch_family_key(segment, plan, mesh)
            k = ("__solo__", pos) if fkey is None else fkey
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(pos)
        return [(None if k[0] == "__solo__" else k, groups[k])
                for k in order]

    # device merge ops per sparse AggOp kind (count columns merge like sums)
    _SPARSE_COMBINE_KINDS = {"count": "add", "sum": "add", "sumsq": "add",
                             "min": "min", "max": "max"}

    def _try_sparse_device_combine(self, query: QueryContext, kept, tracker,
                                   check, cstats=None):
        """Server-level merge ON DEVICE for multi-segment single-key sparse
        group-bys: dispatch every segment's kernel, translate each key
        column to dictionary VALUE space on device (dictionaries are
        segment-local), merge the S tables with one sort/edge-reduce
        (kernels.combine_sparse_group_tables), and fetch ONE merged table —
        replacing S device→host table transfers + the host factorize/
        scatter merge in combine_group_arrays. Restricted to shapes where
        value-space keys are exact: one identifier group key over an
        integer dictionary, vectorizable aggs only. Returns the 1-element
        intermediates list, or None to fall back to the normal
        per-segment collect + host merge (any failure here is recoverable
        — nothing has been consumed)."""
        if query.query_options.get("deviceCombine") in (False, "false", 0):
            return None
        import logging

        import numpy as np

        from ..ops import kernels
        from .results import GroupArrays

        plans, segs = [], []
        for segment in kept:
            run_query, run_segment, rewrite = self._segment_route(
                query, segment)
            if rewrite is not None:
                return None
            try:
                plans.append(self.tpu.plan(run_query, run_segment))
            except UnsupportedQueryError:
                return None
            segs.append(run_segment)
        p0 = plans[0].program
        kinds = tuple(self._SPARSE_COMBINE_KINDS.get(a.kind)
                      for a in p0.aggs)
        agg_kinds = tuple(a.kind for a in p0.aggs)
        if p0.mode != "group_by_sparse" or not kinds or None in kinds:
            return None
        for pl in plans:
            p = pl.program
            if not (p.mode == "group_by_sparse"
                    and p.group_strides == (1,)
                    and len(p.group_slots) == 1
                    and not p.group_vexprs
                    and p.mv_group_slot is None
                    and p.exact_trim == p0.exact_trim
                    and tuple(a.kind for a in p.aggs) == agg_kinds
                    and pl.group_dims
                    and np.issubdtype(
                        pl.group_dims[0].dictionary.values.dtype,
                        np.integer)
                    and all(la.vec is not None for la in pl.lowered_aggs)):
                return None
        # two cache tiers for this path (cache/partial.py): the fully
        # merged host GroupArrays keyed by the ORDERED per-segment keys —
        # a hit is the whole warm repeat with ZERO device dispatches — and
        # per-segment value-space tables kept DEVICE-resident against the
        # HBM budget, so partial overlap still skips member dispatches and
        # feeds the device combine directly.
        cache_on = self._segment_cache_enabled(query) \
            and (TRACING.active_trace() is None or TRACING.analyze_active())
        keys = None
        merged_key = None
        if cache_on:
            keys = [self._partial_cache_key(query, seg, None, pl)
                    for seg, pl in zip(segs, plans)]
            if all(k is not None for k in keys):
                from ..cache.partial import GLOBAL_PARTIAL_CACHE

                # sorted: the sort/edge-reduce merge is order-insensitive,
                # so any segment ordering of the same set may hit
                merged_key = ("sparse_merged",) + tuple(sorted(keys))
                hit = GLOBAL_PARTIAL_CACHE.get(merged_key)
                if hit is not None:
                    if cstats is not None:
                        cstats["hit"] += len(segs)
                    if tracker is not None:
                        GLOBAL_ACCOUNTANT.on_allocation(
                            tracker, _estimate_bytes(hit))
                    with TRACING.scope("SEGMENT_CACHE(hit:merged)") as sp:
                        if sp is not None:
                            sp.set_attribute("segments", len(segs))
                            sp.set_attribute("cache", "hit")
                            sp.set_attribute("cacheHitBytes",
                                             int(_estimate_bytes(hit)))
                    return [hit]
        try:
            # one vmapped dispatch per batch family; members pull lazy
            # device-side rows from the batched outputs (never fetched —
            # the merged table below is the only D2H transfer)
            member_outs: list = [None] * len(segs)
            cached_tabs: dict = {}
            if cache_on and keys is not None:
                for i, k in enumerate(keys):
                    if k is not None:
                        tab = self.tpu.cache.get_partial(("sparse_tab",) + k)
                        if tab is not None:
                            cached_tabs[i] = tab
            msig = self._mesh_sig(query)
            for fkey, positions in self._batch_families(
                    query, list(zip(segs, plans)), mesh=msig):
                positions = [i for i in positions if i not in cached_tabs]
                if not positions:
                    continue
                if fkey is not None and len(positions) > 1:
                    try:
                        # same batched-OOM discipline as _run_segments: a
                        # transient OOM gets one eviction+retry, a persistent
                        # one (or a family-key drift) falls back to the 1x-
                        # footprint per-segment dispatch loop below instead
                        # of abandoning the device combine entirely
                        # (mesh-sharded dispatches arrive gathered to
                        # device 0 so the table merge below colocates)
                        outs_b, views_b = with_oom_retry(
                            lambda: self.tpu.dispatch_plan_batch_raw(
                                [segs[i] for i in positions],
                                [plans[i] for i in positions], mesh=msig),
                            keep_segment=segs[positions[0]],
                            cache=self.tpu.cache)
                    except (BatchFamilyMismatch, HbmExhaustedError):
                        pass
                    else:
                        for row, i in enumerate(positions):
                            member_outs[i] = (
                                tuple(o[row] for o in outs_b), views_b[row])
                        continue
                for i in positions:
                    member_outs[i] = self.tpu.dispatch_plan_raw(
                        segs[i], plans[i])
            seg_keys, seg_counts, seg_states = [], [], []
            for done, (segment, pl) in enumerate(zip(segs, plans)):
                check(done)
                tab = cached_tabs.get(done)
                if tab is not None:
                    keys64, cnt, states = tab[0], tab[1], tuple(tab[2:])
                    if cstats is not None:
                        cstats["hit"] += 1
                else:
                    outs, view = member_outs[done]
                    keys64 = kernels.ids_to_values_i64(
                        outs[-1], view.dict_values(pl.group_dims[0].column))
                    cnt = outs[0]
                    states = tuple(outs[1:-1])
                    if cache_on and keys is not None \
                            and keys[done] is not None:
                        self.tpu.cache.put_partial(
                            ("sparse_tab",) + keys[done],
                            (keys64, cnt) + states,
                            segment_name=getattr(segment, "name", "?"))
                        if cstats is not None:
                            cstats["miss"] += 1
                seg_keys.append(keys64)
                seg_counts.append(cnt)
                seg_states.append(states)
            merged = kernels.combine_sparse_group_tables(
                tuple(seg_keys), tuple(seg_counts), tuple(seg_states),
                kinds)
            # one flat D2H transfer for the whole query
            outs_np = unpack_outputs(kernels.pack_outputs(merged))
        except TimeoutError:
            raise
        except Exception:
            logging.getLogger(__name__).debug(
                "sparse device combine failed; host merge fallback",
                exc_info=True)
            return None
        counts = outs_np[0][:-1]
        gids = np.nonzero(counts)[0]
        trash = int(outs_np[0][-1])
        dim = plans[0].group_dims[0]
        key_col = outs_np[-1][gids].astype(dim.dictionary.values.dtype,
                                           copy=False)
        las = plans[0].lowered_aggs
        ga = GroupArrays(
            [key_col],
            [la.vec.extract(outs_np, gids) for la in las],
            [la.vec.spec for la in las],
            [la.vec.fin_tag for la in las],
            num_docs_scanned=int(counts.sum()) + trash,
            groups_trimmed=trash > 0 and not p0.exact_trim)
        if merged_key is not None:
            from ..cache.partial import GLOBAL_PARTIAL_CACHE

            GLOBAL_PARTIAL_CACHE.put(
                merged_key, ga,
                tuple(getattr(s, "name", "?") for s in segs))
        if tracker is not None:
            GLOBAL_ACCOUNTANT.on_allocation(tracker, _estimate_bytes(ga))
        if any(getattr(s, "is_mutable", False) for s in segs):
            from ..realtime.device_plane import note_realtime_device_query

            note_realtime_device_query()
        return [ga]

    def _segment_route(self, query: QueryContext, segment):
        rewrite = None
        # star-tree pre-aggregates ignore upsert validity → not applicable
        if self.use_star_tree and getattr(segment, "valid_doc_ids", None) is None:
            from ..segment.startree import try_rewrite

            rewrite = try_rewrite(query, segment)
        if rewrite is not None:
            return rewrite.query, rewrite.view, rewrite
        return query, segment, None

    def _account(self, tracker, fn, segment):
        cpu0 = time.thread_time_ns()
        with TRACING.scope(f"segment:{getattr(segment, 'name', '?')}"):
            inter = fn()
        if tracker is not None:
            tracker.add_cpu_ns(time.thread_time_ns() - cpu0)
            GLOBAL_ACCOUNTANT.on_allocation(tracker, _estimate_bytes(inter))
        return inter

    @staticmethod
    def _remap_star_tree(rewrite, result):
        """Inner (pre-agg) states → outer aggregation states; scanned-doc
        count reflects pre-agg rows read (the star-tree speedup is visible
        in numDocsScanned, same as the reference)."""
        from ..segment.startree import remap_states

        if isinstance(result, GroupByIntermediate):
            return GroupByIntermediate(
                {k: remap_states(rewrite, v) for k, v in result.groups.items()},
                result.num_docs_scanned,
            )
        if isinstance(result, AggIntermediate):
            return AggIntermediate(remap_states(rewrite, result.states),
                                   result.num_docs_scanned)
        return result

    def _combine(self, query: QueryContext, intermediates):
        from .combine import combine_group_arrays
        from .results import GroupArrays

        semantics = [semantics_for(a) for a in query.aggregations]
        first = intermediates[0] if intermediates else None
        if (isinstance(first, GroupArrays)
                and all(isinstance(im, GroupArrays) for im in intermediates)):
            merged = combine_group_arrays(intermediates)
            if merged is not None:
                return trim_group_by(merged, query, semantics)
        if isinstance(first, GroupByIntermediate):
            return trim_group_by(combine_group_by(intermediates, semantics),
                                 query, semantics)
        if isinstance(first, AggIntermediate):
            return combine_aggregation(intermediates, semantics)
        if isinstance(first, SelectionIntermediate):
            return combine_selection(intermediates)
        # no segments: shape an empty intermediate from the query
        if query.is_aggregation_query and not query.is_group_by and not query.distinct:
            return AggIntermediate([])
        if query.is_group_by or query.distinct or query.is_aggregation_query:
            return GroupByIntermediate({})
        return SelectionIntermediate([e.identifier for e in query.select_expressions if e.is_identifier], [])
