"""Broker-side reduce: combined intermediates → final ResultTable.

Reference: pinot-core/.../query/reduce/BrokerReduceService.java:61 and the
per-shape reducers (GroupByDataTableReducer handles HAVING, post-aggregation,
ORDER BY, trim). Post-aggregation expressions (e.g. SUM(a)/COUNT(b)) are
evaluated on host over finalized aggregation values, exactly like the
reference's PostAggregationHandler.
"""

from __future__ import annotations

import math
from typing import Optional

from ..query.context import QueryContext
from ..query.expressions import ExpressionContext, is_aggregation
from ..query.filter import FilterContext, FilterNodeType, Predicate, PredicateType
from ..spi.data_types import DataType, Schema
from .aggregation import UnsupportedQueryError, semantics_for
from .plan import like_to_regex
from .results import (
    AggIntermediate,
    DataSchema,
    GroupArrays,
    GroupByIntermediate,
    ResultTable,
    SelectionIntermediate,
)

import numpy as np


class BrokerReducer:
    def __init__(self, schema: Optional[Schema] = None):
        self.schema = schema

    # -- entry -------------------------------------------------------------
    def reduce(self, query: QueryContext, combined) -> ResultTable:
        if isinstance(combined, GroupByIntermediate):
            from .gapfill import apply_gapfill, extract_gapfill

            spec = extract_gapfill(query)
            if spec is not None:
                # fill before pagination (reference: GapfillProcessor runs
                # on the full reduced result, then limit applies)
                import copy

                q2 = copy.copy(query)
                q2.offset = 0
                q2.limit = 1 << 40
                full = self._reduce_group_by(q2, combined)
                filled = apply_gapfill(full, spec)
                rows = filled.rows[query.offset: query.offset + query.limit]
                return ResultTable(filled.schema, rows)
            return self._reduce_group_by(query, combined)
        if isinstance(combined, AggIntermediate):
            return self._reduce_aggregation(query, combined)
        if isinstance(combined, SelectionIntermediate):
            return self._reduce_selection(query, combined)
        raise TypeError(type(combined))

    # -- group by ----------------------------------------------------------
    def _reduce_group_by(self, query: QueryContext, combined: GroupByIntermediate) -> ResultTable:
        group_exprs = list(query.group_by_expressions)
        if query.distinct and not query.is_aggregation_query:
            group_exprs = list(query.select_expressions)
        agg_exprs = query.aggregations
        semantics = [semantics_for(a) for a in agg_exprs]

        if isinstance(combined, GroupArrays):
            fast = self._fast_group_reduce(query, combined, group_exprs,
                                           agg_exprs)
            if fast is not None:
                return fast

        medium = self._medium_group_reduce(query, combined, group_exprs,
                                           agg_exprs, semantics)
        if medium is not None:
            return medium

        # env rows: expression-string → value (+ select aliases, so ORDER BY
        # and HAVING can reference them like the reference's alias handling)
        env_rows = []
        for key, states in combined.groups.items():
            env = {}
            for ge, kv in zip(group_exprs, key):
                env[str(ge)] = kv
            for ae, sem, st in zip(agg_exprs, semantics, states):
                env[str(ae)] = sem.finalize(st)
            for se, alias in zip(query.select_expressions, query.aliases):
                if alias:
                    env[alias] = _eval_post(se, env)
            env_rows.append(env)

        if query.having_filter is not None:
            env_rows = [e for e in env_rows if _eval_having(query.having_filter, e)]

        # ORDER BY
        if query.order_by_expressions:
            for ob in reversed(query.order_by_expressions):
                env_rows.sort(
                    key=lambda env, _ob=ob: _sort_key(_eval_post(_ob.expression, env)),
                    reverse=not ob.ascending,
                )
        rows = []
        names, types = self._select_schema(query, group_exprs)
        for env in env_rows[query.offset : query.offset + query.limit]:
            rows.append([_round_type(_eval_post(e, env), t)
                         for e, t in zip(query.select_expressions, types)])
        return ResultTable(DataSchema(names, types), rows)

    def _medium_group_reduce(self, query: QueryContext, combined,
                             group_exprs, agg_exprs,
                             semantics) -> Optional[ResultTable]:
        """Columnar reduce for dict-form intermediates (aggs without a vec
        form — sketches, distincts) when the query is the plain
        SELECT keys/aggs ... ORDER BY keys/aggs shape: one finalize pass
        into columns + one argsort, instead of 100K env dicts (measured
        ~37µs/group there — seconds at numGroupsLimit scale). Returns None
        for HAVING / post-agg expressions / aliases-in-order-by."""
        if query.having_filter is not None or not combined.groups:
            return None
        gkeys = [str(ge) for ge in group_exprs]
        akeys = [str(ae) for ae in agg_exprs]
        colpos = {k: i for i, k in enumerate(gkeys)}
        for i, k in enumerate(akeys):
            colpos.setdefault(k, len(gkeys) + i)
        sel_keys = [str(e) for e in query.select_expressions]
        if any(k not in colpos for k in sel_keys):
            return None
        for ob in query.order_by_expressions or []:
            if str(ob.expression) not in colpos:
                return None

        nk, na = len(gkeys), len(akeys)
        key_rows = list(combined.groups.keys())
        cols: list[list] = [[] for _ in range(nk + na)]
        for d in range(nk):
            cols[d] = [k[d] for k in key_rows]
        states_it = combined.groups.values()
        fins = [sem.finalize for sem in semantics]
        for states in states_it:
            for i in range(na):
                cols[nk + i].append(fins[i](states[i]))

        # sort with the SAME comparator the env path uses (_sort_key:
        # None-last, bool/str/mixed safe) — numpy argsort would need dtype
        # guards for every shape the general path already tolerates
        idx = list(range(len(key_rows)))
        for ob in reversed(query.order_by_expressions or []):
            vals = cols[colpos[str(ob.expression)]]
            idx.sort(key=lambda i, _v=vals: _sort_key(_v[i]),
                     reverse=not ob.ascending)
        sel = idx[query.offset: query.offset + query.limit]
        names, types = self._select_schema(query, group_exprs)
        rows = []
        sel_cols = [cols[colpos[k]] for k in sel_keys]
        for i in sel:
            rows.append([_round_type(c[i], t)
                         for c, t in zip(sel_cols, types)])
        return ResultTable(DataSchema(names, types), rows)

    def _fast_group_reduce(self, query: QueryContext, ga: GroupArrays,
                           group_exprs, agg_exprs) -> Optional[ResultTable]:
        """Vectorized reduce for the standard SELECT keys..., aggs... shape:
        finalize as numpy columns, argsort for ORDER BY, materialize only the
        LIMIT window. Returns None (→ general env-dict path) for HAVING,
        post-aggregation expressions, or anything else off the fast shape."""
        if query.having_filter is not None:
            return None
        colmap: dict[str, np.ndarray] = {}
        for ge, col in zip(group_exprs, ga.key_cols):
            colmap[str(ge)] = col
        for ae, tag, comps in zip(agg_exprs, ga.fin_tags, ga.state_cols):
            colmap[str(ae)] = _apply_fin_tag(tag, comps)
        for se, alias in zip(query.select_expressions, query.aliases):
            if alias and str(se) in colmap:
                colmap.setdefault(alias, colmap[str(se)])
        if any(str(e) not in colmap for e in query.select_expressions):
            return None
        order = []
        for ob in query.order_by_expressions or []:
            col = colmap.get(str(ob.expression))
            if col is None:
                return None
            if not ob.ascending and col.dtype == object:
                return None  # descending strings: let the general path sort
            order.append((col, ob.ascending))

        perm = np.arange(ga.num_groups)
        for col, asc in reversed(order):
            vals = col[perm]
            k = (np.argsort(vals, kind="stable") if asc
                 else np.argsort(-vals, kind="stable"))
            perm = perm[k]
        sel = perm[query.offset: query.offset + query.limit]
        names, types = self._select_schema(query, group_exprs)
        out_cols = [colmap[str(e)][sel].tolist()
                    for e in query.select_expressions]
        rows = [[_round_type(v, t) for v, t in zip(r, types)]
                for r in zip(*out_cols)]
        return ResultTable(DataSchema(names, types), rows)

    def _reduce_aggregation(self, query: QueryContext, combined: AggIntermediate) -> ResultTable:
        agg_exprs = query.aggregations
        semantics = [semantics_for(a) for a in agg_exprs]
        env = {}
        if combined.states:
            for ae, sem, st in zip(agg_exprs, semantics, combined.states):
                env[str(ae)] = sem.finalize(st)
        else:  # no segments at all: per-function empty results
            for ae, sem in zip(agg_exprs, semantics):
                env[str(ae)] = sem.empty_value
        names, types = self._select_schema(query, [])
        row = [_round_type(_eval_post(e, env), t) for e, t in zip(query.select_expressions, types)]
        return ResultTable(DataSchema(names, types), [row])

    def _reduce_selection(self, query: QueryContext, combined: SelectionIntermediate) -> ResultTable:
        rows = combined.rows
        if query.order_by_expressions:
            idx = {c: i for i, c in enumerate(combined.columns)}
            rows = list(rows)
            for ob in reversed(query.order_by_expressions):
                key = (ob.expression.identifier if ob.expression.is_identifier
                       else str(ob.expression))
                ci = idx[key]
                rows.sort(key=lambda r, _ci=ci: _sort_key(r[_ci]), reverse=not ob.ascending)
        rows = [list(r) for r in rows[query.offset : query.offset + query.limit]]
        # project away hidden ORDER BY-only columns the segments appended
        final_cols = self._selection_final_columns(query, combined.columns)
        if final_cols != list(combined.columns):
            idx = {c: i for i, c in enumerate(combined.columns)}
            keep = [idx[c] for c in final_cols]
            rows = [[r[i] for i in keep] for r in rows]
        types = [self._selection_column_type(c, i, rows)
                 for i, c in enumerate(final_cols)]
        return ResultTable(DataSchema(final_cols, types), rows)

    def _selection_final_columns(self, query: QueryContext, columns) -> list[str]:
        out = []
        for e in query.select_expressions:
            if e.is_identifier and e.identifier == "*":
                out.extend(c for c in columns
                           if self.schema is not None and self.schema.has_column(c))
            elif e.is_identifier:
                out.append(e.identifier)
            else:
                out.append(str(e))
        return out

    def _selection_column_type(self, column: str, ci: int, rows) -> str:
        if self.schema is not None and self.schema.has_column(column):
            return self.schema.field_spec(column).data_type.value
        # transform expression column: infer from materialized values
        for r in rows:
            v = r[ci]
            if isinstance(v, bool):
                return "BOOLEAN"
            if isinstance(v, int):
                return "LONG"
            if isinstance(v, float):
                return "DOUBLE"
            if isinstance(v, str):
                return "STRING"
        return "STRING"

    # -- schema ------------------------------------------------------------
    def _select_schema(self, query: QueryContext, group_exprs):
        names, types = [], []
        group_set = {str(e) for e in group_exprs}
        for e, alias in zip(query.select_expressions, query.aliases):
            names.append(alias or str(e))
            types.append(self._expr_type(e, group_set))
        return names, types

    def _expr_type(self, e: ExpressionContext, group_set) -> str:
        if is_aggregation(e):
            return semantics_for(e).result_type
        if e.is_identifier:
            return self._column_type(e.identifier)
        if e.is_literal:
            v = e.literal
            if isinstance(v, bool):
                return "BOOLEAN"
            if isinstance(v, int):
                return "LONG"
            if isinstance(v, float):
                return "DOUBLE"
            return "STRING"
        return "DOUBLE"  # post-aggregation arithmetic

    def _column_type(self, column: str) -> str:
        if self.schema is not None and self.schema.has_column(column):
            return self.schema.field_spec(column).data_type.value
        return "STRING"


# -- post-aggregation expression eval (host scalars) -------------------------


def _eval_post(e: ExpressionContext, env: dict):
    key = str(e)
    if key in env:
        return env[key]
    if e.is_literal:
        return e.literal
    if e.is_identifier:
        if e.identifier in env:
            return env[e.identifier]
        raise UnsupportedQueryError(f"column {e.identifier} not in group-by result")
    fn = e.function
    name, args = fn.name, fn.arguments
    a = [_eval_post(x, env) for x in args]
    if name == "plus":
        return a[0] + a[1]
    if name == "minus":
        return a[0] - a[1]
    if name == "times":
        return a[0] * a[1]
    if name == "divide":
        return a[0] / a[1] if a[1] else math.nan
    if name == "mod":
        return a[0] % a[1]
    if name in ("pow", "power"):
        return a[0] ** a[1]
    if name == "abs":
        return abs(a[0])
    if name == "neg":
        return -a[0]
    if name == "sqrt":
        return math.sqrt(a[0])
    if name == "ln":
        return math.log(a[0])
    if name == "log10":
        return math.log10(a[0])
    if name == "exp":
        return math.exp(a[0])
    if name in ("ceil", "ceiling"):
        return math.ceil(a[0])
    if name == "floor":
        return math.floor(a[0])
    if name == "cast":
        to = str(args[1].literal).upper()
        v = a[0]
        if to in ("INT", "LONG"):
            return int(v)
        if to in ("FLOAT", "DOUBLE"):
            return float(v)
        if to == "STRING":
            return str(v)
        if to == "BOOLEAN":
            return bool(v)
        return v
    if name == "equals":
        return a[0] == a[1]
    if name == "notequals":
        return a[0] != a[1]
    if name == "greaterthan":
        return a[0] > a[1]
    if name == "greaterthanorequal":
        return a[0] >= a[1]
    if name == "lessthan":
        return a[0] < a[1]
    if name == "lessthanorequal":
        return a[0] <= a[1]
    if name == "and":
        return bool(a[0]) and bool(a[1])
    if name == "or":
        return bool(a[0]) or bool(a[1])
    if name == "not":
        return not a[0]
    if name == "case":
        for i in range(0, len(a) - 1, 2):
            if a[i]:
                return a[i + 1]
        return a[-1]
    if name == "coalesce":
        for v in a:
            if v is not None:
                return v
        return None
    from ..query.transforms import eval_scalar

    return eval_scalar(name, a)


def _eval_having(f: FilterContext, env: dict) -> bool:
    if f.type == FilterNodeType.AND:
        return all(_eval_having(c, env) for c in f.children)
    if f.type == FilterNodeType.OR:
        return any(_eval_having(c, env) for c in f.children)
    if f.type == FilterNodeType.NOT:
        return not _eval_having(f.children[0], env)
    if f.type == FilterNodeType.CONSTANT:
        return f.constant_value
    p: Predicate = f.predicate
    v = _eval_post(p.lhs, env)
    if p.type == PredicateType.EQ:
        return v == p.values[0]
    if p.type == PredicateType.NOT_EQ:
        return v != p.values[0]
    if p.type == PredicateType.IN:
        return v in p.values
    if p.type == PredicateType.NOT_IN:
        return v not in p.values
    if p.type == PredicateType.RANGE:
        ok = True
        if p.lower is not None:
            ok = ok and ((v >= p.lower) if p.lower_inclusive else (v > p.lower))
        if p.upper is not None:
            ok = ok and ((v <= p.upper) if p.upper_inclusive else (v < p.upper))
        return ok
    if p.type == PredicateType.LIKE:
        return like_to_regex(p.values[0]).search(str(v)) is not None
    raise UnsupportedQueryError(f"HAVING predicate {p.type}")


def _sort_key(v):
    # mixed-type safe ordering: None/NaN last-ish, bools as ints
    if v is None:
        return (2, 0)
    if isinstance(v, float) and math.isnan(v):
        return (1, 0)
    if isinstance(v, bool):
        return (0, int(v))
    return (0, v)


def _apply_fin_tag(tag: tuple, comps: tuple) -> np.ndarray:
    """Evaluate a picklable finalize recipe over state component columns."""
    if tag[0] == "id":
        return comps[tag[1]]
    if tag[0] == "sub":
        return comps[tag[1]] - comps[tag[2]]
    if tag[0] == "div":
        num, den = comps[tag[1]].astype(float), comps[tag[2]]
        return np.divide(num, den, out=np.full(len(num), math.nan),
                         where=den != 0)
    raise ValueError(f"unknown finalize tag {tag}")


def _round_type(v, t: str):
    """Coerce finalized values to the declared result type (reference
    ColumnDataType.convert)."""
    if v is None:
        return None
    try:
        if t == "LONG" or t == "INT" or t == "TIMESTAMP":
            if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
                return v
            return int(v)
        if t == "DOUBLE" or t == "FLOAT":
            return float(v)
        if t == "BOOLEAN":
            return bool(v)
    except (TypeError, ValueError):
        return v
    return v
