"""Intermediate + final result containers.

Reference analogues: per-segment IntermediateResultsBlock, per-server
DataTable (pinot-common/.../datatable/DataTableImplV4.java:82), broker
ResultTable. Intermediates here are host-side (keys are group VALUES, not
dict ids — dict ids are segment-local, exactly why the reference's
IndexedTable keys on Record values too).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class DataSchema:
    column_names: list[str]
    column_types: list[str]  # INT/LONG/FLOAT/DOUBLE/BOOLEAN/STRING/BYTES/TIMESTAMP

    def to_json(self) -> dict:
        return {"columnNames": self.column_names, "columnDataTypes": self.column_types}


@dataclass
class ResultTable:
    schema: DataSchema
    rows: list[list]

    def to_json(self) -> dict:
        return {"dataSchema": self.schema.to_json(), "rows": self.rows}


@dataclass
class BrokerResponse:
    """Final response shape (reference BrokerResponseNative)."""

    result_table: Optional[ResultTable] = None
    num_docs_scanned: int = 0
    total_docs: int = 0
    num_segments_queried: int = 0
    num_segments_processed: int = 0
    num_segments_pruned: int = 0
    time_used_ms: float = 0.0
    exceptions: list = field(default_factory=list)
    trace_info: Optional[list] = None  # set when the trace option is on

    def to_json(self) -> dict:
        out = {
            "resultTable": self.result_table.to_json() if self.result_table else None,
            "numDocsScanned": self.num_docs_scanned,
            "totalDocs": self.total_docs,
            "numSegmentsQueried": self.num_segments_queried,
            "numSegmentsProcessed": self.num_segments_processed,
            "numSegmentsPrunedByServer": self.num_segments_pruned,
            "timeUsedMs": self.time_used_ms,
            "exceptions": self.exceptions,
        }
        if self.trace_info is not None:
            out["traceInfo"] = self.trace_info
        return out


# -- per-segment intermediates ----------------------------------------------


@dataclass
class GroupByIntermediate:
    """group key tuple (values) → list of per-agg states."""

    groups: dict[tuple, list]
    num_docs_scanned: int = 0


@dataclass
class AggIntermediate:
    states: list  # one state per aggregation
    num_docs_scanned: int = 0


@dataclass
class SelectionIntermediate:
    columns: list[str]
    rows: list[tuple]
    num_docs_scanned: int = 0
