"""Intermediate + final result containers.

Reference analogues: per-segment IntermediateResultsBlock, per-server
DataTable (pinot-common/.../datatable/DataTableImplV4.java:82), broker
ResultTable. Intermediates here are host-side (keys are group VALUES, not
dict ids — dict ids are segment-local, exactly why the reference's
IndexedTable keys on Record values too).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class DataSchema:
    column_names: list[str]
    column_types: list[str]  # INT/LONG/FLOAT/DOUBLE/BOOLEAN/STRING/BYTES/TIMESTAMP

    def to_json(self) -> dict:
        return {"columnNames": self.column_names, "columnDataTypes": self.column_types}


@dataclass
class ResultTable:
    schema: DataSchema
    rows: list[list]

    def to_json(self) -> dict:
        return {"dataSchema": self.schema.to_json(), "rows": self.rows}


@dataclass
class BrokerResponse:
    """Final response shape (reference BrokerResponseNative)."""

    result_table: Optional[ResultTable] = None
    num_docs_scanned: int = 0
    total_docs: int = 0
    num_segments_queried: int = 0
    num_segments_processed: int = 0
    num_segments_pruned: int = 0
    time_used_ms: float = 0.0
    exceptions: list = field(default_factory=list)
    trace_info: Optional[list] = None  # set when the trace option is on
    # a size guard truncated the result (reference: maxRowsInJoinReached)
    partial_result: bool = False
    # the numGroupsLimit trim dropped groups (reference:
    # numGroupsLimitReached) — surviving groups stay exact
    num_groups_limit_reached: bool = False
    # MSE only: stage_id → {rows_in, rows_out, shuffled_rows,
    # shuffled_bytes, wall_ms, workers, leaf_pushdown}
    mse_stage_stats: Optional[dict] = None
    # device launch accounting (engine/executor.py per-query counters):
    # with stacked segment batching, dispatches scale with batch FAMILIES,
    # not segments — these make the win visible per query
    num_device_dispatches: int = 0
    num_compiles: int = 0
    # segment partial-result cache outcome for this query (cache/partial.py):
    # kept segments served from cache vs actually executed
    num_segments_cache_hit: int = 0
    num_segments_cache_miss: int = 0
    # scatter/gather accounting (reference: numServersQueried/Responded in
    # BrokerResponseNative) — responded < queried implies a degraded path
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    # self-healing scatter/gather accounting (cluster/broker.py): RPCs
    # re-scattered to another replica, straggler RPCs duplicated after the
    # hedge delay, and hedges that beat their primary
    num_scatter_retries: int = 0
    num_hedged_requests: int = 0
    num_hedge_wins: int = 0
    # wire-integrity healing: scatter shards whose DataTable failed its
    # checksum and re-dispatched to another replica (the final answer is
    # still exact — the corrupt response never entered the merge)
    num_corrupt_shards_retried: int = 0
    # broker admission control shed this query (429-style rejection)
    query_rejected: bool = False
    # tiered storage: cold (metadata-only) segments still warming when the
    # response was assembled — the answer may be partial, never wrong
    cold_segments_warming: int = 0
    # continuous batching (engine/coalesce.py): peer queries whose family
    # dispatch this query shared (leader + followers all report the group
    # size minus themselves), and how long this query held for its group
    num_coalesced_queries: int = 0
    coalesce_wait_ms: float = 0.0

    def to_json(self) -> dict:
        out = {
            "resultTable": self.result_table.to_json() if self.result_table else None,
            "numDocsScanned": self.num_docs_scanned,
            "totalDocs": self.total_docs,
            "numSegmentsQueried": self.num_segments_queried,
            "numSegmentsProcessed": self.num_segments_processed,
            "numSegmentsPrunedByServer": self.num_segments_pruned,
            "timeUsedMs": self.time_used_ms,
            "exceptions": self.exceptions,
        }
        if self.trace_info is not None:
            out["traceInfo"] = self.trace_info
        if self.partial_result:
            out["partialResult"] = True
        if self.num_groups_limit_reached:
            out["numGroupsLimitReached"] = True
        if self.mse_stage_stats is not None:
            out["mseStageStats"] = {str(k): v for k, v in
                                    self.mse_stage_stats.items()}
        if self.num_device_dispatches:
            out["numDeviceDispatches"] = self.num_device_dispatches
            out["numCompiles"] = self.num_compiles
        if self.num_segments_cache_hit or self.num_segments_cache_miss:
            out["numSegmentsCacheHit"] = self.num_segments_cache_hit
            out["numSegmentsCacheMiss"] = self.num_segments_cache_miss
        if self.num_servers_queried:
            out["numServersQueried"] = self.num_servers_queried
            out["numServersResponded"] = self.num_servers_responded
        if self.num_scatter_retries:
            out["numScatterRetries"] = self.num_scatter_retries
        if self.num_hedged_requests:
            out["numHedgedRequests"] = self.num_hedged_requests
            out["numHedgeWins"] = self.num_hedge_wins
        if self.num_corrupt_shards_retried:
            out["numCorruptShardsRetried"] = self.num_corrupt_shards_retried
        if self.query_rejected:
            out["queryRejected"] = True
        if self.cold_segments_warming:
            out["coldSegmentsWarming"] = self.cold_segments_warming
        if self.num_coalesced_queries:
            out["numCoalescedQueries"] = self.num_coalesced_queries
            out["coalesceWindowMs"] = self.coalesce_wait_ms
        return out


# -- per-segment intermediates ----------------------------------------------


@dataclass
class GroupByIntermediate:
    """group key tuple (values) → list of per-agg states."""

    groups: dict[tuple, list]
    num_docs_scanned: int = 0
    # the numGroupsLimit trim dropped groups somewhere below (reference:
    # numGroupsLimitReached in the broker response metadata)
    groups_trimmed: bool = False


class GroupArrays(GroupByIntermediate):
    """Columnar group-by intermediate — the vectorized fast path.

    The dict-of-tuples form costs microseconds per group in Python; at the
    reference's numGroupsLimit (100K groups/segment) that dominates query
    time. Scalar reductions (COUNT/SUM/MIN/MAX/AVG/RANGE) instead travel as
    numpy columns: ``key_cols`` hold decoded group VALUES per dimension and
    ``state_cols[i]`` is a tuple of per-component arrays for aggregation i
    (avg → (sum, count)). ``vec_specs[i]`` gives each component's merge op
    ("add"|"min"|"max"); ``fin_tags[i]`` a picklable finalize recipe
    (("id",c) | ("div",a,b) | ("sub",a,b)) so the broker can finalize
    without callables crossing the wire.

    ``groups`` materializes the per-group dict lazily, so every general-path
    consumer (cluster broker merge, MSE, HAVING/post-agg reduce) keeps
    working unchanged.
    """

    def __init__(self, key_cols, state_cols, vec_specs, fin_tags,
                 num_docs_scanned: int = 0, groups_trimmed: bool = False):
        self.key_cols = list(key_cols)
        self.state_cols = [tuple(c) for c in state_cols]
        self.vec_specs = [tuple(s) for s in vec_specs]
        self.fin_tags = list(fin_tags)
        self.num_docs_scanned = num_docs_scanned
        self.groups_trimmed = groups_trimmed
        self._groups: Optional[dict] = None

    @property
    def num_groups(self) -> int:
        if self.key_cols:
            return len(self.key_cols[0])
        if self.state_cols:
            return len(self.state_cols[0][0])
        return 0

    @property
    def groups(self) -> dict:
        if self._groups is None:
            keys = list(zip(*(c.tolist() for c in self.key_cols)))
            per_agg = []
            for comps in self.state_cols:
                lists = [c.tolist() for c in comps]
                per_agg.append(lists[0] if len(lists) == 1 else list(zip(*lists)))
            self._groups = {
                k: [pa[j] for pa in per_agg] for j, k in enumerate(keys)}
        return self._groups

    @groups.setter
    def groups(self, value):  # general-path consumers may assign
        self._groups = value


@dataclass
class AggIntermediate:
    states: list  # one state per aggregation
    num_docs_scanned: int = 0


@dataclass
class SelectionIntermediate:
    columns: list[str]
    rows: list[tuple]
    num_docs_scanned: int = 0
