"""Query scheduler + resource accounting + query killing.

Reference analogues:
- QueryScheduler.submit (pinot-core/.../query/scheduler/QueryScheduler.java
  :93) with FCFS and token-bucket priority policies
  (MultiLevelPriorityQueue), picked by QuerySchedulerFactory.
- PerQueryCPUMemResourceUsageAccountant (pinot-core/.../accounting/
  PerQueryCPUMemAccountantFactory.java:70): samples per-query resource
  usage and interrupts the most expensive query under pressure (:832-937).

Cooperative cancellation: Python threads can't be interrupted, so queries
check their kill flag between segments (`check_cancel` from
QueryExecutor's segment loop) — the same effective granularity as the
reference, which also only interrupts between operator blocks.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..spi.metrics import SERVER_METRICS, ServerMeter, ServerTimer


class QueryKilledError(Exception):
    """Reference: QueryCancelledException from the accountant interrupt."""


class QueryRejectedError(Exception):
    """Admission control rejection (scheduler queue full)."""


@dataclass
class QueryResourceTracker:
    query_id: str
    scheduler_group: str = "default"
    start_time: float = field(default_factory=time.perf_counter)
    cpu_ns: int = 0
    allocated_bytes: int = 0
    _kill_reason: Optional[str] = None

    def add_cpu_ns(self, ns: int) -> None:
        self.cpu_ns += ns

    def add_allocated_bytes(self, n: int) -> None:
        self.allocated_bytes += n

    def kill(self, reason: str) -> None:
        self._kill_reason = reason

    def check_cancel(self) -> None:
        if self._kill_reason is not None:
            SERVER_METRICS.add_meter(ServerMeter.QUERIES_KILLED)
            raise QueryKilledError(self._kill_reason)

    @property
    def cost(self) -> int:
        """Ranking for the kill heuristic (reference ranks by allocated
        bytes, falling back to CPU time)."""
        return self.allocated_bytes or self.cpu_ns


class ResourceAccountant:
    """Tracks in-flight queries; kills the most expensive one when the
    memory budget is exceeded (reference: the watcher task heap-pressure
    path). Budget is an explicit byte budget for query intermediates —
    there is no JVM heap to watch."""

    def __init__(self, memory_budget_bytes: Optional[int] = None,
                 tombstone_ttl_s: float = 10.0):
        self.memory_budget_bytes = memory_budget_bytes
        self.tombstone_ttl_s = tombstone_ttl_s
        self._lock = threading.Lock()
        self._inflight: dict[str, QueryResourceTracker] = {}
        # cancel-before-register race: a cancel that arrives before the
        # query registers leaves a short-TTL tombstone — id (or shard-id
        # prefix) → (reason, expiry, is_prefix) — so the late-registering
        # query is killed on arrival instead of running to completion
        self._tombstones: dict[str, tuple[str, float, bool]] = {}

    def start_query(self, query_id: Optional[str] = None,
                    group: str = "default") -> QueryResourceTracker:
        t = QueryResourceTracker(query_id or uuid.uuid4().hex[:12], group)
        reason = None
        with self._lock:
            if self._tombstones:
                reason = self._tombstone_match_locked(t.query_id)
            self._inflight[t.query_id] = t
        if reason is not None:
            t.kill(reason)
        return t

    def _tombstone_match_locked(self, query_id: str) -> Optional[str]:
        now = time.monotonic()
        expired = [k for k, (_r, exp, _p) in self._tombstones.items()
                   if exp <= now]
        for k in expired:
            del self._tombstones[k]
        for key, (reason, _exp, is_prefix) in self._tombstones.items():
            if query_id == key or (
                    is_prefix and query_id.startswith(key + ":")):
                return reason
        return None

    def _tombstone_locked(self, key: str, reason: str,
                          is_prefix: bool) -> None:
        self._tombstones[key] = (
            reason, time.monotonic() + self.tombstone_ttl_s, is_prefix)

    def end_query(self, tracker: QueryResourceTracker) -> None:
        with self._lock:
            self._inflight.pop(tracker.query_id, None)

    def on_allocation(self, tracker: QueryResourceTracker, n_bytes: int) -> None:
        tracker.add_allocated_bytes(n_bytes)
        self.maybe_kill()

    def total_allocated(self) -> int:
        with self._lock:
            return sum(t.allocated_bytes for t in self._inflight.values())

    def maybe_kill(self) -> Optional[str]:
        """If over budget, flag the most expensive in-flight query
        (reference :832-937 interrupts the runner thread of the costliest
        query)."""
        if self.memory_budget_bytes is None:
            return None
        with self._lock:
            total = sum(t.allocated_bytes for t in self._inflight.values())
            if total <= self.memory_budget_bytes:
                return None
            victim = max(self._inflight.values(), key=lambda t: t.cost,
                         default=None)
        if victim is not None:
            victim.kill(
                f"query {victim.query_id} killed: intermediates "
                f"{total} bytes exceed budget {self.memory_budget_bytes}")
            return victim.query_id
        return None

    def kill_query(self, query_id: str, reason: str = "killed by admin") -> bool:
        with self._lock:
            t = self._inflight.get(query_id)
            if t is None:
                # not registered (yet): tombstone the id so a query that
                # lost the race to the cancel RPC still dies on arrival
                self._tombstone_locked(query_id, reason, is_prefix=False)
        if t is None:
            return False
        t.kill(reason)
        return True

    def kill_prefix(self, prefix: str,
                    reason: str = "killed by admin") -> int:
        """Kill every in-flight query whose id is ``prefix`` or a shard of
        it (``prefix:<n>`` — the broker stamps one shard id per scatter
        RPC), and tombstone the prefix so late-registering shards die on
        arrival. Returns the number of live trackers killed."""
        with self._lock:
            victims = [t for qid, t in self._inflight.items()
                       if qid == prefix or qid.startswith(prefix + ":")]
            self._tombstone_locked(prefix, reason, is_prefix=True)
        for t in victims:
            t.kill(reason)
        return len(victims)

    def inflight(self) -> list[str]:
        with self._lock:
            return sorted(self._inflight)


GLOBAL_ACCOUNTANT = ResourceAccountant()


class QueryScheduler:
    """Bounded-concurrency admission control (reference FCFS policy:
    fcfs QuerySchedulerFactory default)."""

    def __init__(self, max_concurrent: int = 8, max_pending: int = 64,
                 accountant: Optional[ResourceAccountant] = None):
        self.max_concurrent = max_concurrent
        self.max_pending = max_pending
        self.accountant = accountant or GLOBAL_ACCOUNTANT
        self._sem = threading.Semaphore(max_concurrent)
        self._pending = 0
        self._lock = threading.Lock()
        self.wait_ms_total = 0.0

    def submit(self, fn: Callable, *args, group: str = "default",
               timeout_s: float = 60.0, query_id: Optional[str] = None,
               **kwargs):
        """Run fn(tracker, *args) under admission control. ``timeout_s``
        bounds queue wait (deadline propagation: the server passes the
        query's remaining budget); ``query_id`` names the tracker so a
        broker-sent cancel can find it via ``kill_query``."""
        with self._lock:
            if self._pending >= self.max_pending:
                SERVER_METRICS.add_meter(ServerMeter.QUERIES_REJECTED)
                raise QueryRejectedError(
                    f"scheduler queue full ({self.max_pending} pending)")
            self._pending += 1
        t0 = time.perf_counter()
        try:
            if not self._sem.acquire(timeout=timeout_s):
                SERVER_METRICS.add_meter(ServerMeter.QUERIES_REJECTED)
                raise QueryRejectedError("scheduler wait timeout")
        finally:
            with self._lock:
                self._pending -= 1
        wait_ms = (time.perf_counter() - t0) * 1000
        self.wait_ms_total += wait_ms
        # reference ServerQueryPhase.SCHEDULER_WAIT: admission-control
        # latency into the server timer histogram
        SERVER_METRICS.update_timer(ServerTimer.SCHEDULER_WAIT_MS, wait_ms)
        tracker = self.accountant.start_query(query_id=query_id, group=group)
        try:
            return fn(tracker, *args, **kwargs)
        finally:
            self.accountant.end_query(tracker)
            self._sem.release()


class PriorityQueryScheduler(QueryScheduler):
    """Token-bucket fairness across scheduler groups (reference:
    MultiLevelPriorityQueue / TokenPriorityScheduler): a group that has
    consumed more CPU-milliseconds waits behind lighter groups when the
    cluster is saturated."""

    def __init__(self, max_concurrent: int = 8, max_pending: int = 64,
                 accountant: Optional[ResourceAccountant] = None):
        super().__init__(max_concurrent, max_pending, accountant)
        self._tokens_used: dict[str, float] = {}
        self._waiting: dict[str, int] = {}
        self._cv = threading.Condition()
        self._running = 0

    def submit(self, fn: Callable, *args, group: str = "default",
               timeout_s: float = 60.0, query_id: Optional[str] = None,
               **kwargs):
        deadline = time.monotonic() + timeout_s
        t_wait = time.perf_counter()
        with self._cv:
            if self._pending >= self.max_pending:
                SERVER_METRICS.add_meter(ServerMeter.QUERIES_REJECTED)
                raise QueryRejectedError("scheduler queue full")
            self._pending += 1
            self._waiting[group] = self._waiting.get(group, 0) + 1
            try:
                while self._running >= self.max_concurrent or not \
                        self._my_turn(group):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        SERVER_METRICS.add_meter(ServerMeter.QUERIES_REJECTED)
                        raise QueryRejectedError("scheduler wait timeout")
                    self._cv.wait(min(remaining, 0.05))
                self._running += 1
            finally:
                self._pending -= 1
                self._waiting[group] -= 1
                if not self._waiting[group]:
                    del self._waiting[group]
        wait_ms = (time.perf_counter() - t_wait) * 1000
        self.wait_ms_total += wait_ms
        SERVER_METRICS.update_timer(ServerTimer.SCHEDULER_WAIT_MS, wait_ms)
        tracker = self.accountant.start_query(query_id=query_id, group=group)
        t0 = time.perf_counter()
        try:
            return fn(tracker, *args, **kwargs)
        finally:
            used = (time.perf_counter() - t0) * 1000
            with self._cv:
                self._tokens_used[group] = self._tokens_used.get(group, 0.0) + used
                self._running -= 1
                self._cv.notify_all()
            self.accountant.end_query(tracker)

    def _my_turn(self, group: str) -> bool:
        """Contention resolves toward the group with the fewest consumed
        tokens — but only among groups WAITING right now; a lone waiter
        always proceeds (otherwise historical heavy groups would starve)."""
        mine = self._tokens_used.get(group, 0.0)
        return all(mine <= self._tokens_used.get(g, 0.0)
                   for g in self._waiting if g != group)
