"""Shared selection materialization used by BOTH backends.

One implementation so the device and host engines cannot diverge on
cap/sort/trim semantics (reference: SelectionOperatorService ordering rules).
"""

from __future__ import annotations

import numpy as np

from .aggregation import UnsupportedQueryError
from .results import SelectionIntermediate


def selection_from_mask(query, segment, columns: list[str], mask: np.ndarray) -> SelectionIntermediate:
    """Materialize selected rows from a boolean doc mask (len == num_docs).

    Without ORDER BY, rows are capped at offset+limit per segment; with
    ORDER BY, rows sort per segment then trim to offset+limit (a valid
    per-segment top-k — the broker re-sorts the merged rows)."""
    doc_ids = np.nonzero(mask)[0]
    total = int(doc_ids.shape[0])
    cap = query.offset + query.limit
    if not query.order_by_expressions:
        doc_ids = doc_ids[:cap]
    cols = [segment.get_values(c)[doc_ids] for c in columns]
    rows = list(zip(*[c.tolist() for c in cols])) if cols else []
    if query.order_by_expressions:
        idx = {c: i for i, c in enumerate(columns)}
        for ob in reversed(query.order_by_expressions):
            if not ob.expression.is_identifier or ob.expression.identifier not in idx:
                raise UnsupportedQueryError("selection ORDER BY must reference selected columns")
            ci = idx[ob.expression.identifier]
            rows.sort(key=lambda r, _ci=ci: r[_ci], reverse=not ob.ascending)
        rows = rows[:cap]
    return SelectionIntermediate(columns, rows, num_docs_scanned=total)
