"""Shared selection materialization used by BOTH backends.

One implementation so the device and host engines cannot diverge on
cap/sort/trim semantics (reference: SelectionOperatorService ordering rules).
"""

from __future__ import annotations

import numpy as np

from .aggregation import UnsupportedQueryError
from .results import SelectionIntermediate


def selection_from_mask(query, segment, columns: list[str], mask: np.ndarray,
                        extra_exprs: dict | None = None,
                        evaluator=None) -> SelectionIntermediate:
    """Materialize selected rows from a boolean doc mask (len == num_docs).

    Without ORDER BY, rows are capped at offset+limit per segment; with
    ORDER BY, rows sort per segment then trim to offset+limit (a valid
    per-segment top-k — the broker re-sorts the merged rows).

    ``extra_exprs`` maps expression labels (appearing in ``columns``) →
    ExpressionContext for transform select/order expressions;
    ``evaluator(expr, doc_ids)`` materializes one of them over the already-
    filtered (and, without ORDER BY, already-capped) doc ids only."""
    doc_ids = np.nonzero(mask)[0]
    total = int(doc_ids.shape[0])
    cap = query.offset + query.limit
    if not query.order_by_expressions:
        doc_ids = doc_ids[:cap]

    def column_values(c: str) -> np.ndarray:
        if extra_exprs is not None and c in extra_exprs:
            return np.asarray(evaluator(extra_exprs[c], doc_ids))
        return segment.get_values(c)[doc_ids]

    cols = [column_values(c) for c in columns]
    rows = list(zip(*[c.tolist() for c in cols])) if cols else []
    if query.order_by_expressions:
        idx = {c: i for i, c in enumerate(columns)}
        order = list(range(len(rows)))
        for ob in reversed(query.order_by_expressions):
            key = (ob.expression.identifier if ob.expression.is_identifier
                   else str(ob.expression))
            if key not in idx:
                raise UnsupportedQueryError(
                    "selection ORDER BY must reference selected columns")
            arr = cols[idx[key]].tolist()
            order.sort(key=lambda i, _a=arr: _a[i], reverse=not ob.ascending)
        rows = [rows[i] for i in order[:cap]]
    return SelectionIntermediate(columns, rows, num_docs_scanned=total)


def selection_columns_for(query, segment) -> tuple[list[str], dict]:
    """(column labels incl. hidden ORDER BY-only transforms, label → expr map
    for the transform columns). Shared by both planners so the intermediates
    always carry every column the broker needs to re-sort; the reducer
    projects hidden columns away after the final sort."""
    cols: list[str] = []
    exprs: dict = {}
    for e in query.select_expressions:
        if e.is_identifier:
            if e.identifier == "*":
                cols.extend(segment.columns())
            else:
                cols.append(e.identifier)
        else:
            label = str(e)
            cols.append(label)
            exprs[label] = e
    for ob in query.order_by_expressions:
        if not ob.expression.is_identifier:
            label = str(ob.expression)
            if label not in cols:
                cols.append(label)
                exprs[label] = ob.expression
    return cols, exprs
