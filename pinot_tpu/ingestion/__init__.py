from .transform import CompositeTransformer, build_transform_pipeline  # noqa: F401
