"""Batch ingestion: job spec → segments → push.

Reference analogue: the batch ingestion spec model (pinot-spi/.../spi/
ingestion/batch/spec/SegmentGenerationJobSpec.java — YAML job files), the
standalone runner (pinot-plugins/pinot-batch-ingestion/
pinot-batch-ingestion-standalone/ SegmentGenerationJobRunner), and
IngestionJobLauncher + SegmentPushUtils (SURVEY.md §3.4): per input file,
RecordReader → TransformPipeline → two-pass segment build → push (copy to
deep store + controller metadata registration).
"""

from __future__ import annotations

import tarfile
import tempfile
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from ..plugins.inputformat import create_record_reader
from ..segment.builder import SegmentBuilder
from ..spi.data_types import Schema
from ..spi.filesystem import get_fs
from ..spi.table_config import TableConfig
from .transform import build_transform_pipeline


@dataclass
class SegmentGenerationJobSpec:
    """Reference: SegmentGenerationJobSpec.java (11 spec classes collapsed
    to the fields the runner consumes)."""

    input_dir_uri: str
    output_dir_uri: str
    schema: Schema
    table_config: TableConfig
    input_format: Optional[str] = None  # None → infer per file extension
    record_reader_config: dict = field(default_factory=dict)
    include_file_name_pattern: Optional[str] = None  # glob, e.g. "*.csv"
    segment_name_prefix: Optional[str] = None
    overwrite_output: bool = True
    create_tar: bool = False  # reference pushes tar.gz; dirs are the default here
    # standalone = in-process sequential; multiprocess = one build per
    # worker process (the Spark/Hadoop runner analogue — the reference
    # distributes file→segment tasks over executors,
    # pinot-plugins/pinot-batch-ingestion/pinot-batch-ingestion-spark-3/
    # SparkSegmentGenerationJobRunner; here the unit of distribution is a
    # local process pool, and the FS abstraction keeps inputs/outputs on
    # shared/object storage exactly as the cluster runners do)
    execution_framework: str = "standalone"
    parallelism: Optional[int] = None  # multiprocess worker count
    # module imported in each worker before building — re-registers
    # process-global state (custom index types, stream decoders) that a
    # spawned worker would not inherit (reference: plugin jars shipped to
    # Spark executors via --jars)
    worker_setup_module: Optional[str] = None

    @classmethod
    def from_yaml(cls, path: str, schema: Schema,
                  table_config: TableConfig) -> "SegmentGenerationJobSpec":
        import yaml

        d = yaml.safe_load(Path(path).read_text())
        rr = d.get("recordReaderSpec", {})
        return cls(
            input_dir_uri=d["inputDirURI"],
            output_dir_uri=d["outputDirURI"],
            schema=schema,
            table_config=table_config,
            input_format=rr.get("dataFormat"),
            record_reader_config=rr.get("configs", {}) or {},
            include_file_name_pattern=d.get("includeFileNamePattern"),
            segment_name_prefix=(d.get("segmentNameGeneratorSpec", {}) or {})
            .get("configs", {}).get("segment.name.prefix"),
        )


@dataclass
class SegmentGenerationResult:
    segment_name: str
    output_uri: str
    num_docs: int
    rows_filtered: int
    # {col: [partition ids]} from builder stamping (segmentPartitionConfig)
    partitions: dict = field(default_factory=dict)


class IngestionJobLauncher:
    """Reference: IngestionJobLauncher.runIngestionJob — resolves input
    files, runs one segment build per file, pushes outputs."""

    def __init__(self, spec: SegmentGenerationJobSpec):
        self.spec = spec

    def list_input_files(self) -> list[str]:
        fs = get_fs(self.spec.input_dir_uri)
        files = fs.list_files(self.spec.input_dir_uri, recursive=True)
        pat = self.spec.include_file_name_pattern
        if pat:
            from fnmatch import fnmatch

            files = [f for f in files if fnmatch(Path(f).name, pat)]
        return files

    def run(self) -> list[SegmentGenerationResult]:
        files = self.list_input_files()
        if not files:
            raise FileNotFoundError(
                f"no input files under {self.spec.input_dir_uri}")
        out_fs = get_fs(self.spec.output_dir_uri)
        out_fs.mkdir(self.spec.output_dir_uri)
        fw = self.spec.execution_framework
        if fw == "multiprocess" and len(files) > 1:
            import concurrent.futures as cf
            import multiprocessing
            import os

            workers = self.spec.parallelism or min(len(files),
                                                   os.cpu_count() or 1)
            # spawn, explicitly: fork from a threaded parent can deadlock,
            # and spawn makes worker state deterministic everywhere — any
            # process-global registrations come back via worker_setup_module
            with cf.ProcessPoolExecutor(
                    max_workers=workers,
                    mp_context=multiprocessing.get_context("spawn"),
                    initializer=_worker_init,
                    initargs=(self.spec.worker_setup_module,)) as pool:
                futs = [pool.submit(_generate_one_job, self.spec, path, seq)
                        for seq, path in enumerate(files)]
                # fail-fast like the reference runners: one failed file
                # task fails the job (results keep input order)
                return [f.result() for f in futs]
        if fw not in ("standalone", "multiprocess"):
            raise ValueError(
                f"unknown executionFrameworkSpec {fw!r} "
                "(standalone | multiprocess)")
        return [self._generate_one(path, seq)
                for seq, path in enumerate(files)]

    def _generate_one(self, path: str, seq: int) -> SegmentGenerationResult:
        return _generate_one_job(self.spec, path, seq)


def _worker_init(setup_module: Optional[str]) -> None:
    if setup_module:
        import importlib

        importlib.import_module(setup_module)


def upload_segment_from_rows(schema: Schema, table_config, segment_name: str,
                             rows, out_dir_uri: str,
                             create_tar: bool = False) -> tuple[str, dict]:
    """Rows → two-pass segment build → upload to the output FS. Returns
    (out_uri, partition stamps). The ONE build-and-upload recipe shared by
    the batch runners and the streaming sink, so metadata (partition
    stamps, tar layout) can't diverge between push paths."""
    with tempfile.TemporaryDirectory() as tmp:
        local = Path(tmp) / segment_name
        SegmentBuilder(schema, table_config, segment_name) \
            .build_from_rows(rows, local)
        from ..segment.format import partition_push_metadata

        parts = partition_push_metadata(local).get("partitions", {})
        out_uri = f"{out_dir_uri.rstrip('/')}/{segment_name}"
        fs = get_fs(out_dir_uri)
        fs.mkdir(out_dir_uri)
        if create_tar:
            tar_path = Path(tmp) / f"{segment_name}.tar.gz"
            with tarfile.open(tar_path, "w:gz") as tf:
                tf.add(local, arcname=segment_name)
            out_uri += ".tar.gz"
            fs.copy_from_local(str(tar_path), out_uri)
        else:
            fs.copy_from_local(str(local), out_uri)
    return out_uri, parts


def _generate_one_job(spec: SegmentGenerationJobSpec, path: str,
                      seq: int) -> SegmentGenerationResult:
    """File → segment → push, self-contained so worker processes can run it
    (reference: SegmentGenerationTaskRunner inside each Spark executor)."""
    prefix = spec.segment_name_prefix or spec.table_config.table_name
    segment_name = f"{prefix}_{seq}"
    reader = create_record_reader(path, spec.input_format,
                                  spec.record_reader_config)
    pipeline = build_transform_pipeline(spec.schema, spec.table_config)
    rows = []
    filtered = 0
    for raw in reader:
        row = pipeline.transform(dict(raw))
        if row is None:
            filtered += 1
            continue
        rows.append(row)
    out_uri, parts = upload_segment_from_rows(
        spec.schema, spec.table_config, segment_name, rows,
        spec.output_dir_uri, create_tar=spec.create_tar)
    return SegmentGenerationResult(segment_name, out_uri, len(rows), filtered,
                                   partitions=parts)


def push_segments_to_cluster(results: list[SegmentGenerationResult],
                             controller, table_name_with_type: str,
                             extra_meta: Optional[dict] = None) -> None:
    """Metadata push (reference: SegmentPushUtils → controller
    /v2/segments): register each built segment's location + doc count with
    the cluster controller, which assigns replicas and updates the ideal
    state. ``extra_meta`` merges into every segment's metadata (e.g. the
    distributed runner's ``inputFile`` dedup marker)."""
    for r in results:
        meta = {"location": r.output_uri, "numDocs": r.num_docs}
        if r.partitions:
            meta["partitions"] = r.partitions
        if extra_meta:
            meta.update(extra_meta)
        controller.add_segment(table_name_with_type, r.segment_name, meta)


def untar_segment(tar_uri: str, dest_dir: str) -> str:
    """Server-side fetch+untar (reference: SegmentFetcherFactory + untar on
    OFFLINE→ONLINE)."""
    fs = get_fs(tar_uri)
    with tempfile.TemporaryDirectory() as tmp:
        local = Path(tmp) / Path(tar_uri).name
        fs.copy_to_local(tar_uri, str(local))
        with tarfile.open(local, "r:gz") as tf:
            tf.extractall(dest_dir, filter="data")
    name = Path(tar_uri).name
    for suffix in (".tar.gz", ".tgz"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    return str(Path(dest_dir) / name)
