"""Row-level ingestion transform pipeline.

Reference: pinot-segment-local/.../recordtransformer/ (CompositeTransformer
ordering: complex-type flatten → filter → expression → data-type coercion →
null handling → sanitization → time validation) and the scalar-function
registry those expressions call (pinot-common/.../function/). Expressions
evaluate through the shared transform registry (query/transforms.py) so
ingestion-time and query-time semantics are one implementation.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..query.expressions import ExpressionContext
from ..query.parser.sql import parse_expression_str
from ..query.transforms import eval_expr_np
from ..spi.data_types import DataType, Schema, coerce_value


def eval_row_expression(e: ExpressionContext, row: dict):
    """Evaluate an expression against one row dict (scalars in/out)."""

    def resolve(name: str):
        if name not in row:
            raise KeyError(name)
        return row[name]

    out = eval_expr_np(e, resolve)
    if isinstance(out, np.generic):
        return out.item()
    if isinstance(out, np.ndarray):
        return out.tolist()
    return out


class RecordTransformer:
    """transform(row) → row (possibly mutated) or None to drop it."""

    def transform(self, row: dict) -> Optional[dict]:
        raise NotImplementedError


class ComplexTypeTransformer(RecordTransformer):
    """Flatten nested dicts into dotted column names (reference
    ComplexTypeTransformer default '.' delimiter); lists of scalars pass
    through as MV values."""

    def __init__(self, delimiter: str = "."):
        self.delimiter = delimiter

    def transform(self, row: dict) -> Optional[dict]:
        if not any(isinstance(v, dict) for v in row.values()):
            return row
        out: dict = {}
        for k, v in row.items():
            if isinstance(v, dict):
                for ik, iv in self._flatten(v).items():
                    out[f"{k}{self.delimiter}{ik}"] = iv
            else:
                out[k] = v
        return out

    def _flatten(self, d: dict) -> dict:
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                for ik, iv in self._flatten(v).items():
                    out[f"{k}{self.delimiter}{ik}"] = iv
            else:
                out[k] = v
        return out


class FilterTransformer(RecordTransformer):
    """Drops rows where the filter function evaluates true (reference
    FilterTransformer — note the inverted semantics: true = filtered OUT)."""

    def __init__(self, filter_function: str):
        self.expr = parse_expression_str(filter_function)

    def transform(self, row: dict) -> Optional[dict]:
        try:
            drop = bool(eval_row_expression(self.expr, row))
        except Exception:
            drop = False
        return None if drop else row


class ExpressionTransformer(RecordTransformer):
    """Derives columns from transform expressions; skips when the source
    value is already present (reference ExpressionTransformer)."""

    def __init__(self, transform_configs: list[dict]):
        self.derived: list[tuple[str, ExpressionContext]] = [
            (c["columnName"], parse_expression_str(c["transformFunction"]))
            for c in transform_configs or []
        ]

    def transform(self, row: dict) -> Optional[dict]:
        for column, expr in self.derived:
            if row.get(column) is None:
                try:
                    row[column] = eval_row_expression(expr, row)
                except Exception:
                    row[column] = None
        return row


class DataTypeTransformer(RecordTransformer):
    """Coerces values to the schema's declared types; unparseable values
    become None (→ null handling downstream)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def transform(self, row: dict) -> Optional[dict]:
        for name, spec in self.schema.fields.items():
            v = row.get(name)
            if v is None:
                continue
            try:
                if spec.single_value:
                    row[name] = _coerce(v, DataType(spec.data_type))
                else:
                    vals = v if isinstance(v, (list, tuple)) else [v]
                    row[name] = [_coerce(x, DataType(spec.data_type)) for x in vals]
            except (TypeError, ValueError):
                row[name] = None
        return row


class NullValueTransformer(RecordTransformer):
    """Missing schema columns become explicit None so the segment writer
    records them in the null vector (reference NullValueTransformer)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def transform(self, row: dict) -> Optional[dict]:
        for name in self.schema.fields:
            if name not in row:
                row[name] = None
        return row


class TimeValidationTransformer(RecordTransformer):
    """Rejects rows whose time value is outside a sane epoch window
    (reference TimeValidationTransformer / TimeUtils.timeValueInValidRange:
    1971-01-01 .. 2071-01-01 millis)."""

    _MIN_MS = 31_536_000_000
    _MAX_MS = 3_187_296_000_000

    def __init__(self, time_column: Optional[str]):
        self.time_column = time_column

    def transform(self, row: dict) -> Optional[dict]:
        if not self.time_column:
            return row
        v = row.get(self.time_column)
        if v is None:
            return row
        try:
            t = int(v)
        except (TypeError, ValueError):
            return None
        return row if self._MIN_MS <= t <= self._MAX_MS else None


class SpecialValueTransformer(RecordTransformer):
    """NaN/Inf float values → None (reference SpecialValueTransformer)."""

    def __init__(self, schema: Schema):
        self.float_cols = [n for n, s in schema.fields.items()
                           if DataType(s.data_type) in (DataType.FLOAT, DataType.DOUBLE)]

    def transform(self, row: dict) -> Optional[dict]:
        for name in self.float_cols:
            v = row.get(name)
            if isinstance(v, float) and (v != v or v in (float("inf"), float("-inf"))):
                row[name] = None
        return row


class CompositeTransformer(RecordTransformer):
    def __init__(self, transformers: list[RecordTransformer]):
        self.transformers = transformers

    def transform(self, row: dict) -> Optional[dict]:
        for t in self.transformers:
            row = t.transform(row)
            if row is None:
                return None
        return row


_coerce = coerce_value


def build_transform_pipeline(schema: Schema, table_config=None) -> CompositeTransformer:
    """Standard ordering (reference CompositeTransformer.getDefaultTransformers)."""
    ing = getattr(table_config, "ingestion", None)
    val = getattr(table_config, "validation", None)
    ts: list[RecordTransformer] = [ComplexTypeTransformer()]
    if ing is not None and ing.filter_function:
        ts.append(FilterTransformer(ing.filter_function))
    if ing is not None and ing.transform_configs:
        ts.append(ExpressionTransformer(ing.transform_configs))
    ts.append(DataTypeTransformer(schema))
    ts.append(SpecialValueTransformer(schema))
    ts.append(TimeValidationTransformer(val.time_column_name if val else None))
    ts.append(NullValueTransformer(schema))
    return CompositeTransformer(ts)
