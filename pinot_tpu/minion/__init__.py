"""Minion: stateless task execution framework + built-in tasks.

Reference analogue: pinot-minion (BaseMinionStarter, task registry via
@TaskExecutorFactory) + the Helix task framework orchestration on the
controller (PinotTaskManager, PinotHelixTaskResourceManager —
pinot-controller/.../helix/core/minion/) + built-in tasks
(pinot-plugins/pinot-minion-tasks/pinot-minion-builtin-tasks/).
"""

from .framework import MinionInstance, PinotTaskManager, TaskSpec
from . import tasks  # noqa: F401 — registers built-in executors

__all__ = ["MinionInstance", "PinotTaskManager", "TaskSpec"]
