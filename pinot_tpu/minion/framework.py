"""Minion task framework: generation, queueing, claiming, execution.

Reference analogue: the Helix task framework as Pinot uses it —
PinotTaskManager generates task configs from each table's taskConfig
(pinot-controller/.../helix/core/minion/PinotTaskManager.java), tasks queue
in ZK, minions claim and run them via registered executors
(pinot-minion/.../taskfactory/TaskFactoryRegistry.java). Store layout:

  /TASKS/{taskType}/{taskId} → {state: PENDING|RUNNING|COMPLETED|ERROR,
                                table, config, owner, output, error}
"""

from __future__ import annotations

import threading
import time
import traceback
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..cluster.controller import ClusterController
from ..cluster.store import BadVersionError, PropertyStore

PENDING = "PENDING"
RUNNING = "RUNNING"
COMPLETED = "COMPLETED"
ERROR = "ERROR"


@dataclass
class TaskSpec:
    task_type: str
    table: str  # tableNameWithType
    config: dict = field(default_factory=dict)
    task_id: str = ""

    def path(self) -> str:
        return f"/TASKS/{self.task_type}/{self.task_id}"


# taskType → generator(controller, table, task_cfg) -> list[TaskSpec]
_GENERATORS: dict[str, Callable] = {}
# taskType → executor(ctx, spec) -> dict (output)
_EXECUTORS: dict[str, Callable] = {}


def register_task_generator(task_type: str, fn: Callable) -> None:
    _GENERATORS[task_type] = fn


def register_task_executor(task_type: str, fn: Callable) -> None:
    _EXECUTORS[task_type] = fn


class PinotTaskManager:
    """Controller-side: reads each table's taskConfigs and enqueues task
    specs (reference: PinotTaskManager.scheduleTasks)."""

    def __init__(self, store: PropertyStore, controller: ClusterController):
        self.store = store
        self.controller = controller

    def schedule_tasks(self, table: Optional[str] = None,
                       task_type: Optional[str] = None) -> list[str]:
        tables = [table] if table else self.store.children("/CONFIGS/TABLE")
        scheduled = []
        for t in tables:
            cfg = self.controller.table_config(t) or {}
            for ttype, task_cfg in (cfg.get("taskConfigs") or {}).items():
                if task_type and ttype != task_type:
                    continue
                gen = _GENERATORS.get(ttype)
                if gen is None:
                    raise ValueError(f"no generator for task type {ttype}")
                for spec in gen(self.controller, t, task_cfg or {}):
                    spec.task_id = spec.task_id or f"{ttype}_{uuid.uuid4().hex[:12]}"
                    self.store.set(spec.path(), {
                        "state": PENDING, "table": spec.table,
                        "taskType": spec.task_type, "config": spec.config,
                        "owner": None, "output": None, "error": None,
                        "scheduledAtMs": int(time.time() * 1000)})
                    scheduled.append(spec.task_id)
        return scheduled

    def task_state(self, task_type: str, task_id: str) -> Optional[dict]:
        return self.store.get(f"/TASKS/{task_type}/{task_id}")

    def wait_all(self, timeout_s: float = 30.0) -> bool:
        """Wait for every queued task to reach a terminal state."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            states = []
            for ttype in self.store.children("/TASKS"):
                for tid in self.store.children(f"/TASKS/{ttype}"):
                    states.append(self.store.get(f"/TASKS/{ttype}/{tid}")["state"])
            if all(s in (COMPLETED, ERROR) for s in states):
                return True
            time.sleep(0.02)
        return False


@dataclass
class TaskContext:
    """What executors get to work with (reference: MinionContext +
    controller API access through MinionTaskBaseObserver helpers)."""

    store: PropertyStore
    controller: ClusterController
    work_dir: str


class MinionInstance:
    """Claims PENDING tasks via CAS and runs registered executors
    (reference: BaseMinionStarter + TaskFactoryRegistry; the Helix task
    runner thread pool becomes a poll thread here)."""

    def __init__(self, store: PropertyStore, instance_id: str,
                 controller: ClusterController, work_dir: str,
                 poll_interval_s: float = 0.02):
        self.store = store
        self.instance_id = instance_id
        self.controller = controller
        self.work_dir = work_dir
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tasks_run = 0

    def start(self) -> None:
        self.store.set(f"/INSTANCECONFIGS/{self.instance_id}",
                       {"type": "MINION", "tags": ["minion_untagged"]})
        self.store.set(f"/LIVEINSTANCES/{self.instance_id}", {"type": "MINION"},
                       ephemeral_owner=self.instance_id)
        self._thread = threading.Thread(target=self._poll_loop,
                                        name=f"minion-{self.instance_id}",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10)
        self.store.expire_session(self.instance_id)

    def run_pending_once(self) -> int:
        """Synchronous drain for tests/CLI."""
        n = 0
        while self._claim_and_run_one():
            n += 1
        return n

    # -- internals ----------------------------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            if not self._claim_and_run_one():
                time.sleep(self.poll_interval_s)

    def _claim_and_run_one(self) -> bool:
        for ttype in self.store.children("/TASKS"):
            for tid in self.store.children(f"/TASKS/{ttype}"):
                path = f"/TASKS/{ttype}/{tid}"
                task, version = self.store.get_with_version(path)
                if task is None or task["state"] != PENDING:
                    continue
                claimed = dict(task, state=RUNNING, owner=self.instance_id)
                try:
                    self.store.set(path, claimed, expected_version=version)
                except BadVersionError:
                    continue  # another minion won the claim
                self._execute(path, claimed)
                return True
        return False

    def _execute(self, path: str, task: dict) -> None:
        executor = _EXECUTORS.get(task["taskType"])
        ctx = TaskContext(self.store, self.controller, self.work_dir)
        spec = TaskSpec(task["taskType"], task["table"], task["config"],
                        path.rsplit("/", 1)[-1])
        try:
            if executor is None:
                raise ValueError(f"no executor for {task['taskType']}")
            output = executor(ctx, spec)
            self.store.update(path, lambda t: dict(
                t, state=COMPLETED, output=output))
        except Exception as e:
            self.store.update(path, lambda t: dict(
                t, state=ERROR, error=f"{type(e).__name__}: {e}",
                traceback=traceback.format_exc()[-2000:]))
        finally:
            self.tasks_run += 1
