"""Built-in minion tasks.

Reference analogue: pinot-plugins/pinot-minion-tasks/
pinot-minion-builtin-tasks/.../tasks/ — MergeRollupTask,
RealtimeToOfflineSegmentsTask, PurgeTask, RefreshSegmentTask,
UpsertCompactionTask, SegmentGenerationAndPushTask. Each is a
(generator, executor) pair registered with the framework; generators run on
the controller (PinotTaskManager), executors on minions.
"""

from __future__ import annotations

import time
from pathlib import Path

import numpy as np

from ..cluster.controller import raw_table_name, table_name_with_type
from ..query.parser.sql import parse_filter_expression
from ..segment.builder import SegmentBuilder
from ..segment.loader import load_segment
from ..spi.data_types import Schema
from ..spi.table_config import TableConfig
from .framework import (
    TaskContext,
    TaskSpec,
    register_task_executor,
    register_task_generator,
)


# -- shared helpers ----------------------------------------------------------


def _schema_of(ctx: TaskContext, table: str) -> Schema:
    raw = raw_table_name(table)
    d = ctx.store.get(f"/SCHEMAS/{raw}")
    if d is None:
        raise KeyError(f"schema {raw} not registered")
    return Schema.from_json(d)


def _table_config_of(ctx: TaskContext, table: str) -> TableConfig:
    cfg = ctx.controller.table_config(table) or {}
    return TableConfig(table_name=raw_table_name(table))if not cfg.get("pinotConfig") \
        else TableConfig.from_json(cfg["pinotConfig"])


def _load(ctx: TaskContext, table: str, segment_name: str):
    meta = ctx.controller.segment_metadata(table, segment_name)
    if meta is None:
        raise KeyError(f"{table}/{segment_name} has no metadata")
    location = meta["location"]
    if location.endswith((".tar.gz", ".tgz")):
        from ..ingestion.batch import untar_segment

        location = untar_segment(location, str(Path(ctx.work_dir) / "untar"))
    return load_segment(location)


def segment_rows(segment) -> list[dict]:
    """Materialize a segment as row dicts (minion rewrite path — the
    reference's SegmentProcessorFramework mapper input)."""
    cols = {}
    for c in segment.columns():
        md = segment.column_metadata(c)
        if md.single_value:
            cols[c] = segment.get_values(c)
        else:
            cols[c] = segment.get_mv_values(c)
    n = segment.num_docs
    nulls = {c: segment.get_null_bitmap(c) for c in segment.columns()}
    out = []
    for i in range(n):
        row = {}
        for c, vals in cols.items():
            if nulls.get(c) is not None and nulls[c][i]:
                row[c] = None
            else:
                v = vals[i]
                row[c] = (v.item() if isinstance(v, np.generic)
                          else list(v) if isinstance(v, np.ndarray) else v)
        out.append(row)
    return out


def _build_and_add(ctx: TaskContext, table: str, segment_name: str,
                   schema: Schema, rows: list[dict], extra_meta=None) -> str:
    out_dir = Path(ctx.work_dir) / table / segment_name
    # rebuild WITH the table config: index declarations and partition
    # stamping survive minion rewrites (reference SegmentProcessorFramework
    # builds from the table config too)
    SegmentBuilder(schema, table_config=_table_config_of(ctx, table),
                   segment_name=segment_name).build_from_rows(rows, out_dir)
    from ..segment.format import partition_push_metadata

    meta = {"location": str(out_dir), "numDocs": len(rows)}
    meta.update(partition_push_metadata(out_dir))
    meta.update(extra_meta or {})
    ctx.controller.add_segment(table, segment_name, meta)
    return segment_name


# -- MergeRollupTask ---------------------------------------------------------


def merge_rollup_generator(controller, table: str, cfg: dict) -> list[TaskSpec]:
    """Bundle small segments into one merge task (reference:
    MergeRollupTaskGenerator buckets by time + merge level; here one bundle
    per run capped by maxNumRecordsPerTask)."""
    max_records = int(cfg.get("maxNumRecordsPerTask", 5_000_000))
    segs = []
    total = 0
    for name in controller.store.children(f"/SEGMENTS/{table}"):
        meta = controller.segment_metadata(table, name) or {}
        if meta.get("mergedFrom"):
            continue  # don't re-merge outputs
        n = int(meta.get("numDocs", 0))
        if total + n > max_records and segs:
            break
        segs.append(name)
        total += n
    if len(segs) < 2:
        return []
    return [TaskSpec("MergeRollupTask", table,
                     {**cfg, "segments": segs})]


def _replace_via_lineage(ctx: TaskContext, table: str, from_names: list[str],
                         add_fn, to_names: list[str],
                         online_timeout_s: float = 30.0) -> None:
    """Atomic segment replacement: start lineage (brokers keep routing the
    FROM set, ignore TO), add the replacement segments, wait until every TO
    segment has an online replica, then commit the swap with the lineage
    state flip. On timeout the replacement is reverted so queries never see
    a half-swapped table (reference: PinotHelixResourceManager
    startReplaceSegments/endReplaceSegments driven from minion merge
    tasks)."""
    from ..cluster.periodic import SegmentLineageManager

    lineage = SegmentLineageManager(ctx.controller.store, ctx.controller)
    lid = lineage.start_replace(table, from_names, to_names)
    try:
        add_fn()
        store = ctx.controller.store
        deadline = time.time() + online_timeout_s
        live_key = "/LIVEINSTANCES"
        while True:
            view = store.get(f"/EXTERNALVIEW/{table}") or {}
            live = set(store.children(live_key))
            ok = all(
                any(st == "ONLINE" and inst in live
                    for inst, st in (view.get(seg) or {}).items())
                for seg in to_names)
            if ok:
                break
            if time.time() > deadline:
                raise TimeoutError(
                    f"replacement segments {to_names} never came online")
            time.sleep(0.02)
    except Exception:
        lineage.revert_replace(table, lid)
        raise
    lineage.end_replace(table, lid)


def merge_rollup_executor(ctx: TaskContext, spec: TaskSpec) -> dict:
    """Concat or rollup N segments into one (reference:
    MergeRollupTaskExecutor over SegmentProcessorFramework)."""
    table = spec.table
    schema = _schema_of(ctx, table)
    names = spec.config["segments"]
    merge_type = spec.config.get("mergeType", "concat").lower()
    rows: list[dict] = []
    for name in names:
        rows.extend(segment_rows(_load(ctx, table, name)))
    if merge_type == "rollup":
        rows = _rollup(schema, rows, spec.config)
    out_name = f"merged_{raw_table_name(table)}_{int(time.time() * 1000)}"
    _replace_via_lineage(
        ctx, table, names,
        lambda: _build_and_add(ctx, table, out_name, schema, rows,
                               {"mergedFrom": names}),
        [out_name])
    return {"outputSegment": out_name, "numDocs": len(rows),
            "merged": names}


def _rollup(schema: Schema, rows: list[dict], cfg: dict) -> list[dict]:
    """Group by every dimension/date-time column, aggregate metrics
    (default SUM; cfg 'aggregationTypes': {metric: SUM|MIN|MAX})."""
    key_cols = [c for c in schema.column_names()
                if c not in schema.metric_names()]
    metrics = schema.metric_names()
    aggs = {m: (cfg.get("aggregationTypes", {}).get(m, "SUM")).upper()
            for m in metrics}
    grouped: dict[tuple, dict] = {}
    for row in rows:
        key = tuple(_hashable(row.get(c)) for c in key_cols)
        cur = grouped.get(key)
        if cur is None:
            grouped[key] = dict(row)
            continue
        for m in metrics:
            a, b = cur.get(m), row.get(m)
            if a is None:
                cur[m] = b
            elif b is not None:
                cur[m] = (a + b if aggs[m] == "SUM"
                          else min(a, b) if aggs[m] == "MIN" else max(a, b))
    return list(grouped.values())


def _hashable(v):
    return tuple(v) if isinstance(v, (list, np.ndarray)) else v


# -- RealtimeToOfflineSegmentsTask -------------------------------------------


def rt2off_generator(controller, table: str, cfg: dict) -> list[TaskSpec]:
    """Move committed realtime segments into the offline twin (reference:
    RealtimeToOfflineSegmentsTaskGenerator windows on the time column with
    a watermark; here: all registered realtime segments not yet moved)."""
    if not table.endswith("_REALTIME"):
        return []
    moved = set(controller.store.get(f"/MINION_WATERMARKS/{table}") or [])
    segs = [s for s in controller.store.children(f"/SEGMENTS/{table}")
            if s not in moved]
    if not segs:
        return []
    return [TaskSpec("RealtimeToOfflineSegmentsTask", table,
                     {**cfg, "segments": segs})]


def rt2off_executor(ctx: TaskContext, spec: TaskSpec) -> dict:
    table = spec.table
    offline = table_name_with_type(raw_table_name(table), "OFFLINE")
    if ctx.controller.table_config(offline) is None:
        raise KeyError(f"offline twin {offline} does not exist")
    schema = _schema_of(ctx, table)
    time_col = (ctx.controller.table_config(offline) or {}).get("timeColumn")
    rows = []
    for name in spec.config["segments"]:
        rows.extend(segment_rows(_load(ctx, table, name)))
    out_name = f"{raw_table_name(table)}_rt2off_{int(time.time() * 1000)}"
    extra = {}
    if time_col and rows:
        tv = [r[time_col] for r in rows if r.get(time_col) is not None]
        if tv:
            extra = {"startTimeMs": min(tv), "endTimeMs": max(tv)}
    _build_and_add(ctx, offline, out_name, schema, rows, extra)
    ctx.store.update(f"/MINION_WATERMARKS/{table}", lambda cur: sorted(
        set(cur or []) | set(spec.config["segments"])))
    return {"outputSegment": out_name, "offlineTable": offline,
            "numDocs": len(rows)}


# -- PurgeTask ---------------------------------------------------------------


def purge_generator(controller, table: str, cfg: dict) -> list[TaskSpec]:
    segs = controller.store.children(f"/SEGMENTS/{table}")
    return [TaskSpec("PurgeTask", table, {**cfg, "segments": segs})] if segs else []


def purge_executor(ctx: TaskContext, spec: TaskSpec) -> dict:
    """Rewrite segments dropping rows that match purgeFilter (reference:
    PurgeTaskExecutor with a RecordPurger; the filter here is a SQL boolean
    expression over the row)."""
    from ..engine.host_executor import HostSegmentExecutor

    table = spec.table
    schema = _schema_of(ctx, table)
    fctx = parse_filter_expression(spec.config["purgeFilter"])
    host = HostSegmentExecutor()
    purged = {}
    for name in spec.config["segments"]:
        seg = _load(ctx, table, name)
        mask = host._filter_mask(fctx, seg)  # rows to PURGE
        if not mask.any():
            continue
        rows = [r for r, m in zip(segment_rows(seg), mask) if not m]
        new_name = f"{name}_purged"
        _replace_via_lineage(
            ctx, table, [name],
            lambda new_name=new_name, rows=rows:
                _build_and_add(ctx, table, new_name, schema, rows),
            [new_name])
        purged[name] = int(mask.sum())
    return {"purged": purged}


# -- UpsertCompactionTask ----------------------------------------------------


def upsert_compaction_executor(ctx: TaskContext, spec: TaskSpec) -> dict:
    """Rewrite segments keeping only upsert-valid docs (reference:
    UpsertCompactionTaskExecutor reads validDocIds from the server). The
    validity snapshot rides in the task config as {segment: [valid doc
    ids]} since minions don't share server memory."""
    table = spec.table
    schema = _schema_of(ctx, table)
    compacted = {}
    for name, valid_ids in spec.config["validDocIds"].items():
        seg = _load(ctx, table, name)
        keep = set(valid_ids)
        rows = [r for i, r in enumerate(segment_rows(seg)) if i in keep]
        if len(rows) == seg.num_docs:
            continue
        new_name = f"{name}_compacted"
        _replace_via_lineage(
            ctx, table, [name],
            lambda new_name=new_name, rows=rows:
                _build_and_add(ctx, table, new_name, schema, rows),
            [new_name])
        compacted[name] = seg.num_docs - len(rows)
    return {"compacted": compacted}


def upsert_compact_merge_executor(ctx: TaskContext, spec: TaskSpec) -> dict:
    """Compact N upsert segments AND merge the survivors into one segment
    (reference: UpsertCompactMergeTaskExecutor — the compact-only task
    still leaves many small segments; this variant concats the valid rows
    of a group of segments into a single replacement). Validity snapshots
    ride in the config exactly like UpsertCompactionTask."""
    table = spec.table
    schema = _schema_of(ctx, table)
    valid_ids = spec.config["validDocIds"]  # {segment: [valid doc ids]}
    group = spec.config.get("segments") or sorted(valid_ids)
    rows: list[dict] = []
    dropped = 0
    for name in group:
        seg = _load(ctx, table, name)
        keep = set(valid_ids.get(name, range(seg.num_docs)))
        kept = [r for i, r in enumerate(segment_rows(seg)) if i in keep]
        dropped += seg.num_docs - len(kept)
        rows.extend(kept)
    new_name = spec.config.get(
        "mergedSegmentName", f"{group[0]}_merged_{len(group)}")
    _replace_via_lineage(
        ctx, table, group,
        lambda: _build_and_add(ctx, table, new_name, schema, rows),
        [new_name])
    return {"merged": group, "outputSegment": new_name,
            "numDocs": len(rows), "invalidDropped": dropped}


# -- RefreshSegmentTask ------------------------------------------------------


def refresh_executor(ctx: TaskContext, spec: TaskSpec) -> dict:
    """Rebuild segments under the CURRENT schema/config so new indexes and
    schema evolution apply (reference: RefreshSegmentTaskExecutor)."""
    table = spec.table
    schema = _schema_of(ctx, table)
    refreshed = []
    for name in spec.config["segments"]:
        seg = _load(ctx, table, name)
        rows = segment_rows(seg)
        out_dir = Path(ctx.work_dir) / table / f"{name}_refreshed"
        SegmentBuilder(schema, table_config=_table_config_of(ctx, table),
                       segment_name=name).build_from_rows(rows, out_dir)
        from ..segment.format import partition_push_metadata

        meta = {"location": str(out_dir), "numDocs": len(rows),
                "refreshedAtMs": int(time.time() * 1000)}
        meta.update(partition_push_metadata(out_dir))
        ctx.controller.add_segment(table, name, meta)
        refreshed.append(name)
    return {"refreshed": refreshed}


# -- SegmentGenerationAndPushTask --------------------------------------------


def segment_gen_push_generator(controller, table: str,
                               cfg: dict) -> list[TaskSpec]:
    """ONE TASK PER INPUT FILE — the distributed batch-ingestion runner.

    The reference distributes file→segment build tasks over cluster
    executors (pinot-plugins/pinot-batch-ingestion/
    pinot-batch-ingestion-spark-3/.../SparkSegmentGenerationJobRunner.java
    parallelizes the input-file URI list; SegmentGenerationAndPushTask's
    generator emits tableMaxNumTasks single-file tasks). Here each file
    becomes its own TaskSpec, so any number of minion workers — on any
    host sharing the property store and filesystem — claim and build
    concurrently. Files already ingested are skipped by checking pushed
    segments' ``inputFile`` marker, mirroring the reference generator's
    ZK-metadata dedup."""
    from ..ingestion.batch import IngestionJobLauncher, SegmentGenerationJobSpec

    schema_raw = controller.store.get(f"/SCHEMAS/{raw_table_name(table)}")
    if schema_raw is None:
        raise KeyError(f"schema {raw_table_name(table)} not registered")
    job = SegmentGenerationJobSpec(
        input_dir_uri=cfg["inputDirURI"],
        output_dir_uri=cfg.get("outputDirURI", cfg["inputDirURI"]),
        schema=Schema.from_json(schema_raw),
        table_config=TableConfig(table_name=raw_table_name(table)),
        include_file_name_pattern=cfg.get("includeFileNamePattern"),
    )
    files = sorted(IngestionJobLauncher(job).list_input_files())
    done = set()
    for seg in controller.store.children(f"/SEGMENTS/{table}"):
        meta = controller.segment_metadata(table, seg) or {}
        if meta.get("inputFile"):
            done.add(meta["inputFile"])
    # also skip files with a non-terminal task in flight (reference: the
    # generator checks task states so a scheduler tick during a long build
    # cannot double-ingest a file)
    for tid in controller.store.children("/TASKS/SegmentGenerationAndPushTask"):
        t = controller.store.get(
            f"/TASKS/SegmentGenerationAndPushTask/{tid}") or {}
        if t.get("table") == table and t.get("state") in ("PENDING", "RUNNING"):
            f = (t.get("config") or {}).get("inputFile")
            if f:
                done.add(f)
    max_tasks = int(cfg.get("tableMaxNumTasks", 0) or 0)
    new_files = [p for p in files if p not in done]
    if max_tasks:
        new_files = new_files[:max_tasks]
    if not new_files:
        return []
    # sequence ids come from a monotonic per-table counter in the store —
    # NOT the file's position in today's listing, which would reuse a
    # consumed seq (and thus a segment name) when a late-arriving file
    # sorts before already-ingested ones. An ABSENT counter seeds past any
    # existing `{prefix}_{n}` segments (tables first loaded through the
    # standalone/whole-job path carry no counter, and reusing their names
    # would overwrite their metadata).
    import re as _re

    prefix = cfg.get("segmentNamePrefix") or raw_table_name(table)
    pat = _re.compile(rf"^{_re.escape(prefix)}_(\d+)$")
    floor = 0
    for seg in controller.store.children(f"/SEGMENTS/{table}"):
        m = pat.match(seg)
        if m:
            floor = max(floor, int(m.group(1)) + 1)
    base = {"n": 0}

    def alloc(cur):
        cur = max(int(cur or 0), floor)
        base["n"] = cur
        return cur + len(new_files)

    controller.store.update(f"/INGEST_SEQ/{table}", alloc)
    return [TaskSpec("SegmentGenerationAndPushTask", table,
                     config=dict(cfg, inputFile=path,
                                 sequenceId=base["n"] + i))
            for i, path in enumerate(new_files)]


def segment_gen_push_executor(ctx: TaskContext, spec: TaskSpec) -> dict:
    """Batch build + push as a minion task (reference:
    SegmentGenerationAndPushTaskExecutor). With ``inputFile`` in the
    config (set by the per-file generator) this builds exactly one file —
    the unit of cluster-wide distribution; without it, the whole job runs
    in-process (the standalone fallback)."""
    from ..ingestion.batch import (
        IngestionJobLauncher,
        SegmentGenerationJobSpec,
        _generate_one_job,
        push_segments_to_cluster,
    )

    table = spec.table
    schema = _schema_of(ctx, table)
    job = SegmentGenerationJobSpec(
        input_dir_uri=spec.config["inputDirURI"],
        output_dir_uri=spec.config.get(
            "outputDirURI", str(Path(ctx.work_dir) / table / "generated")),
        schema=schema,
        table_config=TableConfig(table_name=raw_table_name(table)),
        input_format=spec.config.get("inputFormat"),
        include_file_name_pattern=spec.config.get("includeFileNamePattern"),
        segment_name_prefix=spec.config.get("segmentNamePrefix"),
    )
    if spec.config.get("inputFile"):
        r = _generate_one_job(job, spec.config["inputFile"],
                              int(spec.config.get("sequenceId", 0)))
        push_segments_to_cluster([r], ctx.controller, table,
                                 extra_meta={"inputFile":
                                             spec.config["inputFile"]})
        return {"segments": [r.segment_name], "numDocs": r.num_docs,
                "inputFile": spec.config["inputFile"]}
    results = IngestionJobLauncher(job).run()
    push_segments_to_cluster(results, ctx.controller, table)
    return {"segments": [r.segment_name for r in results],
            "numDocs": sum(r.num_docs for r in results)}


# -- registration ------------------------------------------------------------

register_task_generator("MergeRollupTask", merge_rollup_generator)
register_task_executor("MergeRollupTask", merge_rollup_executor)
register_task_generator("RealtimeToOfflineSegmentsTask", rt2off_generator)
register_task_executor("RealtimeToOfflineSegmentsTask", rt2off_executor)
register_task_generator("PurgeTask", purge_generator)
register_task_executor("PurgeTask", purge_executor)
register_task_executor("UpsertCompactionTask", upsert_compaction_executor)
register_task_executor("UpsertCompactMergeTask", upsert_compact_merge_executor)
register_task_executor("RefreshSegmentTask", refresh_executor)
register_task_generator("SegmentGenerationAndPushTask",
                        segment_gen_push_generator)
register_task_executor("SegmentGenerationAndPushTask", segment_gen_push_executor)
