"""Multi-stage query engine (MSE).

Reference analogue: the V2 engine — Calcite front-end + logical planner
(pinot-query-planner/, QueryEnvironment.planQuery:179), fragmenter
(PlanFragmenter), and the worker runtime with mailbox shuffle
(pinot-query-runtime/, QueryRunner.processQuery:210, MailboxService:40).

TPU-first shape: leaf stages compile down to the single-stage device engine
(the reference runs leaf stages on ServerQueryExecutorV1Impl the same way —
ServerPlanRequestUtils); intermediate operators run vectorized columnar
numpy on host, and the shuffle plane is an in-memory mailbox service whose
hash/broadcast exchanges map 1:1 onto jax all-to-all / broadcast collectives
when stages are placed on device meshes (parallel/mesh.py).
"""

from .executor import MultistageExecutor
from .parser import parse_relational

__all__ = ["MultistageExecutor", "parse_relational"]
