"""Relational AST for the multi-stage SQL dialect.

Reference analogue: Calcite's SqlNode tree as consumed by
pinot-query-planner/.../QueryEnvironment.java:179 (parse → validate). The
single-stage dialect (query/parser/sql.py) covers one-table queries; this
AST adds FROM-clause joins, derived tables, set operations, CTEs and window
functions — the constructs that force multi-stage execution.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union

from ..query.expressions import ExpressionContext


# -- FROM-clause relations ---------------------------------------------------


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef:
    query: "Stmt"
    alias: str


@dataclass
class JoinRel:
    """join_type: INNER | LEFT | RIGHT | FULL | CROSS | SEMI | ANTI
    (SEMI/ANTI are produced by IN / NOT IN subquery rewrites, mirroring the
    reference's Calcite SubQueryRemoveRule)."""

    left: "Relation"
    right: "Relation"
    join_type: str
    condition: Optional[ExpressionContext] = None


Relation = Union[TableRef, SubqueryRef, JoinRel]


# -- window functions --------------------------------------------------------


@dataclass
class WindowSpec:
    """OVER (PARTITION BY ... ORDER BY ...). Frames default to the reference's
    semantics: RANGE UNBOUNDED PRECEDING..CURRENT ROW with ORDER BY, the whole
    partition without (pinot-query-runtime/.../operator/window/)."""

    partition_by: list[ExpressionContext] = field(default_factory=list)
    order_by: list[tuple[ExpressionContext, bool]] = field(default_factory=list)  # (expr, asc)
    # frame: (kind, start, end); start/end None = UNBOUNDED, int = offset rows
    frame: Optional[tuple[str, Optional[int], Optional[int]]] = None


@dataclass
class SelectItem:
    expression: ExpressionContext
    alias: Optional[str] = None
    window: Optional[WindowSpec] = None  # set when expression is `agg(...) OVER (...)`


# -- statements --------------------------------------------------------------


@dataclass
class OrderItem:
    expression: ExpressionContext
    ascending: bool = True
    nulls_last: Optional[bool] = None


@dataclass
class SelectStmt:
    select_items: list[SelectItem]
    from_rel: Relation
    distinct: bool = False
    where: Optional[ExpressionContext] = None
    group_by: list[ExpressionContext] = field(default_factory=list)
    having: Optional[ExpressionContext] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


@dataclass
class SetOpStmt:
    kind: str  # UNION | INTERSECT | EXCEPT
    all: bool
    left: "Stmt"
    right: "Stmt"
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


Stmt = Union[SelectStmt, SetOpStmt]


@dataclass
class RelationalQuery:
    statement: Stmt
    options: dict = field(default_factory=dict)
    explain: bool = False
