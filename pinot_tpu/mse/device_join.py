"""Device sort-merge equi-join for large MSE intermediates.

Reference analogue: HashJoinOperator
(pinot-query-runtime/.../runtime/operator/HashJoinOperator.java) builds a
host hash table per worker. Hash tables are hostile to a TPU's vector
units; the TPU-first shape is sort + vectorized binary search — the same
machinery the sparse group-by kernel rides:

    rs            = sort(right_keys, iota)          one lax.sort
    starts, ends  = searchsorted(rs, left_keys)     log-passes, vectorized
    expansion     = searchsorted(cumsum(counts), j) one output row per match

Only the JOIN KEYS travel to the device (already dict-coded to int64 by
the host join's joint-code pass); the result is (left_idx, right_idx)
pairs, and payload columns gather on host. Output is capped at a static
bucket so compiled programs are shared; overflow reports back for the
THROW/BREAK join guards.

Gating: ``PINOT_TPU_DEVICE_JOIN`` = auto (default: on when a non-CPU jax
backend is live and the sides are large) | 1 (force) | 0 (off).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# below this many total key rows the host numpy argsort wins (device
# dispatch + transfer overhead dominates)
AUTO_MIN_ROWS = 4_000_000


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return max(b, 1024)


@functools.cache
def _jit_join_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)  # engine-wide invariant
    # ln/rn are TRACED scalars: only the padded bucket shapes and the
    # output cap are static, so compiled programs are shared across the
    # actual row counts (a static ln would recompile per input size,
    # defeating the bucket padding)
    return functools.partial(jax.jit, static_argnames=("max_out",))(
        _join_kernel)


def _join_kernel(lk, rk, ln, rn, max_out: int):
    import jax
    import jax.numpy as jnp

    SENT = jnp.int64(1 << 62)
    lvalid = jnp.arange(lk.shape[0]) < ln
    rvalid = jnp.arange(rk.shape[0]) < rn
    lkm = jnp.where(lvalid, lk, SENT)
    rkm = jnp.where(rvalid, rk, SENT)
    rs_keys, rs_idx = jax.lax.sort(
        (rkm, jnp.arange(rk.shape[0], dtype=jnp.int32)), num_keys=1)
    starts = jnp.searchsorted(rs_keys, lkm, side="left")
    ends = jnp.searchsorted(rs_keys, lkm, side="right")
    counts = jnp.where(lvalid, ends - starts, 0)
    incl = jnp.cumsum(counts)
    total = incl[-1]
    excl = incl - counts
    j = jnp.arange(max_out)
    li = jnp.searchsorted(incl, j, side="right")
    li_c = jnp.minimum(li, lk.shape[0] - 1)
    ri = rs_idx[jnp.minimum(starts[li_c] + (j - excl[li_c]),
                            rk.shape[0] - 1)]
    valid_out = j < jnp.minimum(total, max_out)
    return (jnp.where(valid_out, li_c, -1).astype(jnp.int32),
            jnp.where(valid_out, ri, -1).astype(jnp.int32),
            total.astype(jnp.int64))


def device_join_indices(lcodes: np.ndarray, rcodes: np.ndarray,
                        max_out: int):
    """(lidx, ridx, total) for the INNER equi-join of two int64 key
    arrays. ``total`` is the TRUE match count; at most ``max_out`` pairs
    are returned (ascending left order, right order within a left row
    following the right side's sort)."""
    ln, rn = len(lcodes), len(rcodes)
    lk = np.full(_bucket(ln), 0, dtype=np.int64)
    rk = np.full(_bucket(rn), 0, dtype=np.int64)
    lk[:ln] = lcodes
    rk[:rn] = rcodes
    li, ri, total = _jit_join_kernel()(
        lk, rk, np.int64(ln), np.int64(rn), max_out=_bucket(max_out))
    total = int(total)
    n = min(total, max_out)
    return np.asarray(li)[:n], np.asarray(ri)[:n], total


@functools.cache
def _jit_gather_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)

    def _gather(idx, cols):
        import jax.numpy as jnp

        return [jnp.take(c, idx, mode="clip") for c in cols]

    return jax.jit(_gather)


def gather_payload(cols: dict, idx: np.ndarray):
    """Fused payload gather: materialize every pruned output column of a
    device-joined side in ONE device dispatch (XLA fuses the per-column
    takes) instead of one host fancy-index per column. Inputs are padded to
    power-of-2 buckets so compiled programs are shared across row counts.
    Returns None (caller falls back to the host gather) on any failure."""
    if _FAILED or not cols:
        return None
    try:
        n = len(idx)
        pidx = np.zeros(_bucket(max(n, 1)), dtype=np.int64)
        pidx[:n] = idx
        padded = []
        for v in cols.values():
            pv = np.zeros(_bucket(max(len(v), 1)), dtype=v.dtype)
            pv[:len(v)] = v
            padded.append(pv)
        out = _jit_gather_kernel()(pidx, padded)
        return {name: np.asarray(o)[:n] for name, o in zip(cols, out)}
    except Exception as e:
        note_failure(e)
        return None


_FAILED = False


def note_failure(exc: BaseException) -> None:
    """Log the first device-join failure and disable the path for the
    process — a persistent misconfiguration must be visible, not a silent
    per-join failed attempt."""
    global _FAILED
    if not _FAILED:
        _FAILED = True
        import logging

        logging.getLogger(__name__).warning(
            "device join failed (%s: %s); falling back to the host join "
            "for this process", type(exc).__name__, exc)


def enabled(ln: int, rn: int) -> bool:
    if _FAILED:
        return False
    mode = os.environ.get("PINOT_TPU_DEVICE_JOIN", "auto").lower()
    if mode in ("0", "off", "false"):
        return False
    if mode in ("1", "on", "force", "true"):
        return True
    if ln + rn < AUTO_MIN_ROWS:
        return False
    try:
        from ..ops.mxu_groupby import backend_platform

        return backend_platform() != "cpu"
    except Exception:
        return False


# -- fused partition→join→aggregate stage ------------------------------------
#
# The pair-producing join above still materializes (lidx, ridx) and hands
# aggregation back to the host. The fused path below never materializes
# pairs: the whole ``Aggregate ← INNER Join ← 2×hash-receive`` stage runs
# as three device dispatches (partition left, partition right, join+agg)
# and only a [n_aggs, G] group table crosses back — see
# ops/join_pipeline.py for the kernels.

# auto threshold for the fused stage: unlike the pair join it pays off on
# the CPU backend too (it skips materializing `total_pairs` index/payload
# arrays entirely), so the gate is on input size alone
FUSED_AUTO_MIN_ROWS = 500_000


def fused_min_rows() -> int:
    try:
        return int(os.environ.get("PINOT_TPU_DEVICE_JOIN_MIN_ROWS",
                                  FUSED_AUTO_MIN_ROWS))
    except ValueError:
        return FUSED_AUTO_MIN_ROWS


def fused_partitions() -> int:
    """P of the device hash partition. Pure routing width: every P yields
    the same result (partition combine is exact), so this only trades
    plane height against vmap width."""
    try:
        return max(1, int(os.environ.get(
            "PINOT_TPU_DEVICE_JOIN_PARTITIONS", 8)))
    except ValueError:
        return 8


def env_mode() -> str:
    return os.environ.get("PINOT_TPU_DEVICE_JOIN", "auto").lower()


@dataclass
class FusedStagePlan:
    """Shape proof that a stage is ``Aggregate ← INNER equi-Join ← two hash
    receives`` with aggregates the device kernel can produce. Built once
    per query by plan_fused_stage; None means the stage keeps the generic
    host operator tree."""
    agg_node: object
    join_node: object
    receives: tuple            # (left recv, right recv) MailboxReceiveNodes
    probe_side: str            # "left" | "right": the side the groups live on
    group_cols: list = field(default_factory=list)   # (schema name, probe col)
    # (kind, "probe"|"build"|None, value col name|None, out_name) per agg
    aggs: list = field(default_factory=list)


def _match_col(name: str, schema: list) -> Optional[str]:
    if name in schema:
        return name
    suffix = [c for c in schema if c.endswith("." + name)]
    return suffix[0] if len(suffix) == 1 else None


def plan_fused_stage(stage) -> Optional[FusedStagePlan]:
    from .fragmenter import MailboxReceiveNode
    from .logical import AggregateNode, JoinNode

    agg = stage.root
    if not isinstance(agg, AggregateNode) or not agg.group_exprs:
        return None
    join = agg.inputs[0]
    if (not isinstance(join, JoinNode) or join.join_type != "INNER"
            or join.residual is not None or not join.left_keys
            or len(join.inputs) != 2):
        return None
    recv_l, recv_r = join.inputs
    if not all(isinstance(r, MailboxReceiveNode) and r.dist == "hash"
               for r in (recv_l, recv_r)):
        return None
    lschema, rschema = list(recv_l.schema), list(recv_r.schema)

    def resolve(name):
        lc, rc = _match_col(name, lschema), _match_col(name, rschema)
        if (lc is None) == (rc is None):   # missing or ambiguous
            return None
        return ("left", lc) if lc is not None else ("right", rc)

    group_cols, sides = [], set()
    for out_name, g in zip(agg.schema, agg.group_exprs):
        if not g.is_identifier:
            return None
        got = resolve(g.identifier)
        if got is None:
            return None
        sides.add(got[0])
        group_cols.append((out_name, got[1]))
    if len(sides) != 1:
        # groups split across sides: every probe row would need two group
        # codes — host path handles it
        return None
    probe_side = sides.pop()

    aggs = []
    for call in agg.agg_calls:
        if call.condition is not None or call.extra:
            return None
        if call.name == "count" and not call.args:
            aggs.append(("count", None, None, call.out_name))
            continue
        if call.name not in ("sum", "min", "max") or len(call.args) != 1 \
                or not call.args[0].is_identifier:
            return None
        got = resolve(call.args[0].identifier)
        if got is None:
            return None
        rel = "probe" if got[0] == probe_side else "build"
        aggs.append((call.name, rel, got[1], call.out_name))
    return FusedStagePlan(agg, join, (recv_l, recv_r), probe_side,
                          group_cols, aggs)


def run_fused(left, right, plan: FusedStagePlan, ctx=None):
    """Execute a fused stage device-resident. Returns (block, info) or
    None when any gate fails (dtype, empty side, plane overflow, join row
    limit) — the caller's host fallback owns exact semantics for those."""
    if _FAILED:
        return None
    from . import operators
    from ..ops import join_pipeline as jp
    from .mailbox import block_len

    ln, rn = block_len(left), block_len(right)
    if ln == 0 or rn == 0:
        return None
    join = plan.join_node
    lcodes, rcodes = operators._joint_codes(
        [np.asarray(left[k]) for k in join.left_keys],
        [np.asarray(right[k]) for k in join.right_keys], ln, rn, ctx)

    probe, build = (left, right) if plan.probe_side == "left" else (right, left)
    pcodes, bcodes = ((lcodes, rcodes) if plan.probe_side == "left"
                      else (rcodes, lcodes))
    pn, bn = len(pcodes), len(bcodes)
    # raw int keys ARE their own codes (the int fast path): values at or
    # above the kernel's pad sentinels would alias padding
    for c in (pcodes, bcodes):
        if len(c) and (int(c.max()) >= (1 << 62)
                       or int(c.min()) <= -(1 << 62)):
            return None
    # min build code feeds the partition kernel's packed-sort fast path
    bmin = int(bcodes.min()) if len(bcodes) else 0

    # bit-identity gate: integer-valued f64 accumulation is exact, hence
    # reduction-order-free; float args would make partition order visible
    pv_names = [c for k, s, c, _ in plan.aggs if s == "probe"]
    bv_names = [c for k, s, c, _ in plan.aggs if s == "build"]
    for side_block, names in ((probe, pv_names), (build, bv_names)):
        for nm in dict.fromkeys(names):
            if not operators._int_like(np.asarray(side_block[nm])):
                return None

    gcols = [np.asarray(probe[c]) for _, c in plan.group_cols]
    gcodes, num, first = operators.group_codes(gcols)
    if num == 0:
        return None

    P = fused_partitions()
    Np, Nb = jp.bucket(pn), jp.bucket(bn)
    # plane caps: the partition mix is pure, so the EXACT per-partition
    # counts are a ~1ms host bincount — size each plane to the real max
    # (pow2-bucketed for compile sharing). Tight caps halve every
    # downstream plane pass vs a fixed headroom factor, and skewed keys
    # (NULL buckets, heavy hitters) stay on device as long as their
    # partition fits a plane at all.
    cap_l = min(Np, jp.bucket(max(
        64, int(jp.host_partition_counts(pcodes, P).max()))))
    cap_r = min(Nb, jp.bucket(max(
        64, int(jp.host_partition_counts(bcodes, P).max()))))
    Gp = jp.bucket(num)

    def pad1(a, n_to, dtype):
        out = np.zeros(n_to, dtype=dtype)
        out[:len(a)] = a
        return out

    pv_order = list(dict.fromkeys(pv_names))
    bv_order = list(dict.fromkeys(bv_names))
    pvals = np.stack([pad1(np.asarray(probe[c], dtype=np.float64), Np,
                           np.float64) for c in pv_order]) \
        if pv_order else np.zeros((1, Np))
    bvals = np.stack([pad1(np.asarray(build[c], dtype=np.float64), Nb,
                           np.float64) for c in bv_order]) \
        if bv_order else np.zeros((1, Nb))
    spec = tuple(
        ("count", "probe", 0) if k == "count"
        else (k, s, (pv_order if s == "probe" else bv_order).index(c))
        for k, s, c, _ in plan.aggs)

    try:
        pk = pad1(pcodes, Np, np.int64)
        bk = pad1(bcodes, Nb, np.int64)
        pg = pad1(gcodes, Np, np.int64)
        # probe plane only needs partition grouping (cheap one-key sort);
        # the build plane must come out ascending-key for binary search
        pplane, pcounts = jp.partition_planes(pk, pn, P, cap_l)
        bplane, bcounts = jp.partition_planes(bk, bn, P, cap_r,
                                              key_sorted=True, cmin=bmin)
        packed = jp.fused_join_agg(pk, pg, pvals, pplane, pcounts,
                                   bk, bvals, bplane, bcounts,
                                   pn, bn, spec, P, Gp)
        out = jp.fetch_packed(packed)
    except Exception as e:
        note_failure(e)
        return None

    n_aggs = len(plan.aggs)
    meta = out[n_aggs + 1]
    total_pairs = int(meta[0])
    if meta[1] != 0.0 or total_pairs > operators.MAX_ROWS_IN_JOIN:
        # plane overflow (key skew beyond the cap headroom) or the join row
        # guard: the host path owns THROW/BREAK semantics
        return None
    pair_cnt = out[n_aggs][:num]
    present = pair_cnt > 0

    block = {}
    for (out_name, col), kv in zip(plan.group_cols, gcols):
        block[out_name] = kv[first][present]
    for i, (kind, _s, _c, out_name) in enumerate(plan.aggs):
        vals = out[i][:num][present]
        block[out_name] = vals.astype(np.int64) if kind == "count" else vals
    return block, {"total_pairs": total_pairs, "dispatches": 3}
