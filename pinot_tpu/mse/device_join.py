"""Device sort-merge equi-join for large MSE intermediates.

Reference analogue: HashJoinOperator
(pinot-query-runtime/.../runtime/operator/HashJoinOperator.java) builds a
host hash table per worker. Hash tables are hostile to a TPU's vector
units; the TPU-first shape is sort + vectorized binary search — the same
machinery the sparse group-by kernel rides:

    rs            = sort(right_keys, iota)          one lax.sort
    starts, ends  = searchsorted(rs, left_keys)     log-passes, vectorized
    expansion     = searchsorted(cumsum(counts), j) one output row per match

Only the JOIN KEYS travel to the device (already dict-coded to int64 by
the host join's joint-code pass); the result is (left_idx, right_idx)
pairs, and payload columns gather on host. Output is capped at a static
bucket so compiled programs are shared; overflow reports back for the
THROW/BREAK join guards.

Gating: ``PINOT_TPU_DEVICE_JOIN`` = auto (default: on when a non-CPU jax
backend is live and the sides are large) | 1 (force) | 0 (off).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# below this many total key rows the host numpy argsort wins (device
# dispatch + transfer overhead dominates)
AUTO_MIN_ROWS = 4_000_000


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return max(b, 1024)


@functools.cache
def _jit_join_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)  # engine-wide invariant
    # ln/rn are TRACED scalars: only the padded bucket shapes and the
    # output cap are static, so compiled programs are shared across the
    # actual row counts (a static ln would recompile per input size,
    # defeating the bucket padding)
    return functools.partial(jax.jit, static_argnames=("max_out",))(
        _join_kernel)


def _join_kernel(lk, rk, ln, rn, max_out: int):
    import jax
    import jax.numpy as jnp

    SENT = jnp.int64(1 << 62)
    lvalid = jnp.arange(lk.shape[0]) < ln
    rvalid = jnp.arange(rk.shape[0]) < rn
    lkm = jnp.where(lvalid, lk, SENT)
    rkm = jnp.where(rvalid, rk, SENT)
    rs_keys, rs_idx = jax.lax.sort(
        (rkm, jnp.arange(rk.shape[0], dtype=jnp.int32)), num_keys=1)
    starts = jnp.searchsorted(rs_keys, lkm, side="left")
    ends = jnp.searchsorted(rs_keys, lkm, side="right")
    counts = jnp.where(lvalid, ends - starts, 0)
    incl = jnp.cumsum(counts)
    total = incl[-1]
    excl = incl - counts
    j = jnp.arange(max_out)
    li = jnp.searchsorted(incl, j, side="right")
    li_c = jnp.minimum(li, lk.shape[0] - 1)
    ri = rs_idx[jnp.minimum(starts[li_c] + (j - excl[li_c]),
                            rk.shape[0] - 1)]
    valid_out = j < jnp.minimum(total, max_out)
    return (jnp.where(valid_out, li_c, -1).astype(jnp.int32),
            jnp.where(valid_out, ri, -1).astype(jnp.int32),
            total.astype(jnp.int64))


def device_join_indices(lcodes: np.ndarray, rcodes: np.ndarray,
                        max_out: int):
    """(lidx, ridx, total) for the INNER equi-join of two int64 key
    arrays. ``total`` is the TRUE match count; at most ``max_out`` pairs
    are returned (ascending left order, right order within a left row
    following the right side's sort)."""
    ln, rn = len(lcodes), len(rcodes)
    lk = np.full(_bucket(ln), 0, dtype=np.int64)
    rk = np.full(_bucket(rn), 0, dtype=np.int64)
    lk[:ln] = lcodes
    rk[:rn] = rcodes
    li, ri, total = _jit_join_kernel()(
        lk, rk, np.int64(ln), np.int64(rn), max_out=_bucket(max_out))
    total = int(total)
    n = min(total, max_out)
    return np.asarray(li)[:n], np.asarray(ri)[:n], total


@functools.cache
def _jit_gather_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)

    def _gather(idx, cols):
        import jax.numpy as jnp

        return [jnp.take(c, idx, mode="clip") for c in cols]

    return jax.jit(_gather)


def gather_payload(cols: dict, idx: np.ndarray):
    """Fused payload gather: materialize every pruned output column of a
    device-joined side in ONE device dispatch (XLA fuses the per-column
    takes) instead of one host fancy-index per column. Inputs are padded to
    power-of-2 buckets so compiled programs are shared across row counts.
    Returns None (caller falls back to the host gather) on any failure."""
    if _FAILED or not cols:
        return None
    try:
        n = len(idx)
        pidx = np.zeros(_bucket(max(n, 1)), dtype=np.int64)
        pidx[:n] = idx
        padded = []
        for v in cols.values():
            pv = np.zeros(_bucket(max(len(v), 1)), dtype=v.dtype)
            pv[:len(v)] = v
            padded.append(pv)
        out = _jit_gather_kernel()(pidx, padded)
        return {name: np.asarray(o)[:n] for name, o in zip(cols, out)}
    except Exception as e:
        note_failure(e)
        return None


_FAILED = False


def note_failure(exc: BaseException) -> None:
    """Log the first device-join failure and disable the path for the
    process — a persistent misconfiguration must be visible, not a silent
    per-join failed attempt."""
    global _FAILED
    if not _FAILED:
        _FAILED = True
        import logging

        logging.getLogger(__name__).warning(
            "device join failed (%s: %s); falling back to the host join "
            "for this process", type(exc).__name__, exc)


def enabled(ln: int, rn: int) -> bool:
    if _FAILED:
        return False
    mode = os.environ.get("PINOT_TPU_DEVICE_JOIN", "auto").lower()
    if mode in ("0", "off", "false"):
        return False
    if mode in ("1", "on", "force", "true"):
        return True
    if ln + rn < AUTO_MIN_ROWS:
        return False
    try:
        from ..ops.mxu_groupby import backend_platform

        return backend_platform() != "cpu"
    except Exception:
        return False


# -- fused partition→join→aggregate stage ------------------------------------
#
# The pair-producing join above still materializes (lidx, ridx) and hands
# aggregation back to the host. The fused path below never materializes
# pairs: the whole ``Aggregate ← INNER Join ← 2×hash-receive`` stage runs
# as three device dispatches (partition left, partition right, join+agg)
# and only a [n_aggs, G] group table crosses back — see
# ops/join_pipeline.py for the kernels.

# auto threshold for the fused stage: unlike the pair join it pays off on
# the CPU backend too (it skips materializing `total_pairs` index/payload
# arrays entirely), so the gate is on input size alone
FUSED_AUTO_MIN_ROWS = 500_000


def fused_min_rows() -> int:
    try:
        return int(os.environ.get("PINOT_TPU_DEVICE_JOIN_MIN_ROWS",
                                  FUSED_AUTO_MIN_ROWS))
    except ValueError:
        return FUSED_AUTO_MIN_ROWS


def fused_partitions() -> int:
    """P of the device hash partition. Pure routing width: every P yields
    the same result (partition combine is exact), so this only trades
    plane height against vmap width."""
    try:
        return max(1, int(os.environ.get(
            "PINOT_TPU_DEVICE_JOIN_PARTITIONS", 8)))
    except ValueError:
        return 8


def env_mode() -> str:
    return os.environ.get("PINOT_TPU_DEVICE_JOIN", "auto").lower()


@dataclass
class FusedStagePlan:
    """Shape proof that a stage is ``Aggregate ← equi-Join ← two hash
    receives`` (INNER/LEFT/SEMI/ANTI, optional side-separable residual)
    with aggregates the device kernel can produce. Built once per query by
    plan_fused_stage; None means the stage keeps the generic host operator
    tree."""
    agg_node: object
    join_node: object
    receives: tuple            # (left recv, right recv) MailboxReceiveNodes
    probe_side: str            # "left" | "right": the side the groups live on
    group_cols: list = field(default_factory=list)   # (schema name, probe col)
    # (kind, "probe"|"build"|None, value col name|None, out_name) per agg
    aggs: list = field(default_factory=list)
    join_type: str = "INNER"
    # residual conjuncts: (rel "probe"|"build", expr, [(blk key, side col)])
    residual: list = field(default_factory=list)
    # absorbed upstream join chain: which join input it replaces + source
    chain_side: Optional[str] = None    # "left" | "right" | None
    chain: object = None                # ChainSource | None


@dataclass
class ChainSource:
    """An upstream join stage absorbed into a fused stage: its output
    table never materializes — the fused stage expands the join on row
    INDICES and its leaf blocks hand off raw through the mailbox, so
    intermediates stay in HBM (values) or never exist (pairs)."""
    stage_id: int
    join_node: object
    left: object     # MailboxReceiveNode | ChainSource
    right: object    # MailboxReceiveNode | ChainSource

    def leaf_receives(self):
        for side in (self.left, self.right):
            if isinstance(side, ChainSource):
                yield from side.leaf_receives()
            else:
                yield side

    def stage_ids(self):
        yield self.stage_id
        for side in (self.left, self.right):
            if isinstance(side, ChainSource):
                yield from side.stage_ids()


def _match_col(name: str, schema: list) -> Optional[str]:
    if name in schema:
        return name
    suffix = [c for c in schema if c.endswith("." + name)]
    return suffix[0] if len(suffix) == 1 else None


def _conjuncts(e) -> list:
    """Flatten an AND-tree into its conjunct expressions."""
    if e.is_function and e.function.name == "and":
        out = []
        for a in e.function.arguments:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _plan_residual(residual, lschema, rschema) -> Optional[list]:
    """Decompose a residual filter into per-side conjuncts the device can
    apply as row masks. Each conjunct must reference exactly ONE side
    (then pair-filtering factorizes into a probe mask × a build mask) and
    resolve unambiguously under the same naming rule the host's
    _residual_block applies (right-side duplicate names carry a "0"
    suffix). Returns [(side, expr, [(eval-block key, side column)])] or
    None — ambiguous/cross-side conjuncts keep the host path, which also
    owns the host's error behavior for unresolvable names."""
    from . import operators

    out = []
    for conj in _conjuncts(residual):
        ids: set = set()
        operators._expr_ids(conj, ids)
        if not ids:
            return None       # literal-only conjunct: host path
        side, cols = None, []
        for i in ids:
            lc, rc = _match_col(i, lschema), _match_col(i, rschema)
            if lc is not None and rc is not None:
                return None   # ambiguous across sides (host raises)
            if lc is not None:
                got, key, col = "left", lc, lc
            elif rc is not None:
                got, key, col = "right", rc, rc
            elif (i.endswith("0") and i[:-1] in rschema
                    and i[:-1] in lschema):
                # the host's dup rename: right column shadowed by a
                # same-named left column surfaces as <name>0
                got, key, col = "right", i, i[:-1]
            else:
                return None
            if side is None:
                side = got
            elif side != got:
                return None   # conjunct spans both sides
            cols.append((key, col))
        out.append((side, conj, cols))
    return out


def plan_fused_stage(stage) -> Optional[FusedStagePlan]:
    from .fragmenter import MailboxReceiveNode
    from .logical import AggregateNode, JoinNode

    agg = stage.root
    if not isinstance(agg, AggregateNode) or not agg.group_exprs:
        return None
    join = agg.inputs[0]
    if (not isinstance(join, JoinNode)
            or join.join_type not in ("INNER", "LEFT", "SEMI", "ANTI")
            or not join.left_keys or len(join.inputs) != 2):
        return None
    recv_l, recv_r = join.inputs
    if not all(isinstance(r, MailboxReceiveNode) and r.dist == "hash"
               for r in (recv_l, recv_r)):
        return None
    lschema, rschema = list(recv_l.schema), list(recv_r.schema)

    def resolve(name):
        lc, rc = _match_col(name, lschema), _match_col(name, rschema)
        if (lc is None) == (rc is None):   # missing or ambiguous
            return None
        return ("left", lc) if lc is not None else ("right", rc)

    group_cols, sides = [], set()
    for out_name, g in zip(agg.schema, agg.group_exprs):
        if not g.is_identifier:
            return None
        got = resolve(g.identifier)
        if got is None:
            return None
        sides.add(got[0])
        group_cols.append((out_name, got[1]))
    if len(sides) != 1:
        # groups split across sides: every probe row would need two group
        # codes — host path handles it
        return None
    probe_side = sides.pop()
    if join.join_type in ("LEFT", "SEMI", "ANTI") and probe_side != "left":
        # LEFT preserves the left side (probe must be the preserved side);
        # SEMI/ANTI project the left side only
        return None

    aggs = []
    for call in agg.agg_calls:
        if call.condition is not None or call.extra:
            return None
        if call.name == "count" and not call.args:
            aggs.append(("count", None, None, call.out_name))
            continue
        if call.name not in ("sum", "min", "max") or len(call.args) != 1 \
                or not call.args[0].is_identifier:
            return None
        got = resolve(call.args[0].identifier)
        if got is None:
            return None
        rel = "probe" if got[0] == probe_side else "build"
        if rel == "build" and join.join_type in ("SEMI", "ANTI"):
            return None    # output schema is probe-side only
        aggs.append((call.name, rel, got[1], call.out_name))

    residual = []
    if join.residual is not None:
        planned = _plan_residual(join.residual, lschema, rschema)
        if planned is None:
            return None
        residual = [("probe" if side == probe_side else "build", expr, cols)
                    for side, expr, cols in planned]
    return FusedStagePlan(agg, join, (recv_l, recv_r), probe_side,
                          group_cols, aggs, join.join_type, residual)


def plan_chain_source(stage) -> Optional[ChainSource]:
    """One absorbable chain level: a stage whose whole output is a plain
    INNER equi-join of two hash receives (no residual, no other
    operators). The runtime nests these and rewires the leaves' mailboxes
    straight to the consuming fused stage."""
    from .fragmenter import MailboxReceiveNode
    from .logical import JoinNode

    join = stage.root
    if (not isinstance(join, JoinNode) or join.join_type != "INNER"
            or join.residual is not None or not join.left_keys
            or len(join.inputs) != 2):
        return None
    if not all(isinstance(r, MailboxReceiveNode) and r.dist == "hash"
               for r in join.inputs):
        return None
    return ChainSource(stage.stage_id, join, join.inputs[0], join.inputs[1])


def _src_schema(side) -> list:
    return list(side.join_node.schema if isinstance(side, ChainSource)
                else side.schema)


def chain_resolve(src: ChainSource, name: str):
    """Resolve an output column of an absorbed join to its leaf receive
    node + leaf column, through the host joiner's naming rule (left wins
    name collisions; the shadowed right column carries a "0" suffix).
    None when the fused consumer could not reconstruct the column."""
    lsch, rsch = _src_schema(src.left), _src_schema(src.right)
    if name in lsch:
        side, col = src.left, name
    elif name in rsch:
        side, col = src.right, name
    elif name.endswith("0") and name[:-1] in rsch:
        side, col = src.right, name[:-1]
    else:
        return None
    if isinstance(side, ChainSource):
        return chain_resolve(side, col)
    return (side, col)


# -- chain expansion: joins as composed row indices --------------------------


class _SideView:
    """A join input as (leaf array, composed row index) pairs: column
    VALUES stay in their leaf blocks; only int indices materialize."""
    n: int

    def raw(self, name):
        raise NotImplementedError

    def host_col(self, name) -> np.ndarray:
        arr, idx = self.raw(name)
        return arr if idx is None else arr[idx]


class _LeafView(_SideView):
    def __init__(self, block: dict, n: int):
        self.block, self.n = block, n

    def raw(self, name):
        return np.asarray(self.block[name]), None


class _JoinView(_SideView):
    """An expanded chain level: left/right views + the (lidx, ridx) pair
    indices of the equi-join between them (exactly the host joiner's
    argsort/searchsorted expansion, so pair sets match bit-for-bit)."""

    def __init__(self, src: ChainSource, left, right, lidx, ridx, n):
        self.src, self.left, self.right = src, left, right
        self.lidx, self.ridx, self.n = lidx, ridx, n
        self._memo: dict = {}

    def raw(self, name):
        lsch, rsch = _src_schema(self.src.left), _src_schema(self.src.right)
        if name in lsch:
            side, col, idx = self.left, name, self.lidx
        elif name in rsch:
            side, col, idx = self.right, name, self.ridx
        elif name.endswith("0") and name[:-1] in rsch:
            side, col, idx = self.right, name[:-1], self.ridx
        else:
            raise KeyError(name)
        arr, sub = side.raw(col)
        key = (id(side), sub is None)
        if sub is not None:
            key = (id(side), id(sub))
        if key not in self._memo:
            self._memo[key] = idx if sub is None else sub[idx]
        return arr, self._memo[key]


def expand_chain(src: ChainSource, get_leaf, ctx=None):
    """Expand an absorbed chain into a _JoinView bottom-up on the host's
    OWN join machinery (joint codes + stable argsort + searchsorted +
    repeat — the exact expansion op_join performs), but producing only
    index vectors. Returns None when a level's pair count exceeds
    MAX_ROWS_IN_JOIN — the host fallback owns THROW/BREAK semantics."""
    from . import operators

    def build(node):
        if not isinstance(node, ChainSource):
            block, n = get_leaf(node)
            return _LeafView(block, n)
        lv, rv = build(node.left), build(node.right)
        if lv is None or rv is None:
            return None
        join = node.join_node
        lcodes, rcodes = operators._joint_codes(
            [lv.host_col(k) for k in join.left_keys],
            [rv.host_col(k) for k in join.right_keys], lv.n, rv.n, ctx)
        rs = np.argsort(rcodes, kind="stable")
        rsorted = rcodes[rs]
        starts = np.searchsorted(rsorted, lcodes, side="left")
        ends = np.searchsorted(rsorted, lcodes, side="right")
        counts = ends - starts
        total = int(counts.sum())
        if total > operators.MAX_ROWS_IN_JOIN:
            return None
        lidx = np.repeat(np.arange(lv.n), counts)
        offs = np.arange(total) - np.repeat(
            np.cumsum(counts) - counts, counts)
        ridx = rs[np.repeat(starts, counts) + offs]
        return _JoinView(node, lv, rv, lidx, ridx, total)

    return build(src)


def host_expand_chain(src: ChainSource, get_leaf, ctx=None) -> dict:
    """Materialize an absorbed chain as the block its stage would have
    sent, via the host joiner itself — the fused fallback path for
    absorbed plans (exact semantics including the join-row guards)."""
    from . import operators

    def build(node):
        if not isinstance(node, ChainSource):
            return get_leaf(node)[0]
        lb, rb = build(node.left), build(node.right)
        j = node.join_node
        return operators.op_join(lb, rb, j.join_type, j.left_keys,
                                 j.right_keys, j.residual, list(j.schema),
                                 ctx)

    return build(src)


def _as_view(side) -> _SideView:
    if isinstance(side, _SideView):
        return side
    from .mailbox import block_len

    return _LeafView(side, block_len(side))


def run_fused(left, right, plan: FusedStagePlan, ctx=None):
    """Execute a fused stage device-resident. ``left``/``right`` are
    blocks or chain _SideViews (absorbed upstream joins). Returns
    (block, info) or None when any gate fails (dtype, empty side, plane
    overflow, join row limit, non-bool residual) — the caller's host
    fallback owns exact semantics for those."""
    if _FAILED:
        return None
    from . import operators
    from ..ops import join_pipeline as jp

    lview, rview = _as_view(left), _as_view(right)
    ln, rn = lview.n, rview.n
    if ln == 0 or rn == 0:
        return None
    join = plan.join_node
    lcodes, rcodes = operators._joint_codes(
        [lview.host_col(k) for k in join.left_keys],
        [rview.host_col(k) for k in join.right_keys], ln, rn, ctx)

    probe, build = ((lview, rview) if plan.probe_side == "left"
                    else (rview, lview))
    pcodes, bcodes = ((lcodes, rcodes) if plan.probe_side == "left"
                      else (rcodes, lcodes))
    pn, bn = len(pcodes), len(bcodes)
    # raw int keys ARE their own codes (the int fast path): values at or
    # above the kernel's pad sentinels would alias padding
    for c in (pcodes, bcodes):
        if len(c) and (int(c.max()) >= (1 << 62)
                       or int(c.min()) <= -(1 << 62)):
            return None
    # min build code feeds the partition kernel's packed-sort fast path
    bmin = int(bcodes.min()) if len(bcodes) else 0

    # bit-identity gate: integer-valued f64 accumulation is exact, hence
    # reduction-order-free; float args would make partition order visible
    pv_names = [c for k, s, c, _ in plan.aggs if s == "probe"]
    bv_names = [c for k, s, c, _ in plan.aggs if s == "build"]
    for side_view, names in ((probe, pv_names), (build, bv_names)):
        for nm in dict.fromkeys(names):
            arr, _ = side_view.raw(nm)
            if not operators._int_like(np.asarray(arr)):
                return None

    # residual conjuncts factorize into per-side row masks; each must
    # evaluate to a real boolean vector (then the host's AND/_truthy and
    # the device's mask multiply agree exactly — NaN truthiness never
    # enters), else the host path owns the semantics
    pmask = bmask = None
    for rel, expr, cols in plan.residual:
        view = probe if rel == "probe" else build
        blk = {key: view.host_col(col) for key, col in cols}
        m = np.asarray(operators.eval_expr(
            expr, blk, probe.n if rel == "probe" else build.n))
        if m.ndim != 1 or m.dtype != np.bool_:
            return None
        if rel == "probe":
            pmask = m if pmask is None else (pmask & m)
        else:
            bmask = m if bmask is None else (bmask & m)

    gcols = [probe.host_col(c) for _, c in plan.group_cols]
    gcodes, num, first = operators.group_codes(gcols)
    if num == 0:
        return None

    P = fused_partitions()
    Np, Nb = jp.bucket(pn), jp.bucket(bn)
    # plane caps: the partition mix is pure, so the EXACT per-partition
    # counts are a ~1ms host bincount — size each plane to the real max
    # (pow2-bucketed for compile sharing). Tight caps halve every
    # downstream plane pass vs a fixed headroom factor, and skewed keys
    # (NULL buckets, heavy hitters) stay on device as long as their
    # partition fits a plane at all.
    cap_l = min(Np, jp.bucket(max(
        64, int(jp.host_partition_counts(pcodes, P).max()))))
    cap_r = min(Nb, jp.bucket(max(
        64, int(jp.host_partition_counts(bcodes, P).max()))))
    Gp = jp.bucket(num)

    def pad1(a, n_to, dtype):
        out = np.zeros(n_to, dtype=dtype)
        out[:len(a)] = a
        return out

    def padmask(m, n_to):
        out = np.zeros(n_to, dtype=bool)
        out[:len(m)] = m
        return out

    dispatches = [3]

    def side_vals(view, order, n_to):
        """Value plane of one side: plain blocks pad on host; chained
        sides gather ON DEVICE through the composed chain indices (one
        dispatch per distinct leaf), so chain values never materialize
        host-side."""
        if not order:
            return np.zeros((1, n_to))
        if isinstance(view, _LeafView):
            return np.stack([pad1(np.asarray(view.block[c], np.float64),
                                  n_to, np.float64) for c in order])
        import jax.numpy as jnp

        groups: dict = {}
        for pos, c in enumerate(order):
            arr, idx = view.raw(c)
            groups.setdefault(id(idx), (idx, []))[1].append((pos, arr))
        parts = [None] * len(order)
        for idx, cols in groups.values():
            plane = jp.gather_stack([a for _, a in cols], idx, view.n, n_to)
            dispatches[0] += 1
            for row, (pos, _) in enumerate(cols):
                parts[pos] = plane[row]
        return jnp.stack(parts)

    pv_order = list(dict.fromkeys(pv_names))
    bv_order = list(dict.fromkeys(bv_names))
    spec = tuple(
        ("count", "probe", 0) if k == "count"
        else (k, s, (pv_order if s == "probe" else bv_order).index(c))
        for k, s, c, _ in plan.aggs)

    try:
        pvals = side_vals(probe, pv_order, Np)
        bvals = side_vals(build, bv_order, Nb)
        pk = pad1(pcodes, Np, np.int64)
        bk = pad1(bcodes, Nb, np.int64)
        pg = pad1(gcodes, Np, np.int64)
        # probe plane only needs partition grouping (cheap one-key sort);
        # the build plane must come out ascending-key for binary search
        pplane, pcounts = jp.partition_planes(pk, pn, P, cap_l)
        bplane, bcounts = jp.partition_planes(bk, bn, P, cap_r,
                                              key_sorted=True, cmin=bmin)
        packed = jp.fused_join_agg(
            pk, pg, pvals, pplane, pcounts, bk, bvals, bplane, bcounts,
            pn, bn, spec, P, Gp, join_type=plan.join_type,
            pmask=padmask(pmask, Np) if pmask is not None else None,
            bmask=padmask(bmask, Nb) if bmask is not None else None)
        out = jp.fetch_packed(packed)
    except Exception as e:
        note_failure(e)
        return None

    n_aggs = len(plan.aggs)
    meta = out[n_aggs + 2]
    total_pairs = int(meta[0])
    if meta[1] != 0.0 or total_pairs > operators.MAX_ROWS_IN_JOIN:
        # plane overflow (key skew beyond the cap headroom) or the join row
        # guard: the host path owns THROW/BREAK semantics
        return None
    w_row = out[n_aggs][:num]         # output rows per group
    match_row = out[n_aggs + 1][:num]  # matched pairs per group
    present = w_row > 0

    block = {}
    for (out_name, col), kv in zip(plan.group_cols, gcols):
        block[out_name] = kv[first][present]
    no_match = match_row[present] == 0
    for i, (kind, s, _c, out_name) in enumerate(plan.aggs):
        vals = out[i][:num][present]
        if kind == "count":
            block[out_name] = vals.astype(np.int64)
            continue
        if s == "build" and no_match.any():
            # a group whose every output row is LEFT-padded aggregates
            # NULL build payload — the host emits NaN there
            vals = vals.copy()
            vals[no_match] = np.nan
        block[out_name] = vals
    return block, {"total_pairs": total_pairs,
                   "dispatches": dispatches[0]}
