"""Device sort-merge equi-join for large MSE intermediates.

Reference analogue: HashJoinOperator
(pinot-query-runtime/.../runtime/operator/HashJoinOperator.java) builds a
host hash table per worker. Hash tables are hostile to a TPU's vector
units; the TPU-first shape is sort + vectorized binary search — the same
machinery the sparse group-by kernel rides:

    rs            = sort(right_keys, iota)          one lax.sort
    starts, ends  = searchsorted(rs, left_keys)     log-passes, vectorized
    expansion     = searchsorted(cumsum(counts), j) one output row per match

Only the JOIN KEYS travel to the device (already dict-coded to int64 by
the host join's joint-code pass); the result is (left_idx, right_idx)
pairs, and payload columns gather on host. Output is capped at a static
bucket so compiled programs are shared; overflow reports back for the
THROW/BREAK join guards.

Gating: ``PINOT_TPU_DEVICE_JOIN`` = auto (default: on when a non-CPU jax
backend is live and the sides are large) | 1 (force) | 0 (off).
"""

from __future__ import annotations

import functools
import os

import numpy as np

# below this many total key rows the host numpy argsort wins (device
# dispatch + transfer overhead dominates)
AUTO_MIN_ROWS = 4_000_000


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return max(b, 1024)


@functools.cache
def _jit_join_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)  # engine-wide invariant
    # ln/rn are TRACED scalars: only the padded bucket shapes and the
    # output cap are static, so compiled programs are shared across the
    # actual row counts (a static ln would recompile per input size,
    # defeating the bucket padding)
    return functools.partial(jax.jit, static_argnames=("max_out",))(
        _join_kernel)


def _join_kernel(lk, rk, ln, rn, max_out: int):
    import jax
    import jax.numpy as jnp

    SENT = jnp.int64(1 << 62)
    lvalid = jnp.arange(lk.shape[0]) < ln
    rvalid = jnp.arange(rk.shape[0]) < rn
    lkm = jnp.where(lvalid, lk, SENT)
    rkm = jnp.where(rvalid, rk, SENT)
    rs_keys, rs_idx = jax.lax.sort(
        (rkm, jnp.arange(rk.shape[0], dtype=jnp.int32)), num_keys=1)
    starts = jnp.searchsorted(rs_keys, lkm, side="left")
    ends = jnp.searchsorted(rs_keys, lkm, side="right")
    counts = jnp.where(lvalid, ends - starts, 0)
    incl = jnp.cumsum(counts)
    total = incl[-1]
    excl = incl - counts
    j = jnp.arange(max_out)
    li = jnp.searchsorted(incl, j, side="right")
    li_c = jnp.minimum(li, lk.shape[0] - 1)
    ri = rs_idx[jnp.minimum(starts[li_c] + (j - excl[li_c]),
                            rk.shape[0] - 1)]
    valid_out = j < jnp.minimum(total, max_out)
    return (jnp.where(valid_out, li_c, -1).astype(jnp.int32),
            jnp.where(valid_out, ri, -1).astype(jnp.int32),
            total.astype(jnp.int64))


def device_join_indices(lcodes: np.ndarray, rcodes: np.ndarray,
                        max_out: int):
    """(lidx, ridx, total) for the INNER equi-join of two int64 key
    arrays. ``total`` is the TRUE match count; at most ``max_out`` pairs
    are returned (ascending left order, right order within a left row
    following the right side's sort)."""
    ln, rn = len(lcodes), len(rcodes)
    lk = np.full(_bucket(ln), 0, dtype=np.int64)
    rk = np.full(_bucket(rn), 0, dtype=np.int64)
    lk[:ln] = lcodes
    rk[:rn] = rcodes
    li, ri, total = _jit_join_kernel()(
        lk, rk, np.int64(ln), np.int64(rn), max_out=_bucket(max_out))
    total = int(total)
    n = min(total, max_out)
    return np.asarray(li)[:n], np.asarray(ri)[:n], total


@functools.cache
def _jit_gather_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)

    def _gather(idx, cols):
        import jax.numpy as jnp

        return [jnp.take(c, idx, mode="clip") for c in cols]

    return jax.jit(_gather)


def gather_payload(cols: dict, idx: np.ndarray):
    """Fused payload gather: materialize every pruned output column of a
    device-joined side in ONE device dispatch (XLA fuses the per-column
    takes) instead of one host fancy-index per column. Inputs are padded to
    power-of-2 buckets so compiled programs are shared across row counts.
    Returns None (caller falls back to the host gather) on any failure."""
    if _FAILED or not cols:
        return None
    try:
        n = len(idx)
        pidx = np.zeros(_bucket(max(n, 1)), dtype=np.int64)
        pidx[:n] = idx
        padded = []
        for v in cols.values():
            pv = np.zeros(_bucket(max(len(v), 1)), dtype=v.dtype)
            pv[:len(v)] = v
            padded.append(pv)
        out = _jit_gather_kernel()(pidx, padded)
        return {name: np.asarray(o)[:n] for name, o in zip(cols, out)}
    except Exception as e:
        note_failure(e)
        return None


_FAILED = False


def note_failure(exc: BaseException) -> None:
    """Log the first device-join failure and disable the path for the
    process — a persistent misconfiguration must be visible, not a silent
    per-join failed attempt."""
    global _FAILED
    if not _FAILED:
        _FAILED = True
        import logging

        logging.getLogger(__name__).warning(
            "device join failed (%s: %s); falling back to the host join "
            "for this process", type(exc).__name__, exc)


def enabled(ln: int, rn: int) -> bool:
    if _FAILED:
        return False
    mode = os.environ.get("PINOT_TPU_DEVICE_JOIN", "auto").lower()
    if mode in ("0", "off", "false"):
        return False
    if mode in ("1", "on", "force", "true"):
        return True
    if ln + rn < AUTO_MIN_ROWS:
        return False
    try:
        from ..ops.mxu_groupby import backend_platform

        return backend_platform() != "cpu"
    except Exception:
        return False
