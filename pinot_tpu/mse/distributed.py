"""Cross-process MSE: plan dispatch + mailbox shuffle over the TCP transport.

Reference analogue: QueryDispatcher.submit (pinot-query-runtime/.../service/
dispatch/QueryDispatcher.java:126) serializes plan fragments to workers over
gRPC, GrpcMailboxService carries shuffled blocks between worker processes
(pinot-common/src/main/proto/mailbox.proto), and the broker performs the
final receive + reduce.

Here the dispatcher lives on the broker (`DistributedMseDispatcher`), plan
fragments travel as the JSON contract in plan_serde.py, and mailbox blocks
ride the same framed-TCP RPC plane the scatter/gather query path uses
(cluster/transport.py). Stage workers are `ServerInstance` processes; each
hosts an `MseWorkerService` holding its mailbox store.

The data plane is PIPELINED, like the reference's streaming gRPC mailboxes
(GrpcMailboxServer.java:43 + .../runtime/operator/exchange/): all stages'
workers are dispatched CONCURRENTLY, producers ship their output in row
CHUNKS as they become available followed by a per-sender EOS marker, and a
receive blocks only until every declared sender has finished. Stages
therefore overlap in wall time, and a final-phase aggregate consumes its
mailbox incrementally (chunk → partial-merge) so a large shuffle never
fully materializes in one process: buffered bytes are bounded by a credit
(`MAILBOX_BUFFER_BYTES`) that blocks producers when a draining consumer
falls behind (backpressure).

Leaf stages execute over an explicit per-worker segment list chosen by the
broker's replica selector (never "all hosted segments": with replication
> 1 that would double-count rows), and hybrid tables are split
offline/realtime at the time boundary exactly like the single-stage broker
path (TimeBoundaryManager semantics).
"""

from __future__ import annotations

import copy
import itertools
import os
import threading
import time
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ..cluster import datatable
from ..engine.aggregation import UnsupportedQueryError
from ..engine.reduce import BrokerReducer
from ..engine.results import BrokerResponse
from ..spi import faults
from ..spi.metrics import SERVER_METRICS, ServerMeter
from ..spi.trace import TRACING
from ..query.converter import filter_from_expression
from ..query.expressions import ExpressionContext
from .executor import _block_to_result
from .fragmenter import Stage, explain_stages, fragment, receive_nodes
from .logical import LogicalPlanner, prune_columns
from .optimizer import push_filters
from .mailbox import (Block, block_len, block_nbytes, concat_blocks,
                      hash_partition, table_partition)
from .operators import op_filter
from .parser import parse_relational
from .plan_serde import expr_from_json, expr_to_json, stage_from_json, stage_to_json
from .runtime import StageRunner

EC = ExpressionContext


# rows per shipped chunk; small enough that a consumer overlaps a producer,
# large enough that framing overhead stays negligible
CHUNK_ROWS = int(os.environ.get("PINOT_TPU_MSE_CHUNK_ROWS", 65536))
# buffered-bytes credit per mailbox once a streaming consumer is draining it
MAILBOX_BUFFER_BYTES = int(os.environ.get(
    "PINOT_TPU_MSE_MAILBOX_BUFFER_BYTES", 64 << 20))
# ceiling on waiting for senders (a crashed producer must not hang a worker)
MAILBOX_WAIT_S = float(os.environ.get("PINOT_TPU_MSE_MAILBOX_WAIT_S", 300))
# blocks at least this large cross servers as ONE device-packed byte blob
# (the PR-12 byte-pack kernel flattens the columns on device; the host side
# is a single memcpy to the socket instead of per-row DataTable encodes)
DEVICE_PACK_MIN_BYTES = int(os.environ.get(
    "PINOT_TPU_DEVICE_PACK_MIN_BYTES", 1 << 20))


def _block_nbytes(block: Block) -> int:
    return sum(np.asarray(v).nbytes for v in block.values())


def _wire_packable(block: Block) -> bool:
    """Eligible for the device-packed wire format: numeric columnar block at
    least DEVICE_PACK_MIN_BYTES (below that, framing a second format is not
    worth skipping the row encodes)."""
    return (block is not None and _block_nbytes(block) >= DEVICE_PACK_MIN_BYTES
            and datatable.packable_block(block))


def _pack_for_wire(block: Block):
    """Device-serialize an eligible block for a cross-server hop, or None
    to fall back to shipping the raw column dict."""
    if not _wire_packable(block):
        return None
    try:
        return datatable.encode_packed_block(block)
    except Exception:
        return None  # e.g. no device available — raw dict still works


class MailboxCancelled(Exception):
    pass


class MailboxStore:
    """Per-process store of streamed chunks, keyed by
    (query_id, from_stage, to_stage, partition) — the mailbox id scheme of
    the reference (`{requestId}|{sender}|{receiver}|{worker}`).

    Producers append chunks and finally mark per-sender EOS; consumers
    either materialize (wait for all senders, concat) or stream (drain
    chunks as they arrive — registering as a streamer arms the buffer
    credit so `put` backpressures a runaway producer). Tracks cumulative
    and high-water buffered bytes per query for the pipeline stats."""

    def __init__(self):
        self._chunks: dict[tuple, list[Block]] = defaultdict(list)
        self._eos: dict[tuple, set] = defaultdict(set)
        self._buffered: dict[tuple, int] = defaultdict(int)
        self._streaming: set = set()
        self._cancelled: set = set()
        self._total_bytes: dict[str, int] = defaultdict(int)
        self._peak_bytes: dict[str, int] = defaultdict(int)
        # (key, sender) → highest seq accepted: transport-level retries
        # re-deliver a chunk whose response was lost; duplicates must be
        # dropped, not double-counted (reference: gRPC stream sequencing).
        # _inflight_seq guards the window where the ORIGINAL delivery is
        # still blocked in the backpressure wait — a retry arriving then
        # must neither enqueue a second copy nor mark the seq accepted
        # before the append actually happened.
        self._last_seq: dict[tuple, int] = {}
        self._inflight_seq: set = set()
        # query_id → absolute monotonic deadline: every wait clamps to the
        # query's REMAINING budget instead of the flat MAILBOX_WAIT_S
        # ceiling (deadline propagation across the shuffle plane)
        self._deadlines: dict[str, float] = {}
        self._cond = threading.Condition()

    def set_deadline(self, query_id: str, deadline: float) -> None:
        """Register the query's absolute (monotonic) deadline."""
        with self._cond:
            self._deadlines[query_id] = deadline
            self._cond.notify_all()

    def _deadline_for(self, query_id: str) -> float:
        return min(time.monotonic() + MAILBOX_WAIT_S,
                   self._deadlines.get(query_id, float("inf")))

    def _check(self, query_id: str) -> None:
        if query_id in self._cancelled:
            raise MailboxCancelled(query_id)

    def put(self, query_id: str, from_stage: int, to_stage: int,
            partition: int, block: Block, sender: int = 0,
            seq: Optional[int] = None) -> None:
        key = (query_id, from_stage, to_stage, partition)
        nbytes = _block_nbytes(block)
        with self._cond:
            skey = None
            if seq is not None:
                skey = (key, sender, seq)
                if seq <= self._last_seq.get((key, sender), -1) \
                        or skey in self._inflight_seq:
                    return  # duplicate delivery (retried RPC)
                self._inflight_seq.add(skey)
            deadline = self._deadline_for(query_id)
            try:
                while (key in self._streaming
                       and self._buffered[key] + nbytes > MAILBOX_BUFFER_BYTES
                       and self._buffered[key] > 0):
                    self._check(query_id)
                    if not self._cond.wait(1.0) and time.monotonic() > deadline:
                        raise TimeoutError(f"mailbox {key} backpressure stall")
                self._check(query_id)
            finally:
                if skey is not None:
                    self._inflight_seq.discard(skey)
            if seq is not None:
                # accepted only now — a put that failed in the wait leaves
                # the seq unrecorded so a later retry can land it
                self._last_seq[(key, sender)] = seq
            self._chunks[key].append(block)
            self._buffered[key] += nbytes
            total = sum(v for k, v in self._buffered.items()
                        if k[0] == query_id)
            self._total_bytes[query_id] += nbytes
            self._peak_bytes[query_id] = max(
                self._peak_bytes[query_id], total)
            self._cond.notify_all()

    def deliver(self, request: dict) -> None:
        """Apply one mse_mailbox request (chunk and/or EOS) — the single
        decode point shared by worker and broker endpoints."""
        block = request.get("block")
        if block is None and request.get("packed") is not None:
            # device-packed exchange: one contiguous byte blob → device,
            # split back into columns there (CRC-checked; a corrupted frame
            # raises instead of materializing garbage rows)
            block = datatable.decode_packed_block(request["packed"])
        if block is not None:
            self.put(request["query_id"], request["from_stage"],
                     request["to_stage"], request["partition"],
                     block, sender=request.get("sender", 0),
                     seq=request.get("seq"))
        if request.get("eos"):
            self.mark_eos(request["query_id"], request["from_stage"],
                          request["to_stage"], request["partition"],
                          request.get("sender", 0))

    def mark_eos(self, query_id: str, from_stage: int, to_stage: int,
                 partition: int, sender: int) -> None:
        with self._cond:
            self._eos[(query_id, from_stage, to_stage, partition)].add(sender)
            self._cond.notify_all()

    def wait_all(self, query_id: str, from_stage: int, to_stage: int,
                 partition: int, expected_senders: int) -> list[Block]:
        """Materializing receive: all senders' chunks, after every EOS."""
        key = (query_id, from_stage, to_stage, partition)
        deadline = self._deadline_for(query_id)
        with self._cond:
            while len(self._eos[key]) < expected_senders:
                self._check(query_id)
                if not self._cond.wait(1.0) and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"mailbox {key}: {len(self._eos[key])}/"
                        f"{expected_senders} senders at deadline")
            self._check(query_id)
            chunks = self._chunks.pop(key, [])
            self._buffered[key] = 0
            self._cond.notify_all()
            return chunks

    def stream(self, query_id: str, from_stage: int, to_stage: int,
               partition: int, expected_senders: int):
        """Draining receive: yield chunks in arrival order, freeing each
        (credit release) — stops once all senders EOS'd and queue is dry."""
        key = (query_id, from_stage, to_stage, partition)
        with self._cond:
            self._streaming.add(key)
        deadline = self._deadline_for(query_id)
        try:
            while True:
                with self._cond:
                    while not self._chunks[key] and \
                            len(self._eos[key]) < expected_senders:
                        self._check(query_id)
                        if not self._cond.wait(1.0) and \
                                time.monotonic() > deadline:
                            raise TimeoutError(f"mailbox {key} stream stall")
                    self._check(query_id)
                    if self._chunks[key]:
                        chunk = self._chunks[key].pop(0)
                        self._buffered[key] -= _block_nbytes(chunk)
                        self._cond.notify_all()
                    else:
                        return
                yield chunk
                deadline = self._deadline_for(query_id)
        finally:
            with self._cond:
                self._streaming.discard(key)

    def metrics(self, query_id: str) -> dict:
        with self._cond:
            return {"mailbox_bytes_total": self._total_bytes.get(query_id, 0),
                    "mailbox_bytes_peak": self._peak_bytes.get(query_id, 0)}

    def cancel(self, query_id: str) -> None:
        with self._cond:
            self._cancelled.add(query_id)
            self._cond.notify_all()

    def cleanup(self, query_id: str) -> None:
        with self._cond:
            for d in (self._chunks, self._eos, self._buffered):
                for key in [k for k in d if k[0] == query_id]:
                    del d[key]
            for skey in [k for k in self._last_seq if k[0][0] == query_id]:
                del self._last_seq[skey]
            self._inflight_seq = {k for k in self._inflight_seq
                                  if k[0][0] != query_id}
            self._total_bytes.pop(query_id, None)
            self._peak_bytes.pop(query_id, None)
            self._deadlines.pop(query_id, None)
            self._cancelled.discard(query_id)
            self._cond.notify_all()


class RoutedMailbox:
    """StageRunner-compatible mailbox whose sends cross process boundaries.

    ``routing`` maps (to_stage, partition) → (host, port); a partition routed
    to this process's own address short-circuits to the local store.
    ``sender`` identifies this worker in EOS markers; ``expected`` maps
    from_stage → number of sender workers a receive must wait for."""

    def __init__(self, boxes: MailboxStore, query_id: str,
                 routing: dict[tuple[int, int], tuple[str, int]],
                 self_addr: tuple[str, int], send_rpc: Callable,
                 sender: int = 0, expected: Optional[dict[int, int]] = None):
        self.boxes = boxes
        self.query_id = query_id
        self.routing = routing
        self.self_addr = self_addr
        self.send_rpc = send_rpc  # (addr, request_dict) → None
        self.sender = sender
        self.expected = expected or {}
        self._seq: dict[tuple[int, int], int] = defaultdict(int)
        self.first_send_ts: Optional[float] = None
        self.last_send_ts: Optional[float] = None
        # same stage-stats counters as the in-process MailboxService
        self.sent_rows: dict[int, int] = defaultdict(int)
        self.sent_bytes: dict[int, int] = defaultdict(int)

    def _expected_senders(self, from_stage: int) -> int:
        # an absent declared-sender count must be loud: defaulting to 0 would
        # make wait_all return immediately with whatever raced in (silently
        # empty/partial results). A genuinely zero-worker child (empty table)
        # is declared explicitly as 0 by the dispatcher.
        if from_stage not in self.expected:
            raise UnsupportedQueryError(
                f"no declared sender count for child stage {from_stage} "
                f"(dispatcher omitted child_workers)")
        return self.expected[from_stage]

    def receive(self, from_stage: int, to_stage: int, partition: int,
                schema=None) -> Block:
        chunks = self.boxes.wait_all(
            self.query_id, from_stage, to_stage, partition,
            self._expected_senders(from_stage))
        return concat_blocks(chunks, schema)

    def stream(self, from_stage: int, to_stage: int, partition: int,
               schema=None):
        return self.boxes.stream(self.query_id, from_stage, to_stage,
                                 partition, self._expected_senders(from_stage))

    def send(self, from_stage: int, to_stage: int, partition: int,
             block: Block, eos: bool = False) -> None:
        addr = self.routing.get((to_stage, partition))
        if addr is None:
            raise UnsupportedQueryError(
                f"no route for stage {to_stage} partition {partition}")
        now = time.monotonic()
        self.first_send_ts = self.first_send_ts or now
        self.last_send_ts = now
        if block is not None:
            self.sent_rows[from_stage] += block_len(block)
            self.sent_bytes[from_stage] += block_nbytes(block)
        seq = self._seq[(to_stage, partition)]
        self._seq[(to_stage, partition)] += 1
        if tuple(addr) == tuple(self.self_addr):
            if block is not None:
                self.boxes.put(self.query_id, from_stage, to_stage,
                               partition, block, sender=self.sender, seq=seq)
            if eos:
                self.boxes.mark_eos(self.query_id, from_stage, to_stage,
                                    partition, self.sender)
            return
        req = {"type": "mse_mailbox", "query_id": self.query_id,
               "from_stage": from_stage, "to_stage": to_stage,
               "partition": partition, "block": block,
               "sender": self.sender, "seq": seq}
        packed = _pack_for_wire(block)
        if packed is not None:
            req["block"] = None
            req["packed"] = packed
            SERVER_METRICS.add_meter(
                ServerMeter.DEVICE_PACKED_EXCHANGE_BYTES, len(packed))
        if eos:
            req["eos"] = True
        self.send_rpc(tuple(addr), req)

    def finish(self, from_stage: int, to_stage: int,
               num_partitions: int) -> None:
        """EOS to every partition of the parent stage (empty ones too)."""
        for p in range(num_partitions):
            self.send(from_stage, to_stage, p, None, eos=True)

    def send_partitioned(self, from_stage: int, to_stage: int, block: Block,
                         dist: str, keys: list[str], num_partitions: int,
                         pfunc: Optional[str] = None,
                         final: bool = True) -> None:
        """Ship one output block in CHUNK_ROWS chunks (pipelining: the
        consumer starts while later chunks are still in flight). With
        ``final`` (the default, one-shot producers) EOS follows the last
        chunk; chunked producers pass final=False and call finish()."""
        # a pack-eligible block skips row-chunking: it crosses the wire as
        # ONE device-packed blob, so splitting it first would re-introduce
        # the per-chunk host encodes the packed path exists to avoid
        chunks = [block] if _wire_packable(block) else _iter_chunks(block)
        for chunk in chunks:
            if dist == "partitioned" and keys and num_partitions > 1:
                # colocated join: route by the TABLE partition function — a
                # leaf whose segments are all one partition sends one
                # non-empty box
                for p, b in enumerate(table_partition(
                        chunk, keys[0], pfunc, num_partitions)):
                    if block_len(b):
                        self.send(from_stage, to_stage, p, b)
            elif dist == "hash" and keys and num_partitions > 1:
                for p, b in enumerate(hash_partition(
                        chunk, keys, num_partitions)):
                    if block_len(b):
                        self.send(from_stage, to_stage, p, b)
            elif dist == "broadcast":
                for p in range(num_partitions):
                    self.send(from_stage, to_stage, p, chunk)
            else:
                self.send(from_stage, to_stage, 0, chunk)
        if final:
            if dist == "broadcast" or (dist in ("hash", "partitioned")
                                       and keys and num_partitions > 1):
                self.finish(from_stage, to_stage, num_partitions)
            else:
                self.finish(from_stage, to_stage, 1)


def _iter_chunks(block: Block):
    n = block_len(block)
    if n <= CHUNK_ROWS:
        yield block
        return
    for lo in range(0, n, CHUNK_ROWS):
        yield {c: np.asarray(v)[lo:lo + CHUNK_ROWS]
               for c, v in block.items()}


# -- worker side --------------------------------------------------------------


class MseWorkerService:
    """Stage execution endpoint living on a ServerInstance. Handles
    ``mse_stage`` (run one stage worker), ``mse_mailbox`` (accept a shuffled
    block), and ``mse_cleanup`` — the worker half of QueryRunner.processQuery
    + GrpcMailboxService."""

    def __init__(self, server):
        self.server = server  # cluster.server.ServerInstance
        self.boxes = MailboxStore()
        self._clients: dict[tuple, object] = {}
        self._lock = threading.Lock()

    # -- transport helpers -------------------------------------------------
    def _send_rpc(self, addr: tuple[str, int], request: dict) -> None:
        from ..cluster.transport import RpcClient

        with self._lock:
            client = self._clients.get(addr)
            if client is None:
                client = RpcClient(addr[0], addr[1])
                self._clients[addr] = client
        client.call(request)

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()

    # -- request dispatch --------------------------------------------------
    def handle(self, request: dict):
        kind = request["type"]
        if kind == "mse_mailbox":
            if faults.ACTIVE:
                # safe to fail-and-retry: the store dedups on (sender, seq)
                faults.FAULTS.fire("mailbox.deliver",
                                   query_id=request.get("query_id"))
            self.boxes.deliver(request)
            return True
        if kind == "mse_cancel":
            self.boxes.cancel(request["query_id"])
            return True
        if kind == "mse_cleanup":
            self.boxes.cleanup(request["query_id"])
            return True
        if kind == "mse_stage":
            return self._run_stage(request)
        raise ValueError(f"unknown mse request {kind}")

    # -- stage execution ---------------------------------------------------
    def _run_stage(self, request: dict) -> dict:
        # trace ships back in the stats payload so the dispatcher can merge
        # every worker's spans into one broker-side tree (the scatter/gather
        # path in cluster/broker.py does the same for leaf queries)
        opts = request.get("options") or {}
        if opts.get("trace") not in (True, "true", 1) \
                or TRACING.active_trace() is not None:
            return self._run_stage_inner(request)
        trace = TRACING.start_trace(
            f"mse:{self.server.instance_id}",
            analyze=opts.get("analyze") in (True, "true", 1))
        try:
            stats = self._run_stage_inner(request)
            stats["trace"] = trace.to_json()
            return stats
        finally:
            TRACING.end_trace()

    def _run_stage_inner(self, request: dict) -> dict:
        stage = stage_from_json(request["stage"])
        query_id = request["query_id"]
        worker = request["worker"]
        parent_workers = request["parent_workers"]
        routing = {(stage.parent_stage, int(p)): tuple(a)
                   for p, a in request["routing"].items()}
        # halves: raw table → [(name_with_type, [segment], extra_filter_json)]
        halves = request.get("tables", {})
        # deadline propagation: the dispatcher ships its remaining budget;
        # this worker's mailbox waits and leaf executions clamp to it
        deadline = None
        deadline_ms = request.get("deadline_ms")
        if deadline_ms is not None:
            deadline = time.monotonic() + float(deadline_ms) / 1000.0
            self.boxes.set_deadline(query_id, deadline)

        mailbox = RoutedMailbox(
            self.boxes, query_id, routing, self.server.address,
            self._send_rpc, sender=worker,
            expected={int(k): int(v) for k, v in
                      (request.get("child_workers") or {}).items()})
        runner = StageRunner([stage], request.get("parallelism", 1),
                             self._make_execute_query(halves, deadline),
                             self._make_read_table(halves),
                             query_options=request.get("options") or {})
        runner.mailbox = mailbox

        from .operators import pop_join_overflow

        pop_join_overflow()  # clear any stale flag on this handler thread
        runner.stats["exec_start_ts"] = time.monotonic()
        sstat = runner._sstat(stage.stage_id)
        t0 = time.perf_counter()
        with TRACING.scope(f"mse_stage:{stage.stage_id}") as span:
            pushed = runner._try_ssqe(stage) if stage.is_leaf else None
            if pushed is not None:
                runner.stats["leaf_ssqe_pushdowns"] += 1
                sstat["leaf_pushdown"] = True
                block = pushed
            else:
                if stage.is_leaf and runner._null_handling_requested():
                    raise UnsupportedQueryError(
                        "enableNullHandling requires this leaf stage to push "
                        "down to the single-stage engine")
                block = runner._exec(stage.root, stage, worker)
            sstat["workers"] = 1  # this worker's share; the dispatcher sums
            sstat["rows_out"] += block_len(block)
            mailbox.send_partitioned(stage.stage_id, stage.parent_stage,
                                     runner._trim_to_send(stage, block),
                                     stage.send_dist, stage.send_keys,
                                     parent_workers, pfunc=stage.send_pfunc)
            sstat["wall_ms"] += (time.perf_counter() - t0) * 1000
            sstat["shuffled_rows"] = mailbox.sent_rows[stage.stage_id]
            sstat["shuffled_bytes"] = mailbox.sent_bytes[stage.stage_id]
            if span is not None:
                span.set_attribute("worker", worker)
                span.set_attribute("rows_out", int(sstat["rows_out"]))
                span.set_attribute("shuffled_rows",
                                   int(sstat["shuffled_rows"]))
                span.set_attribute("shuffled_bytes",
                                   int(sstat["shuffled_bytes"]))
                if sstat.get("leaf_pushdown"):
                    span.set_attribute("leaf_pushdown", True)
        runner.stats["join_overflow"] = (
            pop_join_overflow() or bool(runner.stats.get("join_overflow")))
        runner.stats["first_send_ts"] = mailbox.first_send_ts
        runner.stats["last_send_ts"] = mailbox.last_send_ts
        runner.stats["stage_stats"] = {
            str(k): v for k, v in runner.stage_stats.items()}
        runner.stats.update(self.boxes.metrics(query_id))
        return runner.stats

    def _halves_for(self, halves: dict, table: str):
        entry = halves.get(table)
        if entry is None:
            raise UnsupportedQueryError(
                f"table {table} not assigned to this worker")
        return entry

    def _leaf_segments(self, nwt: str, seg_names,
                       deadline: Optional[float] = None) -> dict:
        """Resolve routed segment names to loaded segments, lazily warming
        cold (metadata-only) registrations within the stage deadline. An
        MSE leaf has no partial-results channel, so a routed-but-still-cold
        segment must raise (the broker surfaces a query exception) rather
        than be skipped into a silently truncated scan."""
        server = self.server
        with server._lock:
            hosted = server.segments.get(nwt, {})
            cold = [n for n in seg_names if n not in hosted
                    and n in server._cold.get(nwt, {})]
        if cold:
            deadline_ms = None
            if deadline is not None:
                deadline_ms = max(
                    0.0, (deadline - time.monotonic()) * 1000.0)
            server._warm_cold_segments(nwt, cold, deadline_ms)
            with server._lock:
                hosted = server.segments.get(nwt, {})
                still = [n for n in cold if n not in hosted]
            if still:
                raise RuntimeError(
                    f"cold segments still warming for {nwt}: {still}")
        return dict(hosted)

    def _make_execute_query(self, halves: dict,
                            deadline: Optional[float] = None) -> Callable:
        """Leaf SSQE entry: run the compiled QueryContext over this worker's
        assigned segments (per hybrid half), reduce each half table-locally,
        and concatenate — the parent stage's final aggregation phase merges
        partials across halves and workers. ``deadline`` (absolute
        monotonic) clamps each half's per-segment timeoutMs to the query's
        remaining budget."""

        def execute_query(qc) -> BrokerResponse:
            from ..query.filter import FilterContext

            out_rows, schema = [], None
            scanned = total = dispatches = compiles = 0
            for nwt, seg_names, extra in self._halves_for(halves, qc.table_name):
                hosted = self._leaf_segments(nwt, seg_names, deadline)
                segs = [hosted[n] for n in seg_names if n in hosted]
                q2 = copy.deepcopy(qc)
                q2.table_name = nwt
                if deadline is not None:
                    remaining_ms = max(
                        50.0, (deadline - time.monotonic()) * 1000.0)
                    cur = q2.query_options.get("timeoutMs")
                    try:
                        cur = float(cur) if cur is not None else None
                    except (TypeError, ValueError):
                        cur = None
                    q2.query_options["timeoutMs"] = (
                        remaining_ms if cur is None
                        else min(cur, remaining_ms))
                if extra is not None:
                    fc = filter_from_expression(expr_from_json(extra))
                    q2.filter = fc if q2.filter is None else \
                        FilterContext.and_(q2.filter, fc)
                with self.server._tier.reading(
                        nwt, [n for n in seg_names if n in hosted]):
                    combined, stats = self.server.executor.execute_segments(
                        q2, segs)
                table = self.server.executor.tables.get(nwt)
                result = BrokerReducer(table.schema if table else None).reduce(
                    q2, combined)
                scanned += getattr(combined, "num_docs_scanned", 0)
                total += stats.get("total_docs", 0)
                dispatches += stats.get("num_device_dispatches", 0)
                compiles += stats.get("num_compiles", 0)
                if result is not None:
                    schema = schema or result.schema
                    out_rows.extend(result.rows)
            from ..engine.results import ResultTable

            rt = ResultTable(schema, out_rows) if schema is not None else None
            return BrokerResponse(result_table=rt, num_docs_scanned=scanned,
                                  total_docs=total,
                                  num_device_dispatches=dispatches,
                                  num_compiles=compiles)

        return execute_query

    def _make_read_table(self, halves: dict) -> Callable:
        """Generic scan over assigned segments (non-SSQE leaf shapes), with
        the hybrid time-boundary filter applied per half."""

        def read_table(table: str, columns: list[str]) -> dict[str, np.ndarray]:
            blocks = []
            for nwt, seg_names, extra in self._halves_for(halves, table):
                hosted = self._leaf_segments(nwt, seg_names)
                extra_ec = expr_from_json(extra) if extra is not None else None
                need = list(dict.fromkeys(
                    list(columns) + sorted(extra_ec.columns() if extra_ec else [])))
                parts: dict[str, list] = {c: [] for c in need}
                with self.server._tier.reading(
                        nwt, [n for n in seg_names if n in hosted]):
                    for name in seg_names:
                        seg = hosted.get(name)
                        if seg is None:
                            continue
                        view = seg.snapshot_view() \
                            if getattr(seg, "is_mutable", False) else seg
                        vd = getattr(view, "valid_doc_ids", None)
                        keep = vd.mask(view.num_docs) if vd is not None else None
                        for c in need:
                            vals = np.asarray(view.get_values(c))
                            parts[c].append(
                                vals if keep is None else vals[keep])
                block = {}
                for c, arrs in parts.items():
                    if not arrs:
                        block[c] = np.empty(0)
                    elif len(arrs) == 1:
                        block[c] = arrs[0]
                    else:
                        if any(a.dtype.kind == "O" for a in arrs):
                            arrs = [a.astype(object) for a in arrs]
                        block[c] = np.concatenate(arrs)
                if extra_ec is not None:
                    block = op_filter(block, extra_ec)
                    block = {c: block[c] for c in columns}
                blocks.append(block)
            return concat_blocks(blocks, list(columns))

        return read_table


# -- dispatcher (broker side) -------------------------------------------------


class DistributedMseDispatcher:
    """Broker-side MSE entry: plan → fragment → assign stages to server
    processes → dispatch bottom-up → final receive + result assembly."""

    def __init__(self, broker, parallelism: int = 2):
        from ..cluster.transport import RpcServer

        self.broker = broker
        self.store = broker.store
        self.parallelism = parallelism
        self.boxes = MailboxStore()
        self._rpc = RpcServer(self._handle)
        self._qid = itertools.count()
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="mse-dispatch")

    def close(self) -> None:
        self._rpc.close()
        self._pool.shutdown(wait=False)

    @property
    def address(self) -> tuple[str, int]:
        return (self._rpc.host, self._rpc.port)

    def _handle(self, request: dict):
        if request.get("type") == "mse_mailbox":
            if faults.ACTIVE:
                faults.FAULTS.fire("mailbox.deliver",
                                   query_id=request.get("query_id"))
            self.boxes.deliver(request)
            return True
        raise ValueError("broker mailbox accepts only mse_mailbox")

    # -- catalog -----------------------------------------------------------
    def _catalog(self) -> dict[str, list[str]]:
        from ..spi.data_types import Schema

        out = {}
        for raw in self.store.children("/SCHEMAS"):
            sj = self.store.get(f"/SCHEMAS/{raw}")
            if sj is not None:
                out[raw] = Schema.from_json(sj).column_names()
        return out

    def _partition_catalog(self) -> dict[str, dict]:
        """table → {column: (pfunc, n_partitions)} from the DECLARED
        segmentPartitionConfig of the stored table configs (reference:
        the broker's TablePartitionInfo). A hybrid table only qualifies
        when both halves declare identical partitioning."""
        from ..cluster.controller import table_name_with_type

        def column_partition_map(cfg: dict) -> dict:
            # canonical location is tableIndexConfig.segmentPartitionConfig
            # (TableConfig.to_json / from_json); accept the top level too for
            # hand-rolled cluster configs
            spc = (cfg.get("tableIndexConfig") or {}).get(
                "segmentPartitionConfig") or cfg.get(
                "segmentPartitionConfig") or {}
            return spc.get("columnPartitionMap") or {}

        out: dict[str, dict] = {}
        for raw in self.store.children("/SCHEMAS"):
            maps = []
            for ttype in ("OFFLINE", "REALTIME"):
                cfg = self.store.get(
                    f"/CONFIGS/TABLE/{table_name_with_type(raw, ttype)}")
                if cfg is not None:
                    maps.append(column_partition_map(cfg))
            if not maps or (len(maps) == 2 and maps[0] != maps[1]):
                continue
            per_col = {}
            for col, v in maps[0].items():
                if v.get("functionName") and v.get("numPartitions"):
                    per_col[col] = (str(v["functionName"]).lower(),
                                    int(v["numPartitions"]))
            if per_col:
                out[raw] = per_col
        return out

    def _server_instances(self) -> list[str]:
        out = []
        for inst in sorted(self.store.children("/LIVEINSTANCES")):
            cfg = self.store.get(f"/LIVEINSTANCES/{inst}") or {}
            if "host" in cfg:
                out.append(inst)
        return out

    def _instance_addr(self, instance: str) -> tuple[str, int]:
        cfg = self.store.get(f"/LIVEINSTANCES/{instance}") or \
            self.store.get(f"/INSTANCECONFIGS/{instance}") or {}
        return (cfg["host"], cfg["port"])

    # -- physical assignment ----------------------------------------------
    def _leaf_assignment(self, stage: Stage):
        """instance → {raw_table: [(name_with_type, [segments], extra_json)]}
        via the broker's replica selector, with hybrid time-boundary split."""
        from ..cluster.controller import table_name_with_type

        per_instance: dict[str, dict[str, list]] = {}
        for scan in stage.scans():
            raw = scan.table
            offline = table_name_with_type(raw, "OFFLINE")
            realtime = table_name_with_type(raw, "REALTIME")
            has_off = self.store.get(f"/CONFIGS/TABLE/{offline}") is not None
            has_rt = self.store.get(f"/CONFIGS/TABLE/{realtime}") is not None
            if not has_off and not has_rt:
                raise UnsupportedQueryError(f"table {raw} not found")
            halves: list[tuple[str, Optional[dict]]] = []
            if has_off and has_rt:
                boundary = self.broker._time_boundary(offline)
                time_col = (self.store.get(f"/CONFIGS/TABLE/{offline}") or {}) \
                    .get("timeColumn")
                if boundary is not None and time_col:
                    halves.append((offline, expr_to_json(EC.for_function(
                        "lessthanorequal", EC.for_identifier(time_col),
                        EC.for_literal(boundary)))))
                    halves.append((realtime, expr_to_json(EC.for_function(
                        "greaterthan", EC.for_identifier(time_col),
                        EC.for_literal(boundary)))))
                else:
                    halves.append((offline, None))
                    halves.append((realtime, None))
            else:
                halves.append((offline if has_off else realtime, None))
            for nwt, extra in halves:
                routing = self.broker.routing_table(nwt)
                if not routing:
                    # distinguish an empty table (no segments → empty scan)
                    # from segments hidden/unroutable — the latter must be
                    # an availability error, not silent zero rows
                    if self.store.get(f"/IDEALSTATES/{nwt}"):
                        raise UnsupportedQueryError(
                            f"no routable segments for {nwt}")
                    continue
                plan = self.broker._select_instances(routing)
                for inst, segs in plan.items():
                    per_instance.setdefault(inst, {}).setdefault(raw, []) \
                        .append([nwt, sorted(segs), extra])
        # an existing-but-empty table yields zero workers: the stage is
        # skipped and its parent receives an empty block — matching the
        # in-process StageRunner's scan over zero segments
        return per_instance

    def _partition_worker_placement(self, stage, stages, workers,
                                    n: int) -> dict:
        """partition id → instance for a stage fed by "partitioned"
        (colocated-join) exchanges: worker p lands on the instance whose
        assigned child segments carry partition p on the exchange's OWN
        key column with a COMPATIBLE stamp (same function and count — a
        stale stamp from a changed segmentPartitionConfig must not place),
        so a single-partition leaf's send short-circuits to the local
        mailbox instead of crossing the wire. Partitions without a stamped
        host fall back to round-robin."""
        from collections import Counter, defaultdict

        if not any(node.dist == "partitioned"
                   for node in receive_nodes(stage.root)):
            return {}
        votes: dict[int, Counter] = defaultdict(Counter)
        for child_id in stage.child_stages:
            child = stages[child_id]
            if child.send_dist != "partitioned" or not child.send_keys:
                continue
            # the exchange key is qualified against the child's output
            # schema; map it to the scanned source column
            key_cols = set()
            for scan in child.scans():
                for q, src in zip(scan.schema, scan.source_columns):
                    if q == child.send_keys[0]:
                        key_cols.add(src)
            if not key_cols:
                continue
            for w in workers.get(child_id, []):
                for raw, entries in (w.get("tables") or {}).items():
                    for nwt, seg_names, _extra in entries:
                        for s in seg_names:
                            # name-with-type: controller-pushed segments;
                            # raw name: the realtime completion protocol's
                            # DONE records (realtime/completion.py)
                            rec = self.store.get(f"/SEGMENTS/{nwt}/{s}") \
                                or self.store.get(f"/SEGMENTS/{raw}/{s}") or {}
                            for col, info in (rec.get("partitions") or {}).items():
                                if col not in key_cols:
                                    continue
                                if not isinstance(info, dict) \
                                        or info.get("numPartitions") != n \
                                        or (child.send_pfunc and
                                            info.get("functionName") != child.send_pfunc):
                                    continue
                                for p in info.get("partitions") or []:
                                    if 0 <= int(p) < n:
                                        votes[int(p)][w["instance"]] += 1
        return {p: c.most_common(1)[0][0] for p, c in votes.items()}

    # -- execution ---------------------------------------------------------
    def execute_sql(self, sql: str) -> BrokerResponse:
        import time as _time

        t0 = _time.perf_counter()
        try:
            resp = self._execute(sql)
        except Exception as e:
            resp = BrokerResponse(exceptions=[f"{type(e).__name__}: {e}"])
        resp.time_used_ms = (_time.perf_counter() - t0) * 1000
        if getattr(resp, "_analyze_pending", False):
            from ..engine.explain import analyze_table

            resp._analyze_pending = False
            resp.result_table = analyze_table(resp.trace_info or [], resp)
        return resp

    def _execute(self, sql: str) -> BrokerResponse:
        from ..engine.results import DataSchema, ResultTable

        query = parse_relational(sql)
        planner = LogicalPlanner(query, self._catalog(),
                                 partition_catalog=self._partition_catalog)
        plan = planner.plan()
        plan = push_filters(plan)
        prune_columns(plan)
        stages = fragment(plan)
        analyze = query.explain == "analyze"
        if query.explain and not analyze:
            text = explain_stages(stages)
            return BrokerResponse(result_table=ResultTable(
                DataSchema(["plan"], ["STRING"]),
                [[line] for line in text.split("\n")]))

        # per-table QPS quota applies to every engine at the broker
        # (reference: quota check in BrokerRequestHandler before dispatch)
        quota_tables = set()
        for stage in stages:
            if stage.stage_id != 0:
                quota_tables.update(s.table for s in stage.scans())
        for t in sorted(quota_tables):
            self.broker.quota.acquire(t)

        topo = StageRunner(stages, self.parallelism, None, None)
        servers = self._server_instances()
        if not servers:
            raise UnsupportedQueryError("no live servers")
        query_id = f"q{next(self._qid)}_{id(self):x}"

        # deadline propagation: only when the query EXPLICITLY sets
        # timeoutMs (no default MSE budget — long analytical joins own
        # their wall time); the budget clamps the broker-side final
        # receive, every worker's mailbox waits, and the leaf timeoutMs
        deadline = None
        opt = (query.options or {}).get("timeoutMs")
        if opt is not None:
            try:
                deadline = time.monotonic() + float(opt) / 1000.0
            except (TypeError, ValueError):
                deadline = None
        if deadline is not None:
            self.boxes.set_deadline(query_id, deadline)

        # choose workers per stage: leaf stages follow segment placement,
        # intermediate stages round-robin over live servers
        workers: dict[int, list[dict]] = {}
        rr = 0
        for stage in sorted(stages, key=lambda s: -s.stage_id):
            if stage.stage_id == 0:
                continue
            if stage.scans():
                assignment = self._leaf_assignment(stage)
                workers[stage.stage_id] = [
                    {"instance": inst, "addr": self._instance_addr(inst),
                     "tables": assignment[inst]}
                    for inst in sorted(assignment)]
            else:
                n = topo.workers_of(stage)
                placed = self._partition_worker_placement(
                    stage, stages, workers, n)
                chosen = []
                for p in range(n):
                    inst = placed.get(p) if placed else None
                    if inst is None:
                        inst = servers[rr % len(servers)]
                        rr += 1
                    chosen.append({"instance": inst,
                                   "addr": self._instance_addr(inst),
                                   "tables": {}})
                workers[stage.stage_id] = chosen

        # PIPELINED dispatch: every stage's workers are submitted
        # concurrently, children strictly BEFORE parents (the pool queue is
        # FIFO, so child workers always get slots first and a parent can
        # never starve the children it waits on). A parent stage starts
        # executing immediately and blocks inside its mailbox receive/stream
        # while child chunks arrive — stages overlap in wall time, like the
        # reference's streaming gRPC OpChains. Each mse_stage call rides a
        # DEDICATED connection: the shared per-instance client serializes
        # calls under a lock, and a long-blocking parent stage on it would
        # deadlock the dispatch of its own children to the same instance.
        from ..cluster.transport import RpcClient

        # EXPLAIN ANALYZE (or an explicit trace option) arms a dispatcher
        # trace; workers see trace/analyze in their options and ship spans
        # back for the merge in the gather loop. Armed here — after worker
        # placement, which can raise — so the finally below always unwinds
        # the thread-local.
        trace = None
        own_trace = False
        if (analyze or (query.options or {}).get("trace") in
                (True, "true", 1)) and TRACING.active_trace() is None:
            trace = TRACING.start_trace(f"mse:{query_id}", analyze=analyze)
            own_trace = True
        else:
            trace = TRACING.active_trace()
        if trace is not None:
            query.options = dict(query.options or {})
            query.options["trace"] = True
            if getattr(trace, "analyze", False):
                query.options["analyze"] = True

        stats_agg = {"num_docs_scanned": 0, "total_docs": 0,
                     "leaf_ssqe_pushdowns": 0, "stages": len(stages),
                     "num_device_dispatches": 0, "num_compiles": 0,
                     "join_overflow": False, "num_groups_limit_reached": False}
        touched: set[str] = set()

        def submit(stage, w_idx, w, parent_addrs, routing, sj, child_workers):
            touched.add(w["instance"])
            # a stage worker legitimately blocks in its receive while
            # upstream stages still run — the dispatch call must outlive
            # the worker's own mailbox-wait ceiling, and must NOT retry
            # (a re-sent mse_stage would re-run the stage against
            # already-consumed mailboxes)
            wait_s = MAILBOX_WAIT_S
            if deadline is not None:
                wait_s = min(wait_s, max(0.05, deadline - time.monotonic()))
            client = RpcClient(*w["addr"], timeout=wait_s + 30)
            req = {"type": "mse_stage", "query_id": query_id,
                   "stage": sj, "worker": w_idx,
                   "parent_workers": len(parent_addrs),
                   "routing": routing, "tables": w["tables"],
                   "child_workers": child_workers,
                   "parallelism": self.parallelism,
                   "options": dict(query.options)}
            if deadline is not None:
                req["deadline_ms"] = max(
                    50.0, (deadline - time.monotonic()) * 1000.0)
            try:
                return w["instance"], client.call(req, retry=False)
            finally:
                client.close()

        futures = []
        try:
            for stage in sorted(stages, key=lambda s: -s.stage_id):
                if stage.stage_id == 0:
                    continue
                parent_id = stage.parent_stage
                if parent_id == 0:
                    parent_addrs = [self.address]
                else:
                    parent_addrs = [w["addr"] for w in workers[parent_id]]
                routing = {str(p): list(a) for p, a in enumerate(parent_addrs)}
                sj = stage_to_json(stage)
                child_workers = {str(cid): len(workers.get(cid, []))
                                 for cid in stage.child_stages}
                for w_idx, w in enumerate(workers[stage.stage_id]):
                    futures.append(self._pool.submit(
                        submit, stage, w_idx, w, parent_addrs, routing, sj,
                        child_workers))

            stage_stats_agg: dict[int, dict] = {}
            worker_traces: list[tuple[str, list]] = []
            for f in futures:
                inst, st = f.result()
                if st.get("trace"):
                    worker_traces.append((inst, st["trace"]))
                for k in ("num_docs_scanned", "total_docs",
                          "leaf_ssqe_pushdowns", "num_device_dispatches",
                          "num_compiles"):
                    stats_agg[k] += st.get(k, 0)
                stats_agg["join_overflow"] |= bool(st.get("join_overflow"))
                stats_agg["num_groups_limit_reached"] |= bool(
                    st.get("num_groups_limit_reached"))
                for sid, ss in (st.get("stage_stats") or {}).items():
                    agg = stage_stats_agg.setdefault(int(sid), {
                        "workers": 0, "leaf_pushdown": False, "rows_in": 0,
                        "rows_out": 0, "shuffled_rows": 0,
                        "shuffled_bytes": 0, "cross_stage_bytes": 0,
                        "host_crossings": 0, "device_partition_ms": 0.0,
                        "join_impl": "", "wall_ms": 0.0})
                    for k in ("workers", "rows_in", "rows_out",
                              "shuffled_rows", "shuffled_bytes",
                              "cross_stage_bytes", "host_crossings"):
                        agg[k] += ss.get(k, 0)
                    agg["device_partition_ms"] += float(
                        ss.get("device_partition_ms", 0.0))
                    # workers run concurrently: the stage's wall time is
                    # its slowest worker, not the sum
                    agg["wall_ms"] = max(agg["wall_ms"],
                                         float(ss.get("wall_ms", 0.0)))
                    agg["leaf_pushdown"] |= bool(ss.get("leaf_pushdown"))
                    agg["join_impl"] = ss.get("join_impl") or agg["join_impl"]

            final_sid = stages[0].child_stages[0]
            block = concat_blocks(
                self.boxes.wait_all(query_id, final_sid, 0, 0,
                                    len(workers.get(final_sid, []))),
                stages[0].root.schema)
            result = _block_to_result(block, stages[0].root.schema)
            resp = BrokerResponse(
                result_table=result,
                num_docs_scanned=stats_agg["num_docs_scanned"],
                total_docs=stats_agg["total_docs"],
                partial_result=stats_agg["join_overflow"],
                num_groups_limit_reached=stats_agg["num_groups_limit_reached"],
                num_device_dispatches=stats_agg["num_device_dispatches"],
                num_compiles=stats_agg["num_compiles"],
                mse_stage_stats=stage_stats_agg)
            if trace is not None:
                trace_info = trace.to_json()
                # namespace per (instance, dispatch ordinal): one instance
                # can serve several stage workers, and bare instance
                # prefixes would collide their span ids
                ordinal: dict[str, int] = {}
                for inst, spans in worker_traces:
                    n = ordinal.get(inst, 0)
                    ordinal[inst] = n + 1
                    prefix = inst if n == 0 else f"{inst}#{n}"
                    for s in spans:
                        s = dict(s)
                        s["spanId"] = f"{prefix}:{s['spanId']}"
                        if s.get("parentId") is not None:
                            s["parentId"] = f"{prefix}:{s['parentId']}"
                        else:
                            s["server"] = inst
                        trace_info.append(s)
                resp.trace_info = trace_info
                # the annotated-plan render is deferred to execute_sql so
                # the root row carries the real wall time (time_used_ms is
                # only stamped there)
                resp._analyze_pending = analyze
            return resp
        except Exception:
            # a failed worker must not hang its peers in receive/backpressure:
            # stop still-queued dispatches (they'd land on instances the
            # cancel broadcast below doesn't know about yet), cancel the
            # query's mailboxes everywhere, then re-raise
            for f in futures:
                f.cancel()
            self.boxes.cancel(query_id)
            for inst in touched:
                try:
                    self.broker._client(inst).call(
                        {"type": "mse_cancel", "query_id": query_id})
                except Exception:
                    pass
            for f in futures:
                try:
                    f.result()
                # CancelledError is a BaseException since 3.8
                except BaseException:
                    pass
            raise
        finally:
            if own_trace:
                TRACING.end_trace()
            self.boxes.cleanup(query_id)
            for inst in touched:
                try:
                    self.broker._client(inst).call(
                        {"type": "mse_cleanup", "query_id": query_id})
                except Exception:
                    pass
