"""MSE facade: SQL → stage DAG → BrokerResponse.

Reference analogue: MultiStageBrokerRequestHandler + QueryDispatcher
(pinot-query-runtime/.../service/dispatch/QueryDispatcher.java:126 —
submitAndReduce) collapsed into one in-process entry point, the same
topology the reference uses in its own in-process MSE tests
(QueryRunnerTestBase).
"""

from __future__ import annotations

import re
import time
from typing import Optional

import numpy as np

from ..cache.keys import mse_plan_fingerprint, segment_token
from ..cache.results import BrokerResultCache, result_cache_enabled
from ..engine.results import BrokerResponse, DataSchema, ResultTable
from ..spi.trace import TRACING
from .fragmenter import explain_stages, fragment
from .logical import LogicalPlanner, prune_columns
from .optimizer import push_filters
from .mailbox import Block, block_len
from .parser import parse_relational
from .runtime import StageRunner


class MultistageExecutor:
    """Runs the multi-stage dialect over a single-stage QueryExecutor's
    table registry (engine/query_executor.py)."""

    def __init__(self, query_executor, parallelism: int = 2):
        self.qe = query_executor
        self.parallelism = parallelism
        # stage-plan result cache (the MSE analogue of the broker tier):
        # keyed by (plan fingerprint, every scanned segment's (name, crc)),
        # so segment replacement/refresh self-invalidates through the crc
        # with no epoch plumbing. The executor instance is persistent
        # (engine/query_executor.py caches it), so warm repeats of a join
        # query skip the runner entirely.
        self.result_cache = BrokerResultCache()

    def _cache_key(self, stages, options) -> Optional[tuple]:
        """None = uncacheable (unfingerprintable plan, missing table,
        mutable or crc-less segment). Computed AFTER the resultCache
        option gate so opted-out queries never pay a fingerprint."""
        fp = mse_plan_fingerprint(stages, options, self.parallelism)
        if fp is None:
            return None
        toks = []
        for st in stages:
            if st.root is None:
                continue
            for scan in st.scans():
                t = self.qe.tables.get(scan.table)
                if t is None:
                    return None
                for seg in list(t.segments):
                    tok = segment_token(seg)
                    if tok is None:
                        return None
                    toks.append((scan.table,) + tok)
        return (fp, tuple(sorted(toks)))

    # -- catalog -----------------------------------------------------------
    def _catalog(self) -> dict[str, list[str]]:
        return {name: t.schema.column_names()
                for name, t in self.qe.tables.items()}

    def _partition_catalog(self) -> dict[str, dict]:
        """table → {column: (pfunc, n_partitions)} where EVERY segment is
        stamped with the same function/count (reference: the broker's
        TablePartitionInfo is computed the same way — from per-segment
        ColumnPartitionMetadata, invalidated on any inconsistent segment)."""
        out: dict[str, dict] = {}
        for name, t in self.qe.tables.items():
            segs = list(t.segments)
            if not segs:
                continue
            per_col: dict[str, tuple] = {}
            for col in t.schema.column_names():
                infos = set()
                for seg in segs:
                    meta = getattr(seg, "metadata", None)
                    m = meta.columns.get(col) if meta is not None else None
                    if m is None or not getattr(m, "partition_function", None) \
                            or not getattr(m, "num_partitions", None):
                        infos = None
                        break
                    infos.add((m.partition_function, m.num_partitions))
                if infos and len(infos) == 1:
                    per_col[col] = next(iter(infos))
            if per_col:
                out[name] = per_col
        return out

    def _read_table(self, table: str, columns: list[str]) -> dict[str, np.ndarray]:
        t = self.qe.tables.get(table)
        if t is None:
            raise KeyError(f"table {table} not found")
        out: dict[str, list] = {c: [] for c in columns}
        for seg in list(t.segments):
            view = seg.snapshot_view() if getattr(seg, "is_mutable", False) else seg
            vd = getattr(view, "valid_doc_ids", None)
            keep = vd.mask(view.num_docs) if vd is not None else None
            for c in columns:
                vals = np.asarray(view.get_values(c))
                out[c].append(vals if keep is None else vals[keep])
        result = {}
        for c, parts in out.items():
            if not parts:
                result[c] = np.empty(0)
            elif len(parts) == 1:
                result[c] = parts[0]
            else:
                if any(p.dtype.kind == "O" for p in parts):
                    parts = [p.astype(object) for p in parts]
                result[c] = np.concatenate(parts)
        return result

    # -- entry -------------------------------------------------------------
    def execute_sql(self, sql: str) -> BrokerResponse:
        t0 = time.perf_counter()
        trace = None
        try:
            query = parse_relational(sql)
            # the MSE entry owns the span tree: stage spans (runtime.py)
            # and nested leaf-engine dispatch spans all join this trace.
            # EXPLAIN ANALYZE arms it unconditionally (analyze-flagged so
            # cache layers stay live) — the annotated plan IS the trace.
            analyze = query.explain == "analyze"
            if (analyze or query.options.get("trace") in (True, "true", 1)) \
                    and TRACING.active_trace() is None:
                trace = TRACING.start_trace(f"mse:{id(query):x}",
                                            analyze=analyze)
            planner = LogicalPlanner(query, self._catalog(),
                                     partition_catalog=self._partition_catalog)
            plan = planner.plan()
            plan = push_filters(plan)
            prune_columns(plan)
            stages = fragment(plan)
            if query.explain is True:
                text = explain_stages(stages)
                return BrokerResponse(
                    result_table=ResultTable(
                        DataSchema(["plan"], ["STRING"]),
                        [[line] for line in text.split("\n")]),
                    time_used_ms=(time.perf_counter() - t0) * 1000)
            cache_key = None
            if query.explain is False and trace is None \
                    and result_cache_enabled() \
                    and not _option_false(query.options, "resultCache"):
                cache_key = self._cache_key(stages, query.options)
            if cache_key is not None:
                cached = self.result_cache.get(cache_key)
                if cached is not None:
                    # bit-identical rows, zero dispatches: restamp only the
                    # per-request fields on the shallow copy
                    cached.cache_outcome = "hit"
                    cached.num_device_dispatches = 0
                    cached.num_compiles = 0
                    cached.time_used_ms = (time.perf_counter() - t0) * 1000
                    return cached
            from .operators import pop_join_overflow

            pop_join_overflow()  # clear any stale flag on this thread
            runner = StageRunner(
                stages, self.parallelism, self.qe.execute, self._read_table,
                query_options=query.options,
                execute_columnar=getattr(self.qe, "execute_selection_columnar",
                                         None))
            block = runner.run()
            if query.explain == "implementation":
                # the query RAN; the plan text carries each stage's
                # measured rows/bytes/time
                text = explain_stages(stages, runner.stage_stats)
                return BrokerResponse(
                    result_table=ResultTable(
                        DataSchema(["plan"], ["STRING"]),
                        [[line] for line in text.split("\n")]),
                    time_used_ms=(time.perf_counter() - t0) * 1000)
            schema = stages[0].root.schema
            result = _block_to_result(block, schema)
            resp = BrokerResponse(
                result_table=result,
                num_docs_scanned=runner.stats["num_docs_scanned"],
                total_docs=runner.stats["total_docs"],
                partial_result=pop_join_overflow()
                or bool(runner.stats.get("join_overflow")),
                num_groups_limit_reached=runner.stats.get(
                    "num_groups_limit_reached", False),
                num_device_dispatches=runner.stats.get(
                    "num_device_dispatches", 0),
                num_compiles=runner.stats.get("num_compiles", 0),
                mse_stage_stats=runner.stage_stats,
                time_used_ms=(time.perf_counter() - t0) * 1000)
            if cache_key is not None:
                resp.cache_outcome = "miss"
                if not resp.partial_result:
                    self.result_cache.put(cache_key, resp)
            if trace is not None:
                resp.trace_info = trace.to_json()
            if analyze:
                from ..engine.explain import analyze_table

                resp.result_table = analyze_table(
                    resp.trace_info or [], resp)
            return resp
        except Exception as e:
            return BrokerResponse(
                exceptions=[f"{type(e).__name__}: {e}"],
                time_used_ms=(time.perf_counter() - t0) * 1000)
        finally:
            if trace is not None:
                TRACING.end_trace()


def _option_false(options: dict, name: str) -> bool:
    for k, v in (options or {}).items():
        if str(k).lower() == name.lower():
            return v is False or str(v).lower() in ("0", "false", "off")
    return False


def _block_to_result(block: Block, schema: list[str]) -> ResultTable:
    n = block_len(block)
    cols = []
    types = []
    for name in schema:
        v = np.asarray(block.get(name, np.empty(0)))
        cols.append(v)
        types.append(_np_type(v))
    rows = []
    for i in range(n):
        rows.append([_py(c[i]) for c in cols])
    return ResultTable(DataSchema([_display(s) for s in schema], types), rows)


def _np_type(v: np.ndarray) -> str:
    k = v.dtype.kind
    if k == "b":
        return "BOOLEAN"
    if k in "iu":
        return "LONG"
    if k == "f":
        return "DOUBLE"
    return "STRING"


def _py(v):
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and np.isnan(v):
        return None
    return v


_QUALIFIED_RE = re.compile(r"[A-Za-z_][\w$]*(?:\.[A-Za-z_][\w$]*)+")


def _display(name: str) -> str:
    """Qualified plain identifiers render unqualified in the response header
    (reference: MSE result headers use the field name, not `table.field`);
    expression strings pass through untouched."""
    if _QUALIFIED_RE.fullmatch(name):
        return name.rsplit(".", 1)[-1]
    return name
