"""Plan fragmenter: logical plan with exchanges → stage DAG.

Reference analogue: PlanFragmenter + MailboxAssignmentVisitor
(pinot-query-planner/.../planner/PlanFragmenter.java, physical/
MailboxAssignmentVisitor.java). Every ExchangeNode becomes a stage
boundary: the subtree below it runs as its own stage whose output is sent
through the mailbox service with the exchange's distribution; the parent
stage reads it through a MailboxReceiveNode leaf. Stage 0 is the broker
rendezvous (reference: the final MailboxReceive at the broker in
QueryDispatcher.submitAndReduce).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .logical import ExchangeNode, PlanNode, TableScanNode


@dataclass
class MailboxReceiveNode(PlanNode):
    from_stage: int = -1
    dist: str = "singleton"
    keys: list[str] = field(default_factory=list)
    pfunc: Optional[str] = None       # partitioned dist only
    n_partitions: Optional[int] = None

    def describe(self) -> str:
        return f"MailboxReceive(fromStage={self.from_stage}, dist={self.dist}, keys={self.keys})"


@dataclass
class Stage:
    stage_id: int
    root: PlanNode  # subtree with MailboxReceiveNode leaves
    send_dist: str  # distribution of this stage's output
    send_keys: list[str]
    parent_stage: Optional[int]  # None for stage 0
    # stages whose output this stage consumes, in receive order
    child_stages: list[int] = field(default_factory=list)
    # partitioned send only; the fan-out COUNT comes from the receive side
    # (MailboxReceiveNode.n_partitions → parent worker count)
    send_pfunc: Optional[str] = None
    # the exchange's (pruned) schema: the stage's output block is trimmed
    # to exactly these columns before it enters the mailbox. None (old
    # serialized plans) means "ship whatever the root produced".
    send_schema: Optional[list[str]] = None

    @property
    def is_leaf(self) -> bool:
        return not self.child_stages

    def scans(self) -> list[TableScanNode]:
        out: list[TableScanNode] = []

        def walk(n: PlanNode):
            if isinstance(n, TableScanNode):
                out.append(n)
            for i in n.inputs:
                walk(i)

        walk(self.root)
        return out


def receive_nodes(node: PlanNode) -> list[MailboxReceiveNode]:
    """All MailboxReceiveNode leaves under a stage root (shared by the
    runtime's worker-count topology and the dispatcher's placement)."""
    out: list[MailboxReceiveNode] = []
    if isinstance(node, MailboxReceiveNode):
        out.append(node)
    for i in node.inputs:
        out.extend(receive_nodes(i))
    return out


def fragment(root: ExchangeNode) -> list[Stage]:
    """Split at exchanges. Returns stages indexed by stage_id; stage 0 is
    the broker stage (a bare receive of the root exchange)."""
    if not isinstance(root, ExchangeNode):
        raise TypeError("plan root must be an ExchangeNode")
    stages: list[Stage] = []

    broker = Stage(0, None, send_dist="", send_keys=[], parent_stage=None)
    stages.append(broker)

    def make_stage(exchange: ExchangeNode, parent_id: int) -> int:
        sid = len(stages)
        stage = Stage(sid, None, send_dist=exchange.dist,
                      send_keys=list(exchange.keys), parent_stage=parent_id,
                      send_pfunc=exchange.pfunc,
                      send_schema=list(exchange.schema))
        stages.append(stage)
        stage.root = rewrite(exchange.inputs[0], sid)
        return sid

    def rewrite(node: PlanNode, owner_stage: int) -> PlanNode:
        if isinstance(node, ExchangeNode):
            child_id = make_stage(node, owner_stage)
            stages[owner_stage].child_stages.append(child_id)
            return MailboxReceiveNode([], list(node.schema), from_stage=child_id,
                                      dist=node.dist, keys=list(node.keys),
                                      pfunc=node.pfunc,
                                      n_partitions=node.n_partitions)
        node.inputs = [rewrite(i, owner_stage) for i in node.inputs]
        return node

    root_child = make_stage(root, 0)
    broker.child_stages.append(root_child)
    broker.root = MailboxReceiveNode([], list(root.schema), from_stage=root_child,
                                     dist=root.dist, keys=list(root.keys))
    return stages


def explain_stages(stages: list[Stage],
                   stage_stats: Optional[dict] = None) -> str:
    lines = []
    for s in stages:
        head = f"[Stage {s.stage_id}]"
        if s.parent_stage is not None:
            head += f" → stage {s.parent_stage} ({s.send_dist}" + (
                f" on {s.send_keys})" if s.send_keys else ")")
        lines.append(head)
        st = (stage_stats or {}).get(s.stage_id)
        if st is not None:
            line = ("  [impl] workers={workers} leaf_pushdown={leaf_pushdown} "
                    "rows_in={rows_in} rows_out={rows_out} "
                    "shuffled_rows={shuffled_rows} "
                    "shuffled_bytes={shuffled_bytes} "
                    "wall_ms={wall_ms:.1f}".format(**st))
            if st.get("join_impl"):
                line += (" join={join_impl} "
                         "cross_stage_bytes={cross_stage_bytes} "
                         "device_partition_ms={device_partition_ms:.1f}"
                         .format(**st))
                if st.get("host_crossings"):
                    line += " hostCrossings={host_crossings}".format(**st)
            elif "cross_stage_bytes" in st:
                line += " cross_stage_bytes={cross_stage_bytes}".format(**st)
            lines.append(line)
        lines.extend("  " + ln for ln in s.root.tree_lines())
    return "\n".join(lines)
