"""Logical planner: relational AST → PlanNode tree with exchanges.

Reference analogue: pinot-query-planner's RelNode optimization +
RelToPlanNodeConverter (.../planner/logical/RelToPlanNodeConverter.java) and
the plan-node zoo (.../planner/plannode/: Join/Window/Aggregate/Sort/
SetOp/MailboxSend/MailboxReceive). Differences by design:

- Columns are carried by *qualified name* (``alias.col``), not ordinal — the
  runtime is columnar dicts, so names are the natural join currency.
- Exchange placement mirrors the reference's distribution traits: hash on
  join keys / group keys / window partition keys, singleton at the root and
  for set ops (PinotLogicalQueryPlanner + MailboxAssignmentVisitor).
- IN/NOT IN subqueries rewrite to SEMI/ANTI joins (Calcite
  SubQueryRemoveRule analogue) here in the planner.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..engine.aggregation import UnsupportedQueryError, get_semantics
from ..query.expressions import ExpressionContext
from ..query.parser.sql import SqlParseError
from .ast import (
    JoinRel,
    OrderItem,
    RelationalQuery,
    Relation,
    SelectItem,
    SelectStmt,
    SetOpStmt,
    Stmt,
    SubqueryRef,
    TableRef,
    WindowSpec,
)

EC = ExpressionContext


class PlanError(SqlParseError):
    pass


# -- plan nodes --------------------------------------------------------------


@dataclass
class PlanNode:
    inputs: list["PlanNode"]
    schema: list[str]

    def tree_lines(self, indent: int = 0) -> list[str]:
        out = ["  " * indent + self.describe()]
        for i in self.inputs:
            out.extend(i.tree_lines(indent + 1))
        return out

    def describe(self) -> str:
        return type(self).__name__


@dataclass
class TableScanNode(PlanNode):
    table: str = ""
    alias: str = ""
    source_columns: list[str] = field(default_factory=list)  # parallel to schema

    def describe(self) -> str:
        return f"TableScan(table={self.table}, columns={self.source_columns})"


@dataclass
class FilterNode(PlanNode):
    condition: Optional[EC] = None

    def describe(self) -> str:
        return f"Filter(condition={self.condition})"


@dataclass
class ProjectNode(PlanNode):
    exprs: list[EC] = field(default_factory=list)

    def describe(self) -> str:
        return f"Project({', '.join(f'{n}={e}' for n, e in zip(self.schema, self.exprs))})"


@dataclass
class AggCall:
    name: str  # canonical aggregation function name
    args: list[EC]
    out_name: str
    extra: tuple = ()
    # AGG(x) FILTER (WHERE cond): rows failing cond contribute the identity
    # (reference: FilteredAggregationFunction). Evaluated over the
    # aggregate's INPUT rows — on the partial (pre-shuffle) phase when the
    # call decomposes, so leaf pushdowns compile it into the device plan.
    condition: Optional[EC] = None


@dataclass
class AggregateNode(PlanNode):
    group_exprs: list[EC] = field(default_factory=list)  # schema[:len(group_exprs)]
    agg_calls: list[AggCall] = field(default_factory=list)

    def describe(self) -> str:
        return (f"Aggregate(groups=[{', '.join(map(str, self.group_exprs))}], "
                f"aggs=[{', '.join(a.name + '(' + ','.join(map(str, a.args)) + ')' for a in self.agg_calls)}])")


@dataclass
class JoinNode(PlanNode):
    join_type: str = "INNER"  # INNER/LEFT/RIGHT/FULL/CROSS/SEMI/ANTI
    left_keys: list[str] = field(default_factory=list)
    right_keys: list[str] = field(default_factory=list)
    residual: Optional[EC] = None  # evaluated over combined schema

    def describe(self) -> str:
        return (f"Join(type={self.join_type}, left={self.left_keys}, "
                f"right={self.right_keys}, residual={self.residual})")


@dataclass
class WindowCall:
    name: str  # rownumber/rank/denserank/ntile/lag/lead/firstvalue/lastvalue or agg
    args: list[EC]
    spec: WindowSpec = None
    out_name: str = ""


@dataclass
class WindowNode(PlanNode):
    calls: list[WindowCall] = field(default_factory=list)
    partition_keys: list[EC] = field(default_factory=list)

    def describe(self) -> str:
        return f"Window(calls=[{', '.join(c.name for c in self.calls)}])"


@dataclass
class SortNode(PlanNode):
    sort_items: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0

    def describe(self) -> str:
        keys = ", ".join(f"{it.expression}{'' if it.ascending else ' DESC'}"
                         for it in self.sort_items)
        return f"Sort(keys=[{keys}], limit={self.limit}, offset={self.offset})"


@dataclass
class SetOpNode(PlanNode):
    kind: str = "UNION"
    all: bool = False

    def describe(self) -> str:
        return f"SetOp({self.kind}{' ALL' if self.all else ''})"


@dataclass
class ExchangeNode(PlanNode):
    """Distribution boundary → becomes MailboxSend/Receive at fragmenting
    (reference: PinotLogicalExchange → MailboxSendNode/MailboxReceiveNode).

    dist="partitioned" is the colocated-join exchange: both join sides are
    table-partitioned on a join key by the SAME function/count, so rows
    route by that table partition function instead of a generic row hash —
    worker p of the join joins table partition p from each side, and a
    distributed leaf serves partition p from its stamped segments without
    re-hashing (reference: partition-aware exchange elision behind the
    is_colocated_by_join_keys hint, PinotJoinToDynamicBroadcastRule's
    sibling rule in pinot-query-planner/.../rules/)."""

    dist: str = "singleton"  # hash | singleton | broadcast | partitioned
    keys: list[str] = field(default_factory=list)
    pfunc: Optional[str] = None       # partitioned: table partition function
    n_partitions: Optional[int] = None

    def describe(self) -> str:
        if self.dist == "partitioned":
            return (f"Exchange(dist=partitioned, keys={self.keys}, "
                    f"fn={self.pfunc}, n={self.n_partitions})")
        return f"Exchange(dist={self.dist}, keys={self.keys})"


# -- aggregation detection ---------------------------------------------------


def is_agg_function(name: str) -> bool:
    try:
        get_semantics(name)
        return True
    except (UnsupportedQueryError, KeyError):
        return False


_WINDOW_ONLY = {"rownumber", "rank", "denserank", "ntile", "lag", "lead",
                "firstvalue", "lastvalue", "cumedist", "percentrank"}

# aggregations splittable into partial (producer stage) + final merge
_DECOMPOSE = {"count", "sum", "min", "max", "avg", "minmaxrange"}


# -- planner -----------------------------------------------------------------


class LogicalPlanner:
    """Builds a PlanNode tree; identifiers are rewritten to exact input
    column names during planning so the runtime never resolves names.

    ``catalog`` maps table name → list of physical column names (the
    reference binds against ZK table schemas in Calcite's validator)."""

    def __init__(self, query: RelationalQuery, catalog: dict[str, list[str]],
                 partition_catalog=None):
        self.query = query
        self.catalog = catalog
        # table → {column: (partition function name, num_partitions)} — or a
        # zero-arg callable producing it, resolved only when a join asks
        # (metadata sweeps shouldn't tax joinless queries); drives
        # colocated joins
        self._partition_catalog = partition_catalog
        self._counter = 0

    def plan(self) -> PlanNode:
        root = self.plan_stmt(self.query.statement)
        return ExchangeNode([root], root.schema, dist="singleton")

    # -- statements --------------------------------------------------------
    def plan_stmt(self, stmt: Stmt) -> PlanNode:
        if isinstance(stmt, SetOpStmt):
            return self._plan_setop(stmt)
        return self._plan_select(stmt)

    def _plan_setop(self, stmt: SetOpStmt) -> PlanNode:
        left = self.plan_stmt(stmt.left)
        right = self.plan_stmt(stmt.right)
        if len(left.schema) != len(right.schema):
            raise PlanError(f"{stmt.kind} inputs have different column counts")
        # align right's names to left's (positional, like SQL set ops)
        if right.schema != left.schema:
            right = ProjectNode(
                [right], list(left.schema),
                exprs=[EC.for_identifier(c) for c in right.schema])
        node = SetOpNode(
            [ExchangeNode([left], left.schema, dist="singleton"),
             ExchangeNode([right], right.schema, dist="singleton")],
            list(left.schema), kind=stmt.kind, all=stmt.all)
        if stmt.order_by or stmt.limit is not None:
            node = SortNode([node], node.schema,
                            sort_items=self._resolve_order(stmt.order_by, node.schema),
                            limit=stmt.limit, offset=stmt.offset)
        return node

    # -- SELECT ------------------------------------------------------------
    def _plan_select(self, stmt: SelectStmt) -> PlanNode:
        node = self.plan_relation(stmt.from_rel)

        # WHERE (with IN-subquery → SEMI/ANTI join rewrite)
        if stmt.where is not None:
            node, remaining = self._rewrite_subqueries(node, stmt.where)
            if remaining is not None:
                _reject_nested_subqueries(remaining)
                node = FilterNode([node], node.schema,
                                  condition=self._resolve(remaining, node.schema))
        if stmt.having is not None:
            _reject_nested_subqueries(stmt.having)

        has_windows = any(it.window is not None for it in stmt.select_items)
        agg_in_select = any(
            self._contains_agg(it.expression) for it in stmt.select_items
            if it.window is None)
        grouped = bool(stmt.group_by) or agg_in_select or (
            stmt.having is not None and self._contains_agg(stmt.having))

        if grouped and has_windows:
            raise PlanError("window functions over grouped queries are not supported")

        if grouped:
            node, out_names, out_exprs = self._plan_aggregate(stmt, node)
        elif has_windows:
            node, out_names, out_exprs = self._plan_window(stmt, node)
        else:
            out_names, out_exprs = self._select_outputs(stmt.select_items, node.schema)

        # final projection
        proj = ProjectNode([node], out_names, exprs=out_exprs)

        if stmt.distinct:
            proj = AggregateNode(
                [ExchangeNode([proj], proj.schema, dist="hash", keys=list(proj.schema))],
                list(proj.schema),
                group_exprs=[EC.for_identifier(c) for c in proj.schema], agg_calls=[])

        if stmt.order_by or stmt.limit is not None:
            proj = self._plan_sort(proj, node, stmt)
        return proj

    def _plan_sort(self, proj: PlanNode, pre_proj: PlanNode,
                   stmt: SelectStmt) -> PlanNode:
        """Sort above the projection. ORDER BY keys not present in the
        projection become hidden `$sort{i}` columns (computed from the
        pre-projection input), dropped by a final trim projection —
        Calcite's Sort-with-hidden-fields pattern."""
        sort_items: list[OrderItem] = []
        hidden: list[tuple[str, EC]] = []
        for it in stmt.order_by:
            try:
                e = self._resolve(it.expression, proj.schema)
            except PlanError:
                resolved = self._resolve(it.expression, pre_proj.schema)
                hname = f"$sort{len(hidden)}"
                hidden.append((hname, resolved))
                e = EC.for_identifier(hname)
            sort_items.append(OrderItem(e, it.ascending, it.nulls_last))
        if hidden:
            if not isinstance(proj, ProjectNode) or proj.inputs[0] is not pre_proj:
                raise PlanError(
                    "ORDER BY expression must appear in the SELECT list here")
            visible = list(proj.schema)
            proj = ProjectNode([pre_proj], visible + [h for h, _ in hidden],
                               exprs=list(proj.exprs) + [e for _, e in hidden])
            sort = SortNode([self._gather(proj, sort_items, stmt)],
                            proj.schema, sort_items=sort_items,
                            limit=stmt.limit, offset=stmt.offset)
            return ProjectNode([sort], visible,
                               exprs=[EC.for_identifier(c) for c in visible])
        return SortNode([self._gather(proj, sort_items, stmt)], proj.schema,
                        sort_items=sort_items,
                        limit=stmt.limit, offset=stmt.offset)

    @staticmethod
    def _gather(node: PlanNode, sort_items: list, stmt) -> PlanNode:
        """Singleton exchange under a global Sort: its input may be
        hash-partitioned (e.g. a parallel aggregate), and a per-partition
        sort+LIMIT would emit workers×LIMIT rows in partition order
        (reference: Calcite plans a SortExchange gathering to one worker
        before the final Sort). With a LIMIT, each partition pre-sorts and
        keeps only its top offset+limit rows first, bounding the shuffle to
        workers×(offset+limit) instead of the full result."""
        if stmt.limit is not None and sort_items:
            node = SortNode([node], list(node.schema),
                            sort_items=list(sort_items),
                            limit=stmt.limit + (stmt.offset or 0), offset=0)
        return ExchangeNode([node], list(node.schema), dist="singleton")

    # -- relations ---------------------------------------------------------
    def plan_relation(self, rel: Relation) -> PlanNode:
        if isinstance(rel, TableRef):
            alias = rel.alias or rel.name
            cols = self.catalog.get(rel.name)
            if cols is None:
                raise PlanError(f"unknown table {rel.name!r}")
            return TableScanNode(
                [], [f"{alias}.{c}" for c in cols],
                table=rel.name, alias=alias, source_columns=list(cols))
        if isinstance(rel, SubqueryRef):
            sub = self.plan_stmt(rel.query)
            qualified = [f"{rel.alias}.{_short(c)}" for c in sub.schema]
            return ProjectNode([sub], qualified,
                               exprs=[EC.for_identifier(c) for c in sub.schema])
        if isinstance(rel, JoinRel):
            return self._plan_join(rel)
        raise PlanError(f"unsupported relation {rel!r}")

    def _plan_join(self, rel: JoinRel) -> PlanNode:
        left = self.plan_relation(rel.left)
        right = self.plan_relation(rel.right)
        return self._make_join(left, right, rel.join_type, rel.condition)

    def _make_join(self, left: PlanNode, right: PlanNode, join_type: str,
                   condition: Optional[EC]) -> PlanNode:
        lkeys: list[str] = []
        rkeys: list[str] = []
        residual_parts: list[EC] = []
        combined = list(left.schema) + [c for c in right.schema]
        if condition is not None:
            for conj in _split_and(condition):
                pair = self._equi_pair(conj, left.schema, right.schema)
                if pair:
                    lkeys.append(pair[0])
                    rkeys.append(pair[1])
                else:
                    residual_parts.append(self._resolve(conj, combined))
        residual = None
        for p in residual_parts:
            residual = p if residual is None else EC.for_function("and", residual, p)
        if join_type in ("SEMI", "ANTI"):
            schema = list(left.schema)
        else:
            schema = combined
        if lkeys:
            colo = self._colocation(left, right, lkeys, rkeys)
            if colo:
                lk, rk, fn, nparts = colo
                lx = ExchangeNode([left], left.schema, dist="partitioned",
                                  keys=[lk], pfunc=fn, n_partitions=nparts)
                rx = ExchangeNode([right], right.schema, dist="partitioned",
                                  keys=[rk], pfunc=fn, n_partitions=nparts)
            else:
                lx = ExchangeNode([left], left.schema, dist="hash", keys=lkeys)
                rx = ExchangeNode([right], right.schema, dist="hash", keys=rkeys)
        else:
            # non-equi / cross join: broadcast the right side
            lx = ExchangeNode([left], left.schema, dist="singleton")
            rx = ExchangeNode([right], right.schema, dist="broadcast")
        return JoinNode([lx, rx], schema, join_type=join_type,
                        left_keys=lkeys, right_keys=rkeys, residual=residual)

    # -- colocated join detection ------------------------------------------
    def _colocation(self, left: PlanNode, right: PlanNode,
                    lkeys: list[str], rkeys: list[str]):
        """If some equi-key pair is the partition column of BOTH sides'
        tables with the same function + count, route by that partition
        function: rows equal on ALL join keys are equal on the partition
        key, so matching rows meet in the same partition-indexed worker."""
        if self._partition_catalog is None:
            return None
        if callable(self._partition_catalog):
            self._partition_catalog = self._partition_catalog() or {}
        linfo = self._partition_info(left)
        rinfo = self._partition_info(right)
        for lk, rk in zip(lkeys, rkeys):
            li, ri = linfo.get(lk), rinfo.get(rk)
            if li is not None and li == ri:
                return lk, rk, li[0], li[1]
        return None

    def _partition_info(self, node: PlanNode) -> dict[str, tuple]:
        """qualified column name → (pfunc, n_partitions) for columns whose
        table partitioning SURVIVES to this node's output: propagates
        through Filter (row subset) and identifier Projects (rename); any
        other node breaks the guarantee."""
        if isinstance(node, TableScanNode):
            per_col = self._partition_catalog.get(node.table) or {}
            return {q: per_col[s] for q, s in
                    zip(node.schema, node.source_columns) if s in per_col}
        if isinstance(node, FilterNode):
            return self._partition_info(node.inputs[0])
        if isinstance(node, ProjectNode):
            inner = self._partition_info(node.inputs[0])
            out = {}
            for q, e in zip(node.schema, node.exprs):
                if e.is_identifier and e.identifier in inner:
                    out[q] = inner[e.identifier]
            return out
        return {}

    def _equi_pair(self, conj: EC, lschema: list[str], rschema: list[str]):
        """a.x = b.y with sides living in different inputs → (lcol, rcol)."""
        if not (conj.is_function and conj.function.name == "equals"):
            return None
        a, b = conj.function.arguments
        if not (a.is_identifier and b.is_identifier):
            return None
        try:
            ra = _resolve_name(a.identifier, lschema)
        except PlanError:
            ra = None
        try:
            rb = _resolve_name(b.identifier, rschema)
        except PlanError:
            rb = None
        if ra and rb:
            return ra, rb
        try:
            ra2 = _resolve_name(b.identifier, lschema)
            rb2 = _resolve_name(a.identifier, rschema)
            return ra2, rb2
        except PlanError:
            return None

    # -- IN-subquery rewrite ------------------------------------------------
    def _rewrite_subqueries(self, node: PlanNode, where: EC):
        """Pull top-level [NOT] IN (SELECT …) conjuncts out of WHERE and turn
        them into SEMI/ANTI joins; returns (new_node, remaining_filter)."""
        conjs = _split_and(where)
        remaining: list[EC] = []
        for conj in conjs:
            if conj.is_function and conj.function.name in (
                    "__insubquery__", "__notinsubquery__"):
                left_expr, sub_lit = conj.function.arguments
                sub_plan = self.plan_stmt(sub_lit.literal)
                if len(sub_plan.schema) != 1:
                    raise PlanError("IN subquery must select exactly one column")
                jt = "SEMI" if conj.function.name == "__insubquery__" else "ANTI"
                cond = EC.for_function(
                    "equals", left_expr, EC.for_identifier(sub_plan.schema[0]))
                # distinct-ify the subquery side so SEMI join is a set test
                sub_plan = AggregateNode(
                    [ExchangeNode([sub_plan], sub_plan.schema, dist="hash",
                                  keys=list(sub_plan.schema))],
                    list(sub_plan.schema),
                    group_exprs=[EC.for_identifier(sub_plan.schema[0])], agg_calls=[])
                node = self._make_join(node, sub_plan, jt, cond)
            else:
                remaining.append(conj)
        rem = None
        for p in remaining:
            rem = p if rem is None else EC.for_function("and", rem, p)
        return node, rem

    # -- aggregation --------------------------------------------------------
    def _plan_aggregate(self, stmt: SelectStmt, node: PlanNode):
        group_exprs = [self._resolve(g, node.schema) for g in stmt.group_by]
        group_names = [_expr_name(g, raw) for g, raw in zip(group_exprs, stmt.group_by)]
        agg_calls: list[AggCall] = []

        def add_agg(e: EC, cond: Optional[EC]) -> EC:
            args = [self._resolve(a, node.schema)
                    for a in e.function.arguments
                    if not (a.is_identifier and a.identifier == "*")]
            # literal trailing args (percentile level etc.) stay as extras
            value_args = [a for a in args if not a.is_literal]
            extra = tuple(a.literal for a in args if a.is_literal)
            sig = (e.function.name, tuple(map(str, value_args)),
                   tuple(map(repr, extra)), str(cond))
            for c in agg_calls:
                if (c.name, tuple(map(str, c.args)), tuple(map(repr, c.extra)),
                        str(c.condition)) == sig:
                    return EC.for_identifier(c.out_name)
            out = f"{e.function.name}({','.join(str(a) for a in e.function.arguments)})"
            if cond is not None:
                out += f" FILTER({cond})"
            agg_calls.append(AggCall(e.function.name, value_args, out, extra,
                                     condition=cond))
            return EC.for_identifier(out)

        def extract(e: EC, raw_alias: Optional[str] = None) -> EC:
            """Replace group exprs / agg calls in a post-agg expression with
            identifiers over the Aggregate's output schema."""
            resolved_candidates = [self._try_resolve(e, node.schema)]
            for ge, gn in zip(group_exprs, group_names):
                if resolved_candidates[0] is not None and resolved_candidates[0] == ge:
                    return EC.for_identifier(gn)
            if e.is_function and e.function.name == "filter":
                inner, cond_raw = e.function.arguments
                if not (inner.is_function and is_agg_function(inner.function.name)):
                    raise PlanError(
                        "FILTER (WHERE ...) must be attached to an aggregate")
                return add_agg(inner, self._resolve(cond_raw, node.schema))
            if e.is_function and is_agg_function(e.function.name):
                return add_agg(e, None)
            if e.is_function:
                return EC.for_function(
                    e.function.name, *[extract(a) for a in e.function.arguments])
            if e.is_identifier:
                resolved = self._resolve(e, node.schema)
                for ge, gn in zip(group_exprs, group_names):
                    if resolved == ge:
                        return EC.for_identifier(gn)
                raise PlanError(
                    f"column {e.identifier!r} must appear in GROUP BY or an aggregate")
            return e

        out_names: list[str] = []
        out_exprs: list[EC] = []
        for it in stmt.select_items:
            if it.expression.is_identifier and it.expression.identifier == "*":
                raise PlanError("SELECT * with GROUP BY is not supported")
            post = extract(it.expression)
            out_exprs.append(post)
            out_names.append(it.alias or str(it.expression))

        having_post = extract(stmt.having) if stmt.having is not None else None

        # ORDER BY may reference aggregates (even ones absent from SELECT) —
        # extract them BEFORE the phase build so their agg calls materialize
        if stmt.order_by:
            new_order = []
            for item in stmt.order_by:
                try:
                    resolved = extract(item.expression)
                except PlanError:
                    resolved = item.expression  # alias reference, resolved later
                new_order.append(OrderItem(resolved, item.ascending, item.nulls_last))
            stmt.order_by = new_order

        out = self._build_agg_phases(node, group_exprs, group_names, agg_calls)
        if having_post is not None:
            out = FilterNode([out], out.schema, condition=having_post)
        return out, out_names, out_exprs

    def _build_agg_phases(self, node: PlanNode, group_exprs: list[EC],
                          group_names: list[str], agg_calls: list[AggCall]) -> PlanNode:
        """Two-phase aggregation when every call is decomposable: a PARTIAL
        aggregate below the exchange (runs in the producer stage, where the
        leaf compiler can hand it to the single-stage TPU engine) and a FINAL
        merge above — the reference's leaf/intermediate AggType split
        (pinot-query-runtime/.../operator/AggregateOperator.java, AggType).
        Non-decomposable calls fall back to single-phase over shuffled rows."""
        decomposable = all(c.name in _DECOMPOSE and not c.extra for c in agg_calls)
        if not decomposable:
            keys = [g.identifier for g in group_exprs if g.is_identifier]
            ex = ExchangeNode([node], node.schema,
                              dist="hash" if keys and len(keys) == len(group_exprs)
                              else "singleton", keys=keys)
            return AggregateNode(
                [ex], group_names + [c.out_name for c in agg_calls],
                group_exprs=group_exprs, agg_calls=agg_calls)

        partial_calls: list[AggCall] = []
        final_calls: list[AggCall] = []
        reconstruct: list[EC] = []  # parallel to agg_calls

        def add_phase(pname: str, fname: str, args: list[EC],
                      cond: Optional[EC] = None) -> str:
            """The FILTER condition applies on the PARTIAL (pre-shuffle)
            phase where raw input rows live; the final merge is unfiltered."""
            p = f"$p{len(partial_calls)}"
            partial_calls.append(AggCall(pname, args, p, condition=cond))
            final_calls.append(AggCall(fname, [EC.for_identifier(p)], p))
            return p

        for c in agg_calls:
            if c.name in ("count", "countmv"):
                p = add_phase("count", "sum", c.args, c.condition)
                reconstruct.append(EC.for_function(
                    "cast", EC.for_identifier(p), EC.for_literal("LONG")))
            elif c.name == "sum":
                reconstruct.append(EC.for_identifier(
                    add_phase("sum", "sum", c.args, c.condition)))
            elif c.name == "min":
                reconstruct.append(EC.for_identifier(
                    add_phase("min", "min", c.args, c.condition)))
            elif c.name == "max":
                reconstruct.append(EC.for_identifier(
                    add_phase("max", "max", c.args, c.condition)))
            elif c.name == "avg":
                s = add_phase("sum", "sum", c.args, c.condition)
                n = add_phase("count", "sum", c.args, c.condition)
                reconstruct.append(EC.for_function(
                    "divide", EC.for_identifier(s), EC.for_identifier(n)))
            elif c.name == "minmaxrange":
                mx = add_phase("max", "max", c.args, c.condition)
                mn = add_phase("min", "min", c.args, c.condition)
                reconstruct.append(EC.for_function(
                    "minus", EC.for_identifier(mx), EC.for_identifier(mn)))
            else:  # pragma: no cover — guarded by _DECOMPOSE
                raise PlanError(c.name)

        partial_schema = group_names + [c.out_name for c in partial_calls]
        partial = AggregateNode([node], partial_schema,
                                group_exprs=group_exprs, agg_calls=partial_calls)
        ex = ExchangeNode([partial], partial_schema,
                          dist="hash" if group_names else "singleton",
                          keys=list(group_names))
        final = AggregateNode(
            [ex], group_names + [c.out_name for c in final_calls],
            group_exprs=[EC.for_identifier(g) for g in group_names],
            agg_calls=final_calls)
        return ProjectNode(
            [final], group_names + [c.out_name for c in agg_calls],
            exprs=[EC.for_identifier(g) for g in group_names] + reconstruct)

    # -- windows ------------------------------------------------------------
    def _plan_window(self, stmt: SelectStmt, node: PlanNode):
        calls: list[WindowCall] = []
        out_names: list[str] = []
        out_exprs: list[EC] = []
        for it in stmt.select_items:
            if it.window is not None:
                e = it.expression
                if not e.is_function:
                    raise PlanError("OVER must follow a function call")
                spec = WindowSpec(
                    partition_by=[self._resolve(p, node.schema) for p in it.window.partition_by],
                    order_by=[(self._resolve(o, node.schema), asc)
                              for o, asc in it.window.order_by],
                    frame=it.window.frame)
                name = e.function.name
                if name not in _WINDOW_ONLY and not is_agg_function(name):
                    raise PlanError(f"unsupported window function {name}")
                out = f"$w{len(calls)}"
                calls.append(WindowCall(
                    name, [self._resolve(a, node.schema) for a in e.function.arguments
                           if not (a.is_identifier and a.identifier == "*")],
                    spec, out))
                out_exprs.append(EC.for_identifier(out))
                out_names.append(it.alias or str(e) + " OVER(...)")
            else:
                if it.expression.is_identifier and it.expression.identifier in ("*",):
                    for c in node.schema:
                        out_exprs.append(EC.for_identifier(c))
                        out_names.append(_short(c))
                    continue
                out_exprs.append(self._resolve(it.expression, node.schema))
                out_names.append(it.alias or str(it.expression))
        partition_keys = calls[0].spec.partition_by if calls else []
        # all calls must share a partition for the hash exchange to be valid;
        # otherwise fall back to singleton (reference: one window group per
        # WindowNode, WindowAggregateOperator)
        same = all(c.spec.partition_by == partition_keys for c in calls)
        keys = [p.identifier for p in partition_keys if p.is_identifier] if same else []
        dist = "hash" if keys else "singleton"
        wnode = WindowNode(
            [ExchangeNode([node], node.schema, dist=dist, keys=keys)],
            node.schema + [c.out_name for c in calls],
            calls=calls, partition_keys=partition_keys)
        return wnode, out_names, out_exprs

    # -- helpers ------------------------------------------------------------
    def _select_outputs(self, items: list[SelectItem], schema: list[str]):
        names: list[str] = []
        exprs: list[EC] = []
        for it in items:
            e = it.expression
            if e.is_identifier and e.identifier == "*":
                for c in schema:
                    exprs.append(EC.for_identifier(c))
                    names.append(_short(c))
                continue
            if e.is_identifier and e.identifier.endswith(".*"):
                prefix = e.identifier[:-2] + "."
                matched = [c for c in schema if c.startswith(prefix)]
                if not matched:
                    raise PlanError(f"no columns match {e.identifier!r}")
                for c in matched:
                    exprs.append(EC.for_identifier(c))
                    names.append(_short(c))
                continue
            exprs.append(self._resolve(e, schema))
            names.append(it.alias or str(e))
        return names, exprs

    def _resolve_order(self, items: list[OrderItem], schema: list[str],
                       fallback: Optional[list[str]] = None) -> list[OrderItem]:
        out = []
        for it in items:
            try:
                e = self._resolve(it.expression, schema)
            except PlanError:
                if fallback is None:
                    raise
                e = self._resolve(it.expression, fallback)
            out.append(OrderItem(e, it.ascending, it.nulls_last))
        return out

    def _resolve(self, e: EC, schema: list[str]) -> EC:
        r = self._try_resolve(e, schema)
        if r is None:
            raise PlanError(f"cannot resolve expression {e} against {schema}")
        return r

    def _try_resolve(self, e: EC, schema: list[str]) -> Optional[EC]:
        if e.is_literal:
            return e
        if e.is_identifier:
            try:
                return EC.for_identifier(_resolve_name(e.identifier, schema))
            except PlanError:
                return None
        args = []
        for a in e.function.arguments:
            r = self._try_resolve(a, schema)
            if r is None:
                return None
            args.append(r)
        return EC.for_function(e.function.name, *args)

    def _contains_agg(self, e: EC) -> bool:
        if not e.is_function:
            return False
        if e.function.name in _WINDOW_ONLY:
            return False
        if is_agg_function(e.function.name):
            return True
        return any(self._contains_agg(a) for a in e.function.arguments)


# -- name utilities ----------------------------------------------------------


def _short(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _resolve_name(ident: str, schema: list[str]) -> str:
    """Resolve `col` or `alias.col` against qualified schema names."""
    if ident in schema:
        return ident
    matches = [c for c in schema if c.endswith("." + ident)]
    if len(matches) == 1:
        return matches[0]
    if len(matches) > 1:
        raise PlanError(f"ambiguous column {ident!r}: {matches}")
    # alias.col given but schema holds bare names (subquery outputs)
    if "." in ident:
        tail = _short(ident)
        if tail in schema:
            return tail
        matches = [c for c in schema if c.endswith("." + tail)]
        if len(matches) == 1:
            return matches[0]
    raise PlanError(f"unknown column {ident!r} (have {schema})")


def _reject_nested_subqueries(e: EC) -> None:
    """IN (SELECT …) is only rewritable as a top-level AND conjunct of WHERE
    (Calcite's SubQueryRemoveRule handles more; we fail clearly instead of
    leaking internal markers to the runtime)."""
    if e.is_function:
        if e.function.name in ("__insubquery__", "__notinsubquery__"):
            raise PlanError(
                "IN (SELECT ...) is only supported as a top-level AND "
                "conjunct of WHERE")
        for a in e.function.arguments:
            _reject_nested_subqueries(a)


def _split_and(e: EC) -> list[EC]:
    if e.is_function and e.function.name == "and":
        out = []
        for a in e.function.arguments:
            out.extend(_split_and(a))
        return out
    return [e]


def _expr_name(resolved: EC, raw: EC) -> str:
    if resolved.is_identifier:
        return resolved.identifier
    return str(raw)


# -- column pruning ----------------------------------------------------------


def prune_columns(node: PlanNode, required: Optional[set[str]] = None) -> PlanNode:
    """Trim the plan to columns actually consumed upstream (reference:
    Calcite's ProjectPushDown / field trimming). Three cuts, all in place:

    - TableScan outputs narrow to referenced columns (as before);
    - ExchangeNode schemas narrow to what the consuming stage references
      (plus routing keys) — the fragmenter turns these into each stage's
      send schema, so only referenced columns are shuffled. A column a
      pushed-down filter consumes at the leaf no longer crosses the wire;
    - JoinNode schemas narrow to what the parent references — the join's
      late-materialized gather then touches only those payload columns.
    """
    if required is None:
        required = set(node.schema)

    def node_refs(n: PlanNode) -> set[str]:
        out: set[str] = set()
        if isinstance(n, FilterNode) and n.condition is not None:
            out |= n.condition.columns()
        elif isinstance(n, ProjectNode):
            for e in n.exprs:
                out |= e.columns()
        elif isinstance(n, AggregateNode):
            for g in n.group_exprs:
                out |= g.columns()
            for c in n.agg_calls:
                for a in c.args:
                    out |= a.columns()
                if c.condition is not None:
                    out |= c.condition.columns()
        elif isinstance(n, JoinNode):
            out |= set(n.left_keys) | set(n.right_keys)
            if n.residual is not None:
                out |= n.residual.columns()
        elif isinstance(n, WindowNode):
            for c in n.calls:
                for a in c.args:
                    out |= a.columns()
                for p in c.spec.partition_by:
                    out |= p.columns()
                for o, _ in c.spec.order_by:
                    out |= o.columns()
        elif isinstance(n, SortNode):
            for it in n.sort_items:
                out |= it.expression.columns()
        elif isinstance(n, ExchangeNode):
            out |= set(n.keys)
        return out

    def visit(n: PlanNode, req: set[str]) -> None:
        if isinstance(n, TableScanNode):
            keep = [i for i, c in enumerate(n.schema) if c in req]
            if keep and len(keep) < len(n.schema):
                n.source_columns = [n.source_columns[i] for i in keep]
                n.schema = [n.schema[i] for i in keep]
            return
        refs = node_refs(n)
        if isinstance(n, ExchangeNode):
            # narrow the shuffle schema: only columns the consuming stage
            # references (plus the routing keys) cross the mailbox. Keep at
            # least one column so row counts survive (COUNT(*) shapes).
            keep = [c for c in n.schema if c in req or c in n.keys]
            n.schema = keep if keep else n.schema[:1]
            visit(n.inputs[0], set(n.schema))
            return
        if isinstance(n, JoinNode):
            # narrow the join OUTPUT: the late-materialized gather in
            # op_join only touches these columns. Keys/residual columns
            # still flow to the children via refs.
            keep = [c for c in n.schema if c in req]
            n.schema = keep if keep else n.schema[:1]
            child_req = set(n.schema) | refs
            for inp in n.inputs:
                visit(inp, child_req)
            return
        if isinstance(n, (ProjectNode, AggregateNode, WindowNode)):
            child_req = refs if not isinstance(n, WindowNode) else refs | {
                c for c in n.inputs[0].schema if c in req}
        elif isinstance(n, SetOpNode):
            child_req = None  # positional: keep everything
        else:
            # pass-through nodes: child columns flow to output
            child_req = (req | refs)
        for inp in n.inputs:
            visit(inp, child_req if child_req is not None else set(inp.schema))

    visit(node, required)
    return node
