"""Mailbox service: the MSE shuffle data plane.

Reference analogue: pinot-query-runtime/.../mailbox/MailboxService.java:40 —
getSendingMailbox:113 / getReceivingMailbox:125, with gRPC channels between
hosts and InMemory mailboxes for same-host pairs, and the exchange
strategies (hash/broadcast/singleton) in .../runtime/operator/exchange/.

Here every mailbox is in-memory (one process); the addressing scheme
(from_stage, to_stage, partition) matches the reference's mailbox id
`{requestId}|{senderStage}|{senderWorker}|{receiverStage}|{receiverWorker}`.
Payloads are columnar blocks (dict[str, np.ndarray]) — the analogue of
TransferableBlock wrapping a columnar DataBlock. When stages are placed on
TPU meshes, a hash exchange lowers to an all-to-all over ICI and broadcast
to a replicated device_put (parallel/mesh.py holds the collectives).
"""

from __future__ import annotations

import zlib
from collections import defaultdict
from typing import Optional

import numpy as np

from ..spi.partition import get_partition_function

Block = dict  # column name → np.ndarray (equal lengths)


def block_len(block: Block) -> int:
    for v in block.values():
        return len(v)
    return 0


def block_nbytes(block: Block) -> int:
    """Payload size of a block as shipped through a mailbox. Object columns
    count pointer width per row (cheap, consistent) — the stats consumer
    compares plans against each other, not against the wire."""
    return sum(np.asarray(v).nbytes for v in block.values())


def concat_blocks(blocks: list[Block], schema: Optional[list[str]] = None) -> Block:
    blocks = [b for b in blocks if b and block_len(b)]
    if not blocks:
        return {c: np.empty(0) for c in (schema or [])}
    cols = schema if schema is not None else list(blocks[0].keys())
    out = {}
    for c in cols:
        parts = [b[c] for b in blocks if c in b]
        if not parts:
            continue
        if len(parts) == 1:
            out[c] = np.asarray(parts[0])
        else:
            arrs = [np.asarray(p) for p in parts]
            if any(a.dtype.kind == "O" for a in arrs):
                arrs = [a.astype(object) for a in arrs]
            out[c] = np.concatenate(arrs)
    return out


def take_block(block: Block, idx) -> Block:
    return {c: np.asarray(v)[idx] for c, v in block.items()}


def _string_crc(v: np.ndarray) -> np.ndarray:
    """CRC32 memoized per distinct value — shuffle keys are dict-decoded
    strings with few distincts, so encode+crc runs once per distinct and
    every repeat is a dict hit (Python caches each str object's hash, and
    dict-decoded columns share value objects). Hash values are identical
    to the former per-row loop (str(x) then crc32)."""
    cache: dict = {}
    get = cache.get
    return np.fromiter(
        (h if (h := get(x)) is not None
         else cache.setdefault(x, zlib.crc32(str(x).encode("utf-8")))
         for x in v),
        dtype=np.uint64, count=len(v))


def hash_codes(block: Block, keys: list[str], n: int) -> np.ndarray:
    """uint64 combined hash of the key columns (row-wise)."""
    h = np.zeros(n, dtype=np.uint64)
    for k in keys:
        v = np.asarray(block[k])
        if v.dtype.kind in "iub":
            hv = v.astype(np.int64).view(np.uint64)
        elif v.dtype.kind == "f":
            f = v.astype(np.float64)
            f = np.where(f == 0.0, 0.0, f)  # -0.0 == 0.0 must hash equal
            hv = f.view(np.uint64)
        else:
            # deterministic across OS processes — Python's str hash is
            # randomized per process (PYTHONHASHSEED) and would route the
            # same key to different workers on different hosts
            hv = _string_crc(v)
        h = h * np.uint64(1000003) ^ hv
    return h


def split_by_partition(block: Block, part: np.ndarray,
                       num_partitions: int) -> list[Block]:
    """One stable argsort + one gather per column, then zero-copy slices —
    replaces the O(n·P) boolean-mask scan. Output blocks are views over the
    gathered arrays; consumers treat blocks as immutable."""
    order = np.argsort(part, kind="stable")
    counts = np.bincount(part, minlength=num_partitions)
    offs = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(counts, out=offs[1:])
    gathered = {c: np.asarray(v)[order] for c, v in block.items()}
    return [{c: v[offs[p]:offs[p + 1]] for c, v in gathered.items()}
            for p in range(num_partitions)]


def hash_partition(block: Block, keys: list[str], num_partitions: int) -> list[Block]:
    """Deterministic value-hash partitioning — every producer must route the
    same key to the same consumer worker (reference: KeySelector hashCode %
    partitions in HashExchange)."""
    n = block_len(block)
    if num_partitions == 1 or not keys:
        return [block]
    h = hash_codes(block, keys, n)
    part = (h % np.uint64(num_partitions)).astype(np.int64)
    return split_by_partition(block, part, num_partitions)


def table_partition(block: Block, key: str, pfunc: str,
                    num_partitions: int) -> list[Block]:
    """Colocated-join routing: split by the TABLE's partition function on
    the partition key, so worker p sees exactly table partition p — the
    same assignment the segments were stamped with at build time."""
    fn = get_partition_function(pfunc, num_partitions)
    part = np.asarray(fn.partitions_of(np.asarray(block[key])), dtype=np.int64)
    return split_by_partition(block, part, num_partitions)


class MailboxService:
    """In-memory post office for one query execution."""

    # pseudo-partition for whole-block handoffs (device-resident path):
    # no hash split, the consumer takes the block as one unit
    RAW_PARTITION = -1

    def __init__(self):
        self._boxes: dict[tuple, list[Block]] = defaultdict(list)
        # per sending stage, for the stage-stats plane. sent_bytes is the
        # LOGICAL payload moved between stages (comparable across the
        # encode/decode and handoff paths); cross_bytes is what actually
        # crossed a process/host boundary — zero for raw handoffs.
        self.sent_rows: dict[int, int] = defaultdict(int)
        self.sent_bytes: dict[int, int] = defaultdict(int)
        self.cross_bytes: dict[int, int] = defaultdict(int)

    def send(self, from_stage: int, to_stage: int, partition: int, block: Block) -> None:
        self.sent_rows[from_stage] += block_len(block)
        nb = block_nbytes(block)
        self.sent_bytes[from_stage] += nb
        self.cross_bytes[from_stage] += nb
        self._boxes[(from_stage, to_stage, partition)].append(block)

    def send_raw(self, from_stage: int, to_stage: int, block: Block) -> None:
        """Same-process device handoff: the block changes hands by
        reference — no partition split, no encode/decode, nothing crosses
        a wire. Logical bytes still accrue to sent_bytes so
        /debug/workload cost rollups stay comparable across join paths;
        cross_bytes stays untouched (that is the 5x the fused path buys)."""
        self.sent_rows[from_stage] += block_len(block)
        self.sent_bytes[from_stage] += block_nbytes(block)
        self._boxes[(from_stage, to_stage, self.RAW_PARTITION)].append(block)

    def receive_raw(self, from_stage: int, to_stage: int,
                    schema: Optional[list[str]] = None) -> Block:
        return concat_blocks(
            self._boxes.get((from_stage, to_stage, self.RAW_PARTITION), []),
            schema)

    def receive(self, from_stage: int, to_stage: int, partition: int,
                schema: Optional[list[str]] = None) -> Block:
        return concat_blocks(self._boxes.get((from_stage, to_stage, partition), []),
                             schema)

    def stream(self, from_stage: int, to_stage: int, partition: int):
        """Chunk-at-a-time receive (same contract as the distributed
        RoutedMailbox.stream); in-process all chunks already exist."""
        yield from self._boxes.get((from_stage, to_stage, partition), [])

    def send_partitioned(self, from_stage: int, to_stage: int, block: Block,
                         dist: str, keys: list[str], num_partitions: int,
                         pfunc: Optional[str] = None) -> None:
        if dist == "partitioned" and keys and num_partitions > 1:
            for p, b in enumerate(table_partition(
                    block, keys[0], pfunc, num_partitions)):
                self.send(from_stage, to_stage, p, b)
        elif dist == "hash" and keys and num_partitions > 1:
            for p, b in enumerate(hash_partition(block, keys, num_partitions)):
                self.send(from_stage, to_stage, p, b)
        elif dist == "broadcast":
            for p in range(num_partitions):
                self.send(from_stage, to_stage, p, block)
        else:  # singleton
            self.send(from_stage, to_stage, 0, block)
