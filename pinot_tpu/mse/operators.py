"""MSE runtime operators over columnar blocks.

Reference analogue: pinot-query-runtime/.../runtime/operator/ —
HashJoinOperator, AggregateOperator (+MultistageGroupByExecutor),
WindowAggregateOperator (+.../operator/window/), SortOperator, SetOperator,
FilterOperator, TransformOperator. Execution model differs by design: each
stage materializes its hash-partitioned input and runs whole-partition
vectorized numpy (a TPU-host analogue of the reference's block-at-a-time
pull loops); the per-partition work is embarrassingly parallel across
workers, and big leaf aggregations never reach these operators at all —
they're pushed into the single-stage device engine by the leaf compiler.
"""

from __future__ import annotations

import os
import re
import threading
from collections import Counter
from typing import Optional

import numpy as np

from ..engine.aggregation import UnsupportedQueryError, get_semantics, host_state_full
from ..query.expressions import ExpressionContext
from ..query.transforms import eval_expr_np
from .ast import OrderItem, WindowSpec
from .logical import AggCall, WindowCall
from .mailbox import Block, block_len, take_block

EC = ExpressionContext


# -- expression evaluation ---------------------------------------------------


def eval_expr(e: EC, block: Block, n: Optional[int] = None):
    """Evaluate an expression over a block; result is ndarray of length n or
    a scalar. Adds the predicate forms eval_expr_np leaves to FilterContext
    (in/between/like/isnull) since MSE filters stay as raw expressions."""
    if n is None:
        n = block_len(block)
    if e.is_function:
        name = e.function.name
        args = e.function.arguments
        if name in ("in", "notin"):
            v = np.asarray(eval_expr(args[0], block, n))
            vals = [a.literal if a.is_literal else eval_expr(a, block, n) for a in args[1:]]
            mask = np.zeros(len(v) if v.ndim else n, dtype=bool)
            for x in vals:
                mask |= v == x
            return ~mask if name == "notin" else mask
        if name == "between":
            v = eval_expr(args[0], block, n)
            lo = eval_expr(args[1], block, n)
            hi = eval_expr(args[2], block, n)
            return (v >= lo) & (v <= hi)
        if name == "like":
            v = np.asarray(eval_expr(args[0], block, n))
            pat = _like_regex(str(args[1].literal))
            return np.fromiter((bool(pat.fullmatch(str(x))) for x in v),
                               dtype=bool, count=len(v))
        if name in ("regexplike", "regexp_like"):
            v = np.asarray(eval_expr(args[0], block, n))
            pat = re.compile(str(args[1].literal))
            return np.fromiter((bool(pat.search(str(x))) for x in v),
                               dtype=bool, count=len(v))
        if name == "isnull":
            return _null_mask(np.asarray(eval_expr(args[0], block, n)))
        if name == "isnotnull":
            return ~_null_mask(np.asarray(eval_expr(args[0], block, n)))
        if name == "coalesce":
            out = None
            for a in args:
                v = np.asarray(eval_expr(a, block, n))
                if v.ndim == 0:
                    v = np.full(n, v.item() if hasattr(v, "item") else v)
                if out is None:
                    out = v.astype(object) if v.dtype.kind == "O" else v.astype(np.float64) \
                        if v.dtype.kind == "f" else v
                    continue
                mask = _null_mask(np.asarray(out))
                if not mask.any():
                    break
                out = np.where(mask, v, out)
            return out
        # recurse through composite ops so the predicate forms above are
        # reachable at ANY depth (e.g. `x > 5 OR y IN (...)`)
        from ..query.transforms import NP_BIN, NP_UN

        if name in NP_BIN:
            return NP_BIN[name](eval_expr(args[0], block, n),
                                eval_expr(args[1], block, n))
        if name in NP_UN:
            return NP_UN[name](eval_expr(args[0], block, n))
        if name == "case":
            out = eval_expr(args[-1], block, n)
            for i in range(len(args) - 3, -1, -2):
                cond = np.asarray(eval_expr(args[i], block, n)).astype(bool)
                out = np.where(cond, eval_expr(args[i + 1], block, n), out)
            return out
    return eval_expr_np(e, lambda name: _resolve_col(block, name))


def _resolve_col(block: Block, name: str):
    if name in block:
        return np.asarray(block[name])
    matches = [c for c in block if c.endswith("." + name)]
    if len(matches) == 1:
        return np.asarray(block[matches[0]])
    raise UnsupportedQueryError(f"unknown column {name!r} in block {list(block)}")


def _null_mask(v: np.ndarray) -> np.ndarray:
    if v.dtype.kind == "f":
        return np.isnan(v)
    if v.dtype.kind == "O":
        return np.fromiter((x is None or (isinstance(x, float) and np.isnan(x)) for x in v),
                           dtype=bool, count=len(v))
    return np.zeros(len(v), dtype=bool)


def _like_regex(pattern: str) -> "re.Pattern":
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out), re.DOTALL)


def _truthy(v, n: int) -> np.ndarray:
    a = np.asarray(v)
    if a.ndim == 0:
        return np.full(n, bool(a), dtype=bool)
    if a.dtype.kind == "f":
        return ~np.isnan(a) & (a != 0)
    return a.astype(bool)


# -- filter / project --------------------------------------------------------


def op_filter(block: Block, condition: EC) -> Block:
    n = block_len(block)
    mask = _truthy(eval_expr(condition, block, n), n)
    return take_block(block, mask)


def op_project(block: Block, names: list[str], exprs: list[EC]) -> Block:
    n = block_len(block)
    out: Block = {}
    for name, e in zip(names, exprs):
        v = np.asarray(eval_expr(e, block, n))
        if v.ndim == 0:
            v = np.full(n, v.item() if hasattr(v, "item") else v)
        out[name] = v
    return out


# -- group codes -------------------------------------------------------------


def group_codes(cols: list[np.ndarray]):
    """Row tuples → dense int codes. Returns (codes, num_groups,
    first_occurrence_index per group, in first-seen order? no — np.unique
    sorted order; callers use representative indices to recover values)."""
    n = len(cols[0]) if cols else 0
    codes = np.zeros(n, dtype=np.int64)
    num = 1 if n else 0
    for j, c in enumerate(cols):
        inv, card = _factorize(np.asarray(c))
        if j == 0:
            codes, num = inv, card
        else:
            codes, num = _factorize(codes * np.int64(card) + inv)
    # representative row per group (first occurrence in stable sort order)
    order = np.argsort(codes, kind="stable")
    starts = np.searchsorted(codes[order], np.arange(num), "left")
    first = order[starts] if n else starts
    return codes, num, first


def _factorize(a: np.ndarray) -> tuple[np.ndarray, int]:
    """Dense int64 codes + cardinality. Integer keys ride the native
    open-addressing factorizer (native/pinot_native.cpp — the
    DictionaryBasedGroupKeyGenerator analogue); everything else uses
    np.unique. Code ORDER differs between the two (first-occurrence vs
    sorted) — callers only rely on density."""
    if a.dtype.kind in "iub":
        from ..segment import native_bridge

        r = native_bridge.factorize_i64(a.astype(np.int64, copy=False))
        if r is not None:
            codes, uniques = r
            return codes, len(uniques)
    if a.dtype.kind == "O":
        # object columns may hold SQL NULLs (None / NaN from outer joins):
        # np.unique cannot order mixed None/str — dict-encode instead.
        # All NULLs land in one group (SQL GROUP BY null semantics).
        table: dict = {}
        codes = np.empty(len(a), dtype=np.int64)
        null_code = -1
        for i, v in enumerate(a):
            if v is None or (isinstance(v, float) and np.isnan(v)):
                if null_code < 0:
                    null_code = len(table)
                    table[_NULL_KEY] = null_code
                codes[i] = null_code
                continue
            c = table.get(v)
            if c is None:
                c = table[v] = len(table)
            codes[i] = c
        return codes, len(table)
    _, inv = np.unique(a, return_inverse=True)
    return inv.astype(np.int64), int(inv.max(initial=-1)) + 1


_NULL_KEY = object()  # sentinel: the NULL group in object factorize


# -- aggregate ---------------------------------------------------------------

_FAST_AGGS = {"count", "sum", "min", "max"}


def op_aggregate(block: Block, group_exprs: list[EC], agg_calls: list[AggCall],
                 schema: list[str]) -> Block:
    n = block_len(block)
    key_vals = [np.asarray(eval_expr(g, block, n)) for g in group_exprs]

    if not group_exprs:
        out: Block = {}
        for call in agg_calls:
            out[call.out_name] = np.asarray([_agg_full(call, block, n)], dtype=object)
        return _tighten(out)

    if n == 0:
        return {c: np.empty(0) for c in schema}

    codes, num, first = group_codes(key_vals)
    out = {}
    for name, kv in zip(schema, key_vals):
        out[name] = kv[first]
    for call in agg_calls:
        out[call.out_name] = _agg_grouped(call, block, codes, num, n)
    return out


def _agg_args(call: AggCall, block: Block, n: int):
    return [np.asarray(eval_expr(a, block, n)) for a in call.args]


def _valid_mask(arg_vals: list[np.ndarray], n: int) -> np.ndarray:
    mask = np.ones(n, dtype=bool)
    for v in arg_vals:
        mask &= ~_null_mask(v)
    return mask


def _cond_mask(call: AggCall, block: Block, n: int):
    """FILTER (WHERE cond) mask; a NULL clause result is false (3VL)."""
    if call.condition is None:
        return None
    v = np.asarray(eval_expr(call.condition, block, n))
    if v.dtype.kind == "O":
        return np.asarray([bool(x) and not (isinstance(x, float) and np.isnan(x))
                           and x is not None for x in v], dtype=bool)
    return v.astype(bool) & ~_null_mask(v)


def _agg_full(call: AggCall, block: Block, n: int):
    """Whole-input aggregate → finalized scalar."""
    sem = get_semantics(call.name, call.extra)
    cmask = _cond_mask(call, block, n)
    if call.name == "count" and not call.args:
        return n if cmask is None else int(cmask.sum())
    vals = _agg_args(call, block, n)
    mask = _valid_mask(vals, n)
    if cmask is not None:
        mask &= cmask
    vals = [v[mask] for v in vals]
    if not (len(vals[0]) if vals else 0) and call.name not in _ZERO_ON_EMPTY:
        return None  # SQL: aggregate over zero (non-null) rows is NULL
    state = host_state_full(call.name, vals, call.extra)
    return sem.finalize(state)


# aggregates whose empty result is a value, not NULL
_ZERO_ON_EMPTY = {"count", "countmv", "distinctcount", "distinctcounthll",
                  "distinctcountbitmap", "distinctcountrawhll", "booland",
                  "boolor", "boolagg", "arrayagg", "listagg", "histogram"}


def _agg_grouped(call: AggCall, block: Block, codes: np.ndarray, num: int, n: int):
    name = call.name
    cmask = _cond_mask(call, block, n)
    if name == "count" and not call.args:
        return np.bincount(codes if cmask is None else codes[cmask],
                           minlength=num).astype(np.int64)
    vals = _agg_args(call, block, n)
    mask = _valid_mask(vals, n)
    if cmask is not None:
        mask &= cmask
    v = vals[0] if vals else None
    if name in _FAST_AGGS and v is not None and v.dtype.kind in "iufb":
        c = codes[mask]
        x = v[mask].astype(np.float64)
        valid = np.bincount(c, minlength=num)
        if name == "count":
            return valid.astype(np.int64)
        if name == "sum":
            out = np.bincount(c, weights=x, minlength=num)
        else:
            out = np.full(num, np.inf if name == "min" else -np.inf)
            (np.minimum if name == "min" else np.maximum).at(out, c, x)
        out[valid == 0] = np.nan  # all-NULL group → NULL
        return out
    if name == "avg" and v is not None and v.dtype.kind in "iufb":
        c = codes[mask]
        s = np.bincount(c, weights=v[mask].astype(np.float64), minlength=num)
        cnt = np.bincount(c, minlength=num)
        with np.errstate(invalid="ignore"):
            return s / cnt
    # generic: per-group host state + finalize
    sem = get_semantics(name, call.extra)
    order = np.argsort(codes[mask], kind="stable")
    mvals = [x[mask][order] for x in vals]
    mcodes = codes[mask][order]
    bounds = np.searchsorted(mcodes, np.arange(num + 1), "left")
    out = []
    for g in range(num):
        lo, hi = bounds[g], bounds[g + 1]
        if lo == hi:
            out.append(sem.empty_value if name in _ZERO_ON_EMPTY else None)
            continue
        state = host_state_full(name, [x[lo:hi] for x in mvals], call.extra)
        out.append(sem.finalize(state))
    return _tighten_col(np.asarray(out, dtype=object))


def _tighten(block: Block) -> Block:
    return {k: _tighten_col(v) for k, v in block.items()}


def _tighten_col(v: np.ndarray) -> np.ndarray:
    if v.dtype.kind != "O":
        return v
    try:
        kinds = {type(x) for x in v}
        if kinds <= {int, np.int64, bool}:
            return v.astype(np.int64)
        if kinds <= {float, int, np.float64, np.int64}:
            return v.astype(np.float64)
    except (TypeError, ValueError):
        pass
    return v


# -- hash join ---------------------------------------------------------------


class JoinRowLimitExceeded(Exception):
    """The join would materialize more rows than maxRowsInJoin (reference:
    HashJoinOperator's join-overflow THROW mode)."""


# reference defaults: maxRowsInJoin (InstancePlanMakerImplV2 /
# HashJoinOperator); override per deployment via PINOT_TPU_MAX_ROWS_IN_JOIN
MAX_ROWS_IN_JOIN = int(os.environ.get("PINOT_TPU_MAX_ROWS_IN_JOIN",
                                      5_000_000))
# THROW (fail the query) or BREAK (truncate and mark partial)
JOIN_OVERFLOW_MODE = os.environ.get("PINOT_TPU_JOIN_OVERFLOW_MODE",
                                    "THROW").upper()


_overflow = threading.local()


def pop_join_overflow() -> bool:
    """True if a BREAK-mode truncation happened since the last call on this
    thread — the runtime surfaces it as a partial-result marker (reference:
    HashJoinOperator sets maxRowsInJoinReached in the stats)."""
    hit = getattr(_overflow, "hit", False)
    _overflow.hit = False
    return hit


def _guard_join_rows(total: int, ln: int, rn: int,
                     join_type: str) -> Optional[int]:
    """Returns a truncation bound in BREAK mode, raises in THROW mode, None
    when under the limit — checked BEFORE materializing index arrays so an
    accidental many-to-many cross blowup cannot OOM the host silently.
    ANTI/RIGHT/FULL joins always raise: truncating their inputs would emit
    WRONG rows (false anti-matches, false null-padded right rows), not a
    partial subset."""
    if total <= MAX_ROWS_IN_JOIN:
        return None
    if JOIN_OVERFLOW_MODE == "BREAK" and join_type in ("INNER", "LEFT",
                                                       "SEMI", "CROSS"):
        _overflow.hit = True
        return MAX_ROWS_IN_JOIN
    raise JoinRowLimitExceeded(
        f"{join_type} join would produce {total} rows ({ln}x{rn} inputs), "
        f"over maxRowsInJoin={MAX_ROWS_IN_JOIN}"
        + ("" if JOIN_OVERFLOW_MODE == "BREAK" else
           "; set PINOT_TPU_JOIN_OVERFLOW_MODE=BREAK to truncate instead"))


class JoinCtx:
    """Per-query join state shared by every partition (and worker thread)
    of a join stage: persistent value→code maps keyed by (stage, key
    position) so a second partition factorizes only values it has not seen,
    plus counters for the perf plane (int fast-path, cache reuse)."""

    def __init__(self):
        self.counters: Counter = Counter()
        self._maps: dict = {}
        self.lock = threading.RLock()

    def for_stage(self, stage_id: int) -> "_StageJoinCtx":
        return _StageJoinCtx(self, stage_id)

    def mapping(self, stage_id: int, pos: int) -> dict:
        with self.lock:
            return self._maps.setdefault((stage_id, pos), {})

    def bump(self, name: str) -> None:
        with self.lock:
            self.counters[name] += 1


class _StageJoinCtx:
    """JoinCtx view bound to one stage id (what op_join receives)."""

    __slots__ = ("_ctx", "_stage")

    def __init__(self, ctx: JoinCtx, stage_id: int):
        self._ctx = ctx
        self._stage = stage_id

    @property
    def lock(self):
        return self._ctx.lock

    @property
    def counters(self) -> Counter:
        return self._ctx.counters

    def mapping(self, pos: int) -> dict:
        return self._ctx.mapping(self._stage, pos)

    def bump(self, name: str) -> None:
        self._ctx.bump(name)


def op_join(left: Block, right: Block, join_type: str,
            left_keys: list[str], right_keys: list[str],
            residual: Optional[EC], schema: list[str],
            ctx=None) -> Block:
    """Late-materialized hash join: match on key codes, thread (lidx, ridx)
    index pairs through residual/SEMI/ANTI/padding, and gather ONLY the
    columns the output schema demands at the very end. An empty schema means
    "emit everything" (back-compat for direct callers)."""
    ln = block_len(left)
    rn = block_len(right)

    if join_type == "CROSS" or not left_keys:
        kind = join_type if join_type in ("SEMI", "ANTI") else "CROSS"
        cap = _guard_join_rows(ln * rn, ln, rn, kind)
        if cap is not None:
            # truncate BOTH sides so ln*rn ≤ cap even when one side alone
            # exceeds it
            rn = min(rn, max(1, cap // max(ln, 1)))
            ln = min(ln, max(1, cap // rn))
            left = take_block(left, np.arange(ln))
            right = take_block(right, np.arange(rn))
        lidx = np.repeat(np.arange(ln), rn)
        ridx = np.tile(np.arange(rn), ln)
        if residual is not None and len(lidx):
            rb = _residual_block(left, right, lidx, ridx, residual)
            m = _truthy(eval_expr(residual, rb, len(lidx)), len(lidx))
            lidx, ridx = lidx[m], ridx[m]
        if join_type in ("SEMI", "ANTI"):
            sel = np.unique(lidx)
            if join_type == "ANTI":
                sel = np.setdiff1d(np.arange(ln), sel)
            return _project_side(left, schema, sel)
        return _emit(left, right, lidx, ridx, schema)

    # dict-encode key tuples across both sides so codes are comparable
    lcodes, rcodes = _joint_codes(
        [np.asarray(left[k]) for k in left_keys],
        [np.asarray(right[k]) for k in right_keys], ln, rn, ctx)

    lidx = ridx = None
    device_used = False
    from . import device_join

    if device_join.enabled(ln, rn):
        # large sides: the sort + binary-search runs on the accelerator
        # (mse/device_join.py); only int64 key codes travel. Overflow
        # (or any device hiccup) falls back to the host path, which owns
        # the THROW/BREAK guard semantics.
        try:
            li, ri, total = device_join.device_join_indices(
                lcodes, rcodes, MAX_ROWS_IN_JOIN)
            if total <= MAX_ROWS_IN_JOIN:
                lidx = li.astype(np.int64)
                ridx = ri.astype(np.int64)
                device_used = True
        except Exception as e:
            device_join.note_failure(e)  # logged once, then host path
            lidx = ridx = None

    if lidx is None:
        rs = np.argsort(rcodes, kind="stable")
        sorted_r = rcodes[rs]
        starts = np.searchsorted(sorted_r, lcodes, "left")
        ends = np.searchsorted(sorted_r, lcodes, "right")
        counts = ends - starts
        total = int(counts.sum())
        cap = _guard_join_rows(total, ln, rn, join_type)
        if cap is not None:
            # BREAK: keep whole left rows up to the cap (partial result)
            keep = np.searchsorted(np.cumsum(counts), cap, "right")
            counts = counts[:keep]
            starts = starts[:keep]
            ln = keep
            left = take_block(left, np.arange(keep))
            total = int(counts.sum())
        lidx = np.repeat(np.arange(ln), counts)
        offs = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
        ridx = rs[np.repeat(starts, counts) + offs]

    if residual is not None and len(lidx):
        # evaluate over a gather of ONLY the residual's columns, not the
        # whole combined row set
        rb = _residual_block(left, right, lidx, ridx, residual)
        m = _truthy(eval_expr(residual, rb, len(lidx)), len(lidx))
        lidx, ridx = lidx[m], ridx[m]

    if join_type == "SEMI":
        return _project_side(left, schema, np.unique(lidx))
    if join_type == "ANTI":
        return _project_side(left, schema,
                             np.setdiff1d(np.arange(ln), np.unique(lidx)))

    if join_type in ("LEFT", "FULL"):
        matched_l = np.zeros(ln, dtype=bool)
        matched_l[lidx] = True
        extra_l = np.nonzero(~matched_l)[0]
        lidx = np.concatenate([lidx, extra_l])
        ridx = np.concatenate([ridx, np.full(len(extra_l), -1, dtype=np.int64)])
        device_used = False
    if join_type in ("RIGHT", "FULL"):
        matched_r = np.zeros(rn, dtype=bool)
        if len(ridx):
            matched_r[ridx[ridx >= 0]] = True
        extra_r = np.nonzero(~matched_r)[0]
        lidx = np.concatenate([lidx, np.full(len(extra_r), -1, dtype=np.int64)])
        ridx = np.concatenate([ridx, extra_r])
        device_used = False

    return _emit(left, right, lidx, ridx, schema, device_used)


def _expr_ids(e: EC, out: set) -> None:
    if e.is_identifier:
        out.add(e.identifier)
    elif e.is_function:
        for a in e.function.arguments:
            _expr_ids(a, out)


def _residual_block(left: Block, right: Block, lidx: np.ndarray,
                    ridx: np.ndarray, residual: EC) -> Block:
    """Gather only the columns the residual filter references (qualified or
    suffix-matchable), mirroring _combine's dup naming so eval_expr resolves
    identifiers identically to the old full-row path."""
    ids: set = set()
    _expr_ids(residual, ids)

    def want(c: str) -> bool:
        return c in ids or any(c.endswith("." + i) for i in ids)

    out: Block = {}
    for c, v in left.items():
        if want(c):
            out[c] = _gather_pad(np.asarray(v), lidx)
    for c, v in right.items():
        if not want(c):
            continue
        name = c if c not in out else c + "0"
        out[name] = _gather_pad(np.asarray(v), ridx)
    return out


def _project_side(side: Block, schema: list[str], sel: np.ndarray) -> Block:
    """SEMI/ANTI output: rows of one side, trimmed to the columns the
    output schema still needs."""
    proj = {c: side[c] for c in schema if c in side}
    return take_block(proj if proj else side, sel)


def _emit(left: Block, right: Block, lidx: np.ndarray, ridx: np.ndarray,
          schema: list[str], device_used: bool = False) -> Block:
    """The deferred gather: materialize exactly the schema's columns from
    the surviving index pairs. Right-side columns may appear under their
    own name or _combine's dup suffix (c+"0")."""
    if not schema:
        return _combine(left, right, lidx, ridx)
    plan: list[tuple] = []
    for name in schema:
        if name in left:
            plan.append((name, True, np.asarray(left[name])))
        elif name in right:
            plan.append((name, False, np.asarray(right[name])))
        elif name.endswith("0") and name[:-1] in right:
            plan.append((name, False, np.asarray(right[name[:-1]])))
        else:
            raise UnsupportedQueryError(
                f"join schema column {name!r} missing from inputs")
    out: Block = {}
    for is_left, idx in ((True, lidx), (False, ridx)):
        cols = {nm: a for nm, s, a in plan if s is is_left}
        if not cols:
            continue
        got = None
        if (device_used and len(cols) > 1 and len(idx)
                and all(a.dtype.kind in "iufb" for a in cols.values())
                and int(idx.min()) >= 0):
            from . import device_join
            got = device_join.gather_payload(cols, idx)
        if got is None:
            got = {nm: _gather_pad(a, idx) for nm, a in cols.items()}
        out.update(got)
    return {nm: out[nm] for nm, _, _ in plan}


def _int_like(c: np.ndarray) -> bool:
    # uint64 is excluded: viewing it as int64 would alias large values onto
    # real negatives from the other side
    return c.dtype.kind in "ib" or (c.dtype.kind == "u" and c.dtype.itemsize < 8)


_FALLBACK_LOCK = threading.RLock()  # string-code path without a JoinCtx


def _joint_codes(lcols, rcols, ln, rn, ctx=None):
    if len(lcols) == 1:
        lc, rc = lcols[0], rcols[0]
        if _int_like(lc) and _int_like(rc):
            # already-integer keys ARE their own codes (q8's lo_orderkey):
            # skip factorization entirely. Int columns cannot hold SQL
            # NULL, so no sentinel handling is needed here.
            if ctx is not None:
                ctx.bump("joint_codes_int_fastpath")
            return lc.astype(np.int64), rc.astype(np.int64)
        il, ir, _ = _column_codes(lc, rc, ln, ctx, 0)
        return il, ir
    codes_l = np.zeros(ln, dtype=np.int64)
    codes_r = np.zeros(rn, dtype=np.int64)
    for pos, (lc, rc) in enumerate(zip(lcols, rcols)):
        il, ir, m = _column_codes(lc, rc, ln, ctx, pos)
        mm = np.int64(max(m, 1))
        combined_l = codes_l * mm + il
        combined_r = codes_r * mm + ir
        _, inv2 = np.unique(np.concatenate([combined_l, combined_r]),
                            return_inverse=True)
        codes_l, codes_r = inv2[:ln].astype(np.int64), inv2[ln:].astype(np.int64)
    return codes_l, codes_r


def _column_codes(lc: np.ndarray, rc: np.ndarray, ln: int, ctx, pos: int):
    """Per-column join codes: int64 arrays in [0, m) where equal non-NULL
    values share a code and NULL keys never match across sides (left NULLs
    take code m-2, right NULLs m-1). Returns (lcodes, rcodes, m)."""
    if _int_like(lc) and _int_like(rc):
        both = np.concatenate([lc.astype(np.int64), rc.astype(np.int64)])
        _, inv = np.unique(both, return_inverse=True)
        m = int(inv.max(initial=-1)) + 1
        return (inv[:ln].astype(np.int64), inv[ln:].astype(np.int64), m)
    if lc.dtype.kind in "iufb" and rc.dtype.kind in "iufb":
        l64 = lc.astype(np.float64)
        r64 = rc.astype(np.float64)
        nl, nr = np.isnan(l64), np.isnan(r64)
        both = np.concatenate([np.where(nl, 0.0, l64), np.where(nr, 0.0, r64)])
        _, inv = np.unique(both, return_inverse=True)
        m = int(inv.max(initial=-1)) + 1
        il = inv[:ln].astype(np.int64)
        ir = inv[ln:].astype(np.int64)
        il[nl] = m      # NaN is SQL NULL: never equal, not even to itself
        ir[nr] = m + 1
        return il, ir, m + 2
    # string/object path: persistent value→code map (JoinCtx) so a second
    # partition of the same stage reuses codes instead of re-factorizing
    lock = ctx.lock if ctx is not None else _FALLBACK_LOCK
    with lock:
        mp = ctx.mapping(pos) if ctx is not None else {}
        if ctx is not None and mp:
            ctx.bump("joint_codes_cache_hits")
        nl = _null_mask(lc) if lc.dtype.kind == "O" else \
            np.zeros(len(lc), dtype=bool)
        nr = _null_mask(rc) if rc.dtype.kind == "O" else \
            np.zeros(len(rc), dtype=bool)
        il = _mapped_codes(np.where(nl, "", lc) if nl.any() else lc, mp)
        ir = _mapped_codes(np.where(nr, "", rc) if nr.any() else rc, mp)
        m = len(mp)
    il[nl] = m
    ir[nr] = m + 1
    return il, ir, m + 2


def _mapped_codes(arr: np.ndarray, mp: dict) -> np.ndarray:
    """Dense codes from a persistent value→code dict; values normalize
    through str() (matching the old astype(str) factorization, where 1 and
    "1" joined). Caller holds the map's lock."""
    get = mp.get

    def code(x):
        if type(x) is not str:
            x = str(x)
        c = get(x)
        if c is None:
            c = mp[x] = len(mp)
        return c

    return np.fromiter((code(x) for x in arr), dtype=np.int64,
                       count=len(arr))


def _combine(left: Block, right: Block, lidx: np.ndarray, ridx: np.ndarray) -> Block:
    out: Block = {}
    for c, v in left.items():
        out[c] = _gather_pad(np.asarray(v), lidx)
    for c, v in right.items():
        name = c if c not in out else c + "0"
        out[name] = _gather_pad(np.asarray(v), ridx)
    return out


def _gather_pad(v: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Gather with -1 → SQL NULL (NaN for numerics, None for objects)."""
    if not len(idx):
        return v[:0]
    mask = idx < 0
    out = v[np.clip(idx, 0, max(len(v) - 1, 0))]
    if mask.any():
        if v.dtype.kind in "iub":
            out = out.astype(np.float64)
            out[mask] = np.nan
        elif v.dtype.kind == "f":
            out = out.copy()
            out[mask] = np.nan
        else:
            out = out.astype(object)
            out[mask] = None
    return out


# -- window ------------------------------------------------------------------


def op_window(block: Block, calls: list[WindowCall], schema: list[str]) -> Block:
    n = block_len(block)
    out = dict(block)
    for call in calls:
        out[call.out_name] = _window_call(block, call, n)
    return out


def _desc_rank(r: np.ndarray) -> np.ndarray:
    """Descending sort key for one rank array. Floats negate exactly;
    integer keys dense-rank first (unique inverse) and negate in int64 —
    a float64 cast would collapse int64 keys above 2^53, and native int64
    negation overflows on INT64_MIN."""
    if r.dtype.kind == "f":
        return -r
    return -np.unique(r, return_inverse=True)[1].astype(np.int64)


def _order_rank_arrays(v: np.ndarray) -> list[np.ndarray]:
    """Sortable numeric arrays for one ORDER BY column, minor-first
    ([value, class]), matching _sort_key's NULL<numeric<string classes."""
    if v.dtype.kind in "iub":
        return [v]
    if v.dtype.kind == "f":
        nan = np.isnan(v)
        return [np.where(nan, 0.0, v), np.where(nan, 0, 1)]
    keys = [_sort_key(x) for x in v]
    uniq = {k: i for i, k in enumerate(sorted(set(keys)))}
    return [np.asarray([uniq[k] for k in keys], dtype=np.int64)]


def _window_call(block: Block, call: WindowCall, n: int) -> np.ndarray:
    """One window column, fully vectorized (reference:
    WindowAggregateOperator + window/ frames in pinot-query-runtime).
    Global lexsort (partition major, order keys minor) + segment-boundary
    arithmetic replaces per-group Python sorting; only exotic frames
    (sliding MIN/MAX etc.) drop to a per-partition loop."""
    spec: WindowSpec = call.spec
    pcols = [np.asarray(eval_expr(p, block, n)) for p in spec.partition_by]
    if pcols:
        codes, num, _ = group_codes(pcols)
    else:
        codes, num = np.zeros(n, dtype=np.int64), 1 if n else 0
    ocols = [(np.asarray(eval_expr(e, block, n)), asc) for e, asc in spec.order_by]

    if n == 0:
        return np.empty(0)

    # whole-partition aggregates don't need ordering at all: reuse the
    # grouped-aggregate kernels and broadcast per-group results
    if not spec.order_by and spec.frame is None and call.name not in (
            "rownumber", "rank", "denserank", "cumedist", "percentrank",
            "ntile", "lag", "lead", "firstvalue", "lastvalue"):
        per_group = _agg_grouped(AggCall(call.name, call.args, "$w"),
                                 block, codes, num, n)
        return _tighten_col(np.asarray(per_group, dtype=object)[codes])

    # global ordering: minor→major key list for lexsort (codes are primary)
    lex: list[np.ndarray] = []
    rank_arrays: list[list[np.ndarray]] = []  # per order col, asc direction
    for v, asc in ocols:
        rank_arrays.append(_order_rank_arrays(v))
    for (v, asc), ranks in zip(reversed(ocols), reversed(rank_arrays)):
        lex.extend(r if asc else _desc_rank(r) for r in ranks)
    lex.append(codes)
    order = np.lexsort(lex)

    scodes = codes[order]
    idx = np.arange(n, dtype=np.int64)
    pstart = np.empty(n, dtype=bool)
    pstart[0] = True
    pstart[1:] = scodes[1:] != scodes[:-1]
    pstart_idx = np.maximum.accumulate(np.where(pstart, idx, 0))
    is_last = np.empty(n, dtype=bool)
    is_last[:-1] = pstart[1:]
    is_last[-1] = True
    pend_idx = np.minimum.accumulate(
        np.where(is_last, idx, n - 1)[::-1])[::-1]
    pos = idx - pstart_idx
    k_arr = pend_idx - pstart_idx + 1

    newkey = pstart.copy()
    for ranks in rank_arrays:
        for r in ranks:
            rs = r[order]
            newkey[1:] |= rs[1:] != rs[:-1]

    out_sorted = np.asarray(_window_sorted(
        block, call, ocols, order, n, pstart, pstart_idx, pend_idx, pos,
        k_arr, newkey, idx))
    result = np.empty(n, dtype=out_sorted.dtype)
    result[order] = out_sorted
    return _tighten_col(result)


def _window_sorted(block, call, ocols, order, n, pstart, pstart_idx,
                   pend_idx, pos, k_arr, newkey, idx) -> np.ndarray:
    """Window values in sorted (partition, order-key) order."""
    name = call.name
    if name == "rownumber":
        return pos + 1
    if name in ("rank", "denserank", "percentrank"):
        lastkey_idx = np.maximum.accumulate(np.where(newkey, idx, 0))
        rank = lastkey_idx - pstart_idx + 1
        if name == "rank":
            return rank
        if name == "percentrank":
            return np.where(k_arr > 1, (rank - 1) / np.maximum(k_arr - 1, 1), 0.0)
        dense = np.cumsum(newkey)
        return dense - dense[pstart_idx] + 1
    if name == "cumedist":
        grp = np.cumsum(newkey) - 1  # global peer-group id, nondecreasing
        grp_end = np.searchsorted(grp, np.arange(grp[-1] + 2), "left")[1:] - 1
        return (grp_end[grp] - pstart_idx + 1) / k_arr
    if name == "ntile":
        buckets = int(call.args[0].literal) if call.args else 1
        return (pos * buckets // k_arr) + 1
    if name in ("lag", "lead"):
        v = np.asarray(eval_expr(call.args[0], block, n))[order]
        off = int(call.args[1].literal) if len(call.args) > 1 else 1
        default = call.args[2].literal if len(call.args) > 2 else None
        tgt = idx - off if name == "lag" else idx + off
        valid = (tgt >= pstart_idx) & (tgt <= pend_idx)
        out = np.empty(n, dtype=object)
        out[:] = v[np.clip(tgt, 0, n - 1)]
        out[~valid] = default
        return out
    if name in ("firstvalue", "lastvalue"):
        v = np.asarray(eval_expr(call.args[0], block, n))[order]
        return v[pstart_idx if name == "firstvalue" else pend_idx]

    # aggregates over the window frame
    frame = call.spec.frame
    if not call.spec.order_by and frame is None:
        per_group = _agg_grouped(AggCall(name, call.args, "$w"), block,
                                 np.cumsum(pstart) - 1, int(pstart.sum()), n)
        # codes in sorted space = partition ordinal
        return np.asarray(per_group, dtype=object)[np.cumsum(pstart) - 1]
    if frame is None:
        frame = ("RANGE", None, 0)
    kind, start, end = frame

    vals = [np.asarray(eval_expr(a, block, n))[order] for a in call.args]
    numeric = all(v.dtype.kind in "iufb" for v in vals)
    # vectorized running frames: UNBOUNDED PRECEDING → CURRENT ROW (+peers
    # for RANGE) for COUNT/SUM/AVG — prefix sums reproduce the sequential
    # left-to-right addition order of a from-scratch per-frame sum
    if start is None and end == 0 and name in ("count", "sum", "avg") \
            and (numeric or not vals):
        if kind == "RANGE" and call.spec.order_by:
            grp = np.cumsum(newkey) - 1
            grp_end = np.searchsorted(grp, np.arange(grp[-1] + 2), "left")[1:] - 1
            hi = grp_end[grp]  # frame end includes peers
        else:
            hi = idx
        if vals:
            nulls = _null_mask(vals[0])
            w = np.where(nulls, 0, vals[0])
            cnt_prefix = np.cumsum(~nulls)
        else:
            w = np.ones(n, dtype=np.int64)
            cnt_prefix = idx + 1
        counts = cnt_prefix[hi] - np.where(
            pstart_idx > 0, cnt_prefix[pstart_idx - 1], 0)
        if name == "count":
            return counts
        prefix = np.cumsum(w.astype(np.float64) if w.dtype.kind == "f"
                           else w.astype(np.int64))
        sums = prefix[hi] - np.where(pstart_idx > 0, prefix[pstart_idx - 1], 0)
        if name == "sum":
            return np.where(counts > 0, sums, np.nan) if vals else sums
        with np.errstate(invalid="ignore"):
            return np.where(counts > 0, sums / np.maximum(counts, 1), np.nan)

    # fallback: per-partition loop for exotic frames (sliding MIN/MAX, ...)
    sem = get_semantics(name)
    keys = None
    if kind == "RANGE" and call.spec.order_by:
        grp = np.cumsum(newkey) - 1
        grp_end = np.searchsorted(grp, np.arange(grp[-1] + 2), "left")[1:] - 1
    out = np.empty(n, dtype=object)
    for i in range(n):
        p0, p1 = pstart_idx[i], pend_idx[i]
        lo = p0 if start is None else max(p0, i + start)
        hi = p1 + 1 if end is None else min(p1 + 1, i + end + 1)
        if kind == "RANGE" and call.spec.order_by:
            hi = max(hi, grp_end[grp[i]] + 1)
        if name == "count" and not vals:
            out[i] = hi - lo
        else:
            seg = [v[lo:hi] for v in vals]
            out[i] = sem.finalize(host_state_full(name, seg, ()))
    return out


def _window_partition(block: Block, call: WindowCall, rows: np.ndarray, ocols):
    """Values for one ordered partition (rows are in window order)."""
    k = len(rows)
    name = call.name
    if name == "rownumber":
        return np.arange(1, k + 1)
    if name in ("rank", "denserank", "cumedist", "percentrank"):
        keys = [tuple(_sort_key(vals[rows][i]) for vals, _ in ocols) for i in range(k)]
        rank = np.empty(k, dtype=np.int64)
        dense = np.empty(k, dtype=np.int64)
        r = d = 0
        for i in range(k):
            if i == 0 or keys[i] != keys[i - 1]:
                r = i + 1
                d += 1
            rank[i] = r
            dense[i] = d
        if name == "rank":
            return rank
        if name == "denserank":
            return dense
        if name == "percentrank":
            return (rank - 1) / (k - 1) if k > 1 else np.zeros(k)
        # cumedist: fraction of rows ≤ current order key
        cume = np.empty(k, dtype=np.float64)
        i = 0
        while i < k:
            j = i
            while j + 1 < k and keys[j + 1] == keys[i]:
                j += 1
            cume[i:j + 1] = (j + 1) / k
            i = j + 1
        return cume
    if name == "ntile":
        buckets = int(call.args[0].literal) if call.args else 1
        return np.asarray([int(i * buckets / k) + 1 for i in range(k)])
    if name in ("lag", "lead"):
        v = np.asarray(eval_expr(call.args[0], block, block_len(block)))[rows]
        off = int(call.args[1].literal) if len(call.args) > 1 else 1
        default = call.args[2].literal if len(call.args) > 2 else None
        out = np.empty(k, dtype=object)
        for i in range(k):
            j = i - off if name == "lag" else i + off
            out[i] = v[j] if 0 <= j < k else default
        return out
    if name in ("firstvalue", "lastvalue"):
        v = np.asarray(eval_expr(call.args[0], block, block_len(block)))[rows]
        if k == 0:
            return v
        return np.full(k, v[0] if name == "firstvalue" else v[-1])
    # aggregates over the window frame
    vals = [np.asarray(eval_expr(a, block, block_len(block)))[rows] for a in call.args]
    sem = get_semantics(name)
    frame = call.spec.frame
    if not call.spec.order_by and frame is None:
        # whole partition
        state = host_state_full(name, vals, ()) if (vals or name != "count") \
            else len(rows)
        if name == "count" and not vals:
            return np.full(k, k)
        return np.full(k, sem.finalize(state))
    # running / framed aggregate over rows
    if frame is None:
        frame = ("RANGE", None, 0)
    _, start, end = frame
    keys = None
    if frame[0] == "RANGE" and call.spec.order_by:
        keys = [tuple(_sort_key(vals2[rows][x]) for vals2, _ in ocols)
                for x in range(k)]
    out = np.empty(k, dtype=object)
    for i in range(k):
        lo = 0 if start is None else max(0, i + start)
        hi = k if end is None else min(k, i + end + 1)
        if keys is not None:
            # peers share the frame end (RANGE CURRENT ROW includes ties)
            while hi < k and keys[hi] == keys[i]:
                hi += 1
        if name == "count" and not vals:
            out[i] = hi - lo
        else:
            seg = [v[lo:hi] for v in vals]
            out[i] = sem.finalize(host_state_full(name, seg, ()))
    return _tighten_col(out)


def _sort_key(x):
    if x is None:
        return (0, 0)
    if isinstance(x, (int, float, np.integer, np.floating)):
        if isinstance(x, float) and np.isnan(x):
            return (0, 0)
        return (1, float(x))
    return (2, str(x))


# -- set operations ----------------------------------------------------------


def op_setop(kind: str, all_: bool, left: Block, right: Block,
             schema: list[str]) -> Block:
    lrows = _rows_of(left, schema)
    rrows = _rows_of(right, schema)
    if kind == "UNION":
        rows = lrows + rrows if all_ else list(dict.fromkeys(lrows + rrows))
    elif kind == "INTERSECT":
        if all_:  # bag semantics: emit min(countL, countR) copies per row
            rcount = Counter(rrows)
            rows = []
            for r in lrows:
                if rcount.get(r, 0) > 0:
                    rcount[r] -= 1
                    rows.append(r)
        else:
            rset = set(rrows)
            rows = list(dict.fromkeys(r for r in lrows if r in rset))
    else:  # EXCEPT
        if all_:  # bag semantics: subtract counts, max(0, countL - countR)
            rcount = Counter(rrows)
            rows = []
            for r in lrows:
                if rcount.get(r, 0) > 0:
                    rcount[r] -= 1
                else:
                    rows.append(r)
        else:
            rset = set(rrows)
            rows = list(dict.fromkeys(r for r in lrows if r not in rset))
    return _rows_to_block(rows, schema)


def _rows_of(block: Block, schema: list[str]) -> list[tuple]:
    n = block_len(block)
    cols = [np.asarray(block[c]) for c in schema]
    return [tuple(_item(c[i]) for c in cols) for i in range(n)]


def _rows_to_block(rows: list[tuple], schema: list[str]) -> Block:
    if not rows:
        return {c: np.empty(0) for c in schema}
    out = {}
    for j, c in enumerate(schema):
        out[c] = _tighten_col(np.asarray([r[j] for r in rows], dtype=object))
    return out


def _item(v):
    return v.item() if isinstance(v, np.generic) else v


# -- sort --------------------------------------------------------------------


def op_sort(block: Block, sort_items: list[OrderItem], limit: Optional[int],
            offset: int) -> Block:
    n = block_len(block)
    if sort_items and n:
        idx = list(range(n))
        for it in reversed(sort_items):
            vals = np.asarray(eval_expr(it.expression, block, n))
            if vals.ndim == 0:
                continue
            idx.sort(key=lambda i: _sort_key(vals[i]), reverse=not it.ascending)
        block = take_block(block, np.asarray(idx))
    if limit is not None or offset:
        end = None if limit is None else offset + limit
        block = {c: np.asarray(v)[offset:end] for c, v in block.items()}
    return block
