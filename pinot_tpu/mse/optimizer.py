"""Rule-based logical-plan optimizer for the multi-stage engine.

Reference analogue: the Calcite rule stack Pinot applies before converting
RelNodes to PlanNodes (pinot-query-planner/.../planner/logical/ and Calcite's
FilterJoinRule / FilterProjectTransposeRule / FilterAggregateTransposeRule /
FilterSetOpTransposeRule). The single rule that matters most for a
distributed columnar engine is **filter pushdown**: a predicate that reaches
the TableScan side of an exchange (a) runs on the device engine inside the
leaf SSQE compile (runtime._try_ssqe) instead of row-at-a-time above a
shuffle, and (b) shrinks the shuffle itself.

Rules implemented (all pure tree rewrites over logical.PlanNode):

- Filter ∘ Filter        → merge conjuncts
- Filter ∘ Exchange      → Exchange ∘ Filter          (filters are row-local)
- Filter ∘ Project       → Project ∘ Filter           (substitute expressions)
- Filter ∘ Join          → push side-local conjuncts into the inner-side
                           input(s); outer sides keep their predicates above
                           the join (null-extension would change results)
- Filter ∘ Aggregate     → push conjuncts over group keys below the agg
- Filter ∘ SetOp         → copy the filter into every branch
- Filter ∘ Sort(no lim)  → push below the sort
- Filter ∘ Window        → push conjuncts over plain-identifier partition
                           keys below the window (per-partition predicate)

Conjuncts that no rule accepts stay where they are, so the pass is always
semantics-preserving; it never duplicates non-deterministic work because the
expression language has no non-deterministic functions.
"""

from __future__ import annotations

from typing import Optional

from ..query.expressions import ExpressionContext
from .logical import (
    AggregateNode,
    ExchangeNode,
    FilterNode,
    JoinNode,
    PlanNode,
    ProjectNode,
    SetOpNode,
    SortNode,
    TableScanNode,
    WindowNode,
    _split_and,
)

EC = ExpressionContext


def _and_all(conjs: list[EC]) -> Optional[EC]:
    cond = None
    for c in conjs:
        cond = c if cond is None else EC.for_function("and", cond, c)
    return cond


def _substitute(e: EC, mapping: dict[str, EC]) -> Optional[EC]:
    """Rewrite identifiers through a projection; None if unmappable."""
    if e.is_identifier:
        return mapping.get(e.identifier)
    if e.is_function:
        args = []
        for a in e.function.arguments:
            s = _substitute(a, mapping)
            if s is None:
                return None
            args.append(s)
        return EC.for_function(e.function.name, *args)
    return e  # literal


def _filter_over(node: PlanNode, conjs: list[EC]) -> PlanNode:
    cond = _and_all(conjs)
    if cond is None:
        return node
    return FilterNode([node], list(node.schema), condition=cond)


def push_filters(root: PlanNode) -> PlanNode:
    """Run the pushdown rules to fixpoint (single recursive descent — each
    conjunct only ever moves down, so one pass that re-pushes at every sink
    point is a fixpoint)."""
    return _push(root)


def _push(node: PlanNode) -> PlanNode:
    if isinstance(node, FilterNode) and node.condition is not None:
        child = node.inputs[0]
        # merge stacked filters first so all conjuncts travel together
        conjs = _split_and(node.condition)
        while isinstance(child, FilterNode) and child.condition is not None:
            conjs.extend(_split_and(child.condition))
            child = child.inputs[0]
        new_child, kept = _sink(child, conjs)
        new_child = _push(new_child)
        return _filter_over(new_child, kept)
    node.inputs = [_push(i) for i in node.inputs]
    return node


def _sink(child: PlanNode, conjs: list[EC]) -> tuple[PlanNode, list[EC]]:
    """Try to sink ``conjs`` into ``child``. Returns (rewritten child,
    conjuncts that must remain above it)."""
    if isinstance(child, ExchangeNode):
        # row-local predicates commute with any re-distribution: whatever
        # the inner node rejects still sits below the exchange boundary
        inner, kept = _sink(child.inputs[0], conjs)
        child.inputs = [_filter_over(inner, kept)]
        return child, []

    if isinstance(child, ProjectNode):
        mapping = dict(zip(child.schema, child.exprs))
        moved: list[EC] = []
        kept: list[EC] = []
        for c in conjs:
            s = _substitute(c, mapping)
            (moved.append(s) if s is not None else kept.append(c))
        if moved:
            inner, inner_kept = _sink(child.inputs[0], moved)
            child.inputs = [_filter_over(inner, inner_kept)]
        return child, kept

    if isinstance(child, JoinNode):
        lschema = set(child.inputs[0].schema)
        rschema = set(child.inputs[1].schema)
        jt = child.join_type
        push_left = jt in ("INNER", "LEFT", "CROSS", "SEMI", "ANTI")
        push_right = jt in ("INNER", "RIGHT", "CROSS")
        left_c: list[EC] = []
        right_c: list[EC] = []
        kept = []
        for c in conjs:
            cols = c.columns()
            if cols and cols <= lschema and push_left:
                left_c.append(c)
            elif cols and cols <= rschema and push_right:
                right_c.append(c)
            else:
                kept.append(c)
        if left_c:
            inner, ik = _sink(child.inputs[0], left_c)
            child.inputs[0] = _filter_over(inner, ik)
        if right_c:
            inner, ik = _sink(child.inputs[1], right_c)
            child.inputs[1] = _filter_over(inner, ik)
        return child, kept

    if isinstance(child, AggregateNode):
        group_names = set(child.schema[:len(child.group_exprs)])
        mapping = {n: g for n, g in zip(child.schema, child.group_exprs)}
        moved, kept = [], []
        for c in conjs:
            cols = c.columns()
            # a column-free conjunct (HAVING 1 = 0) must NOT sink: a global
            # aggregate over zero rows still emits one row, so pushing the
            # constant predicate below the agg would change the row count
            if cols and cols <= group_names:
                moved.append(_substitute(c, mapping))
            else:
                kept.append(c)
        if moved:
            inner, ik = _sink(child.inputs[0], moved)
            child.inputs = [_filter_over(inner, ik)]
        return child, kept

    if isinstance(child, SetOpNode):
        # branches were projected to the left schema at planning time, so
        # the predicate applies verbatim on every branch
        new_inputs = []
        for inp in child.inputs:
            inner, ik = _sink(inp, list(conjs))
            new_inputs.append(_filter_over(inner, ik))
        child.inputs = new_inputs
        return child, []

    if isinstance(child, SortNode) and child.limit is None:
        inner, ik = _sink(child.inputs[0], conjs)
        child.inputs = [_filter_over(inner, ik)]
        return child, []

    if isinstance(child, WindowNode):
        # a predicate may only sink below the window if it is constant
        # within EVERY call's partitions — node.partition_keys reflects just
        # calls[0], so intersect the per-call PARTITION BY key sets
        pkeys = None
        for call in child.calls:
            spec = call.spec
            ck = {p.identifier for p in (spec.partition_by if spec else [])
                  if p.is_identifier}
            pkeys = ck if pkeys is None else (pkeys & ck)
        pkeys = pkeys or set()
        moved, kept = [], []
        for c in conjs:
            cols = c.columns()
            (moved.append(c) if cols and cols <= pkeys else kept.append(c))
        if moved:
            inner, ik = _sink(child.inputs[0], moved)
            child.inputs = [_filter_over(inner, ik)]
        return child, kept

    if isinstance(child, (TableScanNode, FilterNode)):
        # scans keep the filter directly above them (the leaf SSQE compile
        # consumes Filter ∘ Scan); stacked filters merge in _push
        return child, conjs

    return child, conjs
