"""Multi-stage SQL parser: full relational dialect → ast.RelationalQuery.

Reference analogue: Calcite 1.37 parse+validate as driven by
pinot-query-planner/.../QueryEnvironment.java:179. Extends the single-stage
recursive-descent parser with: FROM-clause joins (INNER/LEFT/RIGHT/FULL/
CROSS + USING), derived tables, WITH CTEs, UNION/INTERSECT/EXCEPT [ALL],
window functions (`agg(...) OVER (PARTITION BY ... ORDER BY ... [frame])`),
and IN/NOT IN (SELECT ...) subqueries (kept as `__insubquery__` marker
functions; the planner rewrites them to SEMI/ANTI joins like Calcite's
SubQueryRemoveRule).
"""

from __future__ import annotations

from typing import Any, Optional

from ..query.expressions import ExpressionContext
from ..query.parser.sql import SqlParseError, Token, _Parser, _literal_value, tokenize
from .ast import (
    JoinRel,
    OrderItem,
    RelationalQuery,
    Relation,
    SelectItem,
    SelectStmt,
    SetOpStmt,
    Stmt,
    SubqueryRef,
    TableRef,
    WindowSpec,
)

_JOIN_TYPES = ("INNER", "LEFT", "RIGHT", "FULL", "CROSS", "SEMI", "ANTI")


class _RelationalParser(_Parser):
    def __init__(self, tokens: list[Token]):
        super().__init__(tokens)
        self.ctes: dict[str, Stmt] = {}

    # keep full dotted qualifiers (t.col) for join disambiguation
    def _make_identifier(self, parts: list[str]) -> str:
        return ".".join(parts)

    # -- entry -------------------------------------------------------------
    def parse_relational_query(self) -> RelationalQuery:
        options: dict[str, Any] = {}
        while self.at_kw("SET"):
            self.next()
            key = self.next().value
            self.expect_op("=")
            options[key] = _literal_value(self.next())
            self.accept_op(";")
        explain: Any = False
        if self.accept_kw("EXPLAIN"):
            # EXPLAIN IMPLEMENTATION [PLAN] [FOR]: execute the query and
            # annotate each stage with its runtime stats (rows in/out,
            # shuffled bytes, wall time)
            if self.accept_kw("IMPLEMENTATION"):
                explain = "implementation"
            elif self.accept_kw("ANALYZE"):
                # EXPLAIN ANALYZE: run with tracing armed and annotate each
                # stage with observed rows, shuffle volume, and phase ms
                explain = "analyze"
            else:
                explain = True
            self.accept_kw("PLAN")
            self.accept_kw("FOR")
        stmt = self._parse_statement()
        self.accept_op(";")
        if self.peek().kind != "eof":
            raise SqlParseError(f"trailing input at {self.peek().value!r}")
        return RelationalQuery(stmt, options, explain)

    def _parse_statement(self) -> Stmt:
        if self.accept_kw("WITH"):
            while True:
                name = self.next().value
                self.expect_kw("AS")
                self.expect_op("(")
                self.ctes[name.lower()] = self._parse_statement()
                self.expect_op(")")
                if not self.accept_op(","):
                    break
        return self._parse_set_expr()

    # -- set operations (left-associative; INTERSECT binds tighter) --------
    def _parse_set_expr(self) -> Stmt:
        left = self._parse_intersect_expr()
        while self.at_kw("UNION", "EXCEPT"):
            kind = self.next().upper
            all_ = self.accept_kw("ALL")
            if not all_:
                self.accept_kw("DISTINCT")
            right = self._parse_intersect_expr()
            left = SetOpStmt(kind, all_, left, right)
        self._parse_trailing_order_limit(left)
        return left

    def _parse_intersect_expr(self) -> Stmt:
        left = self._parse_query_primary()
        while self.at_kw("INTERSECT"):
            self.next()
            all_ = self.accept_kw("ALL")
            if not all_:
                self.accept_kw("DISTINCT")
            right = self._parse_query_primary()
            left = SetOpStmt("INTERSECT", all_, left, right)
        return left

    def _parse_query_primary(self) -> Stmt:
        if self.accept_op("("):
            s = self._parse_statement()
            self.expect_op(")")
            return s
        return self._parse_select_stmt()

    def _parse_trailing_order_limit(self, stmt: Stmt) -> None:
        """ORDER BY / LIMIT after a set-op chain attach to the whole set op."""
        if not isinstance(stmt, SetOpStmt):
            return
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            stmt.order_by = self._parse_order_items()
        if self.accept_kw("LIMIT"):
            first = self._expect_int()
            if self.accept_op(","):
                stmt.offset = first
                stmt.limit = self._expect_int()
            else:
                stmt.limit = first
                if self.accept_kw("OFFSET"):
                    stmt.offset = self._expect_int()

    # -- SELECT core -------------------------------------------------------
    def _parse_select_stmt(self) -> SelectStmt:
        self.expect_kw("SELECT")
        distinct = self.accept_kw("DISTINCT")
        items: list[SelectItem] = []
        while True:
            if self.peek().kind == "op" and self.peek().value == "*":
                self.next()
                items.append(SelectItem(ExpressionContext.for_identifier("*")))
            elif (self.peek().kind == "ident" and self.peek(1).kind == "op"
                  and self.peek(1).value == "." and self.peek(2).kind == "op"
                  and self.peek(2).value == "*"):
                alias = self.next().value
                self.next()
                self.next()
                items.append(SelectItem(ExpressionContext.for_identifier(alias + ".*")))
            else:
                e = self.parse_expression()
                win = self._maybe_window()
                items.append(SelectItem(e, self._maybe_alias(), win))
            if not self.accept_op(","):
                break
        self.expect_kw("FROM")
        from_rel = self._parse_from()
        stmt = SelectStmt(select_items=items, from_rel=from_rel, distinct=distinct)
        if self.accept_kw("WHERE"):
            stmt.where = self.parse_expression()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            stmt.group_by.append(self.parse_expression())
            while self.accept_op(","):
                stmt.group_by.append(self.parse_expression())
        if self.accept_kw("HAVING"):
            stmt.having = self.parse_expression()
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            stmt.order_by = self._parse_order_items()
        if self.accept_kw("LIMIT"):
            first = self._expect_int()
            if self.accept_op(","):
                stmt.offset = first
                stmt.limit = self._expect_int()
            else:
                stmt.limit = first
                if self.accept_kw("OFFSET"):
                    stmt.offset = self._expect_int()
        return stmt

    def _parse_order_items(self) -> list[OrderItem]:
        out: list[OrderItem] = []
        while True:
            e = self.parse_expression()
            asc = True
            if self.accept_kw("DESC"):
                asc = False
            else:
                self.accept_kw("ASC")
            nulls_last = None
            if self.accept_kw("NULLS"):
                if self.accept_kw("LAST"):
                    nulls_last = True
                else:
                    self.expect_kw("FIRST")
                    nulls_last = False
            out.append(OrderItem(e, asc, nulls_last))
            if not self.accept_op(","):
                break
        return out

    # -- FROM clause -------------------------------------------------------
    def _parse_from(self) -> Relation:
        rel = self._parse_table_primary()
        while True:
            join_type = None
            if self.at_kw(*_JOIN_TYPES):
                join_type = self.next().upper
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
            elif self.at_kw("JOIN"):
                self.next()
                join_type = "INNER"
            elif self.accept_op(","):  # comma join = cross join + WHERE
                join_type = "CROSS"
            else:
                return rel
            right = self._parse_table_primary()
            condition = None
            if self.accept_kw("ON"):
                condition = self.parse_expression()
            elif self.accept_kw("USING"):
                self.expect_op("(")
                cols = [self.next().value]
                while self.accept_op(","):
                    cols.append(self.next().value)
                self.expect_op(")")
                condition = None
                for c in cols:
                    eq = ExpressionContext.for_function(
                        "equals",
                        ExpressionContext.for_identifier(_rel_alias(rel) + "." + c
                                                         if _rel_alias(rel) else c),
                        ExpressionContext.for_identifier(_rel_alias(right) + "." + c
                                                         if _rel_alias(right) else c),
                    )
                    condition = eq if condition is None else \
                        ExpressionContext.for_function("and", condition, eq)
            elif join_type != "CROSS":
                raise SqlParseError(f"{join_type} JOIN requires ON or USING")
            rel = JoinRel(rel, right, join_type, condition)

    def _parse_table_primary(self) -> Relation:
        if self.accept_op("("):
            sub = self._parse_statement()
            self.expect_op(")")
            if self.accept_kw("AS"):
                t = self.next()
                if t.kind != "ident":
                    raise SqlParseError(
                        f"derived table needs an alias, got {t.value!r}")
                return SubqueryRef(sub, t.value)
            if self.peek().kind == "ident" \
                    and self.peek().upper not in _STOP_ALIAS:
                return SubqueryRef(sub, self.next().value)
            # anonymous derived table: synthesize an alias (Calcite allows
            # unaliased subqueries in FROM; columns resolve unqualified)
            self._anon_subq = getattr(self, "_anon_subq", 0) + 1
            return SubqueryRef(sub, f"$sq{self._anon_subq}")
        t = self.next()
        if t.kind != "ident":
            raise SqlParseError(f"expected table name, got {t.value!r}")
        parts = [t.value]
        while self.accept_op("."):
            parts.append(self.next().value)
        name = ".".join(parts)
        alias = None
        if self.accept_kw("AS"):
            alias = self.next().value
        elif self.peek().kind == "ident" and self.peek().upper not in _STOP_ALIAS:
            alias = self.next().value
        if name.lower() in self.ctes:
            return SubqueryRef(self.ctes[name.lower()], alias or name)
        return TableRef(name, alias)

    # -- window functions --------------------------------------------------
    def _maybe_window(self) -> Optional[WindowSpec]:
        if not self.accept_kw("OVER"):
            return None
        self.expect_op("(")
        spec = WindowSpec()
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            spec.partition_by.append(self.parse_expression())
            while self.accept_op(","):
                spec.partition_by.append(self.parse_expression())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                e = self.parse_expression()
                asc = True
                if self.accept_kw("DESC"):
                    asc = False
                else:
                    self.accept_kw("ASC")
                spec.order_by.append((e, asc))
                if not self.accept_op(","):
                    break
        if self.at_kw("ROWS", "RANGE"):
            kind = self.next().upper
            if self.accept_kw("BETWEEN"):
                start = self._parse_frame_bound()
                self.expect_kw("AND")
                end = self._parse_frame_bound()
            else:
                start = self._parse_frame_bound()
                end = 0  # CURRENT ROW
            spec.frame = (kind, start, end)
        self.expect_op(")")
        return spec

    def _parse_frame_bound(self) -> Optional[int]:
        """None = UNBOUNDED; int = signed row offset (0 = CURRENT ROW)."""
        if self.accept_kw("UNBOUNDED"):
            if self.accept_kw("PRECEDING") or self.accept_kw("FOLLOWING"):
                return None
            raise SqlParseError("expected PRECEDING/FOLLOWING after UNBOUNDED")
        if self.accept_kw("CURRENT"):
            self.expect_kw("ROW")
            return 0
        n = self._expect_int()
        if self.accept_kw("PRECEDING"):
            return -n
        self.expect_kw("FOLLOWING")
        return n

    # -- IN (SELECT ...) subqueries ----------------------------------------
    def _parse_comparison(self) -> ExpressionContext:
        # intercept `x [NOT] IN (SELECT ...)` before the base literal-IN path
        save = self.i
        left = self._parse_additive()
        negated = False
        if self.at_kw("NOT") and self.peek(1).upper == "IN":
            if self._in_select_ahead(2):
                self.next()
                negated = True
        if self.at_kw("IN") and (negated or self._in_select_ahead(1)):
            self.next()
            self.expect_op("(")
            sub = self._parse_statement()
            self.expect_op(")")
            name = "__notinsubquery__" if negated else "__insubquery__"
            return ExpressionContext.for_function(
                name, left, ExpressionContext.for_literal(sub))
        self.i = save
        return super()._parse_comparison()

    def _in_select_ahead(self, ahead: int) -> bool:
        t = self.peek(ahead)
        return (t.kind == "op" and t.value == "("
                and self.peek(ahead + 1).upper in ("SELECT", "WITH"))


_STOP_ALIAS = frozenset({
    "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "ON", "USING",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "SEMI", "ANTI",
    "UNION", "INTERSECT", "EXCEPT", "SET",
})


def _rel_alias(rel: Relation) -> Optional[str]:
    if isinstance(rel, TableRef):
        return rel.alias or rel.name
    if isinstance(rel, SubqueryRef):
        return rel.alias
    return None


def parse_relational(sql: str) -> RelationalQuery:
    """Parse the full multi-stage dialect."""
    return _RelationalParser(tokenize(sql)).parse_relational_query()
