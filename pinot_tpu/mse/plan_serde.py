"""Plan fragment serde: stage DAG ↔ JSON-compatible dicts.

Reference analogue: the reference ships serialized plan fragments to
workers over gRPC (pinot-common/src/main/proto/plan.proto, consumed by
QueryDispatcher.java:126 submit → PlanNode protobuf tree). Here the wire
form is plain JSON-compatible dicts: every PlanNode subclass gets a type
tag plus its fields, expressions serialize recursively. The contract is
explicit and versioned so a worker process can reconstruct and execute a
stage without sharing Python object identity with the dispatcher.
"""

from __future__ import annotations

from typing import Any, Optional

from ..query.expressions import ExpressionContext, ExpressionType, FunctionContext
from .ast import OrderItem, WindowSpec
from .fragmenter import MailboxReceiveNode, Stage
from .logical import (
    AggCall,
    AggregateNode,
    ExchangeNode,
    FilterNode,
    JoinNode,
    PlanNode,
    ProjectNode,
    SetOpNode,
    SortNode,
    TableScanNode,
    WindowCall,
    WindowNode,
)

SERDE_VERSION = 1

EC = ExpressionContext


# -- expressions --------------------------------------------------------------


def expr_to_json(e: Optional[EC]) -> Any:
    if e is None:
        return None
    if e.is_identifier:
        return {"t": "id", "v": e.identifier}
    if e.is_literal:
        v = e.literal
        if isinstance(v, bool) or v is None or isinstance(v, (int, float, str)):
            return {"t": "lit", "v": v}
        return {"t": "lit", "v": str(v)}
    return {"t": "fn", "n": e.function.name,
            "a": [expr_to_json(a) for a in e.function.arguments]}


def expr_from_json(d: Any) -> Optional[EC]:
    if d is None:
        return None
    t = d["t"]
    if t == "id":
        return EC.for_identifier(d["v"])
    if t == "lit":
        return EC.for_literal(d["v"])
    if t == "fn":
        return EC(ExpressionType.FUNCTION,
                  function=FunctionContext(d["n"],
                                           tuple(expr_from_json(a) for a in d["a"])))
    raise ValueError(f"bad expression tag {t!r}")


def _order_to_json(it: OrderItem) -> dict:
    return {"e": expr_to_json(it.expression), "asc": it.ascending,
            "nl": it.nulls_last}


def _order_from_json(d: dict) -> OrderItem:
    return OrderItem(expr_from_json(d["e"]), d["asc"], d.get("nl"))


def _wspec_to_json(s: Optional[WindowSpec]) -> Any:
    if s is None:
        return None
    return {"p": [expr_to_json(e) for e in s.partition_by],
            "o": [[expr_to_json(e), asc] for e, asc in s.order_by],
            "f": list(s.frame) if s.frame else None}


def _wspec_from_json(d: Any) -> Optional[WindowSpec]:
    if d is None:
        return None
    return WindowSpec(
        partition_by=[expr_from_json(e) for e in d["p"]],
        order_by=[(expr_from_json(e), asc) for e, asc in d["o"]],
        frame=tuple(d["f"]) if d.get("f") else None)


# -- plan nodes ---------------------------------------------------------------


def node_to_json(node: PlanNode) -> dict:
    d: dict = {"node": type(node).__name__, "schema": list(node.schema),
               "inputs": [node_to_json(i) for i in node.inputs]}
    if isinstance(node, TableScanNode):
        d.update(table=node.table, alias=node.alias,
                 source_columns=list(node.source_columns))
    elif isinstance(node, FilterNode):
        d.update(condition=expr_to_json(node.condition))
    elif isinstance(node, ProjectNode):
        d.update(exprs=[expr_to_json(e) for e in node.exprs])
    elif isinstance(node, AggregateNode):
        d.update(group_exprs=[expr_to_json(e) for e in node.group_exprs],
                 agg_calls=[{"n": c.name, "a": [expr_to_json(a) for a in c.args],
                             "o": c.out_name, "x": list(c.extra),
                             "f": expr_to_json(c.condition)
                             if c.condition is not None else None}
                            for c in node.agg_calls])
    elif isinstance(node, JoinNode):
        d.update(join_type=node.join_type, left_keys=list(node.left_keys),
                 right_keys=list(node.right_keys),
                 residual=expr_to_json(node.residual))
    elif isinstance(node, WindowNode):
        d.update(calls=[{"n": c.name, "a": [expr_to_json(a) for a in c.args],
                         "s": _wspec_to_json(c.spec), "o": c.out_name}
                        for c in node.calls],
                 partition_keys=[expr_to_json(e) for e in node.partition_keys])
    elif isinstance(node, SortNode):
        d.update(sort_items=[_order_to_json(it) for it in node.sort_items],
                 limit=node.limit, offset=node.offset)
    elif isinstance(node, SetOpNode):
        d.update(kind=node.kind, all=node.all)
    elif isinstance(node, ExchangeNode):
        d.update(dist=node.dist, keys=list(node.keys), pfunc=node.pfunc,
                 n_partitions=node.n_partitions)
    elif isinstance(node, MailboxReceiveNode):
        d.update(from_stage=node.from_stage, dist=node.dist, keys=list(node.keys),
                 pfunc=node.pfunc, n_partitions=node.n_partitions)
    else:
        raise TypeError(f"unserializable plan node {type(node).__name__}")
    return d


def node_from_json(d: dict) -> PlanNode:
    kind = d["node"]
    inputs = [node_from_json(i) for i in d["inputs"]]
    schema = list(d["schema"])
    if kind == "TableScanNode":
        return TableScanNode(inputs, schema, table=d["table"], alias=d["alias"],
                             source_columns=list(d["source_columns"]))
    if kind == "FilterNode":
        return FilterNode(inputs, schema, condition=expr_from_json(d["condition"]))
    if kind == "ProjectNode":
        return ProjectNode(inputs, schema,
                           exprs=[expr_from_json(e) for e in d["exprs"]])
    if kind == "AggregateNode":
        return AggregateNode(
            inputs, schema,
            group_exprs=[expr_from_json(e) for e in d["group_exprs"]],
            agg_calls=[AggCall(c["n"], [expr_from_json(a) for a in c["a"]],
                               c["o"], tuple(c["x"]),
                               condition=expr_from_json(c["f"])
                               if c.get("f") is not None else None)
                       for c in d["agg_calls"]])
    if kind == "JoinNode":
        return JoinNode(inputs, schema, join_type=d["join_type"],
                        left_keys=list(d["left_keys"]),
                        right_keys=list(d["right_keys"]),
                        residual=expr_from_json(d["residual"]))
    if kind == "WindowNode":
        return WindowNode(
            inputs, schema,
            calls=[WindowCall(c["n"], [expr_from_json(a) for a in c["a"]],
                              _wspec_from_json(c["s"]), c["o"])
                   for c in d["calls"]],
            partition_keys=[expr_from_json(e) for e in d["partition_keys"]])
    if kind == "SortNode":
        return SortNode(inputs, schema,
                        sort_items=[_order_from_json(it) for it in d["sort_items"]],
                        limit=d["limit"], offset=d["offset"])
    if kind == "SetOpNode":
        return SetOpNode(inputs, schema, kind=d["kind"], all=d["all"])
    if kind == "ExchangeNode":
        return ExchangeNode(inputs, schema, dist=d["dist"], keys=list(d["keys"]),
                            pfunc=d.get("pfunc"),
                            n_partitions=d.get("n_partitions"))
    if kind == "MailboxReceiveNode":
        return MailboxReceiveNode(inputs, schema, from_stage=d["from_stage"],
                                  dist=d["dist"], keys=list(d["keys"]),
                                  pfunc=d.get("pfunc"),
                                  n_partitions=d.get("n_partitions"))
    raise ValueError(f"unknown plan node tag {kind!r}")


# -- stages -------------------------------------------------------------------


def stage_to_json(stage: Stage) -> dict:
    return {"v": SERDE_VERSION, "stage_id": stage.stage_id,
            "root": node_to_json(stage.root), "send_dist": stage.send_dist,
            "send_keys": list(stage.send_keys),
            "parent_stage": stage.parent_stage,
            "child_stages": list(stage.child_stages),
            "send_pfunc": stage.send_pfunc,
            "send_schema": stage.send_schema}


def stage_from_json(d: dict) -> Stage:
    if d.get("v") != SERDE_VERSION:
        raise ValueError(f"unsupported plan serde version {d.get('v')}")
    return Stage(d["stage_id"], node_from_json(d["root"]), d["send_dist"],
                 list(d["send_keys"]), d["parent_stage"],
                 list(d["child_stages"]),
                 send_pfunc=d.get("send_pfunc"),
                 send_schema=d.get("send_schema"))
