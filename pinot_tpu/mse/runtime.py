"""MSE stage runtime: executes the fragmented stage DAG.

Reference analogue: pinot-query-runtime's QueryRunner.processQuery:210 —
build an OpChain per stage, run leaf stages through the single-stage engine
(ServerPlanRequestUtils → ServerQueryExecutorV1Impl, results adapted by
LeafStageTransferableBlockOperator.java:87), run intermediate stages as
operator trees, connect everything through the mailbox service.

Leaf compilation is where the TPU shows up: a leaf stage whose shape is
``[partial Aggregate] ← [Filter] ← Scan`` compiles to a single-stage
QueryContext and runs on the device engine (whole-segment kernels +
segment combine); only stages above the first exchange run host-side.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ..engine.aggregation import UnsupportedQueryError
from ..query.context import QueryContext
from ..spi.trace import TRACING
from ..query.converter import FilterConversionError, filter_from_expression
from ..query.expressions import ExpressionContext
from .fragmenter import MailboxReceiveNode, Stage, receive_nodes
from .logical import (
    AggregateNode,
    FilterNode,
    JoinNode,
    PlanNode,
    ProjectNode,
    SetOpNode,
    SortNode,
    TableScanNode,
    WindowNode,
)
from . import device_join
from .mailbox import (
    Block,
    MailboxService,
    block_len,
    concat_blocks,
    hash_partition,
)
from .operators import (
    JoinCtx,
    op_aggregate,
    op_filter,
    op_join,
    op_project,
    op_setop,
    op_sort,
    op_window,
    pop_join_overflow,
)

EC = ExpressionContext

_LEAF_LIMIT = 1_000_000_000  # effectively unlimited (leaf results feed merges)


def _mse_threads() -> int:
    """Worker threads available to one stage's partitions. NumPy releases
    the GIL on the hot kernels (argsort/unique/gather), so partition-level
    threading pays off even on CPython."""
    try:
        return max(1, int(os.environ.get("PINOT_TPU_MSE_THREADS",
                                         os.cpu_count() or 1)))
    except ValueError:
        return 1


class LeafError(Exception):
    """A leaf SSQE pushdown executed and FAILED (timeout, kill, engine
    error) — distinct from UnsupportedQueryError (shape can't push down,
    generic path takes over) so failures propagate instead of silently
    re-running without their query options."""


class StageRunner:
    """Executes one fragmented plan. ``execute_query`` is the single-stage
    engine entry (QueryContext → BrokerResponse); ``read_table`` returns raw
    column arrays for generic scans."""

    def __init__(self, stages: list[Stage], parallelism: int,
                 execute_query: Callable, read_table: Callable,
                 query_options: Optional[dict] = None,
                 execute_columnar: Optional[Callable] = None):
        self.stages = stages
        self.parallelism = max(1, parallelism)
        self.execute_query = execute_query
        self.read_table = read_table
        # optional columnar leaf entry (QueryContext → (block, stats) or
        # None): a selection leaf that skips Python row materialization
        self.execute_columnar = execute_columnar
        # per-(stage, key-columns) joint-code cache + counters, shared by
        # every partition of a join stage (operators.JoinCtx)
        self._join_ctx = JoinCtx()
        self._overflow_lock = threading.Lock()
        # SET options from the MSE statement, forwarded into leaf SSQE
        # pushdowns (enableNullHandling / numGroupsLimit / timeoutMs act at
        # the single-stage engine)
        self.query_options = dict(query_options or {})
        self.mailbox = MailboxService()
        self.stats = {"stages": len(stages), "leaf_ssqe_pushdowns": 0,
                      "num_docs_scanned": 0, "total_docs": 0,
                      "num_device_dispatches": 0, "num_compiles": 0,
                      "num_groups_limit_reached": False}
        # per-stage observability: stage_id → counters (rows in/out,
        # shuffled rows/bytes, wall time) — the attribution plane for
        # EXPLAIN IMPLEMENTATION and bench's mse_stage_stats
        self.stage_stats: dict[int, dict] = {}
        # device-resident join data path: stage_id → FusedStagePlan for
        # stages that run the fused partition→join→aggregate kernels, and
        # child stage_id → consumer stage_id for stages whose output stays
        # a same-process device handoff (mailbox send_raw) instead of a
        # hash shuffle. Populated by run(); always empty for the
        # distributed per-stage runners (they never call run()).
        self._fused: dict[int, object] = {}
        self._handoff: dict[int, int] = {}
        # join stages absorbed INTO a fused consumer (multi-join chains):
        # absorbed stage_id → fused stage_id. Absorbed stages never
        # execute — the fused stage expands their join from the leaf
        # blocks, which hand off raw straight to it.
        self._absorbed: dict[int, int] = {}

    def _sstat(self, stage_id: int) -> dict:
        st = self.stage_stats.get(stage_id)
        if st is None:
            st = self.stage_stats[stage_id] = {
                "workers": 0, "leaf_pushdown": False, "rows_in": 0,
                "rows_out": 0, "shuffled_rows": 0, "shuffled_bytes": 0,
                "cross_stage_bytes": 0, "device_partition_ms": 0.0,
                "join_impl": "", "host_crossings": 0, "wall_ms": 0.0}
        return st

    def _null_handling_requested(self) -> bool:
        opt = self.query_options.get("enableNullHandling")
        return opt is True or str(opt).lower() == "true"

    def _device_join_option(self) -> Optional[bool]:
        """SET deviceJoin = true (force) / false (opt out) / unset (auto:
        size-gated at consume time)."""
        for k, v in self.query_options.items():
            if k.lower() == "devicejoin":
                s = str(v).lower()
                if s in ("0", "false", "off"):
                    return False
                if s in ("1", "true", "on", "force") or v is True:
                    return True
        return None

    def _plan_fused(self) -> None:
        """Mark the stages that take the device-resident join path. Only
        the in-process mailbox can hand device arrays across a stage
        boundary by reference; the distributed RoutedMailbox keeps the
        DataTable wire path (its runners never call run(), so this is
        also never reached there)."""
        if type(self.mailbox) is not MailboxService:
            return
        if self._device_join_option() is False:
            return
        if device_join.env_mode() in ("0", "off", "false"):
            return
        by_id = {s.stage_id: s for s in self.stages}
        for stage in self.stages:
            if stage.stage_id == 0:
                continue
            plan = device_join.plan_fused_stage(stage)
            if plan is None:
                continue
            self._fused[stage.stage_id] = plan
            for recv in plan.receives:
                self._handoff[recv.from_stage] = stage.stage_id
        # multi-join chains: a fused stage whose input is itself a plain
        # INNER-join stage absorbs it — the child never executes, its leaf
        # blocks hand off raw to the fused stage, and the chain expands as
        # composed row indices (values gathered on device)
        for sid, plan in self._fused.items():
            if plan.residual:
                continue
            for pos, recv in zip(("left", "right"), plan.receives):
                src = self._build_chain(by_id.get(recv.from_stage), by_id, 0)
                if src is None or not self._chain_resolvable(plan, pos, src):
                    continue
                plan.chain_side, plan.chain = pos, src
                for csid in src.stage_ids():
                    self._absorbed[csid] = sid
                    self._handoff.pop(csid, None)
                for leaf in src.leaf_receives():
                    self._handoff[leaf.from_stage] = sid
                break   # at most one chained input per fused stage

    def _build_chain(self, stage, by_id: dict, depth: int):
        """ChainSource for an absorbable join stage, nesting absorbable
        grandchildren (up to 3 chained joins) when the level's own join
        keys stay resolvable through the nested source."""
        if stage is None or depth > 2 or stage.stage_id in self._fused:
            return None
        src = device_join.plan_chain_source(stage)
        if src is None:
            return None
        for attr, keys in (("left", src.join_node.left_keys),
                           ("right", src.join_node.right_keys)):
            recv = getattr(src, attr)
            nested = self._build_chain(by_id.get(recv.from_stage), by_id,
                                       depth + 1)
            if nested is not None and all(
                    device_join.chain_resolve(nested, k) is not None
                    for k in keys):
                setattr(src, attr, nested)
        return src

    def _chain_resolvable(self, plan, pos: str, src) -> bool:
        """Every column the fused stage needs from the chained side must
        reconstruct from the leaf blocks."""
        join = plan.join_node
        need = list(join.left_keys if pos == "left" else join.right_keys)
        chain_rel = "probe" if plan.probe_side == pos else "build"
        if chain_rel == "probe":
            need += [c for _, c in plan.group_cols]
        need += [c for _k, rel, c, _o in plan.aggs
                 if rel == chain_rel and c is not None]
        return all(device_join.chain_resolve(src, c) is not None
                   for c in need)

    # -- topology ----------------------------------------------------------
    def workers_of(self, stage: Stage) -> int:
        nodes = receive_nodes(stage.root)
        nparts = [n.n_partitions for n in nodes
                  if n.dist == "partitioned" and n.n_partitions]
        if nparts:
            # colocated join: one worker per table partition
            return max(nparts)
        return self.parallelism if any(n.dist == "hash" for n in nodes) else 1

    # -- run ---------------------------------------------------------------
    def run(self) -> Block:
        self._plan_fused()
        # children have higher ids than parents: run bottom-up. Absorbed
        # chain stages never run — their fused consumer expands them.
        for stage in sorted(self.stages, key=lambda s: -s.stage_id):
            if stage.stage_id == 0 or stage.stage_id in self._absorbed:
                continue
            self._run_stage(stage)
        self.stats["join_ctx"] = dict(self._join_ctx.counters)
        broker = self.stages[0]
        return self.mailbox.receive(broker.child_stages[0], 0, 0,
                                    broker.root.schema)

    def _trim_to_send(self, stage: Stage, block: Block) -> Block:
        """Drop columns the consuming stage never references (the pruned
        exchange schema) — e.g. a filter column a leaf consumed locally."""
        ss = stage.send_schema
        if ss is None or set(ss) >= set(block.keys()):
            return block
        return {c: block[c] for c in ss if c in block}

    def _worker_block(self, stage: Stage, w: int) -> Block:
        """One partition worker: execute the stage tree and capture the
        thread-local BREAK-overflow flag before leaving the thread (a
        pooled worker's flag would otherwise be stranded in the pool)."""
        block = self._exec(stage.root, stage, w)
        if pop_join_overflow():
            with self._overflow_lock:
                self.stats["join_overflow"] = True
        return block

    def _run_stage(self, stage: Stage) -> None:
        if TRACING.active_trace() is None:
            return self._run_stage_inner(stage)
        # one span per stage so broker reduce → stage → nested leaf-engine
        # family_dispatch spans line up in one tree
        with TRACING.scope(f"mse_stage:{stage.stage_id}") as span:
            self._run_stage_inner(stage)
            st = self._sstat(stage.stage_id)
            for k in ("workers", "rows_in", "rows_out", "shuffled_rows",
                      "shuffled_bytes", "cross_stage_bytes",
                      "device_partition_ms", "join_impl", "host_crossings",
                      "leaf_pushdown"):
                if k in st and st[k] != "":
                    span.set_attribute(k, st[k])

    def _run_stage_inner(self, stage: Stage) -> None:
        import time

        parent = self.stages[stage.parent_stage]
        parent_workers = 1 if parent.stage_id == 0 else self.workers_of(parent)
        st = self._sstat(stage.stage_id)
        t0 = time.perf_counter()
        pushed = None
        blocks = None
        if stage.stage_id in self._fused:
            blocks = self._run_fused_stage(stage, st)
        elif stage.is_leaf:
            pushed = self._try_ssqe(stage)
            if pushed is None and self._null_handling_requested():
                # the generic scan path has no null semantics — failing is
                # honest; silently flipping to basic mode per plan shape
                # is not
                raise UnsupportedQueryError(
                    "enableNullHandling requires this leaf stage to push "
                    "down to the single-stage engine")
        if pushed is not None:
            self.stats["leaf_ssqe_pushdowns"] += 1
            st["workers"] = 1
            st["leaf_pushdown"] = True
            blocks = [pushed]
        elif blocks is None:
            st["workers"] = self.workers_of(stage)
            pool_size = min(st["workers"], _mse_threads())
            if pool_size > 1:
                # independent partitions of the stage execute concurrently;
                # sends stay in worker order below, so mailbox contents are
                # deterministic regardless of completion order
                caller_trace = TRACING.active_trace()
                caller_span = TRACING.current_span()

                def run_worker(w):
                    if caller_trace is None:
                        return self._worker_block(stage, w)
                    # traces are thread-local: nest pool-worker scopes
                    # under this stage's span
                    TRACING.adopt(caller_trace, caller_span)
                    try:
                        return self._worker_block(stage, w)
                    finally:
                        TRACING.adopt(None)

                with ThreadPoolExecutor(max_workers=pool_size) as pool:
                    futs = [pool.submit(run_worker, w)
                            for w in range(st["workers"])]
                    blocks = [f.result() for f in futs]
            else:
                blocks = [self._worker_block(stage, w)
                          for w in range(st["workers"])]
        # a stage feeding a fused consumer hands its block over whole: the
        # consumer partitions on device (or re-partitions itself on
        # fallback), so nothing is encoded or split here. A chain leaf's
        # direct parent is an ABSORBED stage — its blocks skip that stage
        # entirely and hand off to the fused consumer.
        target = self._handoff.get(stage.stage_id)
        handoff = target is not None and (
            target == parent.stage_id
            or self._absorbed.get(parent.stage_id) == target)
        for block in blocks:
            st["rows_out"] += block_len(block)
            trimmed = self._trim_to_send(stage, block)
            if handoff:
                self.mailbox.send_raw(stage.stage_id, target, trimmed)
            else:
                self.mailbox.send_partitioned(
                    stage.stage_id, parent.stage_id, trimmed,
                    stage.send_dist, stage.send_keys, parent_workers,
                    pfunc=stage.send_pfunc)
        st["wall_ms"] += (time.perf_counter() - t0) * 1000
        st["shuffled_rows"] = self.mailbox.sent_rows[stage.stage_id]
        st["shuffled_bytes"] = self.mailbox.sent_bytes[stage.stage_id]
        st["cross_stage_bytes"] = getattr(
            self.mailbox, "cross_bytes",
            self.mailbox.sent_bytes)[stage.stage_id]

    def _run_fused_stage(self, stage: Stage, st: dict) -> list[Block]:
        """The device-resident join stage: both inputs arrive as raw
        same-process handoffs; the whole Aggregate←Join subtree runs as
        three device dispatches (partition ×2, fused join+agg) with one
        host fetch. Any gate failure re-creates the hash shuffle the
        handoff skipped and runs the exact host operators per partition —
        bit-identical to the never-fused plan."""
        import time

        from ..spi.metrics import SERVER_METRICS, ServerMeter

        plan = self._fused[stage.stage_id]
        recv_l, recv_r = plan.receives
        chain_sids = list(plan.chain.stage_ids()) if plan.chain else []

        def _recv(r):
            return self.mailbox.receive_raw(r.from_stage, stage.stage_id,
                                            r.schema)

        leaf_blocks: dict[int, tuple] = {}
        rows_in = 0
        sides: dict[str, object] = {}
        for pos, recv in (("left", recv_l), ("right", recv_r)):
            if plan.chain_side == pos:
                for leaf in plan.chain.leaf_receives():
                    blk = _recv(leaf)
                    leaf_blocks[id(leaf)] = (blk, block_len(blk))
                    rows_in += block_len(blk)
                sides[pos] = None     # expanded below
            else:
                sides[pos] = _recv(recv)
                rows_in += block_len(sides[pos])
        st["rows_in"] += rows_in
        forced = self._device_join_option() is True \
            or device_join.env_mode() in ("1", "on", "force", "true")
        eligible = forced or rows_in >= device_join.fused_min_rows()
        ctx = self._join_ctx.for_stage(stage.stage_id)

        def get_leaf(r):
            return leaf_blocks[id(r)]

        if eligible:
            t0 = time.perf_counter()
            result = None
            try:
                if plan.chain_side is not None:
                    # host expands the chain's pair INDICES (the same
                    # argsort expansion the host joiner runs); values stay
                    # put and gather on device
                    view = device_join.expand_chain(plan.chain, get_leaf,
                                                    ctx)
                    if view is not None:
                        sides[plan.chain_side] = view
                        result = device_join.run_fused(
                            sides["left"], sides["right"], plan, ctx)
                else:
                    result = device_join.run_fused(
                        sides["left"], sides["right"], plan, ctx)
            except Exception as e:
                device_join.note_failure(e)
            if result is not None:
                block, info = result
                st["device_partition_ms"] += (time.perf_counter() - t0) * 1000
                st["join_impl"] = "device-fused"
                st["workers"] = 1
                st["host_crossings"] = 1
                self.stats["num_device_dispatches"] += info["dispatches"]
                SERVER_METRICS.add_meter(ServerMeter.MSE_DEVICE_JOINS)
                SERVER_METRICS.add_meter(ServerMeter.MSE_FUSED_STAGES,
                                         1 + len(chain_sids))
                SERVER_METRICS.add_meter(ServerMeter.MSE_HOST_CROSSINGS)
                for csid in chain_sids:
                    self._sstat(csid)["join_impl"] = "device-fused"
                return [block]
            SERVER_METRICS.add_meter(ServerMeter.MSE_DEVICE_JOIN_FALLBACKS)
            from ..engine.perf_ledger import PERF_LEDGER

            PERF_LEDGER.note_event("device-join-host")
        # host fallback: same hash routing the children would have used,
        # then the exact host join+aggregate operators per partition. An
        # absorbed chain re-materializes through the host joiner itself —
        # exact semantics including the join-row guards.
        st["join_impl"] = "host"
        for csid in chain_sids:
            self._sstat(csid)["join_impl"] = "host"
        if plan.chain_side is not None:
            sides[plan.chain_side] = device_join.host_expand_chain(
                plan.chain, get_leaf, ctx)
            if pop_join_overflow():
                self.stats["join_overflow"] = True
        left, right = sides["left"], sides["right"]
        workers = self.workers_of(stage)
        st["workers"] = workers
        lparts = hash_partition(left, recv_l.keys, workers)
        rparts = hash_partition(right, recv_r.keys, workers)
        blocks = []
        for lw, rw in zip(lparts, rparts):
            joined = op_join(lw, rw, plan.join_node.join_type,
                             plan.join_node.left_keys,
                             plan.join_node.right_keys,
                             plan.join_node.residual,
                             plan.join_node.schema, ctx=ctx)
            if pop_join_overflow():
                self.stats["join_overflow"] = True
            blocks.append(op_aggregate(
                joined, plan.agg_node.group_exprs, plan.agg_node.agg_calls,
                plan.agg_node.schema))
        return blocks

    # -- node execution ----------------------------------------------------
    def _exec(self, node: PlanNode, stage: Stage, worker: int) -> Block:
        if isinstance(node, MailboxReceiveNode):
            block = self.mailbox.receive(node.from_stage, stage.stage_id,
                                         worker, node.schema)
            self._sstat(stage.stage_id)["rows_in"] += block_len(block)
            return block
        if isinstance(node, TableScanNode):
            return self._scan(node)
        if isinstance(node, FilterNode):
            return op_filter(self._exec(node.inputs[0], stage, worker), node.condition)
        if isinstance(node, ProjectNode):
            return op_project(self._exec(node.inputs[0], stage, worker),
                              node.schema, node.exprs)
        if isinstance(node, AggregateNode):
            if self._can_stream_aggregate(node):
                return self._streaming_aggregate(node, stage, worker)
            return op_aggregate(self._exec(node.inputs[0], stage, worker),
                                node.group_exprs, node.agg_calls, node.schema)
        if isinstance(node, JoinNode):
            left = self._exec(node.inputs[0], stage, worker)
            right = self._exec(node.inputs[1], stage, worker)
            return op_join(left, right, node.join_type, node.left_keys,
                           node.right_keys, node.residual, node.schema,
                           ctx=self._join_ctx.for_stage(stage.stage_id))
        if isinstance(node, WindowNode):
            return op_window(self._exec(node.inputs[0], stage, worker),
                             node.calls, node.schema)
        if isinstance(node, SortNode):
            return op_sort(self._exec(node.inputs[0], stage, worker),
                           node.sort_items, node.limit, node.offset)
        if isinstance(node, SetOpNode):
            left = self._exec(node.inputs[0], stage, worker)
            right = self._exec(node.inputs[1], stage, worker)
            return op_setop(node.kind, node.all, left, right, node.schema)
        raise UnsupportedQueryError(f"MSE cannot execute node {type(node).__name__}")

    # rows buffered before an incremental collapse in a streaming aggregate
    STREAM_COLLAPSE_ROWS = 262_144

    def _can_stream_aggregate(self, node: AggregateNode) -> bool:
        """True for the FINAL-merge shape of a two-phase aggregation: input
        is a mailbox receive, every call is a re-mergeable merge fn
        (sum/min/max — applying the aggregate to its own output is a no-op
        on semantics), and the output schema equals the input schema so the
        collapsed partial feeds back in. This is the streaming consumer of
        the pipelined shuffle: chunks partial-merge as they arrive instead
        of materializing the whole mailbox (reference: AggregateOperator
        consuming TransferableBlocks incrementally)."""
        child = node.inputs[0]
        return (isinstance(child, MailboxReceiveNode)
                and bool(node.agg_calls)
                and all(c.name in ("sum", "min", "max") and c.condition is None
                        and not c.extra for c in node.agg_calls)
                and all(g.is_identifier for g in node.group_exprs)
                and list(node.schema) == list(child.schema))

    def _streaming_aggregate(self, node: AggregateNode, stage: Stage,
                             worker: int) -> Block:
        recv: MailboxReceiveNode = node.inputs[0]
        buf: list[Block] = []
        buf_rows = 0

        def collapse() -> Block:
            return op_aggregate(
                concat_blocks(buf, list(recv.schema)),
                node.group_exprs, node.agg_calls, node.schema)

        for chunk in self.mailbox.stream(recv.from_stage, stage.stage_id,
                                         worker):
            buf.append(chunk)
            self._sstat(stage.stage_id)["rows_in"] += block_len(chunk)
            buf_rows += block_len(chunk)
            if buf_rows >= self.STREAM_COLLAPSE_ROWS:
                buf = [collapse()]
                buf_rows = block_len(buf[0])
        return collapse()

    def _scan(self, node: TableScanNode) -> Block:
        cols = self.read_table(node.table, node.source_columns)
        return {q: cols[s] for q, s in zip(node.schema, node.source_columns)}

    # -- leaf → single-stage compilation -----------------------------------
    def _try_ssqe(self, stage: Stage) -> Optional[Block]:
        """Compile ``[partial Aggregate] ← [Filter]* ← Scan`` to a
        QueryContext and run it on the single-stage (device) engine."""
        node = stage.root
        agg: Optional[AggregateNode] = None
        if isinstance(node, AggregateNode):
            agg = node
            node = node.inputs[0]
        filters = []
        while isinstance(node, FilterNode):
            filters.append(node.condition)
            node = node.inputs[0]
        if not isinstance(node, TableScanNode):
            return None
        scan = node
        unq = dict(zip(scan.schema, scan.source_columns))

        try:
            cond = None
            for f in filters:
                cond = f if cond is None else EC.for_function("and", cond, f)
            fctx = None
            if cond is not None:
                fctx = filter_from_expression(_unqualify(cond, unq))

            if agg is None:
                # plain scan+filter leaf: the filter is pushed into the
                # QueryContext, so only the columns the exchange actually
                # ships (send_schema) need to be selected — consumed
                # filter columns stay on the server
                names = [c for c in (stage.send_schema or list(scan.schema))
                         if c in unq] or list(scan.schema)
                select = [EC.for_identifier(unq[c]) for c in names]
                qc = QueryContext(
                    table_name=scan.table, select_expressions=select,
                    aliases=[None] * len(select), filter=fctx, limit=_LEAF_LIMIT,
                    query_options=dict(self.query_options)).finish()
                if self.execute_columnar is not None:
                    got = self.execute_columnar(qc)
                    if got is not None:
                        cols, cstats = got
                        self.stats["num_docs_scanned"] += \
                            cstats.get("num_docs_scanned", 0)
                        self.stats["total_docs"] += cstats.get("total_docs", 0)
                        for k in ("num_device_dispatches", "num_compiles"):
                            self.stats[k] += cstats.get(k, 0)
                        self.stats["leaf_columnar"] = \
                            self.stats.get("leaf_columnar", 0) + 1
                        return {q: cols[unq[q]] for q in names}
                resp = self.execute_query(qc)
                return self._resp_to_block(resp, names)

            select: list[EC] = []
            for g in agg.group_exprs:
                select.append(_unqualify(g, unq))
            for call in agg.agg_calls:
                if call.extra:
                    return None
                args = [_unqualify(a, unq) for a in call.args] or \
                    [EC.for_identifier("*")]
                e = EC.for_function(call.name, *args)
                if call.condition is not None:
                    # AGG(x) FILTER (WHERE cond) — the V1 grammar's form
                    e = EC.for_function(
                        "filter", e, _unqualify(call.condition, unq))
                select.append(e)
            qc = QueryContext(
                table_name=scan.table, select_expressions=select,
                aliases=[None] * len(select),
                group_by_expressions=[_unqualify(g, unq) for g in agg.group_exprs],
                filter=fctx, limit=_LEAF_LIMIT,
                query_options=dict(self.query_options))
            resp = self.execute_query(qc.finish())
            return self._resp_to_block(resp, list(agg.schema))
        except (FilterConversionError, UnsupportedQueryError, KeyError):
            return None

    def _resp_to_block(self, resp, names: list[str]) -> Optional[Block]:
        if resp.exceptions:
            if all("UnsupportedQueryError" in e for e in resp.exceptions):
                # shape the single-stage engine can't plan (e.g. strict-tpu
                # backend + raw-string predicate): generic path takes over
                raise UnsupportedQueryError(
                    f"leaf stage unsupported: {resp.exceptions}")
            # a leaf that RAN and failed (timeout, kill) must fail the
            # query, not silently re-run on the generic path with no
            # deadline and basic semantics
            raise LeafError(f"leaf stage failed: {resp.exceptions}")
        self.stats["num_docs_scanned"] += resp.num_docs_scanned
        self.stats["total_docs"] += resp.total_docs
        for k in ("num_device_dispatches", "num_compiles"):
            self.stats[k] += getattr(resp, k, 0)
        if getattr(resp, "num_groups_limit_reached", False):
            self.stats["num_groups_limit_reached"] = True
        rt = resp.result_table
        if rt is None:
            # empty result still counts as a successful pushdown — a None
            # here would re-run the leaf on the generic path
            return {name: np.empty(0, object) for name in names}
        rows = rt.rows
        out: Block = {}
        for j, name in enumerate(names):
            ctype = rt.schema.column_types[j] if j < len(rt.schema.column_types) else "STRING"
            vals = [r[j] for r in rows]
            if ctype in ("INT", "LONG", "TIMESTAMP"):
                out[name] = np.asarray(vals, dtype=np.int64) if vals else np.empty(0, np.int64)
            elif ctype in ("FLOAT", "DOUBLE"):
                out[name] = np.asarray(vals, dtype=np.float64) if vals else np.empty(0, np.float64)
            elif ctype == "BOOLEAN":
                out[name] = np.asarray(vals, dtype=bool) if vals else np.empty(0, bool)
            else:
                out[name] = np.asarray(vals, dtype=object) if vals else np.empty(0, object)
        return out


def _unqualify(e: EC, mapping: dict) -> EC:
    if e.is_identifier:
        name = mapping.get(e.identifier)
        if name is None:
            raise KeyError(e.identifier)
        return EC.for_identifier(name)
    if e.is_function:
        return EC.for_function(e.function.name,
                               *[_unqualify(a, mapping) for a in e.function.arguments])
    return e
