"""Single-pass fused dense group-by: filter + gid + limbs INSIDE the MXU
kernel.

Why: a Pallas call is opaque to XLA — nothing fuses INTO it. The two-step
dense path (ops/kernels.py `_dense_group_by_entry` → mxu_groupby.limb_sums)
therefore materializes every intermediate to HBM: the widened id planes,
the filter mask, the int32 gid vector, and one int8 limb plane per 7 bits
of every summed column. For SSB q2 at 100M rows that turns an 800MB
problem into ~2.8GB of HBM traffic. This kernel reads each RAW column
plane (uint8/uint16/int32, exactly as resident in HBM) once per block,
computes mask → gid → limb planes in VMEM, and feeds them straight into
the same Kronecker-factored one-hot matmul chain (mxu_groupby._matmul_tail)
— no intermediate ever touches HBM.

Scope (the common hot shape; everything else stays on the two-step path):
  * filter: None / TRUE / a CONJUNCTION of closed dict-id or raw-int32
    intervals (EQ, BETWEEN, range — what sorted dictionaries normalize
    predicates to at plan time; reference: the predicate→dict-id-interval
    rewrite replacing PredicateEvaluator trees)
  * group key: plain id-plane slots with static strides
  * aggregations: COUNT and int32-exact SUMs (the MXU limb recipe)

Runtime bounds ride a scalar-prefetch vector (SMEM), so one compiled
kernel serves every literal value of the same query shape. Failures
(unsupported dtype on a given Mosaic version, VMEM pressure) permanently
fall back to the two-step path via note_failure() — the dispatcher retries
the same program unfused.

Reference analogue being replaced: the per-block filter→transform→
aggregate operator chain (pinot-core/.../query/aggregation/groupby/
DefaultGroupByExecutor.java:191) — collapsed into one systolic-array pass.
"""

from __future__ import annotations

import functools
import logging
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import mxu_groupby
from ..engine import ir

logger = logging.getLogger(__name__)

_I32_MIN = -(1 << 31)
_I32_MAX = (1 << 31) - 1
_MAX_TERMS = 8

_STATE: dict = {"error": None}


def active() -> str:
    """'' = off | 'tpu' = real kernel | 'interpret' = interpret mode (CPU
    tests). Controlled by PINOT_TPU_FUSED: auto (default, on when the TPU
    backend is live) | 1 | 0 | interpret."""
    if _STATE["error"] is not None:
        return ""
    mode = os.environ.get("PINOT_TPU_FUSED", "auto")
    if mode == "0":
        return ""
    if mode == "interpret":
        return "interpret"
    if mode in ("auto", "1"):
        return "tpu" if mxu_groupby.backend_platform() == "tpu" else ""
    return ""


def note_failure(e: BaseException) -> None:
    if _STATE["error"] is None:
        logger.warning("fused group-by disabled after failure: %s", e)
        _STATE["error"] = e


@dataclass(frozen=True)
class FusedPlan:
    # ("iv", slot, lo_param|None, hi_param|None, lo_inc, hi_inc)
    # | ("runs", slot, runs_param, n_runs) — a dict-LUT predicate (IN,
    #   LIKE, NOT...) whose boolean LUT compresses to n_runs contiguous
    #   dict-id ranges; the [lo0,hi0,lo1,hi1,...] i64 array rides in
    #   params[runs_param] (appended at dispatch — lut_run_params)
    terms: tuple
    groups: tuple  # (slot, stride)
    # ("count",) | ("limb", slot, shift) | ("neg", slot)
    planes: tuple
    # per agg: ("count",) | ("sum", ((plane_idx, shift), ...), neg_idx|None)
    recipes: tuple
    slots: tuple  # unique slots the kernel loads, in ref order


MAX_LUT_RUNS = 4


def lut_run_params(program: ir.Program, params):
    """Dispatch-time (host, CONCRETE params) analysis: for each Lut filter
    leaf whose boolean LUT is a union of ≤ MAX_LUT_RUNS contiguous
    dict-id ranges, build the [lo,hi,...] run array. Returns
    (extra_params, meta) — meta is the STATIC ((lut_param, appended_param
    index, n_runs), ...) that keys the jit trace; ((), ()) when any Lut
    doesn't compress (the program then stays on the two-step path)."""
    if program.mode != "group_by" or program.filter is None:
        return (), ()
    extra: list = []
    meta: list = []
    base = len(params)
    for leaf in _filter_leaves(program.filter):
        if not isinstance(leaf, ir.Lut):
            continue
        lut = np.asarray(params[leaf.lut_param])
        if lut.dtype != np.bool_ or lut.ndim != 1:
            return (), ()
        idx = np.flatnonzero(lut)
        if idx.size == 0:
            runs = np.asarray([1, 0], dtype=np.int64)  # empty interval
        else:
            breaks = np.flatnonzero(np.diff(idx) > 1)
            starts = np.concatenate([[idx[0]], idx[breaks + 1]])
            ends = np.concatenate([idx[breaks], [idx[-1]]])
            if len(starts) > MAX_LUT_RUNS:
                return (), ()
            runs = np.empty(2 * len(starts), dtype=np.int64)
            runs[0::2] = starts
            runs[1::2] = ends
        meta.append((leaf.lut_param, base + len(extra), len(runs) // 2))
        extra.append(runs)
    return tuple(extra), tuple(meta)


def _filter_leaves(node):
    if isinstance(node, ir.FAnd):
        for c in node.children:
            yield from _filter_leaves(c)
    else:
        yield node


def plan(program: ir.Program, arrays,
         lut_meta: tuple = ()) -> Optional[FusedPlan]:
    """Static shape analysis; `arrays` contributes only dtypes/ndims (known
    at trace time). Returns None when the program leaves the fused scope.
    ``arrays=None`` checks program STRUCTURE only (EXPLAIN eligibility:
    Lut leaves count as eligible — run-compression is a dispatch-time
    property). ``lut_meta`` is lut_run_params' static description of the
    appended run arrays."""
    if program.mode != "group_by" or program.mv_group_slot is not None:
        return None
    if program.group_vexprs or not program.group_slots:
        return None

    def plane_ok(slot, payload=False):
        if arrays is None:
            return True
        a = arrays[slot]
        if getattr(a, "ndim", None) != 1:
            return False
        dt = a.dtype
        if payload:
            return dt == jnp.int32
        return dt in (jnp.uint8, jnp.uint16, jnp.int32)

    runs_of = {m[0]: m for m in lut_meta}
    terms = []
    if program.filter is not None:
        for leaf in _filter_leaves(program.filter):
            if isinstance(leaf, ir.FConst):
                if leaf.value:
                    continue
                return None
            if isinstance(leaf, ir.Lut):
                if leaf.mv or not plane_ok(leaf.ids_slot):
                    return None
                m = runs_of.get(leaf.lut_param)
                if m is None:
                    if arrays is None:  # EXPLAIN structural eligibility
                        terms.append(("runs", leaf.ids_slot, -1, 1))
                        continue
                    return None
                terms.append(("runs", leaf.ids_slot, m[1], m[2]))
                continue
            if not isinstance(leaf, ir.Interval):
                return None
            ve = leaf.vexpr
            if not isinstance(ve, (ir.IdsCol, ir.Col)) or \
                    not plane_ok(ve.slot):
                return None
            terms.append(("iv", ve.slot, leaf.lo_param, leaf.hi_param,
                          leaf.lo_inclusive, leaf.hi_inclusive))
    if len(terms) > _MAX_TERMS:
        return None

    for slot in program.group_slots:
        if not plane_ok(slot):
            return None
    groups = tuple(zip(program.group_slots, program.group_strides))

    # limb policy comes from the ONE shared helper so fused and two-step
    # sums can never drift (kernels._limb_shifts)
    from .kernels import _limb_shifts

    planes: list = [("count",)]
    recipes: list = []
    b = mxu_groupby.LIMB_BITS
    for agg in program.aggs:
        if agg.kind == "count":
            recipes.append(("count",))
            continue
        if agg.kind != "sum" or not isinstance(agg.vexpr, ir.Col) or \
                not plane_ok(agg.vexpr.slot, payload=True):
            return None
        slot = agg.vexpr.slot
        shifts, nonneg = _limb_shifts(agg.vmin, agg.vmax, b)
        refs = tuple((len(planes) + k, s) for k, s in enumerate(shifts))
        planes.extend(("limb", slot, s) for s in shifts)
        neg_idx = None
        if not nonneg:
            neg_idx = len(planes)
            planes.append(("neg", slot))
        recipes.append(("sum", refs, neg_idx))

    num_segments = program.num_groups + 1
    if not mxu_groupby.supports(num_segments, len(planes)):
        return None

    slots = []
    for term in terms:
        if term[1] not in slots:
            slots.append(term[1])
    for s, _ in groups:
        if s not in slots:
            slots.append(s)
    for p in planes:
        if p[0] in ("limb", "neg") and p[1] not in slots:
            slots.append(p[1])
    return FusedPlan(tuple(terms), groups, tuple(planes), tuple(recipes),
                     tuple(slots))


def execute(fp: FusedPlan, program: ir.Program, arrays, params, num_docs,
            n: int, row_offset, interpret: bool):
    """Run the fused kernel; returns the `_run_dense_group_by` output
    contract: (counts_i64, per-agg columns...)."""
    num_segments = program.num_groups + 1
    # runtime scalar vector: [num_docs, row_offset, lo0, hi0, lo1, hi1, ..].
    # Bounds normalize to CLOSED i32 intervals over integer planes:
    #   * float bounds round INWARD (v >= 5.5 ≡ v >= 6; v <= 5.5 ≡ v <= 5;
    #     open bounds v > 5.0 ≡ v >= 6) — matching the two-step path's
    #     float-space compare on integer values
    #   * bounds outside int32 collapse to an EMPTY interval when they
    #     exclude the whole plane (lo > I32_MAX / hi < I32_MIN), never to
    #     a spurious point-match at the clipped extreme
    svals = [jnp.asarray(num_docs, jnp.int64),
             jnp.asarray(row_offset, jnp.int64)]
    for term in fp.terms:
        if term[0] == "runs":
            # dict-id run bounds: already closed i32-safe intervals
            _, _slot, runs_param, n_runs = term
            arr = jnp.asarray(params[runs_param])
            for k in range(2 * n_runs):
                svals.append(arr[k].astype(jnp.int64))
            continue
        _, _slot, lo_p, hi_p, lo_inc, hi_inc = term
        if lo_p is None:
            lo = jnp.int64(_I32_MIN)
        else:
            p = jnp.asarray(params[lo_p])
            if jnp.issubdtype(p.dtype, jnp.inexact):
                lo = (jnp.ceil(p) if lo_inc
                      else jnp.floor(p) + 1).astype(jnp.int64)
            else:
                lo = p.astype(jnp.int64) + (0 if lo_inc else 1)
        if hi_p is None:
            hi = jnp.int64(_I32_MAX)
        else:
            p = jnp.asarray(params[hi_p])
            if jnp.issubdtype(p.dtype, jnp.inexact):
                hi = (jnp.floor(p) if hi_inc
                      else jnp.ceil(p) - 1).astype(jnp.int64)
            else:
                hi = p.astype(jnp.int64) - (0 if hi_inc else 1)
        empty = (lo > _I32_MAX) | (hi < _I32_MIN) | (lo > hi)
        svals.append(jnp.where(empty, jnp.int64(1),
                               jnp.clip(lo, _I32_MIN, _I32_MAX)))
        svals.append(jnp.where(empty, jnp.int64(0),
                               jnp.clip(hi, _I32_MIN, _I32_MAX)))
    scalars = jnp.stack([v.astype(jnp.int32) for v in svals])

    planes_in = tuple(arrays[s] for s in fp.slots)
    sums = _fused_limb_sums(fp, planes_in, scalars, num_segments, n,
                            interpret)

    counts = sums[0]
    outputs = [counts]
    for r in fp.recipes:
        if r[0] == "count":
            outputs.append(counts)
            continue
        _, refs, neg_idx = r
        total = jnp.zeros(counts.shape[0], dtype=jnp.int64)
        for idx, shift in refs:
            total = total + (sums[idx] << shift)
        if neg_idx is not None:
            total = total - (sums[neg_idx] << 32)
        outputs.append(total.astype(jnp.float64))
    return tuple(outputs)


@functools.partial(
    jax.jit, static_argnames=("fp", "num_segments", "n", "interpret"))
def _fused_limb_sums(fp: FusedPlan, planes_in, scalars, num_segments: int,
                     n: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s1, bpsb, nsb, n_pad = mxu_groupby._geometry(n, num_segments)
    if n_pad != n:
        # zero padding is safe: the kernel's row-validity test masks pad
        # rows to the trash slot with zero plane contributions
        planes_in = tuple(jnp.pad(p, (0, n_pad - p.shape[0]))
                          for p in planes_in)
    nb_total = n_pad // (mxu_groupby.SUBLANES * mxu_groupby.LANES)
    planes2 = tuple(
        p.reshape(nb_total, mxu_groupby.SUBLANES, mxu_groupby.LANES)
        for p in planes_in)

    zero = np.int32(0)
    row_spec = pl.BlockSpec(
        (mxu_groupby.G_TILES, mxu_groupby.SUBLANES, mxu_groupby.LANES),
        lambda i, j, s: (i * bpsb + j, zero, zero))
    num_planes = len(fp.planes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nsb, bpsb),
        in_specs=[row_spec] * len(planes2),
        out_specs=pl.BlockSpec((1, num_planes * s1, mxu_groupby.LANES),
                               lambda i, j, s: (i, zero, zero)),
    )
    out = pl.pallas_call(
        functools.partial(_kernel, fp, s1, bpsb, num_segments),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (nsb, num_planes * s1, mxu_groupby.LANES), jnp.int32),
        interpret=interpret,
    )(scalars, *planes2)
    total = out.astype(jnp.int64).sum(axis=0)
    return total.reshape(num_planes, s1 * mxu_groupby.LANES)[:, :num_segments]


def _kernel(fp: FusedPlan, s1: int, bpsb: int, num_segments: int,
            scal_ref, *rest):
    from jax.experimental import pallas as pl

    LANES = mxu_groupby.LANES
    nb = mxu_groupby.G_TILES * mxu_groupby.SUBLANES
    refs = dict(zip(fp.slots, rest[: len(fp.slots)]))
    out_ref = rest[len(fp.slots)]
    i = pl.program_id(0)
    j = pl.program_id(1)

    # widened (nb, 128) i32 view of each raw plane — ONE load per plane
    loaded = {slot: r[...].reshape(nb, LANES).astype(jnp.int32)
              for slot, r in refs.items()}

    # row validity: global row id vs num_docs (covers segment tail AND the
    # zero padding added by _fused_limb_sums), plus shard row_offset
    base = (i * bpsb + j) * mxu_groupby.BLOCK_ROWS
    rows = (base
            + jax.lax.broadcasted_iota(jnp.int32, (nb, LANES), 0) * LANES
            + jax.lax.broadcasted_iota(jnp.int32, (nb, LANES), 1))
    m = (rows + scal_ref[1]) < scal_ref[0]
    si = 2  # scalar cursor: [num_docs, row_offset, <term bounds...>]
    for term in fp.terms:
        if term[0] == "runs":
            p = loaded[term[1]]
            tm = jnp.zeros_like(m)
            for _ in range(term[3]):
                tm |= (p >= scal_ref[si]) & (p <= scal_ref[si + 1])
                si += 2
            m &= tm
        else:
            p = loaded[term[1]]
            m &= (p >= scal_ref[si]) & (p <= scal_ref[si + 1])
            si += 2

    gid = jnp.zeros((nb, LANES), dtype=jnp.int32)
    for slot, stride in fp.groups:
        gid = gid + loaded[slot] * jnp.int32(stride)
    gid = jnp.where(m, gid, jnp.int32(num_segments - 1))

    dt = mxu_groupby.PLANE_DTYPE
    bmask = jnp.uint32((1 << mxu_groupby.LIMB_BITS) - 1)
    mats = []
    for pd in fp.planes:
        if pd[0] == "count":
            mats.append(m.astype(dt))
        elif pd[0] == "limb":
            _, slot, shift = pd
            u = jnp.where(m, loaded[slot], 0).astype(jnp.uint32)
            mats.append(((u >> shift) & bmask).astype(dt))
        else:  # neg
            mats.append((m & (loaded[pd[1]] < 0)).astype(dt))

    mxu_groupby._matmul_tail(gid, mats, s1, out_ref, j)
