"""Device-resident join pipeline kernels: hash partition + fused join+agg.

The MSE shuffle-join path (mse/device_join.py orchestrates, this module
holds the jitted programs) replaces the host loop of
``hash_partition → per-partition argsort join → pair gather → bincount``
with two device dispatches per join stage:

1. **Partition kernel** — ``partition_id = mix(key_code) % P`` on device,
   then the ragged per-partition row sets packed into a padded
   ``[P, cap]`` index plane (the Ragged Paged Attention shape; pow2
   ``cap`` so compiled programs are shared across row counts, pad slots
   masked by the per-partition counts). The probe side only needs
   partition grouping, so it rides a scatter counting sort (no
   ``lax.sort`` at all); the build side must come out ascending-key per
   plane slice — one stable single-key sort on the packed
   ``partition * B + key`` composite when the key span fits
   ``pack_base(P)``, a two-key (partition, key) sort otherwise — so the
   join kernel never sorts again.
2. **Fused join+aggregate kernel** — vmapped over the P partition planes:
   binary-search every probe row against its pre-sorted build plane and
   aggregate match contributions straight into a padded
   ``[G]`` group table (count / sum via run prefix-sums, min/max via
   key-run segment scatter; small group tables aggregate through a
   one-hot masked reduction instead of element scatters). Join pairs are
   NEVER materialized; only the packed group table crosses back to the
   host — one fetch per stage.

Bit-identity discipline (the PR-12 mesh-combine rule): callers gate the
fused path to integer-typed aggregate arguments. Integer-valued f64 sums
are exact (and therefore reduction-order-free) below 2^53, so the
device's probe-order/partition-order accumulation is bit-identical to the
host's ``np.bincount`` row-order accumulation; min/max and count are
order-independent by construction. Float-typed args fall back to host.
"""

from __future__ import annotations

import functools

import numpy as np

from . import kernels

# output plane layout: one row per aggregate, then these three bookkeeping
# rows (output-row weight per group; matched-pair count per group;
# [total_pairs, overflow, ...] metadata)
META_ROWS = 3

# pad-slot sentinels: distinct per side so a padded probe row can never
# binary-search onto a padded build row
_SENT_PROBE = 1 << 62
_SENT_BUILD = (1 << 62) + 1

_DISPATCHES = [0]


def dispatches() -> int:
    """Lifetime fused-pipeline device dispatches in this process."""
    return _DISPATCHES[0]


def bucket(n: int) -> int:
    """Power-of-2 padding bucket (shared-compile discipline)."""
    b = 1
    while b < n:
        b <<= 1
    return max(b, 8)


def _mix_mod(codes, P: int):
    """Partition id of each int64 key code: multiplicative (Fibonacci)
    hash so dense code spaces spread across partitions, then mod P. Pure
    routing — both sides of a join use the same function, which is the
    only property the shuffle needs."""
    import jax.numpy as jnp

    h = codes.astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15)
    return ((h >> jnp.uint64(33)) % jnp.uint64(P)).astype(jnp.int32)


def host_partition_counts(codes: np.ndarray, P: int) -> np.ndarray:
    """Exact per-partition row counts of ``_mix_mod`` on the host (uint64
    wraparound matches the device kernel bit-for-bit). Callers size the
    plane cap off ``counts.max()`` so planes fit the REAL distribution —
    no headroom guess, and key skew (NULL buckets, heavy hitters) only
    overflows when it wouldn't fit any plane at all."""
    h = codes.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    return np.bincount(((h >> np.uint64(33)) % np.uint64(P)).astype(np.int64),
                       minlength=P)


def pack_base(P: int) -> int:
    """Largest pow2 ``B`` such that packed keys ``part * B + rel`` stay in
    int64 for part ≤ P (the pad partition) and 0 ≤ rel < B."""
    B = 1
    while B * 2 * (P + 1) <= (1 << 63) - 1:
        B <<= 1
    return B


@functools.cache
def _jit_partition_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)  # engine-wide invariant
    # n/cmin are TRACED so one compiled program serves every row count in
    # a pow2 bucket; P/cap/sort_mode are static (they shape the program)
    return functools.partial(
        jax.jit, static_argnames=("P", "cap", "sort_mode"))(
        _partition_kernel)


def _partition_kernel(codes, n, cmin, P: int, cap: int, sort_mode: str):
    import jax
    import jax.numpy as jnp

    N = codes.shape[0]
    valid = jnp.arange(N) < n
    # invalid (pad) rows route past the last real partition so they fall
    # off the end of every plane slice
    part = jnp.where(valid, _mix_mod(codes, P), P).astype(jnp.int32)
    iota = jnp.arange(N, dtype=jnp.int32)
    if sort_mode == "packed":
        # one single-key sort on part*B + (code - cmin): ascending packed
        # == ascending (partition, key), stable on row id — the plane is
        # ascending-key, at ~70% the cost of the two-key sort. Callers
        # gate on key span < B so rel never overflows into the part digit.
        B = jnp.int64(pack_base(P))
        packed = jnp.where(valid, part.astype(jnp.int64) * B
                           + (codes - cmin), jnp.int64(P) * B)
        ksorted, order = jax.lax.sort((packed, iota), num_keys=1)
        bounds = jnp.searchsorted(
            ksorted, jnp.arange(P + 1, dtype=jnp.int64) * B, side="left")
    elif sort_mode == "keyed":
        # wide-span keys: two-key sort, same ascending-key plane
        psorted, _, order = jax.lax.sort((part, codes, iota), num_keys=2)
        bounds = jnp.searchsorted(
            psorted, jnp.arange(P + 1, dtype=jnp.int32), side="left")
    else:  # "rows": partition grouping only, original row order within —
        # a counting sort (running rank per partition + one scatter)
        # beats lax.sort ~2.5x and keeps the same stable row order
        onehot = part[:, None] == jnp.arange(P, dtype=jnp.int32)[None, :]
        rank = jnp.cumsum(onehot.astype(jnp.int32), axis=0)
        counts = rank[-1]
        myrank = jnp.take_along_axis(
            rank, jnp.clip(part, 0, P - 1)[:, None], axis=1)[:, 0] - 1
        # pad rows dump onto a clipped slot; row ids are ≥ 0 so the .max
        # scatter lets any real occupant win, and overflowed partitions
        # (counts > cap) surface through the join kernel's flag
        pp = jnp.where(valid, part, P - 1)
        slot = jnp.where(valid, jnp.clip(myrank, 0, cap - 1), cap - 1)
        plane = jnp.zeros((P, cap), dtype=jnp.int32).at[pp, slot].max(
            jnp.where(valid, iota, 0))
        return plane, counts
    counts = (bounds[1:] - bounds[:-1]).astype(jnp.int32)
    starts = bounds[:-1].astype(jnp.int32)
    idx = jnp.clip(starts[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :],
                   0, N - 1)
    plane = order[idx]
    return plane, counts


def partition_planes(codes: np.ndarray, n: int, P: int, cap: int,
                     key_sorted: bool = False, cmin: int = 0):
    """Device hash partition: pack ``codes[:n]`` (padded to ``codes``'s
    pow2 length) into a ``[P, cap]`` row-index plane + per-partition
    counts. One kernel; the result stays on device for the join kernel.
    ``key_sorted=True`` additionally orders each plane slice by ascending
    key code (stable on row id) so the join kernel can binary-search it
    without re-sorting — pass the side's min code as ``cmin`` and the
    kernel rides the cheap packed single-key sort whenever the side's key
    span fits ``pack_base(P)``. Overflowed partitions (count > cap, heavy
    key skew) are detected by the join kernel and reported in the packed
    output."""
    _DISPATCHES[0] += 1
    if not key_sorted:
        mode = "rows"
    elif len(codes) == 0 or int(codes.max()) - cmin < pack_base(P):
        mode = "packed"
    else:
        mode = "keyed"
    return _jit_partition_kernel()(codes, np.int64(n), np.int64(cmin),
                                   P=P, cap=cap, sort_mode=mode)


@functools.cache
def _jit_fused_kernel():
    import jax

    jax.config.update("jax_enable_x64", True)
    return functools.partial(
        jax.jit, static_argnames=("spec", "P", "Gp", "join_type",
                                  "use_masks"))(_fused_join_agg)


def _fused_join_agg(pcodes, pg, pvals, pplane, pcounts,
                    bcodes, bvals, bplane, bcounts,
                    pn, bn, pmask, bmask, spec: tuple, P: int, Gp: int,
                    join_type: str, use_masks: bool):
    """spec: tuple of ("count"|"sum"|"min"|"max", "probe"|"build",
    value-row index) per aggregate. Returns a packed f64 plane
    ``[len(spec) + META_ROWS, Gp]``: one group-table row per aggregate,
    then the per-group output-row weight (count(*) semantics for the join
    type), then the per-group matched-pair count (the LEFT-join
    all-unmatched → NULL rule rides it), then [total_pairs, overflow]
    metadata (total_pairs is PRE-residual, mirroring the host guard).

    ``join_type`` picks the per-probe-row output weight ``w`` from the
    (residual-masked) match count ``cnt``: INNER emits ``cnt`` rows, LEFT
    ``max(cnt, 1)`` (the unmatched probe row survives with NULL build
    payload), SEMI ``cnt > 0`` and ANTI ``cnt == 0`` (one row per
    [non-]matching probe row, never per pair). ``use_masks`` gates the
    residual-filter masks: per-side boolean rows evaluated on the host
    (each conjunct references one side only), applied on device as a probe
    multiplier and a masked build prefix-sum — exactly the pairs the host
    residual filter would keep."""
    import jax
    import jax.numpy as jnp

    capL = pplane.shape[1]
    capR = bplane.shape[1]
    need_runs = any(k in ("min", "max") and s == "build" for k, s, _ in spec)
    # small group tables aggregate through a one-hot masked reduction
    # (an MXU matmul shape) instead of a 1-element-at-a-time scatter —
    # exact either way under the int gate, ~4x faster at bench scale
    masked_groups = Gp <= 16

    def one_partition(lrows, lcnt, rrows, rcnt):
        lvalid = jnp.arange(capL) < lcnt
        rvalid = jnp.arange(capR) < rcnt
        lk = jnp.where(lvalid, pcodes[lrows], _SENT_PROBE)
        lg = jnp.where(lvalid, pg[lrows], 0)
        # the partition kernel emitted the build plane in ascending-key
        # order (stable on row id within equal keys), and every gated key
        # code is below the pad sentinel, so masking pads keeps the lane
        # sorted: no sort here
        rs_k = jnp.where(rvalid, bcodes[rrows], _SENT_BUILD)
        rs_row = rrows
        s = jnp.searchsorted(rs_k, lk, side="left")
        e = jnp.searchsorted(rs_k, lk, side="right")
        cnt_raw = jnp.where(lvalid, e - s, 0).astype(jnp.int64)
        bsorted_valid = rs_k < _SENT_BUILD
        if use_masks:
            pm = lvalid & pmask[lrows]
            bm = bsorted_valid & bmask[rs_row]
            # matched pairs surviving the residual: prefix-sum of the
            # build mask over each probe row's [s, e) key run, zeroed
            # where the probe row itself fails its side's conjuncts
            prefm = jnp.concatenate(
                [jnp.zeros(1, jnp.int64),
                 jnp.cumsum(bm.astype(jnp.int64))])
            cnt = jnp.where(pm, prefm[e] - prefm[s], 0)
        else:
            bm = bsorted_valid
            cnt = cnt_raw
        has = cnt > 0
        if join_type == "LEFT":
            w = jnp.where(lvalid, jnp.maximum(cnt, 1), 0)
        elif join_type == "SEMI":
            w = has.astype(jnp.int64)
        elif join_type == "ANTI":
            w = jnp.where(lvalid, 1 - has.astype(jnp.int64), 0)
        else:  # INNER: one output row per surviving pair
            w = cnt
        if masked_groups:
            gmask = lg[:, None] == jnp.arange(Gp, dtype=lg.dtype)[None, :]

        def group_sum(contrib):
            if masked_groups:
                return jnp.matmul(contrib, gmask.astype(jnp.float64))
            return jnp.zeros(Gp).at[lg].add(contrib)

        def group_ext(kind, contrib, pad):
            if masked_groups:
                red = jnp.min if kind == "min" else jnp.max
                return red(jnp.where(gmask, contrib[:, None], pad), axis=0)
            op = (jnp.full(Gp, pad).at[lg].min if kind == "min"
                  else jnp.full(Gp, pad).at[lg].max)
            return op(contrib)

        if need_runs:
            # key-run segmentation of the sorted build plane (for
            # min/max): run id increments where the sorted key changes
            change = jnp.concatenate(
                [jnp.array([0], dtype=jnp.int32),
                 (rs_k[1:] != rs_k[:-1]).astype(jnp.int32)])
            run_id = jnp.cumsum(change)
            s_run = run_id[jnp.clip(s, 0, capR - 1)]

        w_row = group_sum(jnp.where(lvalid, w.astype(jnp.float64), 0.0))
        match_row = group_sum(jnp.where(lvalid, cnt.astype(jnp.float64),
                                        0.0))
        rows = []
        for kind, side, vrow in spec:
            if kind == "count":
                rows.append(w_row)
                continue
            if side == "probe":
                val = pvals[vrow][lrows]
                if kind == "sum":
                    contrib = val * w.astype(jnp.float64)
                    rows.append(group_sum(jnp.where(lvalid, contrib, 0.0)))
                else:  # min/max: the probe row's own value, where emitted
                    pad = jnp.inf if kind == "min" else -jnp.inf
                    rows.append(group_ext(
                        kind, jnp.where(lvalid & (w > 0), val, pad), pad))
                continue
            # build-side value column, gathered through the sorted plane;
            # only MATCHED pairs contribute (a LEFT join's padded rows
            # carry NULL build payload, which the host aggregate drops)
            if kind == "sum":
                bv = jnp.where(bm, bvals[vrow][rs_row], 0.0)
                pref = jnp.concatenate(
                    [jnp.zeros(1), jnp.cumsum(bv)])
                contrib = jnp.where(has, pref[e] - pref[s], 0.0)
                rows.append(group_sum(jnp.where(lvalid, contrib, 0.0)))
            else:
                pad = jnp.inf if kind == "min" else -jnp.inf
                bvm = jnp.where(bm, bvals[vrow][rs_row], pad)
                seg = (jnp.full(capR, pad).at[run_id].min(bvm)
                       if kind == "min"
                       else jnp.full(capR, pad).at[run_id].max(bvm))
                contrib = jnp.where(lvalid & has, seg[s_run], pad)
                rows.append(group_ext(kind, contrib, pad))
        return jnp.stack(rows + [w_row, match_row]), jnp.sum(cnt_raw)

    per_part, totals = jax.vmap(one_partition)(
        pplane, pcounts, bplane, bcounts)
    # combine across partitions ON DEVICE: adds are f64 sums of
    # integer-valued terms (exact, order-free under the int gate);
    # min/max are order-free by definition
    combined = []
    for i, (kind, _side, _vrow) in enumerate(spec):
        col = per_part[:, i, :]
        if kind == "min":
            combined.append(jnp.min(col, axis=0))
        elif kind == "max":
            combined.append(jnp.max(col, axis=0))
        else:
            combined.append(jnp.sum(col, axis=0))
    combined.append(jnp.sum(per_part[:, len(spec), :], axis=0))     # weight
    combined.append(jnp.sum(per_part[:, len(spec) + 1, :], axis=0))  # pairs
    overflow = ((jnp.max(pcounts) > capL) | (jnp.max(bcounts) > capR)
                | (pn > pplane.shape[0] * capL)
                | (bn > bplane.shape[0] * capR)).astype(jnp.float64)
    meta = jnp.zeros(Gp).at[0].set(
        jnp.sum(totals).astype(jnp.float64)).at[1].set(overflow)
    combined.append(meta)
    return jnp.stack(combined)


def fused_join_agg(pcodes, pg, pvals, pplane, pcounts,
                   bcodes, bvals, bplane, bcounts,
                   pn: int, bn: int, spec: tuple, P: int, Gp: int,
                   join_type: str = "INNER", pmask=None, bmask=None):
    """One dispatch: probe every partition plane against its sorted build
    plane and return the packed ``[n_aggs + 3, Gp]`` group table — the
    single array that crosses back to the host for the whole stage.
    ``pmask``/``bmask`` are optional per-row residual masks (padded bool
    arrays aligned with pcodes/bcodes); pass neither for an unfiltered
    join."""
    _DISPATCHES[0] += 1
    use_masks = pmask is not None or bmask is not None
    if use_masks:
        if pmask is None:
            pmask = np.ones(len(pcodes), dtype=bool)
        if bmask is None:
            bmask = np.ones(len(bcodes), dtype=bool)
    else:
        pmask = np.zeros(1, dtype=bool)
        bmask = np.zeros(1, dtype=bool)
    return _jit_fused_kernel()(
        pcodes, pg, pvals, pplane, pcounts, bcodes, bvals, bplane, bcounts,
        np.int64(pn), np.int64(bn), pmask, bmask, spec=spec, P=P, Gp=Gp,
        join_type=join_type, use_masks=use_masks)


def fetch_packed(packed) -> np.ndarray:
    """The stage's single device→host crossing; counted at the same
    process-lifetime site the mesh perf guards watch."""
    kernels.count_host_fetch()
    return np.asarray(packed)


@functools.cache
def _jit_gather_stack():
    import jax

    jax.config.update("jax_enable_x64", True)
    return functools.partial(jax.jit, static_argnames=("n_cols",))(
        _gather_stack_kernel)


def _gather_stack_kernel(cols, idx, n, n_cols: int):
    """Stack ``n_cols`` f64 source columns gathered through one composed
    index vector into a ``[n_cols, len(idx)]`` plane (pad slots past ``n``
    zeroed)."""
    import jax.numpy as jnp

    valid = jnp.arange(idx.shape[0]) < n
    safe = jnp.where(valid, idx, 0)
    return jnp.stack(
        [jnp.where(valid, jnp.take(cols[i], safe, mode="clip"), 0.0)
         for i in range(n_cols)])


def gather_stack(cols, idx: np.ndarray, n: int, n_to: int):
    """One dispatch: gather each f64 column in ``cols`` through the
    host-composed chain index ``idx[:n]`` and stack into a padded
    ``[len(cols), n_to]`` device plane — the expanded chain's value
    columns, built in HBM without ever materializing host-side."""
    _DISPATCHES[0] += 1
    idx_pad = np.zeros(n_to, dtype=np.int64)
    idx_pad[:n] = idx[:n]
    stacked = np.stack([np.asarray(c, dtype=np.float64) for c in cols])
    return _jit_gather_stack()(stacked, idx_pad, np.int64(n),
                               n_cols=len(cols))
