"""The jitted per-segment kernel interpreter.

This one function replaces the reference's entire per-segment execution stack
— filter operators, DocIdSet iteration, DataFetcher/ProjectionOperator and
DefaultGroupByExecutor.aggregateGroupBySV
(pinot-core/.../groupby/DefaultGroupByExecutor.java:191-218) — with a single
fused XLA computation per (program, segment-shape):

    mask  = filter tree as boolean vector algebra        (VPU, fused)
    gid   = Σ dict_ids[d] * stride[d]  (+ trash bucket for masked rows)
    out_k = segment_sum / segment_min / segment_max per aggregation

Design notes (SURVEY.md §7):
- masked fixed-shape execution: all rows compute, invalid rows route to a
  trash group that is sliced off on host. No dynamic shapes anywhere.
- `program` is a static jit arg (hashable IR, engine/ir.py); literals arrive
  via `params`, so repeated query shapes reuse the compiled executable.
- int64/float64 accumulation for exact parity with the reference's
  long/double agg results (jax x64 enabled at package import).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..engine import ir
from . import mxu_groupby

jax.config.update("jax_enable_x64", True)


def _eval_value(node: ir.ValueExpr, arrays, params):
    if isinstance(node, ir.Col):
        return arrays[node.slot]
    if isinstance(node, ir.IdsCol):
        return arrays[node.slot]
    if isinstance(node, ir.DictGather):
        return arrays[node.dict_slot][arrays[node.ids_slot]]
    if isinstance(node, ir.ConstParam):
        return params[node.idx]
    if isinstance(node, ir.ParamGather):
        ids = _eval_value(node.ids, arrays, params)
        return params[node.param_idx][ids]
    if isinstance(node, ir.Bin):
        a = _eval_value(node.a, arrays, params)
        b = _eval_value(node.b, arrays, params)
        return _BIN_OPS[node.op](a, b)
    if isinstance(node, ir.Un):
        return _UN_OPS[node.op](_eval_value(node.a, arrays, params))
    if isinstance(node, ir.Cast):
        return _eval_value(node.a, arrays, params).astype(_CAST_DTYPES[node.to])
    if isinstance(node, ir.Where):
        return jnp.where(
            _eval_value(node.cond, arrays, params),
            _eval_value(node.a, arrays, params),
            _eval_value(node.b, arrays, params),
        )
    if isinstance(node, ir.NullCol):
        return arrays[node.null_slot]
    if isinstance(node, ir.FilterVal):
        # n=1 for constant leaves: a (1,) mask broadcasts against (n,)
        # operands in the Where wrap
        return _eval_filter(node.filter, arrays, params, 1)
    if isinstance(node, ir.MvLutReduce):
        if node.op == "count":  # non-pad slots per doc; no LUT gather
            return (arrays[node.ids_slot] != node.card).sum(
                axis=1).astype(jnp.int32)
        vals = params[node.lut_param][arrays[node.ids_slot]]  # (n, max_mv)
        if node.op == "sum":
            return vals.sum(axis=1)
        if node.op == "min":
            return vals.min(axis=1)
        return vals.max(axis=1)
    raise TypeError(f"unknown value node {node}")


_BIN_OPS = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.true_divide,
    "fdiv": jnp.floor_divide,
    "mod": jnp.mod,
    "pow": jnp.power,
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "and": jnp.logical_and,
    "or": jnp.logical_or,
    "min": jnp.minimum,
    "max": jnp.maximum,
}

_UN_OPS = {
    "neg": jnp.negative,
    "abs": jnp.abs,
    "not": jnp.logical_not,
    "exp": jnp.exp,
    "ln": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "sqrt": jnp.sqrt,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "sign": jnp.sign,
}

_CAST_DTYPES = {
    "INT": jnp.int32,
    "LONG": jnp.int64,
    "FLOAT": jnp.float32,
    "DOUBLE": jnp.float64,
    "BOOLEAN": jnp.bool_,
    "STRING": jnp.float64,  # numeric-context cast; real string cast is host-side
    "TIMESTAMP": jnp.int64,
}


def _eval_filter(node: ir.FilterNode, arrays, params, n: int):
    if isinstance(node, ir.FConst):
        return jnp.full((n,), node.value, dtype=bool)
    if isinstance(node, ir.Interval):
        v = _eval_value(node.vexpr, arrays, params)
        mask = jnp.ones(v.shape, dtype=bool)
        if node.lo_param is not None:
            lo = params[node.lo_param]
            mask &= (v >= lo) if node.lo_inclusive else (v > lo)
        if node.hi_param is not None:
            hi = params[node.hi_param]
            mask &= (v <= hi) if node.hi_inclusive else (v < hi)
        if mask.ndim == 2:  # MV plane: row matches if any value matches
            mask = mask.any(axis=1)
        return mask
    if isinstance(node, ir.Lut):
        m = params[node.lut_param][arrays[node.ids_slot]]
        if m.ndim == 2:
            m = m.any(axis=1)
        return m
    if isinstance(node, ir.Isin):
        v = _eval_value(node.vexpr, arrays, params)
        vals = params[node.values_param]
        return (v[:, None] == vals[None, :]).any(axis=1)
    if isinstance(node, ir.Null):
        return arrays[node.null_slot]
    if isinstance(node, ir.MaskParam):
        return params[node.idx]
    if isinstance(node, ir.FAnd):
        m = _eval_filter(node.children[0], arrays, params, n)
        for c in node.children[1:]:
            m &= _eval_filter(c, arrays, params, n)
        return m
    if isinstance(node, ir.FOr):
        m = _eval_filter(node.children[0], arrays, params, n)
        for c in node.children[1:]:
            m |= _eval_filter(c, arrays, params, n)
        return m
    if isinstance(node, ir.FNot):
        return ~_eval_filter(node.child, arrays, params, n)
    raise TypeError(f"unknown filter node {node}")


def _apply_packed(arrays: tuple, packed: tuple) -> tuple:
    """Widen narrow (uint8/uint16) id planes to int32 in-register. A
    sub-byte bitstream decode was tried and measured ~1000x slower on TPU
    than this astype (the 32-lane stack/reshape forces lane relayouts), so
    byte-aligned narrow planes are the TPU-correct HBM packing — 4x/2x less
    residency and read bandwidth, decode fused for free. `packed` entries
    are (slot, width) with width ∈ {8, 16} (see dict_ids_packed)."""
    if not packed:
        return arrays
    out = list(arrays)
    for slot, _width in packed:
        out[slot] = out[slot].astype(jnp.int32)
    return tuple(out)


class PackedOuts:
    """Kernel outputs flattened into ONE device buffer + host-side metas.

    Tunneled devices (axon) pay a fixed round trip per materialized array
    (~60ms measured) — a query with k outputs costs k round trips if each
    is fetched separately. Packing on device makes the whole query ONE
    D2H transfer; shapes/dtypes are host-known attributes of the device
    arrays, so unpacking never touches the wire."""

    __slots__ = ("flat", "metas")

    def __init__(self, flat, metas):
        self.flat = flat
        self.metas = metas  # [(np.dtype, shape), ...]


# float64 cannot bitcast-convert on the axon AOT compile path (its
# X64-element-type rewrite pass lacks f64 bitcast support; int64 works).
# Encode f64 outputs with pure arithmetic instead: scale by a power-of-two
# bucket into f32-safe exponent range, split into a non-overlapping f32
# triplet (a = f32(y), b = f32(y-a), c = f32(y-a-b) — exact: 3x24 bits
# cover the 53-bit mantissa with every residual in f32 normal range), and
# carry bucket + nan/inf/sign flags in a fourth u32 word. Bit-exact for
# every f64 including subnormals, +-0, +-inf, nan (verified on hardware).
_F64_HALF_SCALES = tuple(2.0 ** (-90 * k) for k in range(-6, 7))


def _encode_f64(x):
    finite = jnp.isfinite(x)
    xs = jnp.where(finite, x, 0.0)
    ax = jnp.abs(xs)
    # bucket k: exponent(x) in [180k-60, 180k+120) — thresholds 2^(180k-60)
    # for k=-5..6 (the k=-6 threshold underflows f64 and is implicit)
    k = sum(((ax >= (2.0 ** (180 * kk - 60))).astype(jnp.int32))
            for kk in range(-5, 7)) - 6
    half = jnp.asarray(_F64_HALF_SCALES, dtype=jnp.float64)[k + 6]
    y = xs * half * half  # two exact multiplies (2^(180*6) overflows alone)
    a = y.astype(jnp.float32)
    r1 = y - a.astype(jnp.float64)
    b = r1.astype(jnp.float32)
    c = (r1 - b.astype(jnp.float64)).astype(jnp.float32)
    # signbit without bitcast (jnp.signbit bitcasts f64 internally, which
    # this compile path rejects): 1/-0.0 = -inf distinguishes the zero sign
    neg = (x < 0) | ((x == 0) & (jnp.float64(1.0) / x < 0))
    meta = ((k + 6).astype(jnp.uint32)
            | (jnp.isnan(x).astype(jnp.uint32) << 8)
            | ((~finite & ~jnp.isnan(x)).astype(jnp.uint32) << 9)
            | (neg.astype(jnp.uint32) << 10))
    words = jnp.stack(
        [jax.lax.bitcast_convert_type(a, jnp.uint32),
         jax.lax.bitcast_convert_type(b, jnp.uint32),
         jax.lax.bitcast_convert_type(c, jnp.uint32), meta], axis=-1)
    return words


def _decode_f64(raw: np.ndarray, shape) -> np.ndarray:
    w = raw.view(np.uint32).reshape(-1, 4)
    a = np.ascontiguousarray(w[:, 0]).view(np.float32).astype(np.float64)
    b = np.ascontiguousarray(w[:, 1]).view(np.float32).astype(np.float64)
    c = np.ascontiguousarray(w[:, 2]).view(np.float32).astype(np.float64)
    k = (w[:, 3] & 0xFF).astype(np.int32) - 6
    neg = (w[:, 3] >> 10) & 1
    x = np.ldexp(a + b + c, 180 * k)
    zneg = (x == 0) & (neg == 1)  # -0.0 + 0.0 = +0.0 loses the zero sign
    if zneg.any():
        x = np.where(zneg, -0.0, x)
    isinf = (w[:, 3] >> 9) & 1
    if isinf.any():
        x = np.where(isinf == 1, np.where(neg == 1, -np.inf, np.inf), x)
    isnan = (w[:, 3] >> 8) & 1
    if isnan.any():
        x = np.where(isnan == 1, np.nan, x)
    return x.reshape(shape)


@jax.jit
def _pack_u8(outs: tuple):
    chunks = []
    for o in outs:
        if o.dtype == jnp.bool_:
            o = o.astype(jnp.uint8)
        elif o.dtype == jnp.float64:
            o = _encode_f64(o)
        chunks.append(jax.lax.bitcast_convert_type(o, jnp.uint8).reshape(-1))
    return jnp.concatenate(chunks) if len(chunks) > 1 else chunks[0]


def pack_outputs(outs: tuple) -> PackedOuts:
    metas = [(np.dtype(str(o.dtype)), tuple(o.shape)) for o in outs]
    return PackedOuts(_pack_u8(outs), metas)


# lifetime count of device→host materializations at the packed-output
# fetch sites (single-stage packed outputs + the MSE fused-join group
# table); the perf guards pin a warm query to exactly ONE per dispatch
_HOST_FETCHES = [0]


def host_fetches() -> int:
    """Process-lifetime device→host fetch count (packed-output sites)."""
    return _HOST_FETCHES[0]


def count_host_fetch() -> None:
    """Record one deliberate device→host crossing. Every fetch site in the
    engine calls this right before its np.asarray so the structure guards
    can pin 'exactly one crossing per stage' without monkeypatching jax."""
    _HOST_FETCHES[0] += 1


def unpack_outputs(p: PackedOuts) -> list:
    count_host_fetch()
    flat = np.asarray(p.flat)  # the query's single device→host transfer
    return _split_flat(flat, p.metas)


def _split_flat(flat: np.ndarray, metas) -> list:
    out, off = [], 0
    for dt, shape in metas:
        if dt == np.float64:  # wire format: 4 u32 words per value
            nbytes = int(np.prod(shape, dtype=np.int64)) * 16
            out.append(_decode_f64(flat[off:off + nbytes], shape))
        else:
            nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
            out.append(flat[off:off + nbytes].view(dt).reshape(shape))
        off += nbytes
    return out


@jax.jit
def _concat_flats(flats: tuple):
    return jnp.concatenate(flats)


# batch-fetch only round-trip-DOMINATED transfers: above this total the
# wire time dwarfs the per-fetch latency, and the on-device concat copy +
# whole-batch host buffer would only raise peak memory for no win
_BATCH_FETCH_CAP = 128 << 20


def fetch_packed_batch(packs: list) -> list:
    """Materialize many segments' packed outputs in as few device→host
    transfers as possible: EQUAL-LENGTH flat buffers (same segment bucket ×
    same program — the multi-segment combine case) concatenate on device
    and fetch once, so a 16-segment combine costs one tunnel round trip
    instead of 16. Unequal lengths fetch individually — batching them
    would compile a fresh concat executable per length combination."""
    out = [None] * len(packs)
    by_len: dict[int, list[int]] = {}
    for i, p in enumerate(packs):
        by_len.setdefault(int(p.flat.shape[0]), []).append(i)
    for n, idxs in by_len.items():
        group_ok = len(idxs) > 1 and n * len(idxs) <= _BATCH_FETCH_CAP
        if not group_ok:
            for i in idxs:
                out[i] = unpack_outputs(packs[i])
            continue
        _HOST_FETCHES[0] += 1
        flat = np.asarray(_concat_flats(tuple(packs[i].flat for i in idxs)))
        for j, i in enumerate(idxs):
            out[i] = _split_flat(flat[j * n:(j + 1) * n], packs[i].metas)
    return out


@partial(jax.jit, static_argnames=("program", "padded", "packed", "fused",
                                   "fused_lut_meta"))
def run_program(program: ir.Program, arrays: tuple, params: tuple, num_docs, padded: int,
                row_offset=0, packed: tuple = (), fused: str = "",
                fused_lut_meta: tuple = ()):
    """Execute a Program over padded column planes. Returns a tuple:

    selection   → (mask bitmap, packed little-endian)
    aggregation → (count, agg_0, agg_1, ...) each shape (1+trash,) sliced later
    group_by    → (counts[G+1], agg_0[G+1], ...)

    `padded` is the bucket row count (static); every SV plane has that length.
    `row_offset` supports row-sharded multi-device execution (shard_map over a
    mesh row axis — parallel/mesh.py): each shard sees rows
    [row_offset, row_offset+padded) of the global segment.
    `packed` marks id slots resident in HBM as packed/narrow planes.
    `fused` ('' | 'tpu' | 'interpret') enables the single-pass fused dense
    group-by kernel (ops/fused_groupby.py) for programs in its scope — the
    RAW narrow planes feed the kernel directly, skipping `_apply_packed`.
    """
    if fused and program.mode == "group_by":
        from . import fused_groupby

        fp = fused_groupby.plan(program, arrays, fused_lut_meta)
        if fp is not None:
            return fused_groupby.execute(
                fp, program, arrays, params, num_docs, padded, row_offset,
                interpret=(fused == "interpret"))
    arrays = _apply_packed(arrays, packed)
    return _run_program_impl(program, arrays, params, num_docs, padded, row_offset)


@partial(jax.jit, static_argnames=("program", "padded", "packed"))
def run_program_batch(program: ir.Program, arrays: tuple, params: tuple,
                      num_docs, padded: int, packed: tuple = ()):
    """Execute one Program over a stacked FAMILY of segments in a single
    dispatch: every plane in `arrays` and every param in `params` carries a
    leading batch dim [S, ...] (one row per member segment) and `num_docs`
    is an (S,) vector. The body is `jax.vmap` of the exact per-segment
    implementation, so each output gains a leading S dim and row s is
    bit-for-bit what `run_program(..., fused="")` would have produced for
    member s — the host slices outputs per segment after one transfer.

    Narrow packed planes widen via `_apply_packed` BEFORE the vmap
    (elementwise astype is shape-agnostic), so stacks stay narrow in HBM.
    The fused dense kernel is per-segment-only: batched families always
    take the reference `_run_program_impl` path.
    """
    arrays = _apply_packed(arrays, packed)

    def one(arrays_s, params_s, nd):
        return _run_program_impl(program, arrays_s, params_s, nd, padded)

    return jax.vmap(one)(arrays, params, num_docs)


def _run_program_impl(program: ir.Program, arrays: tuple, params: tuple, num_docs, padded: int,
                      row_offset=0):
    n = padded
    valid = (jnp.arange(n, dtype=jnp.int32) + row_offset) < num_docs
    if program.filter is not None:
        mask = valid & _eval_filter(program.filter, arrays, params, n)
    else:
        mask = valid

    if program.mode == "selection":
        # ship the mask as a BITMAP (n/8 uint8), not one byte per row: a
        # 100M-row segment's selection leaf costs 12.5MB D2H instead of
        # 100MB — the MSE leaf-selection transfer is tunnel-bound.
        # Padded buckets (and row shards of them) are always 8-divisible.
        # bitorder matches every other packed bitmap in the repo
        # (segment/bitpack.py, aggregation.py occupancy words: little).
        return (jnp.packbits(mask, bitorder="little"),)

    if program.mv_group_slot is not None and program.mode in (
            "group_by", "group_by_sparse"):
        # MV group dim: expand to (doc × mv-slot) pairs — broadcast every
        # 1-D plane across the MV width, flatten the MV id matrix, mask
        # off pad slots — and let the dense/sparse paths run unchanged.
        # Matched DOCS are counted pre-expansion (pair counts ≠ docs).
        scanned_docs = mask.astype(jnp.int32).sum().astype(jnp.int64)[None]
        mv = arrays[program.mv_group_slot]  # (n, max_mv) int32
        width = mv.shape[1]
        doc_slots = set(program.mv_doc_slots)
        arrays = tuple(
            mv.reshape(-1) if i == program.mv_group_slot
            else (jnp.broadcast_to(a[:, None], (n, width)).reshape(-1)
                  if i in doc_slots else a)  # dict planes / filter-only MV
            for i, a in enumerate(arrays))  # matrices pass through
        mask = (mask[:, None] & (mv != program.mv_group_card)).reshape(-1)
        n = n * width
        if program.mode == "group_by_sparse":
            outs = _run_sparse_group_by(program, arrays, params, mask, n)
        else:
            outs = _dense_group_by_entry(program, arrays, params, mask, n)
        return outs + (scanned_docs,)

    if program.mode == "group_by_sparse":
        return _run_sparse_group_by(program, arrays, params, mask, n)

    if program.mode != "group_by":
        # un-grouped aggregation: NO scatter at all — plain masked
        # reductions shaped (value, trash) to keep the output contract.
        # Scatters to a 2-slot table were pure overhead (and 64-bit
        # scatters are emulated on TPU)
        return _run_ungrouped(program, arrays, params, mask, n)
    return _dense_group_by_entry(program, arrays, params, mask, n)


def _dense_group_by_entry(program: ir.Program, arrays, params, mask, n):
    """Dense group-by gid assembly + dispatch, shared by the SV path and
    the MV (doc × mv-slot) pre-expanded path — after expansion the MV
    dim's flattened ids are just another id plane; pad slots are already
    masked → trash."""
    gid = jnp.zeros((n,), dtype=jnp.int32)
    if program.group_vexprs:
        for vexpr, stride in zip(program.group_vexprs, program.group_strides):
            v = _eval_value(vexpr, arrays, params)
            gid = gid + v.astype(jnp.int32) * jnp.int32(stride)
    else:
        for slot, stride in zip(program.group_slots, program.group_strides):
            gid = gid + arrays[slot].astype(jnp.int32) * jnp.int32(stride)
    trash = jnp.int32(program.num_groups)
    gid = jnp.where(mask, gid, trash)
    return _run_dense_group_by(program, arrays, params, mask, gid,
                               program.num_groups + 1, n)


def _run_dense_group_by(program: ir.Program, arrays, params, mask, gid,
                        num_segments, n):
    """COUNT and every int32-safe SUM ride ONE MXU pass (8-bit limb planes
    through the kron-factored one-hot matmul — ops/mxu_groupby.py); scatters
    only remain for what the MXU cannot reduce (min/max, float sums, matrix
    ops). Replaces the batched (n, C) vector-payload scatter, whose minor
    dim was padded 6→128 lanes by TPU tiling (a 21x HBM blowup that OOMed
    real 100M-row segments)."""
    planes = [mask.astype(mxu_groupby.PLANE_DTYPE)]  # count plane
    recipes: list = []  # per agg: callable(sums, counts) | None → _run_agg
    for agg in program.aggs:
        recipes.append(_mxu_agg(agg, arrays, params, mask, planes))
    if not mxu_groupby.supports(num_segments, len(planes)):
        # too many groups/planes for the VMEM-resident accumulator: sums
        # drop back to per-plane 32-bit scatters; COUNTs still answer from
        # the shared counts column (their recipe reads no limb sums)
        planes = []
        recipes = [r if agg.kind == "count" else None
                   for agg, r in zip(program.aggs, recipes)]
    if planes:
        sums = mxu_groupby.limb_sums(planes, gid, num_segments)
        counts = sums[0]
    else:
        sums = None
        counts = jax.ops.segment_sum(
            mask.astype(jnp.int32), gid,
            num_segments=num_segments).astype(jnp.int64)
    outputs = [counts]
    for agg, recipe in zip(program.aggs, recipes):
        if recipe is None:
            outputs.append(_run_agg(agg, arrays, params, mask, gid,
                                    num_segments, n, counts=counts))
        else:
            outputs.append(recipe(sums, counts))
    return tuple(outputs)


def _mxu_agg(agg: ir.AggOp, arrays, params, mask, planes):
    """Register an aggregation's 8-bit limb planes for the MXU pass;
    returns a recipe (sums, counts) → output column, or None if this agg
    kind must run through its own scatter (_run_agg)."""
    if agg.kind == "count":
        return lambda sums, counts: counts
    if agg.kind != "sum":
        return None
    v = _eval_value(agg.vexpr, arrays, params)
    if not (jnp.issubdtype(v.dtype, jnp.integer) and _fits_i32(v, agg)):
        return None
    vm = jnp.where(mask, v, 0).astype(jnp.int32)
    u = vm.astype(jnp.uint32)
    b = mxu_groupby.LIMB_BITS
    shifts, nonneg = _limb_shifts(agg.vmin, agg.vmax, b)
    if len(planes) + len(shifts) + (0 if nonneg else 1) > mxu_groupby.MAX_PLANES:
        return None
    refs = []
    for s in shifts:
        refs.append((len(planes), s))
        planes.append(((u >> s) & jnp.uint32((1 << b) - 1))
                      .astype(mxu_groupby.PLANE_DTYPE))
    neg_ref = None
    if not nonneg:
        neg_ref = len(planes)
        planes.append((vm < 0).astype(mxu_groupby.PLANE_DTYPE))

    def recipe(sums, counts, _refs=refs, _neg=neg_ref):
        total = jnp.zeros(counts.shape[0], dtype=jnp.int64)
        for idx, shift in _refs:
            total = total + (sums[idx] << shift)
        if _neg is not None:
            total = total - (sums[_neg] << 32)
        return total.astype(jnp.float64)

    return recipe


def _run_ungrouped(program: ir.Program, arrays, params, mask, n):
    count = mask.astype(jnp.int32).sum().astype(jnp.int64)
    zero_i = jnp.int64(0)
    outputs = [jnp.stack([count, zero_i])]
    for agg in program.aggs:
        if agg.kind == "count":
            outputs.append(jnp.stack([count, zero_i]))
            continue
        if agg.kind in ("distinct_bitmap", "value_hist", "hist_fixed",
                        "hist_adaptive"):
            # matrix shapes keep the (1 group + trash) scatter layout
            outputs.append(_run_agg(agg, arrays, params, mask,
                                    jnp.where(mask, 0, 1).astype(jnp.int32),
                                    2, n, counts=None))
            continue
        v = _eval_value(agg.vexpr, arrays, params)
        is_int = jnp.issubdtype(v.dtype, jnp.integer)
        fast32 = is_int and _fits_i32(v, agg)
        if agg.kind == "sum":
            if fast32 and n % 4096 == 0:
                # the TPU has no 64-bit ALU: a whole-column i64 (or f64)
                # reduction runs on emulated adds per element. Split into
                # u16 limbs, reduce 4096-element blocks in NATIVE i32
                # (4096*65535 < 2^31: exact), and only the tiny per-block
                # partials touch i64. Two's complement fixes negatives:
                # sum(u32) = sum(v) + 2^32 * count_neg. _limb_shifts skips
                # the high limb and/or the negative pass when the planner
                # proved bounds.
                vm = jnp.where(mask, v.astype(jnp.int32), 0)
                u = vm.astype(jnp.uint32)
                shifts, nonneg = _limb_shifts(agg.vmin, agg.vmax, 16)

                def _blk(x):
                    return x.reshape(-1, 4096).sum(
                        axis=1).astype(jnp.int64).sum()

                s = jnp.int64(0)
                for sh in shifts:
                    s = s + (_blk(((u >> sh) & jnp.uint32(0xFFFF))
                                  .astype(jnp.int32)) << sh)
                if not nonneg:
                    s = s - (_blk((vm < 0).astype(jnp.int32)) << 32)
                s = s.astype(jnp.float64)
            elif is_int:
                s = jnp.where(mask, v, 0).astype(jnp.int64).sum() \
                    .astype(jnp.float64)
            else:
                s = jnp.where(mask, v, 0).astype(jnp.float64).sum()
            outputs.append(jnp.stack([s, jnp.float64(0)]))
        elif agg.kind == "sumsq":
            vf = jnp.where(mask, v, 0).astype(jnp.float64)
            outputs.append(jnp.stack([(vf * vf).sum(), jnp.float64(0)]))
        elif agg.kind == "min":
            if fast32:  # native i32 compares; empty → +inf via the count
                s = jnp.where(mask, v.astype(jnp.int32), _I32_MAX).min()
                out = jnp.where(count > 0, s.astype(jnp.float64), jnp.inf)
            elif v.dtype == jnp.float32:  # exact: f32→f64 is lossless
                out = jnp.where(mask, v, jnp.float32(jnp.inf)).min() \
                    .astype(jnp.float64)
            else:
                vf = jnp.where(mask, v, jnp.inf).astype(jnp.float64)
                out = vf.min()
            outputs.append(jnp.stack([out, jnp.float64(jnp.inf)]))
        elif agg.kind == "max":
            if fast32:
                s = jnp.where(mask, v.astype(jnp.int32), _I32_MIN).max()
                out = jnp.where(count > 0, s.astype(jnp.float64), -jnp.inf)
            elif v.dtype == jnp.float32:
                out = jnp.where(mask, v, jnp.float32(-jnp.inf)).max() \
                    .astype(jnp.float64)
            else:
                vf = jnp.where(mask, v, -jnp.inf).astype(jnp.float64)
                out = vf.max()
            outputs.append(jnp.stack([out, jnp.float64(-jnp.inf)]))
        else:
            raise ValueError(f"unknown agg kind {agg.kind}")
    return tuple(outputs)


def _run_sparse_group_by(program: ir.Program, arrays, params, mask, n):
    """High-cardinality group-by: sort-based aggregation on device.

    When the cardinality product exceeds the dense segment_sum table limit,
    the reference switches DictionaryBasedGroupKeyGenerator to hash maps
    with a numGroupsLimit trim (DictionaryBasedGroupKeyGenerator.java:119-137,
    InstancePlanMakerImplV2.java:245-270). Hash maps are hostile to the TPU's
    vector units, but a bitonic sort of 64-bit composite keys is not:

        key   = Σ dict_ids[d] * stride[d]          (int64; masked → sentinel)
        sort  (key, agg inputs...) together        (lax.sort, one fused pass)
        first = key[i] != key[i-1]                 (segment boundaries)
        gidx  = cumsum(first) - 1                  (dense 0-based group index)
        out_k = segment_sum/min/max by gidx        (K+1 slots, K = groups limit)

    Groups past numGroupsLimit (in key sort order) route to the trash slot —
    the same "stop creating new groups" trim semantics as the reference. The
    composite keys of the surviving groups are emitted as the LAST output so
    the host can decode per-dim dict ids with the usual stride arithmetic.

    Two fast paths shave the sort cost (ir.sparse_groupby_path names the
    variant for EXPLAIN IMPLEMENTATION):
    - keys_presorted: the single group key plane is already nondecreasing in
      doc order (sorted ingestion) — skip lax.sort entirely; group edges
      come from transitions in the raw id plane.
    - sort-iota + gather: with >= 2 payload operands, sort only
      (key[, distinct_ids], iota32) and gather each payload through the
      permutation — (1+A)·n sorted bytes become ~2·n.
    """
    # 64-bit sorts/scatters are emulated on TPU: sort 32-bit keys whenever
    # the composite key space fits (key_space is static on the Program)
    key32 = 0 < program.key_space < (1 << 31) - 1
    kdtype = jnp.int32 if key32 else jnp.int64
    key = jnp.zeros((n,), dtype=kdtype)
    if program.group_vexprs:
        for vexpr, stride in zip(program.group_vexprs, program.group_strides):
            key = key + _eval_value(vexpr, arrays, params).astype(kdtype) * stride
    else:
        for slot, stride in zip(program.group_slots, program.group_strides):
            key = key + arrays[slot].astype(kdtype) * stride
    sentinel = (jnp.int32((1 << 31) - 1) if key32
                else jnp.int64(ir.SPARSE_KEY_SPACE))
    if not program.keys_presorted:
        # masked rows sort to a sentinel tail. The presorted path keeps the
        # RAW key plane instead: rows never move, so masked rows stay in
        # place and are skipped via op identities + mask prefix sums.
        key = jnp.where(mask, key, sentinel)

    # agg inputs with mask-neutral elements, computed BEFORE the sort so one
    # lax.sort carries key + all values into group-contiguous order.
    # COUNT DISTINCT rides the SAME sort as a SECONDARY key: with dict ids
    # sorted within each group, distinct (group, id) pairs are exactly the
    # first-occurrence rows, and per-slot distinct counts + id bitmaps
    # reduce on the already-computed group edges — no second n-length
    # sort, no n-length output (the old pair-list output was ~100x the
    # query's real bytes through a tunneled fetch and blew up compiles).
    num_sort_keys = 1
    distinct_aggs = [a for a in program.aggs if a.kind == "distinct_bitmap"]
    if len(distinct_aggs) > 1:
        raise ValueError("sparse group-by supports one DISTINCT column")
    # DISTINCT ids PACK into the key's low digits when the combined space
    # fits int32 (key' = key*card + id): one sort operand fewer — the
    # secondary sort order arrives free, and uniq/group edges both fall
    # out of the single packed key. Falls back to a two-key sort when the
    # product overflows.
    pack_card = None
    if distinct_aggs and key32 and not program.keys_presorted and \
            0 < program.key_space * distinct_aggs[0].card < _I32_MAX:
        pack_card = int(distinct_aggs[0].card)
        ids_raw = arrays[distinct_aggs[0].ids_slot].astype(jnp.int32)
        key = jnp.where(mask, key * jnp.int32(pack_card) + ids_raw, sentinel)
    operands = [key]
    if distinct_aggs and pack_card is None:
        operands.append(arrays[distinct_aggs[0].ids_slot].astype(jnp.int32))
        num_sort_keys = 2
    specs = []  # per agg: (reduce_kind, operand index | None[, agg])
    for agg in program.aggs:
        if agg.kind == "count":
            specs.append(("count", None))
            continue
        if agg.kind == "distinct_bitmap":
            specs.append(("distinct", None if pack_card else 1, agg))
            continue
        v = _eval_value(agg.vexpr, arrays, params)
        fast32 = jnp.issubdtype(v.dtype, jnp.integer) and _fits_i32(v, agg)
        if agg.kind in ("sum", "sumsq"):
            if agg.kind == "sumsq":
                v = jnp.where(mask, v, 0).astype(jnp.float64)
                v = v * v
                specs.append(("sum_f", len(operands), agg))
            elif fast32:
                v = jnp.where(mask, v, 0).astype(jnp.int32)
                specs.append(("sum_i", len(operands), agg))
            else:
                v = jnp.where(mask, v, 0).astype(jnp.float64)
                specs.append(("sum_f", len(operands), agg))
        elif agg.kind == "min":
            if fast32:
                v = jnp.where(mask, v.astype(jnp.int32), _I32_MAX)
                specs.append(("min_i", len(operands), agg))
            else:
                v = jnp.where(mask, v, jnp.inf).astype(jnp.float64)
                specs.append(("min_f", len(operands), agg))
        elif agg.kind == "max":
            if fast32:
                v = jnp.where(mask, v.astype(jnp.int32), _I32_MIN)
                specs.append(("max_i", len(operands), agg))
            else:
                v = jnp.where(mask, v, -jnp.inf).astype(jnp.float64)
                specs.append(("max_f", len(operands), agg))
        else:  # matrix-shaped aggs are planner-rejected in sparse mode
            raise ValueError(f"agg kind {agg.kind} unsupported in sparse group-by")
        operands.append(v)

    if program.keys_presorted:
        return _presorted_sparse_tail(program, operands, specs, mask, n)

    num_payloads = len(operands) - num_sort_keys
    if num_payloads >= 2:
        # sort-iota + gather: dragging every payload through the bitonic
        # sort network costs (num_keys+A)·n sorted bytes and A extra
        # compare-network permute lanes. Sort only (keys..., iota32) and
        # gather each payload through the permutation instead — the sort
        # moves ~2·n values and the payloads cross HBM once via gathers.
        # lax.sort is stable, so the permutation (iota as the tie-broken
        # last operand) reproduces the multi-operand sort bit-for-bit.
        iota = jnp.arange(n, dtype=jnp.int32)
        head = jax.lax.sort(tuple(operands[:num_sort_keys]) + (iota,),
                            num_keys=num_sort_keys)
        perm = head[num_sort_keys]
        sorted_ops = tuple(head[:num_sort_keys]) + tuple(
            op[perm] for op in operands[num_sort_keys:])
    else:
        sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_sort_keys)
    skey_raw = sorted_ops[0]
    valid = skey_raw < sentinel
    if pack_card is not None:
        # unpack: group key = high digits; the id low digit feeds the
        # distinct branch. Sentinel rows' quotient stays huge (> any real
        # key) so the sentinel-tail ordering survives the division.
        skey = skey_raw // jnp.int32(pack_card)
        packed_sids = skey_raw - skey * jnp.int32(pack_card)
    else:
        skey = skey_raw
        packed_sids = None
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), skey[1:] != skey[:-1]]) & valid
    gidx = jnp.cumsum(first.astype(jnp.int32)) - 1
    k = program.num_groups

    # ZERO scatters after the sort (each n-update scatter costs ~7.7ns/row
    # on the TPU scatter unit — ~0.5s per payload at 64M rows): with keys
    # sorted, group slot edges come from one vectorized binary search, and
    # every per-group reduction becomes a prefix-scan diff / gather at the
    # edges. Invalid rows sort to the sentinel tail; pin their gidx above
    # every slot so edges never include them.
    n_valid = valid.astype(jnp.int32).sum()
    gidx_m = jnp.where(valid, gidx, jnp.int32(1 << 30))
    edges = jnp.searchsorted(gidx_m, jnp.arange(k + 1, dtype=jnp.int32))
    counts_k = (edges[1:] - edges[:-1]).astype(jnp.int64)
    # trash slot counts valid-but-trimmed rows (invalid rows contribute 0),
    # so the host can report every post-filter doc as scanned even when the
    # numGroupsLimit trim drops groups
    counts = jnp.concatenate(
        [counts_k, (n_valid - edges[k]).astype(jnp.int64)[None]])
    fi = edges[:k]
    li = jnp.maximum(edges[1:] - 1, fi)  # clamp empty slots
    occupied = counts_k > 0

    def group_sums(prefix_incl, v_f64):
        s = prefix_incl[li] - prefix_incl[fi] + v_f64[fi]
        return jnp.where(occupied, s, 0.0)

    outputs = [counts]
    for spec in specs:
        kind, oi = spec[0], spec[1]
        agg = spec[2] if len(spec) > 2 else None
        if kind == "count":
            outputs.append(counts)
        elif kind == "distinct":
            agg = spec[2]
            card = agg.card
            if oi is None:  # ids packed into the sort key's low digit
                sids = packed_sids
                uniq = jnp.concatenate(
                    [jnp.ones((1,), dtype=bool),
                     skey_raw[1:] != skey_raw[:-1]]) & valid
            else:
                sids = sorted_ops[oi]  # dict ids, sorted within each group
                uniq = jnp.concatenate(
                    [jnp.ones((1,), dtype=bool),
                     (skey[1:] != skey[:-1]) | (sids[1:] != sids[:-1])]) & valid
            bit = sids.astype(jnp.uint32)
            cols = []
            for w in range(-(-card // 32)):
                # each (group, id) bit appears at most once (uniq-masked),
                # so the per-group OR equals the per-group SUM — one
                # wrapping uint32 cumsum + edge diffs (mod-2^32 prefix
                # differences are exact because every group sum < 2^32),
                # instead of a log2(n)-pass segmented scan
                val = jnp.where(uniq & ((bit >> 5) == jnp.uint32(w)),
                                jnp.uint32(1) << (bit & jnp.uint32(31)),
                                jnp.uint32(0))
                pw = jnp.cumsum(val)
                word = pw[li] - pw[fi] + val[fi]
                cols.append(jnp.where(occupied, word, jnp.uint32(0)))
            matrix = jnp.stack(cols, axis=1)  # (k, W) bitmap words
            outputs.append(jnp.concatenate(
                [matrix, jnp.zeros((1, matrix.shape[1]), jnp.uint32)]))
        elif kind == "sum_i" and not _prefix_exact_gate(sorted_ops[oi], agg):
            # unbounded int64 columns: f64 prefix DIFFS would round (the
            # per-group result must stay exact) — keep the limb scatters
            gid = jnp.where(valid & (gidx < k), gidx, jnp.int32(k))
            outputs.append(_segment_sum_exact_i64(
                sorted_ops[oi], gid, k + 1, n, agg.vmin, agg.vmax,
                indices_are_sorted=True).astype(jnp.float64))
        elif kind == "sum_i":
            v = sorted_ops[oi]
            sums = group_sums(_sorted_prefix_f64(v, agg), v.astype(jnp.float64))
            outputs.append(jnp.concatenate([sums, jnp.zeros(1)]))
        elif kind == "sum_f":
            # f64 values: a GLOBAL prefix-diff would round each group to
            # ulp(global running total); the segmented tree scan keeps
            # rounding local to the group, like the scatter it replaces
            s = _segmented_scan(sorted_ops[oi], first, jnp.add)[li]
            outputs.append(jnp.concatenate(
                [jnp.where(occupied, s, 0.0), jnp.zeros(1)]))
        elif kind in ("min_i", "min_f"):
            v = sorted_ops[oi]
            smin = _segmented_scan(v, first, jnp.minimum)[li]
            outputs.append(jnp.concatenate(
                [jnp.where(occupied, smin.astype(jnp.float64), jnp.inf),
                 jnp.full(1, jnp.inf)]))
        else:  # max_i / max_f
            v = sorted_ops[oi]
            smax = _segmented_scan(v, first, jnp.maximum)[li]
            outputs.append(jnp.concatenate(
                [jnp.where(occupied, smax.astype(jnp.float64), -jnp.inf),
                 jnp.full(1, -jnp.inf)]))
    # surviving composite key per slot = the key at its left edge
    keys_out = jnp.where(occupied,
                         skey[jnp.clip(fi, 0, n - 1)].astype(jnp.int64),
                         jnp.int64(-1))
    outputs.append(keys_out)
    return tuple(outputs)


def _presorted_sparse_tail(program: ir.Program, operands, specs, mask, n):
    """Sorted-key fast path: ZERO lax.sort (reference SortedGroupByOperator).

    The single key plane (operands[0], RAW — no sentinel) is nondecreasing
    over the segment (planner checked ColumnMetadata.is_sorted), so group
    runs are already contiguous in DOC order. Rows never move, which changes
    the bookkeeping versus the sorted path in two ways: masked rows (filter
    misses + the padded tail) sit INSIDE/AFTER runs instead of sorting to a
    sentinel tail, so

    - a group exists only where a key run has >= 1 masked-in row, and the
      run's FIRST such row opens the group — fully-masked runs must not
      consume numGroupsLimit slots, or an exact ORDER BY trim could drop a
      live group that a sorted-path run would keep;
    - per-group reductions skip masked rows via op identities (the operand
      loop already substituted them) and counts come from a mask prefix sum.

    The padded tail (device planes pad dict id 0 past num_docs) would break
    the nondecreasing invariant, but those rows are always masked off
    (run_program ANDs the doc-count iota mask), and masked rows only ever
    contribute op identities here — a masked out-of-order row can at worst
    sit inside the span [fi, li] of an earlier group, where its identity
    value is harmless. Only MASKED-IN rows must be nondecreasing, which the
    planner's is_sorted check guarantees.
    """
    key = operands[0]
    k = program.num_groups
    first_key = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), key[1:] != key[:-1]])
    # running masked-in row count within each key run (inclusive): the row
    # where it first hits 1 opens that run's group
    mrun = _segmented_scan(mask.astype(jnp.int32), first_key, jnp.add)
    first = mask & (mrun == 1)
    gidx = jnp.cumsum(first.astype(jnp.int32)) - 1
    # gidx is nondecreasing (-1 before the first live group), so slot edges
    # still come from one vectorized binary search — same machinery as the
    # sorted path, no scatters
    edges = jnp.searchsorted(gidx, jnp.arange(k + 1, dtype=jnp.int32))
    fi = edges[:k]
    li = jnp.maximum(edges[1:] - 1, fi)
    fic = jnp.clip(fi, 0, n - 1)
    lic = jnp.clip(li, 0, n - 1)
    occupied = jnp.arange(k, dtype=jnp.int32) < gidx[n - 1] + 1
    # per-group masked-in row counts from one mask prefix sum: rows of later
    # fully-masked runs inside [fi, li] contribute zero by construction
    pm = jnp.cumsum(mask.astype(jnp.int32))
    counts_k = jnp.where(
        occupied, pm[lic] - pm[fic] + mask[fic].astype(jnp.int32),
        0).astype(jnp.int64)
    n_valid = pm[n - 1].astype(jnp.int64)
    counts = jnp.concatenate([counts_k, (n_valid - counts_k.sum())[None]])
    # a group's span [fi, li] may run past its own key run into later
    # FULLY-masked runs (which never opened a group) — segmented scans reset
    # at those run boundaries, so scan-based reductions must read at the
    # last row of the group's OWN run, not at li. Mask/value prefix-diffs
    # don't care (masked rows contribute exact zeros globally).
    run_id = jnp.cumsum(first_key.astype(jnp.int32)) - 1  # nondecreasing
    rlast = jnp.clip(
        jnp.searchsorted(run_id, run_id[fic], side="right") - 1, 0, n - 1)

    def group_sums(prefix_incl, v_f64):
        s = prefix_incl[lic] - prefix_incl[fic] + v_f64[fic]
        return jnp.where(occupied, s, 0.0)

    outputs = [counts]
    for spec in specs:
        kind, oi = spec[0], spec[1]
        agg = spec[2] if len(spec) > 2 else None
        if kind == "count":
            outputs.append(counts)
        elif kind == "distinct":
            # ids are NOT sorted within a run here (no sort happened), so
            # the sorted path's uniq-row trick is unavailable — but OR is
            # idempotent, so the log2(n)-pass segmented OR scan builds the
            # same per-group bitmap words without dedup
            card = agg.card
            bit = operands[oi].astype(jnp.uint32)
            cols = []
            for w in range(-(-card // 32)):
                val = jnp.where(mask & ((bit >> 5) == jnp.uint32(w)),
                                jnp.uint32(1) << (bit & jnp.uint32(31)),
                                jnp.uint32(0))
                word = _segmented_scan(val, first_key, jnp.bitwise_or)[rlast]
                cols.append(jnp.where(occupied, word, jnp.uint32(0)))
            matrix = jnp.stack(cols, axis=1)
            outputs.append(jnp.concatenate(
                [matrix, jnp.zeros((1, matrix.shape[1]), jnp.uint32)]))
        elif kind == "sum_i" and not _prefix_exact_gate(operands[oi], agg):
            # unbounded int64 columns keep the exact limb scatters; indices
            # are NOT flagged sorted (masked rows scatter into the trash)
            gid = jnp.where(mask & (gidx >= 0) & (gidx < k),
                            gidx, jnp.int32(k))
            outputs.append(_segment_sum_exact_i64(
                operands[oi], gid, k + 1, n, agg.vmin, agg.vmax,
                indices_are_sorted=False).astype(jnp.float64))
        elif kind == "sum_i":
            v = operands[oi]  # masked rows already zeroed
            sums = group_sums(_sorted_prefix_f64(v, agg),
                              v.astype(jnp.float64))
            outputs.append(jnp.concatenate([sums, jnp.zeros(1)]))
        elif kind == "sum_f":
            s = _segmented_scan(operands[oi], first_key, jnp.add)[rlast]
            outputs.append(jnp.concatenate(
                [jnp.where(occupied, s, 0.0), jnp.zeros(1)]))
        elif kind in ("min_i", "min_f"):
            smin = _segmented_scan(operands[oi], first_key, jnp.minimum)[rlast]
            outputs.append(jnp.concatenate(
                [jnp.where(occupied, smin.astype(jnp.float64), jnp.inf),
                 jnp.full(1, jnp.inf)]))
        else:  # max_i / max_f
            smax = _segmented_scan(operands[oi], first_key, jnp.maximum)[rlast]
            outputs.append(jnp.concatenate(
                [jnp.where(occupied, smax.astype(jnp.float64), -jnp.inf),
                 jnp.full(1, -jnp.inf)]))
    keys_out = jnp.where(occupied, key[fic].astype(jnp.int64), jnp.int64(-1))
    outputs.append(keys_out)
    return tuple(outputs)


def _int_prefix_bound(agg):
    bound = max(abs(int(agg.vmin)), abs(int(agg.vmax))) \
        if agg is not None and agg.vmin is not None and agg.vmax is not None \
        else (1 << 31)
    block = 1 << max(0, min(11, 30 - bound.bit_length()))
    return bound, block


def _prefix_exact_gate(v, agg) -> bool:
    """True when f64 prefix-diff sums are EXACT for this integer column:
    every partial sum is an integer below 2^53."""
    if not jnp.issubdtype(v.dtype, jnp.integer):
        return True  # floats take the segmented-scan sum_f path
    n = v.shape[0]
    bound, block = _int_prefix_bound(agg)
    return block >= 8 and n % block == 0 and n * bound < (1 << 53)


def _sorted_prefix_f64(v, agg):
    """Inclusive prefix sums (n,) f64 of an int column, EXACT under the
    _prefix_exact_gate bound: intra-block cumsums run in int32 sized so
    they cannot overflow, block totals accumulate in f64 where every
    partial sum is an integer below 2^53."""
    n = v.shape[0]
    _, block = _int_prefix_bound(agg)
    m = v.astype(jnp.int32).reshape(n // block, block)
    intra = jnp.cumsum(m, axis=1)  # exact: block * bound < 2^31
    inter = jnp.cumsum(intra[:, -1].astype(jnp.float64))
    inter = jnp.concatenate([jnp.zeros(1), inter[:-1]])
    return (inter[:, None] + intra.astype(jnp.float64)).reshape(n)


def _segmented_scan(v, first, op):
    """Per-segment running reduce over sorted data: at index i, op over
    v[segment_start..i]. log2(n) associative-scan passes — no scatter."""
    def combine(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, op(va, vb)), fa | fb

    out, _ = jax.lax.associative_scan(combine, (v, first))
    return out


def _segment_sum_exact_i64(v, gid, num_segments, n, vmin=None, vmax=None,
                           indices_are_sorted=False):
    """Exact int64 per-segment sums built from int32 scatters.

    64-bit scatters are SOFTWARE-EMULATED on TPU (measured ~10x slower than
    the same scatter at 32 bits — the difference between 1.9s and 0.18s for
    16M rows), so the sum decomposes into b-bit limbs with b chosen so a
    per-group limb sum cannot overflow int32: rows * (2^b - 1) < 2^31.
    Negative values ride two's complement: sum(v) = sum(uint32(v)) - 2^32 *
    count(v < 0); the planner's static value bounds skip unreachable limbs
    and the negative-count pass entirely for non-negative columns."""
    v = v.astype(jnp.int32)
    u = v.astype(jnp.uint32)  # two's-complement reinterpretation
    b = max(1, min(16, 31 - max(1, n - 1).bit_length()))
    shifts, nonneg = _limb_shifts(vmin, vmax, b)
    total = jnp.zeros(num_segments, dtype=jnp.int64)
    for shift in shifts:
        limb = ((u >> shift) & jnp.uint32((1 << b) - 1)).astype(jnp.int32)
        s = jax.ops.segment_sum(limb, gid, num_segments=num_segments,
                                indices_are_sorted=indices_are_sorted)
        total = total + (s.astype(jnp.int64) << shift)
    if not nonneg:
        negs = jax.ops.segment_sum((v < 0).astype(jnp.int32), gid,
                                   num_segments=num_segments,
                                   indices_are_sorted=indices_are_sorted)
        total = total - (negs.astype(jnp.int64) << 32)
    return total


def _mxu_or_scatter_counts(mask, sid, num_slots):
    """Per-slot row counts: MXU one-hot matmul when the table fits its
    accumulator, 32-bit scatter otherwise. Returns (num_slots,) int64."""
    if mxu_groupby.supports(num_slots, 1):
        return mxu_groupby.limb_sums(
            (mask.astype(mxu_groupby.PLANE_DTYPE),), sid, num_slots)[0]
    return jax.ops.segment_sum(
        mask.astype(jnp.int32), sid,
        num_segments=num_slots).astype(jnp.int64)


_I32_MAX = (1 << 31) - 1
_I32_MIN = -(1 << 31)


def _limb_shifts(vmin, vmax, b):
    """Limb starting bits for an exact two's-complement int32 sum split
    into b-bit limbs, and whether the negative-count correction pass can be
    skipped (planner-proved non-negative columns)."""
    nonneg = vmin is not None and vmin >= 0
    nbits = 32
    if nonneg and vmax is not None:
        nbits = max(1, int(vmax).bit_length())
    return list(range(0, nbits, b)), nonneg


def _fits_i32(v, agg: ir.AggOp) -> bool:
    """The 32-bit fast paths are only sound when every value fits int32:
    either the plane is int32 already, or the planner proved bounds.
    LONG/TIMESTAMP columns are int64 planes — without bounds they take the
    float64 path (exact to 2^53, the pre-optimization behavior)."""
    if v.dtype == jnp.int32:
        return True
    return (agg.vmin is not None and agg.vmax is not None
            and agg.vmin >= _I32_MIN and agg.vmax <= _I32_MAX)


def _run_agg(agg: ir.AggOp, arrays, params, mask, gid, num_segments, n,
             counts=None):
    if agg.kind == "count":
        return jax.ops.segment_sum(mask.astype(jnp.int64), gid, num_segments=num_segments)
    if agg.kind in ("distinct_bitmap", "value_hist"):
        # per-(group, dictId) occupancy/count matrix — shipped to host so
        # distinct VALUE sets / exact value histograms (percentile, mode)
        # can merge across segments (dict ids are segment-local). When the
        # (groups x card) table fits the MXU accumulator, the counts ride
        # the one-hot matmul instead of a whole-column scatter (the
        # scatter unit costs ~7.7ns/row — ~0.8s per 100M-row pass).
        card = agg.card
        num_groups = num_segments - 1
        ids = arrays[agg.ids_slot].astype(jnp.int32)
        sid = gid * jnp.int32(card) + ids
        sid = jnp.where(mask, sid, jnp.int32(num_groups * card))
        occ = _mxu_or_scatter_counts(mask, sid, num_groups * card + 1)
        occ = occ[: num_groups * card].reshape(num_groups, card)
        return occ > 0 if agg.kind == "distinct_bitmap" else \
            occ.astype(jnp.int64)
    if agg.kind == "hist_adaptive":
        # percentile sketch: TWO MXU count passes replace the (groups x
        # 2048)-slot scatter histogram. Pass 1 bins values coarsely; the
        # per-group bucket holding the target quantile is found ON DEVICE
        # (cumsum over the small (groups, bins) table); pass 2 re-bins the
        # rows of exactly that bucket `bins`x finer. Effective resolution
        # at the quantile = range/bins^2 with 2*bins+1 output words per
        # group instead of 2048 (the reference's t-digest concentrates
        # centroids at the tails the same way; this concentrates around
        # the asked quantile).
        bins = agg.bins
        num_groups = num_segments - 1
        # the whole-column binning arithmetic runs in f32: the TPU has no
        # f64 ALU (XLA software-emulates it, ~10x), and bucket assignment
        # only needs edge precision — an edge-adjacent row landing one
        # bucket over moves the decoded quantile by ≤ 1 refined bucket,
        # already inside the stated range/bins^2 bound. The ONE op kept in
        # f64 is the (v - lo) rebase: casting v itself to f32 would round
        # by ulp(|v|), which for large-magnitude narrow-range columns
        # (epoch-millis) dwarfs the bucket width; the rebased offset has
        # magnitude ≤ (hi-lo) where f32 ulp is ~1e-7 of the range.
        # Membership between the two passes stays BIT-IDENTICAL because
        # pass 2 recomputes b1 with the same ops.
        lo64 = params[agg.lo_param]
        if agg.prebased:
            # the plane in HBM is already (v - lo) as f32 (the planner's
            # rawf32r slot; lo == the column min the plane was rebased by)
            v = _eval_value(agg.vexpr, arrays, params)
        else:
            v64 = _eval_value(agg.vexpr, arrays, params).astype(jnp.float64)
            v = (v64 - lo64).astype(jnp.float32)  # offset from lo, f32-safe
        span = jnp.float32(params[agg.hi_param] - lo64)
        width1 = span / bins
        b1 = jnp.clip((v / width1).astype(jnp.int32), 0, bins - 1)
        inside = mask & (v >= 0) & (v <= span)
        sid1 = jnp.where(inside, gid * jnp.int32(bins) + b1,
                         jnp.int32(num_groups * bins))
        h1 = _mxu_or_scatter_counts(inside, sid1, num_groups * bins + 1)
        h1 = h1[: num_groups * bins].reshape(num_groups, bins)
        cum = jnp.cumsum(h1, axis=1)
        rank = cum[:, -1].astype(jnp.float64) * (agg.pct / 100.0)
        bstar = jnp.argmax(cum.astype(jnp.float64) >= rank[:, None],
                           axis=1).astype(jnp.int32)
        # refine rows whose COARSE bin equals their group's target bucket
        # (b1 equality, not float range tests: bit-identical membership);
        # bucket offsets stay relative to lo, so all f32 magnitudes ≤ span
        bstar_pad = jnp.concatenate([bstar, jnp.zeros(1, jnp.int32)])
        bstar_r = bstar_pad[jnp.minimum(gid, num_groups)]
        lo_g = bstar.astype(jnp.float32) * width1
        lo_r = jnp.concatenate([lo_g, jnp.zeros(1, jnp.float32)])[
            jnp.minimum(gid, num_groups)]
        width2 = width1 / bins
        inside2 = inside & (b1 == bstar_r)
        b2 = jnp.clip(((v - lo_r) / width2).astype(jnp.int32), 0, bins - 1)
        sid2 = jnp.where(inside2, gid * jnp.int32(bins) + b2,
                         jnp.int32(num_groups * bins))
        h2 = _mxu_or_scatter_counts(inside2, sid2, num_groups * bins + 1)
        h2 = h2[: num_groups * bins].reshape(num_groups, bins)
        return jnp.concatenate(
            [h1, h2, bstar.astype(jnp.int64)[:, None]], axis=1)
    if agg.kind == "hist_fixed":
        # equal-width bins over [lo, hi]; out-of-range rows are dropped
        # (reference HistogramAggregationFunction semantics)
        bins = agg.bins
        num_groups = num_segments - 1
        v = _eval_value(agg.vexpr, arrays, params).astype(jnp.float64)
        lo = params[agg.lo_param]
        hi = params[agg.hi_param]
        width = (hi - lo) / bins
        b = jnp.clip(((v - lo) / width).astype(jnp.int32), 0, bins - 1)
        inside = mask & (v >= lo) & (v <= hi)
        sid = gid * jnp.int32(bins) + b
        sid = jnp.where(inside, sid, jnp.int32(num_groups * bins))
        counts = jax.ops.segment_sum(
            inside.astype(jnp.int32), sid, num_segments=num_groups * bins + 1
        ).astype(jnp.int64)
        return counts[: num_groups * bins].reshape(num_groups, bins)
    v = _eval_value(agg.vexpr, arrays, params)
    fast32 = jnp.issubdtype(v.dtype, jnp.integer) and _fits_i32(v, agg)
    if agg.kind == "sum":
        if fast32:
            vm = jnp.where(mask, v, 0)
            return _segment_sum_exact_i64(
                vm, gid, num_segments, n, agg.vmin, agg.vmax
            ).astype(jnp.float64)
        v = jnp.where(mask, v, 0).astype(jnp.float64)
        return jax.ops.segment_sum(v, gid, num_segments=num_segments)
    if agg.kind == "sumsq":
        v = jnp.where(mask, v, 0).astype(jnp.float64)
        return jax.ops.segment_sum(v * v, gid, num_segments=num_segments)
    if agg.kind == "min":
        if fast32 and counts is not None:
            # masked rows route to the trash slot, so each group's scatter
            # sees only real values; EMPTY groups are detected by the count
            # column (never by a sentinel a real value could collide with)
            vm = jnp.where(mask, v.astype(jnp.int32), _I32_MAX)
            out = jax.ops.segment_min(vm, gid, num_segments=num_segments)
            return jnp.where(counts == 0, jnp.inf, out.astype(jnp.float64))
        if v.dtype == jnp.float32:
            vm = jnp.where(mask, v, jnp.float32(jnp.inf))
            return jax.ops.segment_min(
                vm, gid, num_segments=num_segments).astype(jnp.float64)
        v = jnp.where(mask, v, jnp.inf).astype(jnp.float64)
        return jax.ops.segment_min(v, gid, num_segments=num_segments)
    if agg.kind == "max":
        if fast32 and counts is not None:
            vm = jnp.where(mask, v.astype(jnp.int32), _I32_MIN)
            out = jax.ops.segment_max(vm, gid, num_segments=num_segments)
            return jnp.where(counts == 0, -jnp.inf, out.astype(jnp.float64))
        if v.dtype == jnp.float32:
            vm = jnp.where(mask, v, jnp.float32(-jnp.inf))
            return jax.ops.segment_max(
                vm, gid, num_segments=num_segments).astype(jnp.float64)
        v = jnp.where(mask, v, -jnp.inf).astype(jnp.float64)
        return jax.ops.segment_max(v, gid, num_segments=num_segments)
    raise ValueError(f"unknown agg kind {agg.kind}")


# ---------------------------------------------------------------------------
# Device-side sparse combine (server-level merge of per-segment group tables)
# ---------------------------------------------------------------------------

# empty merged-table slots carry this key; above any real dictionary VALUE
# (sparse value-space keys are int64 dictionary values, not composite ids)
COMBINE_KEY_SENTINEL = 1 << 62


@jax.jit
def ids_to_values_i64(keys, dict_plane):
    """Translate one segment's sparse key output (dict IDS; -1 = empty slot)
    into dictionary VALUE space. Dictionaries are segment-local (the same id
    means different values in different segments — engine/results.py), so
    cross-segment merge keys must be values. int64 holds every integer dict
    exactly; empty slots map to the sort sentinel so they tail the merge."""
    card = dict_plane.shape[0]
    ids = jnp.clip(keys, 0, card - 1).astype(jnp.int32)
    return jnp.where(keys >= 0, dict_plane[ids].astype(jnp.int64),
                     jnp.int64(COMBINE_KEY_SENTINEL))


@partial(jax.jit, static_argnames=("kinds",))
def combine_sparse_group_tables(seg_keys, seg_counts, seg_states, kinds):
    """Merge S per-segment sparse group tables ON DEVICE.

    Replaces the host-side factorize+scatter merge (combine.py
    combine_group_arrays) for single-key sparse group-bys: per-segment
    tables are already key-sorted, so the merge is the SAME
    sort/edges/segmented-scan machinery as _run_sparse_group_by, over
    S*K rows instead of n docs — and only the merged table crosses to host.

    seg_keys:   S × (K,) int64 VALUE-space keys (ids_to_values_i64 output)
    seg_counts: S × (K+1,) int64 count columns (slot K = trash)
    seg_states: S × tuple of (K+1,) state columns (one per Program agg op,
                in op order — count copies are int64, the rest f64)
    kinds:      per state column: "add" | "min" | "max" (static)

    Returns (counts(M+1) i64, *states(M+1), keys(M) i64) with M = S*K — the
    per-segment output layout, so LoweredAgg.vec.extract decodes it
    unchanged. All merged groups are kept (M slots hold the worst-case
    union) for bit-for-bit parity with the host merge; the ordered
    server-level trim still runs downstream on the single merged table.
    """
    key = jnp.concatenate(seg_keys)
    cnt = jnp.concatenate([c[:-1] for c in seg_counts])
    trash = sum(c[-1] for c in seg_counts)
    states = [jnp.concatenate([s[i][:-1] for s in seg_states])
              for i in range(len(kinds))]
    m = key.shape[0]
    # sort-iota + gather, same as the n-row kernel: permute only (key, iota)
    skey, perm = jax.lax.sort(
        (key, jnp.arange(m, dtype=jnp.int32)), num_keys=1)
    cnt = cnt[perm]
    states = [s[perm] for s in states]
    valid = skey < jnp.int64(COMBINE_KEY_SENTINEL)
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), skey[1:] != skey[:-1]]) & valid
    gidx = jnp.cumsum(first.astype(jnp.int32)) - 1
    gidx_m = jnp.where(valid, gidx, jnp.int32(1 << 30))
    edges = jnp.searchsorted(gidx_m, jnp.arange(m + 1, dtype=jnp.int32))
    fi = edges[:m]
    li = jnp.maximum(edges[1:] - 1, fi)
    fic = jnp.clip(fi, 0, m - 1)
    lic = jnp.clip(li, 0, m - 1)
    occupied = edges[1:] > edges[:-1]
    pc = jnp.cumsum(jnp.where(valid, cnt, 0))
    counts_m = jnp.where(
        occupied,
        pc[lic] - pc[fic] + jnp.where(valid[fic], cnt[fic], 0), 0)
    outs = [jnp.concatenate([counts_m, trash[None]])]
    for v, kind in zip(states, kinds):
        if kind == "add":
            vz = jnp.where(valid, v, jnp.zeros((), v.dtype))
            s = _segmented_scan(vz, first, jnp.add)[lic]
            merged = jnp.where(occupied, s, jnp.zeros((), v.dtype))
            tail = jnp.zeros((1,), v.dtype)
        elif kind == "min":
            vz = jnp.where(valid, v, jnp.inf)
            s = _segmented_scan(vz, first, jnp.minimum)[lic]
            merged = jnp.where(occupied, s, jnp.inf)
            tail = jnp.full((1,), jnp.inf)
        else:  # max
            vz = jnp.where(valid, v, -jnp.inf)
            s = _segmented_scan(vz, first, jnp.maximum)[lic]
            merged = jnp.where(occupied, s, -jnp.inf)
            tail = jnp.full((1,), -jnp.inf)
        outs.append(jnp.concatenate([merged, tail]))
    outs.append(jnp.where(occupied, skey[fic], jnp.int64(-1)))
    return tuple(outs)
