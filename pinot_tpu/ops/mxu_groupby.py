"""Dense group-by sums on the MXU: Kronecker-factored one-hot matmuls.

The TPU-first answer to the reference's `DefaultGroupByExecutor` hot loop
(pinot-core/.../query/aggregation/groupby/DefaultGroupByExecutor.java:191):
instead of scatter-adds (7-8ns/update on the TPU scatter unit — a 100M-row
group-by with several payload planes costs seconds) or hash maps, the dense
group key is split into a 7-bit low half and a high half, and the whole
reduction becomes a matmul chain the systolic array executes near peak:

    out[hi, p*128+lo]  +=  oh_hi[hi, row] @ (plane_p[row] * oh_lo[row, lo])

where ``oh_hi`` is the one-hot of ``gid >> 7`` (S1 x B) and the right operand
stacks every payload plane scaled by the one-hot of ``gid & 127`` (B x P*128).
One MXU pass of (S1 x B) @ (B x P*128) replaces P scatters over B rows; for
S1 <= 128 the cost per row is *independent of the group count*, and all
payload planes ride the same pass.

Exactness: payloads must be small non-negative integers. The default plane
dtype is **int8 with 7-bit limbs** (values in [0, 127]): v5e executes s8xs8
matmuls at twice the bf16 rate with native i32 accumulation, and the planes
cost half the HBM bandwidth of bf16. Per-superblock i32 accumulation is
exact (SB_ROWS * 127 < 2^31); superblock partials are summed in int64
outside the kernel. Setting PINOT_TPU_MXU_INT8=0 falls back to bf16 planes
with 8-bit limbs ([0, 255] — bf16-exact; per-block f32 accumulation exact
because B * 255 < 2^24).

Masked rows must already be routed to a trash slot by the caller (the dense
planner convention: gid == num_segments - 1), with zeroed payloads.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

# exact i64 totals (engine-wide invariant, see ops/kernels.py)
jax.config.update("jax_enable_x64", True)

LANES = 128
SUBLANES = 8
# row-blocks per grid step: each step reduces G*8*128 rows with one batched
# MXU pass (batch dim G*8, contraction dim 128). G trades VMEM for fewer
# grid steps.
G_TILES = 4
BLOCK_ROWS = G_TILES * SUBLANES * LANES  # 4096
# superblock = rows whose limb sums stay exact in the i32 accumulator:
# SB_ROWS * 255 < 2^31
SB_BLOCKS = 256
SB_ROWS = SB_BLOCKS * BLOCK_ROWS  # ~1M
# above this many group slots the (S1, P*128) accumulator stops fitting
# comfortably in VMEM next to the one-hot operands
MAX_GROUPS = 1 << 15

# int8 MXU path (2x matmul rate + half the plane bandwidth on v5e).
# PINOT_TPU_MXU_INT8=0 reverts to bf16/8-bit limbs.
_INT8 = os.environ.get("PINOT_TPU_MXU_INT8", "1") != "0"
PLANE_DTYPE = jnp.int8 if _INT8 else jnp.bfloat16
LIMB_BITS = 7 if _INT8 else 8
# int8 planes cost half the VMEM of bf16 AND 7-bit limbs need one more
# plane per signed-i32 sum (5+neg vs 4+neg) — scale the plane budget so a
# 3x signed-SUM query (1 + 3*6 = 19 planes) still rides one MXU pass
MAX_PLANES = 24 if _INT8 else 16


def backend_platform() -> str:
    """The default jax backend's platform, or 'cpu' when backend init
    fails. A flapping accelerator plugin (the axon tunnel going
    unavailable mid-process) must degrade path SELECTION, never raise
    into a query."""
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def supports(num_segments: int, num_planes: int) -> bool:
    if not (0 < num_planes <= MAX_PLANES and num_segments <= MAX_GROUPS):
        return False
    # accumulator block is (num_planes * s1, 128) i32 — bound the product
    # so it stays ~2 MB of VMEM next to the one-hot operands
    s1 = max(1, -(-num_segments // LANES))
    return num_planes * s1 <= 4096


def limb_sums(planes, gid, num_segments: int, *, interpret: bool = False):
    """Sum each plane per group: planes P x (n,) of PLANE_DTYPE holding
    integer limb values in [0, 2**LIMB_BITS - 1] (int8 planes: [0, 127];
    bf16 planes: [0, 255]), gid (n,) int32 in [0, num_segments); returns
    (P, num_segments) int64. Uses the Pallas MXU kernel on TPU, a
    kron-factored XLA matmul elsewhere (interpret=True forces the Pallas
    kernel in interpret mode for kernel-parity tests)."""
    assert supports(num_segments, len(planes))
    if interpret or backend_platform() == "tpu":
        return _pallas_limb_sums(tuple(planes), gid, num_segments,
                                 interpret=interpret)
    return _xla_limb_sums(tuple(planes), gid, num_segments)


# -- shared geometry ---------------------------------------------------------


def _geometry(n: int, num_segments: int):
    s1 = max(1, -(-num_segments // LANES))
    blocks = max(1, -(-n // BLOCK_ROWS))
    bpsb = min(SB_BLOCKS, blocks)
    nsb = -(-blocks // bpsb)
    n_pad = nsb * bpsb * BLOCK_ROWS
    return s1, bpsb, nsb, n_pad


def _pad_inputs(planes, gid, num_segments, n_pad):
    n = gid.shape[0]
    if n_pad != n:
        # padding rows join the caller's trash slot with zero payloads
        gid = jnp.pad(gid, (0, n_pad - n),
                      constant_values=np.int32(num_segments - 1))
        planes = tuple(jnp.pad(p, (0, n_pad - n)) for p in planes)
    return planes, gid


# -- Pallas TPU kernel -------------------------------------------------------


def _kernel(s1: int, num_planes: int, gid_ref, *rest):
    from jax.experimental import pallas as pl

    plane_refs = rest[:num_planes]
    out_ref = rest[num_planes]
    j = pl.program_id(1)
    nb = G_TILES * SUBLANES  # batch dim of the MXU pass
    # leading-dim collapse (G, 8, 128) -> (G*8, 128): pure addressing, no
    # sublane/lane relayout
    g = gid_ref[...].reshape(nb, LANES)
    mats = [pr[...].reshape(nb, LANES) for pr in plane_refs]
    _matmul_tail(g, mats, s1, out_ref, j)


def _matmul_tail(g, mats, s1: int, out_ref, j):
    """The one-hot matmul chain shared by the pre-materialized-plane kernel
    (`_kernel`) and the fused filter+gid+limb kernel
    (ops/fused_groupby.py): g (nb, 128) int32 gids, mats P x (nb, 128)
    PLANE_DTYPE limb values, accumulated into out_ref block (1, P*s1, 128)
    i32 across the j grid axis."""
    from jax.experimental import pallas as pl

    num_planes = len(mats)
    # int8 planes ride the s8xs8->i32 MXU mode (2x bf16 rate on v5e);
    # bf16 planes keep the f32-accumulating dot
    int8 = mats[0].dtype == jnp.int8
    oh_dt = jnp.int8 if int8 else jnp.bfloat16
    acc_dt = jnp.int32 if int8 else jnp.float32
    nb = g.shape[0]
    hi = g >> 7
    lo = g & (LANES - 1)

    def mid(x, m):
        # (nb, LANES) -> (nb, m, LANES): stride-0 sublane broadcast; rows
        # stay on the minor (lane) dim — the only relayout Mosaic rejects
        # is moving lanes off minor
        return jax.lax.broadcast_in_dim(x, (nb, m, LANES), (0, 2))

    # Planes fold into the MATMUL'S M DIMENSION (one (nb, Pg*s1, C) lhs
    # against a SHARED lo one-hot rhs) rather than into N as P separate
    # matmuls: M = Pg*s1 fills the systolic array's 128-row tiles ~2x
    # better than s1 alone (s1 is ~55 for a 7K-group query — a 43% fill),
    # and the rhs one-hot + per-plane multiplies collapse into one
    # compare + P selects. Same MAC count, much higher MXU occupancy.
    # Planes chunk so the lhs + dot output stay within VMEM at the
    # largest supported s1 (256). The binding buffer is the i32/f32 dot
    # OUTPUT (nb, Pg*s1, 128) — 4 bytes per element on BOTH dtypes — so
    # the Pg*s1 <= 384 budget holds for int8 too (a larger int8 chunk
    # would only shrink the 1-byte lhs while doubling the accumulator).
    # one-hot + multiply (not a bool mask + select: Mosaic rejects
    # the i1 relayout when the mask is reused across plane chunks)
    oh_hi = (jax.lax.broadcasted_iota(jnp.int32, (nb, s1, LANES), 1)
             == mid(hi, s1)).astype(oh_dt)
    rhs = (jax.lax.broadcasted_iota(jnp.int32, (nb, LANES, LANES), 1)
           == mid(lo, LANES)).astype(oh_dt)  # (nb, L, C)
    pg = max(1, 384 // s1)
    # both operands keep the contraction (row) dim minor — an NT matmul,
    # the same shape attention uses for q @ k^T (Mosaic supports exactly
    # one contracting dim, so nb stays a batch dim and the batch outputs
    # sum after). Accumulation is exact on both paths: i32 native for s8
    # dots; f32 for bf16 (each dot sums 128 values <= 255 and the batch
    # sum stays below 2^24).
    dn = (((2,), (2,)), ((0,), (0,)))
    parts = []
    for start in range(0, num_planes, pg):
        lhs = jnp.concatenate(
            [oh_hi * mid(pm.astype(oh_dt), s1)
             for pm in mats[start:start + pg]], axis=1)
        out = jax.lax.dot_general(lhs, rhs, dn,
                                  preferred_element_type=acc_dt)
        parts.append(out.sum(axis=0))  # (Pg*s1, L)
    part = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]

    @pl.when(j == 0)
    def _init():
        out_ref[0] = part.astype(jnp.int32)

    @pl.when(j != 0)
    def _acc():
        out_ref[0] = out_ref[0] + part.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def _pallas_limb_sums(planes, gid, num_segments: int, interpret: bool = False):
    from jax.experimental import pallas as pl

    num_planes = len(planes)
    n = gid.shape[0]
    s1, bpsb, nsb, n_pad = _geometry(n, num_segments)
    planes, gid = _pad_inputs(planes, gid, num_segments, n_pad)

    nb = n_pad // (SUBLANES * LANES)
    gid2 = gid.reshape(nb, SUBLANES, LANES)
    planes2 = [p.reshape(nb, SUBLANES, LANES) for p in planes]

    zero = np.int32(0)  # literal 0 traces as i64 under x64; Mosaic needs i32
    row_spec = pl.BlockSpec((G_TILES, SUBLANES, LANES),
                            lambda i, j: (i * bpsb + j, zero, zero))
    out = pl.pallas_call(
        functools.partial(_kernel, s1, num_planes),
        grid=(nsb, bpsb),
        in_specs=[row_spec] * (1 + num_planes),
        out_specs=pl.BlockSpec((1, num_planes * s1, LANES),
                               lambda i, j: (i, zero, zero)),
        out_shape=jax.ShapeDtypeStruct((nsb, num_planes * s1, LANES),
                                       jnp.int32),
        interpret=interpret,
    )(gid2, *planes2)

    # (nsb, P*S1, 128) --sum--> (P*S1, 128) --> (P, S1*128) --> trim
    total = out.astype(jnp.int64).sum(axis=0)
    return total.reshape(num_planes, s1 * LANES)[:, :num_segments]


# -- XLA fallback (CPU / virtual meshes) -------------------------------------


@functools.partial(jax.jit, static_argnames=("num_segments",))
def _xla_limb_sums(planes, gid, num_segments: int):
    stacked = jnp.stack(planes, axis=0)  # (P, n): n minor — no lane padding
    sums = jax.vmap(
        lambda p: jax.ops.segment_sum(p.astype(jnp.float64), gid,
                                      num_segments=num_segments))(stacked)
    return sums.astype(jnp.int64)
