"""Multi-device execution: row-sharded segments over a jax Mesh.

This is the capability the reference lacks (SURVEY.md §2.10: "the analogue —
splitting one segment's rows across workers — does not exist in Pinot; the
segment is the atom"). Here one large segment's column planes shard across
TPU cores on a mesh row axis; every device runs the same fused kernel on its
row slice and the per-group partials combine with XLA collectives riding ICI:

    sum/count/sumsq      → psum
    min / max            → pmin / pmax
    distinct occupancy   → any() via pmax
    selection mask       → stays sharded (masks are row-aligned)

A second mesh axis shards *segments* (scatter/gather parallelism, the
reference's per-server fan-out), giving the dp×sp layout used by
__graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import os
import time
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import ir
from ..ops.kernels import PackedOuts, _apply_packed, _pack_u8, _run_program_impl

ROW_AXIS = "sp"  # intra-segment row sharding (sequence-parallel analogue)
SEGMENT_AXIS = "dp"  # across segments (data-parallel analogue)


def shard_map_compat(f, **kwargs):
    """jax.shard_map with a fallback for jax 0.4.x, where it still lives in
    jax.experimental.shard_map and `check_vma` is spelled `check_rep`."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return shard_map(f, **kwargs)


def make_mesh(n_devices: int | None = None, axes=(ROW_AXIS,)) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    arr = np.array(devices)
    if len(axes) == 2:
        # favor more row-shards than segment-shards
        n = len(devices)
        seg = 2 if n % 2 == 0 and n > 2 else 1
        arr = arr.reshape(seg, n // seg)
    return Mesh(arr, axes)


def _combine_collectives(program: ir.Program, outs: tuple, axis: str) -> tuple:
    """Merge per-shard kernel outputs across the row axis."""
    merged = [jax.lax.psum(outs[0], axis)]
    for agg, o in zip(program.aggs, outs[1:]):
        if agg.kind in ("sum", "sumsq", "count"):
            merged.append(jax.lax.psum(o, axis))
        elif agg.kind == "min":
            merged.append(jax.lax.pmin(o, axis))
        elif agg.kind == "max":
            merged.append(jax.lax.pmax(o, axis))
        elif agg.kind == "distinct_bitmap":
            merged.append(jax.lax.pmax(o.astype(jnp.int32), axis) > 0)
        elif agg.kind in ("value_hist", "hist_fixed"):
            merged.append(jax.lax.psum(o, axis))  # per-(group,bin) counts add
        else:  # pragma: no cover
            raise ValueError(agg.kind)
    return tuple(merged)


def _mask_param_indices(node) -> frozenset:
    """Param slots holding host-evaluated doc-mask planes (ir.MaskParam) —
    those are row-aligned and must shard with the row axis."""
    if node is None:
        return frozenset()
    if isinstance(node, ir.MaskParam):
        return frozenset((node.idx,))
    if isinstance(node, (ir.FAnd, ir.FOr)):
        out = frozenset()
        for c in node.children:
            out |= _mask_param_indices(c)
        return out
    if isinstance(node, ir.FNot):
        return _mask_param_indices(node.child)
    return frozenset()


def slot_specs(slots) -> tuple:
    """PartitionSpecs per kernel input slot: row planes shard on ROW_AXIS,
    dictionaries replicate. Driven by slot KIND, never by shape (a dictionary
    whose cardinality equals the pad bucket must still replicate)."""
    return tuple(P() if kind == "dict" else P(ROW_AXIS) for _col, kind in slots)


@partial(jax.jit, static_argnames=("program", "padded", "mesh", "kinds",
                                   "fused", "lut_meta"))
def _row_sharded_call(program: ir.Program, arrays: tuple, params: tuple, num_docs,
                      padded: int, mesh: Mesh, kinds: tuple,
                      fused: str = "", lut_meta: tuple = ()):
    n_shards = mesh.shape[ROW_AXIS]
    local_n = padded // n_shards
    array_specs = tuple(P() if k == "dict" else P(ROW_AXIS) for k in kinds)
    fp = None
    if fused and program.mode == "group_by":
        # static dtype/ndim analysis — shard dtypes equal global dtypes,
        # so plan once OUTSIDE shard_fn (also scopes check_vma below to
        # programs that genuinely run the fused kernel)
        from ..ops import fused_groupby

        fp = fused_groupby.plan(program, arrays, lut_meta)

    def shard_fn(arrays_l, params_l, num_docs_l):
        idx = jax.lax.axis_index(ROW_AXIS)
        offset = idx.astype(jnp.int32) * jnp.int32(local_n)
        if fp is not None:
            # per-shard fused kernel; table outputs psum over ICI exactly
            # like the two-step path (same output contract)
            from ..ops import fused_groupby

            outs = fused_groupby.execute(
                fp, program, arrays_l, params_l, num_docs_l, local_n,
                offset, interpret=(fused == "interpret"))
            return _combine_collectives(program, outs, ROW_AXIS)
        outs = _run_program_impl(program, arrays_l, params_l, num_docs_l, local_n, offset)
        if program.mode == "selection":
            return outs  # masks stay row-sharded
        return _combine_collectives(program, outs, ROW_AXIS)

    mask_idxs = _mask_param_indices(program.filter)
    param_specs = tuple(
        P(ROW_AXIS) if i in mask_idxs else P() for i in range(len(params)))
    out_specs = P(ROW_AXIS) if program.mode == "selection" else P()
    fn = shard_map_compat(
        shard_fn, mesh=mesh,
        in_specs=(array_specs, param_specs, P()),
        out_specs=out_specs,
        # the fused pallas_call's out_shape carries no varying-mesh-axes
        # annotation, so the vma check cannot validate it; keep the check
        # ON for every path that doesn't actually run the fused kernel
        # (it catches missing collective merges at trace time)
        check_vma=fp is None,
    )
    return fn(arrays, params, num_docs)


def run_program_row_sharded(program: ir.Program, arrays: tuple, params: tuple,
                            num_docs, padded: int, mesh: Mesh, slots=None,
                            fused: str = "", lut_meta: tuple = ()):
    """Execute one segment's program with rows sharded across mesh[ROW_AXIS].

    `arrays` are global (padded) planes; `padded` must divide evenly by the
    row-axis size. Group-by/aggregation outputs come back fully combined
    (every device holds the final table — cheap, tables are small). The jitted
    executable is cached on (program, padded, mesh, slot kinds) so repeated
    queries over resident shards skip tracing entirely.
    """
    if program.mode == "group_by_sparse":
        # keyed (sorted) outputs can't psum-merge across shards; the caller
        # runs sparse programs whole-segment and merges at combine instead
        raise ValueError("sparse group-by does not row-shard; run unsharded")
    if any(op.kind == "hist_adaptive" for op in program.aggs):
        # each shard refines a DIFFERENT per-group bucket (data-dependent),
        # so the refined histograms are not psum-mergeable
        raise ValueError("adaptive histograms do not row-shard; run unsharded")
    if program.mv_group_slot is not None:
        # the MV expansion's trailing scanned-docs output has no psum merge
        # wired; run whole-segment (matrix planes also shard per-doc rows
        # only, which _combine_collectives does not model)
        raise ValueError("MV group-by does not row-shard; run unsharded")
    n_shards = mesh.shape[ROW_AXIS]
    assert padded % n_shards == 0, (padded, n_shards)
    kinds = tuple(kind for _col, kind in slots) if slots else tuple(
        "dict" if (a.ndim >= 1 and a.shape[0] != padded) else "ids" for a in arrays)
    return _row_sharded_call(program, arrays, params, jnp.int32(num_docs),
                             padded, mesh, kinds, fused=fused,
                             lut_meta=lut_meta)


# ---------------------------------------------------------------------------
# Segment-axis sharding for batch families (ISSUE 12).
#
# PR-3 stacks a family's segments into [S, N] planes and vmaps one program
# over the stack on a single chip. Here the SAME stacked arrays shard across
# mesh[SEGMENT_AXIS] instead: each device vmaps over its local S/ndev rows,
# so one dispatch runs the whole family on every local chip concurrently.
# Per-row math is byte-for-byte the solo vmap body, which is what makes the
# mesh path bit-identical to `SET meshExecution=false`.
# ---------------------------------------------------------------------------


def mesh_device_count() -> int:
    """Local devices the segment-axis mesh may span, capped by the
    PINOT_TPU_MESH_DEVICES env knob (<=1 disables mesh execution)."""
    try:
        n = len(jax.devices())
    except Exception:  # backend init failure → solo execution
        return 1
    cap = os.environ.get("PINOT_TPU_MESH_DEVICES")
    if cap:
        try:
            n = min(n, int(cap))
        except ValueError:
            pass
    return max(1, n)


@lru_cache(maxsize=None)
def segment_mesh(ndev: int) -> Mesh:
    """1-D mesh over the first `ndev` local devices on SEGMENT_AXIS."""
    return Mesh(np.array(jax.devices()[:ndev]), (SEGMENT_AXIS,))


def segment_sharding(ndev: int, ndim: int) -> NamedSharding:
    """NamedSharding splitting the leading (stack) dim across the mesh."""
    return NamedSharding(segment_mesh(ndev),
                         P(SEGMENT_AXIS, *([None] * (ndim - 1))))


def mesh_devices(ndev: int) -> list:
    return list(jax.devices()[:ndev])


@partial(jax.jit, static_argnames=("program", "padded", "packed", "ndev"))
def _batch_sharded_call(program: ir.Program, arrays: tuple, params: tuple,
                        num_docs, padded: int, packed: tuple, ndev: int):
    mesh = segment_mesh(ndev)

    def shard_fn(arrays_l, params_l, num_docs_l):
        # mirror run_program_batch exactly: widen packed planes, then vmap
        # the per-segment impl over the (local) stack rows
        arrays_w = _apply_packed(arrays_l, packed)

        def one(arrays_s, params_s, nd):
            return _run_program_impl(program, arrays_s, params_s, nd, padded)

        return jax.vmap(one)(arrays_w, params_l, num_docs_l)

    fn = shard_map_compat(
        shard_fn, mesh=mesh,
        in_specs=(tuple(P(SEGMENT_AXIS) for _ in arrays),
                  tuple(P(SEGMENT_AXIS) for _ in params),
                  P(SEGMENT_AXIS)),
        out_specs=P(SEGMENT_AXIS),
        # outputs vary per stack row by construction; skip the vma/rep
        # analysis so every program mode the solo vmap supports shards
        check_vma=False,
    )
    return fn(arrays, params, num_docs)


def run_program_batch_sharded(program: ir.Program, arrays: tuple, params: tuple,
                              num_docs, padded: int, ndev: int,
                              packed: tuple = ()):
    """run_program_batch with the stack dim sharded over mesh[SEGMENT_AXIS].

    `arrays`/`params`/`num_docs` are the family stacks padded to a multiple
    of `ndev` rows (ragged remainders repeat the last member with num_docs=0
    — the impl's row-validity mask makes those slots contribute nothing).
    Outputs come back [S_pad, ...] sharded on SEGMENT_AXIS; callers slice or
    gather on device (`pack_outputs_gathered` / `gather_outputs`).
    """
    return _batch_sharded_call(program, tuple(arrays), tuple(params),
                               num_docs, padded, tuple(packed), ndev)


@partial(jax.jit, static_argnames=("s_real",))
def _pack_sliced(outs: tuple, s_real: int):
    # drop the ragged pad rows on device, then byte-pack exactly like the
    # solo path so the host sees identical flat bytes
    return _pack_u8(tuple(o[:s_real] for o in outs))


def pack_outputs_gathered(outs: tuple, s_real: int) -> PackedOuts:
    """Device-side cross-chip combine for the packed (dense) path: slice the
    pad rows, byte-pack on device, and commit the flat to device 0 so it
    concatenates with solo packs and crosses to host exactly once."""
    metas = [(np.dtype(str(o.dtype)), (s_real,) + tuple(o.shape[1:]))
             for o in outs]
    flat = jax.device_put(_pack_sliced(tuple(outs), s_real), jax.devices()[0])
    return PackedOuts(flat, metas)


@partial(jax.jit, static_argnames=("s_real", "ndev"))
def _pack_collective(outs: tuple, s_real: int, ndev: int):
    mesh = segment_mesh(ndev)

    def shard_fn(outs_l):
        # all-gather the family stacks over ICI so every chip holds the
        # full [S_pad, ...] outputs, then slice + byte-pack locally — the
        # byte order is exactly _pack_sliced's, so the host decode is shared
        gathered = tuple(
            jax.lax.all_gather(o, SEGMENT_AXIS, axis=0, tiled=True)
            for o in outs_l)
        return _pack_u8(tuple(g[:s_real] for g in gathered))

    fn = shard_map_compat(
        shard_fn, mesh=mesh,
        in_specs=(tuple(P(SEGMENT_AXIS) for _ in outs),),
        out_specs=P(),
        # the gathered pack is replicated by construction; skip the rep
        # analysis for the same reason _batch_sharded_call does
        check_vma=False,
    )
    return fn(tuple(outs))


def pack_outputs_collective(outs: tuple, s_real: int, ndev: int) -> PackedOuts:
    """Mesh-collective variant of pack_outputs_gathered: the shuffle to one
    chip happens INSIDE the sharded program (all_gather over the segment
    axis) and every chip byte-packs the full stack, instead of funneling raw
    outputs to device 0 with per-output device_puts first. One collective +
    one pack kernel; the flat is replicated, so the host still crosses once."""
    metas = [(np.dtype(str(o.dtype)), (s_real,) + tuple(o.shape[1:]))
             for o in outs]
    flat = jax.device_put(_pack_collective(tuple(outs), s_real, ndev),
                          jax.devices()[0])
    return PackedOuts(flat, metas)


def gather_outputs(outs: tuple, s_real: int) -> tuple:
    """Cross-chip gather for the raw path (sparse device combine): commit
    every [S_pad, ...] output to device 0 over ICI — no host crossing — so
    downstream per-row slices and `combine_sparse_group_tables` colocate
    with device-0-resident dictionaries."""
    dev0 = jax.devices()[0]
    return tuple(jax.device_put(o[:s_real], dev0) for o in outs)


def block_per_device(outs: tuple, ndev: int, t0: float) -> list:
    """Block each mesh device's output shards in device order; returns
    [(device_id, ms_since_t0)] — the per-chip deviceExecMs attribution for
    traced dispatches (monotone: chip i's stamp includes chips 0..i-1)."""
    stamps = []
    for d in jax.devices()[:ndev]:
        for o in outs:
            for sh in getattr(o, "addressable_shards", ()):
                if sh.device == d:
                    sh.data.block_until_ready()
        stamps.append((d.id, round((time.perf_counter() - t0) * 1000.0, 3)))
    return stamps


def shard_segment_arrays(arrays: tuple, mesh: Mesh, padded: int, slots=None):
    """Pre-place padded planes with row sharding so repeated queries reuse
    device-resident shards (the multi-device HBM segment cache)."""
    if slots is not None:
        specs = slot_specs(slots)
    else:
        specs = tuple(P(ROW_AXIS) if a.ndim >= 1 and a.shape[0] == padded else P()
                      for a in arrays)
    return tuple(
        jax.device_put(a, NamedSharding(mesh, s)) for a, s in zip(arrays, specs)
    )
