"""Plugin families (reference: pinot-plugins/ — stream ingestion, file
systems, input formats, batch runners, metrics). Stream plugins live in
spi/stream.py; filesystem plugins in spi/filesystem.py; input formats here."""
