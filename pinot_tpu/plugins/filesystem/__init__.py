"""Object-store PinotFS plugins (reference: pinot-plugins/pinot-file-system).

Importing a module registers its URI scheme with spi/filesystem.py;
`get_fs` auto-imports ``pinot_tpu.plugins.filesystem.<scheme>`` on first
use. Cloud SDKs are optional dependencies resolved lazily — each plugin
exposes an injectable client factory so tests (and alternate SDKs) run the
full FS surface against fakes.
"""
