"""ADLS Gen2 PinotFS (reference: pinot-plugins/pinot-file-system/
pinot-adls/AzurePinotFS.java).

Azure Data Lake's blob namespace is flat like S3's, so this plugin adapts
the ``azure-storage-blob`` container client onto the S3 client surface and
reuses S3PinotFS's prefix-directory logic. URI form:
``adl2://<account>/<container-and-path>`` — the "bucket" is the container,
resolved through the account-level service client. The SDK is optional and
lazily imported.
"""

from __future__ import annotations

import io
from typing import Callable

from ...spi.filesystem import register_fs
from .s3 import S3PinotFS


class _AdlsClientAdapter:
    def __init__(self, service_client):
        self.service = service_client

    def _blob(self, container, key):
        return self.service.get_blob_client(container=container, blob=key)

    def put_object(self, Bucket, Key, Body=b""):
        self._blob(Bucket, Key).upload_blob(Body, overwrite=True)

    def get_object(self, Bucket, Key):
        data = self._blob(Bucket, Key).download_blob().readall()
        return {"Body": io.BytesIO(data)}

    def head_object(self, Bucket, Key):
        props = self._blob(Bucket, Key).get_blob_properties()
        return {"ContentLength": props.size}

    def delete_object(self, Bucket, Key):
        self._blob(Bucket, Key).delete_blob()

    def list_objects_v2(self, Bucket, Prefix, ContinuationToken=None):
        cc = self.service.get_container_client(Bucket)
        names = [{"Key": b.name} for b in
                 cc.list_blobs(name_starts_with=Prefix)]
        return {"Contents": names, "IsTruncated": False}

    def copy_object(self, Bucket, Key, CopySource):
        src_url = self._blob(CopySource["Bucket"], CopySource["Key"]).url
        self._blob(Bucket, Key).start_copy_from_url(src_url)


def _default_client_factory():
    try:
        from azure.storage.blob import (  # type: ignore[import-not-found]
            BlobServiceClient,
        )
        from azure.identity import (  # type: ignore[import-not-found]
            DefaultAzureCredential,
        )
    except ImportError as e:
        raise ImportError(
            "scheme 'adl2' needs the azure-storage-blob + azure-identity "
            "packages (or inject AdlsPinotFS.client_factory)") from e
    import os

    account = os.environ.get("AZURE_STORAGE_ACCOUNT_URL")
    return _AdlsClientAdapter(
        BlobServiceClient(account, credential=DefaultAzureCredential()))


class AdlsPinotFS(S3PinotFS):
    client_factory: Callable = staticmethod(_default_client_factory)
    schemes: tuple = ("adl2", "abfs", "abfss")


register_fs("adl2", AdlsPinotFS)
register_fs("abfs", AdlsPinotFS)
register_fs("abfss", AdlsPinotFS)
