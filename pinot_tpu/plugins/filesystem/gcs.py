"""GCS PinotFS (reference: pinot-plugins/pinot-file-system/pinot-gcs/
GcsPinotFS.java).

GCS's flat namespace has the same directory-marker semantics as S3, and
``google-cloud-storage``'s client surface maps almost 1:1 onto the S3
operations this tree already implements — so this plugin adapts the GCS
client to the S3 client surface and reuses S3PinotFS wholesale rather than
re-deriving the prefix logic. The SDK is optional and lazily imported.
"""

from __future__ import annotations

from typing import Callable

from ...spi.filesystem import register_fs
from .s3 import S3PinotFS


class _GcsClientAdapter:
    """google-cloud-storage Client → the boto3-style surface S3PinotFS uses."""

    def __init__(self, client):
        self.client = client

    def put_object(self, Bucket, Key, Body=b""):
        self.client.bucket(Bucket).blob(Key).upload_from_string(Body)

    def get_object(self, Bucket, Key):
        import io

        data = self.client.bucket(Bucket).blob(Key).download_as_bytes()
        return {"Body": io.BytesIO(data)}

    def head_object(self, Bucket, Key):
        blob = self.client.bucket(Bucket).get_blob(Key)
        if blob is None:
            raise FileNotFoundError(f"gs://{Bucket}/{Key}")
        return {"ContentLength": blob.size}

    def delete_object(self, Bucket, Key):
        self.client.bucket(Bucket).blob(Key).delete()

    def list_objects_v2(self, Bucket, Prefix, ContinuationToken=None):
        blobs = self.client.list_blobs(Bucket, prefix=Prefix,
                                       page_token=ContinuationToken)
        contents = [{"Key": b.name} for b in blobs]
        token = getattr(blobs, "next_page_token", None)
        return {"Contents": contents, "IsTruncated": bool(token),
                "NextContinuationToken": token}

    def copy_object(self, Bucket, Key, CopySource):
        src_bucket = self.client.bucket(CopySource["Bucket"])
        src_blob = src_bucket.blob(CopySource["Key"])
        src_bucket.copy_blob(src_blob, self.client.bucket(Bucket), Key)


def _default_client_factory():
    try:
        from google.cloud import storage  # type: ignore[import-not-found]
    except ImportError as e:
        raise ImportError(
            "scheme 'gs' needs the google-cloud-storage package (or inject "
            "GcsPinotFS.client_factory)") from e
    return _GcsClientAdapter(storage.Client())


class GcsPinotFS(S3PinotFS):
    client_factory: Callable = staticmethod(_default_client_factory)
    schemes: tuple = ("gs", "gcs")


register_fs("gs", GcsPinotFS)
register_fs("gcs", GcsPinotFS)
