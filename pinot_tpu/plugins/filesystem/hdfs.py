"""HDFS PinotFS (reference: pinot-plugins/pinot-file-system/pinot-hdfs/
HadoopPinotFS.java).

Unlike the object stores, HDFS has real directories, so this is a direct
PinotFS implementation over ``pyarrow.fs.HadoopFileSystem`` (optional,
lazily imported; inject ``fs_factory`` to use another client — tests use
pyarrow's LocalFileSystem through the same adapter surface).
"""

from __future__ import annotations

from typing import BinaryIO, Callable
from urllib.parse import urlparse

from ...spi.filesystem import PinotFS, register_fs


def _default_fs_factory():
    try:
        from pyarrow import fs  # type: ignore[import-not-found]
    except ImportError as e:
        raise ImportError(
            "scheme 'hdfs' needs the pyarrow package (or inject "
            "HdfsPinotFS.fs_factory)") from e
    return fs.HadoopFileSystem("default")


def _path(uri: str) -> str:
    p = urlparse(uri)
    return p.path if p.scheme else uri


class HdfsPinotFS(PinotFS):
    fs_factory: Callable = staticmethod(_default_fs_factory)

    def __init__(self, filesystem=None):
        self._fs = filesystem if filesystem is not None else \
            type(self).fs_factory()

    def _info(self, uri: str):
        return self._fs.get_file_info([_path(uri)])[0]

    def mkdir(self, uri: str) -> None:
        self._fs.create_dir(_path(uri), recursive=True)

    def exists(self, uri: str) -> bool:
        from pyarrow import fs

        return self._info(uri).type != fs.FileType.NotFound

    def is_directory(self, uri: str) -> bool:
        from pyarrow import fs

        return self._info(uri).type == fs.FileType.Directory

    def length(self, uri: str) -> int:
        return self._info(uri).size

    def list_files(self, uri: str, recursive: bool = False) -> list[str]:
        from pyarrow import fs

        sel = fs.FileSelector(_path(uri), recursive=recursive)
        return sorted(i.path for i in self._fs.get_file_info(sel))

    def delete(self, uri: str, force: bool = False) -> bool:
        from pyarrow import fs

        info = self._info(uri)
        if info.type == fs.FileType.NotFound:
            return False
        if info.type == fs.FileType.Directory:
            if self.list_files(uri) and not force:
                raise OSError(f"{uri} is a non-empty directory (use force)")
            self._fs.delete_dir(_path(uri))
        else:
            self._fs.delete_file(_path(uri))
        return True

    def copy(self, src: str, dst: str) -> bool:
        if self.is_directory(src):
            self.mkdir(dst)
            for f in self.list_files(src, recursive=True):
                rel = f[len(_path(src)):].lstrip("/")
                self.copy(f, _path(dst).rstrip("/") + "/" + rel)
            return True
        with self._fs.open_input_stream(_path(src)) as r, \
                self._fs.open_output_stream(_path(dst)) as w:
            w.write(r.read())
        return True

    def move(self, src: str, dst: str, overwrite: bool = True) -> bool:
        if not overwrite and self.exists(dst):
            return False
        self._fs.move(_path(src), _path(dst))
        return True

    def open(self, uri: str) -> BinaryIO:
        import io

        with self._fs.open_input_stream(_path(uri)) as r:
            return io.BytesIO(r.read())

    def copy_to_local(self, src_uri: str, local_path: str) -> None:
        with open(local_path, "wb") as f:
            f.write(self.open(src_uri).read())

    def copy_from_local(self, local_path: str, dst_uri: str) -> None:
        with open(local_path, "rb") as f, \
                self._fs.open_output_stream(_path(dst_uri)) as w:
            w.write(f.read())


register_fs("hdfs", HdfsPinotFS)
