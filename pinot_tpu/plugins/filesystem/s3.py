"""S3 PinotFS (reference: pinot-plugins/pinot-file-system/pinot-s3/
S3PinotFS.java).

Deep-store layout semantics match the reference: S3 has no real
directories, so ``mkdir`` writes a zero-byte ``<prefix>/`` marker,
``is_directory`` is "any key under the prefix", and copy/move of a
directory prefix copies every object below it.

boto3 is an OPTIONAL dependency: the default ``client_factory`` imports it
lazily; tests inject a fake with the same client surface
(put_object/get_object/delete_object/list_objects_v2/head_object/
copy_object).
"""

from __future__ import annotations

import io
from typing import BinaryIO, Callable
from urllib.parse import urlparse

from ...spi.filesystem import PinotFS, register_fs


def _default_client_factory():
    try:
        import boto3  # type: ignore[import-not-found]
    except ImportError as e:
        raise ImportError(
            "scheme 's3' needs the boto3 package (or inject "
            "S3PinotFS.client_factory)") from e
    return boto3.client("s3")


class S3PinotFS(PinotFS):
    client_factory: Callable = staticmethod(_default_client_factory)
    schemes: tuple = ("s3",)

    def __init__(self, client=None):
        self._client = client if client is not None else \
            type(self).client_factory()

    def _split(self, uri: str) -> tuple[str, str]:
        p = urlparse(uri)
        if p.scheme not in self.schemes:
            raise ValueError(f"not a {self.schemes[0]} uri: {uri}")
        return p.netloc, p.path.lstrip("/")

    # -- helpers -----------------------------------------------------------
    def _keys_under(self, bucket: str, prefix: str) -> list[str]:
        out: list[str] = []
        token = None
        while True:
            kwargs = {"Bucket": bucket, "Prefix": prefix}
            if token:
                kwargs["ContinuationToken"] = token
            resp = self._client.list_objects_v2(**kwargs)
            out.extend(o["Key"] for o in resp.get("Contents", []))
            if not resp.get("IsTruncated"):
                return out
            token = resp.get("NextContinuationToken")

    def _exists_key(self, bucket: str, key: str) -> bool:
        try:
            self._client.head_object(Bucket=bucket, Key=key)
            return True
        except Exception:
            return False

    # -- PinotFS surface ---------------------------------------------------
    def mkdir(self, uri: str) -> None:
        bucket, key = self._split(uri)
        self._client.put_object(Bucket=bucket,
                                Key=key.rstrip("/") + "/", Body=b"")

    def exists(self, uri: str) -> bool:
        bucket, key = self._split(uri)
        return self._exists_key(bucket, key) or self.is_directory(uri)

    def is_directory(self, uri: str) -> bool:
        bucket, key = self._split(uri)
        prefix = key.rstrip("/") + "/"
        return bool(self._keys_under(bucket, prefix))

    def length(self, uri: str) -> int:
        bucket, key = self._split(uri)
        return self._client.head_object(Bucket=bucket, Key=key)["ContentLength"]

    def list_files(self, uri: str, recursive: bool = False) -> list[str]:
        bucket, key = self._split(uri)
        prefix = key.rstrip("/") + "/" if key else ""
        keys = self._keys_under(bucket, prefix)
        out = set()
        for k in keys:
            rest = k[len(prefix):]
            if not rest:
                continue
            if not recursive and "/" in rest.rstrip("/"):
                rest = rest.split("/", 1)[0] + "/"
            out.add(f"{self.schemes[0]}://{bucket}/{prefix}{rest}")
        return sorted(out)

    def delete(self, uri: str, force: bool = False) -> bool:
        bucket, key = self._split(uri)
        if self._exists_key(bucket, key):
            self._client.delete_object(Bucket=bucket, Key=key)
            return True
        prefix = key.rstrip("/") + "/"
        keys = self._keys_under(bucket, prefix)
        if not keys:
            return False
        if len([k for k in keys if k != prefix]) and not force:
            raise OSError(f"{uri} is a non-empty directory (use force)")
        for k in keys:
            self._client.delete_object(Bucket=bucket, Key=k)
        return True

    def copy(self, src: str, dst: str) -> bool:
        sb, sk = self._split(src)
        db, dk = self._split(dst)
        if self._exists_key(sb, sk):
            self._client.copy_object(Bucket=db, Key=dk,
                                     CopySource={"Bucket": sb, "Key": sk})
            return True
        prefix = sk.rstrip("/") + "/"
        keys = self._keys_under(sb, prefix)
        if not keys:
            return False
        for k in keys:
            self._client.copy_object(
                Bucket=db, Key=dk.rstrip("/") + "/" + k[len(prefix):],
                CopySource={"Bucket": sb, "Key": k})
        return True

    def move(self, src: str, dst: str, overwrite: bool = True) -> bool:
        if not overwrite and self.exists(dst):
            return False
        if not self.copy(src, dst):
            return False
        self.delete(src, force=True)
        return True

    def open(self, uri: str) -> BinaryIO:
        bucket, key = self._split(uri)
        body = self._client.get_object(Bucket=bucket, Key=key)["Body"]
        data = body.read()
        return io.BytesIO(data)

    def copy_to_local(self, src_uri: str, local_path: str) -> None:
        with open(local_path, "wb") as f:
            f.write(self.open(src_uri).read())

    def copy_from_local(self, local_path: str, dst_uri: str) -> None:
        bucket, key = self._split(dst_uri)
        with open(local_path, "rb") as f:
            self._client.put_object(Bucket=bucket, Key=key, Body=f.read())


register_fs("s3", S3PinotFS)
