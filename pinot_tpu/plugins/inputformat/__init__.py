"""Input-format record readers.

Reference analogue: pinot-plugins/pinot-input-format/ — RecordReader SPI
(pinot-spi/.../spi/data/readers/RecordReader.java) with avro, csv, json,
orc, parquet, protobuf, thrift, clp-log impls. Here: csv/json native,
parquet+orc via pyarrow, avro via a self-contained container-file decoder
(plugins/inputformat/avro.py), clp-log via the repo's CLP tokenizer
(plugins/inputformat/clplog.py)."""

from .readers import (
    RecordReader,
    create_record_reader,
    register_record_reader,
)

__all__ = ["RecordReader", "create_record_reader", "register_record_reader"]
