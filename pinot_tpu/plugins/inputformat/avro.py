"""Minimal Avro Object Container File reader (decoder only).

Reference analogue: the pinot-avro input-format plugin, which delegates to
the Apache Avro Java library. That library isn't in this image, so the
container format (header/sync/blocks) and binary encoding (zig-zag varints,
length-prefixed bytes, blocked arrays/maps, union indices) are implemented
here directly from the Avro 1.11 spec. Supports codecs null and deflate and
the full primitive + complex type set needed for ingestion; logical types
surface as their underlying primitive (the schema's data-type transformer
coerces downstream).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, BinaryIO, Iterator

MAGIC = b"Obj\x01"


class AvroError(Exception):
    pass


class _Decoder:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise AvroError("truncated avro data")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_long(self) -> int:
        """Zig-zag varint."""
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_value(self, schema) -> Any:
        if isinstance(schema, list):  # union: index then value
            idx = self.read_long()
            return self.read_value(schema[idx])
        if isinstance(schema, str):
            return self._read_primitive(schema)
        t = schema["type"]
        if t == "record":
            return {f["name"]: self.read_value(f["type"])
                    for f in schema["fields"]}
        if t == "array":
            out = []
            while True:
                n = self.read_long()
                if n == 0:
                    break
                if n < 0:  # block with byte size prefix
                    n = -n
                    self.read_long()
                for _ in range(n):
                    out.append(self.read_value(schema["items"]))
            return out
        if t == "map":
            out = {}
            while True:
                n = self.read_long()
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    self.read_long()
                for _ in range(n):
                    k = self.read_bytes().decode("utf-8")
                    out[k] = self.read_value(schema["values"])
            return out
        if t == "enum":
            return schema["symbols"][self.read_long()]
        if t == "fixed":
            return self.read(schema["size"])
        if t == "bytes":
            return self.read_bytes()
        return self._read_primitive(t)

    def _read_primitive(self, t: str) -> Any:
        if t == "null":
            return None
        if t == "boolean":
            return self.read(1) != b"\x00"
        if t in ("int", "long"):
            return self.read_long()
        if t == "float":
            return struct.unpack("<f", self.read(4))[0]
        if t == "double":
            return struct.unpack("<d", self.read(8))[0]
        if t == "bytes":
            return self.read_bytes()
        if t == "string":
            return self.read_bytes().decode("utf-8")
        raise AvroError(f"unsupported avro type {t!r}")


def read_avro_file(f: BinaryIO) -> Iterator[dict]:
    """Yield records from an Avro Object Container File."""
    header = f.read()
    dec = _Decoder(header)
    if dec.read(4) != MAGIC:
        raise AvroError("not an avro object container file")
    meta: dict[str, bytes] = {}
    while True:
        n = dec.read_long()
        if n == 0:
            break
        if n < 0:
            n = -n
            dec.read_long()
        for _ in range(n):
            k = dec.read_bytes().decode("utf-8")
            meta[k] = dec.read_bytes()
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate"):
        raise AvroError(f"unsupported avro codec {codec!r}")
    sync = dec.read(16)
    while dec.pos < len(dec.buf):
        count = dec.read_long()
        size = dec.read_long()
        block = dec.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        bdec = _Decoder(block)
        for _ in range(count):
            yield bdec.read_value(schema)
        if dec.read(16) != sync:
            raise AvroError("sync marker mismatch")


# -- writer (tests + FakeStream fixtures need round-trips) -------------------


def _zigzag(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _write_value(schema, v, out: bytearray) -> None:
    if isinstance(schema, list):
        for i, branch in enumerate(schema):
            t = branch if isinstance(branch, str) else branch["type"]
            if (v is None) == (t == "null"):
                out.extend(_zigzag(i))
                _write_value(branch, v, out)
                return
        raise AvroError(f"no union branch for {v!r}")
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return
    if t == "boolean":
        out.append(1 if v else 0)
    elif t in ("int", "long"):
        out.extend(_zigzag(int(v)))
    elif t == "float":
        out.extend(struct.pack("<f", float(v)))
    elif t == "double":
        out.extend(struct.pack("<d", float(v)))
    elif t == "string":
        b = str(v).encode("utf-8")
        out.extend(_zigzag(len(b)))
        out.extend(b)
    elif t == "bytes":
        out.extend(_zigzag(len(v)))
        out.extend(v)
    elif t == "record":
        for fld in schema["fields"]:
            _write_value(fld["type"], v.get(fld["name"]), out)
    elif t == "array":
        if v:
            out.extend(_zigzag(len(v)))
            for item in v:
                _write_value(schema["items"], item, out)
        out.extend(_zigzag(0))
    elif t == "map":
        if v:
            out.extend(_zigzag(len(v)))
            for k, item in v.items():
                b = str(k).encode("utf-8")
                out.extend(_zigzag(len(b)))
                out.extend(b)
                _write_value(schema["values"], item, out)
        out.extend(_zigzag(0))
    elif t == "enum":
        out.extend(_zigzag(schema["symbols"].index(v)))
    else:
        raise AvroError(f"unsupported avro type {t!r}")


def write_avro_file(f: BinaryIO, schema: dict, records: list[dict],
                    codec: str = "deflate") -> None:
    f.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8")}
    out = bytearray()
    out.extend(_zigzag(len(meta)))
    for k, v in meta.items():
        kb = k.encode("utf-8")
        out.extend(_zigzag(len(kb)))
        out.extend(kb)
        out.extend(_zigzag(len(v)))
        out.extend(v)
    out.extend(_zigzag(0))
    f.write(bytes(out))
    sync = b"\x00\x01\x02\x03" * 4
    f.write(sync)
    block = bytearray()
    for r in records:
        _write_value(schema, r, block)
    payload = bytes(block)
    if codec == "deflate":
        payload = zlib.compress(payload)[2:-4]  # raw deflate (no zlib wrapper)
    f.write(_zigzag(len(records)))
    f.write(_zigzag(len(payload)))
    f.write(payload)
    f.write(sync)
