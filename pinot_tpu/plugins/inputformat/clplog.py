"""CLP log-message input format.

Reference: pinot-plugins/pinot-input-format/pinot-clp-log —
CLPLogRecordExtractor.java splits every configured message field F of an
ingested log event into three columns

    F_logtype        STRING       the message template
    F_dictionaryVars ARRAY STRING variable tokens with letters
    F_encodedVars    ARRAY LONG   numeric tokens packed into 64-bit words

(other fields pass through untouched), so log tables group/filter on tiny
logtype dictionaries instead of raw messages. The template split reuses this
repo's CLP tokenizer (segment/clp.py); the 64-bit numeric-variable packing
below is our own reversible scheme (sign/digit-count/point-position/digits),
with the same fallback contract as the reference: any token the packing
cannot represent losslessly is demoted to a dictionary variable.

Config keys (camelCase accepted for reference parity):
    fields_for_clp_encoding: list[str] — fields to CLP-encode (default: none,
        every field passes through)
"""

from __future__ import annotations

from typing import Iterator, Optional

from ...segment import clp as _clp
from ...segment.clp import decode_message, encode_message
from .readers import JsonRecordReader, register_record_reader

_TAG_FLOAT = 1
# float word layout: [1 | sign:1 | ndigits:5 | point:5 | digits:51]
_MAX_DIGITS = 15  # 10^15 < 2^51


def encode_var_to_long(kind: str, literal: str) -> Optional[int]:
    """Pack one numeric token into a reversible int64, or None if the token
    cannot round-trip (caller demotes it to a dictionary variable)."""
    if kind == "i":
        try:
            v = int(literal)
        except ValueError:
            return None
        if not -(1 << 62) <= v < (1 << 62) or str(v) != literal:
            return None  # "+3" / "007" would not reconstruct
        return v << 1
    # float literal: sign? digits '.' digits — reconstruct the exact string
    s = literal
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    if "." not in s:
        return None
    point = s.index(".")
    digits = s.replace(".", "")
    if not digits.isdigit() or len(digits) > _MAX_DIGITS:
        return None
    m = int(digits)
    word = (_TAG_FLOAT | (1 << 1 if neg else 0) | (len(digits) << 2)
            | (point << 7) | (m << 12))
    if long_to_encoded_var(word)[1] != literal:
        return None
    return word


def long_to_encoded_var(word: int) -> tuple[str, str]:
    """Inverse of encode_var_to_long → (kind, literal)."""
    if not word & _TAG_FLOAT:
        return "i", str(word >> 1)
    neg = bool(word & 2)
    nd = (word >> 2) & 0x1F
    point = (word >> 7) & 0x1F
    digits = str(word >> 12).rjust(nd, "0")
    lit = digits[:point] + "." + digits[point:]
    return "f", ("-" + lit) if neg else lit


def encode_field(message: str) -> tuple[str, list[str], list[int]]:
    """One message → (logtype, dictionaryVars, encodedVars). Walks the
    template's placeholders in order, packing each numeric slot; a token the
    packing cannot represent losslessly demotes to a dictionary-variable
    slot (the same fallback the reference's extractor applies when CLP
    encoding fails)."""
    logtype, dict_vars, enc_vars = encode_message(message)
    out: list[str] = []
    new_dict: list[str] = []
    words: list[int] = []
    di, ei = iter(dict_vars), iter(enc_vars)
    i, n = 0, len(logtype)
    while i < n:
        ch = logtype[i]
        if ch == _clp.ESC and i + 1 < n:
            out.append(logtype[i:i + 2])
            i += 2
            continue
        if ch == _clp.DICT_VAR:
            out.append(ch)
            new_dict.append(next(di))
        elif ch in (_clp.INT_VAR, _clp.FLOAT_VAR):
            kind, lit = next(ei)
            w = encode_var_to_long(kind, lit)
            if w is None:
                out.append(_clp.DICT_VAR)
                new_dict.append(lit)
            else:
                out.append(ch)
                words.append(w)
        else:
            out.append(ch)
        i += 1
    return "".join(out), new_dict, words


def decode_field(logtype: str, dict_vars: list[str],
                 encoded_vars: list[int]) -> str:
    """Reassemble the original message from the three split columns."""
    return decode_message(
        logtype, list(dict_vars),
        [long_to_encoded_var(int(w)) for w in encoded_vars])


class ClpLogRecordReader(JsonRecordReader):
    """JSON log reader (lines or top-level array, inherited) applying the
    CLP field split per record (reference: CLPLogMessageDecoder delegating
    to CLPLogRecordExtractor)."""

    def _fields(self) -> list[str]:
        cfg = self.config or {}
        return list(cfg.get("fields_for_clp_encoding")
                    or cfg.get("fieldsForClpEncoding") or [])

    def _iter(self) -> Iterator[dict]:
        fields = self._fields()
        for record in super()._iter():
            yield extract_record(record, fields)


def extract_record(record: dict, fields: list[str]) -> dict:
    """Apply the CLP split to one decoded record (the reference extractor's
    per-record contract: selected fields become the three split columns,
    everything else passes through)."""
    out = {}
    for k, v in record.items():
        if k in fields:
            # null messages still emit the split columns (empty template)
            # so every row carries the same schema
            lt, dv, ev = encode_field("" if v is None else str(v))
            out[f"{k}_logtype"] = lt
            out[f"{k}_dictionaryVars"] = dv
            out[f"{k}_encodedVars"] = ev
        else:
            out[k] = v
    return out


register_record_reader("clplog", ClpLogRecordReader)
register_record_reader("clp", ClpLogRecordReader)
