"""RecordReader SPI + csv/json/parquet/orc readers.

Reference analogue: RecordReader (pinot-spi/.../spi/data/readers/
RecordReader.java — init/hasNext/next/rewind/close over GenericRow) and the
per-format plugins under pinot-plugins/pinot-input-format/. Rows surface as
plain dicts (the GenericRow analogue) for the ingestion transform pipeline.
"""

from __future__ import annotations

import csv
import gzip
import io
import json
from pathlib import Path
from typing import Callable, Iterator, Optional


class RecordReader:
    """Iterates a file as row dicts. Subclasses implement _iter()."""

    def __init__(self, path: str, config: Optional[dict] = None):
        self.path = path
        self.config = config or {}

    def __iter__(self) -> Iterator[dict]:
        return self._iter()

    def _iter(self) -> Iterator[dict]:
        raise NotImplementedError

    def _open_text(self):
        if str(self.path).endswith(".gz"):
            return io.TextIOWrapper(gzip.open(self.path, "rb"), encoding="utf-8")
        return open(self.path, "r", encoding="utf-8")

    def _open_binary(self):
        if str(self.path).endswith(".gz"):
            return gzip.open(self.path, "rb")
        return open(self.path, "rb")


class CsvRecordReader(RecordReader):
    """Reference: pinot-csv plugin (CSVRecordReader). config keys:
    delimiter, header (comma-separated names when the file has none),
    multiValueDelimiter (splits a cell into an MV list)."""

    def _iter(self) -> Iterator[dict]:
        delim = self.config.get("delimiter", ",")
        mv_delim = self.config.get("multiValueDelimiter")
        header = self.config.get("header")
        with self._open_text() as f:
            if header:
                names = [h.strip() for h in header.split(",")]
                reader = csv.reader(f, delimiter=delim)
            else:
                dict_reader = csv.DictReader(f, delimiter=delim)
                for row in dict_reader:
                    yield self._convert(row, mv_delim)
                return
            for vals in reader:
                yield self._convert(dict(zip(names, vals)), mv_delim)

    @staticmethod
    def _convert(row: dict, mv_delim) -> dict:
        out = {}
        for k, v in row.items():
            if v == "" or v is None:
                out[k] = None
            elif mv_delim and mv_delim in v:
                out[k] = [_auto(x) for x in v.split(mv_delim)]
            else:
                out[k] = _auto(v)
        return out


def _auto(v: str):
    """CSV cells are untyped; coerce numerics (the schema's data-type
    transformer does the authoritative coercion downstream)."""
    try:
        return int(v)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return v


class JsonRecordReader(RecordReader):
    """JSON-lines or a top-level JSON array (reference: pinot-json plugin)."""

    def _iter(self) -> Iterator[dict]:
        with self._open_text() as f:
            first = f.read(1)
            f.seek(0)
            if first == "[":
                for row in json.load(f):
                    yield row
                return
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)


class ParquetRecordReader(RecordReader):
    """Reference: pinot-parquet plugin; pyarrow supplies the columnar
    decode, rows surface batch-by-batch to bound memory."""

    def _iter(self) -> Iterator[dict]:
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(self.path)
        for batch in pf.iter_batches():
            for row in batch.to_pylist():
                yield row


class OrcRecordReader(RecordReader):
    """Reference: pinot-orc plugin."""

    def _iter(self) -> Iterator[dict]:
        from pyarrow import orc

        table = orc.ORCFile(self.path).read()
        for row in table.to_pylist():
            yield row


class AvroRecordReader(RecordReader):
    """Reference: pinot-avro plugin; decoding in plugins/inputformat/avro.py."""

    def _iter(self) -> Iterator[dict]:
        from .avro import read_avro_file

        with self._open_binary() as f:
            yield from read_avro_file(f)


_READERS: dict[str, Callable[..., RecordReader]] = {
    "csv": CsvRecordReader,
    "json": JsonRecordReader,
    "jsonl": JsonRecordReader,
    "parquet": ParquetRecordReader,
    "orc": OrcRecordReader,
    "avro": AvroRecordReader,
}


def register_record_reader(fmt: str, factory: Callable[..., RecordReader]) -> None:
    _READERS[fmt.lower()] = factory


def create_record_reader(path: str, fmt: Optional[str] = None,
                         config: Optional[dict] = None) -> RecordReader:
    """fmt defaults from the file extension (reference:
    RecordReaderFactory.getRecordReaderByClass / format inference)."""
    if fmt is None:
        name = Path(path).name
        for suffix in (".gz",):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        fmt = Path(name).suffix.lstrip(".").lower()
    fmt = fmt.lower()
    if fmt not in _READERS and fmt in ("proto", "protobuf", "thrift"):
        # registration-on-import, like stream plugins
        from . import protobuf, thrift  # noqa: F401
    if fmt not in _READERS and fmt in ("clplog", "clp"):
        from . import clplog  # noqa: F401
    factory = _READERS.get(fmt)
    if factory is None:
        raise ValueError(f"no record reader for format {fmt!r} "
                         f"(known: {sorted(_READERS)})")
    return factory(path, config)


from ...spi.plugins import register_kind as _register_kind  # noqa: E402

_register_kind("inputformat", lambda fmt: _READERS.get(fmt.lower()))
