"""Thrift record reader: self-contained TBinaryProtocol struct decoder.

Reference analogue: pinot-plugins/pinot-input-format/pinot-thrift
(ThriftRecordReader.java) — reads concatenated TBinaryProtocol-serialized
structs. The reference binds field names through the generated thrift
class's metadata map; no thrift runtime is bundled here, so the reader
config supplies the same mapping explicitly:

    {"fieldIdToName": {"1": "name", "2": "price", ...}}

Unmapped fields keep their numeric id as a string key. Nested structs
decode to dicts (their ids unmapped), lists/sets to lists, maps to dicts.
"""

from __future__ import annotations

import struct
from typing import Iterator

from .readers import RecordReader, register_record_reader

# TBinaryProtocol type ids
_STOP, _BOOL, _BYTE, _DOUBLE, _I16, _I32, _I64 = 0, 2, 3, 4, 6, 8, 10
_STRING, _STRUCT, _MAP, _SET, _LIST = 11, 12, 13, 14, 15


class _Reader:
    def __init__(self, f):
        self.f = f

    def read(self, n: int) -> bytes:
        b = self.f.read(n)
        if len(b) != n:
            raise EOFError("truncated thrift data")
        return b

    def value(self, ttype: int):
        if ttype == _BOOL:
            return self.read(1)[0] != 0
        if ttype == _BYTE:
            return struct.unpack(">b", self.read(1))[0]
        if ttype == _DOUBLE:
            return struct.unpack(">d", self.read(8))[0]
        if ttype == _I16:
            return struct.unpack(">h", self.read(2))[0]
        if ttype == _I32:
            return struct.unpack(">i", self.read(4))[0]
        if ttype == _I64:
            return struct.unpack(">q", self.read(8))[0]
        if ttype == _STRING:
            n = struct.unpack(">i", self.read(4))[0]
            raw = self.read(n)
            try:
                return raw.decode("utf-8")
            except UnicodeDecodeError:
                return raw
        if ttype == _STRUCT:
            return self.struct()
        if ttype in (_LIST, _SET):
            etype = self.read(1)[0]
            n = struct.unpack(">i", self.read(4))[0]
            return [self.value(etype) for _ in range(n)]
        if ttype == _MAP:
            ktype = self.read(1)[0]
            vtype = self.read(1)[0]
            n = struct.unpack(">i", self.read(4))[0]
            return {self.value(ktype): self.value(vtype) for _ in range(n)}
        raise ValueError(f"unknown thrift type {ttype}")

    def struct(self) -> dict:
        out = {}
        while True:
            ttype = self.read(1)[0]
            if ttype == _STOP:
                return out
            (fid,) = struct.unpack(">h", self.read(2))
            out[str(fid)] = self.value(ttype)


class ThriftRecordReader(RecordReader):
    """config: ``fieldIdToName`` mapping top-level field ids to row keys."""

    def _iter(self) -> Iterator[dict]:
        names = {str(k): v for k, v in
                 (self.config.get("fieldIdToName") or {}).items()}
        with self._open_binary() as f:
            r = _Reader(f)
            while True:
                first = f.read(1)
                if not first:
                    return
                if first[0] == _STOP:  # empty struct
                    yield {}
                    continue
                (fid,) = struct.unpack(">h", r.read(2))
                row = {str(fid): r.value(first[0])}
                row.update(r.struct())
                yield {names.get(k, k): v for k, v in row.items()}


def write_struct(out: bytearray, fields: dict) -> None:
    """Test/producer helper: TBinaryProtocol-encode {field_id: value}."""
    for fid, v in fields.items():
        fid = int(fid)
        if isinstance(v, bool):
            out += struct.pack(">bhB", _BOOL, fid, 1 if v else 0)
        elif isinstance(v, int):
            out += struct.pack(">bhq", _I64, fid, v)
        elif isinstance(v, float):
            out += struct.pack(">bhd", _DOUBLE, fid, v)
        elif isinstance(v, str):
            raw = v.encode("utf-8")
            out += struct.pack(">bhi", _STRING, fid, len(raw)) + raw
        elif isinstance(v, list):
            out += struct.pack(">bhbi", _LIST, fid, _I64, len(v))
            for x in v:
                out += struct.pack(">q", int(x))
        elif isinstance(v, dict):
            out += struct.pack(">bh", _STRUCT, fid)
            write_struct(out, v)
        else:
            raise TypeError(f"unsupported test value {type(v)}")
    out.append(_STOP)


register_record_reader("thrift", ThriftRecordReader)
