"""Stream connector plugins (reference: pinot-plugins/pinot-stream-ingestion).

Importing a connector module registers its streamType with the SPI registry
(spi/stream.py); `get_stream_consumer_factory` auto-imports
``pinot_tpu.plugins.stream.<streamType>`` on first use, so a table config
naming ``streamType: kafka`` resolves without explicit imports.
"""
