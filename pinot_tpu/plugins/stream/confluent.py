"""Confluent Schema-Registry Avro stream decoder.

Reference analogue: pinot-plugins/pinot-input-format/pinot-confluent-avro
(KafkaConfluentSchemaRegistryAvroMessageDecoder.java) — Kafka payloads in
the Confluent wire format: magic byte 0x00, 4-byte big-endian schema id,
then the Avro binary record. The schema id resolves against the registry.

Zero-egress redesign: schema resolution is injectable. The stream config
can carry inline schemas (``schema.registry.schemas``: {id: avro schema
json}, or a single ``schema.json`` used for every id), or a registry
client object can be injected via ``register_schema_provider`` (the test /
embedded-cluster seam, where the reference would hit the REST registry).
"""

from __future__ import annotations

import json
import struct
from typing import Callable, Optional

from ...spi.stream import (StreamConfig, StreamDataDecoder, StreamMessage,
                           register_decoder)

_MAGIC = 0

# process-global injectable registry: schema id → avro schema (dict)
_PROVIDERS: dict[str, Callable[[int], dict]] = {}


def register_schema_provider(url: str, provider: Callable[[int], dict]) -> None:
    """Bind a schema-registry URL to a resolver (id → avro schema dict) —
    the injectable client seam (same pattern as the kafka plugin's
    injectable consumer)."""
    _PROVIDERS[url] = provider


class ConfluentAvroDecoder(StreamDataDecoder):
    def __init__(self, config: Optional[StreamConfig] = None):
        props = (config.props if config is not None else {}) or {}
        self._schemas: dict[int, dict] = {}
        inline = props.get("schema.registry.schemas")
        if isinstance(inline, str):
            inline = json.loads(inline)
        if isinstance(inline, dict):
            self._schemas = {int(k): (json.loads(v) if isinstance(v, str) else v)
                             for k, v in inline.items()}
        default = props.get("schema.json")
        self._default = (json.loads(default) if isinstance(default, str)
                         else default)
        self._provider = _PROVIDERS.get(
            props.get("schema.registry.rest.url", ""))

    def _schema(self, schema_id: int) -> Optional[dict]:
        s = self._schemas.get(schema_id)
        if s is None and self._provider is not None:
            s = self._provider(schema_id)
            if s is not None:
                self._schemas[schema_id] = s
        return s if s is not None else self._default

    def decode(self, message: StreamMessage) -> Optional[dict]:
        from ..inputformat.avro import _Decoder

        v = message.value
        if not isinstance(v, (bytes, bytearray)) or len(v) < 5 \
                or v[0] != _MAGIC:
            return None
        (schema_id,) = struct.unpack(">i", bytes(v[1:5]))
        schema = self._schema(schema_id)
        if schema is None:
            return None
        try:
            row = _Decoder(bytes(v[5:])).read_value(schema)
        except Exception:
            return None
        return row if isinstance(row, dict) else None


def encode_confluent(schema_id: int, schema: dict, row: dict) -> bytes:
    """Test/producer helper: Confluent wire-format encoding of one row."""
    from ..inputformat.avro import _write_value

    out = bytearray()
    _write_value(schema, row, out)
    return bytes([_MAGIC]) + struct.pack(">i", schema_id) + bytes(out)


register_decoder("confluentavro", ConfluentAvroDecoder)
register_decoder(
    "org.apache.pinot.plugin.inputformat.avro.confluent."
    "KafkaConfluentSchemaRegistryAvroMessageDecoder", ConfluentAvroDecoder)
