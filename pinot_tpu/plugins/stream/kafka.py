"""Kafka stream connector on the stream SPI.

Reference: KafkaPartitionLevelConsumer / KafkaStreamMetadataProvider
(pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0/src/main/java/org/
apache/pinot/plugin/stream/kafka20/KafkaPartitionLevelConsumer.java:45) —
partition-level pull consumption: assign one (topic, partition), seek to the
requested start offset, poll a batch, report the next offset; metadata
provider exposes partition count and earliest/latest offsets.

The Kafka client library (kafka-python) is an OPTIONAL dependency: the
default ``client_factory`` imports it lazily and raises a clear error when
absent. Tests (and alternative client libraries) inject a different factory
returning any object with the kafka-python consumer surface used here:
``assign/seek/poll/partitions_for_topic/beginning_offsets/end_offsets/
close``.

Config keys (reference-compatible):
    streamType: kafka
    stream.kafka.topic.name
    stream.kafka.broker.list                  (bootstrap servers)
    stream.kafka.consumer.prop.auto.offset.reset    smallest | largest
    stream.kafka.consumer.prop.*              (passed through to the client)
"""

from __future__ import annotations

from collections import namedtuple
from typing import Callable

from ...spi.stream import (
    LongMsgOffset,
    MessageBatch,
    PartitionGroupConsumer,
    StreamConfig,
    StreamConsumerFactory,
    StreamMessage,
    StreamMetadataProvider,
    register_stream_type,
)

# structural TopicPartition for client factories that don't bring their own
# (kafka-python's is also a namedtuple with these fields)
TopicPartition = namedtuple("TopicPartition", ["topic", "partition"])

_CONSUMER_PROP_PREFIX = "stream.kafka.consumer.prop."
# client props handled by the SPI itself, never forwarded
_EXCLUDED_PROPS = {"auto.offset.reset"}


def _default_client_factory(config: StreamConfig):
    """(consumer, topic_partition_ctor) using kafka-python."""
    try:
        import kafka  # type: ignore[import-not-found]
    except ImportError as e:
        raise ImportError(
            "streamType 'kafka' needs the kafka-python package (or inject "
            "KafkaStreamConsumerFactory.client_factory)") from e
    props = {}
    for k, v in config.props.items():
        if k.startswith(_CONSUMER_PROP_PREFIX):
            prop = k[len(_CONSUMER_PROP_PREFIX):]
            if prop not in _EXCLUDED_PROPS:
                props[prop.replace(".", "_")] = v
    consumer = kafka.KafkaConsumer(
        bootstrap_servers=config.props.get("stream.kafka.broker.list",
                                           "localhost:9092"),
        enable_auto_commit=False,  # offsets are Pinot's segment checkpoints
        **props)
    return consumer, kafka.TopicPartition


class KafkaPartitionConsumer(PartitionGroupConsumer):
    """Partition-level consumer: seek to the requested offset, poll once.

    Stateless between fetches from the caller's viewpoint — the engine
    passes the start offset on every call (its checkpoint), so a crash or
    catch-up replays exactly from the committed offset; ``seek`` is skipped
    when the consumer is already positioned there."""

    def __init__(self, consumer, tp):
        self._consumer = consumer
        self._tp = tp
        self._position: int | None = None
        self._consumer.assign([tp])

    def fetch_messages(self, start_offset: LongMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        start = start_offset.offset
        if self._position != start:
            self._consumer.seek(self._tp, start)
        polled = self._consumer.poll(timeout_ms=timeout_ms)
        records = polled.get(self._tp, []) if polled else []
        messages = []
        next_offset = start
        for rec in records:
            messages.append(StreamMessage(
                value=rec.value, key=rec.key,
                offset=LongMsgOffset(rec.offset),
                timestamp_ms=getattr(rec, "timestamp", None)))
            next_offset = rec.offset + 1
        self._position = next_offset
        return MessageBatch(messages, LongMsgOffset(next_offset))

    def close(self) -> None:
        self._consumer.close()


class KafkaMetadataProvider(StreamMetadataProvider):
    def __init__(self, consumer, tp_ctor, topic: str):
        self._consumer = consumer
        self._tp_ctor = tp_ctor
        self._topic = topic

    def partition_count(self) -> int:
        parts = self._consumer.partitions_for_topic(self._topic)
        if not parts:
            raise ValueError(f"kafka topic {self._topic!r} has no partitions")
        return len(parts)

    def fetch_earliest_offset(self, partition: int) -> LongMsgOffset:
        tp = self._tp_ctor(self._topic, partition)
        return LongMsgOffset(self._consumer.beginning_offsets([tp])[tp])

    def fetch_latest_offset(self, partition: int) -> LongMsgOffset:
        tp = self._tp_ctor(self._topic, partition)
        return LongMsgOffset(self._consumer.end_offsets([tp])[tp])

    def close(self) -> None:
        self._consumer.close()


class KafkaStreamConsumerFactory(StreamConsumerFactory):
    """``client_factory`` is the injection point: config → (consumer,
    topic_partition_ctor). Swap it for a fake in tests or for an alternate
    client library (confluent-kafka adapter, etc.)."""

    client_factory: Callable = staticmethod(_default_client_factory)

    def create_partition_consumer(self, partition: int) -> KafkaPartitionConsumer:
        consumer, tp_ctor = type(self).client_factory(self.config)
        return KafkaPartitionConsumer(
            consumer, tp_ctor(self.config.topic_name, partition))

    def create_metadata_provider(self) -> KafkaMetadataProvider:
        consumer, tp_ctor = type(self).client_factory(self.config)
        return KafkaMetadataProvider(consumer, tp_ctor, self.config.topic_name)


register_stream_type("kafka", KafkaStreamConsumerFactory)
