"""Kinesis stream connector on the stream SPI.

Reference: KinesisConsumer / KinesisStreamMetadataProvider
(pinot-plugins/pinot-stream-ingestion/pinot-kinesis/src/main/java/org/
apache/pinot/plugin/stream/kinesis/KinesisConsumer.java) — shard-level
consumption via shard iterators, checkpointed on sequence numbers.

Offset model (rides the SPI's ``LongMsgOffset``; Kinesis sequence numbers
are decimal integer strings, unbounded Python ints hold them):

    0      TRIM_HORIZON  — earliest retained record
    1      LATEST        — only records arriving after the probe
    c >= 2 AFTER_SEQUENCE_NUMBER(c - 1) — and c-1 is always the sequence
           number of a record this consumer actually returned (checkpoints
           are only ever minted as ``last_seq + 1``), so the iterator
           request is valid against the real API.

The boto3 client is an OPTIONAL dependency behind ``client_factory``;
tests inject a fake exposing the adapter surface:

    list_shards(stream) -> [shard_id, ...]                    (sorted)
    get_records(stream, shard_id, checkpoint:int, limit)
        -> [(seq:int, key:bytes|None, value:bytes, ts_ms:int|None), ...]
           (checkpoint follows the sentinel model above)
    latest_checkpoint(stream, shard_id) -> int   (1 when idle)
    close()

Config keys (reference-compatible):
    streamType: kinesis
    stream.kinesis.topic.name                 (stream name)
    stream.kinesis.consumer.prop.region       (AWS region)
    stream.kinesis.consumer.prop.maxRecordsToFetch
"""

from __future__ import annotations

from typing import Callable

from ...spi.stream import (
    LongMsgOffset,
    MessageBatch,
    PartitionGroupConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamMetadataProvider,
    register_stream_type,
)

_PROP = "stream.kinesis.consumer.prop."
TRIM_HORIZON = 0
LATEST = 1


class _Boto3Adapter:
    """Adapts a boto3 kinesis client to the shard-level surface above.
    Caches each shard's NextShardIterator keyed by the checkpoint it will
    resume from, so steady-state polling costs one API call (the reference
    consumer likewise holds its iterator between polls)."""

    def __init__(self, client, max_records: int):
        self._c = client
        self._max = max_records
        self._iters: dict[tuple, tuple] = {}  # (stream, shard) → (ckpt, iter)

    def list_shards(self, stream):
        shards = []
        kwargs = {"StreamName": stream}
        while True:
            resp = self._c.list_shards(**kwargs)
            shards.extend(s["ShardId"] for s in resp.get("Shards", []))
            token = resp.get("NextToken")
            if not token:
                return sorted(shards)
            kwargs = {"NextToken": token}

    def _iterator(self, stream, shard_id, checkpoint):
        cached = self._iters.get((stream, shard_id))
        if cached and cached[0] == checkpoint and cached[1]:
            return cached[1]
        kwargs = {"StreamName": stream, "ShardId": shard_id}
        if checkpoint <= TRIM_HORIZON:
            kwargs["ShardIteratorType"] = "TRIM_HORIZON"
        elif checkpoint == LATEST:
            kwargs["ShardIteratorType"] = "LATEST"
        else:
            kwargs["ShardIteratorType"] = "AFTER_SEQUENCE_NUMBER"
            kwargs["StartingSequenceNumber"] = str(checkpoint - 1)
        return self._c.get_shard_iterator(**kwargs)["ShardIterator"]

    def get_records(self, stream, shard_id, checkpoint, limit):
        it = self._iterator(stream, shard_id, checkpoint)
        try:
            resp = self._c.get_records(ShardIterator=it,
                                       Limit=min(limit, self._max))
        except Exception:
            # shard iterators expire after ~5 minutes: a consumer idle (or
            # slow) between polls must re-mint from its checkpoint, not
            # kill the partition. One retry with a fresh iterator; a
            # second failure is a real error.
            self._iters.pop((stream, shard_id), None)
            it = self._iterator(stream, shard_id, checkpoint)
            resp = self._c.get_records(ShardIterator=it,
                                       Limit=min(limit, self._max))
        out = []
        for r in resp.get("Records", []):
            ts = r.get("ApproximateArrivalTimestamp")
            out.append((int(r["SequenceNumber"]),
                        (r.get("PartitionKey") or "").encode() or None,
                        r["Data"],
                        int(ts.timestamp() * 1000) if ts else None))
        next_ckpt = out[-1][0] + 1 if out else checkpoint
        self._iters[(stream, shard_id)] = (next_ckpt,
                                           resp.get("NextShardIterator"))
        return out

    def latest_checkpoint(self, stream, shard_id):
        it = self._iterator(stream, shard_id, LATEST)
        resp = self._c.get_records(ShardIterator=it, Limit=1)
        recs = resp.get("Records", [])
        return int(recs[0]["SequenceNumber"]) + 1 if recs else LATEST

    def close(self):
        pass


def _default_client_factory(config):
    try:
        import boto3  # type: ignore[import-not-found]
    except ImportError as e:
        raise ImportError(
            "streamType 'kinesis' needs the boto3 package (or inject "
            "KinesisStreamConsumerFactory.client_factory)") from e
    region = config.props.get(_PROP + "region")
    max_records = int(config.props.get(_PROP + "maxRecordsToFetch", 1000))
    client = boto3.client("kinesis", region_name=region)
    return _Boto3Adapter(client, max_records)


class KinesisShardConsumer(PartitionGroupConsumer):
    def __init__(self, client, stream: str, shard_id: str):
        self._client = client
        self._stream = stream
        self._shard = shard_id

    def fetch_messages(self, start_offset: LongMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        recs = self._client.get_records(self._stream, self._shard,
                                        start_offset.offset, 1000)
        messages = [
            StreamMessage(value=value, key=key,
                          offset=LongMsgOffset(seq), timestamp_ms=ts)
            for seq, key, value, ts in recs]
        next_off = recs[-1][0] + 1 if recs else start_offset.offset
        return MessageBatch(messages, LongMsgOffset(next_off))

    def close(self) -> None:
        self._client.close()


class KinesisMetadataProvider(StreamMetadataProvider):
    def __init__(self, client, stream: str):
        self._client = client
        self._stream = stream

    def partition_count(self) -> int:
        return len(self._client.list_shards(self._stream))

    def fetch_earliest_offset(self, partition: int) -> LongMsgOffset:
        # the TRIM_HORIZON sentinel: "everything retained", no record reads
        return LongMsgOffset(TRIM_HORIZON)

    def fetch_latest_offset(self, partition: int) -> LongMsgOffset:
        shard = self._client.list_shards(self._stream)[partition]
        return LongMsgOffset(self._client.latest_checkpoint(
            self._stream, shard))

    def close(self) -> None:
        self._client.close()


class KinesisStreamConsumerFactory(StreamConsumerFactory):
    client_factory: Callable = staticmethod(_default_client_factory)

    def create_partition_consumer(self, partition: int) -> KinesisShardConsumer:
        client = type(self).client_factory(self.config)
        shard = client.list_shards(self.config.topic_name)[partition]
        return KinesisShardConsumer(client, self.config.topic_name, shard)

    def create_metadata_provider(self) -> KinesisMetadataProvider:
        return KinesisMetadataProvider(
            type(self).client_factory(self.config), self.config.topic_name)


register_stream_type("kinesis", KinesisStreamConsumerFactory)
