"""Pulsar stream connector on the stream SPI.

Reference: PulsarPartitionLevelConsumer / PulsarStreamMetadataProvider
(pinot-plugins/pinot-stream-ingestion/pinot-pulsar/src/main/java/org/
apache/pinot/plugin/stream/pulsar/PulsarPartitionLevelConsumer.java) —
reader-based (not subscription) partition consumption seeded at a
MessageId, checkpointed per segment.

Offset model (rides the SPI's ``LongMsgOffset``): Pulsar MessageIds are
(ledgerId, entryId, batchIndex) triples packed as

    ((ledgerId + 1) << 36) | (entryId << 8) | batchIndex

monotone within a partition because ledger and entry ids are assigned in
order. The +1 ledger bias keeps every real packed id above the sentinels:

    0  EARLIEST  (MessageId.earliest)
    1  LATEST    (MessageId.latest — only new messages)

entryId is bounded to 28 bits and batchIndex to 8; overflow raises rather
than silently wrapping the checkpoint stream backwards. The pulsar-client
library is OPTIONAL behind ``client_factory``; tests inject a fake with
the adapter surface:

    partition_count(topic) -> int      (0 → non-partitioned, treated as 1)
    read(topic, partition, from_packed:int, timeout_ms)
        -> [(packed:int, key:bytes|None, value:bytes, ts_ms:int|None), ...]
           (from_packed follows the sentinel model above; inclusive start)
    latest(topic, partition) -> int    (1 when idle)
    close()

Config keys (reference-compatible):
    streamType: pulsar
    stream.pulsar.topic.name
    stream.pulsar.consumer.prop.serviceUrl    (pulsar://host:6650)
"""

from __future__ import annotations

from typing import Callable

from ...spi.stream import (
    LongMsgOffset,
    MessageBatch,
    PartitionGroupConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamMetadataProvider,
    register_stream_type,
)

_PROP = "stream.pulsar.consumer.prop."
EARLIEST = 0
LATEST = 1
_ENTRY_BITS = 28
_BATCH_BITS = 8


def pack_message_id(ledger: int, entry: int, batch: int = 0) -> int:
    """(ledger, entry, batch) → flat monotone int offset (> all sentinels)."""
    if not (0 <= entry < (1 << _ENTRY_BITS)):
        raise ValueError(f"entryId {entry} out of the {_ENTRY_BITS}-bit "
                         "packable range — checkpoint would wrap")
    if not (0 <= batch < (1 << _BATCH_BITS)):
        raise ValueError(f"batchIndex {batch} out of the {_BATCH_BITS}-bit "
                         "packable range — checkpoint would wrap")
    return ((ledger + 1) << (_ENTRY_BITS + _BATCH_BITS)) \
        | (entry << _BATCH_BITS) | batch


def unpack_message_id(packed: int) -> tuple[int, int, int]:
    return ((packed >> (_ENTRY_BITS + _BATCH_BITS)) - 1,
            (packed >> _BATCH_BITS) & ((1 << _ENTRY_BITS) - 1),
            packed & ((1 << _BATCH_BITS) - 1))


class _PulsarClientAdapter:
    """Adapts the pulsar-client library to the adapter surface above."""

    def __init__(self, service_url: str):
        import pulsar  # type: ignore[import-not-found]

        self._pulsar = pulsar
        self._client = pulsar.Client(service_url)

    def partition_count(self, topic) -> int:
        parts = self._client.get_topic_partitions(topic)
        # a non-partitioned topic reports itself as its only "partition"
        return len(parts) if len(parts) > 1 or (
            parts and parts[0] != topic) else 0

    def _reader_topic(self, topic, partition):
        # partition -1 = non-partitioned: read the topic itself
        return topic if partition < 0 else f"{topic}-partition-{partition}"

    def _start_id(self, partition, from_packed):
        if from_packed <= EARLIEST:
            return self._pulsar.MessageId.earliest, True
        if from_packed == LATEST:
            return self._pulsar.MessageId.latest, False
        ledger, entry, batch = unpack_message_id(from_packed)
        return self._pulsar.MessageId(max(partition, -1), ledger, entry,
                                      batch), True

    def read(self, topic, partition, from_packed, timeout_ms):
        start, inclusive = self._start_id(partition, from_packed)
        reader = self._client.create_reader(
            self._reader_topic(topic, partition), start_message_id=start,
            start_message_id_inclusive=inclusive)
        out = []
        try:
            while reader.has_message_available():
                msg = reader.read_next(timeout_millis=timeout_ms)
                mid = msg.message_id()
                packed = pack_message_id(mid.ledger_id(), mid.entry_id(),
                                         max(0, mid.batch_index()))
                if inclusive and packed < from_packed:
                    continue  # replayed prefix of a batch
                out.append((packed,
                            (msg.partition_key() or "").encode() or None,
                            msg.data(), msg.publish_timestamp()))
        finally:
            reader.close()
        return out

    def latest(self, topic, partition) -> int:
        # a reader seeded at MessageId.latest sees only the tail; an idle
        # partition therefore reports the LATEST sentinel — never a replay
        # of retained history
        recs = self.read(topic, partition, LATEST, 100)
        return recs[-1][0] + 1 if recs else LATEST

    def close(self):
        self._client.close()


def _default_client_factory(config):
    try:
        import pulsar  # noqa: F401  type: ignore[import-not-found]
    except ImportError as e:
        raise ImportError(
            "streamType 'pulsar' needs the pulsar-client package (or inject "
            "PulsarStreamConsumerFactory.client_factory)") from e
    url = config.props.get(_PROP + "serviceUrl", "pulsar://localhost:6650")
    return _PulsarClientAdapter(url)


class PulsarPartitionConsumer(PartitionGroupConsumer):
    def __init__(self, client, topic: str, partition: int):
        self._client = client
        self._topic = topic
        self._partition = partition

    def fetch_messages(self, start_offset: LongMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        recs = self._client.read(self._topic, self._partition,
                                 start_offset.offset, timeout_ms)
        messages = [
            StreamMessage(value=value, key=key,
                          offset=LongMsgOffset(packed), timestamp_ms=ts)
            for packed, key, value, ts in recs]
        next_off = recs[-1][0] + 1 if recs else start_offset.offset
        return MessageBatch(messages, LongMsgOffset(next_off))

    def close(self) -> None:
        self._client.close()


class PulsarMetadataProvider(StreamMetadataProvider):
    def __init__(self, client, topic: str):
        self._client = client
        self._topic = topic

    def partition_count(self) -> int:
        return max(1, self._client.partition_count(self._topic))

    def fetch_earliest_offset(self, partition: int) -> LongMsgOffset:
        return LongMsgOffset(EARLIEST)

    def fetch_latest_offset(self, partition: int) -> LongMsgOffset:
        return LongMsgOffset(self._client.latest(
            self._topic, self._effective_partition(partition)))

    def _effective_partition(self, partition: int) -> int:
        return -1 if self._client.partition_count(self._topic) == 0 \
            else partition

    def close(self) -> None:
        self._client.close()


class PulsarStreamConsumerFactory(StreamConsumerFactory):
    client_factory: Callable = staticmethod(_default_client_factory)

    def create_partition_consumer(self, partition: int) -> PulsarPartitionConsumer:
        client = type(self).client_factory(self.config)
        if client.partition_count(self.config.topic_name) == 0:
            partition = -1  # non-partitioned: read the topic itself
        return PulsarPartitionConsumer(client, self.config.topic_name,
                                       partition)

    def create_metadata_provider(self) -> PulsarMetadataProvider:
        return PulsarMetadataProvider(
            type(self).client_factory(self.config), self.config.topic_name)


register_stream_type("pulsar", PulsarStreamConsumerFactory)
