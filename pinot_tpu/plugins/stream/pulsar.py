"""Pulsar stream connector on the stream SPI.

Reference: PulsarPartitionLevelConsumer / PulsarStreamMetadataProvider
(pinot-plugins/pinot-stream-ingestion/pinot-pulsar/src/main/java/org/
apache/pinot/plugin/stream/pulsar/PulsarPartitionLevelConsumer.java) —
reader-based (not subscription) partition consumption seeded at a
MessageId, checkpointed per segment.

Offset model (rides the SPI's ``LongMsgOffset``): Pulsar MessageIds are
(ledgerId, entryId, batchIndex) triples packed as

    ((ledgerId + 1) << 36) | (entryId << 8) | batchIndex

monotone within a partition because ledger and entry ids are assigned in
order. The +1 ledger bias keeps every real packed id above the sentinels:

    0  EARLIEST  (MessageId.earliest)
    1  LATEST    (MessageId.latest — only new messages)

entryId is bounded to 28 bits and batchIndex to 8; overflow raises rather
than silently wrapping the checkpoint stream backwards. The pulsar-client
library is OPTIONAL behind ``client_factory``; tests inject a fake with
the adapter surface:

    partition_count(topic) -> int      (0 → non-partitioned, treated as 1)
    open_reader(topic, partition, from_packed:int) -> handle
        (from_packed follows the sentinel model above; inclusive start.
         The handle PERSISTS across polls — a reader opened at LATEST must
         see messages published between polls, which a fresh per-poll
         reader at MessageId.latest would silently skip forever)
    read_batch(handle, max_records, timeout_ms)
        -> [(packed:int, key:bytes|None, value:bytes, ts_ms:int|None), ...]
    close_reader(handle)
    latest(topic, partition) -> int    (1 when idle)
    close()

Config keys (reference-compatible):
    streamType: pulsar
    stream.pulsar.topic.name
    stream.pulsar.consumer.prop.serviceUrl    (pulsar://host:6650)
"""

from __future__ import annotations

from typing import Callable

from ...spi.stream import (
    LongMsgOffset,
    MessageBatch,
    PartitionGroupConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamMetadataProvider,
    register_stream_type,
)

_PROP = "stream.pulsar.consumer.prop."
EARLIEST = 0
LATEST = 1
_ENTRY_BITS = 28
_BATCH_BITS = 8


def pack_message_id(ledger: int, entry: int, batch: int = 0) -> int:
    """(ledger, entry, batch) → flat monotone int offset (> all sentinels)."""
    if not (0 <= entry < (1 << _ENTRY_BITS)):
        raise ValueError(f"entryId {entry} out of the {_ENTRY_BITS}-bit "
                         "packable range — checkpoint would wrap")
    if not (0 <= batch < (1 << _BATCH_BITS)):
        raise ValueError(f"batchIndex {batch} out of the {_BATCH_BITS}-bit "
                         "packable range — checkpoint would wrap")
    return ((ledger + 1) << (_ENTRY_BITS + _BATCH_BITS)) \
        | (entry << _BATCH_BITS) | batch


def unpack_message_id(packed: int) -> tuple[int, int, int]:
    return ((packed >> (_ENTRY_BITS + _BATCH_BITS)) - 1,
            (packed >> _BATCH_BITS) & ((1 << _ENTRY_BITS) - 1),
            packed & ((1 << _BATCH_BITS) - 1))


class _PulsarClientAdapter:
    """Adapts the pulsar-client library to the adapter surface above."""

    def __init__(self, service_url: str, max_records: int = 1000):
        import pulsar  # type: ignore[import-not-found]

        self._pulsar = pulsar
        self._client = pulsar.Client(service_url)
        self.max_records = max_records

    def partition_count(self, topic) -> int:
        parts = self._client.get_topic_partitions(topic)
        # a non-partitioned topic reports itself as its only "partition"
        return len(parts) if len(parts) > 1 or (
            parts and parts[0] != topic) else 0

    def _reader_topic(self, topic, partition):
        # partition -1 = non-partitioned: read the topic itself
        return topic if partition < 0 else f"{topic}-partition-{partition}"

    def _start_id(self, partition, from_packed):
        if from_packed <= EARLIEST:
            return self._pulsar.MessageId.earliest, True
        if from_packed == LATEST:
            return self._pulsar.MessageId.latest, False
        ledger, entry, batch = unpack_message_id(from_packed)
        return self._pulsar.MessageId(max(partition, -1), ledger, entry,
                                      batch), True

    def open_reader(self, topic, partition, from_packed):
        start, inclusive = self._start_id(partition, from_packed)
        reader = self._client.create_reader(
            self._reader_topic(topic, partition), start_message_id=start,
            start_message_id_inclusive=inclusive)
        return {"reader": reader, "skip_below": from_packed if inclusive
                else None}

    def read_batch(self, handle, max_records, timeout_ms):
        reader = handle["reader"]
        out = []
        while len(out) < min(max_records, self.max_records) \
                and reader.has_message_available():
            msg = reader.read_next(timeout_millis=timeout_ms)
            mid = msg.message_id()
            packed = pack_message_id(mid.ledger_id(), mid.entry_id(),
                                     max(0, mid.batch_index()))
            skip = handle["skip_below"]
            if skip is not None and packed < skip:
                continue  # replayed prefix of a batch
            out.append((packed,
                        (msg.partition_key() or "").encode() or None,
                        msg.data(), msg.publish_timestamp()))
        return out

    def close_reader(self, handle):
        handle["reader"].close()

    def latest(self, topic, partition) -> int:
        # a reader seeded at MessageId.latest sees only the tail; an idle
        # partition therefore reports the LATEST sentinel — never a replay
        # of retained history
        handle = self.open_reader(topic, partition, LATEST)
        try:
            recs = self.read_batch(handle, 100, 1000)
        finally:
            self.close_reader(handle)
        return recs[-1][0] + 1 if recs else LATEST

    def close(self):
        self._client.close()


def _default_client_factory(config):
    try:
        import pulsar  # noqa: F401  type: ignore[import-not-found]
    except ImportError as e:
        raise ImportError(
            "streamType 'pulsar' needs the pulsar-client package (or inject "
            "PulsarStreamConsumerFactory.client_factory)") from e
    url = config.props.get(_PROP + "serviceUrl", "pulsar://localhost:6650")
    max_records = int(config.props.get(_PROP + "maxRecordsToFetch", 1000))
    return _PulsarClientAdapter(url, max_records)


class PulsarPartitionConsumer(PartitionGroupConsumer):
    """Holds ONE persistent reader across polls: required for LATEST
    starts (a fresh per-poll reader at MessageId.latest would lose every
    message published between polls) and avoids a create-reader broker
    round trip per poll. Reopens only when the engine rewinds/seeks."""

    def __init__(self, client, topic: str, partition: int,
                 max_records: int = 1000):
        self._client = client
        self._topic = topic
        self._partition = partition
        self._max_records = max_records
        self._handle = None
        self._position: int | None = None  # checkpoint the reader sits at

    def fetch_messages(self, start_offset: LongMsgOffset,
                       timeout_ms: int) -> MessageBatch:
        start = start_offset.offset
        if self._handle is None or self._position != start:
            if self._handle is not None:
                self._client.close_reader(self._handle)
            self._handle = self._client.open_reader(
                self._topic, self._partition, start)
        recs = self._client.read_batch(self._handle, self._max_records,
                                       timeout_ms)
        messages = [
            StreamMessage(value=value, key=key,
                          offset=LongMsgOffset(packed), timestamp_ms=ts)
            for packed, key, value, ts in recs]
        next_off = recs[-1][0] + 1 if recs else start
        self._position = next_off
        return MessageBatch(messages, LongMsgOffset(next_off))

    def close(self) -> None:
        if self._handle is not None:
            self._client.close_reader(self._handle)
            self._handle = None
        self._client.close()


class PulsarMetadataProvider(StreamMetadataProvider):
    def __init__(self, client, topic: str):
        self._client = client
        self._topic = topic
        # partitioned-ness is immutable: resolve once, not per probe
        self._raw_count = client.partition_count(topic)

    def partition_count(self) -> int:
        return max(1, self._raw_count)

    def fetch_earliest_offset(self, partition: int) -> LongMsgOffset:
        return LongMsgOffset(EARLIEST)

    def fetch_latest_offset(self, partition: int) -> LongMsgOffset:
        eff = -1 if self._raw_count == 0 else partition
        return LongMsgOffset(self._client.latest(self._topic, eff))

    def close(self) -> None:
        self._client.close()


class PulsarStreamConsumerFactory(StreamConsumerFactory):
    client_factory: Callable = staticmethod(_default_client_factory)

    def create_partition_consumer(self, partition: int) -> PulsarPartitionConsumer:
        client = type(self).client_factory(self.config)
        if client.partition_count(self.config.topic_name) == 0:
            partition = -1  # non-partitioned: read the topic itself
        return PulsarPartitionConsumer(client, self.config.topic_name,
                                       partition)

    def create_metadata_provider(self) -> PulsarMetadataProvider:
        return PulsarMetadataProvider(
            type(self).client_factory(self.config), self.config.topic_name)


register_stream_type("pulsar", PulsarStreamConsumerFactory)
