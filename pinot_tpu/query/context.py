"""Compiled query representation.

Reference: pinot-core/.../query/request/context/QueryContext.java — the single
compiled form the whole V1 engine consumes: select expressions, filter tree,
aggregations, group-by expressions, HAVING, ORDER BY, limit/offset, options.
The TPU engine additionally derives a *kernel signature* from it (see
engine/plan.py) so structurally identical queries share one compiled XLA
program regardless of literal values.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .expressions import ExpressionContext, extract_aggregations
from .filter import FilterContext


@dataclass
class OrderByExpressionContext:
    expression: ExpressionContext
    ascending: bool = True
    nulls_last: Optional[bool] = None  # None = default per direction

    def __str__(self) -> str:
        return f"{self.expression} {'ASC' if self.ascending else 'DESC'}"


@dataclass
class QueryContext:
    table_name: str
    select_expressions: list[ExpressionContext] = field(default_factory=list)
    aliases: list[Optional[str]] = field(default_factory=list)  # parallel to select
    distinct: bool = False
    filter: Optional[FilterContext] = None
    group_by_expressions: list[ExpressionContext] = field(default_factory=list)
    having_filter: Optional[FilterContext] = None
    order_by_expressions: list[OrderByExpressionContext] = field(default_factory=list)
    limit: int = 10  # reference default LIMIT 10 (CalciteSqlParser DEFAULT_LIMIT)
    offset: int = 0
    query_options: dict[str, Any] = field(default_factory=dict)
    explain: bool = False

    # Derived (filled by finish()):
    aggregations: list[ExpressionContext] = field(default_factory=list)

    def finish(self) -> "QueryContext":
        """Derive aggregation list from select/having/order-by expressions
        (reference QueryContext.Builder.build → generateAggregationFunctions).
        GROUP BY identifiers naming a SELECT alias resolve to the aliased
        expression first (reference: Calcite's groupByAliasEnabled
        behavior — GROUP BY dateTrunc('DAY', ts) AS d ... GROUP BY d)."""
        alias_map = {a: e for e, a in zip(self.select_expressions,
                                          self.aliases) if a}
        if alias_map and self.group_by_expressions:
            self.group_by_expressions = [
                alias_map.get(g.identifier, g) if g.is_identifier else g
                for g in self.group_by_expressions]
        aggs: list[ExpressionContext] = []
        for e in self.select_expressions:
            extract_aggregations(e, aggs)
        if self.having_filter is not None:
            _extract_from_filter(self.having_filter, aggs)
        for o in self.order_by_expressions:
            extract_aggregations(o.expression, aggs)
        self.aggregations = aggs
        return self

    @property
    def is_aggregation_query(self) -> bool:
        return bool(self.aggregations)

    @property
    def is_group_by(self) -> bool:
        return bool(self.group_by_expressions)

    @property
    def is_selection(self) -> bool:
        return not self.aggregations and not self.distinct

    @property
    def null_handling(self) -> bool:
        """Advanced null handling (reference
        QueryContext.isNullHandlingEnabled; SET enableNullHandling = true):
        predicates over null inputs are false (3-valued logic) and
        aggregations skip null operand values. Basic mode (default)
        treats stored default values as values. Group-by KEYS stay in
        basic mode either way (null keys group under the default value),
        and SUM/MIN/MAX over a group whose operand is entirely null
        return the op identity rather than SQL NULL (AVG returns NULL)."""
        opt = self.query_options.get("enableNullHandling")
        return opt is True or str(opt).lower() == "true"

    def referenced_columns(self) -> set[str]:
        cols: set[str] = set()
        for e in self.select_expressions:
            cols |= e.columns()
        if self.filter is not None:
            cols |= self.filter.columns()
        for e in self.group_by_expressions:
            cols |= e.columns()
        if self.having_filter is not None:
            cols |= self.having_filter.columns()
        for o in self.order_by_expressions:
            cols |= o.expression.columns()
        return cols

    def __str__(self) -> str:
        parts = [f"SELECT {', '.join(map(str, self.select_expressions))}", f"FROM {self.table_name}"]
        if self.filter:
            parts.append(f"WHERE {self.filter}")
        if self.group_by_expressions:
            parts.append(f"GROUP BY {', '.join(map(str, self.group_by_expressions))}")
        if self.having_filter:
            parts.append(f"HAVING {self.having_filter}")
        if self.order_by_expressions:
            parts.append(f"ORDER BY {', '.join(map(str, self.order_by_expressions))}")
        parts.append(f"LIMIT {self.limit}")
        return " ".join(parts)


def _extract_from_filter(f: FilterContext, out: list) -> None:
    if f.predicate is not None:
        extract_aggregations(f.predicate.lhs, out)
    for c in f.children:
        _extract_from_filter(c, out)
