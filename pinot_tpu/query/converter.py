"""Boolean expression tree → FilterContext conversion.

Reference: pinot-common/.../request/context/RequestContextUtils.getFilter —
the parser produces pure expression trees (and/or/not/equals/... as
functions); WHERE and HAVING convert those into the typed predicate tree the
filter planner consumes. Comparisons with the literal on the left are
flipped; non-predicate boolean expressions become `expr = true`.
"""

from __future__ import annotations

from .expressions import ExpressionContext
from .filter import FilterContext, Predicate, PredicateType


class FilterConversionError(Exception):
    pass


def filter_from_expression(expr: ExpressionContext) -> FilterContext:
    if expr.is_literal:
        if isinstance(expr.literal, bool):
            return FilterContext.constant(expr.literal)
        raise FilterConversionError(f"non-boolean literal in filter: {expr.literal!r}")
    if expr.is_identifier:
        # bare boolean column: `WHERE flag`
        return FilterContext.pred(
            Predicate(PredicateType.EQ, expr, values=(True,))
        )
    fn = expr.function
    name = fn.name
    args = fn.arguments
    if name == "and":
        return FilterContext.and_(*[filter_from_expression(a) for a in args])
    if name == "or":
        return FilterContext.or_(*[filter_from_expression(a) for a in args])
    if name == "not":
        return FilterContext.not_(filter_from_expression(args[0]))

    if name in ("equals", "notequals"):
        lhs, value = _split_comparison(args[0], args[1])
        ptype = PredicateType.EQ if name == "equals" else PredicateType.NOT_EQ
        return FilterContext.pred(Predicate(ptype, lhs, values=(value,)))

    if name in ("lessthan", "lessthanorequal", "greaterthan", "greaterthanorequal"):
        lhs, value, flipped = _split_comparison_flip(args[0], args[1])
        if flipped:
            name = {
                "lessthan": "greaterthan",
                "lessthanorequal": "greaterthanorequal",
                "greaterthan": "lessthan",
                "greaterthanorequal": "lessthanorequal",
            }[name]
        if name == "lessthan":
            p = Predicate(PredicateType.RANGE, lhs, upper=value, upper_inclusive=False)
        elif name == "lessthanorequal":
            p = Predicate(PredicateType.RANGE, lhs, upper=value, upper_inclusive=True)
        elif name == "greaterthan":
            p = Predicate(PredicateType.RANGE, lhs, lower=value, lower_inclusive=False)
        else:
            p = Predicate(PredicateType.RANGE, lhs, lower=value, lower_inclusive=True)
        return FilterContext.pred(p)

    if name == "between":
        lo = _require_literal(args[1])
        hi = _require_literal(args[2])
        return FilterContext.pred(
            Predicate(PredicateType.RANGE, args[0], lower=lo, upper=hi,
                      lower_inclusive=True, upper_inclusive=True))

    if name in ("in", "notin"):
        values = tuple(_require_literal(a) for a in args[1:])
        ptype = PredicateType.IN if name == "in" else PredicateType.NOT_IN
        return FilterContext.pred(Predicate(ptype, args[0], values=values))

    if name == "like":
        return FilterContext.pred(
            Predicate(PredicateType.LIKE, args[0], values=(_require_literal(args[1]),)))
    if name in ("regexplike", "regexp"):
        return FilterContext.pred(
            Predicate(PredicateType.REGEXP_LIKE, args[0], values=(_require_literal(args[1]),)))
    if name == "textmatch":
        return FilterContext.pred(
            Predicate(PredicateType.TEXT_MATCH, args[0], values=(_require_literal(args[1]),)))
    if name in ("vectorsimilarity", "vector_similarity"):
        # VECTOR_SIMILARITY(col, queryVector, topK) (reference:
        # VectorSimilarityPredicate; topK default 10)
        vec = _require_literal(args[1])
        if not isinstance(vec, (list, tuple)):
            raise FilterConversionError("VECTOR_SIMILARITY needs an ARRAY literal")
        k = int(_require_literal(args[2])) if len(args) > 2 else 10
        return FilterContext.pred(
            Predicate(PredicateType.VECTOR_SIMILARITY, args[0],
                      values=(list(vec), k)))
    if name == "jsonmatch":
        return FilterContext.pred(
            Predicate(PredicateType.JSON_MATCH, args[0], values=(_require_literal(args[1]),)))
    if name == "isnull":
        return FilterContext.pred(Predicate(PredicateType.IS_NULL, args[0]))
    if name == "isnotnull":
        return FilterContext.pred(Predicate(PredicateType.IS_NOT_NULL, args[0]))

    # fallback: arbitrary boolean-valued expression — evaluate `expr = true`
    return FilterContext.pred(Predicate(PredicateType.EQ, expr, values=(True,)))


def _split_comparison(a: ExpressionContext, b: ExpressionContext):
    """Return (lhs_expr, literal_value); flips literal-on-left comparisons."""
    if b.is_literal:
        return a, b.literal
    if a.is_literal:
        return b, a.literal
    raise FilterConversionError(f"comparison requires a literal side: {a} vs {b}")


def _split_comparison_flip(a: ExpressionContext, b: ExpressionContext):
    if b.is_literal:
        return a, b.literal, False
    if a.is_literal:
        return b, a.literal, True
    raise FilterConversionError(f"comparison requires a literal side: {a} vs {b}")


def _require_literal(e: ExpressionContext):
    if not e.is_literal:
        raise FilterConversionError(f"expected literal, got {e}")
    return e.literal
