"""Query expression tree.

Reference: pinot-common/.../request/context/ExpressionContext.java — an
expression is a LITERAL, an IDENTIFIER, or a FUNCTION over child expressions.
This compiled form is shared by the whole engine: filters, projections,
group-by keys, aggregation inputs, post-aggregation, HAVING and ORDER BY all
hold ExpressionContext nodes.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Optional


class ExpressionType(enum.Enum):
    LITERAL = "LITERAL"
    IDENTIFIER = "IDENTIFIER"
    FUNCTION = "FUNCTION"


@dataclass(frozen=True)
class FunctionContext:
    name: str  # canonical lower-case, e.g. "sum", "plus", "cast"
    arguments: tuple["ExpressionContext", ...] = ()

    def __str__(self) -> str:
        # cached: reduce paths key env dicts by expression string per group
        # row — recomputing the recursive form is O(tree) per call and
        # dominated broker reduce at numGroupsLimit scale. Instances are
        # frozen, so the cache can never go stale.
        s = self.__dict__.get("_str")
        if s is None:
            s = f"{self.name}({','.join(map(str, self.arguments))})"
            object.__setattr__(self, "_str", s)
        return s


@dataclass(frozen=True)
class ExpressionContext:
    type: ExpressionType
    identifier: Optional[str] = None
    literal: Any = None
    function: Optional[FunctionContext] = None

    # -- constructors ------------------------------------------------------
    @staticmethod
    def for_identifier(name: str) -> "ExpressionContext":
        return ExpressionContext(ExpressionType.IDENTIFIER, identifier=name)

    @staticmethod
    def for_literal(value: Any) -> "ExpressionContext":
        return ExpressionContext(ExpressionType.LITERAL, literal=value)

    @staticmethod
    def for_function(name: str, *args: "ExpressionContext") -> "ExpressionContext":
        return ExpressionContext(
            ExpressionType.FUNCTION, function=FunctionContext(name.lower(), tuple(args))
        )

    # -- predicates --------------------------------------------------------
    @property
    def is_identifier(self) -> bool:
        return self.type == ExpressionType.IDENTIFIER

    @property
    def is_literal(self) -> bool:
        return self.type == ExpressionType.LITERAL

    @property
    def is_function(self) -> bool:
        return self.type == ExpressionType.FUNCTION

    def columns(self) -> set[str]:
        """All identifiers referenced under this expression."""
        if self.is_identifier:
            return {self.identifier}
        if self.is_function:
            out: set[str] = set()
            for a in self.function.arguments:
                out |= a.columns()
            return out
        return set()

    def __str__(self) -> str:
        if self.is_identifier:
            return self.identifier
        if self.is_literal:
            if isinstance(self.literal, str):
                return f"'{self.literal}'"
            return str(self.literal)
        return str(self.function)  # FunctionContext.__str__ caches


# Aggregation function names recognized by the engine. Mirrors the reference's
# AggregationFunctionType enum (pinot-segment-spi/.../AggregationFunctionType.java);
# grows as engine/aggregation.py implements more.
AGGREGATION_FUNCTIONS = frozenset(
    {
        "count", "sum", "min", "max", "avg",
        "minmaxrange", "sumprecision",
        "distinctcount", "distinctcountbitmap", "segmentpartitioneddistinctcount",
        "distinctcounthll", "distinctcounthllplus", "distinctcountull",
        "distinctcountcpc", "distinctcounttheta", "distinctcountrawtheta",
        "distinctcountsmart", "distinctcountsmarthll", "distinctsum", "distinctavg",
        "distinctcountbitmapmv", "distinctcounthllmv", "distinctcounthllplusmv",
        "percentilerawkll",
        "percentile", "percentileest", "percentiletdigest", "percentilekll",
        "percentilerawest", "percentilerawtdigest", "percentilesmarttdigest",
        "percentileestmv", "percentiletdigestmv", "percentilekllmv",
        "skewness", "kurtosis",
        "mode", "firstwithtime", "lastwithtime",
        "arrayagg", "listagg",
        "boolagg", "booland", "boolor",
        "exprmin", "exprmax",
        "stddevpop", "stddevsamp", "varpop", "varsamp", "skewness", "kurtosis",
        "covarpop", "covarsamp", "corr",
        "countmv", "summv", "minmv", "maxmv", "avgmv", "distinctcountmv",
        "percentilemv", "percentileestmv", "percentiletdigestmv", "minmaxrangemv",
        "histogram", "frequentstrings", "frequentlongs",
        "funnelcount", "funnelmatchstep", "funnelcompletecount", "funnelmaxstep",
    }
)


import re as _re

# legacy digit-suffixed percentiles: PERCENTILE95 / PERCENTILETDIGEST99 / ...
# (reference AggregationFunctionType.getAggregationFunctionType matches \d+).
# Single source of truth — engine/aggregation.py canonicalizes with this too.
PERCENTILE_SUFFIX_RE = _re.compile(
    r"^(percentile(?:est|tdigest|kll|rawest|rawtdigest|rawkll|smarttdigest)?)"
    r"(\d+)(mv)?$")


def is_aggregation_name(name: str) -> bool:
    return name in AGGREGATION_FUNCTIONS or PERCENTILE_SUFFIX_RE.match(name) is not None


def is_aggregation(expr: ExpressionContext) -> bool:
    if not expr.is_function:
        return False
    fn = expr.function
    # filter(agg, cond): the FILTER (WHERE ...) clause wrapper
    # (reference FilteredAggregationFunction)
    if fn.name == "filter" and fn.arguments \
            and is_aggregation(fn.arguments[0]):
        return True
    return is_aggregation_name(fn.name)


def contains_aggregation(expr: ExpressionContext) -> bool:
    if is_aggregation(expr):
        return True
    if expr.is_function:
        return any(contains_aggregation(a) for a in expr.function.arguments)
    return False


def extract_aggregations(expr: ExpressionContext, out: list) -> None:
    """Collect aggregation sub-expressions in evaluation order (dedup by eq)."""
    if is_aggregation(expr):
        if expr not in out:
            out.append(expr)
        return
    if expr.is_function:
        for a in expr.function.arguments:
            extract_aggregations(a, out)
