"""Filter tree + predicates.

Reference: pinot-common/.../request/context/FilterContext.java and
pinot-core/.../operator/filter/predicate/ predicate evaluators. The filter is
an AND/OR/NOT tree with typed leaf predicates over one expression (usually an
identifier). On TPU, every leaf lowers to a vectorized compare against the
int32 dict-id plane (dictionary-encoded) or the raw value plane, and the tree
lowers to boolean algebra on masks — there is no iterator/bitmap machinery
because masks are free on the MXU-adjacent VPU.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Optional

from .expressions import ExpressionContext


class FilterNodeType(enum.Enum):
    AND = "AND"
    OR = "OR"
    NOT = "NOT"
    PREDICATE = "PREDICATE"
    CONSTANT = "CONSTANT"  # TRUE / FALSE


class PredicateType(enum.Enum):
    EQ = "EQ"
    NOT_EQ = "NOT_EQ"
    IN = "IN"
    NOT_IN = "NOT_IN"
    RANGE = "RANGE"
    LIKE = "LIKE"
    REGEXP_LIKE = "REGEXP_LIKE"
    TEXT_MATCH = "TEXT_MATCH"
    JSON_MATCH = "JSON_MATCH"
    IS_NULL = "IS_NULL"
    IS_NOT_NULL = "IS_NOT_NULL"
    VECTOR_SIMILARITY = "VECTOR_SIMILARITY"


@dataclass(frozen=True)
class Predicate:
    """Leaf predicate over `lhs` (reference Predicate.java subclasses).

    RANGE carries [lower, upper] with inclusivity flags; None bound = open
    (reference RangePredicate uses "(*" / "*)" sentinels).
    """

    type: PredicateType
    lhs: ExpressionContext
    values: tuple = ()  # EQ/NOT_EQ: 1 value; IN/NOT_IN: n values; LIKE/REGEXP: pattern
    lower: Any = None
    upper: Any = None
    lower_inclusive: bool = True
    upper_inclusive: bool = True

    def __str__(self) -> str:
        if self.type == PredicateType.RANGE:
            lb = "[" if self.lower_inclusive else "("
            ub = "]" if self.upper_inclusive else ")"
            lo = "*" if self.lower is None else self.lower
            hi = "*" if self.upper is None else self.upper
            return f"{self.lhs} {lb}{lo},{hi}{ub}"
        return f"{self.lhs} {self.type.value} {list(self.values)}"


@dataclass(frozen=True)
class FilterContext:
    type: FilterNodeType
    children: tuple["FilterContext", ...] = ()
    predicate: Optional[Predicate] = None
    constant_value: bool = True  # for CONSTANT nodes

    @staticmethod
    def and_(*children: "FilterContext") -> "FilterContext":
        flat = []
        for c in children:
            if c.type == FilterNodeType.AND:
                flat.extend(c.children)
            else:
                flat.append(c)
        return FilterContext(FilterNodeType.AND, tuple(flat))

    @staticmethod
    def or_(*children: "FilterContext") -> "FilterContext":
        flat = []
        for c in children:
            if c.type == FilterNodeType.OR:
                flat.extend(c.children)
            else:
                flat.append(c)
        return FilterContext(FilterNodeType.OR, tuple(flat))

    @staticmethod
    def not_(child: "FilterContext") -> "FilterContext":
        return FilterContext(FilterNodeType.NOT, (child,))

    @staticmethod
    def pred(p: Predicate) -> "FilterContext":
        return FilterContext(FilterNodeType.PREDICATE, predicate=p)

    @staticmethod
    def constant(value: bool) -> "FilterContext":
        return FilterContext(FilterNodeType.CONSTANT, constant_value=value)

    def columns(self) -> set[str]:
        if self.type == FilterNodeType.PREDICATE:
            return self.predicate.lhs.columns()
        out: set[str] = set()
        for c in self.children:
            out |= c.columns()
        return out

    def __str__(self) -> str:
        if self.type == FilterNodeType.PREDICATE:
            return str(self.predicate)
        if self.type == FilterNodeType.CONSTANT:
            return str(self.constant_value).upper()
        if self.type == FilterNodeType.NOT:
            return f"NOT({self.children[0]})"
        sep = f" {self.type.value} "
        return "(" + sep.join(map(str, self.children)) + ")"
