"""Filter optimizer: canonicalize, merge, and fold the filter tree.

Reference: pinot-core/src/main/java/org/apache/pinot/core/query/optimizer/
QueryOptimizer.java and its filter passes —
FlattenAndOrFilterOptimizer, MergeEqInFilterOptimizer (EQ/IN union under OR,
intersection under AND), MergeRangeFilterOptimizer (range intersection under
AND), and constant folding. NOT elimination (De Morgan + predicate
inversion) plays the role Calcite's rewrites play upstream of the reference.

Applied once per query on the server execution path (execute_segments), so
the single-stage engine, the cluster scatter path, and MSE leaf pushdowns
all see optimized trees. Every pass is semantics-preserving; passes that
need value comparisons skip groups with incomparable mixed types rather
than guess.
"""

from __future__ import annotations

from typing import Optional

from .filter import FilterContext, FilterNodeType, Predicate, PredicateType

_P = PredicateType
_N = FilterNodeType

_INVERTIBLE = {
    _P.EQ: _P.NOT_EQ, _P.NOT_EQ: _P.EQ,
    _P.IN: _P.NOT_IN, _P.NOT_IN: _P.IN,
    _P.IS_NULL: _P.IS_NOT_NULL, _P.IS_NOT_NULL: _P.IS_NULL,
}


def optimize_filter(f: Optional[FilterContext]) -> Optional[FilterContext]:
    if f is None:
        return None
    f = _push_not(f, negate=False)
    f = _merge(f)
    return f


# -- NOT elimination ----------------------------------------------------------


def _push_not(f: FilterContext, negate: bool) -> FilterContext:
    """De Morgan + predicate inversion; NOT survives only over predicates
    with no natural inverse (RANGE, LIKE, text/json/vector)."""
    if f.type == _N.NOT:
        return _push_not(f.children[0], not negate)
    if f.type == _N.AND:
        kids = tuple(_push_not(c, negate) for c in f.children)
        return FilterContext.or_(*kids) if negate else FilterContext.and_(*kids)
    if f.type == _N.OR:
        kids = tuple(_push_not(c, negate) for c in f.children)
        return FilterContext.and_(*kids) if negate else FilterContext.or_(*kids)
    if f.type == _N.CONSTANT:
        return FilterContext.constant(f.constant_value != negate)
    # PREDICATE
    if not negate:
        return f
    p = f.predicate
    inv = _INVERTIBLE.get(p.type)
    if inv is not None:
        return FilterContext.pred(Predicate(
            inv, p.lhs, values=p.values, lower=p.lower, upper=p.upper,
            lower_inclusive=p.lower_inclusive, upper_inclusive=p.upper_inclusive))
    return FilterContext.not_(f)


# -- merge + fold (bottom-up) -------------------------------------------------


def _merge(f: FilterContext) -> FilterContext:
    if f.type == _N.AND:
        kids = [_merge(c) for c in f.children]
        return _merge_and(kids)
    if f.type == _N.OR:
        kids = [_merge(c) for c in f.children]
        return _merge_or(kids)
    if f.type == _N.NOT:
        child = _merge(f.children[0])
        if child.type == _N.CONSTANT:
            return FilterContext.constant(not child.constant_value)
        return FilterContext.not_(child)
    return f


def _comparable(values) -> bool:
    try:
        sorted(values)
        return True
    except TypeError:
        return False


def _key(p: Predicate) -> str:
    return str(p.lhs)


def _merge_and(kids: list[FilterContext]) -> FilterContext:
    out: list[FilterContext] = []
    eq_in: dict[str, set] = {}       # lhs → allowed-value intersection
    eq_order: dict[str, Predicate] = {}
    not_in: dict[str, set] = {}      # lhs → excluded-value union
    not_order: dict[str, Predicate] = {}
    ranges: dict[str, list[Predicate]] = {}  # unmergeable ones stay separate

    for c in kids:
        if c.type == _N.CONSTANT:
            if not c.constant_value:
                return FilterContext.constant(False)
            continue  # TRUE contributes nothing
        if c.type != _N.PREDICATE:
            out.append(c)
            continue
        p = c.predicate
        k = _key(p)
        if p.type in (_P.EQ, _P.IN):
            vals = set(p.values)
            eq_in[k] = eq_in[k] & vals if k in eq_in else vals
            eq_order.setdefault(k, p)
        elif p.type in (_P.NOT_EQ, _P.NOT_IN):
            not_in.setdefault(k, set()).update(p.values)
            not_order.setdefault(k, p)
        elif p.type == _P.RANGE:
            group = ranges.setdefault(k, [])
            for i, existing in enumerate(group):
                try:
                    merged = _intersect_ranges(existing, p)
                except TypeError:
                    continue  # incomparable bound types: keep both
                if merged is None:
                    return FilterContext.constant(False)
                group[i] = merged
                break
            else:
                group.append(p)
        else:
            out.append(c)

    # EQ/IN ∩ RANGE on the same column: filter allowed values through the
    # range — only when every value compares against the bounds
    for k in list(eq_in):
        for r in list(ranges.get(k, [])):
            try:
                vals = {v for v in eq_in[k] if _in_range(v, r)}
            except TypeError:
                continue  # incomparable: keep the range as its own predicate
            eq_in[k] = vals
            ranges[k].remove(r)
    # EQ/IN minus NOT_IN exclusions on the same column
    for k in list(eq_in):
        if k in not_in:
            eq_in[k] = eq_in[k] - not_in.pop(k)

    for k, vals in eq_in.items():
        if not vals:
            return FilterContext.constant(False)
        out.append(_values_pred(eq_order[k], vals, negated=False))
    for k, vals in not_in.items():
        out.append(_values_pred(not_order[k], vals, negated=True))
    out.extend(FilterContext.pred(r) for group in ranges.values()
               for r in group)

    if not out:
        return FilterContext.constant(True)
    if len(out) == 1:
        return out[0]
    return FilterContext.and_(*out)


def _merge_or(kids: list[FilterContext]) -> FilterContext:
    out: list[FilterContext] = []
    eq_in: dict[str, set] = {}  # lhs → allowed-value union
    eq_order: dict[str, Predicate] = {}

    for c in kids:
        if c.type == _N.CONSTANT:
            if c.constant_value:
                return FilterContext.constant(True)
            continue  # FALSE contributes nothing
        if c.type == _N.PREDICATE and c.predicate.type in (_P.EQ, _P.IN):
            p = c.predicate
            k = _key(p)
            eq_in.setdefault(k, set()).update(p.values)
            eq_order.setdefault(k, p)
        else:
            out.append(c)

    for k, vals in eq_in.items():
        out.append(_values_pred(eq_order[k], vals, negated=False))

    if not out:
        return FilterContext.constant(False)
    if len(out) == 1:
        return out[0]
    return FilterContext.or_(*out)


def _values_pred(template: Predicate, vals: set, negated: bool) -> FilterContext:
    ordered = tuple(sorted(vals)) if _comparable(vals) else tuple(vals)
    if len(ordered) == 1:
        t = _P.NOT_EQ if negated else _P.EQ
    else:
        t = _P.NOT_IN if negated else _P.IN
    return FilterContext.pred(Predicate(t, template.lhs, values=ordered))


def _intersect_ranges(a: Predicate, b: Predicate) -> Optional[Predicate]:
    """[a] ∩ [b], or None when provably empty. Raises TypeError on
    incomparable bound types — the caller keeps both ranges separate."""
    lower, lower_inc = _max_bound(
        (a.lower, a.lower_inclusive), (b.lower, b.lower_inclusive))
    upper, upper_inc = _min_bound(
        (a.upper, a.upper_inclusive), (b.upper, b.upper_inclusive))
    if lower is not None and upper is not None:
        if lower > upper:
            return None
        if lower == upper and not (lower_inc and upper_inc):
            return None
    return Predicate(_P.RANGE, a.lhs, lower=lower, upper=upper,
                     lower_inclusive=lower_inc, upper_inclusive=upper_inc)


def _max_bound(x, y):
    (xv, xi), (yv, yi) = x, y
    if xv is None:
        return yv, yi
    if yv is None:
        return xv, xi
    if xv > yv:
        return xv, xi
    if yv > xv:
        return yv, yi
    return xv, xi and yi


def _min_bound(x, y):
    (xv, xi), (yv, yi) = x, y
    if xv is None:
        return yv, yi
    if yv is None:
        return xv, xi
    if xv < yv:
        return xv, xi
    if yv < xv:
        return yv, yi
    return xv, xi and yi


def _in_range(v, r: Predicate) -> bool:
    """Raises TypeError on incomparable types (caller keeps the range)."""
    if r.lower is not None:
        if v < r.lower or (v == r.lower and not r.lower_inclusive):
            return False
    if r.upper is not None:
        if v > r.upper or (v == r.upper and not r.upper_inclusive):
            return False
    return True
