"""SQL parser: text → QueryContext.

Replaces the reference's Calcite front-end for the single-stage engine
(pinot-common/.../sql/parsers/CalciteSqlParser.java:75,
compileToPinotQuery:160). Hand-rolled recursive descent over a small
tokenizer; expressions parse to ExpressionContext trees with boolean
operators as functions (and/or/not/equals/...), then WHERE/HAVING convert to
FilterContext via converter.filter_from_expression — the same two-layer shape
as the reference's PinotQuery → QueryContext pipeline.

Supports: SELECT [DISTINCT] list FROM t [WHERE e] [GROUP BY list] [HAVING e]
[ORDER BY e [ASC|DESC], ...] [LIMIT n [OFFSET m] | LIMIT o, n], SET options,
EXPLAIN PLAN FOR, expressions with arithmetic/comparison/IN/BETWEEN/LIKE/
IS NULL/CASE WHEN/CAST, function calls, quoted identifiers and aliases.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Optional

from ..context import OrderByExpressionContext, QueryContext
from ..converter import FilterConversionError, filter_from_expression
from ..expressions import ExpressionContext

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
      (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
    | (?P<string>'(?:[^']|'')*')
    | (?P<qident>"(?:[^"]|"")*")
    | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
    | (?P<op><>|!=|>=|<=|=|<|>|\(|\)|\[|\]|,|\+|-|\*|/|%|\.|;)
    )""",
    re.VERBOSE,
)


@dataclass
class Token:
    kind: str  # number|string|ident|qident|op|eof
    value: str
    upper: str = ""


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if not m or m.end() == pos:
            if sql[pos:].strip() == "":
                break
            raise SqlParseError(f"unexpected character {sql[pos]!r} at position {pos}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group(kind)
        if kind == "string":
            tokens.append(Token("string", text[1:-1].replace("''", "'")))
        elif kind == "qident":
            tokens.append(Token("ident", text[1:-1].replace('""', '"')))
        elif kind == "ident":
            tokens.append(Token("ident", text, text.upper()))
        else:
            tokens.append(Token(kind, text, text.upper()))
    tokens.append(Token("eof", ""))
    return tokens


class SqlParseError(Exception):
    pass


_CANON_RE = re.compile(r"[_\s]")


def canonical_function_name(name: str) -> str:
    """Lower-case, underscore-free (reference FunctionRegistry canonicalization:
    pinot-common/.../function/FunctionRegistry.java:70 canonicalize)."""
    return _CANON_RE.sub("", name.lower())


class _Parser:
    def __init__(self, tokens: list[Token]):
        self.tokens = tokens
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in kws

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.i += 1
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlParseError(f"expected {kw}, got {self.peek().value!r}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == op:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlParseError(f"expected {op!r}, got {self.peek().value!r}")

    # -- entry -------------------------------------------------------------
    def parse_query(self) -> QueryContext:
        options: dict[str, Any] = {}
        while self.at_kw("SET"):
            self.next()
            key = self.next().value
            self.expect_op("=")
            val_tok = self.next()
            options[key] = _literal_value(val_tok)
            self.accept_op(";")
        explain: Any = False
        if self.accept_kw("EXPLAIN"):
            # EXPLAIN IMPLEMENTATION FOR names the concrete kernel variants
            # (group-by path, device combine) instead of the logical plan —
            # same contract as the MSE parser (mse/parser.py)
            if self.accept_kw("IMPLEMENTATION"):
                explain = "implementation"
            elif self.accept_kw("ANALYZE"):
                # EXPLAIN ANALYZE runs the query for real (tracing armed,
                # caches live) and annotates the plan with observed rows,
                # dispatches, and phase timings
                explain = "analyze"
            else:
                self.accept_kw("PLAN")
                explain = True
            self.accept_kw("FOR")
        qc = self._parse_select()
        qc.query_options.update(options)
        qc.explain = explain
        self.accept_op(";")
        if self.peek().kind != "eof":
            raise SqlParseError(f"trailing input at {self.peek().value!r}")
        return qc.finish()

    def _parse_select(self) -> QueryContext:
        self.expect_kw("SELECT")
        distinct = self.accept_kw("DISTINCT")
        select_exprs: list[ExpressionContext] = []
        aliases: list[Optional[str]] = []
        while True:
            if self.peek().kind == "op" and self.peek().value == "*":
                self.next()
                select_exprs.append(ExpressionContext.for_identifier("*"))
                aliases.append(None)
            else:
                select_exprs.append(self.parse_expression())
                aliases.append(self._maybe_alias())
            if not self.accept_op(","):
                break
        self.expect_kw("FROM")
        table = self._parse_table_name()
        qc = QueryContext(table_name=table, select_expressions=select_exprs,
                          aliases=aliases, distinct=distinct)
        if self.accept_kw("WHERE"):
            qc.filter = self._to_filter(self.parse_expression())
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            qc.group_by_expressions.append(self.parse_expression())
            while self.accept_op(","):
                qc.group_by_expressions.append(self.parse_expression())
        if self.accept_kw("HAVING"):
            qc.having_filter = self._to_filter(self.parse_expression())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                e = self.parse_expression()
                asc = True
                if self.accept_kw("DESC"):
                    asc = False
                else:
                    self.accept_kw("ASC")
                nulls_last = None
                if self.accept_kw("NULLS"):
                    if self.accept_kw("LAST"):
                        nulls_last = True
                    else:
                        self.expect_kw("FIRST")
                        nulls_last = False
                qc.order_by_expressions.append(OrderByExpressionContext(e, asc, nulls_last))
                if not self.accept_op(","):
                    break
        if self.accept_kw("LIMIT"):
            first = self._expect_int()
            if self.accept_op(","):  # LIMIT offset, count (MySQL style)
                qc.offset = first
                qc.limit = self._expect_int()
            else:
                qc.limit = first
                if self.accept_kw("OFFSET"):
                    qc.offset = self._expect_int()
        return qc

    def _parse_table_name(self) -> str:
        name = self.next()
        if name.kind != "ident":
            raise SqlParseError(f"expected table name, got {name.value!r}")
        parts = [name.value]
        while self.accept_op("."):
            parts.append(self.next().value)
        # swallow optional alias (unused in single-table queries)
        if self.peek().kind == "ident" and not self.at_kw(
            "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OPTION", "AS"
        ):
            self.next()
        elif self.accept_kw("AS"):
            self.next()
        return ".".join(parts)

    def _to_filter(self, expr: ExpressionContext):
        try:
            return filter_from_expression(expr)
        except FilterConversionError as e:
            raise SqlParseError(str(e)) from e

    def _maybe_alias(self) -> Optional[str]:
        if self.accept_kw("AS"):
            t = self.next()
            if t.kind not in ("ident", "string"):
                raise SqlParseError(f"expected alias, got {t.value!r}")
            return t.value
        t = self.peek()
        if t.kind == "ident" and t.upper not in _RESERVED:
            self.next()
            return t.value
        return None

    def _expect_int(self) -> int:
        t = self.next()
        if t.kind != "number":
            raise SqlParseError(f"expected integer, got {t.value!r}")
        try:
            return int(t.value)
        except ValueError:
            raise SqlParseError(f"expected integer, got {t.value!r}") from None

    # -- expressions (precedence climbing) ---------------------------------
    def parse_expression(self) -> ExpressionContext:
        return self._parse_or()

    def _parse_or(self) -> ExpressionContext:
        left = self._parse_and()
        while self.accept_kw("OR"):
            right = self._parse_and()
            left = ExpressionContext.for_function("or", left, right)
        return left

    def _parse_and(self) -> ExpressionContext:
        left = self._parse_not()
        while self.accept_kw("AND"):
            right = self._parse_not()
            left = ExpressionContext.for_function("and", left, right)
        return left

    def _parse_not(self) -> ExpressionContext:
        if self.accept_kw("NOT"):
            return ExpressionContext.for_function("not", self._parse_not())
        return self._parse_comparison()

    def _parse_comparison(self) -> ExpressionContext:
        left = self._parse_additive()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            right = self._parse_additive()
            name = {
                "=": "equals", "!=": "notequals", "<>": "notequals",
                "<": "lessthan", "<=": "lessthanorequal",
                ">": "greaterthan", ">=": "greaterthanorequal",
            }[t.value]
            return ExpressionContext.for_function(name, left, right)
        negated = False
        if self.at_kw("NOT") and self.peek(1).upper in ("IN", "BETWEEN", "LIKE"):
            self.next()
            negated = True
        if self.accept_kw("IN"):
            self.expect_op("(")
            args = [left]
            args.append(self.parse_expression())
            while self.accept_op(","):
                args.append(self.parse_expression())
            self.expect_op(")")
            return ExpressionContext.for_function("notin" if negated else "in", *args)
        if self.accept_kw("BETWEEN"):
            lo = self._parse_additive()
            self.expect_kw("AND")
            hi = self._parse_additive()
            e = ExpressionContext.for_function("between", left, lo, hi)
            return ExpressionContext.for_function("not", e) if negated else e
        if self.accept_kw("LIKE"):
            pattern = self._parse_additive()
            e = ExpressionContext.for_function("like", left, pattern)
            return ExpressionContext.for_function("not", e) if negated else e
        if self.accept_kw("IS"):
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                return ExpressionContext.for_function("isnotnull", left)
            self.expect_kw("NULL")
            return ExpressionContext.for_function("isnull", left)
        return left

    def _parse_additive(self) -> ExpressionContext:
        left = self._parse_multiplicative()
        while True:
            if self.accept_op("+"):
                left = ExpressionContext.for_function("plus", left, self._parse_multiplicative())
            elif self.accept_op("-"):
                left = ExpressionContext.for_function("minus", left, self._parse_multiplicative())
            else:
                return left

    def _parse_multiplicative(self) -> ExpressionContext:
        left = self._parse_unary()
        while True:
            if self.accept_op("*"):
                left = ExpressionContext.for_function("times", left, self._parse_unary())
            elif self.accept_op("/"):
                left = ExpressionContext.for_function("divide", left, self._parse_unary())
            elif self.accept_op("%"):
                left = ExpressionContext.for_function("mod", left, self._parse_unary())
            else:
                return left

    def _parse_unary(self) -> ExpressionContext:
        if self.accept_op("-"):
            inner = self._parse_unary()
            if inner.is_literal and isinstance(inner.literal, (int, float)):
                return ExpressionContext.for_literal(-inner.literal)
            return ExpressionContext.for_function("minus", ExpressionContext.for_literal(0), inner)
        if self.accept_op("+"):
            return self._parse_unary()
        return self._parse_primary()

    def _parse_primary(self) -> ExpressionContext:
        t = self.peek()
        if t.kind == "number":
            self.next()
            return ExpressionContext.for_literal(_number(t.value))
        if t.kind == "string":
            self.next()
            return ExpressionContext.for_literal(t.value)
        if self.accept_op("("):
            e = self.parse_expression()
            self.expect_op(")")
            return e
        if t.kind == "ident":
            if t.upper == "TRUE":
                self.next()
                return ExpressionContext.for_literal(True)
            if t.upper == "FALSE":
                self.next()
                return ExpressionContext.for_literal(False)
            if t.upper == "NULL":
                self.next()
                return ExpressionContext.for_literal(None)
            if t.upper == "CASE":
                return self._parse_case()
            if t.upper == "CAST":
                return self._parse_cast()
            if t.upper == "ARRAY" and self.peek(1).kind == "op" \
                    and self.peek(1).value == "[":
                # ARRAY[1,2,3] literal (VECTOR_SIMILARITY query vectors,
                # array scalar fns)
                self.next()
                self.next()
                vals = []
                if not self.accept_op("]"):
                    while True:
                        e = self.parse_expression()
                        if not e.is_literal:
                            raise SqlParseError("ARRAY[...] takes literals")
                        vals.append(e.literal)
                        if not self.accept_op(","):
                            break
                    self.expect_op("]")
                return ExpressionContext.for_literal(vals)
            self.next()
            # function call?
            if self.accept_op("("):
                return self._parse_function_call(t.value)
            parts = [t.value]
            while self.accept_op("."):
                parts.append(self.next().value)
            return ExpressionContext.for_identifier(self._make_identifier(parts))
        raise SqlParseError(f"unexpected token {t.value!r}")

    def _make_identifier(self, parts: list[str]) -> str:
        """Dotted identifier resolution: the single-stage engine is
        single-table so qualifiers are dropped (reference does the same in
        BaseSingleStageBrokerRequestHandler column resolution); the MSE
        parser overrides this to keep qualifiers for join disambiguation."""
        return parts[-1]

    def _parse_function_call(self, raw_name: str) -> ExpressionContext:
        name = canonical_function_name(raw_name)
        args: list[ExpressionContext] = []
        if self.accept_op(")"):
            return ExpressionContext.for_function(name, *args)
        # COUNT(*) / COUNT(DISTINCT x)
        if self.peek().kind == "op" and self.peek().value == "*":
            self.next()
            args.append(ExpressionContext.for_identifier("*"))
        else:
            if self.at_kw("DISTINCT"):
                # agg(DISTINCT x) rewrites (reference CalciteSqlParser distinct rewrite)
                distinct_map = {"count": "distinctcount", "sum": "distinctsum", "avg": "distinctavg"}
                if name in distinct_map:
                    self.next()
                    name = distinct_map[name]
                elif name in ("distinctcount", "distinctsum", "distinctavg"):
                    self.next()
                else:
                    raise SqlParseError(f"DISTINCT is not supported inside {name}()")
            args.append(self.parse_expression())
        while self.accept_op(","):
            args.append(self.parse_expression())
        self.expect_op(")")
        e = ExpressionContext.for_function(name, *args)
        # AGG(x) FILTER (WHERE cond) — reference FilteredAggregationFunction;
        # postfix here so HAVING / ORDER BY positions parse too
        if self.peek().kind == "ident" and self.peek().upper == "FILTER":
            from ..expressions import is_aggregation

            if not is_aggregation(e):
                raise SqlParseError(
                    "FILTER clause requires an aggregation function")
            self.next()
            self.expect_op("(")
            self.expect_kw("WHERE")
            cond = self.parse_expression()
            self.expect_op(")")
            e = ExpressionContext.for_function("filter", e, cond)
        return e

    def _parse_case(self) -> ExpressionContext:
        """CASE WHEN c1 THEN v1 ... [ELSE d] END → case(c1,v1,...,d)
        (reference: CalciteSqlParser case-when rewrite)."""
        self.expect_kw("CASE")
        args: list[ExpressionContext] = []
        while self.accept_kw("WHEN"):
            args.append(self.parse_expression())
            self.expect_kw("THEN")
            args.append(self.parse_expression())
        if self.accept_kw("ELSE"):
            args.append(self.parse_expression())
        else:
            args.append(ExpressionContext.for_literal(None))
        self.expect_kw("END")
        return ExpressionContext.for_function("case", *args)

    def _parse_cast(self) -> ExpressionContext:
        self.expect_kw("CAST")
        self.expect_op("(")
        e = self.parse_expression()
        self.expect_kw("AS")
        type_name = self.next().value
        self.expect_op(")")
        return ExpressionContext.for_function("cast", e, ExpressionContext.for_literal(type_name.upper()))


_RESERVED = frozenset(
    {
        "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT", "OFFSET", "AS",
        "AND", "OR", "NOT", "IN", "BETWEEN", "LIKE", "IS", "NULL", "SELECT",
        "DISTINCT", "ASC", "DESC", "CASE", "WHEN", "THEN", "ELSE", "END", "SET",
        "OPTION", "EXPLAIN", "PLAN", "FOR", "NULLS", "FIRST", "LAST", "JOIN", "ON",
        "UNION", "INTERSECT", "EXCEPT", "ALL", "INNER", "LEFT", "RIGHT", "FULL",
        "OUTER", "CROSS", "SEMI", "ANTI", "USING", "WITH", "OVER", "PARTITION",
    }
)


def _number(text: str):
    if re.fullmatch(r"\d+", text):
        return int(text)
    return float(text)


def _literal_value(tok: Token):
    if tok.kind == "number":
        return _number(tok.value)
    if tok.kind == "string":
        return tok.value
    if tok.upper == "TRUE":
        return True
    if tok.upper == "FALSE":
        return False
    return tok.value


def parse_sql(sql: str) -> QueryContext:
    """Parse a SQL string into a finished QueryContext."""
    return _Parser(tokenize(sql)).parse_query()


def parse_filter_expression(expr: str):
    """Parse a standalone boolean expression into a FilterContext — used by
    JSON_MATCH inner filter strings (reference: Pinot parses those with its
    own mini-grammar in JsonMatchPredicate; here the main parser serves)."""
    p = _Parser(tokenize(expr))
    e = p.parse_expression()
    if p.peek().kind != "eof":
        raise SqlParseError(f"trailing input in filter expression: {expr!r}")
    return p._to_filter(e)


def parse_expression_str(expr: str) -> ExpressionContext:
    """Parse a standalone value expression (ingestion transformConfigs,
    timeseries value expressions)."""
    p = _Parser(tokenize(expr))
    e = p.parse_expression()
    if p.peek().kind != "eof":
        raise SqlParseError(f"trailing input in expression: {expr!r}")
    return e
