"""Vectorized transform function library.

Reference: pinot-core/.../operator/transform/function/ (72 block-at-a-time
``TransformFunction`` impls behind ``TransformFunctionFactory``) and their
row-level scalar twins (pinot-common/.../common/function/scalar/). In the TPU
build a transform has up to three forms, all defined here so they cannot
diverge:

1. **Device lowering** to kernel IR (engine/ir.py) — pure numeric ops.
   Calendar extraction (year/month/day/...) lowers to integer civil-date
   arithmetic (Howard Hinnant's public-domain algorithms), i.e. a short chain
   of fused int64 mul/add/floordiv that XLA vectorizes over the whole
   segment; no host round-trips, no dynamic shapes.
2. **Numpy form** — used (a) by the planner to transform *dictionaries* once
   per query so string/complex transforms become device gathers
   (engine/plan.py dict-transform path), and (b) by the host fallback engine.
3. **Scalar form** for post-aggregation/HAVING (engine/reduce.py) — the numpy
   form applied to python scalars.
"""

from __future__ import annotations

import base64
import datetime as _dt
import hashlib
import json
import math
import re
import urllib.parse
import zlib
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from ..engine import ir

# ---------------------------------------------------------------------------
# millis-per-unit table (reference TimeUnit conversions)
# ---------------------------------------------------------------------------

MILLIS = {
    "MILLISECONDS": 1,
    "SECONDS": 1000,
    "MINUTES": 60_000,
    "HOURS": 3_600_000,
    "DAYS": 86_400_000,
    "WEEKS": 604_800_000,
}

# epoch day 0 (1970-01-01) is a Thursday; ISO Monday=1 → offset 3
_DOW_OFFSET = 3


# ---------------------------------------------------------------------------
# civil-date integer arithmetic (numpy form)
# ---------------------------------------------------------------------------


def _np_days(millis):
    return np.floor_divide(np.asarray(millis).astype(np.int64), 86_400_000)


def _np_civil(days):
    """days-since-epoch → (year, month, day, civil-doy) via pure int ops."""
    z = np.asarray(days).astype(np.int64) + 719_468
    era = z // 146_097
    doe = z - era * 146_097
    yoe = (doe - doe // 1460 + doe // 36_524 - doe // 146_096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = y + (m <= 2)
    return y, m, d, doy


def _np_days_from_civil(y, m, d):
    y = np.asarray(y).astype(np.int64) - (np.asarray(m) <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = np.where(np.asarray(m) > 2, np.asarray(m) - 3, np.asarray(m) + 9)
    doy = (153 * mp + 2) // 5 + np.asarray(d) - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146_097 + doe - 719_468


def _np_year(ms):
    return _np_civil(_np_days(ms))[0]


def _np_month(ms):
    return _np_civil(_np_days(ms))[1]


def _np_day(ms):
    return _np_civil(_np_days(ms))[2]


def _np_quarter(ms):
    return (_np_month(ms) - 1) // 3 + 1


def _np_dayofweek(ms):
    return (_np_days(ms) + _DOW_OFFSET) % 7 + 1


def _np_dayofyear(ms):
    d = _np_days(ms)
    y, _, _, _ = _np_civil(d)
    return d - _np_days_from_civil(y, 1, 1) + 1


def _np_week(ms):
    """ISO week of year (reference weekOfYear → Joda ISO chronology)."""
    arr = np.atleast_1d(_np_days(ms))
    out = np.empty(arr.shape, dtype=np.int64)
    flat, oflat = arr.ravel(), out.ravel()
    for i, dd in enumerate(flat):
        oflat[i] = (_dt.date(1970, 1, 1) + _dt.timedelta(days=int(dd))).isocalendar()[1]
    return out.reshape(arr.shape) if np.ndim(ms) else out[0]


def _np_yearofweek(ms):
    arr = np.atleast_1d(_np_days(ms))
    out = np.empty(arr.shape, dtype=np.int64)
    flat, oflat = arr.ravel(), out.ravel()
    for i, dd in enumerate(flat):
        oflat[i] = (_dt.date(1970, 1, 1) + _dt.timedelta(days=int(dd))).isocalendar()[0]
    return out.reshape(arr.shape) if np.ndim(ms) else out[0]


def _np_datetrunc(unit, ms):
    unit = str(unit).upper()
    ms = np.asarray(ms).astype(np.int64)
    simple = {"MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
              "DAY": 86_400_000}
    if unit in simple:
        f = simple[unit]
        return (ms // f) * f
    days = _np_days(ms)
    if unit == "WEEK":
        # truncate to Monday (ISO)
        monday = days - (days + _DOW_OFFSET) % 7
        return monday * 86_400_000
    y, m, _, _ = _np_civil(days)
    if unit == "MONTH":
        return _np_days_from_civil(y, m, 1) * 86_400_000
    if unit == "QUARTER":
        qm = ((m - 1) // 3) * 3 + 1
        return _np_days_from_civil(y, qm, 1) * 86_400_000
    if unit == "YEAR":
        return _np_days_from_civil(y, 1, 1) * 86_400_000
    raise ValueError(f"dateTrunc unit {unit}")


def _np_timestampadd(unit, amount, ms):
    unit = str(unit).upper().rstrip("S")
    ms = np.asarray(ms).astype(np.int64)
    amount = np.asarray(amount).astype(np.int64)
    simple = {"MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
              "DAY": 86_400_000, "WEEK": 604_800_000}
    if unit in simple:
        return ms + amount * simple[unit]
    days = _np_days(ms)
    tod = ms - days * 86_400_000
    y, m, d, _ = _np_civil(days)
    if unit == "MONTH":
        t = (y * 12 + (m - 1)) + amount
        y2, m2 = t // 12, t % 12 + 1
    elif unit in ("YEAR", "QUARTER"):
        step = amount * (3 if unit == "QUARTER" else 12)
        t = (y * 12 + (m - 1)) + step
        y2, m2 = t // 12, t % 12 + 1
    else:
        raise ValueError(f"timestampAdd unit {unit}")
    # clamp day to target month length
    nxt = _np_days_from_civil(y2 + (m2 == 12), np.where(m2 == 12, 1, m2 + 1), 1)
    cur = _np_days_from_civil(y2, m2, 1)
    d2 = np.minimum(d, nxt - cur)
    return (_np_days_from_civil(y2, m2, d2)) * 86_400_000 + tod


def _np_timestampdiff(unit, a, b):
    """timestampDiff(unit, a, b) = (b - a) in unit (reference semantics)."""
    unit = str(unit).upper().rstrip("S")
    a = np.asarray(a).astype(np.int64)
    b = np.asarray(b).astype(np.int64)
    simple = {"MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
              "DAY": 86_400_000, "WEEK": 604_800_000}
    if unit in simple:
        return (b - a) // simple[unit]
    ya, ma, da, _ = _np_civil(_np_days(a))
    yb, mb, db, _ = _np_civil(_np_days(b))
    months = (yb * 12 + mb) - (ya * 12 + ma) - (db < da)
    if unit == "MONTH":
        return months
    if unit == "QUARTER":
        return months // 3
    if unit == "YEAR":
        return months // 12
    raise ValueError(f"timestampDiff unit {unit}")


# joda-style pattern → strftime (subset: y M d H h m s S E a)
_JODA = [("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
         ("hh", "%I"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"), ("EEE", "%a"),
         ("a", "%p"), ("M", "%m"), ("d", "%d"), ("H", "%H"), ("m", "%M"), ("s", "%S")]


def joda_to_strftime(pattern: str) -> str:
    out, i = [], 0
    p = str(pattern)
    while i < len(p):
        for j, (src, dst) in enumerate(_JODA):
            if p.startswith(src, i):
                out.append(dst)
                i += len(src)
                break
        else:
            out.append(p[i])
            i += 1
    return "".join(out)


def _ms_to_str(ms, pattern):
    fmt = joda_to_strftime(pattern)
    t = _dt.datetime(1970, 1, 1) + _dt.timedelta(milliseconds=int(ms))
    s = t.strftime(fmt)
    if "%f" in fmt:  # strftime %f is micros; joda SSS is millis
        s = s.replace(t.strftime("%f"), f"{t.microsecond // 1000:03d}")
    return s


def _str_to_ms(s, pattern):
    fmt = joda_to_strftime(pattern)
    t = _dt.datetime.strptime(str(s), fmt)
    return int((t - _dt.datetime(1970, 1, 1)).total_seconds() * 1000)


# ---------------------------------------------------------------------------
# row-wise vectorization helper (string/json functions)
# ---------------------------------------------------------------------------


def rowfn(f):
    """Wrap a scalar python function into one broadcasting over ndarray args.

    Dictionary transforms call these over cardinality-sized arrays (small);
    the host fallback over full columns accepts the python-loop cost —
    the device path never runs these per-row.
    """

    def wrapped(*args):
        arrs = [a for a in args if isinstance(a, np.ndarray) and a.ndim > 0]
        if not arrs:
            return f(*args)
        n = len(arrs[0])
        out = [f(*[(a[i] if (isinstance(a, np.ndarray) and a.ndim > 0) else a)
                   for a in args]) for i in range(n)]
        return np.asarray(out)

    return wrapped


def _sstr(v):
    return v if isinstance(v, str) else str(v)


# ---------------------------------------------------------------------------
# IR builder combinators (device lowering)
# ---------------------------------------------------------------------------


class IRBuilder:
    """Tiny DSL over engine/ir.py used by device lowerings. ``planner`` is
    engine/plan.py SegmentPlanner (value_expr + param slots)."""

    def __init__(self, planner):
        self.p = planner
        self._consts: dict = {}

    def v(self, expr) -> ir.ValueExpr:
        return self.p.value_expr(expr)

    def c(self, value) -> ir.ValueExpr:
        key = (type(value).__name__, value)
        if key not in self._consts:
            v = np.int64(value) if isinstance(value, (int, np.integer)) else np.float64(value)
            self._consts[key] = ir.ConstParam(self.p.param(v))
        return self._consts[key]

    @staticmethod
    def lit(arg):
        from ..engine.aggregation import UnsupportedQueryError

        if not arg.is_literal:
            raise UnsupportedQueryError("argument must be a literal")
        return arg.literal

    # arithmetic
    def add(self, a, b):
        return ir.Bin("add", a, b)

    def sub(self, a, b):
        return ir.Bin("sub", a, b)

    def mul(self, a, b):
        return ir.Bin("mul", a, b)

    def fdiv(self, a, b):
        return ir.Bin("fdiv", a, b)

    def mod(self, a, b):
        return ir.Bin("mod", a, b)

    def where(self, c, a, b):
        return ir.Where(c, a, b)

    def le(self, a, b):
        return ir.Bin("le", a, b)

    def lt(self, a, b):
        return ir.Bin("lt", a, b)

    def long(self, a):
        return ir.Cast(a, "LONG")

    # civil-date chains (device twin of _np_civil / _np_days_from_civil)
    def days(self, ms):
        return self.fdiv(self.long(ms), self.c(86_400_000))

    def civil(self, days):
        z = self.add(days, self.c(719_468))
        era = self.fdiv(z, self.c(146_097))
        doe = self.sub(z, self.mul(era, self.c(146_097)))
        # yoe = (doe - doe//1460 + doe//36524 - doe//146096) // 365
        yoe = self.fdiv(
            self.sub(self.add(self.sub(doe, self.fdiv(doe, self.c(1460))),
                              self.fdiv(doe, self.c(36_524))),
                     self.fdiv(doe, self.c(146_096))),
            self.c(365))
        y = self.add(yoe, self.mul(era, self.c(400)))
        # doy = doe - (365*yoe + yoe//4 - yoe//100)
        doy = self.sub(doe, self.sub(self.add(self.mul(self.c(365), yoe),
                                              self.fdiv(yoe, self.c(4))),
                                     self.fdiv(yoe, self.c(100))))
        mp = self.fdiv(self.add(self.mul(self.c(5), doy), self.c(2)), self.c(153))
        d = self.add(self.sub(doy, self.fdiv(self.add(self.mul(self.c(153), mp),
                                                      self.c(2)), self.c(5))),
                     self.c(1))
        m = self.where(self.lt(mp, self.c(10)), self.add(mp, self.c(3)),
                       self.sub(mp, self.c(9)))
        y = self.add(y, self.long(self.le(m, self.c(2))))
        return y, m, d, doy

    def days_from_civil(self, y, m, d):
        y = self.sub(y, self.long(self.le(m, self.c(2))))
        era = self.fdiv(y, self.c(400))
        yoe = self.sub(y, self.mul(era, self.c(400)))
        mp = self.where(self.lt(self.c(2), m), self.sub(m, self.c(3)),
                        self.add(m, self.c(9)))
        doy = self.add(self.fdiv(self.add(self.mul(self.c(153), mp), self.c(2)),
                                 self.c(5)),
                       self.sub(d, self.c(1)))
        doe = self.add(self.sub(self.add(self.mul(yoe, self.c(365)),
                                         self.fdiv(yoe, self.c(4))),
                                self.fdiv(yoe, self.c(100))),
                       doy)
        return self.sub(self.add(self.mul(era, self.c(146_097)), doe), self.c(719_468))


# ---------------------------------------------------------------------------
# device lowerings
# ---------------------------------------------------------------------------


def _lower_extract(part: str):
    def lower(B: IRBuilder, args):
        ms = B.long(B.v(args[0]))
        if part == "hour":
            return B.mod(B.fdiv(ms, B.c(3_600_000)), B.c(24))
        if part == "minute":
            return B.mod(B.fdiv(ms, B.c(60_000)), B.c(60))
        if part == "second":
            return B.mod(B.fdiv(ms, B.c(1000)), B.c(60))
        if part == "millisecond":
            return B.mod(ms, B.c(1000))
        days = B.days(ms)
        if part == "dayofweek":
            return B.add(B.mod(B.add(days, B.c(_DOW_OFFSET)), B.c(7)), B.c(1))
        y, m, d, _ = B.civil(days)
        if part == "year":
            return y
        if part == "month":
            return m
        if part == "quarter":
            return B.add(B.fdiv(B.sub(m, B.c(1)), B.c(3)), B.c(1))
        if part == "day":
            return d
        if part == "dayofyear":
            return B.add(B.sub(days, B.days_from_civil(y, B.c(1), B.c(1))), B.c(1))
        raise ValueError(part)

    return lower


def _lower_scale(factor: int, to_millis: bool):
    def lower(B: IRBuilder, args):
        v = B.long(B.v(args[0]))
        if to_millis:
            return B.mul(v, B.c(factor))
        return B.fdiv(v, B.c(factor))

    return lower


def _lower_epoch_rounded(factor: int, bucket_only: bool):
    def lower(B: IRBuilder, args):
        v = B.fdiv(B.long(B.v(args[0])), B.c(factor))
        n = int(IRBuilder.lit(args[1]))
        if bucket_only:
            return B.fdiv(v, B.c(n))
        return B.mul(B.fdiv(v, B.c(n)), B.c(n))

    return lower


def _lower_from_epoch_bucket(factor: int):
    def lower(B: IRBuilder, args):
        n = int(IRBuilder.lit(args[1]))
        return B.mul(B.long(B.v(args[0])), B.c(factor * n))

    return lower


def _lower_datetrunc(B: IRBuilder, args):
    unit = str(IRBuilder.lit(args[0])).upper()
    ms = B.long(B.v(args[1]))
    if len(args) > 2:
        u = str(IRBuilder.lit(args[2])).upper()
        ms = B.mul(ms, B.c(MILLIS[u]))  # normalize input to millis
    simple = {"MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
              "DAY": 86_400_000}
    if unit in simple:
        f = simple[unit]
        out = B.mul(B.fdiv(ms, B.c(f)), B.c(f))
    elif unit == "WEEK":
        days = B.days(ms)
        monday = B.sub(days, B.mod(B.add(days, B.c(_DOW_OFFSET)), B.c(7)))
        out = B.mul(monday, B.c(86_400_000))
    elif unit in ("MONTH", "QUARTER", "YEAR"):
        days = B.days(ms)
        y, m, _, _ = B.civil(days)
        if unit == "MONTH":
            first = B.days_from_civil(y, m, B.c(1))
        elif unit == "QUARTER":
            qm = B.add(B.mul(B.fdiv(B.sub(m, B.c(1)), B.c(3)), B.c(3)), B.c(1))
            first = B.days_from_civil(y, qm, B.c(1))
        else:
            first = B.days_from_civil(y, B.c(1), B.c(1))
        out = B.mul(first, B.c(86_400_000))
    else:
        raise ValueError(f"dateTrunc unit {unit}")
    if len(args) > 2:
        u = str(IRBuilder.lit(args[2])).upper()
        out = B.fdiv(out, B.c(MILLIS[u]))  # back to the caller's unit
    return out


def _lower_timeconvert(B: IRBuilder, args):
    src = MILLIS[str(IRBuilder.lit(args[1])).upper()]
    dst = MILLIS[str(IRBuilder.lit(args[2])).upper()]
    return B.fdiv(B.mul(B.long(B.v(args[0])), B.c(src)), B.c(dst))


def parse_datetime_format(spec: str):
    """'1:MILLISECONDS:EPOCH' / '1:DAYS:SIMPLE_DATE_FORMAT:yyyy-MM-dd' →
    (size, unit, kind, pattern)."""
    parts = str(spec).split(":", 3)
    size = int(parts[0])
    unit = parts[1].upper()
    kind = parts[2].upper()
    pattern = parts[3] if len(parts) > 3 else None
    return size, unit, kind, pattern


def _lower_datetimeconvert(B: IRBuilder, args):
    from ..engine.aggregation import UnsupportedQueryError

    isz, iu, ik, _ = parse_datetime_format(IRBuilder.lit(args[1]))
    osz, ou, ok, _ = parse_datetime_format(IRBuilder.lit(args[2]))
    if ik != "EPOCH" or ok != "EPOCH":
        raise UnsupportedQueryError("SIMPLE_DATE_FORMAT stays on host")
    gsz, gu = str(IRBuilder.lit(args[3])).split(":")
    ms = B.mul(B.long(B.v(args[0])), B.c(MILLIS[iu] * isz))
    gran = MILLIS[gu.upper()] * int(gsz)
    ms = B.mul(B.fdiv(ms, B.c(gran)), B.c(gran))
    return B.fdiv(ms, B.c(MILLIS[ou] * osz))


def _lower_timestampadd(B: IRBuilder, args):
    from ..engine.aggregation import UnsupportedQueryError

    unit = str(IRBuilder.lit(args[0])).upper().rstrip("S")
    simple = {"MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
              "DAY": 86_400_000, "WEEK": 604_800_000}
    if unit not in simple:
        raise UnsupportedQueryError("calendar timestampAdd stays on host")
    return B.add(B.long(B.v(args[2])),
                 B.mul(B.long(B.v(args[1])), B.c(simple[unit])))


def _lower_timestampdiff(B: IRBuilder, args):
    from ..engine.aggregation import UnsupportedQueryError

    unit = str(IRBuilder.lit(args[0])).upper().rstrip("S")
    simple = {"MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
              "DAY": 86_400_000, "WEEK": 604_800_000}
    if unit not in simple:
        raise UnsupportedQueryError("calendar timestampDiff stays on host")
    return B.fdiv(B.sub(B.long(B.v(args[2])), B.long(B.v(args[1]))), B.c(simple[unit]))


def _lower_round(B: IRBuilder, args):
    if len(args) == 1:
        return ir.Un("floor", B.add(B.v(args[0]), B.c(0.5)))
    # round(timeValue, n) = (v // n) * n  (reference DateTimeFunctions.round)
    n = int(IRBuilder.lit(args[1]))
    return B.mul(B.fdiv(B.long(B.v(args[0])), B.c(n)), B.c(n))


def _lower_rounddecimal(B: IRBuilder, args):
    scale = int(IRBuilder.lit(args[1])) if len(args) > 1 else 0
    f = B.c(float(10 ** scale))
    return ir.Bin("div", ir.Un("floor", B.add(B.mul(B.v(args[0]), f), B.c(0.5))), f)


def _lower_truncate(B: IRBuilder, args):
    scale = int(IRBuilder.lit(args[1])) if len(args) > 1 else 0
    f = B.c(float(10 ** scale))
    v = B.mul(B.v(args[0]), f)
    return ir.Bin("div", B.where(ir.Bin("ge", v, B.c(0.0)), ir.Un("floor", v),
                                 ir.Un("ceil", v)), f)


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


@dataclass
class TransformDef:
    eval_np: Callable
    lower: Optional[Callable] = None  # (IRBuilder, args) -> ir.ValueExpr
    mv_arg: bool = False  # first arg is a multi-value column (array fns)


def _np_round(x, n=None):
    if n is None:
        return np.floor(np.asarray(x, dtype=np.float64) + 0.5)
    return (np.asarray(x).astype(np.int64) // int(n)) * int(n)


def _np_rounddecimal(x, scale=0):
    f = 10.0 ** int(scale)
    return np.floor(np.asarray(x, dtype=np.float64) * f + 0.5) / f


def _np_truncate(x, scale=0):
    f = 10.0 ** int(scale)
    return np.trunc(np.asarray(x, dtype=np.float64) * f) / f


def _np_datetimeconvert(v, infmt, outfmt, gran):
    isz, iu, ik, ipat = parse_datetime_format(infmt)
    osz, ou, ok, opat = parse_datetime_format(outfmt)
    if ik == "EPOCH":
        ms = np.asarray(v).astype(np.int64) * (MILLIS[iu] * isz)
    else:
        ms = rowfn(lambda s: _str_to_ms(s, ipat))(v)
        ms = np.asarray(ms).astype(np.int64)
    gsz, gu = str(gran).split(":")
    g = MILLIS[gu.upper()] * int(gsz)
    ms = (ms // g) * g
    if ok == "EPOCH":
        return ms // (MILLIS[ou] * osz)
    return rowfn(lambda m: _ms_to_str(int(m), opat))(ms)


def _np_substr(s, start, end=None):
    def f(x, st=start, en=end):
        x = _sstr(x)
        st_i = int(st)
        if en is None or int(en) == -1:
            return x[st_i:]
        return x[st_i:int(en)]  # end exclusive (reference substr(col,start,end))

    return rowfn(f)(s)


def _np_strpos(s, sub, instance=1):
    def f(x, sb=None, inst=None):
        x = _sstr(x)
        sb = _sstr(sub if np.ndim(sub) == 0 else sb)
        k = int(instance if np.ndim(instance) == 0 else inst)
        pos = -1
        for _ in range(max(1, k)):
            pos = x.find(sb, pos + 1)
            if pos < 0:
                return -1
        return pos

    return rowfn(f)(s)


def _np_jsonextractscalar(blob, path, rtype="STRING", default=None):
    rtype = str(rtype).upper()

    def f(x):
        try:
            doc = json.loads(x) if isinstance(x, (str, bytes)) else x
            cur = doc
            p = str(path)
            if p.startswith("$"):
                p = p[1:]
            for tok in re.findall(r"\.([^.\[\]]+)|\[(\d+)\]", p):
                key, idx = tok
                cur = cur[int(idx)] if idx else cur[key]
            if cur is None:
                raise KeyError
            if rtype in ("INT", "LONG"):
                return int(cur)
            if rtype in ("FLOAT", "DOUBLE"):
                return float(cur)
            return str(cur)
        except Exception:
            if default is not None:
                return default
            return {"INT": -2147483648, "LONG": -9223372036854775808,
                    "FLOAT": math.inf, "DOUBLE": math.inf}.get(rtype, "null")

    return rowfn(f)(blob)


def _np_mapvalue(blob, key, default=None):
    """mapCol['key'] / mapValue(col, 'key'[, default]) — row-wise parse of
    the JSON/dict map column (reference: MapItemTransformFunction +
    MapFunctions.mapValue). Segments carrying a map index answer indexed
    predicates from dense planes instead (segment/map_index.py)."""
    from ..segment.map_index import _parse_map

    k = str(key)

    def f(x):
        m = _parse_map(x)
        if m is None or k not in m:
            return default
        return m[k]

    return rowfn(f)(blob)


def _np_lookup(table, attr, pk, keys):
    """LOOKUP('dimTable', 'valueColumn', 'pkColumn', factKeyExpr) — the
    dimension-table join UDF (reference: LookupTransformFunction backed by
    DimensionTableDataManager). On the device path this never runs per
    row: the planner evaluates it over the fact column's DICTIONARY grid,
    so the join becomes a cardinality-sized LUT gather fused into the
    kernel (the TPU-first broadcast join)."""
    from ..engine.dim_tables import get_dimension_table

    t = get_dimension_table(str(table))
    if t is None:
        raise ValueError(f"dimension table {table!r} not registered")
    if str(pk) != t.pk_column:
        raise ValueError(
            f"dim table {table!r} joins on {t.pk_column!r}, not {pk!r}")
    vals, _found = t.lookup(str(attr), np.asarray(keys))
    return vals


def _np_jsonextractkey(blob, path):
    def f(x):
        try:
            doc = json.loads(x) if isinstance(x, (str, bytes)) else x
            return json.dumps(sorted(doc.keys()))
        except Exception:
            return "[]"

    return rowfn(f)(blob)


_H = {"md5": hashlib.md5, "sha": hashlib.sha1, "sha256": hashlib.sha256,
      "sha512": hashlib.sha512}


def _hashfn(name):
    def f(x):
        b = x if isinstance(x, bytes) else _sstr(x).encode()
        return _H[name](b).hexdigest()

    return rowfn(f)


def _np_stdistance(lat1, lng1, lat2, lng2):
    from ..segment.indexes import haversine_m

    return haversine_m(lat1, lng1, lat2, lng2)


def _np_arraylength(v):
    return rowfn(lambda x: len(x) if isinstance(x, (list, tuple, np.ndarray)) else 1)(v)


def _np_cosinedistance(a, b):
    def f(x):
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(b, dtype=np.float64)
        denom = np.linalg.norm(x) * np.linalg.norm(y)
        return 1.0 - float(x @ y) / denom if denom else 1.0

    return rowfn(f)(a)


TRANSFORMS: dict[str, TransformDef] = {
    # -- geo (reference: pinot-core/.../geospatial/transform/; the point
    # type is a (lat, lng) column pair here, not WKB bytes) ----------------
    "stdistance": TransformDef(_np_stdistance),
    "distance": TransformDef(_np_stdistance),
    # -- vector scalar fns (reference VectorFunctions) ----------------------
    "cosinedistance": TransformDef(_np_cosinedistance),
    "arraylength": TransformDef(_np_arraylength),
    "vectordims": TransformDef(_np_arraylength),
    # -- math ---------------------------------------------------------------
    "round": TransformDef(_np_round, _lower_round),
    "rounddecimal": TransformDef(_np_rounddecimal, _lower_rounddecimal),
    "truncate": TransformDef(_np_truncate, _lower_truncate),
    "cbrt": TransformDef(lambda x: np.cbrt(np.asarray(x, dtype=np.float64)),
                         lambda B, a: ir.Bin("pow", B.v(a[0]), B.c(1.0 / 3.0))),
    "sin": TransformDef(np.sin), "cos": TransformDef(np.cos), "tan": TransformDef(np.tan),
    "asin": TransformDef(np.arcsin), "acos": TransformDef(np.arccos),
    "atan": TransformDef(np.arctan),
    "atan2": TransformDef(np.arctan2),
    "sinh": TransformDef(np.sinh), "cosh": TransformDef(np.cosh),
    "tanh": TransformDef(np.tanh),
    "degrees": TransformDef(np.degrees), "radians": TransformDef(np.radians),
    "log": TransformDef(np.log),
    # -- datetime extraction (device = civil-date int arithmetic) -----------
    "year": TransformDef(_np_year, _lower_extract("year")),
    "month": TransformDef(_np_month, _lower_extract("month")),
    "monthofyear": TransformDef(_np_month, _lower_extract("month")),
    "quarter": TransformDef(_np_quarter, _lower_extract("quarter")),
    "day": TransformDef(_np_day, _lower_extract("day")),
    "dayofmonth": TransformDef(_np_day, _lower_extract("day")),
    "dayofweek": TransformDef(_np_dayofweek, _lower_extract("dayofweek")),
    "dow": TransformDef(_np_dayofweek, _lower_extract("dayofweek")),
    "dayofyear": TransformDef(_np_dayofyear, _lower_extract("dayofyear")),
    "doy": TransformDef(_np_dayofyear, _lower_extract("dayofyear")),
    "hour": TransformDef(lambda ms: (np.asarray(ms).astype(np.int64) // 3_600_000) % 24,
                         _lower_extract("hour")),
    "minute": TransformDef(lambda ms: (np.asarray(ms).astype(np.int64) // 60_000) % 60,
                           _lower_extract("minute")),
    "second": TransformDef(lambda ms: (np.asarray(ms).astype(np.int64) // 1000) % 60,
                           _lower_extract("second")),
    "millisecond": TransformDef(lambda ms: np.asarray(ms).astype(np.int64) % 1000,
                                _lower_extract("millisecond")),
    "week": TransformDef(_np_week),
    "weekofyear": TransformDef(_np_week),
    "yearofweek": TransformDef(_np_yearofweek),
    "yow": TransformDef(_np_yearofweek),
    # -- epoch conversions --------------------------------------------------
    "toepochseconds": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) // 1000, _lower_scale(1000, False)),
    "toepochminutes": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) // 60_000, _lower_scale(60_000, False)),
    "toepochhours": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) // 3_600_000, _lower_scale(3_600_000, False)),
    "toepochdays": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) // 86_400_000, _lower_scale(86_400_000, False)),
    "fromepochseconds": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) * 1000, _lower_scale(1000, True)),
    "fromepochminutes": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) * 60_000, _lower_scale(60_000, True)),
    "fromepochhours": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) * 3_600_000, _lower_scale(3_600_000, True)),
    "fromepochdays": TransformDef(
        lambda v: np.asarray(v).astype(np.int64) * 86_400_000, _lower_scale(86_400_000, True)),
    "toepochsecondsrounded": TransformDef(
        lambda v, n: (np.asarray(v).astype(np.int64) // 1000 // int(n)) * int(n),
        _lower_epoch_rounded(1000, False)),
    "toepochminutesrounded": TransformDef(
        lambda v, n: (np.asarray(v).astype(np.int64) // 60_000 // int(n)) * int(n),
        _lower_epoch_rounded(60_000, False)),
    "toepochhoursrounded": TransformDef(
        lambda v, n: (np.asarray(v).astype(np.int64) // 3_600_000 // int(n)) * int(n),
        _lower_epoch_rounded(3_600_000, False)),
    "toepochdaysrounded": TransformDef(
        lambda v, n: (np.asarray(v).astype(np.int64) // 86_400_000 // int(n)) * int(n),
        _lower_epoch_rounded(86_400_000, False)),
    "toepochsecondsbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) // 1000 // int(n),
        _lower_epoch_rounded(1000, True)),
    "toepochminutesbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) // 60_000 // int(n),
        _lower_epoch_rounded(60_000, True)),
    "toepochhoursbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) // 3_600_000 // int(n),
        _lower_epoch_rounded(3_600_000, True)),
    "toepochdaysbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) // 86_400_000 // int(n),
        _lower_epoch_rounded(86_400_000, True)),
    "fromepochsecondsbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) * 1000 * int(n),
        _lower_from_epoch_bucket(1000)),
    "fromepochminutesbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) * 60_000 * int(n),
        _lower_from_epoch_bucket(60_000)),
    "fromepochhoursbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) * 3_600_000 * int(n),
        _lower_from_epoch_bucket(3_600_000)),
    "fromepochdaysbucket": TransformDef(
        lambda v, n: np.asarray(v).astype(np.int64) * 86_400_000 * int(n),
        _lower_from_epoch_bucket(86_400_000)),
    "datetrunc": TransformDef(
        lambda unit, v, *rest: (
            _np_datetrunc(unit, np.asarray(v).astype(np.int64)
                          * MILLIS[str(rest[0]).upper()])
            // MILLIS[str(rest[0]).upper()]
        ) if rest else _np_datetrunc(unit, v),
        _lower_datetrunc),
    "timeconvert": TransformDef(
        lambda v, a, b: np.asarray(v).astype(np.int64) * MILLIS[str(a).upper()]
        // MILLIS[str(b).upper()],
        _lower_timeconvert),
    "datetimeconvert": TransformDef(_np_datetimeconvert, _lower_datetimeconvert),
    # gapfill markers (engine/gapfill.py): identity of arg0 during
    # execution; the broker reducer reads the remaining literal args
    "gapfill": TransformDef(lambda x, *rest: x, lambda B, a: B.v(a[0])),
    "fill": TransformDef(lambda x, *rest: x, lambda B, a: B.v(a[0])),
    "timestampadd": TransformDef(_np_timestampadd, _lower_timestampadd),
    "dateadd": TransformDef(_np_timestampadd, _lower_timestampadd),
    "timestampdiff": TransformDef(_np_timestampdiff, _lower_timestampdiff),
    "datediff": TransformDef(_np_timestampdiff, _lower_timestampdiff),
    "todatetime": TransformDef(rowfn(lambda ms, p: _ms_to_str(int(ms), p))),
    "fromdatetime": TransformDef(rowfn(lambda s, p: _str_to_ms(s, p))),
    # -- string -------------------------------------------------------------
    "upper": TransformDef(rowfn(lambda s: _sstr(s).upper())),
    "lower": TransformDef(rowfn(lambda s: _sstr(s).lower())),
    "reverse": TransformDef(rowfn(lambda s: _sstr(s)[::-1])),
    "substr": TransformDef(_np_substr),
    "substring": TransformDef(_np_substr),
    "concat": TransformDef(rowfn(
        lambda a, b, sep="": f"{_sstr(a)}{_sstr(sep)}{_sstr(b)}")),
    "trim": TransformDef(rowfn(lambda s: _sstr(s).strip())),
    "ltrim": TransformDef(rowfn(lambda s: _sstr(s).lstrip())),
    "rtrim": TransformDef(rowfn(lambda s: _sstr(s).rstrip())),
    "length": TransformDef(rowfn(lambda s: len(_sstr(s)))),
    "strpos": TransformDef(_np_strpos),
    "startswith": TransformDef(rowfn(lambda s, p: _sstr(s).startswith(_sstr(p)))),
    "endswith": TransformDef(rowfn(lambda s, p: _sstr(s).endswith(_sstr(p)))),
    "contains": TransformDef(rowfn(lambda s, p: _sstr(p) in _sstr(s))),
    "replace": TransformDef(rowfn(lambda s, a, b: _sstr(s).replace(_sstr(a), _sstr(b)))),
    "lpad": TransformDef(rowfn(lambda s, n, p: _sstr(s).rjust(int(n), _sstr(p)))),
    "rpad": TransformDef(rowfn(lambda s, n, p: _sstr(s).ljust(int(n), _sstr(p)))),
    "codepoint": TransformDef(rowfn(lambda s: ord(_sstr(s)[0]) if _sstr(s) else 0)),
    "chr": TransformDef(rowfn(lambda c: chr(int(c)))),
    "ascii": TransformDef(rowfn(lambda s: ord(_sstr(s)[0]) if _sstr(s) else 0)),
    "repeat": TransformDef(rowfn(
        lambda s, n, sep="": _sstr(sep).join([_sstr(s)] * int(n)))),
    "remove": TransformDef(rowfn(lambda s, r: _sstr(s).replace(_sstr(r), ""))),
    "splitpart": TransformDef(rowfn(
        lambda s, sep, i: (_sstr(s).split(_sstr(sep)) + ["null"])[int(i)]
        if int(i) < len(_sstr(s).split(_sstr(sep))) else "null")),
    "regexpextract": TransformDef(rowfn(
        lambda s, pat, group=0, default="": (
            (lambda m: m.group(int(group)) if m else _sstr(default))
            (re.search(str(pat), _sstr(s)))))),
    "regexpreplace": TransformDef(rowfn(
        lambda s, pat, rep: re.sub(str(pat), _sstr(rep), _sstr(s)))),
    "urlencode": TransformDef(rowfn(lambda s: urllib.parse.quote_plus(_sstr(s)))),
    "urldecode": TransformDef(rowfn(lambda s: urllib.parse.unquote_plus(_sstr(s)))),
    "tobase64": TransformDef(rowfn(
        lambda s: base64.b64encode(s if isinstance(s, bytes) else _sstr(s).encode()).decode())),
    "frombase64": TransformDef(rowfn(lambda s: base64.b64decode(_sstr(s)).decode()))
    ,
    "toutf8": TransformDef(rowfn(lambda s: _sstr(s).encode().hex())),
    "isjson": TransformDef(rowfn(
        lambda s: (lambda: (json.loads(s), True)[1])() if _try_json(s) else False)),
    "strcmp": TransformDef(rowfn(
        lambda a, b: (_sstr(a) > _sstr(b)) - (_sstr(a) < _sstr(b)))),
    "md5": TransformDef(_hashfn("md5")),
    "sha": TransformDef(_hashfn("sha")),
    "sha256": TransformDef(_hashfn("sha256")),
    "sha512": TransformDef(_hashfn("sha512")),
    "crc32": TransformDef(rowfn(
        lambda s: zlib.crc32(s if isinstance(s, bytes) else _sstr(s).encode()))),
    # -- lookup join --------------------------------------------------------
    "lookup": TransformDef(_np_lookup),
    # -- map ----------------------------------------------------------------
    "mapvalue": TransformDef(_np_mapvalue),
    "map_value": TransformDef(_np_mapvalue),
    "item": TransformDef(_np_mapvalue),
    # -- json ---------------------------------------------------------------
    "jsonextractscalar": TransformDef(_np_jsonextractscalar),
    "jsonextractkey": TransformDef(_np_jsonextractkey),
    "jsonformat": TransformDef(rowfn(
        lambda x: json.dumps(x) if not isinstance(x, str) else json.dumps(json.loads(x)))),
    "json_format": TransformDef(rowfn(
        lambda x: json.dumps(x) if not isinstance(x, str) else json.dumps(json.loads(x)))),
    # -- array (MV) ---------------------------------------------------------
    "arraylength": TransformDef(rowfn(lambda a: len(a)), mv_arg=True),
    "cardinality": TransformDef(rowfn(lambda a: len(a)), mv_arg=True),
    "arraymin": TransformDef(rowfn(lambda a: min(a) if len(a) else math.inf), mv_arg=True),
    "arraymax": TransformDef(rowfn(lambda a: max(a) if len(a) else -math.inf), mv_arg=True),
    "arraysum": TransformDef(rowfn(lambda a: sum(a)), mv_arg=True),
    "arrayaverage": TransformDef(rowfn(
        lambda a: sum(a) / len(a) if len(a) else math.nan), mv_arg=True),
    "arraydistinctcount": TransformDef(rowfn(lambda a: len(set(a))), mv_arg=True),
}


def _try_json(s):
    try:
        json.loads(s)
        return True
    except Exception:
        return False


def get_transform(name: str) -> Optional[TransformDef]:
    return TRANSFORMS.get(name)


# ---------------------------------------------------------------------------
# generic numpy expression evaluator (shared by dict-transform + host engine)
# ---------------------------------------------------------------------------

NP_BIN = {
    "plus": np.add, "minus": np.subtract, "times": np.multiply,
    "divide": lambda a, b: np.true_divide(a, b, where=np.asarray(b) != 0,
                                          out=np.full(np.broadcast(a, b).shape, np.nan)),
    "mod": np.mod, "pow": np.power, "power": np.power,
    "equals": lambda a, b: a == b, "notequals": lambda a, b: a != b,
    "lessthan": lambda a, b: a < b, "lessthanorequal": lambda a, b: a <= b,
    "greaterthan": lambda a, b: a > b, "greaterthanorequal": lambda a, b: a >= b,
    "and": np.logical_and, "or": np.logical_or,
    "least": np.minimum, "greatest": np.maximum,
}

NP_UN = {
    "neg": np.negative, "abs": np.abs, "not": np.logical_not, "exp": np.exp,
    "ln": np.log, "log10": np.log10, "log2": np.log2, "sqrt": np.sqrt,
    "ceiling": np.ceil, "ceil": np.ceil, "floor": np.floor, "sign": np.sign,
}


def np_cast(v, to: str):
    to = to.upper()
    v = np.asarray(v)
    if to == "INT":
        return v.astype(np.float64).astype(np.int32) if v.dtype.kind == "f" else v.astype(np.int32)
    if to in ("LONG", "TIMESTAMP"):
        return v.astype(np.float64).astype(np.int64) if v.dtype.kind == "f" else v.astype(np.int64)
    if to == "FLOAT":
        return v.astype(np.float32)
    if to == "DOUBLE":
        return v.astype(np.float64)
    if to == "BOOLEAN":
        return v.astype(bool)
    if to == "STRING":
        return rowfn(lambda x: _fmt_str(x))(v)
    return v


def _fmt_str(x):
    if isinstance(x, (float, np.floating)):
        return repr(float(x))
    if isinstance(x, (bool, np.bool_)):
        return "true" if x else "false"
    if isinstance(x, np.generic):
        return str(x.item())
    return str(x)


def eval_expr_np(e, resolve: Callable[[str], object]):
    """Evaluate an ExpressionContext with numpy semantics. ``resolve(name)``
    returns the values for an identifier (ndarray or scalar). Literals stay
    python scalars so string functions receive clean arguments."""
    from ..engine.aggregation import UnsupportedQueryError

    if e.is_literal:
        v = e.literal
        return int(v) if isinstance(v, bool) else v
    if e.is_identifier:
        return resolve(e.identifier)
    fn = e.function
    name, args = fn.name, fn.arguments
    if name in NP_BIN:
        return NP_BIN[name](eval_expr_np(args[0], resolve), eval_expr_np(args[1], resolve))
    if name in NP_UN:
        return NP_UN[name](eval_expr_np(args[0], resolve))
    if name == "cast":
        return np_cast(eval_expr_np(args[0], resolve), str(args[1].literal))
    if name == "case":
        out = eval_expr_np(args[-1], resolve)
        for i in range(len(args) - 3, -1, -2):
            cond = np.asarray(eval_expr_np(args[i], resolve)).astype(bool)
            out = np.where(cond, eval_expr_np(args[i + 1], resolve), out)
        return out
    if name == "coalesce":
        # per-doc nullness is not representable in dictionary-value space;
        # callers with null planes (plan.value_expr / host eval_value) handle
        # coalesce themselves — refuse here so they fall back correctly
        raise UnsupportedQueryError("coalesce needs null planes")
    td = get_transform(name)
    if td is not None:
        return td.eval_np(*[eval_expr_np(a, resolve) for a in args])
    raise UnsupportedQueryError(f"transform function {name}")


def eval_scalar(name: str, args: list):
    """Scalar form for post-aggregation/HAVING (engine/reduce.py)."""
    from ..engine.aggregation import UnsupportedQueryError

    td = get_transform(name)
    if td is None:
        raise UnsupportedQueryError(f"post-aggregation function {name}")
    out = td.eval_np(*args)
    if isinstance(out, np.generic):
        return out.item()
    return out
