from .manager import RealtimeTableDataManager  # noqa: F401
