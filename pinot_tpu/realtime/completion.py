"""Controller-side segment completion FSM with committer election.

Reference: SegmentCompletionManager (pinot-controller/.../helix/core/
realtime/SegmentCompletionManager.java:53) + SegmentCompletionFSM: replica
consumers that reach their end criteria call ``segmentConsumed``; the
controller collects offsets, elects ONE committer (the replica at the
largest reported offset — others are told to CATCHUP to it), and walks the
segment through HOLDING → COMMITTER_DECIDED → COMMITTER_UPLOADING →
COMMITTED. The committer builds + uploads and calls ``segmentCommitEnd``;
the controller then writes the segment metadata (the DONE record) as one
store transaction. Losing replicas are told to DISCARD and download the
committed build instead of their own.

Failure handling mirrors the reference's lease model: the elected committer
holds a commit lease; if it dies between election and ``segmentCommitEnd``,
the lease expires and the next replica to poll ``segmentConsumed`` is
re-elected (reference: COMMITTER_NOTIFIED timeout → pick a new committer).

The FSM state lives in this manager; the *commit record* lives in the
property store under ``/SEGMENTS/{table}/{segment}`` so every replica (and
the broker/controller) observes the same committed metadata — the ZK write
in the reference.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

# FSM states (reference SegmentCompletionFSM.State)
HOLDING = "HOLDING"
COMMITTER_DECIDED = "COMMITTER_DECIDED"
COMMITTER_UPLOADING = "COMMITTER_UPLOADING"
COMMITTED = "COMMITTED"

# protocol responses (reference SegmentCompletionProtocol.ControllerResponseStatus)
HOLD = "HOLD"
CATCHUP = "CATCHUP"
COMMIT = "COMMIT"
DISCARD = "DISCARD"
CONTINUE = "COMMIT_CONTINUE"
COMMIT_SUCCESS = "COMMIT_SUCCESS"
FAILED = "FAILED"


@dataclass
class CompletionResponse:
    status: str
    offset: Optional[int] = None  # target end offset for CATCHUP/COMMIT
    location: Optional[str] = None  # committed build for DISCARD downloads


@dataclass
class _Fsm:
    state: str = HOLDING
    votes: dict = field(default_factory=dict)  # instance → max reported offset
    committer: Optional[str] = None
    target_offset: Optional[int] = None
    lease_deadline: float = 0.0
    first_vote_at: float = 0.0


class SegmentCompletionManager:
    """One controller-side manager for all in-flight consuming segments."""

    def __init__(self, store, num_replicas: int = 1,
                 commit_lease_s: float = 10.0, decision_wait_s: float = 2.0):
        self.store = store
        self.num_replicas = num_replicas
        self.commit_lease_s = commit_lease_s
        self.decision_wait_s = decision_wait_s
        self._fsms: dict[tuple[str, str], _Fsm] = {}
        self._lock = threading.Lock()

    # -- server → controller protocol --------------------------------------
    def segment_consumed(self, table: str, segment: str, instance: str,
                         offset: int) -> CompletionResponse:
        """A replica reached its end criteria at ``offset``. Returns HOLD,
        CATCHUP(target), COMMIT(target), or DISCARD(location)."""
        with self._lock:
            fsm = self._fsms.get((table, segment))
            if fsm is None:
                # completed FSMs are pruned from memory (commit_end); the
                # store record is the durable answer for late replicas
                rec = self.store.get(f"/SEGMENTS/{table}/{segment}")
                if rec is not None and rec.get("status") == "DONE":
                    return CompletionResponse(
                        DISCARD, offset=int(rec["endOffset"]),
                        location=rec.get("location"))
                fsm = self._fsms.setdefault((table, segment), _Fsm())
            now = time.monotonic()
            if not fsm.votes:
                fsm.first_vote_at = now
            fsm.votes[instance] = max(offset, fsm.votes.get(instance, offset))

            if fsm.state == HOLDING:
                quorum = len(fsm.votes) >= self.num_replicas
                waited = now - fsm.first_vote_at >= self.decision_wait_s
                if not (quorum or waited):
                    return CompletionResponse(HOLD)
                # elect: the replica at the largest offset commits; ties
                # break on report order (dict preserves insertion)
                fsm.target_offset = max(fsm.votes.values())
                fsm.committer = next(i for i, o in fsm.votes.items()
                                     if o == fsm.target_offset)
                fsm.state = COMMITTER_DECIDED
                fsm.lease_deadline = now + self.commit_lease_s

            # COMMITTER_DECIDED / COMMITTER_UPLOADING
            if now > fsm.lease_deadline:
                # committer died mid-commit: re-elect the polling replica
                # (reference: FSM timeout → new committer). The target moves
                # up if the new committer consumed past the old target (a
                # late voter that was HOLDing above it) — committing its
                # superset is correct, spinning on an unreachable target is
                # not.
                fsm.committer = instance
                fsm.target_offset = max(fsm.target_offset, offset)
                fsm.state = COMMITTER_DECIDED
                fsm.lease_deadline = now + self.commit_lease_s
            if instance == fsm.committer:
                if offset < fsm.target_offset:
                    return CompletionResponse(CATCHUP, offset=fsm.target_offset)
                return CompletionResponse(COMMIT, offset=fsm.target_offset)
            if offset < fsm.target_offset:
                return CompletionResponse(CATCHUP, offset=fsm.target_offset)
            return CompletionResponse(HOLD)

    def segment_commit_start(self, table: str, segment: str, instance: str,
                             offset: int) -> CompletionResponse:
        """Committer announces the build is starting (renews the lease)."""
        with self._lock:
            fsm = self._fsms.get((table, segment))
            if fsm is None or fsm.state == COMMITTED:
                return CompletionResponse(FAILED)
            if instance != fsm.committer or offset != fsm.target_offset:
                return CompletionResponse(FAILED)
            fsm.state = COMMITTER_UPLOADING
            fsm.lease_deadline = time.monotonic() + self.commit_lease_s
            return CompletionResponse(CONTINUE, offset=fsm.target_offset)

    def extend_build_time(self, table: str, segment: str, instance: str,
                          extra_s: float) -> bool:
        """Reference: extendBuildTime — a slow build renews its lease."""
        with self._lock:
            fsm = self._fsms.get((table, segment))
            if fsm is None or fsm.committer != instance:
                return False
            fsm.lease_deadline = time.monotonic() + extra_s
            return True

    def segment_commit_end(self, table: str, segment: str, instance: str,
                           offset: int, location: str,
                           metadata: Optional[dict] = None) -> CompletionResponse:
        """Committer uploaded the build; write the DONE record. Exactly one
        caller can succeed — a re-elected committer racing the 'dead' one is
        resolved here by the committer check under the lock."""
        with self._lock:
            fsm = self._fsms.get((table, segment))
            if fsm is None or fsm.state == COMMITTED:
                # idempotent across controller failover: if the previous
                # leader durably wrote THIS committer's DONE record but died
                # before acking, the retried commit_end on the new leader
                # (which has no FSM) must succeed, not fail — the outcome is
                # decided by the store record, not by in-memory state
                rec = self.store.get(f"/SEGMENTS/{table}/{segment}")
                if (rec is not None and rec.get("status") == "DONE"
                        and rec.get("committer") == instance
                        and int(rec.get("endOffset", -1)) == offset):
                    return CompletionResponse(COMMIT_SUCCESS, offset=offset,
                                              location=rec.get("location"))
                return CompletionResponse(FAILED)
            if instance != fsm.committer or offset != fsm.target_offset:
                return CompletionResponse(FAILED)
            record = dict(metadata or {}, segmentName=segment,
                          location=location, endOffset=str(offset),
                          status="DONE", committer=instance,
                          commitTimeMs=int(time.time() * 1000))
            self.store.set(f"/SEGMENTS/{table}/{segment}", record)
            # a realtime commit changes the table's served content: bump
            # the lineage epoch so broker result-cache entries keyed on the
            # old epoch become unreachable (cache/results.py)
            from ..cache.results import bump_lineage_epoch

            bump_lineage_epoch(self.store, table)
            # prune: the store DONE record (checked first in
            # segment_consumed/fsm_state) answers late polls; keeping every
            # finished FSM would leak for the life of the controller
            self._fsms.pop((table, segment), None)
            return CompletionResponse(COMMIT_SUCCESS, offset=fsm.target_offset,
                                      location=location)

    # -- introspection ------------------------------------------------------
    def fsm_state(self, table: str, segment: str) -> Optional[str]:
        with self._lock:
            fsm = self._fsms.get((table, segment))
            if fsm is not None:
                return fsm.state
        rec = self.store.get(f"/SEGMENTS/{table}/{segment}")
        if rec is not None and rec.get("status") == "DONE":
            return COMMITTED
        return None

    def committed_record(self, table: str, segment: str) -> Optional[dict]:
        return self.store.get(f"/SEGMENTS/{table}/{segment}")


class NoControllerLeaderError(Exception):
    """No controller currently holds the leader seat (or the leader is not
    resolvable to a live controller). Completion clients retry with capped
    backoff — consumers HOLD through a controller outage, never ERROR."""


class LeaderCompletionClient:
    """Server-side completion stub that routes every protocol call to
    whichever controller currently leads.

    Reference: ServerSegmentCompletionProtocolHandler resolves the lead
    controller per request (LeadControllerManager on the server side) and
    raises/retries when no leader is up. ``resolver`` maps a leader
    instance id to its live ``ClusterController`` (None when that
    controller is dead — e.g. killed before its ephemeral leader entry
    expired), standing in for the HTTP hop to the leader's REST port."""

    def __init__(self, store, resolver):
        self.store = store
        self.resolver = resolver

    def _manager(self):
        from ..cluster.leader import LEADER_PATH

        cur = self.store.get(LEADER_PATH)
        if not isinstance(cur, dict) or not cur.get("instance"):
            raise NoControllerLeaderError("no controller leader claimed")
        inst = cur["instance"]
        controller = self.resolver(inst)
        if controller is None:
            raise NoControllerLeaderError(f"leader {inst} not reachable")
        mgr = controller.completion_manager()
        if mgr is None:
            raise NoControllerLeaderError(f"{inst} lost leadership")
        return mgr

    def segment_consumed(self, *args, **kw) -> CompletionResponse:
        return self._manager().segment_consumed(*args, **kw)

    def segment_commit_start(self, *args, **kw) -> CompletionResponse:
        return self._manager().segment_commit_start(*args, **kw)

    def extend_build_time(self, *args, **kw) -> bool:
        return self._manager().extend_build_time(*args, **kw)

    def segment_commit_end(self, *args, **kw) -> CompletionResponse:
        return self._manager().segment_commit_end(*args, **kw)
