"""Mutable → immutable segment conversion on commit.

Reference: RealtimeSegmentConverter (pinot-segment-local/.../realtime/
converter/) — snapshot the consuming segment's rows, sort on the configured
sorted column, and run the standard two-pass immutable build
(SegmentBuilder), after which the segment is device-executable (sorted
dictionaries, fixed-bit planes, persisted indexes).
"""

from __future__ import annotations

from pathlib import Path


from ..segment.builder import SegmentBuilder
from ..segment.mutable import MutableSegment


class RealtimeSegmentConverter:
    def __init__(self, schema, table_config=None, preserve_doc_order=False):
        self.schema = schema
        self.table_config = table_config
        # upsert/dedup tables keep ingestion doc order so validity planes
        # and record locations transfer 1:1 (reference: upsert tables
        # cannot use a sorted column either)
        self.preserve_doc_order = preserve_doc_order

    def convert(self, segment: MutableSegment, out_dir: str | Path) -> Path:
        columns = segment.to_columns()
        sort_col = None
        if self.table_config is not None and not self.preserve_doc_order:
            sort_col = self.table_config.indexing.sorted_column
        if sort_col and sort_col in columns and segment.num_docs > 0:
            keys = columns[sort_col]
            order = sorted(range(len(keys)),
                           key=lambda i: (keys[i] is None, keys[i]))
            columns = {c: [v[i] for i in order] for c, v in columns.items()}
        builder = SegmentBuilder(self.schema, segment_name=segment.segment_name,
                                 table_config=self.table_config)
        return builder.build(columns, out_dir)
