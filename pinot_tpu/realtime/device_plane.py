"""Realtime device planes: consuming segments join the device fast path.

A consuming (mutable) segment is append-only below every published row
count: once ``MutableSegment.index()`` publishes ``num_docs = n``, rows
``< n`` — forward ids, raw values, null docs, dictionary entries they
reference — never change again. This module exploits that invariant to
keep per-column *device-resident planes* for each consuming segment:

- Planes live in pow2 row buckets (``pad_bucket``, the engine's shared
  kernel shape bucket). Capacity grows device-side (no host re-upload)
  when a snapshot outgrows its bucket.
- On query, only the rows appended since the last uploaded watermark are
  shipped host→device (``jax.lax.dynamic_update_slice`` with a *runtime*
  start index, so the write executable is cached per shape bucket — no
  per-offset recompiles). Delta bytes are metered
  (``realtimeDeltaUploadBytes``) and are proportional to new rows, never
  to snapshot size; an unchanged generation uploads zero bytes.
- Kernels slice the plane to the snapshot's pad bucket and mask rows
  ``>= num_docs`` (the engine-wide pad-row invariant), so device results
  are bit-identical to the host path over the same pinned snapshot.
- Upsert tables ride the same planes: the snapshot view pins the
  validity mask together with its upsert generation
  (``ValidDocIds.snapshot``), and the mask ships as a kernel param plane
  exactly like the immutable upsert path — host and device AND the same
  bits.

The ``RealtimeSegmentPlanner`` lowers plans against a pinned
``MutableSegmentView``: the insertion-ordered mutable dictionary breaks
the sorted-id-interval RANGE lowering, so ranges lower to value-space
boolean LUTs instead; MV and rebased-float planes stay host-side.

Fault point ``realtime.upload`` covers the delta upload: an error fault
fails ONLY this query over to the host (planes and watermark keep their
pre-fault state); a corrupt fault poisons the whole plane set so the next
query re-uploads from scratch — degraded, never wrong; a delay fault that
overruns ``PINOT_TPU_RT_UPLOAD_BUDGET_MS`` falls back to host inside the
query deadline without advancing the watermark.

Layout reference: Ragged Paged Attention's append-only paged device
buffers (pages grow without recompiling; readers bound by a row
watermark) — the same shape a consuming segment needs.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..engine import ir
from ..engine.aggregation import UnsupportedQueryError
from ..engine.plan import SegmentPlanner, _coerce_like
from ..query.filter import PredicateType
from ..segment.device_cache import _note_upload, pad_bucket
from ..spi import faults
from ..spi.data_types import DataType
from ..spi.metrics import SERVER_METRICS, ServerMeter

# smallest delta chunk shipped in one update-slice write: pow2 chunking
# bounds the distinct update shapes (and thus cached write executables)
# to log2(bucket) variants while keeping upload bytes ∝ new rows
_MIN_CHUNK = 256


class RealtimeUploadError(Exception):
    """A delta upload failed (injected fault / budget overrun). The query
    executor answers this query on the HOST path; device planes keep a
    consistent pre-fault state (or were dropped wholesale on corruption),
    so no wrong bytes can survive to a later query."""


def realtime_device_enabled(query=None) -> bool:
    """Master knob: env PINOT_TPU_REALTIME_DEVICE (default on) with a
    per-query ``SET realtimeDevicePlanes = true|false`` override."""
    on = os.environ.get("PINOT_TPU_REALTIME_DEVICE", "1").strip().lower() \
        not in ("0", "false", "off")
    if query is not None:
        for k, v in getattr(query, "query_options", {}).items():
            if str(k).lower() == "realtimedeviceplanes":
                return str(v).strip().lower() not in ("0", "false", "off")
    return on


def _upload_budget_ms() -> float:
    try:
        return float(os.environ.get("PINOT_TPU_RT_UPLOAD_BUDGET_MS", "100"))
    except ValueError:
        return 100.0


# -- per-query upload attribution (tests / bench payloads) --------------------

_TLS = threading.local()


def reset_realtime_stats() -> None:
    """Arm per-thread delta-upload counters (test/bench attribution)."""
    _TLS.stats = {"deltaBytes": 0, "uploads": 0, "deviceQueries": 0}


def realtime_stats() -> Optional[dict]:
    return getattr(_TLS, "stats", None)


def _note_delta(nbytes: int) -> None:
    st = getattr(_TLS, "stats", None)
    if st is not None:
        st["deltaBytes"] += nbytes
        st["uploads"] += 1


def note_realtime_device_query() -> None:
    """One query answered over a consuming segment on the device path."""
    SERVER_METRICS.add_meter(ServerMeter.REALTIME_DEVICE_QUERIES, 1)
    st = getattr(_TLS, "stats", None)
    if st is not None:
        st["deviceQueries"] += 1


# -- device plane store -------------------------------------------------------


class _Plane:
    """One device array + its uploaded-row watermark. For dictionary
    planes ``rows`` counts uploaded dictionary entries instead."""

    __slots__ = ("arr", "rows")

    def __init__(self):
        self.arr = None
        self.rows = 0


def _chunk_len(delta: int, room: int) -> int:
    """Pow2 write-chunk ≥ delta, clipped to the rows remaining before the
    plane's capacity. Zeros beyond the delta land strictly above the new
    watermark (still-unuploaded territory), so they can never clobber
    uploaded data."""
    c = _MIN_CHUNK
    while c < delta:
        c <<= 1
    return min(c, room)


class RealtimePlaneSet:
    """Append-only device planes for ONE consuming segment, shared by
    every query/snapshot over it. Holds the segment's NAME only — the
    registry's weak key owns the lifetime; a strong segment ref here
    would leak the entry forever."""

    def __init__(self, name: str, registry: "RealtimePlaneRegistry"):
        self.name = name
        self.registry = registry
        self._planes: dict[tuple[str, str], _Plane] = {}
        self._lock = threading.Lock()
        self._gen_rows = 0  # highest row watermark any plane reached

    # -- fault seam ---------------------------------------------------------
    def _fire_fault(self, column: str, kind: str, nbytes: int) -> None:
        """Called with self._lock held, BEFORE the delta touches device
        state — error faults leave planes and watermarks exactly as they
        were."""
        if not faults.ACTIVE:
            return
        t0 = time.perf_counter()
        try:
            faults.FAULTS.fire("realtime.upload", segment=self.name,
                               column=column, plane=kind, nbytes=nbytes)
        except faults.InjectedCorruption as c:
            # a damaged delta on device could silently poison every later
            # query — drop the WHOLE set; next query re-uploads from zero
            self._planes.clear()
            raise RealtimeUploadError(
                f"injected corruption uploading {self.name}.{column}: "
                f"plane set dropped, full re-upload next query") from c
        except RealtimeUploadError:
            raise
        except faults.InjectedFault as e:
            raise RealtimeUploadError(
                f"injected fault uploading {self.name}.{column}") from e
        # delay faults sleep inside fire(): enforce the upload budget so a
        # stalled PCIe/DMA degrades to host INSIDE the query deadline
        waited_ms = (time.perf_counter() - t0) * 1000.0
        budget = _upload_budget_ms()
        if waited_ms > budget:
            raise RealtimeUploadError(
                f"delta upload for {self.name}.{column} stalled "
                f"{waited_ms:.0f}ms > budget {budget:.0f}ms")

    def _account(self, column: str, kind: str, nbytes: int) -> None:
        _note_upload((f"rt:{self.name}:{column}", kind), nbytes)
        SERVER_METRICS.add_meter(
            ServerMeter.REALTIME_DELTA_UPLOAD_BYTES, nbytes)
        _note_delta(nbytes)
        self.registry._note(nbytes)

    def _ensure_capacity(self, st: _Plane, padded: int, dtype,
                         shape_tail: tuple = ()) -> None:
        if st.arr is None:
            st.arr = jnp.zeros((padded,) + shape_tail, dtype=dtype)
        elif st.arr.shape[0] < padded:
            # device-side grow: copy the old plane into a bigger zero
            # bucket without any host→device traffic
            grown = jnp.zeros((padded,) + st.arr.shape[1:],
                              dtype=st.arr.dtype)
            st.arr = jax.lax.dynamic_update_slice(
                grown, st.arr, (0,) * st.arr.ndim)

    # -- plane builders -----------------------------------------------------
    def row_plane(self, view, column: str, kind: str):
        """Device plane for (column, kind ∈ ids|raw|null), delta-uploaded
        up to the view's pinned row count and sliced to its pad bucket."""
        col = view._seg.column(column)
        n = view.num_docs
        padded = pad_bucket(max(1, n))
        if kind == "ids":
            slicer, dtype = col.ids_slice, np.dtype(np.int32)
        elif kind == "raw":
            if not col.data_type.is_numeric:
                raise RealtimeUploadError(
                    f"{column}: non-numeric raw plane")
            # the SPI storage dtype, NOT the mutable buffer dtype, so the
            # plane matches what the immutable path would upload (family
            # keys and kernel dtypes line up across hybrid members)
            slicer, dtype = col.raw_slice, col.data_type.numpy_dtype
        elif kind == "null":
            slicer, dtype = col.null_slice, np.dtype(bool)
        else:  # pragma: no cover - planner only requests the kinds above
            raise ValueError(kind)
        with self._lock:
            st = self._planes.setdefault((column, kind), _Plane())
            self._ensure_capacity(st, padded, dtype)
            if st.rows < n:
                delta = slicer(st.rows, n)
                self._fire_fault(column, kind, int(delta.nbytes))
                chunk = _chunk_len(len(delta), st.arr.shape[0] - st.rows)
                upd = np.zeros(chunk, dtype=dtype)
                upd[: len(delta)] = delta
                st.arr = jax.lax.dynamic_update_slice(
                    st.arr, jnp.asarray(upd), (np.int32(st.rows),))
                st.rows = n
                self._account(column, kind, int(upd.nbytes))
                if n > self._gen_rows:
                    self._gen_rows = n
                    SERVER_METRICS.add_meter(
                        ServerMeter.REALTIME_PLANE_GENERATIONS, 1)
                    self.registry.generations += 1
            arr = st.arr
        return arr if arr.shape[0] == padded else arr[:padded]

    def dict_plane(self, view, column: str):
        """Device dictionary-values plane, delta-uploaded up to the view's
        pinned cardinality and padded to its _dict_pad bucket (pad entries
        are never gathered — prefix ids stay below the pinned card)."""
        col = view._seg.column(column)
        card = view.pinned_cardinality(column)
        if not col.data_type.is_numeric:
            raise RealtimeUploadError(f"{column}: non-numeric dict plane")
        dtype = col.data_type.numpy_dtype
        target = _dict_pad(card)
        with self._lock:
            st = self._planes.setdefault((column, "dict"), _Plane())
            if st.arr is None:
                st.arr = jnp.zeros((max(target, 1),), dtype=dtype)
            elif st.arr.shape[0] < target:
                grown = jnp.zeros((target,), dtype=st.arr.dtype)
                st.arr = jax.lax.dynamic_update_slice(grown, st.arr, (0,))
            if st.rows < card:
                delta = col.dict_values_numeric(st.rows, card)
                self._fire_fault(column, "dict", int(delta.nbytes))
                room = st.arr.shape[0] - st.rows
                chunk = _chunk_len(len(delta), room)
                upd = np.zeros(chunk, dtype=dtype)
                upd[: len(delta)] = delta
                st.arr = jax.lax.dynamic_update_slice(
                    st.arr, jnp.asarray(upd), (np.int32(st.rows),))
                st.rows = card
                self._account(column, "dict", int(upd.nbytes))
            arr = st.arr
        return arr if arr.shape[0] == target else arr[:target]

    # -- bookkeeping --------------------------------------------------------
    def nbytes(self) -> int:
        with self._lock:
            return sum(p.arr.nbytes for p in self._planes.values()
                       if p.arr is not None)

    def evict(self) -> None:
        with self._lock:
            self._planes.clear()

    def watermark(self, column: str, kind: str) -> int:
        """Uploaded-row watermark for one plane (tests/observability)."""
        with self._lock:
            st = self._planes.get((column, kind))
            return st.rows if st is not None else 0


def _dict_pad(card: int) -> int:
    """Pow2 shape bucket for dictionary planes — mirrors
    engine/executor._dict_pad (redeclared: the executor imports this
    module lazily, not the other way around)."""
    b = 1
    while b < card:
        b <<= 1
    return b


class RealtimeDeviceView:
    """Per-query adapter: duck-types SegmentDeviceView's gather API over
    one snapshot view + the segment's shared plane set. ``padded`` is the
    SNAPSHOT's pad bucket — a plane whose capacity outgrew it is sliced
    device-side, so every kernel shape matches what an immutable segment
    of this bucket would produce."""

    def __init__(self, planes: RealtimePlaneSet, snapshot):
        self.planes = planes
        self.snapshot = snapshot
        self.padded = pad_bucket(max(1, snapshot.num_docs))

    def dict_ids(self, column: str):
        return self.planes.row_plane(self.snapshot, column, "ids")

    def dict_ids_packed(self, column: str):
        # realtime ids planes are always unpacked int32 (mutable metadata
        # carries no bits_per_value) — width 0 matches the family key
        return self.dict_ids(column), 0

    def mv_dict_ids(self, column: str):
        raise RealtimeUploadError(
            f"{column}: MV planes stay host-side for consuming segments")

    def raw(self, column: str):
        return self.planes.row_plane(self.snapshot, column, "raw")

    def raw_f32_rebased(self, column: str):
        # the rebase base (column min) is unstable while consuming —
        # planner refuses the slot; this guard is defense in depth
        raise RealtimeUploadError(
            f"{column}: rebased f32 planes stay host-side while consuming")

    def dict_values(self, column: str):
        return self.planes.dict_plane(self.snapshot, column)

    def null_plane(self, column: str):
        return self.planes.row_plane(self.snapshot, column, "null")

    def nbytes(self) -> int:
        return self.planes.nbytes()

    def evict(self) -> None:
        self.planes.evict()


class RealtimePlaneRegistry:
    """Process-wide plane sets, weakly keyed by the live MutableSegment:
    GC reclaims a set when its segment dies; commit/discard paths drop
    eagerly by name (realtime/manager.py) and OOM relief clears wholesale
    (engine/oom.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stat_lock = threading.Lock()
        self._sets: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.delta_bytes = 0
        self.uploads = 0
        self.generations = 0

    def _note(self, nbytes: int) -> None:
        with self._stat_lock:
            self.delta_bytes += nbytes
            self.uploads += 1

    def plane_set(self, segment) -> RealtimePlaneSet:
        with self._lock:
            ps = self._sets.get(segment)
            if ps is None:
                ps = RealtimePlaneSet(
                    str(getattr(segment, "name", segment)), self)
                self._sets[segment] = ps
            return ps

    def view(self, snapshot) -> RealtimeDeviceView:
        """Device view for one pinned MutableSegmentView. Plane state is
        keyed by the UNDERLYING segment so consecutive snapshots share
        (and incrementally advance) the same planes."""
        seg = getattr(snapshot, "_seg", snapshot)
        return RealtimeDeviceView(self.plane_set(seg), snapshot)

    def drop_named(self, name: str) -> int:
        """Release planes for every set of this segment name (commit /
        discard / departure). Returns bytes freed."""
        name = str(name)
        freed = 0
        with self._lock:
            victims = [(seg, ps) for seg, ps in self._sets.items()
                       if ps.name == name]
            for seg, _ in victims:
                del self._sets[seg]
        for _, ps in victims:
            freed += ps.nbytes()
            ps.evict()
        return freed

    def clear(self, keep=None) -> int:
        """Drop every plane set (HBM-pressure relief), optionally sparing
        the segment currently executing — its planes back the retry's
        uploads. Returns bytes freed."""
        freed = 0
        with self._lock:
            victims = [(seg, ps) for seg, ps in self._sets.items()
                       if seg is not keep]
            for seg, _ in victims:
                del self._sets[seg]
        for _, ps in victims:
            freed += ps.nbytes()
            ps.evict()
        return freed

    def nbytes(self) -> int:
        with self._lock:
            sets = list(self._sets.values())
        return sum(ps.nbytes() for ps in sets)

    def stats(self) -> dict:
        with self._stat_lock:
            return {"deltaBytes": self.delta_bytes,
                    "uploads": self.uploads,
                    "generations": self.generations,
                    "planeBytes": self.nbytes()}


REALTIME_PLANES = RealtimePlaneRegistry()


# -- planner ------------------------------------------------------------------


class RealtimeSegmentPlanner(SegmentPlanner):
    """Per-segment planner over a pinned MutableSegmentView. Differences
    from the immutable planner:

    - mutable segments are allowed (the view pins row count, dictionary
      cardinalities and upsert validity, so lowering is deterministic);
    - RANGE over a dict column lowers in VALUE space (boolean LUT over
      snapshot dictionary values) — the insertion-ordered mutable
      dictionary has no sorted id intervals;
    - MV id planes and rebased-f32 planes are refused (host fallback):
      ragged MV matrices and a min-value rebase base are unstable while
      the segment is consuming.
    """

    allow_mutable = True

    def slot(self, column: str, kind: str) -> int:
        if kind in ("rawf32r", "mvids"):
            raise UnsupportedQueryError(
                f"realtime device planes: no {kind} plane for "
                f"consuming segments")
        if kind == "dict":
            m = self._meta(column)
            if not DataType(m.data_type).is_numeric:
                raise UnsupportedQueryError(
                    f"realtime device planes: non-numeric dictionary "
                    f"for {column}")
        return super().slot(column, kind)

    def _lower_dict_predicate(self, p, lhs, info):
        if p.type != PredicateType.RANGE:
            return super()._lower_dict_predicate(p, lhs, info)
        ids_slot, card, d = info
        mv = not self._meta(lhs.identifier).single_value
        vals = d.values
        m = np.ones(card, dtype=bool)
        if card:
            if p.lower is not None:
                lo = _coerce_like(vals, p.lower)
                m &= (vals >= lo) if p.lower_inclusive else (vals > lo)
            if p.upper is not None:
                hi = _coerce_like(vals, p.upper)
                m &= (vals <= hi) if p.upper_inclusive else (vals < hi)
        lut = np.zeros(card + 1, dtype=bool)
        lut[:card] = m
        return ir.Lut(ids_slot, self.param(lut), mv=mv)


def realtime_plan(query, segment):
    """Lower a device plan for a pinned consuming-segment snapshot, or
    raise UnsupportedQueryError so the caller falls back to host."""
    if getattr(segment, "snapshot_generation", None) is None:
        raise UnsupportedQueryError(
            "mutable segment without a pinned snapshot view")
    if not realtime_device_enabled(query):
        raise UnsupportedQueryError("realtime device planes disabled")
    return RealtimeSegmentPlanner(query, segment).plan()
