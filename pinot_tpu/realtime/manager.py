"""Realtime consumption: per-partition consumer threads + commit protocol.

Reference call stack (SURVEY.md §3.3): RealtimeTableDataManager.
doAddConsumingSegment → RealtimeSegmentDataManager (pinot-core/.../data/
manager/realtime/RealtimeSegmentDataManager.java:123) whose PartitionConsumer
thread (run:717-880) loops CONSUMING → (end criteria) → HOLDING → COMMITTING
→ COMMITTED, then the table manager replaces the mutable segment with the
committed immutable one and opens the next consuming segment from the end
offset.

Single-process simplifications vs the reference, kept behind the same
interfaces so the cluster layer can swap them out:
- the segment-completion FSM (controller SegmentCompletionManager) collapses
  to an in-process ``commit()`` — one replica, always the winner;
- ZK segment metadata collapses to a JSON checkpoint file per table holding
  committed end offsets (crash → resume from last committed offset, the
  reference's exactly-once guarantee via segments-as-checkpoints).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path
from typing import Callable, Optional

from ..ingestion.transform import build_transform_pipeline
from ..segment.loader import ImmutableSegment, load_segment
from ..segment.mutable import MutableSegment
from ..spi import faults
from ..spi.stream import (
    LongMsgOffset,
    StreamConfig,
    get_decoder,
    get_stream_consumer_factory,
)
from .converter import RealtimeSegmentConverter

log = logging.getLogger(__name__)

# consumption states (reference RealtimeSegmentDataManager.State)
CONSUMING = "CONSUMING"
HOLDING = "HOLDING"
COMMITTING = "COMMITTING"
COMMITTED = "COMMITTED"
ERROR = "ERROR"


def llc_segment_name(table: str, partition: int, seq: int,
                     ts_ms: Optional[int] = None) -> str:
    """LLC naming: {table}__{partition}__{seq}__{timestamp} (reference
    LLCSegmentName)."""
    ts = ts_ms if ts_ms is not None else int(time.time() * 1000)
    return f"{table}__{partition}__{seq}__{ts}"


class RealtimeSegmentDataManager:
    """One consuming segment on one partition: consumer thread with the
    consume → end-criteria → commit state machine."""

    def __init__(self, schema, table_config, stream_config: StreamConfig,
                 partition: int, seq: int, start_offset: LongMsgOffset,
                 on_commit: Callable[["RealtimeSegmentDataManager"], None],
                 poll_idle_s: float = 0.02, pk_manager=None,
                 completion=None, instance_id: str = "server_0",
                 on_build: Optional[Callable] = None,
                 on_commit_success: Optional[Callable] = None,
                 on_discard: Optional[Callable] = None,
                 on_elected: Optional[Callable] = None,
                 test_hooks: Optional[dict] = None):
        self.schema = schema
        self.table_config = table_config
        self.stream_config = stream_config
        self.partition = partition
        self.seq = seq
        self.start_offset = start_offset
        self.current_offset = start_offset
        self.on_commit = on_commit
        self.poll_idle_s = poll_idle_s
        # multi-replica completion protocol (realtime/completion.py); None →
        # in-process commit, the single-replica fast path
        self.completion = completion
        self.instance_id = instance_id
        self.on_build = on_build
        self.on_commit_success = on_commit_success
        self.on_discard = on_discard
        self.on_elected = on_elected  # pauseless successor start
        self.test_hooks = test_hooks or {}
        # upsert/dedup metadata manager (upsert/manager.py): process_row
        # pre-index (partial merge / duplicate drop), add_record post-index
        self.pk_manager = pk_manager

        # under the replica completion protocol every replica must mint the
        # IDENTICAL segment name for (table, partition, seq) — the reference
        # has the controller assign it; here the name's timestamp field is
        # derived from the start offset so it is deterministic across hosts
        ts_ms = start_offset.offset if completion is not None else None
        self.segment = MutableSegment(
            schema, llc_segment_name(table_config.table_name, partition, seq,
                                     ts_ms=ts_ms))
        factory = get_stream_consumer_factory(stream_config)
        self.consumer = factory.create_partition_consumer(partition)
        self.decoder = get_decoder(stream_config)
        self.pipeline = build_transform_pipeline(schema, table_config)

        self.state = CONSUMING
        self.consume_start_ms = int(time.time() * 1000)
        self.last_consumed_ms = self.consume_start_ms  # IngestionDelayTracker
        self.rows_indexed = 0
        self.rows_filtered = 0
        self.rows_errored = 0
        self._stop = threading.Event()
        self._force_commit = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"consumer-{self.segment.segment_name}", daemon=True)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._thread.start()

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        self._thread.join(timeout)
        self.consumer.close()

    def force_commit(self):
        """Seal now regardless of thresholds (reference forceCommit /
        pauseless commit trigger; minion RealtimeToOfflineSegmentsTask uses
        this to roll segments)."""
        self._force_commit.set()

    def join_committed(self, timeout: float = 30.0) -> bool:
        t0 = time.time()
        while time.time() - t0 < timeout:
            if self.state in (COMMITTED, ERROR):
                return self.state == COMMITTED
            time.sleep(0.01)
        return False

    # -- the consume loop (reference PartitionConsumer.run:717-880) --------
    def _run(self):
        try:
            fetch_errors = 0
            while not self._stop.is_set():
                try:
                    batch = self._fetch()
                except Exception:
                    # transient stream hiccup (broker rebalance, network
                    # blip) must not kill the consumer: back off and retry;
                    # only persistent failure drops to ERROR (reference:
                    # the consumer's transient-exception handling in
                    # RealtimeSegmentDataManager.consumeLoop)
                    fetch_errors += 1
                    if fetch_errors > 5:
                        raise
                    log.warning("consumer %s: fetch failed (%d/5), retrying",
                                self.segment.segment_name, fetch_errors)
                    time.sleep(self.poll_idle_s)
                    continue
                fetch_errors = 0
                if batch.message_count:
                    self._index_batch(batch)
                    self.current_offset = batch.offset_of_next_batch
                    self.last_consumed_ms = int(time.time() * 1000)
                else:
                    time.sleep(self.poll_idle_s)
                if self._end_criteria_reached():
                    self._commit()
                    return
            # stopped while consuming: leave segment mutable (HOLDING);
            # offsets below the last commit re-consume on restart
            self.state = HOLDING
        except Exception:  # noqa: BLE001 — consumer thread must not die silently
            log.exception("consumer %s failed", self.segment.segment_name)
            self.state = ERROR

    def _fetch(self):
        """One consumer fetch — the stream.fetch injection point."""
        if faults.ACTIVE:
            faults.FAULTS.fire("stream.fetch",
                               segment=self.segment.segment_name,
                               offset=self.current_offset.offset)
        return self.consumer.fetch_messages(
            self.current_offset, self.stream_config.fetch_timeout_ms)

    def _index_batch(self, batch):
        for msg in batch.messages:
            row = self.decoder.decode(msg)
            if row is None:
                self.rows_errored += 1
                continue
            row = self.pipeline.transform(dict(row))
            if row is None:
                self.rows_filtered += 1
                continue
            if self.pk_manager is not None:
                row = self.pk_manager.process_row(self.segment, row)
                if row is None:  # dedup drop
                    self.rows_filtered += 1
                    continue
            doc_id = self.segment.index(row)
            if self.pk_manager is not None:
                self.pk_manager.add_record(self.segment, doc_id, row)
            self.rows_indexed += 1

    @property
    def num_docs(self) -> int:
        return self.segment.num_docs

    def _end_criteria_reached(self) -> bool:
        if self._force_commit.is_set() and self.segment.num_docs > 0:
            return True
        if self.segment.num_docs >= self.stream_config.flush_threshold_rows:
            return True
        age_ms = int(time.time() * 1000) - self.consume_start_ms
        return (age_ms >= self.stream_config.flush_threshold_time_ms
                and self.segment.num_docs > 0)

    def _commit(self):
        self.state = COMMITTING
        try:
            if self.completion is None:
                self.on_commit(self)
                self.state = COMMITTED
                return
            self._commit_via_protocol()
        except Exception:  # noqa: BLE001
            log.exception("commit of %s failed", self.segment.segment_name)
            self.state = ERROR

    def _completion_call(self, fn):
        """Run one completion-protocol call, retrying with capped backoff
        through controller outages: a vacant leader seat
        (NoControllerLeaderError) or a glitching store write keeps the
        consumer HOLDing — never ERROR — until leadership is claimable
        again (reference: ServerSegmentCompletionProtocolHandler retries
        NOT_LEADER responses). Returns None only when stopped mid-wait."""
        from ..cluster.store import StoreError
        from .completion import NoControllerLeaderError

        delay = 0.02
        while not self._stop.is_set():
            try:
                return fn()
            except NoControllerLeaderError:
                from ..spi.metrics import SERVER_METRICS, ServerMeter

                SERVER_METRICS.add_meter(ServerMeter.COMPLETION_HOLDS_NO_LEADER)
            except (StoreError, faults.InjectedFault):
                log.warning("completion call failed transiently; retrying",
                            exc_info=True)
            self._stop.wait(delay)
            delay = min(delay * 2, 2.0)
        return None

    def _commit_via_protocol(self):
        """Replica-aware commit: segmentConsumed → HOLD/CATCHUP until the
        controller elects a committer; the winner builds + commits, losers
        DISCARD and download (reference PartitionConsumer commit loop,
        RealtimeSegmentDataManager.java:880-960)."""
        from .completion import CATCHUP, COMMIT, COMMIT_SUCCESS, CONTINUE, DISCARD

        table = self.table_config.table_name
        name = self.segment.segment_name
        while not self._stop.is_set():
            resp = self._completion_call(lambda: self.completion.segment_consumed(
                table, name, self.instance_id, self.current_offset.offset))
            if resp is None:
                break
            if resp.status == CATCHUP:
                self._catchup(resp.offset)
                continue
            if resp.status == COMMIT:
                start = self._completion_call(
                    lambda: self.completion.segment_commit_start(
                        table, name, self.instance_id,
                        self.current_offset.offset))
                if start is None:
                    break
                if start.status != CONTINUE:
                    continue
                if self.on_elected is not None:
                    # pauseless: the successor consumer starts at the
                    # elected end offset BEFORE the build/upload completes
                    # (reference: PauselessSegmentCompletionFSM — ingestion
                    # never pauses for the commit)
                    self.on_elected(self, self.current_offset.offset)
                location = self.on_build(self)
                die = self.test_hooks.get("die_before_commit_end")
                if die is not None and die(self):
                    # simulated process death between build and commit —
                    # the lease expires and another replica is re-elected
                    return
                from ..segment.format import partition_push_metadata

                # DONE records carry partition stamps ({} when the table
                # declares no partitioning); the MSE dispatcher reads them
                # (falling back from the name-with-type namespace to this
                # completion-protocol one) to place colocated workers next
                # to realtime segments
                end = self._completion_call(
                    lambda: self.completion.segment_commit_end(
                        table, name, self.instance_id,
                        self.current_offset.offset, location,
                        metadata=partition_push_metadata(location)))
                if end is None:
                    break
                if end.status == COMMIT_SUCCESS:
                    self.on_commit_success(self, location)
                    self.state = COMMITTED
                    return
                # lost a late race: re-poll (likely DISCARD next); never
                # hot-spin on repeated FAILED responses
                time.sleep(self.poll_idle_s)
                continue
            if resp.status == DISCARD:
                self.on_discard(self, resp.location, resp.offset)
                # downloaded the winner's build; done with this segment
                self.state = COMMITTED
                return
            # HOLD
            time.sleep(self.poll_idle_s)
        self.state = HOLDING

    def _catchup(self, target_offset: int):
        """Consume up to the elected committer's end offset so every replica
        commits the identical row set (reference: CatchingUp state)."""
        while (not self._stop.is_set()
               and self.current_offset.offset < target_offset):
            batch = self._fetch()
            if not batch.message_count:
                time.sleep(self.poll_idle_s)
                continue
            # never index past the elected end offset. Record offsets may be
            # sparse (Kafka log compaction / txn markers), so truncate by
            # OFFSET when records carry one, by count only as a fallback
            from ..spi.stream import MessageBatch

            if all(m.offset is not None for m in batch.messages):
                msgs = [m for m in batch.messages
                        if m.offset.offset < target_offset]
                if (len(msgs) < batch.message_count
                        or batch.offset_of_next_batch.offset > target_offset):
                    batch = MessageBatch(msgs, LongMsgOffset(target_offset))
            else:
                take = target_offset - self.current_offset.offset
                if batch.message_count > take:
                    batch = MessageBatch(list(batch.messages)[:take],
                                         LongMsgOffset(target_offset))
            self._index_batch(batch)
            self.current_offset = batch.offset_of_next_batch
            self.last_consumed_ms = int(time.time() * 1000)


class RealtimeTableDataManager:
    """Per-table realtime lifecycle: one consuming segment per partition,
    sealed segments on disk, committed-offset checkpointing.

    ``segments`` is a live list (committed immutables + consuming mutables) —
    the query executor snapshots it per query."""

    def __init__(self, schema, table_config, data_dir: str | Path,
                 segment_hook: Optional[Callable] = None,
                 completion=None, instance_id: str = "server_0",
                 pauseless: bool = False,
                 test_hooks: Optional[dict] = None):
        self.schema = schema
        self.table_config = table_config
        self.stream_config = StreamConfig.from_table_config(
            table_config.ingestion.stream_configs)
        self.data_dir = Path(data_dir)
        self.data_dir.mkdir(parents=True, exist_ok=True)
        self.pk_manager = None
        if table_config.upsert.mode.upper() != "NONE":
            from ..upsert import TableUpsertMetadataManager

            self.pk_manager = TableUpsertMetadataManager(schema, table_config)
        elif table_config.dedup.enabled:
            from ..upsert import TableDedupManager

            self.pk_manager = TableDedupManager(schema, table_config)
        # upsert doc ids must survive conversion: never re-sort
        self.converter = RealtimeSegmentConverter(
            schema, table_config,
            preserve_doc_order=self.pk_manager is not None)
        self.segment_hook = segment_hook  # cluster layer: upsert/dedup attach
        # replica completion protocol (realtime/completion.py). Upsert/dedup
        # tables keep the single-replica fast path: their pk metadata is
        # partition-pinned and cannot be rebuilt from a downloaded build.
        self.completion = completion if self.pk_manager is None else None
        self.instance_id = instance_id
        # pauseless (reference PauselessSegmentCompletionFSM): the successor
        # consumer starts at election time, while the elected committer is
        # still building — requires the completion protocol
        self.pauseless = bool(pauseless and self.completion is not None)
        # segments sealed-but-not-yet-committed, still serving queries:
        # segment name → (mutable segment, its manager — still mid-commit)
        self._committing: dict[str, tuple] = {}
        self.test_hooks = test_hooks or {}
        self.segments: list = []  # live view: immutables + mutables
        self._committed: list[ImmutableSegment] = []
        self._consuming: dict[int, RealtimeSegmentDataManager] = {}
        self._seq: dict[int, int] = {}
        self._lock = threading.RLock()
        self._shutdown = False
        self._checkpoint_file = self.data_dir / "_checkpoints.json"
        cp = self._load_checkpoints()
        self._offsets: dict[str, str] = cp.get("partitions", {})
        self._segment_names: list[str] = cp.get("segments", [])
        # freshness gauges (reference: IngestionDelayTracker publishing
        # realtimeIngestionDelayMs / realtimeIngestionOffsetLag per table)
        from ..spi.metrics import SERVER_METRICS

        tname = self.table_config.table_name
        def _worst_delay():
            return max(self.ingestion_delay_ms().values(), default=0)

        def _worst_lag():
            # -1 (provider error on ANY partition) must surface, not be
            # masked by a healthy partition's larger non-negative lag
            lags = self.offset_lag().values()
            return -1 if any(v < 0 for v in lags) else max(lags, default=0)

        self._gauges = {f"realtimeIngestionDelayMs.{tname}": _worst_delay,
                        f"realtimeIngestionOffsetLag.{tname}": _worst_lag}
        for gname, fn in self._gauges.items():
            SERVER_METRICS.set_gauge(gname, fn)
        self._meta_provider = None  # cached for offset_lag polls

    # -- checkpoints (ZK segment-metadata equivalent) ----------------------
    # The checkpoint file is the COMMIT POINT: it atomically records both the
    # committed segment names and the advanced offsets, so a crash anywhere
    # around conversion either (a) leaves the file untouched — the partial
    # segment dir is ignored+removed on restart and its rows re-consume, or
    # (b) records both — the segment loads and consumption resumes past it.
    # Rows land in exactly one committed segment either way.
    def _load_checkpoints(self) -> dict:
        if self._checkpoint_file.exists():
            try:
                return json.loads(self._checkpoint_file.read_text())
            except ValueError:
                # torn write can only happen with the legacy non-atomic
                # writer; treat as empty (segments re-consume)
                return {}
        return {}

    def _save_checkpoints(self):
        tmp = self._checkpoint_file.with_suffix(".tmp")
        tmp.write_text(json.dumps(
            {"partitions": self._offsets, "segments": self._segment_names}))
        tmp.replace(self._checkpoint_file)  # atomic on POSIX

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Load committed segments from disk, resume consumption from the
        last committed offsets (crash recovery — reference: servers replay
        Helix transitions then resume from segment.realtime.startOffset)."""
        with self._lock:
            known = set(self._segment_names)
            found: dict[str, object] = {}
            for d in sorted(self.data_dir.iterdir()):
                if not d.is_dir():
                    continue
                if d.name in known:
                    found[d.name] = d
                else:
                    # crash leftover: conversion finished (or half-finished)
                    # but the checkpoint never recorded it — rows re-consume
                    import shutil

                    shutil.rmtree(d, ignore_errors=True)
            # load in COMMIT order (checkpoint list) so upsert bootstrap
            # resolves pk conflicts the same way the live path did
            for name in self._segment_names:
                if name in found:
                    seg = load_segment(found[name])
                    if self.pk_manager is not None:
                        self.pk_manager.add_segment(seg)
                    self._committed.append(seg)
            factory = get_stream_consumer_factory(self.stream_config)
            meta = factory.create_metadata_provider()
            n = meta.partition_count()
            meta.close()
            for p in range(n):
                self._start_partition(p)
            self._refresh_view()

    def _start_partition(self, partition: int):
        seq = self._seq.get(partition, 0)
        start = LongMsgOffset.parse(self._offsets.get(str(partition), "0"))
        if self._offsets.get(str(partition)) is None \
                and self.stream_config.offset_criteria == "largest":
            factory = get_stream_consumer_factory(self.stream_config)
            meta = factory.create_metadata_provider()
            start = meta.fetch_latest_offset(partition)
            meta.close()
        mgr = self._make_manager(partition, seq, start)
        self._consuming[partition] = mgr
        self._seq[partition] = seq + 1
        mgr.start()

    def _make_manager(self, partition: int, seq: int,
                      start: LongMsgOffset) -> RealtimeSegmentDataManager:
        return RealtimeSegmentDataManager(
            self.schema, self.table_config, self.stream_config, partition, seq,
            start, self._handle_commit, pk_manager=self.pk_manager,
            completion=self.completion, instance_id=self.instance_id,
            on_build=self._handle_build,
            on_commit_success=self._handle_commit_success,
            on_discard=self._handle_discard,
            on_elected=self._handle_elected if self.pauseless else None,
            test_hooks=self.test_hooks)

    def _handle_elected(self, mgr: RealtimeSegmentDataManager,
                        end_offset: int) -> None:
        """Pauseless: the sealed segment moves to a committing-holding list
        (still queryable) and the successor consumer starts NOW from the
        elected end offset — ingestion never waits for build/upload."""
        with self._lock:
            if self._consuming.get(mgr.partition) is not mgr:
                return  # successor already started (re-elected committer)
            self._committing[mgr.segment.segment_name] = (mgr.segment, mgr)
            self._consuming.pop(mgr.partition, None)
            if not self._shutdown:
                self._start_partition_from(mgr.partition,
                                           LongMsgOffset(end_offset))
            self._refresh_view()

    def stop(self):
        # order matters: the shutdown flag first, so a commit racing with us
        # cannot spawn a successor consumer after we snapshot; then drain
        # until no live managers remain (a successor may have started just
        # before the flag was set)
        with self._lock:
            self._shutdown = True
        while True:
            with self._lock:
                managers = [m for m in self._consuming.values()
                            if m._thread.is_alive() or not m._stop.is_set()]
                # pauseless: elected committers left _consuming but their
                # threads are still building/committing — drain them too, or
                # they'd keep writing checkpoints after "shutdown"
                managers += [m for _seg, m in self._committing.values()
                             if m._thread.is_alive() or not m._stop.is_set()]
            if not managers:
                break
            for m in managers:
                m.stop()
        # release the freshness gauges: they close over self, and the global
        # registry would otherwise pin this manager (and poll a dead table's
        # stream metadata) forever. Identity-guarded: if a replacement
        # manager for the same table already re-registered, leave its
        # gauges alone.
        from ..spi.metrics import SERVER_METRICS

        for gname, fn in self._gauges.items():
            SERVER_METRICS.remove_gauge(gname, fn)
        with self._lock:
            provider, self._meta_provider = self._meta_provider, None
        if provider is not None:
            try:
                provider.close()
            except Exception:
                pass

    # -- commit (in-process completion FSM) --------------------------------
    def _handle_commit(self, mgr: RealtimeSegmentDataManager):
        out_dir = self.data_dir / mgr.segment.segment_name
        self.converter.convert(mgr.segment, out_dir)
        committed = load_segment(out_dir)
        if self.pk_manager is not None:
            # transfer validity plane + record locations mutable → immutable
            self.pk_manager.replace_segment(mgr.segment, committed)
        if self.segment_hook is not None:
            self.segment_hook(committed)
        with self._lock:
            self._committed.append(committed)
            self._offsets[str(mgr.partition)] = str(mgr.current_offset)
            self._segment_names.append(mgr.segment.segment_name)
            self._save_checkpoints()  # ← the commit point (see above)
            self._consuming.pop(mgr.partition, None)
            if not self._shutdown:
                self._start_partition_from(mgr.partition, mgr.current_offset)
            self._refresh_view()
        # the mutable segment is NOT destroyed here: in-flight queries may
        # hold snapshot views of it; it drops out of the live list above and
        # the GC reclaims it once the last query releases its snapshot
        self._drop_device_state(mgr.segment.segment_name)

    def _drop_device_state(self, name: str) -> None:
        """Retire the mutable segment's device footprint once its immutable
        replacement is queryable: realtime planes, generation-keyed stacked
        views, and partial-cache entries all carry the segment name, so one
        name-drop clears them. Best-effort — these are performance caches,
        never correctness (a stale plane would simply never be consulted
        again since the name left the live list)."""
        try:
            from ..cache.partial import GLOBAL_PARTIAL_CACHE
            from ..segment.device_cache import GLOBAL_DEVICE_CACHE
            from .device_plane import REALTIME_PLANES

            REALTIME_PLANES.drop_named(name)
            GLOBAL_DEVICE_CACHE.drop_named(name)
            GLOBAL_PARTIAL_CACHE.invalidate_segment(name)
        except Exception:  # pragma: no cover - cleanup must never fail a commit
            pass

    # -- replica completion protocol callbacks ------------------------------
    def _handle_build(self, mgr: RealtimeSegmentDataManager) -> str:
        """Build-only half of the commit (reference: buildSegmentInternal);
        registration waits for segmentCommitEnd success."""
        out_dir = self.data_dir / mgr.segment.segment_name
        self.converter.convert(mgr.segment, out_dir)
        return str(out_dir)

    def _handle_commit_success(self, mgr: RealtimeSegmentDataManager,
                               location: str) -> None:
        committed = load_segment(location)
        if self.segment_hook is not None:
            self.segment_hook(committed)
        with self._lock:
            self._committed.append(committed)
            # pauseless: the successor may have committed a LATER offset
            # already — never move the checkpoint backwards (restart would
            # re-ingest the successor's rows)
            cur = int(self._offsets.get(str(mgr.partition), "0") or 0)
            self._offsets[str(mgr.partition)] = str(
                max(cur, mgr.current_offset.offset))
            self._segment_names.append(mgr.segment.segment_name)
            self._save_checkpoints()
            was_pauseless = self._committing.pop(
                mgr.segment.segment_name, None) is not None
            if not was_pauseless:
                self._consuming.pop(mgr.partition, None)
                if not self._shutdown:
                    self._start_partition_from(mgr.partition,
                                               mgr.current_offset)
            # pauseless: the successor is already consuming
            self._refresh_view()
        self._drop_device_state(mgr.segment.segment_name)

    def _handle_discard(self, mgr: RealtimeSegmentDataManager,
                        location: str, end_offset: int) -> None:
        """This replica lost the election: drop the local build and download
        the committer's (reference: non-winner replicas download from deep
        store on SegmentCompletionProtocol DISCARD/KEEP)."""
        import shutil

        name = mgr.segment.segment_name
        local = self.data_dir / name
        if Path(location).resolve() != local.resolve():
            if local.exists():
                shutil.rmtree(local, ignore_errors=True)
            shutil.copytree(location, local)
        committed = load_segment(local)
        if self.segment_hook is not None:
            self.segment_hook(committed)
        with self._lock:
            self._committed.append(committed)
            cur = int(self._offsets.get(str(mgr.partition), "0") or 0)
            self._offsets[str(mgr.partition)] = str(max(cur, int(end_offset)))
            self._segment_names.append(name)
            self._save_checkpoints()
            was_pauseless = self._committing.pop(name, None) is not None
            if not was_pauseless:
                self._consuming.pop(mgr.partition, None)
                if not self._shutdown:
                    self._start_partition_from(mgr.partition,
                                               LongMsgOffset(end_offset))
            self._refresh_view()
        self._drop_device_state(name)

    def _start_partition_from(self, partition: int, offset: LongMsgOffset):
        seq = self._seq.get(partition, 0)
        nxt = self._make_manager(partition, seq, offset)
        self._consuming[partition] = nxt
        self._seq[partition] = seq + 1
        nxt.start()

    def _refresh_view(self):
        # committing-holding segments (pauseless) stay queryable until their
        # immutable replacement lands
        self.segments[:] = (list(self._committed)
                            + [seg for seg, _m in self._committing.values()]
                            + [m.segment for m in self._consuming.values()])

    # -- ops ---------------------------------------------------------------
    def force_commit(self, timeout: float = 30.0) -> list[str]:
        """Seal all non-empty consuming segments, wait for their commits, and
        return the committed segment names (ops endpoint + minion rollover).
        Empty partitions are skipped — there is nothing to seal."""
        with self._lock:
            managers = [m for m in self._consuming.values() if m.num_docs > 0]
        for m in managers:
            m.force_commit()
        out = []
        for m in managers:
            if m.join_committed(timeout):
                out.append(m.segment.segment_name)
        return out

    def ingestion_delay_ms(self) -> dict[int, int]:
        now = int(time.time() * 1000)
        with self._lock:
            return {p: now - m.last_consumed_ms for p, m in self._consuming.items()}

    def offset_lag(self) -> dict[int, int]:
        """Per-partition messages behind the stream head (reference:
        IngestionDelayTracker's offset lag companion metric). Uses the
        stream's metadata provider; a provider error reports -1 for that
        partition rather than failing the caller (it is a metric)."""
        with self._lock:
            current = {p: m.current_offset.offset
                       for p, m in self._consuming.items()}
        if not current:
            return {}
        out = {}
        # cache the metadata provider across polls (the gauge is scraped
        # continuously; a fresh client connection per scrape would churn);
        # drop it on any error so the next poll reconnects. The cache slot
        # is guarded by self._lock (creation races between concurrent
        # scrapes, and against stop(), would leak live client connections);
        # the fetches themselves run outside the lock — they are network I/O.
        with self._lock:
            if self._shutdown:
                return {p: -1 for p in current}
            provider = self._meta_provider
            if provider is None:
                try:
                    provider = get_stream_consumer_factory(
                        self.stream_config).create_metadata_provider()
                except Exception:
                    return {p: -1 for p in current}
                self._meta_provider = provider
        errored = False
        for p, off in current.items():
            try:
                out[p] = max(0, provider.fetch_latest_offset(p).offset - off)
            except Exception:
                out[p] = -1
                errored = True
        if errored:
            with self._lock:
                if self._meta_provider is provider:
                    self._meta_provider = None
            try:
                provider.close()
            except Exception:
                pass
        return out

    def total_docs(self) -> int:
        with self._lock:
            return sum(s.num_docs for s in self.segments)
