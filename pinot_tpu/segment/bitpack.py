"""Fixed-bit packing of dictionary ids.

The reference stores dict-encoded forward indexes bit-packed at
ceil(log2(cardinality)) bits/value and decodes them with hand-unrolled shift
code (pinot-segment-local/.../io/reader/impl/FixedBitIntReader.java:27,
readUnchecked:44). Here the on-disk format is the same idea (LSB-first packed
bitstream) but decode is a vectorized whole-column operation: the loader
unpacks the full column once into an int32 plane destined for HBM, so there is
no per-lookup decode at query time at all. A Pallas decode-on-device kernel can
replace this later to cut PCIe/DMA volume by bits/32.
"""

from __future__ import annotations

import numpy as np

_CHUNK = 1 << 20  # rows per packing chunk, bounds transient bit-matrix memory


def num_bits_for_cardinality(cardinality: int) -> int:
    """Bits needed to store dict ids in [0, cardinality)."""
    if cardinality <= 1:
        return 1
    return int(cardinality - 1).bit_length()


def pack(values: np.ndarray, num_bits: int) -> np.ndarray:
    """Pack non-negative ints < 2**num_bits into an LSB-first uint8 bitstream."""
    assert 1 <= num_bits <= 32
    from . import native_bridge

    native = native_bridge.pack_bits(np.asarray(values), num_bits)
    if native is not None:
        return native
    values = np.ascontiguousarray(values, dtype=np.uint32)
    n = values.shape[0]
    if num_bits == 8:
        return values.astype(np.uint8)
    if num_bits == 16:
        return values.astype(np.uint16).view(np.uint8)
    if num_bits == 32:
        return values.view(np.uint8)
    out = np.empty((n * num_bits + 7) // 8, dtype=np.uint8)
    # Chunk on boundaries where chunk_rows * num_bits is a multiple of 8 so
    # each chunk packs to whole bytes.
    rows_per_chunk = max(8, (_CHUNK // 8) * 8)
    shifts = np.arange(num_bits, dtype=np.uint32)
    pos = 0
    for start in range(0, n, rows_per_chunk):
        chunk = values[start : start + rows_per_chunk]
        bits = ((chunk[:, None] >> shifts) & 1).astype(np.uint8)
        packed = np.packbits(bits.reshape(-1), bitorder="little")
        out[pos : pos + packed.shape[0]] = packed
        pos += packed.shape[0]
    return out[:pos] if pos != out.shape[0] else out


def unpack(data: np.ndarray, num_bits: int, count: int, dtype=np.int32) -> np.ndarray:
    """Unpack `count` values from an LSB-first bitstream produced by pack()."""
    assert 1 <= num_bits <= 32
    from . import native_bridge

    native = native_bridge.unpack_bits(np.asarray(data), num_bits, count, dtype)
    if native is not None:
        return native
    data = np.ascontiguousarray(data, dtype=np.uint8)
    if num_bits == 8:
        return data[:count].astype(dtype)
    if num_bits == 16:
        return data.view(np.uint16)[:count].astype(dtype)
    if num_bits == 32:
        return data.view(np.uint32)[:count].astype(dtype)
    out = np.empty(count, dtype=dtype)
    rows_per_chunk = max(8, (_CHUNK // 8) * 8)
    weights = (np.uint32(1) << np.arange(num_bits, dtype=np.uint32)).astype(np.uint32)
    for start in range(0, count, rows_per_chunk):
        stop = min(start + rows_per_chunk, count)
        bit_lo = start * num_bits
        bit_hi = stop * num_bits
        byte_lo, byte_hi = bit_lo // 8, (bit_hi + 7) // 8
        bits = np.unpackbits(data[byte_lo:byte_hi], bitorder="little")
        bits = bits[bit_lo - byte_lo * 8 : bit_lo - byte_lo * 8 + (stop - start) * num_bits]
        mat = bits.reshape(stop - start, num_bits).astype(np.uint32)
        out[start:stop] = (mat * weights).sum(axis=1).astype(dtype)
    return out


def pack_bitmap(bools: np.ndarray) -> np.ndarray:
    """Dense boolean vector -> packed uint8 bitmap (null vectors, filter masks)."""
    return np.packbits(np.ascontiguousarray(bools, dtype=bool), bitorder="little")


def unpack_bitmap(data: np.ndarray, count: int) -> np.ndarray:
    return np.unpackbits(np.ascontiguousarray(data, dtype=np.uint8), bitorder="little")[:count].astype(bool)
