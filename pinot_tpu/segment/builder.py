"""Immutable segment builder.

Reference: pinot-segment-local/.../segment/creator/impl/
SegmentIndexCreationDriverImpl.java (init:116, build:231) — a two-pass build
(stats collection, then per-column index creation). Here ingestion is columnar
from the start (rows are transposed once), so stats + dictionary + encode
happen in one vectorized pass per column; there is no per-row code anywhere.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from ..spi.data_types import DataType, Schema
from ..spi.partition import get_partition_function
from ..spi.table_config import TableConfig
from . import bitpack
from .dictionary import build_dictionary, serialize_dictionary
from .format import ColumnMetadata, SegmentMetadata, SegmentWriter
from .indexes import (
    BloomFilter,
    InvertedIndex,
    JsonIndex,
    RawRangeIndex,
    serialize_bloom,
    serialize_inverted,
    serialize_json_index,
    serialize_raw_range,
)


def rows_to_columns(rows: Sequence[Mapping], schema: Schema) -> dict[str, list]:
    cols: dict[str, list] = {name: [] for name in schema.column_names()}
    for row in rows:
        for name in cols:
            cols[name].append(row.get(name))
    return cols


class SegmentBuilder:
    """Builds one immutable segment directory from columnar data."""

    def __init__(
        self,
        schema: Schema,
        table_config: Optional[TableConfig] = None,
        segment_name: str = "segment_0",
    ):
        self.schema = schema
        self.table_config = table_config or TableConfig(table_name=schema.schema_name)
        self.segment_name = segment_name

    def build_from_rows(self, rows: Sequence[Mapping], out_dir: str | Path) -> Path:
        return self.build(rows_to_columns(rows, self.schema), out_dir)

    def build(self, columns: Mapping[str, Iterable], out_dir: str | Path) -> Path:
        """columns: column name -> values (may contain None for nulls)."""
        out_dir = Path(out_dir)
        writer = SegmentWriter(out_dir)
        num_docs = None
        col_metas: dict[str, ColumnMetadata] = {}
        no_dict = set(self.table_config.indexing.no_dictionary_columns)

        for name in self.schema.column_names():
            spec = self.schema.field_spec(name)
            if name not in columns:
                raise KeyError(f"schema column {name!r} missing from input columns {sorted(columns)}")
            values = columns[name]
            if not isinstance(values, np.ndarray):
                values = list(values)
            if num_docs is None:
                num_docs = len(values)
            elif len(values) != num_docs:
                raise ValueError(f"column {name}: {len(values)} values, expected {num_docs}")
            if not spec.single_value:
                meta = self._build_mv_column(writer, name, spec, values, num_docs)
            else:
                meta = self._build_sv_column(writer, name, spec, values, num_docs, raw=name in no_dict)
                pconf = self.table_config.indexing.segment_partition_config.get(name)
                if pconf:
                    self._stamp_partition(meta, pconf, values)
            col_metas[name] = meta

        self._build_indexes(writer, columns, col_metas)

        star_tree_metas = self._build_star_trees(writer, col_metas)

        num_docs = num_docs or 0
        time_col = self.table_config.validation.time_column_name
        start_t = end_t = None
        if time_col and time_col in col_metas:
            m = col_metas[time_col]
            if m.min_value is not None and DataType(m.data_type).is_integral:
                start_t, end_t = int(m.min_value), int(m.max_value)

        meta = SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.table_config.table_name,
            num_docs=num_docs,
            columns=col_metas,
            time_column=time_col,
            start_time=start_t,
            end_time=end_t,
            creation_time_ms=int(time.time() * 1000),
            star_trees=star_tree_metas,
            sort_order=self._compute_sort_order(writer, col_metas),
        )
        writer.write(meta)
        return out_dir

    def _compute_sort_order(self, writer, col_metas) -> list:
        """Ingestion-order metadata: the longest greedy chain of dict-
        encoded SV columns whose dict ids are LEXICOGRAPHICALLY
        nondecreasing over the rows — the leading column is globally
        sorted, each later column is nondecreasing within every run of
        equal chain-prefix values. Any prefix of the chain qualifies as
        presorted composite group keys: with row-major strides the
        composite id Σ id_i·stride_i is then nondecreasing, which is all
        the sparse kernel's presorted fast path needs (engine/plan.py
        keys_presorted, ops/kernels.py _presorted_sparse_tail)."""
        names = [n for n, m in col_metas.items()
                 if m.encoding == "DICT" and m.single_value]
        ids_cache: dict[str, np.ndarray] = {}

        def diff_of(n):
            if n not in ids_cache:
                m = col_metas[n]
                ids = bitpack.unpack(
                    writer.peek_buffer(f"{n}.fwd"), m.bits_per_value,
                    m.total_number_of_entries)
                ids_cache[n] = np.diff(ids.astype(np.int64))
            return ids_cache[n]

        chain: list[str] = []
        new_run = None  # True where the chain prefix changes between rows
        progress = True
        while progress:
            progress = False
            for n in names:
                if n in chain:
                    continue
                d = diff_of(n)
                ok = bool(np.all(d >= 0)) if new_run is None \
                    else bool(np.all((d >= 0) | new_run))
                if ok:
                    chain.append(n)
                    new_run = (d != 0) if new_run is None \
                        else (new_run | (d != 0))
                    progress = True
        return chain

    def _build_star_trees(self, writer, col_metas) -> list:
        """Pre-aggregated dense tables per star_tree_index_configs
        (segment/startree.py design notes)."""
        from .dictionary import deserialize_dictionary
        from .startree import StarTreeConfig, build_star_tree

        metas = []
        for tree_id, cfg_json in enumerate(self.table_config.indexing.star_tree_index_configs):
            cfg = StarTreeConfig.from_json(cfg_json) if isinstance(cfg_json, dict) else cfg_json
            if not cfg.split_order:
                raise ValueError("star-tree requires a non-empty dimensionsSplitOrder")
            for d in cfg.split_order:
                m = col_metas.get(d)
                if m is None or m.encoding != "DICT" or not m.single_value:
                    raise ValueError(
                        f"star-tree split dim {d!r} must be a dict-encoded SV column")
            for fn, col in cfg.pairs():
                if (col == "*") != (fn == "count"):
                    raise ValueError(f"star-tree pair {fn}__{col}: '*' is COUNT-only")
                if col != "*" and col not in col_metas:
                    raise ValueError(f"star-tree pair references unknown column {col!r}")

            def decode_ids(col):
                m = col_metas[col]
                return bitpack.unpack(
                    writer.peek_buffer(f"{col}.fwd"), m.bits_per_value,
                    m.total_number_of_entries)

            dict_ids = {d: decode_ids(d) for d in cfg.split_order}
            raw_values = {}
            for fn, col in cfg.pairs():
                if col == "*" or col in raw_values:
                    continue
                m = col_metas[col]
                if m.encoding == "RAW":
                    raw_values[col] = writer.peek_buffer(f"{col}.fwd").view(
                        DataType(m.data_type).numpy_dtype)
                else:
                    d = deserialize_dictionary(
                        bytes(writer.peek_buffer(f"{col}.dict")),
                        DataType(m.data_type), m.cardinality)
                    ids = dict_ids.get(col)
                    raw_values[col] = d.take(ids if ids is not None else decode_ids(col))
            buffers, meta = build_star_tree(tree_id, cfg, dict_ids, raw_values)
            for name, arr in buffers:
                writer.add_buffer(name, np.ascontiguousarray(arr))
            metas.append(meta)
        return metas

    def _build_indexes(self, writer, columns, col_metas: dict[str, ColumnMetadata]):
        """Auxiliary indexes requested by TableConfig.indexing (reference:
        per-column IndexCreators invoked by SegmentColumnarIndexCreator).

        `is_sorted` dict columns need no stored sorted index — SortedIndex
        derives from the forward index at load time."""
        idx = self.table_config.indexing

        def add(col: str, bufs: list):
            for suffix, arr in bufs:
                writer.add_buffer(f"{col}.{suffix}", np.ascontiguousarray(arr))

        for col in idx.inverted_index_columns:
            m = col_metas.get(col)
            if m is None or m.encoding != "DICT":
                continue
            # flat dict-id stream works for SV and MV alike (MV: a doc is
            # posted under every value it holds — reference MV inverted index)
            ids = bitpack.unpack(
                writer.peek_buffer(f"{col}.fwd"), m.bits_per_value, m.total_number_of_entries)
            if not m.single_value:
                # entry stream → doc ids: CSR over entries, then map each
                # entry back to its document
                off = writer.peek_buffer(f"{col}.mvoff").view(np.uint32)
                doc_of_entry = np.repeat(
                    np.arange(len(off) - 1, dtype=np.int64), np.diff(off.astype(np.int64)))
                b = InvertedIndex.build(ids, m.cardinality)
                inv = InvertedIndex(b.offsets, doc_of_entry[b.docs].astype(np.uint32))
            else:
                inv = InvertedIndex.build(ids, m.cardinality)
            add(col, serialize_inverted(inv))

        for col in idx.range_index_columns:
            m = col_metas.get(col)
            if m is None or not m.single_value:
                continue
            if m.encoding == "DICT":
                # dict range queries ride the CSR inverted index (contiguous
                # dictId slice) — build one if not already requested
                if f"{col}.inv.off" not in writer.buffer_names():
                    ids = bitpack.unpack(
                        writer.peek_buffer(f"{col}.fwd"), m.bits_per_value,
                        m.total_number_of_entries)
                    add(col, serialize_inverted(InvertedIndex.build(ids, m.cardinality)))
            else:
                raw = writer.peek_buffer(f"{col}.fwd").view(
                    DataType(m.data_type).numpy_dtype)
                add(col, serialize_raw_range(RawRangeIndex.build(raw)))

        for col in idx.bloom_filter_columns:
            m = col_metas.get(col)
            if m is None:
                continue
            values = columns[col]
            flat = []
            for v in values:
                if isinstance(v, (list, tuple, np.ndarray)):
                    flat.extend(v)
                elif v is not None:
                    flat.append(v)
            add(col, serialize_bloom(BloomFilter.build(flat)))

        for col in idx.json_index_columns:
            if col not in columns:
                continue
            add(col, serialize_json_index(JsonIndex.build(columns[col])))

        for col in idx.text_index_columns:
            if col not in columns:
                continue
            from .indexes import TextIndex, serialize_text_index

            add(col, serialize_text_index(TextIndex.build(columns[col])))

        for col in idx.vector_index_columns:
            if col not in columns:
                continue
            from .indexes import VectorIndex, serialize_vector_index

            vecs = np.stack([np.asarray(v, dtype=np.float32)
                             for v in columns[col]])
            add(col, serialize_vector_index(VectorIndex.build(vecs)))

        for cfg in getattr(idx, "geo_index_configs", []):
            lat_col, lng_col = cfg["latColumn"], cfg["lngColumn"]
            if lat_col not in columns or lng_col not in columns:
                continue
            from .indexes import GeoGridIndex, serialize_geo_index

            lat = np.asarray(columns[lat_col], dtype=np.float64)
            lng = np.asarray(columns[lng_col], dtype=np.float64)
            geo = GeoGridIndex.build(lat, lng,
                                     float(cfg.get("resolutionDeg", 0.5)))
            add(f"{lat_col}__{lng_col}", serialize_geo_index(geo))

        if getattr(idx, "custom_index_configs", None):
            from .index_spi import build_custom_indexes

            for name, arr in build_custom_indexes(columns,
                                                  idx.custom_index_configs):
                writer.add_buffer(name, np.ascontiguousarray(arr))

    def _stamp_partition(self, meta: ColumnMetadata, pconf: dict, values) -> None:
        """Record which partitions this segment's values fall in
        (reference SegmentColumnarIndexCreator stamps ColumnPartitionMetadata
        from the column's partition config). Ids are computed over the
        DISTINCT values — a column plane's partition set equals the
        partition set of its unique values."""
        fn = get_partition_function(
            pconf["functionName"], int(pconf["numPartitions"]))
        if isinstance(values, np.ndarray) and values.dtype != object:
            uniq = np.unique(values)
        else:
            uniq = sorted({v for v in values if v is not None}, key=repr)
        parts = sorted({int(p) for p in fn.partitions_of(uniq)}) if len(uniq) else []
        meta.partition_function = fn.name
        meta.num_partitions = fn.num_partitions
        meta.partitions = parts
        meta.partition_id = parts[0] if len(parts) == 1 else None

    def _replace_nulls(self, values, spec) -> tuple[list, np.ndarray]:
        if isinstance(values, np.ndarray) and values.dtype != object:
            # numpy fast path: fixed-width arrays cannot hold None
            return values, np.zeros(len(values), dtype=bool)
        nulls = np.array([v is None for v in values], dtype=bool)
        if nulls.any():
            dv = spec.default_null_value
            values = [dv if v is None else v for v in values]
        return values, nulls

    def _build_sv_column(self, writer, name, spec, values, num_docs, raw: bool) -> ColumnMetadata:
        values, nulls = self._replace_nulls(values, spec)
        dt = spec.data_type
        codec = self.table_config.indexing.compression_configs.get(name)
        if codec == "CLP" and not (raw and dt.value == "STRING"):
            # validate at the misconfiguration, not as a KeyError deep in
            # the chunk-codec table at write time
            raise ValueError(
                f"column {name!r}: CLP encoding requires a STRING column "
                "listed in noDictionaryColumns")
        if raw and dt.is_fixed_width:
            arr = np.ascontiguousarray(values, dtype=dt.numpy_dtype)
            writer.add_buffer(f"{name}.fwd", arr, codec=codec)
            meta = ColumnMetadata(
                name=name, data_type=dt.value, field_type=spec.field_type.value,
                encoding="RAW", cardinality=0, bits_per_value=arr.dtype.itemsize * 8,
                min_value=arr.min() if num_docs else None,
                max_value=arr.max() if num_docs else None,
                is_sorted=bool(num_docs == 0 or np.all(np.diff(arr) >= 0)),
                total_number_of_entries=num_docs,
            )
        elif raw and codec == "CLP" and dt.value == "STRING":
            # log-structured encoding: template dictionary + variable
            # streams (reference CLPForwardIndexCreatorV1)
            from .clp import encode_column, serialize_clp

            col = encode_column(values)
            writer.add_buffer(f"{name}.fwd", serialize_clp(col))
            meta = ColumnMetadata(
                name=name, data_type=dt.value, field_type=spec.field_type.value,
                encoding="CLP", cardinality=0, bits_per_value=0,
                min_value=None, max_value=None, is_sorted=False,
                total_number_of_entries=num_docs)
        elif raw:
            # var-byte raw (STRING/BYTES/JSON): utf-8 stream + u64 offsets,
            # no dictionary required for selection (reference
            # VarByteChunkForwardIndexWriterV4)
            meta = self._build_var_byte_column(
                writer, name, spec, values, num_docs, codec)
        else:
            dictionary, dict_ids = build_dictionary(values, dt)
            bits = bitpack.num_bits_for_cardinality(dictionary.cardinality)
            writer.add_buffer(f"{name}.fwd", bitpack.pack(dict_ids, bits),
                              codec=codec)
            writer.add_buffer(f"{name}.dict", serialize_dictionary(dictionary))
            meta = ColumnMetadata(
                name=name, data_type=dt.value, field_type=spec.field_type.value,
                encoding="DICT", cardinality=dictionary.cardinality, bits_per_value=bits,
                min_value=dictionary.min_value, max_value=dictionary.max_value,
                is_sorted=bool(num_docs == 0 or np.all(np.diff(dict_ids) >= 0)),
                total_number_of_entries=num_docs,
            )
        if nulls.any():
            writer.add_buffer(f"{name}.nulls", bitpack.pack_bitmap(nulls))
            meta.has_nulls = True
        return meta

    def _build_var_byte_column(self, writer, name, spec, values, num_docs,
                               codec) -> ColumnMetadata:
        dt = spec.data_type
        is_bytes = dt.value == "BYTES"
        offsets = np.zeros(num_docs + 1, dtype=np.uint64)
        parts = []
        total = 0
        mn = mx = None
        is_sorted = True
        prev = None
        for i, v in enumerate(values):
            if is_bytes:
                b = bytes(v)
            else:
                v = str(v)
                b = v.encode("utf-8")
                v_cmp = v
            v_cmp = b if is_bytes else v
            parts.append(b)
            total += len(b)
            offsets[i + 1] = total
            if mn is None or v_cmp < mn:
                mn = v_cmp
            if mx is None or v_cmp > mx:
                mx = v_cmp
            if prev is not None and v_cmp < prev:
                is_sorted = False
            prev = v_cmp
        writer.add_buffer(f"{name}.fwd", b"".join(parts), codec=codec)
        writer.add_buffer(f"{name}.voff", offsets, codec=codec)
        return ColumnMetadata(
            name=name, data_type=dt.value, field_type=spec.field_type.value,
            encoding="RAW", cardinality=0, bits_per_value=0,
            min_value=mn, max_value=mx, is_sorted=is_sorted,
            total_number_of_entries=num_docs,
        )

    def _build_mv_column(self, writer, name, spec, values, num_docs) -> ColumnMetadata:
        """MV column: flatten value lists, dict-encode the stream, store u32 offsets.

        Device layout is produced at load time: a (num_docs, max_mv) padded
        dict-id matrix (pad = cardinality, an always-false sentinel for
        predicates). Reference: MV forward index
        (pinot-segment-local/.../readers/forward/*MVForwardIndexReader*).
        """
        dt = spec.data_type
        flat: list = []
        offsets = np.zeros(num_docs + 1, dtype=np.uint32)
        nulls = np.zeros(num_docs, dtype=bool)
        for i, v in enumerate(values):
            if v is None:
                nulls[i] = True
                v = [spec.default_null_value]
            elif not isinstance(v, (list, tuple, np.ndarray)):
                v = [v]
            flat.extend(v)
            offsets[i + 1] = len(flat)
        dictionary, dict_ids = build_dictionary(flat, dt)
        bits = bitpack.num_bits_for_cardinality(dictionary.cardinality)
        writer.add_buffer(f"{name}.fwd", bitpack.pack(dict_ids, bits))
        writer.add_buffer(f"{name}.dict", serialize_dictionary(dictionary))
        writer.add_buffer(f"{name}.mvoff", offsets)
        lens = np.diff(offsets.astype(np.int64))
        meta = ColumnMetadata(
            name=name, data_type=dt.value, field_type=spec.field_type.value,
            encoding="DICT", single_value=False,
            cardinality=dictionary.cardinality, bits_per_value=bits,
            min_value=dictionary.min_value, max_value=dictionary.max_value,
            total_number_of_entries=len(flat),
            max_number_of_multi_values=int(lens.max()) if num_docs else 0,
        )
        if nulls.any():
            writer.add_buffer(f"{name}.nulls", bitpack.pack_bitmap(nulls))
            meta.has_nulls = True
        return meta
