"""CLP-style log-message encoding.

Reference: the CLP forward index (pinot-segment-local/.../creator/impl/fwd/
CLPForwardIndexCreatorV1.java, built on the CLP paper's insight): machine
logs are a small set of TEMPLATES with variable tokens spliced in. A message
splits into

    logtype   — the template with placeholders (\\x11 dict var, \\x12 int,
                \\x13 float); template cardinality is tiny → dictionary id
    dictVars  — variable tokens containing letters (task_12, /api/v2/users)
    encVars   — pure numeric tokens, stored as their binary value

so "Task task_12 failed after 3.50s" becomes
logtype "Task \\x11 failed after \\x13s", dictVars [task_12], encVars [3.50].
Selected with ``compressionConfigs: {col: "CLP"}`` on a no-dictionary
STRING column; decoding reconstructs the exact original strings.
"""

from __future__ import annotations

import re
import struct

import numpy as np

ESC = "\x10"
DICT_VAR = "\x11"
INT_VAR = "\x12"
FLOAT_VAR = "\x13"
_SPECIALS = (ESC, DICT_VAR, INT_VAR, FLOAT_VAR)


def _esc(text: str) -> str:
    """Escape placeholder bytes occurring LITERALLY in log text (real CLP
    escapes them too) so decode can't mistake them for variable slots."""
    if not any(ch in text for ch in _SPECIALS):
        return text
    return "".join(ESC + ch if ch in _SPECIALS else ch for ch in text)

# a variable token: contains at least one digit; split on whitespace-ish
# boundaries the same way CLP's tokenizer does
_TOKEN_RE = re.compile(r"[^\s=:,;()\[\]{}\"']+")
_INT_RE = re.compile(r"[-+]?\d+\Z")
_FLOAT_RE = re.compile(r"[-+]?\d*\.\d+\Z")
_HAS_DIGIT_RE = re.compile(r"\d")


def encode_message(msg: str) -> tuple[str, list[str], list[tuple[str, str]]]:
    """→ (logtype, dict_vars, enc_vars as (kind, literal))."""
    out = []
    dict_vars: list[str] = []
    enc_vars: list[tuple[str, str]] = []
    pos = 0
    for m in _TOKEN_RE.finditer(msg):
        tok = m.group(0)
        if not _HAS_DIGIT_RE.search(tok):
            continue
        if _INT_RE.match(tok):
            kind, ph = "i", INT_VAR
        elif _FLOAT_RE.match(tok):
            kind, ph = "f", FLOAT_VAR
        else:
            kind, ph = None, DICT_VAR
        out.append(_esc(msg[pos:m.start()]))
        out.append(ph)
        pos = m.end()
        if kind is None:
            dict_vars.append(tok)
        else:
            enc_vars.append((kind, tok))
    out.append(_esc(msg[pos:]))
    return "".join(out), dict_vars, enc_vars


def decode_message(logtype: str, dict_vars: list[str],
                   enc_vars: list[tuple[str, str]]) -> str:
    out = []
    di = ei = 0
    i, n = 0, len(logtype)
    while i < n:
        ch = logtype[i]
        if ch == ESC and i + 1 < n:
            out.append(logtype[i + 1])  # escaped literal placeholder byte
            i += 2
            continue
        if ch == DICT_VAR:
            out.append(dict_vars[di])
            di += 1
        elif ch in (INT_VAR, FLOAT_VAR):
            out.append(enc_vars[ei][1])
            ei += 1
        else:
            out.append(ch)
        i += 1
    return "".join(out)


class ClpColumn:
    """Encoded form of one string column."""

    def __init__(self, logtypes, type_ids, var_dict, var_ids, var_offsets,
                 enc_blob, enc_offsets):
        self.logtypes = logtypes        # list[str] templates (sorted unique)
        self.type_ids = type_ids        # (n,) int32 template id per doc
        self.var_dict = var_dict        # list[str] unique dict vars
        self.var_ids = var_ids          # flat int32 dict-var ids
        self.var_offsets = var_offsets  # (n+1,) int64 into var_ids
        self.enc_blob = enc_blob        # utf-8 literal stream of numeric vars
        self.enc_offsets = enc_offsets  # flat byte offsets, one list per doc
        # enc_offsets layout: (n+1,) int64 into a per-doc count prefix over
        # the token table below
        self.num_docs = len(type_ids)

    def decode_all(self) -> np.ndarray:
        out = np.empty(self.num_docs, dtype=object)
        tokens = self.enc_blob.split("\x00") if self.enc_blob else []
        for d in range(self.num_docs):
            lt = self.logtypes[self.type_ids[d]]
            dvars = [self.var_dict[self.var_ids[j]]
                     for j in range(self.var_offsets[d], self.var_offsets[d + 1])]
            evars = [("x", tokens[j])
                     for j in range(self.enc_offsets[d], self.enc_offsets[d + 1])]
            out[d] = decode_message(lt, dvars, evars)
        return out


def encode_column(values) -> ClpColumn:
    lt_index: dict[str, int] = {}
    vd_index: dict[str, int] = {}
    type_ids = np.empty(len(values), dtype=np.int32)
    var_ids: list[int] = []
    var_offsets = np.zeros(len(values) + 1, dtype=np.int64)
    enc_tokens: list[str] = []
    enc_offsets = np.zeros(len(values) + 1, dtype=np.int64)
    for d, v in enumerate(values):
        lt, dvars, evars = encode_message("" if v is None else str(v))
        tid = lt_index.setdefault(lt, len(lt_index))
        type_ids[d] = tid
        for t in dvars:
            var_ids.append(vd_index.setdefault(t, len(vd_index)))
        var_offsets[d + 1] = len(var_ids)
        for _kind, literal in evars:
            enc_tokens.append(literal)
        enc_offsets[d + 1] = len(enc_tokens)
    return ClpColumn(
        list(lt_index), type_ids, list(vd_index),
        np.asarray(var_ids, dtype=np.int32), var_offsets,
        "\x00".join(enc_tokens), enc_offsets)


# -- buffer (de)serialization -------------------------------------------------


def _pack_strs(strs: list[str]) -> bytes:
    """Length-prefixed strings — tokens may contain ANY byte (including
    NUL), so a delimiter-based join would corrupt them."""
    out = bytearray(struct.pack("<I", len(strs)))
    for s in strs:
        b = s.encode("utf-8")
        out += struct.pack("<I", len(b)) + b
    return bytes(out)


class _Rd:
    def __init__(self, b):
        self.b = b
        self.p = 0

    def take(self, n):
        out = self.b[self.p:self.p + n]
        self.p += n
        return out


def _unpack_strs(r: _Rd) -> list[str]:
    (count,) = struct.unpack("<I", r.take(4))
    out = []
    for _ in range(count):
        (n,) = struct.unpack("<I", r.take(4))
        out.append(bytes(r.take(n)).decode("utf-8"))
    return out


def serialize_clp(col: ClpColumn) -> bytes:
    out = bytearray()
    out += _pack_strs(col.logtypes)
    out += _pack_strs(col.var_dict)
    enc = col.enc_blob.encode("utf-8")
    out += struct.pack("<Q", len(enc)) + enc
    for arr, dtype in ((col.type_ids, np.int32), (col.var_ids, np.int32),
                       (col.var_offsets, np.int64), (col.enc_offsets, np.int64)):
        a = np.ascontiguousarray(arr, dtype=dtype)
        out += struct.pack("<Q", a.size) + a.tobytes()
    return bytes(out)


def deserialize_clp(blob: bytes) -> ClpColumn:
    r = _Rd(memoryview(blob))
    logtypes = _unpack_strs(r)
    var_dict = _unpack_strs(r)
    (elen,) = struct.unpack("<Q", r.take(8))
    enc_blob = bytes(r.take(elen)).decode("utf-8")
    arrays = []
    for dtype in (np.int32, np.int32, np.int64, np.int64):
        (n,) = struct.unpack("<Q", r.take(8))
        arrays.append(np.frombuffer(r.take(n * np.dtype(dtype).itemsize),
                                    dtype=dtype))
    type_ids, var_ids, var_offsets, enc_offsets = arrays
    return ClpColumn(logtypes, type_ids, var_dict, var_ids, var_offsets,
                     enc_blob, enc_offsets)
