"""Chunk compression for segment buffers.

Reference: ChunkCompressionType (pinot-segment-spi/.../compression/
ChunkCompressionType.java:22 — PASS_THROUGH / SNAPPY / ZSTANDARD / LZ4 /
GZIP) and the chunked raw forward indexes that use it
(pinot-segment-local/.../io/writer/impl/BaseChunkForwardIndexWriter.java).

Container layout (self-describing, little-endian):

    magic  b"PTCC"
    u8     codec id
    u8[3]  reserved
    u32    chunk size (uncompressed bytes per chunk)
    u32    num chunks
    u64    total uncompressed size
    u32[n] compressed chunk sizes
    bytes  chunk payloads back-to-back

LZ4 (block format) and Snappy are native C++ (native/pinot_native.cpp,
clean-room from the public format specs) with pure-Python decoders as
fallback; the fallback *encoders* emit spec-valid literal-only streams, so
a toolchain-less host still writes decodable segments. ZSTANDARD uses the
``zstandard`` package, GZIP uses zlib.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from . import native_bridge

MAGIC = b"PTCC"
DEFAULT_CHUNK = 1 << 20

CODEC_IDS = {"PASS_THROUGH": 0, "LZ4": 1, "ZSTANDARD": 2, "GZIP": 3, "SNAPPY": 4}
CODEC_NAMES = {v: k for k, v in CODEC_IDS.items()}


def codecs_available() -> list[str]:
    out = ["PASS_THROUGH", "LZ4", "GZIP", "SNAPPY"]
    try:
        import zstandard  # noqa: F401

        out.insert(2, "ZSTANDARD")
    except ImportError:
        pass
    return out


# -- chunk codecs ------------------------------------------------------------


def _zstd():
    import zstandard

    return zstandard


def _compress_chunk(codec: str, chunk: bytes) -> bytes:
    if codec == "PASS_THROUGH":
        return chunk
    if codec == "LZ4":
        out = native_bridge.lz4_compress(chunk)
        return out if out is not None else _lz4_compress_literal(chunk)
    if codec == "SNAPPY":
        out = native_bridge.snappy_compress(chunk)
        return out if out is not None else _snappy_compress_literal(chunk)
    if codec == "ZSTANDARD":
        return _zstd().ZstdCompressor(level=3).compress(chunk)
    if codec == "GZIP":
        return zlib.compress(chunk, 6)
    raise ValueError(f"unknown compression codec {codec!r}")


def _decompress_chunk(codec: str, blob: bytes, raw_size: int) -> bytes:
    if codec == "PASS_THROUGH":
        return blob
    if codec == "LZ4":
        out = native_bridge.lz4_decompress(blob, raw_size)
        return out if out is not None else lz4_decompress_py(blob, raw_size)
    if codec == "SNAPPY":
        out = native_bridge.snappy_decompress(blob, raw_size)
        return out if out is not None else snappy_decompress_py(blob, raw_size)
    if codec == "ZSTANDARD":
        return _zstd().ZstdDecompressor().decompress(blob, max_output_size=raw_size)
    if codec == "GZIP":
        return zlib.decompress(blob)
    raise ValueError(f"unknown compression codec {codec!r}")


# -- container ---------------------------------------------------------------


def compress_buffer(data: bytes | np.ndarray, codec: str,
                    chunk_size: int = DEFAULT_CHUNK) -> bytes:
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    codec = codec.upper()
    cid = CODEC_IDS[codec]
    n = len(data)
    num_chunks = max(1, (n + chunk_size - 1) // chunk_size)
    chunks = [
        _compress_chunk(codec, data[i * chunk_size:(i + 1) * chunk_size])
        for i in range(num_chunks)
    ]
    head = MAGIC + struct.pack("<B3xIIQ", cid, chunk_size, num_chunks, n)
    sizes = struct.pack(f"<{num_chunks}I", *(len(c) for c in chunks))
    return head + sizes + b"".join(chunks)


def is_compressed(blob: bytes | memoryview) -> bool:
    return bytes(blob[:4]) == MAGIC


def decompress_buffer(blob: bytes | memoryview | np.ndarray) -> bytes:
    if isinstance(blob, np.ndarray):
        blob = blob.tobytes()
    blob = bytes(blob)
    if blob[:4] != MAGIC:
        raise ValueError("not a PTCC compressed buffer")
    cid, chunk_size, num_chunks, raw_size = struct.unpack_from("<B3xIIQ", blob, 4)
    codec = CODEC_NAMES[cid]
    sizes = struct.unpack_from(f"<{num_chunks}I", blob, 24)
    off = 24 + 4 * num_chunks
    out = []
    remaining = raw_size
    for i, sz in enumerate(sizes):
        this_raw = min(chunk_size, remaining)
        out.append(_decompress_chunk(codec, blob[off:off + sz], this_raw))
        if len(out[-1]) != this_raw:
            raise ValueError(
                f"chunk {i}: decompressed {len(out[-1])} bytes, expected {this_raw}")
        off += sz
        remaining -= this_raw
    return b"".join(out)


# -- pure-Python LZ4 block format (fallback) ---------------------------------


def lz4_decompress_py(src: bytes, dst_cap: int) -> bytes:
    out = bytearray()
    i, n = 0, len(src)
    while i < n:
        token = src[i]
        i += 1
        lit = token >> 4
        if lit == 15:
            while True:
                b = src[i]
                i += 1
                lit += b
                if b != 255:
                    break
        out += src[i:i + lit]
        i += lit
        if i >= n:
            break
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0 or offset > len(out):
            raise ValueError("corrupt LZ4 stream")
        mlen = token & 15
        if mlen == 15:
            while True:
                b = src[i]
                i += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        start = len(out) - offset
        for k in range(mlen):  # byte-wise: overlapping matches replicate
            out.append(out[start + k])
    if len(out) > dst_cap:
        raise ValueError("LZ4 output exceeds expected size")
    return bytes(out)


def _lz4_compress_literal(data: bytes) -> bytes:
    """Spec-valid literals-only LZ4 stream (fallback encoder)."""
    n = len(data)
    out = bytearray()
    if n < 15:
        out.append(n << 4)
    else:
        out.append(0xF0)
        rest = n - 15
        while rest >= 255:
            out.append(255)
            rest -= 255
        out.append(rest)
    out += data
    return bytes(out)


# -- pure-Python Snappy (fallback) -------------------------------------------


def _uvarint(src: bytes, i: int) -> tuple[int, int]:
    v = shift = 0
    while True:
        b = src[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def snappy_decompress_py(src: bytes, dst_cap: int) -> bytes:
    expect, i = _uvarint(src, 0)
    if expect > dst_cap:
        raise ValueError("snappy output exceeds expected size")
    out = bytearray()
    n = len(src)
    while i < n:
        tag = src[i]
        i += 1
        kind = tag & 3
        if kind == 0:
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                length = int.from_bytes(src[i:i + extra], "little") + 1
                i += extra
            out += src[i:i + length]
            i += length
            continue
        if kind == 1:
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | src[i]
            i += 1
        elif kind == 2:
            length = (tag >> 2) + 1
            offset = src[i] | (src[i + 1] << 8)
            i += 2
        else:
            length = (tag >> 2) + 1
            offset = int.from_bytes(src[i:i + 4], "little")
            i += 4
        if offset == 0 or offset > len(out):
            raise ValueError("corrupt snappy stream")
        start = len(out) - offset
        for k in range(length):
            out.append(out[start + k])
    if len(out) != expect:
        raise ValueError("snappy length mismatch")
    return bytes(out)


def _snappy_compress_literal(data: bytes) -> bytes:
    n = len(data)
    out = bytearray()
    v = n
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            break
    # one literal element (length fits in 4 extra bytes)
    pos = 0
    while pos < n:
        chunk = min(n - pos, 1 << 24)
        line = chunk - 1
        if line < 60:
            out.append(line << 2)
        elif line < (1 << 8):
            out.append(60 << 2)
            out += line.to_bytes(1, "little")
        elif line < (1 << 16):
            out.append(61 << 2)
            out += line.to_bytes(2, "little")
        else:
            out.append(62 << 2)
            out += line.to_bytes(3, "little")
        out += data[pos:pos + chunk]
        pos += chunk
    return bytes(out)
