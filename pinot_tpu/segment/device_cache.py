"""HBM residency manager: host column planes → padded device arrays.

The TPU-build analogue of the reference's mmap'd PinotDataBuffer +
DataFetcher (pinot-core/.../common/DataFetcher.java:48): instead of batch
point-reads per 10K-doc block, each referenced column is transferred to HBM
ONCE per segment and cached (BASELINE's "HBM segment cache"). Planes are
padded to a shape bucket (next power of two) so differently-sized segments of
similar size share compiled kernels; `num_docs` rides along as a runtime
scalar.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..spi.data_types import DataType
from .loader import ImmutableSegment

_MIN_PAD = 1 << 13

# Per-thread transfer attribution for device-phase tracing: armed by
# reset_transfer_stats() at dispatch start (only when a trace is active),
# read back into span attributes. When disarmed the upload path pays one
# thread-local getattr — nothing else.
_TRANSFER_TL = threading.local()


def reset_transfer_stats() -> None:
    """Arm per-thread transfer counters (tracing on)."""
    _TRANSFER_TL.stats = {"transferBytes": 0, "transfers": {},
                          "stackHits": 0, "stackMisses": 0}


def clear_transfer_stats() -> None:
    _TRANSFER_TL.stats = None


def transfer_stats() -> Optional[dict]:
    """Counters since the last reset on this thread, or None when off:
    host→device bytes total + per-(column, plane-kind) slot, stacked-view
    plane cache hits/misses."""
    return getattr(_TRANSFER_TL, "stats", None)


def _note_upload(key: tuple[str, str], nbytes: int) -> None:
    stats = getattr(_TRANSFER_TL, "stats", None)
    if stats is not None:
        stats["transferBytes"] += nbytes
        slot = f"{key[0]}:{key[1]}"
        stats["transfers"][slot] = stats["transfers"].get(slot, 0) + nbytes


def _note_stack(hit: bool) -> None:
    stats = getattr(_TRANSFER_TL, "stats", None)
    if stats is not None:
        stats["stackHits" if hit else "stackMisses"] += 1


def packed_hbm_enabled() -> bool:
    """Packed id planes default ON for the TPU backend (bandwidth-bound:
    reading bits/32 of the bytes beats the in-register decode cost) and OFF
    on CPU; PINOT_TPU_PACKED_HBM=0/1 overrides."""
    env = os.environ.get("PINOT_TPU_PACKED_HBM")
    if env is not None:
        return env not in ("0", "false", "")
    from ..ops.mxu_groupby import backend_platform

    return backend_platform() not in ("cpu",)


def pad_bucket(n: int) -> int:
    """Next power of two ≥ n (min 8192) — the kernel shape bucket."""
    b = _MIN_PAD
    while b < n:
        b <<= 1
    return b


class SegmentDeviceView:
    """Device-resident planes for one segment. Created once, reused across
    queries (the reference's segment stays mmap-resident similarly)."""

    def __init__(self, segment: ImmutableSegment, device=None):
        self.segment = segment
        self.device = device
        self.padded = pad_bucket(max(1, segment.num_docs))
        self._planes: dict[tuple[str, str], jnp.ndarray] = {}
        # (column,"ids_packed") → dtype width (8|16) of narrow planes
        self.packed_bits: dict[tuple[str, str], int] = {}

    def _put(self, key: tuple[str, str], host: np.ndarray) -> jnp.ndarray:
        """Upload-and-cache. Returns the plane via a LOCAL reference (never
        a second dict read): OOM-relief eviction (engine/oom.py) may clear
        _planes concurrently with readers, which must keep their array and
        at worst re-upload next time — not die on a missing key."""
        arr = self._planes.get(key)
        if arr is None:
            arr = jnp.asarray(host)
            if self.device is not None:
                arr = jax.device_put(arr, self.device)
            self._planes[key] = arr
            _note_upload(key, arr.nbytes)
        return arr

    def dict_ids(self, column: str) -> jnp.ndarray:
        """Padded int32 dict-id plane (pad value 0; rows masked by num_docs)."""
        m = self.segment.column_metadata(column)
        if not m.single_value:
            return self.mv_dict_ids(column)
        key = (column, "ids")
        cached = self._planes.get(key)
        if cached is not None:
            return cached
        ids = self.segment.get_dict_ids(column)
        out = np.zeros(self.padded, dtype=np.int32)
        out[: ids.shape[0]] = ids
        return self._put(key, out)

    def dict_ids_packed(self, column: str):
        """(plane, width) with the id plane stored NARROW in HBM: uint8 for
        ≤8-bit ids, uint16 for ≤16-bit — 4x/2x less residency and read
        bandwidth than int32, widened in-register by the kernel (a free
        elementwise astype that XLA fuses). Sub-byte bitstream decode was
        measured 1000x slower than the narrow-plane astype on TPU (lane
        relayout), so byte alignment is the TPU-correct packing. Falls back
        to the plain int32 plane (width 0) for MV columns / wide ids."""
        m = self.segment.column_metadata(column)
        bits = getattr(m, "bits_per_value", 32) or 32
        if not m.single_value or bits > 16 or not packed_hbm_enabled():
            return self.dict_ids(column), 0
        width = 8 if bits <= 8 else 16
        key = (column, "ids_packed")  # distinct from the plain plane key
        cached = self._planes.get(key)
        if cached is not None:
            return cached, self.packed_bits.get(key, width)
        ids = self.segment.get_dict_ids(column)
        out = np.zeros(self.padded,
                       dtype=np.uint8 if width == 8 else np.uint16)
        out[: ids.shape[0]] = ids
        arr = self._put(key, out)
        self.packed_bits[key] = width
        return arr, width

    def mv_dict_ids(self, column: str) -> jnp.ndarray:
        key = (column, "mvids")
        cached = self._planes.get(key)
        if cached is not None:
            return cached
        mat = self.segment.get_mv_dict_id_matrix(column)
        card = self.segment.column_metadata(column).cardinality
        out = np.full((self.padded, mat.shape[1]), card, dtype=np.int32)
        out[: mat.shape[0]] = mat
        return self._put(key, out)

    def raw(self, column: str) -> jnp.ndarray:
        key = (column, "raw")
        cached = self._planes.get(key)
        if cached is not None:
            return cached
        vals = self.segment.get_raw(column)
        out = np.zeros(self.padded, dtype=vals.dtype)
        out[: vals.shape[0]] = vals
        return self._put(key, out)

    def raw_f32_rebased(self, column: str) -> jnp.ndarray:
        """(v - column_min) as an f32 plane — the histogram-binning view
        of a raw float column. Rebasing BEFORE the f32 cast keeps
        large-magnitude narrow-range columns (epoch millis) at full range
        precision; the f32 plane costs half the f64 plane's HBM residency
        and read bandwidth."""
        key = (column, "rawf32r")
        cached = self._planes.get(key)
        if cached is not None:
            return cached
        vals = self.segment.get_raw(column)
        base = float(self.segment.column_metadata(column).min_value)
        out = np.zeros(self.padded, dtype=np.float32)
        out[: vals.shape[0]] = (vals - base).astype(np.float32)
        return self._put(key, out)

    def dict_values(self, column: str) -> jnp.ndarray:
        """Numeric dictionary shipped to device for on-device decode."""
        key = (column, "dict")
        cached = self._planes.get(key)
        if cached is not None:
            return cached
        d = self.segment.get_dictionary(column)
        assert DataType(self.segment.column_metadata(column).data_type).is_fixed_width, (
            f"{column}: var-width dictionaries stay host-side"
        )
        return self._put(key, np.ascontiguousarray(d.values))

    def null_plane(self, column: str) -> jnp.ndarray:
        key = (column, "null")
        cached = self._planes.get(key)
        if cached is not None:
            return cached
        nulls = self.segment.get_null_bitmap(column)
        out = np.zeros(self.padded, dtype=bool)
        if nulls is not None:
            out[: nulls.shape[0]] = nulls
        return self._put(key, out)

    def nbytes(self) -> int:
        # snapshot: _put inserts without the cache lock, and the budget
        # accounting iterates here under it — iterate a copied list so a
        # concurrent insert can't raise "dict changed size during iteration"
        return sum(p.nbytes for p in list(self._planes.values()))

    def evict(self) -> None:
        self._planes.clear()


class StackedSegmentView:
    """Device-resident [S, ...] planes stacked from a batch FAMILY of
    same-bucket member views (engine/executor.py:dispatch_plan_batch).
    Stacks are DERIVED data: each plane is a `jnp.stack` of the members'
    cached per-segment planes, cached here so repeated queries over the
    same family skip the device-side stack copies. They count against the
    owning DeviceSegmentCache's byte budget and are evicted wholesale
    under HBM pressure — rebuilding a stack only needs the (cheaper,
    also-cached) member planes, so relief still converges."""

    def __init__(self, key: tuple, names: tuple = ()):
        self.key = key  # tuple of member id(segment)s
        # member segment NAMES ride along so departure-time eviction can
        # find stale stacks even after the member objects are gone (a
        # rebalanced-away segment's id() no longer resolves to anything)
        self.names = frozenset(str(n) for n in names)
        self._planes: dict[tuple, jnp.ndarray] = {}

    def plane(self, plane_key: tuple, build) -> jnp.ndarray:
        # same local-reference discipline as SegmentDeviceView._put:
        # OOM relief may clear _planes concurrently with readers
        arr = self._planes.get(plane_key)
        if arr is None:
            arr = build()
            self._planes[plane_key] = arr
            _note_stack(hit=False)
        else:
            _note_stack(hit=True)
        return arr

    def nbytes(self) -> int:
        # same snapshot discipline as SegmentDeviceView.nbytes: plane()
        # mutates _planes lock-free on every batched gather. Per-DEVICE
        # accounting: a mesh-sharded stack costs each chip only its shard,
        # and the budget models one device's HBM.
        return sum(device_nbytes(p) for p in list(self._planes.values()))

    def evict(self) -> None:
        self._planes.clear()


def device_nbytes(arr) -> int:
    """Budget cost of one cached array against a SINGLE device's HBM: the
    max bytes any one device holds. Single-device arrays cost their full
    nbytes; mesh-sharded stacks cost ~nbytes/ndev per chip; replicated
    arrays still cost full nbytes everywhere."""
    n = int(getattr(arr, "nbytes", 0))
    try:
        if len(arr.sharding.device_set) <= 1:
            return n
        per: dict = {}
        for sh in arr.addressable_shards:
            did = sh.device.id
            per[did] = per.get(did, 0) + int(sh.data.nbytes)
        return max(per.values()) if per else n
    except Exception:
        return n


class DeviceSegmentCache:
    """Process-wide segment→device-view cache with byte-budget eviction
    (reference precedent: mmap'd segments stay resident until dropped)."""

    def __init__(self, budget_bytes: Optional[int] = None, device=None):
        self.budget_bytes = budget_bytes
        self.device = device
        self._views: dict[int, SegmentDeviceView] = {}
        self._order: list[int] = []  # LRU
        self._stacks: dict[tuple, StackedSegmentView] = {}
        self._stack_order: list[tuple] = []  # LRU over stacked views
        # device-resident cached partial results (cache/partial.py tier 2:
        # sparse group tables kept in HBM so a warm repeat query feeds the
        # device combine with zero dispatches). key → (arrays, nbytes,
        # segment_name); insertion order doubles as LRU via move-to-end.
        self._partials: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.partial_hits = 0
        self.partial_misses = 0
        # lifetime pressure-eviction count (budget LRU + OOM relief),
        # surfaced in hbm_stats() / dispatch-span HBM snapshots
        self.evictions = 0
        # flight-recorder attribution: which TIER paid each eviction and
        # WHY (budget LRU vs OOM relief vs lineage invalidation), plus
        # per-tier residency high-water marks — the evidence trail for
        # sizing PINOT_TPU_HBM_BUDGET_BYTES (GET /debug/compiles)
        self.eviction_stats = {"views": 0, "stacks": 0, "partials": 0,
                               "budget": 0, "oom": 0, "lineage": 0}
        self._hwm = {"views": 0, "stacks": 0, "partials": 0, "total": 0}
        # guards _views/_order/_stacks: concurrent queries share this cache,
        # and OOM-relief eviction (engine/oom.py) races view()/_maybe_evict()
        self._lock = threading.Lock()

    def view(self, segment: ImmutableSegment) -> SegmentDeviceView:
        key = id(segment)
        with self._lock:
            if key not in self._views:
                self._views[key] = SegmentDeviceView(segment, self.device)
            if key in self._order:
                self._order.remove(key)
            self._order.append(key)
            self._maybe_evict()
            return self._views[key]

    def stacked_view(self, segments: list) -> StackedSegmentView:
        """Get-or-create the stacked [S, ...] view for a batch family
        (identified by its ordered member segments). Realtime snapshot
        views are keyed by (name, snapshot_generation) instead of id():
        snapshot objects are fresh per query, but an unchanged generation
        has byte-identical plane contents, so warm repeats reuse the
        cached stack. A newer generation supersedes the old stack — the
        stale one is evicted eagerly (it can never be requested again).
        A mutable object WITHOUT a pinned generation still gets an
        uncached view (could never be hit again; would only pin dead HBM
        bytes until eviction)."""
        members = []
        rt_names = set()
        uncached = False
        for s in segments:
            if getattr(s, "is_mutable", False):
                gen = getattr(s, "snapshot_generation", None)
                if gen is None:
                    uncached = True
                    members.append(id(s))
                else:
                    name = str(getattr(s, "name", ""))
                    rt_names.add(name)
                    members.append(("rt", name, gen))
            else:
                members.append(id(s))
        key = tuple(members)
        names = tuple(getattr(s, "name", "") for s in segments)
        if uncached:
            return StackedSegmentView(key, names)
        with self._lock:
            if rt_names and key not in self._stacks:
                # superseded generations of the same consuming segment(s)
                for skey in [k for k, s in self._stacks.items()
                             if k != key and any(
                                 isinstance(m, tuple) and len(m) == 3
                                 and m[0] == "rt" and m[1] in rt_names
                                 for m in k)]:
                    self._stacks.pop(skey).evict()
                    if skey in self._stack_order:
                        self._stack_order.remove(skey)
                    self.evictions += 1
                    self.eviction_stats["stacks"] += 1
                    self.eviction_stats["lineage"] += 1
            sv = self._stacks.get(key)
            if sv is None:
                sv = self._stacks[key] = StackedSegmentView(key, names)
            if key in self._stack_order:
                self._stack_order.remove(key)
            self._stack_order.append(key)
            # _maybe_evict never drops the just-touched (last-ordered)
            # stack, and sv is a local reference regardless — the return
            # cannot KeyError under budget pressure
            self._maybe_evict()
            return sv

    def warm(self, segment: ImmutableSegment,
             columns: Optional[list] = None) -> int:
        """Pre-upload a segment's column planes to HBM so the first query
        skips the host→device transfer (reference: segment preload /
        warm-up on load). Returns planes uploaded. Dict-encoded columns
        warm their narrow id planes + dictionary values; raw columns warm
        the value plane. Errors are the caller's to handle (warming is
        best-effort by policy, not by silent excepts)."""
        v = self.view(segment)
        n = 0
        for col in (columns or segment.columns()):
            m = segment.column_metadata(col)
            if m.encoding == "DICT":
                v.dict_ids_packed(col) if m.single_value else v.dict_ids(col)
                n += 1
                if np.asarray(segment.get_dictionary(col).values).dtype.kind \
                        in "iuf":
                    v.dict_values(col)
                    n += 1
            else:
                v.raw(col)
                n += 1
                if m.data_type in ("FLOAT", "DOUBLE") and m.single_value \
                        and m.min_value is not None:
                    # percentile histograms bin from the f32 shadow plane
                    # (plan.py rawf32r) — warm it so the first q5-shaped
                    # query skips a whole-column convert + upload. Pins
                    # 1.5x the raw plane's bytes for float columns; the
                    # budget-driven eviction handles pressure.
                    v.raw_f32_rebased(col)
                    n += 1
        return n

    # -- device-resident cached partials (cache tier 2) ---------------------
    def put_partial(self, key: tuple, arrays: tuple,
                    segment_name: str) -> None:
        """Register a cached partial result (tuple of device arrays — e.g.
        a sparse group table's key/count/state columns) against the HBM
        budget. Partials are DERIVED data like stacks: under pressure they
        evict before any column plane, newest included."""
        nbytes = sum(int(getattr(a, "nbytes", 0)) for a in arrays)
        with self._lock:
            if self.budget_bytes is not None and nbytes > self.budget_bytes:
                return
            old = self._partials.pop(key, None)
            self._partials[key] = (tuple(arrays), nbytes, str(segment_name))
            if old is None:
                self._maybe_evict()

    def get_partial(self, key: tuple) -> Optional[tuple]:
        with self._lock:
            ent = self._partials.get(key)
            if ent is None:
                self.partial_misses += 1
                return None
            self._partials.move_to_end(key)
            self.partial_hits += 1
            return ent[0]

    def drop_partials(self, segment_name: Optional[str] = None) -> int:
        """Evict cached partials — all of them, or only those derived from
        ``segment_name`` (lineage events: segment replace/delete)."""
        with self._lock:
            if segment_name is None:
                n = len(self._partials)
                self._partials.clear()
            else:
                stale = [k for k, ent in self._partials.items()
                         if ent[2] == str(segment_name)]
                for k in stale:
                    del self._partials[k]
                n = len(stale)
            self.evictions += n
            self.eviction_stats["partials"] += n
            self.eviction_stats["lineage"] += n
            return n

    def drop(self, segment: ImmutableSegment) -> None:
        """Release a retired segment's device planes (call on segment drop —
        reference: segment replace/delete in BaseTableDataManager)."""
        key = id(segment)
        name = getattr(segment, "name", None)
        with self._lock:
            victims = 0
            v = self._views.pop(key, None)
            if v is not None:
                v.evict()
                victims += 1
                self.eviction_stats["views"] += 1
            if key in self._order:
                self._order.remove(key)
            # any stack containing the dropped segment is stale — match by
            # member id AND by member name: a stack built from an earlier
            # incarnation of this segment (repair replaced the object,
            # server restart) holds dead ids that only the name resolves
            for skey in [k for k, s in self._stacks.items()
                         if key in k
                         or (name is not None and str(name) in s.names)]:
                self._stacks.pop(skey).evict()
                self._stack_order.remove(skey)
                victims += 1
                self.eviction_stats["stacks"] += 1
            if name is not None:
                for pkey in [k for k, ent in self._partials.items()
                             if ent[2] == str(name)]:
                    del self._partials[pkey]
                    victims += 1
                    self.eviction_stats["partials"] += 1
            self.evictions += victims
            self.eviction_stats["lineage"] += victims

    def drop_named(self, segment_name: str) -> int:
        """Release device planes for EVERY cached view/stack/partial derived
        from a segment with this NAME — the departure path when the live
        object is no longer in hand (the server lost it mid-move, a repair
        replaced it, or the hosting instance died and a sibling converges).
        Views and stacks are keyed by id(segment), so without the object
        only the name can find them; a stacked [S, N] batch-family plane
        that outlives a moved-away segment would otherwise pin its HBM
        bytes until budget pressure. Conservative by design: another live
        copy of the same-named segment just re-uploads on next touch.
        Returns bytes freed."""
        name = str(segment_name)
        freed = victims = 0
        with self._lock:
            dead = [k for k, v in self._views.items()
                    if str(getattr(v.segment, "name", "")) == name]
            for key in dead:
                v = self._views.pop(key)
                freed += v.nbytes()
                v.evict()
                if key in self._order:
                    self._order.remove(key)
                victims += 1
                self.eviction_stats["views"] += 1
            dead_ids = set(dead)
            for skey in [k for k, s in self._stacks.items()
                         if name in s.names or dead_ids.intersection(k)]:
                s = self._stacks.pop(skey)
                freed += s.nbytes()
                s.evict()
                if skey in self._stack_order:
                    self._stack_order.remove(skey)
                victims += 1
                self.eviction_stats["stacks"] += 1
            for pkey in [k for k, ent in self._partials.items()
                         if ent[2] == name]:
                freed += self._partials[pkey][1]
                del self._partials[pkey]
                victims += 1
                self.eviction_stats["partials"] += 1
            self.evictions += victims
            self.eviction_stats["lineage"] += victims
        return freed

    def evict_all_except(self, keep_segment=None) -> tuple[int, int]:
        """HBM-pressure relief (engine/oom.py): evict every cached view
        except ``keep_segment``'s. Returns (bytes_freed, victims)."""
        keep_key = id(keep_segment) if keep_segment is not None else None
        freed = victims = 0
        with self._lock:
            # cached partials are pure derived data — cheapest to shed
            for pkey in list(self._partials):
                freed += self._partials.pop(pkey)[1]
                victims += 1
                self.eviction_stats["partials"] += 1
            # stacks next: derived [S, N] copies, always safe to rebuild
            for skey in list(self._stacks):
                freed += self._stacks[skey].nbytes()
                self._stacks.pop(skey).evict()
                victims += 1
                self.eviction_stats["stacks"] += 1
            self._stack_order.clear()
            for key in list(self._views):
                if key == keep_key:
                    continue
                freed += self._views[key].nbytes()
                self._views[key].evict()
                del self._views[key]
                if key in self._order:
                    self._order.remove(key)
                victims += 1
                self.eviction_stats["views"] += 1
            self.evictions += victims
            self.eviction_stats["oom"] += victims
        return freed, victims

    def _note_hwm_locked(self, views_b: int, stacks_b: int,
                         partials_b: int) -> None:
        h = self._hwm
        if views_b > h["views"]:
            h["views"] = views_b
        if stacks_b > h["stacks"]:
            h["stacks"] = stacks_b
        if partials_b > h["partials"]:
            h["partials"] = partials_b
        total = views_b + stacks_b + partials_b
        if total > h["total"]:
            h["total"] = total

    def _maybe_evict(self) -> None:
        # caller holds self._lock
        views_b = sum(v.nbytes() for v in self._views.values())
        stacks_b = sum(s.nbytes() for s in self._stacks.values())
        partials_b = sum(ent[1] for ent in self._partials.values())
        # every budget check doubles as a high-water sample: the marks
        # describe true peak residency, not just scrape-time snapshots
        self._note_hwm_locked(views_b, stacks_b, partials_b)
        if self.budget_bytes is None:
            return
        total = views_b + stacks_b + partials_b
        # cached partials evict first (pure derived data, a miss only costs
        # a re-dispatch), LRU order and ALL of them evictable — unlike the
        # loops below, nothing here is load-bearing for an in-flight call
        while total > self.budget_bytes and self._partials:
            _, (_, freed, _) = self._partials.popitem(last=False)
            total -= freed
            self.evictions += 1
            self.eviction_stats["partials"] += 1
            self.eviction_stats["budget"] += 1
        # stacks next: they duplicate member planes, so dropping a
        # stack frees bytes without costing a host→device re-upload. Like
        # the views loop below, the most-recently-touched entry survives —
        # stacked_view() must not lose the stack it just registered.
        while total > self.budget_bytes and len(self._stack_order) > 1:
            victim = self._stack_order.pop(0)
            total -= self._stacks[victim].nbytes()
            self._stacks.pop(victim).evict()
            self.evictions += 1
            self.eviction_stats["stacks"] += 1
            self.eviction_stats["budget"] += 1
        while total > self.budget_bytes and len(self._order) > 1:
            victim = self._order.pop(0)
            total -= self._views[victim].nbytes()
            self._views[victim].evict()
            del self._views[victim]
            self.evictions += 1
            self.eviction_stats["views"] += 1
            self.eviction_stats["budget"] += 1

    def hbm_stats(self) -> dict:
        """Residency snapshot for dispatch-span attributes and /metrics
        gauges: bytes used vs budget plus lifetime pressure evictions.
        Sums plane bytes under the lock — call from traced paths, not the
        tracing-off hot path."""
        with self._lock:
            partial_bytes = sum(ent[1] for ent in self._partials.values())
            views_b = sum(v.nbytes() for v in self._views.values())
            stacks_b = sum(s.nbytes() for s in self._stacks.values())
            self._note_hwm_locked(views_b, stacks_b, partial_bytes)
            used = views_b + stacks_b + partial_bytes
            return {"hbmBytesUsed": used,
                    "hbmBudgetBytes": self.budget_bytes,
                    "hbmEvictions": self.evictions,
                    "hbmPartialEntries": len(self._partials),
                    "hbmPartialBytes": partial_bytes}

    def _per_device_locked(self) -> dict:
        # caller holds self._lock; scrape-time only (walks every shard)
        per: dict = {}
        arrays: list = []
        for v in self._views.values():
            arrays.extend(list(v._planes.values()))
        for s in self._stacks.values():
            arrays.extend(list(s._planes.values()))
        for ent in self._partials.values():
            arrays.extend(ent[0])
        for a in arrays:
            try:
                shards = a.addressable_shards
            except Exception:
                per[0] = per.get(0, 0) + int(getattr(a, "nbytes", 0))
                continue
            for sh in shards:
                did = int(sh.device.id)
                per[did] = per.get(did, 0) + int(sh.data.nbytes)
        return {k: per[k] for k in sorted(per)}

    def hbm_per_device(self) -> dict:
        """Resident bytes per device id across every cache tier — the
        scrape-time source for the hbmBytesUsedDevice.{device} gauges."""
        with self._lock:
            return self._per_device_locked()

    def hbm_telemetry(self) -> dict:
        """Flight-recorder HBM view: live residency per tier, lifetime
        per-tier high-water marks, and evictions attributed by tier and
        cause — the GET /debug/compiles HBM section and the scrape-time
        source for the hbmBytesUsed/hbmBytesHighWater gauges."""
        with self._lock:
            partials_b = sum(ent[1] for ent in self._partials.values())
            views_b = sum(v.nbytes() for v in self._views.values())
            stacks_b = sum(s.nbytes() for s in self._stacks.values())
            self._note_hwm_locked(views_b, stacks_b, partials_b)
            return {
                "perDevice": self._per_device_locked(),
                "budgetBytes": self.budget_bytes,
                "bytesUsed": views_b + stacks_b + partials_b,
                "tiers": {"views": views_b, "stacks": stacks_b,
                          "partials": partials_b},
                "highWater": dict(self._hwm),
                "evictions": self.evictions,
                "evictionsByTier": {
                    k: self.eviction_stats[k]
                    for k in ("views", "stacks", "partials")},
                "evictionsByCause": {
                    k: self.eviction_stats[k]
                    for k in ("budget", "oom", "lineage")},
                "partialHits": self.partial_hits,
                "partialMisses": self.partial_misses,
            }


# Default budget keeps headroom on a 16GB v5e; override via env.
_DEFAULT_BUDGET = int(os.environ.get("PINOT_TPU_HBM_BUDGET_BYTES", 12 << 30))
GLOBAL_DEVICE_CACHE = DeviceSegmentCache(budget_bytes=_DEFAULT_BUDGET)
