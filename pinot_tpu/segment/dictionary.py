"""Per-column sorted immutable dictionaries.

Reference: pinot-segment-local/.../segment/index/readers/BaseImmutableDictionary
and SegmentDictionaryCreator. As in the reference, dictionaries are SORTED, so
dict ids preserve value order — the property the TPU filter path exploits:
a range predicate on values becomes an integer interval test on dict ids, and
EQ/IN become integer compares, all evaluated on-device against the int32
forward plane with zero string handling on the TPU.

Numeric dictionaries can additionally be shipped to HBM for on-device
dict-decode (e.g. SUM over a dict-encoded metric = gather + sum).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..spi.data_types import DataType


@dataclass
class Dictionary:
    """Sorted value dictionary: dict id == rank of value."""

    data_type: DataType
    values: np.ndarray  # sorted; dtype per type (object for STRING/BYTES)

    @property
    def cardinality(self) -> int:
        return int(self.values.shape[0])

    def get(self, dict_id: int):
        return self.values[dict_id]

    def take(self, dict_ids: np.ndarray) -> np.ndarray:
        return self.values[dict_ids]

    def index_of(self, value) -> int:
        """Exact lookup; -1 if absent (reference Dictionary.indexOf)."""
        v = self._coerce(value)
        i = int(np.searchsorted(self.values, v))
        if i < self.cardinality and self.values[i] == v:
            return i
        return -1

    def insertion_index(self, value, side: str = "left") -> int:
        """searchsorted position — used to turn value ranges into dict-id ranges."""
        return int(np.searchsorted(self.values, self._coerce(value), side=side))

    def _coerce(self, value):
        if self.data_type in (DataType.STRING, DataType.JSON, DataType.BIG_DECIMAL):
            return str(value)
        if self.data_type == DataType.BYTES:
            return bytes(value)
        # Numerics stay uncoerced: np.searchsorted compares int columns against
        # float probe values exactly, whereas casting 3.5 -> int32(3) would
        # produce false EQ matches and off-by-one range bounds.
        return value

    @property
    def min_value(self):
        return self.values[0] if self.cardinality else None

    @property
    def max_value(self):
        return self.values[-1] if self.cardinality else None


def build_dictionary(raw_values: np.ndarray, data_type: DataType) -> tuple[Dictionary, np.ndarray]:
    """Build sorted dictionary + dict-id plane from raw values.

    Returns (dictionary, dict_ids[int32]). This IS the dictionary encode —
    and the segment builder's hot loop (reference:
    SegmentDictionaryCreator + column stats collection), so it avoids
    np.unique's O(n log n) argsort wherever a linear path exists:
    narrow-range integers take an O(n + range) presence/bincount route,
    and strings/wide ints take a hash factorize (first-occurrence codes)
    re-sorted through a cardinality-sized LUT. All paths produce the same
    SORTED dictionary the predicate planner depends on.
    """
    if data_type in (DataType.STRING, DataType.JSON, DataType.BIG_DECIMAL):
        arr = np.asarray([str(v) for v in raw_values], dtype=object)
        uniques, inverse = _unique_object(arr)
    elif data_type == DataType.BYTES:
        arr = np.asarray([bytes(v) for v in raw_values], dtype=object)
        uniques, inverse = _unique_object(arr)
    else:
        arr = np.ascontiguousarray(raw_values, dtype=data_type.numpy_dtype)
        uniques = inverse = None
        if arr.dtype.kind in "iu" and arr.size:
            vmin = int(arr.min())
            rng = int(arr.max()) - vmin + 1
            if rng <= max(2 * arr.size, 1 << 16):
                off = arr.astype(np.int64) - vmin if vmin else \
                    arr.astype(np.int64, copy=False)
                present = np.zeros(rng, dtype=bool)
                present[off] = True
                values = np.flatnonzero(present) + vmin  # sorted uniques
                lut = np.cumsum(present, dtype=np.int32)
                lut -= 1  # value offset → dict id
                inverse = lut[off]
                uniques = values.astype(arr.dtype)
        if uniques is None:
            uniques, inverse = _factorize_sorted(arr)
    return Dictionary(data_type, uniques), inverse.astype(np.int32)


def _factorize_sorted(arr: np.ndarray):
    """Sorted uniques + inverse via hash factorize (O(n) + sort of the
    cardinality) when pandas is importable; np.unique otherwise."""
    try:
        import pandas as pd
    except ImportError:
        return np.unique(arr, return_inverse=True)
    codes, firsts = pd.factorize(arr, use_na_sentinel=False)
    order = np.argsort(firsts, kind="stable")  # cardinality-sized sort
    rank = np.empty_like(order)
    rank[order] = np.arange(order.size)
    values = np.asarray(firsts)[order]
    if arr.dtype != object:
        values = values.astype(arr.dtype, copy=False)
    return values, rank[codes]


def _unique_object(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    uniques, inverse = _factorize_sorted(arr)
    return uniques.astype(object), inverse


def serialize_dictionary(d: Dictionary) -> bytes:
    """Flat bytes form: numeric = raw array; var-width = u32 offsets + blob."""
    if d.data_type.is_fixed_width:
        return d.values.tobytes()
    blobs = [v.encode("utf-8") if isinstance(v, str) else bytes(v) for v in d.values]
    offsets = np.zeros(len(blobs) + 1, dtype=np.uint32)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    return offsets.tobytes() + b"".join(blobs)


def deserialize_dictionary(data: bytes, data_type: DataType, cardinality: int) -> Dictionary:
    if data_type.is_fixed_width:
        values = np.frombuffer(data, dtype=data_type.numpy_dtype, count=cardinality).copy()
        return Dictionary(data_type, values)
    offsets = np.frombuffer(data, dtype=np.uint32, count=cardinality + 1)
    blob = data[(cardinality + 1) * 4 :]
    if data_type == DataType.BYTES:
        values = np.asarray([blob[offsets[i] : offsets[i + 1]] for i in range(cardinality)], dtype=object)
    else:
        values = np.asarray(
            [blob[offsets[i] : offsets[i + 1]].decode("utf-8") for i in range(cardinality)], dtype=object
        )
    return Dictionary(data_type, values)
