"""On-disk segment format.

Layout mirrors the reference's v3 single-file segment directory
(pinot-segment-local/.../segment/store/SingleFileIndexDirectory.java,
SegmentVersion.java:21-24): one `data.bin` holding every column buffer
back-to-back plus a `metadata.json` carrying the buffer index map and
column metadata. Unlike the reference's row-group-free but chunked layout,
buffers here are whole-column (the unit of TPU transfer is the column plane,
not a 10K-doc block — see SURVEY.md §7 design stance).

Buffer kinds per column:
  fwd    packed dict ids (fixed-bit LSB-first) for DICT encoding, or raw
         fixed-width values for RAW encoding
  dict   serialized sorted dictionary (DICT only)
  nulls  packed null bitmap (present iff column had nulls)
  mvoff  u32 row-offsets into the MV value stream (MV columns only)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

FORMAT_VERSION = 1
DATA_FILE = "data.bin"
METADATA_FILE = "metadata.json"


@dataclass
class ColumnMetadata:
    name: str
    data_type: str              # DataType.value
    field_type: str             # FieldType.value
    encoding: str               # "DICT" | "RAW"
    single_value: bool = True
    cardinality: int = 0
    bits_per_value: int = 0
    min_value: Optional[object] = None
    max_value: Optional[object] = None
    is_sorted: bool = False
    has_nulls: bool = False
    total_number_of_entries: int = 0   # == num_docs for SV; total MV values for MV
    max_number_of_multi_values: int = 0
    # partition stamping (reference ColumnPartitionMetadata: function name,
    # numPartitions, and the SET of partition ids observed in this segment)
    partition_function: Optional[str] = None
    partition_id: Optional[int] = None  # singleton convenience when len(partitions)==1
    num_partitions: Optional[int] = None
    partitions: Optional[list] = None

    def to_json(self) -> dict:
        d = dict(self.__dict__)
        for k in ("min_value", "max_value"):
            v = d[k]
            if isinstance(v, (np.integer,)):
                d[k] = int(v)
            elif isinstance(v, (np.floating,)):
                d[k] = float(v)
            elif isinstance(v, bytes):
                d[k] = v.hex()
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ColumnMetadata":
        m = cls(**d)
        if m.data_type == "BYTES":
            for k in ("min_value", "max_value"):
                v = getattr(m, k)
                if isinstance(v, str):
                    setattr(m, k, bytes.fromhex(v))
        return m


def partition_push_metadata(segment_dir) -> dict:
    """{"partitions": {col: [ids]}} for partition-stamped columns of a
    built segment directory, or {} — attached to the controller push
    record so the MSE dispatcher can place partition-aligned (colocated)
    workers next to their segments (reference: SegmentZKMetadata's
    partitionMetadata feeding the broker's TablePartitionInfo)."""
    meta_path = Path(segment_dir) / METADATA_FILE
    if not meta_path.exists():
        return {}
    meta = SegmentMetadata.from_json(json.loads(meta_path.read_text()))
    out = {}
    for col, m in meta.columns.items():
        if m.partition_function and m.partitions is not None \
                and m.num_partitions:
            # function + count travel with the ids so consumers can reject
            # stamps that predate a segmentPartitionConfig change
            out[col] = {"functionName": m.partition_function,
                        "numPartitions": int(m.num_partitions),
                        "partitions": [int(p) for p in m.partitions]}
    return {"partitions": out} if out else {}


@dataclass
class SegmentMetadata:
    segment_name: str
    table_name: str
    num_docs: int
    columns: dict[str, ColumnMetadata] = field(default_factory=dict)
    buffers: dict[str, list[int]] = field(default_factory=dict)  # name -> [offset, size]
    time_column: Optional[str] = None
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    format_version: int = FORMAT_VERSION
    crc: Optional[str] = None
    # integrity fingerprints finer than the whole-segment crc: per-buffer
    # crc32 of the bytes as written (compressed form for PTCC buffers) and
    # per-column crc32 chained over that column's buffers in write order —
    # the loader verifies on load and names the damaged column(s)
    buffer_crcs: dict = field(default_factory=dict)   # buffer name -> crc hex
    column_crcs: dict = field(default_factory=dict)   # column name -> crc hex
    creation_time_ms: int = 0
    star_trees: list = field(default_factory=list)  # build_star_tree meta dicts
    # ingestion-order metadata (builder._compute_sort_order): longest
    # column chain whose dict ids are LEXICOGRAPHICALLY nondecreasing over
    # the rows — any prefix of it qualifies as presorted composite group
    # keys (engine/plan.py keys_presorted)
    sort_order: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "segmentName": self.segment_name,
            "tableName": self.table_name,
            "numDocs": self.num_docs,
            "formatVersion": self.format_version,
            "timeColumn": self.time_column,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "crc": self.crc,
            "bufferCrcs": self.buffer_crcs,
            "columnCrcs": self.column_crcs,
            "creationTimeMs": self.creation_time_ms,
            "columns": {k: v.to_json() for k, v in self.columns.items()},
            "buffers": self.buffers,
            "starTrees": self.star_trees,
            "sortOrder": self.sort_order,
        }

    @classmethod
    def from_json(cls, d: dict) -> "SegmentMetadata":
        return cls(
            segment_name=d["segmentName"],
            table_name=d["tableName"],
            num_docs=d["numDocs"],
            format_version=d.get("formatVersion", FORMAT_VERSION),
            time_column=d.get("timeColumn"),
            start_time=d.get("startTime"),
            end_time=d.get("endTime"),
            crc=d.get("crc"),
            buffer_crcs=d.get("bufferCrcs", {}),
            column_crcs=d.get("columnCrcs", {}),
            creation_time_ms=d.get("creationTimeMs", 0),
            columns={k: ColumnMetadata.from_json(v) for k, v in d.get("columns", {}).items()},
            buffers=d.get("buffers", {}),
            star_trees=d.get("starTrees", []),
            sort_order=d.get("sortOrder", []),
        )


class SegmentWriter:
    """Accumulates named buffers and writes data.bin + metadata.json."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self._buffers: list[tuple[str, bytes]] = []
        # buffer name -> codec; applied at write() so peek_buffer and the
        # index builders always see uncompressed bytes
        self.compress_on_write: dict[str, str] = {}

    def add_buffer(self, name: str, data: bytes | np.ndarray,
                   codec: Optional[str] = None) -> None:
        if isinstance(data, np.ndarray):
            data = data.tobytes()
        self._buffers.append((name, data))
        if codec and codec.upper() != "PASS_THROUGH":
            self.compress_on_write[name] = codec.upper()

    def buffer_names(self) -> set[str]:
        return {name for name, _ in self._buffers}

    def peek_buffer(self, name: str) -> np.ndarray:
        """Re-read an already-added buffer (index builders derive from the
        forward index without keeping a second copy of the column)."""
        for n, data in self._buffers:
            if n == name:
                return np.frombuffer(data, dtype=np.uint8)
        raise KeyError(name)

    def write(self, metadata: SegmentMetadata) -> None:
        import zlib

        from .compression import compress_buffer

        self.directory.mkdir(parents=True, exist_ok=True)
        offset = 0
        crc = 0
        col_crcs: dict[str, int] = {}
        columns = sorted(metadata.columns, key=len, reverse=True)
        with open(self.directory / DATA_FILE, "wb") as f:
            for name, data in self._buffers:
                codec = self.compress_on_write.get(name)
                if codec:
                    data = compress_buffer(data, codec)
                    # third element marks the buffer as a PTCC container
                    metadata.buffers[name] = [offset, len(data), codec]
                else:
                    metadata.buffers[name] = [offset, len(data)]
                f.write(data)
                metadata.buffer_crcs[name] = format(zlib.crc32(data), "08x")
                # chain this buffer into its owning column's checksum
                # (buffer names are "<column>.<kind>"; longest match wins
                # for column names that themselves contain dots)
                owner = next((c for c in columns
                              if name == c or name.startswith(c + ".")), None)
                if owner is not None:
                    col_crcs[owner] = zlib.crc32(data, col_crcs.get(owner, 0))
                crc = zlib.crc32(data, crc)
                offset += len(data)
        metadata.crc = format(crc, "08x")
        metadata.column_crcs = {c: format(v, "08x")
                                for c, v in col_crcs.items()}
        with open(self.directory / METADATA_FILE, "w") as f:
            json.dump(metadata.to_json(), f, indent=1, default=str)


def read_metadata(directory: str | Path) -> SegmentMetadata:
    with open(Path(directory) / METADATA_FILE) as f:
        return SegmentMetadata.from_json(json.load(f))
