"""Custom index SPI: pluggable index types registered by name.

Reference analogue: the IndexType<C, R, Creator> registration surface
(pinot-segment-spi/.../index/StandardIndexes.java:89-146 and IndexService
— plugins register index types that the segment creator invokes per
column and the loader materializes into readers). Here an index type is a
(build, serialize, deserialize) triple keyed by name:

    register_index_type(IndexType(
        name="suffix",                     # config key
        build=lambda values, cfg: ...,     # column values → index object
        serialize=lambda idx: [(suffix, np.ndarray), ...],
        deserialize=lambda bufs: idx,      # {suffix: np.ndarray} → object
    ))

A table config requests instances per column through
``IndexingConfig.custom_index_configs``:

    {"colA": {"type": "suffix", ...per-index config...}}

The segment builder stores each buffer as ``{col}.x_{name}.{suffix}`` so
custom buffers never collide with built-ins; the loader exposes
``segment.get_custom_index(col)`` which deserializes lazily and caches —
the same lifecycle the built-in indexes get. Query integration is up to
the index's owner (transform functions and filter pruners can fetch the
object via the segment handle), matching the reference where a custom
IndexType ships its own operator integration.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

_BUF_PREFIX = "x_"


@dataclass(frozen=True)
class IndexType:
    name: str
    build: Callable  # (values, config: dict) -> index object
    serialize: Callable  # (index object) -> list[(suffix, np.ndarray)]
    deserialize: Callable  # ({suffix: np.ndarray}) -> index object


_REGISTRY: dict[str, IndexType] = {}


def register_index_type(index_type: IndexType) -> None:
    if not index_type.name.isidentifier():
        raise ValueError(f"index type name {index_type.name!r} must be an "
                         "identifier (it becomes a buffer-name component)")
    _REGISTRY[index_type.name] = index_type


def get_index_type(name: str) -> IndexType:
    if name not in _REGISTRY:
        _load_builtin_types()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown index type {name!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


def _load_builtin_types() -> None:
    """Import modules that register the built-in custom index types (the
    reference's ServiceLoader pass over IndexPlugin implementations)."""
    from . import map_index  # noqa: F401  (registers "map")


def registered_index_types() -> list[str]:
    return sorted(_REGISTRY)


def buffer_name(column: str, type_name: str, suffix: str) -> str:
    return f"{column}.{_BUF_PREFIX}{type_name}.{suffix}"


def build_custom_indexes(columns, custom_configs: dict) -> list[tuple[str, object]]:
    """(buffer_name, array) pairs for every configured custom index."""
    out = []
    for col, cfg in custom_configs.items():
        if col not in columns:
            continue
        it = get_index_type(cfg.get("type", ""))
        idx = it.build(columns[col], cfg)
        for suffix, arr in it.serialize(idx):
            out.append((buffer_name(col, it.name, suffix), arr))
    return out


def load_custom_index(segment, column: str, type_name: str):
    """Deserialize a custom index from a loaded segment's buffers, or None
    when the segment carries none for (column, type)."""
    it = get_index_type(type_name)
    prefix = f"{column}.{_BUF_PREFIX}{type_name}."
    bufs = {name[len(prefix):]: segment.buffer_array(name)
            for name in segment.metadata.buffers if name.startswith(prefix)}
    if not bufs:
        return None
    return it.deserialize(bufs)
