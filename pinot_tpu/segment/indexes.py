"""Per-column auxiliary indexes: inverted, range, bloom, sorted, JSON.

Reference inventory (SURVEY.md §2.2): BitmapInvertedIndexReader,
BitSlicedRangeIndexReader, bloom/, JsonIndexReader, sorted forward index
(pinot-segment-local/.../segment/index/readers/). Design differences for the
TPU build:

- The device kernel already evaluates predicates as whole-segment vector
  compares on the MXU/VPU — per-row index lookups would be SLOWER than the
  fused scan for most selectivities. Indexes here serve (a) segment pruning
  (skip entire segments — engine/pruner.py), (b) the host fallback engine,
  and (c) predicates the kernel can't express vectorially (JSON_MATCH,
  TEXT_MATCH), which are evaluated host-side into a boolean plane passed to
  the kernel as a mask parameter (ir.MaskParam).

- The inverted index is CSR over (dictId → sorted docIds). Because posting
  lists are laid out in ascending dictId order, a *dictId range* is ONE
  contiguous slice — so for dict columns the inverted index doubles as the
  range index (the reference needs a separate bit-sliced structure,
  BitSlicedRangeIndexReader, because RoaringBitmaps don't concatenate).

- Raw-column range index = (sorted values, argsort permutation): a value
  range binary-searches to one slice of the permutation. This replaces
  bit-slicing with two dense arrays — O(log n) + slice, TPU-friendly if ever
  shipped to device.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

from ..utils.sketches import hash64_any

# ---------------------------------------------------------------------------
# Inverted index (CSR): dictId → sorted docId posting list
# ---------------------------------------------------------------------------


@dataclass
class InvertedIndex:
    offsets: np.ndarray  # u32[card+1]
    docs: np.ndarray     # u32[num_docs] grouped by dictId, ascending docId

    @staticmethod
    def build(dict_ids: np.ndarray, cardinality: int) -> "InvertedIndex":
        order = np.argsort(dict_ids, kind="stable")  # stable ⇒ docIds ascend per id
        counts = np.bincount(dict_ids, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.uint32)
        np.cumsum(counts, out=offsets[1:])
        return InvertedIndex(offsets, order.astype(np.uint32))

    def postings(self, dict_id: int) -> np.ndarray:
        return self.docs[self.offsets[dict_id] : self.offsets[dict_id + 1]]

    def postings_range(self, lo_id: int, hi_id: int) -> np.ndarray:
        """All docIds with lo_id <= dictId <= hi_id — one contiguous slice."""
        if hi_id < lo_id:
            return self.docs[0:0]
        return self.docs[self.offsets[lo_id] : self.offsets[hi_id + 1]]

    def mask_for_ids(self, ids, num_docs: int) -> np.ndarray:
        m = np.zeros(num_docs, dtype=bool)
        for i in ids:
            m[self.postings(int(i))] = True
        return m

    def mask_for_range(self, lo_id: int, hi_id: int, num_docs: int) -> np.ndarray:
        m = np.zeros(num_docs, dtype=bool)
        m[self.postings_range(lo_id, hi_id)] = True
        return m


# ---------------------------------------------------------------------------
# Raw-column range index: sorted values + permutation
# ---------------------------------------------------------------------------


@dataclass
class RawRangeIndex:
    sorted_values: np.ndarray
    perm: np.ndarray  # u32: sorted_values[i] == raw[perm[i]]

    @staticmethod
    def build(values: np.ndarray) -> "RawRangeIndex":
        perm = np.argsort(values, kind="stable")
        return RawRangeIndex(values[perm], perm.astype(np.uint32))

    def docs_in_range(self, lower, upper, lower_inc=True, upper_inc=True) -> np.ndarray:
        lo = 0
        hi = len(self.sorted_values)
        if lower is not None:
            lo = np.searchsorted(self.sorted_values, lower,
                                 side="left" if lower_inc else "right")
        if upper is not None:
            hi = np.searchsorted(self.sorted_values, upper,
                                 side="right" if upper_inc else "left")
        return self.perm[lo:hi]

    def mask_in_range(self, num_docs: int, lower, upper, lower_inc=True, upper_inc=True):
        m = np.zeros(num_docs, dtype=bool)
        m[self.docs_in_range(lower, upper, lower_inc, upper_inc)] = True
        return m


# ---------------------------------------------------------------------------
# Sorted index: for a sorted dict column, dictId → contiguous [start, end)
# docId range (reference SortedIndexReader reads this off the forward index)
# ---------------------------------------------------------------------------


@dataclass
class SortedIndex:
    starts: np.ndarray  # u32[card+1]: dictId d occupies docs [starts[d], starts[d+1])

    @staticmethod
    def build(dict_ids: np.ndarray, cardinality: int) -> "SortedIndex":
        counts = np.bincount(dict_ids, minlength=cardinality)
        starts = np.zeros(cardinality + 1, dtype=np.uint32)
        np.cumsum(counts, out=starts[1:])
        return SortedIndex(starts)

    def doc_range(self, lo_id: int, hi_id: int) -> tuple[int, int]:
        if hi_id < lo_id:
            return (0, 0)
        return int(self.starts[lo_id]), int(self.starts[hi_id + 1])


# ---------------------------------------------------------------------------
# Bloom filter (per-column EQ pruning — reference guava-backed
# BloomFilterSegmentPruner path)
# ---------------------------------------------------------------------------


@dataclass
class BloomFilter:
    bits: np.ndarray  # packed u8
    num_bits: int
    num_hashes: int

    @staticmethod
    def build(values, fpp: float = 0.05) -> "BloomFilter":
        vals = _bloom_canon(np.asarray(values))
        n = max(1, len(vals))
        num_bits = max(64, int(-n * np.log(fpp) / (np.log(2) ** 2)))
        num_bits = (num_bits + 7) & ~7
        k = max(1, int(round(num_bits / n * np.log(2))))
        bf = BloomFilter(np.zeros(num_bits // 8, dtype=np.uint8), num_bits, k)
        bf._add_hashes(hash64_any(vals))
        return bf

    def _positions(self, h: np.ndarray) -> np.ndarray:
        h1 = h & np.uint64(0xFFFFFFFF)
        h2 = h >> np.uint64(32)
        ks = np.arange(self.num_hashes, dtype=np.uint64)
        with np.errstate(over="ignore"):
            return ((h1[:, None] + ks[None, :] * h2[:, None])
                    % np.uint64(self.num_bits)).astype(np.int64)

    def _add_hashes(self, h: np.ndarray):
        pos = self._positions(h).ravel()
        np.bitwise_or.at(self.bits, pos >> 3, (1 << (pos & 7)).astype(np.uint8))

    def might_contain(self, value) -> bool:
        pos = self._positions(hash64_any(_bloom_canon(np.asarray([value])))).ravel()
        return bool(np.all((self.bits[pos >> 3] >> (pos & 7)) & 1))


def _bloom_canon(vals: np.ndarray) -> np.ndarray:
    """Numerics hash as float64 so `WHERE fare = 5` (int literal) finds rows
    of a DOUBLE column and vice versa; hash64_any would otherwise hash int
    and float bit patterns differently."""
    if vals.dtype.kind in ("i", "u", "f", "b"):
        return vals.astype(np.float64)
    return vals


# ---------------------------------------------------------------------------
# JSON index: flattened path=value → posting lists
# (reference JsonIndexReader / MutableJsonIndexImpl semantics subset:
# '$.a.b' exact paths, '$.arr[*].k' array wildcards)
# ---------------------------------------------------------------------------


@dataclass
class JsonIndex:
    keys: dict[str, np.ndarray]  # "path\x00value" → sorted u32 docIds
    paths: dict[str, np.ndarray]  # "path" → sorted u32 docIds where path exists

    @staticmethod
    def build(json_strings) -> "JsonIndex":
        key_docs: dict[str, list[int]] = {}
        path_docs: dict[str, list[int]] = {}
        for doc_id, s in enumerate(json_strings):
            try:
                obj = json.loads(s) if isinstance(s, str) else s
            except (json.JSONDecodeError, TypeError):
                continue
            seen_keys: set[str] = set()
            seen_paths: set[str] = set()
            _flatten(obj, "$", seen_keys, seen_paths)
            for k in seen_keys:
                key_docs.setdefault(k, []).append(doc_id)
            for p in seen_paths:
                path_docs.setdefault(p, []).append(doc_id)
        return JsonIndex(
            {k: np.asarray(v, dtype=np.uint32) for k, v in key_docs.items()},
            {k: np.asarray(v, dtype=np.uint32) for k, v in path_docs.items()},
        )

    def docs_eq(self, path: str, value) -> np.ndarray:
        return self.keys.get(f"{path}\x00{_canon(value)}", np.empty(0, dtype=np.uint32))

    def docs_exists(self, path: str) -> np.ndarray:
        return self.paths.get(path, np.empty(0, dtype=np.uint32))

    def mask_match(self, expr: str, num_docs: int) -> np.ndarray:
        """Evaluate a JSON_MATCH filter expression string → doc mask.

        Supports the reference's common forms: "$.path" = 'v', <>, IN,
        IS [NOT] NULL, AND/OR/NOT combinations (MatchAllPredicate etc. are
        out of scope)."""
        from ..query.filter import FilterContext, FilterNodeType, PredicateType
        from ..query.parser.sql import parse_filter_expression

        f = parse_filter_expression(expr)

        def ev(node: FilterContext) -> np.ndarray:
            if node.type == FilterNodeType.AND:
                m = ev(node.children[0])
                for c in node.children[1:]:
                    m = m & ev(c)
                return m
            if node.type == FilterNodeType.OR:
                m = ev(node.children[0])
                for c in node.children[1:]:
                    m = m | ev(c)
                return m
            if node.type == FilterNodeType.NOT:
                return ~ev(node.children[0])
            if node.type == FilterNodeType.CONSTANT:
                return np.full(num_docs, node.constant_value, dtype=bool)
            p = node.predicate
            path = p.lhs.identifier
            if path is None:
                raise ValueError(f"JSON_MATCH lhs must be a path: {p.lhs}")
            if not path.startswith("$"):
                path = "$." + path
            m = np.zeros(num_docs, dtype=bool)
            if p.type == PredicateType.EQ:
                m[self.docs_eq(path, p.values[0])] = True
            elif p.type == PredicateType.NOT_EQ:
                m[self.docs_exists(path)] = True
                m[self.docs_eq(path, p.values[0])] = False
            elif p.type == PredicateType.IN:
                for v in p.values:
                    m[self.docs_eq(path, v)] = True
            elif p.type == PredicateType.NOT_IN:
                m[self.docs_exists(path)] = True
                for v in p.values:
                    m[self.docs_eq(path, v)] = False
            elif p.type == PredicateType.IS_NOT_NULL:
                m[self.docs_exists(path)] = True
            elif p.type == PredicateType.IS_NULL:
                m[self.docs_exists(path)] = True
                m = ~m
            else:
                raise ValueError(f"JSON_MATCH predicate {p.type} unsupported")
            return m

        return ev(f)


def _canon(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


def _flatten(obj, prefix: str, keys: set[str], paths: set[str]):
    if isinstance(obj, dict):
        paths.add(prefix)
        for k, v in obj.items():
            _flatten(v, f"{prefix}.{k}", keys, paths)
    elif isinstance(obj, list):
        paths.add(prefix)
        for v in obj:
            _flatten(v, f"{prefix}[*]", keys, paths)
    else:
        paths.add(prefix)
        if obj is None:
            return
        keys.add(f"{prefix}\x00{_canon(obj)}")


# ---------------------------------------------------------------------------
# serialization: each index packs to named buffers in the segment data file
# ---------------------------------------------------------------------------


def serialize_inverted(idx: InvertedIndex) -> list[tuple[str, np.ndarray]]:
    return [("inv.off", idx.offsets), ("inv.docs", idx.docs)]


def deserialize_inverted(off: np.ndarray, docs: np.ndarray) -> InvertedIndex:
    return InvertedIndex(off.view(np.uint32), docs.view(np.uint32))


def serialize_raw_range(idx: RawRangeIndex) -> list[tuple[str, np.ndarray]]:
    return [("rng.sorted", idx.sorted_values), ("rng.perm", idx.perm)]


def serialize_bloom(bf: BloomFilter) -> list[tuple[str, np.ndarray]]:
    header = np.asarray([bf.num_bits, bf.num_hashes], dtype=np.int64)
    return [("bloom.hdr", header), ("bloom.bits", bf.bits)]


def deserialize_bloom(hdr: np.ndarray, bits: np.ndarray) -> BloomFilter:
    hdr = hdr.view(np.int64)
    return BloomFilter(bits.view(np.uint8), int(hdr[0]), int(hdr[1]))


def serialize_json_index(idx: JsonIndex) -> list[tuple[str, np.ndarray]]:
    """keys/paths dictionaries → (utf8 key table, CSR offsets, docs)."""
    out = []
    for field_name, table in (("keys", idx.keys), ("paths", idx.paths)):
        names = sorted(table)
        blob = "\x01".join(names).encode("utf-8")
        offsets = np.zeros(len(names) + 1, dtype=np.uint64)
        docs_parts = []
        total = 0
        for i, k in enumerate(names):
            total += len(table[k])
            offsets[i + 1] = total
            docs_parts.append(table[k])
        docs = (np.concatenate(docs_parts).astype(np.uint32)
                if docs_parts else np.empty(0, dtype=np.uint32))
        out.append((f"json.{field_name}.names", np.frombuffer(blob, dtype=np.uint8)))
        out.append((f"json.{field_name}.off", offsets))
        out.append((f"json.{field_name}.docs", docs))
    return out


def deserialize_json_index(bufs: dict[str, np.ndarray]) -> JsonIndex:
    tables = []
    for field_name in ("keys", "paths"):
        blob = bufs[f"json.{field_name}.names"].tobytes().decode("utf-8")
        names = blob.split("\x01") if blob else []
        off = bufs[f"json.{field_name}.off"].view(np.uint64)
        docs = bufs[f"json.{field_name}.docs"].view(np.uint32)
        tables.append({k: docs[off[i]:off[i + 1]] for i, k in enumerate(names)})
    return JsonIndex(*tables)
