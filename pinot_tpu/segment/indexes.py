"""Per-column auxiliary indexes: inverted, range, bloom, sorted, JSON.

Reference inventory (SURVEY.md §2.2): BitmapInvertedIndexReader,
BitSlicedRangeIndexReader, bloom/, JsonIndexReader, sorted forward index
(pinot-segment-local/.../segment/index/readers/). Design differences for the
TPU build:

- The device kernel already evaluates predicates as whole-segment vector
  compares on the MXU/VPU — per-row index lookups would be SLOWER than the
  fused scan for most selectivities. Indexes here serve (a) segment pruning
  (skip entire segments — engine/pruner.py), (b) the host fallback engine,
  and (c) predicates the kernel can't express vectorially (JSON_MATCH,
  TEXT_MATCH), which are evaluated host-side into a boolean plane passed to
  the kernel as a mask parameter (ir.MaskParam).

- The inverted index is CSR over (dictId → sorted docIds). Because posting
  lists are laid out in ascending dictId order, a *dictId range* is ONE
  contiguous slice — so for dict columns the inverted index doubles as the
  range index (the reference needs a separate bit-sliced structure,
  BitSlicedRangeIndexReader, because RoaringBitmaps don't concatenate).

- Raw-column range index = (sorted values, argsort permutation): a value
  range binary-searches to one slice of the permutation. This replaces
  bit-slicing with two dense arrays — O(log n) + slice, TPU-friendly if ever
  shipped to device.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

from ..utils.sketches import hash64_any

# ---------------------------------------------------------------------------
# Inverted index (CSR): dictId → sorted docId posting list
# ---------------------------------------------------------------------------


@dataclass
class InvertedIndex:
    offsets: np.ndarray  # u32[card+1]
    docs: np.ndarray     # u32[num_docs] grouped by dictId, ascending docId

    @staticmethod
    def build(dict_ids: np.ndarray, cardinality: int) -> "InvertedIndex":
        order = np.argsort(dict_ids, kind="stable")  # stable ⇒ docIds ascend per id
        counts = np.bincount(dict_ids, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.uint32)
        np.cumsum(counts, out=offsets[1:])
        return InvertedIndex(offsets, order.astype(np.uint32))

    def postings(self, dict_id: int) -> np.ndarray:
        return self.docs[self.offsets[dict_id] : self.offsets[dict_id + 1]]

    def postings_range(self, lo_id: int, hi_id: int) -> np.ndarray:
        """All docIds with lo_id <= dictId <= hi_id — one contiguous slice."""
        if hi_id < lo_id:
            return self.docs[0:0]
        return self.docs[self.offsets[lo_id] : self.offsets[hi_id + 1]]

    def mask_for_ids(self, ids, num_docs: int) -> np.ndarray:
        m = np.zeros(num_docs, dtype=bool)
        for i in ids:
            m[self.postings(int(i))] = True
        return m

    def mask_for_range(self, lo_id: int, hi_id: int, num_docs: int) -> np.ndarray:
        m = np.zeros(num_docs, dtype=bool)
        m[self.postings_range(lo_id, hi_id)] = True
        return m


# ---------------------------------------------------------------------------
# Raw-column range index: sorted values + permutation
# ---------------------------------------------------------------------------


@dataclass
class RawRangeIndex:
    sorted_values: np.ndarray
    perm: np.ndarray  # u32: sorted_values[i] == raw[perm[i]]

    @staticmethod
    def build(values: np.ndarray) -> "RawRangeIndex":
        perm = np.argsort(values, kind="stable")
        return RawRangeIndex(values[perm], perm.astype(np.uint32))

    def docs_in_range(self, lower, upper, lower_inc=True, upper_inc=True) -> np.ndarray:
        lo = 0
        hi = len(self.sorted_values)
        if lower is not None:
            lo = np.searchsorted(self.sorted_values, lower,
                                 side="left" if lower_inc else "right")
        if upper is not None:
            hi = np.searchsorted(self.sorted_values, upper,
                                 side="right" if upper_inc else "left")
        return self.perm[lo:hi]

    def mask_in_range(self, num_docs: int, lower, upper, lower_inc=True, upper_inc=True):
        m = np.zeros(num_docs, dtype=bool)
        m[self.docs_in_range(lower, upper, lower_inc, upper_inc)] = True
        return m


# ---------------------------------------------------------------------------
# Sorted index: for a sorted dict column, dictId → contiguous [start, end)
# docId range (reference SortedIndexReader reads this off the forward index)
# ---------------------------------------------------------------------------


@dataclass
class SortedIndex:
    starts: np.ndarray  # u32[card+1]: dictId d occupies docs [starts[d], starts[d+1])

    @staticmethod
    def build(dict_ids: np.ndarray, cardinality: int) -> "SortedIndex":
        counts = np.bincount(dict_ids, minlength=cardinality)
        starts = np.zeros(cardinality + 1, dtype=np.uint32)
        np.cumsum(counts, out=starts[1:])
        return SortedIndex(starts)

    def doc_range(self, lo_id: int, hi_id: int) -> tuple[int, int]:
        if hi_id < lo_id:
            return (0, 0)
        return int(self.starts[lo_id]), int(self.starts[hi_id + 1])


# ---------------------------------------------------------------------------
# Bloom filter (per-column EQ pruning — reference guava-backed
# BloomFilterSegmentPruner path)
# ---------------------------------------------------------------------------


@dataclass
class BloomFilter:
    bits: np.ndarray  # packed u8
    num_bits: int
    num_hashes: int

    @staticmethod
    def build(values, fpp: float = 0.05) -> "BloomFilter":
        vals = _bloom_canon(np.asarray(values))
        n = max(1, len(vals))
        num_bits = max(64, int(-n * np.log(fpp) / (np.log(2) ** 2)))
        num_bits = (num_bits + 7) & ~7
        k = max(1, int(round(num_bits / n * np.log(2))))
        bf = BloomFilter(np.zeros(num_bits // 8, dtype=np.uint8), num_bits, k)
        bf._add_hashes(hash64_any(vals))
        return bf

    def _positions(self, h: np.ndarray) -> np.ndarray:
        h1 = h & np.uint64(0xFFFFFFFF)
        h2 = h >> np.uint64(32)
        ks = np.arange(self.num_hashes, dtype=np.uint64)
        with np.errstate(over="ignore"):
            return ((h1[:, None] + ks[None, :] * h2[:, None])
                    % np.uint64(self.num_bits)).astype(np.int64)

    def _add_hashes(self, h: np.ndarray):
        pos = self._positions(h).ravel()
        np.bitwise_or.at(self.bits, pos >> 3, (1 << (pos & 7)).astype(np.uint8))

    def might_contain(self, value) -> bool:
        pos = self._positions(hash64_any(_bloom_canon(np.asarray([value])))).ravel()
        return bool(np.all((self.bits[pos >> 3] >> (pos & 7)) & 1))


def _bloom_canon(vals: np.ndarray) -> np.ndarray:
    """Numerics hash as float64 so `WHERE fare = 5` (int literal) finds rows
    of a DOUBLE column and vice versa; hash64_any would otherwise hash int
    and float bit patterns differently."""
    if vals.dtype.kind in ("i", "u", "f", "b"):
        return vals.astype(np.float64)
    return vals


# ---------------------------------------------------------------------------
# JSON index: flattened path=value → posting lists
# (reference JsonIndexReader / MutableJsonIndexImpl semantics subset:
# '$.a.b' exact paths, '$.arr[*].k' array wildcards)
# ---------------------------------------------------------------------------


@dataclass
class JsonIndex:
    keys: dict[str, np.ndarray]  # "path\x00value" → sorted u32 docIds
    paths: dict[str, np.ndarray]  # "path" → sorted u32 docIds where path exists

    @staticmethod
    def build(json_strings) -> "JsonIndex":
        key_docs: dict[str, list[int]] = {}
        path_docs: dict[str, list[int]] = {}
        for doc_id, s in enumerate(json_strings):
            try:
                obj = json.loads(s) if isinstance(s, str) else s
            except (json.JSONDecodeError, TypeError):
                continue
            seen_keys: set[str] = set()
            seen_paths: set[str] = set()
            _flatten(obj, "$", seen_keys, seen_paths)
            for k in seen_keys:
                key_docs.setdefault(k, []).append(doc_id)
            for p in seen_paths:
                path_docs.setdefault(p, []).append(doc_id)
        return JsonIndex(
            {k: np.asarray(v, dtype=np.uint32) for k, v in key_docs.items()},
            {k: np.asarray(v, dtype=np.uint32) for k, v in path_docs.items()},
        )

    def docs_eq(self, path: str, value) -> np.ndarray:
        return self.keys.get(f"{path}\x00{_canon(value)}", np.empty(0, dtype=np.uint32))

    def docs_exists(self, path: str) -> np.ndarray:
        return self.paths.get(path, np.empty(0, dtype=np.uint32))

    def mask_match(self, expr: str, num_docs: int) -> np.ndarray:
        """Evaluate a JSON_MATCH filter expression string → doc mask.

        Supports the reference's common forms: "$.path" = 'v', <>, IN,
        IS [NOT] NULL, AND/OR/NOT combinations (MatchAllPredicate etc. are
        out of scope)."""
        from ..query.filter import FilterContext, FilterNodeType, PredicateType
        from ..query.parser.sql import parse_filter_expression

        f = parse_filter_expression(expr)

        def ev(node: FilterContext) -> np.ndarray:
            if node.type == FilterNodeType.AND:
                m = ev(node.children[0])
                for c in node.children[1:]:
                    m = m & ev(c)
                return m
            if node.type == FilterNodeType.OR:
                m = ev(node.children[0])
                for c in node.children[1:]:
                    m = m | ev(c)
                return m
            if node.type == FilterNodeType.NOT:
                return ~ev(node.children[0])
            if node.type == FilterNodeType.CONSTANT:
                return np.full(num_docs, node.constant_value, dtype=bool)
            p = node.predicate
            path = p.lhs.identifier
            if path is None:
                raise ValueError(f"JSON_MATCH lhs must be a path: {p.lhs}")
            if not path.startswith("$"):
                path = "$." + path
            m = np.zeros(num_docs, dtype=bool)
            if p.type == PredicateType.EQ:
                m[self.docs_eq(path, p.values[0])] = True
            elif p.type == PredicateType.NOT_EQ:
                m[self.docs_exists(path)] = True
                m[self.docs_eq(path, p.values[0])] = False
            elif p.type == PredicateType.IN:
                for v in p.values:
                    m[self.docs_eq(path, v)] = True
            elif p.type == PredicateType.NOT_IN:
                m[self.docs_exists(path)] = True
                for v in p.values:
                    m[self.docs_eq(path, v)] = False
            elif p.type == PredicateType.IS_NOT_NULL:
                m[self.docs_exists(path)] = True
            elif p.type == PredicateType.IS_NULL:
                m[self.docs_exists(path)] = True
                m = ~m
            else:
                raise ValueError(f"JSON_MATCH predicate {p.type} unsupported")
            return m

        return ev(f)


def _canon(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


def _flatten(obj, prefix: str, keys: set[str], paths: set[str]):
    if isinstance(obj, dict):
        paths.add(prefix)
        for k, v in obj.items():
            _flatten(v, f"{prefix}.{k}", keys, paths)
    elif isinstance(obj, list):
        paths.add(prefix)
        for v in obj:
            _flatten(v, f"{prefix}[*]", keys, paths)
    else:
        paths.add(prefix)
        if obj is None:
            return
        keys.add(f"{prefix}\x00{_canon(obj)}")


# ---------------------------------------------------------------------------
# serialization: each index packs to named buffers in the segment data file
# ---------------------------------------------------------------------------


def serialize_inverted(idx: InvertedIndex) -> list[tuple[str, np.ndarray]]:
    return [("inv.off", idx.offsets), ("inv.docs", idx.docs)]


def deserialize_inverted(off: np.ndarray, docs: np.ndarray) -> InvertedIndex:
    return InvertedIndex(off.view(np.uint32), docs.view(np.uint32))


def serialize_raw_range(idx: RawRangeIndex) -> list[tuple[str, np.ndarray]]:
    return [("rng.sorted", idx.sorted_values), ("rng.perm", idx.perm)]


def serialize_bloom(bf: BloomFilter) -> list[tuple[str, np.ndarray]]:
    header = np.asarray([bf.num_bits, bf.num_hashes], dtype=np.int64)
    return [("bloom.hdr", header), ("bloom.bits", bf.bits)]


def deserialize_bloom(hdr: np.ndarray, bits: np.ndarray) -> BloomFilter:
    hdr = hdr.view(np.int64)
    return BloomFilter(bits.view(np.uint8), int(hdr[0]), int(hdr[1]))


def serialize_json_index(idx: JsonIndex) -> list[tuple[str, np.ndarray]]:
    """keys/paths dictionaries → (utf8 key table, CSR offsets, docs)."""
    out = []
    for field_name, table in (("keys", idx.keys), ("paths", idx.paths)):
        names = sorted(table)
        blob = "\x01".join(names).encode("utf-8")
        offsets = np.zeros(len(names) + 1, dtype=np.uint64)
        docs_parts = []
        total = 0
        for i, k in enumerate(names):
            total += len(table[k])
            offsets[i + 1] = total
            docs_parts.append(table[k])
        docs = (np.concatenate(docs_parts).astype(np.uint32)
                if docs_parts else np.empty(0, dtype=np.uint32))
        out.append((f"json.{field_name}.names", np.frombuffer(blob, dtype=np.uint8)))
        out.append((f"json.{field_name}.off", offsets))
        out.append((f"json.{field_name}.docs", docs))
    return out


def deserialize_json_index(bufs: dict[str, np.ndarray]) -> JsonIndex:
    tables = []
    for field_name in ("keys", "paths"):
        blob = bufs[f"json.{field_name}.names"].tobytes().decode("utf-8")
        names = blob.split("\x01") if blob else []
        off = bufs[f"json.{field_name}.off"].view(np.uint64)
        docs = bufs[f"json.{field_name}.docs"].view(np.uint32)
        tables.append({k: docs[off[i]:off[i + 1]] for i, k in enumerate(names)})
    return JsonIndex(*tables)


# ---------------------------------------------------------------------------
# Text index: tokenized terms → postings with positions (TEXT_MATCH)
# ---------------------------------------------------------------------------

import re as _re

_TOKEN_SPLIT = _re.compile(r"[^0-9a-z]+")


def tokenize_text(s: str) -> list[str]:
    """Lowercase alphanumeric tokenizer (reference: Lucene's
    StandardAnalyzer as configured by the text index's default)."""
    return [t for t in _TOKEN_SPLIT.split(str(s).lower()) if t]


@dataclass
class TextIndex:
    """Term → (docs, positions) postings supporting Lucene-ish TEXT_MATCH
    queries: `term`, `a AND b`, `a OR b`, `NOT a`, prefix `ab*`, and
    `"exact phrase"` via positions.

    Reference: the Lucene text index + native FST regex engine
    (pinot-segment-local/.../readers/text/, .../utils/nativefst/). Postings
    are dense numpy arrays; phrase matching intersects (doc, pos) pairs —
    the same approach as Lucene's exact PhraseQuery."""

    terms: list  # sorted term strings
    doc_postings: list  # parallel: np.uint32 doc ids (deduped, sorted)
    pos_postings: list  # parallel: (np.uint32 docs-with-dup, np.uint32 pos)

    @staticmethod
    def build(strings) -> "TextIndex":
        acc: dict[str, list] = {}
        for doc_id, s in enumerate(strings):
            if s is None:
                continue
            for pos, term in enumerate(tokenize_text(s)):
                acc.setdefault(term, []).append((doc_id, pos))
        terms = sorted(acc)
        doc_postings = []
        pos_postings = []
        for t in terms:
            pairs = acc[t]
            docs_dup = np.asarray([d for d, _ in pairs], dtype=np.uint32)
            poss = np.asarray([p for _, p in pairs], dtype=np.uint32)
            doc_postings.append(np.unique(docs_dup))
            pos_postings.append((docs_dup, poss))
        return TextIndex(terms, doc_postings, pos_postings)

    # -- term lookups -------------------------------------------------------
    def _term_index(self, term: str) -> int:
        import bisect

        i = bisect.bisect_left(self.terms, term)
        return i if i < len(self.terms) and self.terms[i] == term else -1

    def docs_for_term(self, term: str) -> np.ndarray:
        i = self._term_index(term)
        return self.doc_postings[i] if i >= 0 else np.empty(0, dtype=np.uint32)

    def _prefix_range(self, prefix: str) -> tuple[int, int]:
        """[lo, hi) slice of sorted terms starting with prefix."""
        import bisect

        lo = bisect.bisect_left(self.terms, prefix)
        hi = bisect.bisect_left(self.terms, prefix + "￿")
        return lo, hi

    def docs_for_prefix(self, prefix: str) -> np.ndarray:
        lo, hi = self._prefix_range(prefix)
        if lo >= hi:
            return np.empty(0, dtype=np.uint32)
        return np.unique(np.concatenate(self.doc_postings[lo:hi]))

    def docs_for_regex(self, pattern: str) -> np.ndarray:
        """Docs containing any term matching the regex (reference: the
        native FST regex engine walks the term automaton —
        .../utils/nativefst/RegexpMatcher.java). The sorted term list plays
        the FST's role: a literal prefix extracted from the pattern narrows
        the scan to one bisect range before the full-match test."""
        import bisect

        # terms are lowercased by the analyzer: match case-insensitively so
        # /Error.*/ behaves like every other (lowercased) query form
        pat = _re.compile(pattern, _re.IGNORECASE)
        # literal prefix → restrict the candidate range (the FST descent).
        # A char is only a REQUIRED literal if it is alphanumeric AND not
        # made optional/repeated by a following quantifier (errors? has the
        # literal prefix "error", not "errors"); top-level alternation
        # (foo|bar) voids any prefix
        prefix = []
        if "|" not in pattern:
            for i, ch in enumerate(pattern):
                nxt = pattern[i + 1] if i + 1 < len(pattern) else ""
                if ch.isalnum() and nxt not in "?*{":
                    prefix.append(ch.lower())
                else:
                    break
        lo, hi = (self._prefix_range("".join(prefix)) if prefix
                  else (0, len(self.terms)))
        parts = [self.doc_postings[i] for i in range(lo, hi)
                 if pat.fullmatch(self.terms[i])]
        if not parts:
            return np.empty(0, dtype=np.uint32)
        return np.unique(np.concatenate(parts))

    # -- relevance (reference: Lucene BM25Similarity backing the text
    # index's match scores) -------------------------------------------------
    def bm25_scores(self, query: str, num_docs: int,
                    k1: float = 1.2, b: float = 0.75) -> np.ndarray:
        """BM25 score per doc for the flat terms of a TEXT_MATCH query
        (phrases/prefixes score by their expanded terms)."""
        terms = self._score_terms(_parse_text_query(query))
        doc_len = np.zeros(num_docs, dtype=np.float64)
        for docs_dup, _pos in self.pos_postings:
            np.add.at(doc_len, docs_dup[docs_dup < num_docs], 1.0)
        avg_len = doc_len.mean() if num_docs else 1.0
        avg_len = avg_len or 1.0
        scores = np.zeros(num_docs, dtype=np.float64)
        for term in terms:
            i = self._term_index(term)
            if i < 0:
                continue
            docs_dup, _ = self.pos_postings[i]
            docs_dup = docs_dup[docs_dup < num_docs]
            tf = np.zeros(num_docs, dtype=np.float64)
            np.add.at(tf, docs_dup, 1.0)
            df = len(self.doc_postings[i])
            idf = np.log1p((num_docs - df + 0.5) / (df + 0.5))
            denom = tf + k1 * (1 - b + b * doc_len / avg_len)
            with np.errstate(invalid="ignore", divide="ignore"):
                contrib = idf * tf * (k1 + 1) / np.where(denom == 0, 1, denom)
            scores += np.where(tf > 0, contrib, 0.0)
        return scores

    def _score_terms(self, node) -> list:
        kind = node[0]
        if kind == "term":
            return [node[1]]
        if kind == "phrase":
            return list(node[1])
        if kind == "prefix":
            lo, hi = self._prefix_range(node[1])
            return self.terms[lo:hi]
        if kind == "regex":
            pat = _re.compile(node[1], _re.IGNORECASE)
            return [t for t in self.terms if pat.fullmatch(t)]
        if kind in ("and", "or"):
            out = []
            for c in node[1]:
                out.extend(self._score_terms(c))
            return out
        return []

    def docs_for_phrase(self, phrase_terms: list) -> np.ndarray:
        """Docs containing the terms at consecutive positions."""
        if not phrase_terms:
            return np.empty(0, dtype=np.uint32)
        i = self._term_index(phrase_terms[0])
        if i < 0:
            return np.empty(0, dtype=np.uint32)
        docs, pos = self.pos_postings[i]
        cur = set(zip(docs.tolist(), pos.tolist()))
        for k, term in enumerate(phrase_terms[1:], start=1):
            j = self._term_index(term)
            if j < 0:
                return np.empty(0, dtype=np.uint32)
            d2, p2 = self.pos_postings[j]
            nxt = set(zip(d2.tolist(), (p2 - k).tolist()))
            cur &= nxt
            if not cur:
                return np.empty(0, dtype=np.uint32)
        return np.unique(np.asarray(sorted({d for d, _ in cur}), dtype=np.uint32))

    # -- query --------------------------------------------------------------
    def mask_match(self, query: str, num_docs: int) -> np.ndarray:
        """Evaluate a TEXT_MATCH query into a doc mask."""
        docs = self._eval_query(_parse_text_query(query))
        mask = np.zeros(num_docs, dtype=bool)
        if len(docs):
            mask[docs[docs < num_docs]] = True
        return mask

    def _eval_query(self, node) -> np.ndarray:
        kind = node[0]
        if kind == "term":
            return self.docs_for_term(node[1])
        if kind == "prefix":
            return self.docs_for_prefix(node[1])
        if kind == "regex":
            return self.docs_for_regex(node[1])
        if kind == "phrase":
            return self.docs_for_phrase(node[1])
        if kind == "and":
            out = None
            for child in node[1]:
                d = self._eval_query(child)
                out = d if out is None else np.intersect1d(out, d)
            return out if out is not None else np.empty(0, dtype=np.uint32)
        if kind == "or":
            parts = [self._eval_query(c) for c in node[1]]
            parts = [p for p in parts if len(p)]
            return (np.unique(np.concatenate(parts)) if parts
                    else np.empty(0, dtype=np.uint32))
        if kind == "not":
            raise ValueError("NOT requires an enclosing AND in TEXT_MATCH")
        raise ValueError(f"bad text query node {node!r}")


def _parse_text_query(q: str):
    """Mini Lucene syntax: terms, quoted phrases, AND/OR (AND binds
    tighter), prefix `foo*`, parentheses. Bare adjacency = OR (Lucene's
    default operator)."""
    # regex terms /.../ lex as ONE token — their parens/operators are part
    # of the pattern, not the boolean query
    tokens = _re.findall(r'"[^"]*"|/(?:[^/\\]|\\.)+/|\(|\)|[^\s()"]+', q)
    pos = [0]

    def peek():
        return tokens[pos[0]] if pos[0] < len(tokens) else None

    def next_tok():
        t = peek()
        pos[0] += 1
        return t

    def parse_or():
        left = parse_and()
        parts = [left]
        while peek() is not None and peek() not in (")",):
            if peek().upper() == "OR":
                next_tok()
                parts.append(parse_and())
            elif peek().upper() == "AND":
                break
            else:
                parts.append(parse_and())  # adjacency = OR
        return parts[0] if len(parts) == 1 else ("or", parts)

    def parse_and():
        left = parse_primary()
        parts = [left]
        while peek() is not None and peek().upper() == "AND":
            next_tok()
            parts.append(parse_primary())
        return parts[0] if len(parts) == 1 else ("and", parts)

    def parse_primary():
        t = next_tok()
        if t is None:
            raise ValueError("empty TEXT_MATCH query")
        if t == "(":
            inner = parse_or()
            if next_tok() != ")":
                raise ValueError("unbalanced parens in TEXT_MATCH")
            return inner
        if t.startswith('"'):
            return ("phrase", tokenize_text(t.strip('"')))
        if t.startswith("/") and t.endswith("/") and len(t) > 1:
            # Lucene regex term syntax /pattern/ (reference: the native FST
            # regex engine matches terms against the automaton)
            return ("regex", t[1:-1])
        if t.endswith("*"):
            return ("prefix", t[:-1].lower())
        toks = tokenize_text(t)
        if len(toks) == 1:
            return ("term", toks[0])
        return ("phrase", toks)

    out = parse_or()
    if pos[0] != len(tokens):
        raise ValueError(f"trailing input in TEXT_MATCH query {q!r}")
    return out


def serialize_text_index(idx: TextIndex) -> list[tuple[str, np.ndarray]]:
    blob = "\x01".join(idx.terms).encode("utf-8")
    off = np.zeros(len(idx.terms) + 1, dtype=np.uint64)
    docs_parts, pos_parts = [], []
    total = 0
    for i, (docs, pos) in enumerate(idx.pos_postings):
        total += len(docs)
        off[i + 1] = total
        docs_parts.append(docs)
        pos_parts.append(pos)
    cat = (np.concatenate(docs_parts).astype(np.uint32) if docs_parts
           else np.empty(0, np.uint32))
    pcat = (np.concatenate(pos_parts).astype(np.uint32) if pos_parts
            else np.empty(0, np.uint32))
    return [("text.terms", np.frombuffer(blob, dtype=np.uint8)),
            ("text.off", off), ("text.docs", cat), ("text.pos", pcat)]


def deserialize_text_index(bufs: dict[str, np.ndarray]) -> TextIndex:
    blob = bufs["text.terms"].tobytes().decode("utf-8")
    terms = blob.split("\x01") if blob else []
    off = bufs["text.off"].view(np.uint64)
    docs = bufs["text.docs"].view(np.uint32)
    pos = bufs["text.pos"].view(np.uint32)
    doc_postings, pos_postings = [], []
    for i in range(len(terms)):
        d = docs[off[i]:off[i + 1]]
        p = pos[off[i]:off[i + 1]]
        doc_postings.append(np.unique(d))
        pos_postings.append((d, p))
    return TextIndex(terms, doc_postings, pos_postings)


# ---------------------------------------------------------------------------
# Geo grid index (H3 analogue): lat/lng cells → postings
# ---------------------------------------------------------------------------

EARTH_RADIUS_M = 6371008.8


def haversine_m(lat1, lng1, lat2, lng2):
    """Great-circle distance in meters (vectorized)."""
    lat1, lng1, lat2, lng2 = (np.radians(np.asarray(x, dtype=np.float64))
                              for x in (lat1, lng1, lat2, lng2))
    dlat = lat2 - lat1
    dlng = lng2 - lng1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlng / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


@dataclass
class GeoGridIndex:
    """Fixed-resolution lat/lng grid cells → doc postings.

    Reference: the H3 hexagon index (pinot-segment-local/.../readers/
    geospatial/H3IndexReader + pinot-core/.../geospatial/). Uber's H3
    library isn't in this image, so cells are a uniform lat/lng grid at
    `res_deg` degrees — the same two-phase pattern as the reference's
    H3InclusionIndexFilterOperator: candidate cells covering the query
    circle, then exact haversine refinement on the candidates only."""

    res_deg: float
    cell_ids: np.ndarray  # sorted unique int64 cell ids
    offsets: np.ndarray   # CSR into docs
    docs: np.ndarray

    @staticmethod
    def cell_of(lat: np.ndarray, lng: np.ndarray, res_deg: float) -> np.ndarray:
        r = np.int64(np.ceil(360.0 / res_deg))
        n_lat = np.int64(np.ceil(180.0 / res_deg))
        la = np.floor((np.asarray(lat, dtype=np.float64) + 90.0) / res_deg).astype(np.int64)
        la = np.minimum(la, n_lat - 1)  # lat=+90 lands in the top row
        lo = np.floor((np.asarray(lng, dtype=np.float64) + 180.0) / res_deg).astype(np.int64)
        lo = lo % r  # lng=+180 is the same meridian as -180
        return la * r + lo

    @staticmethod
    def build(lat: np.ndarray, lng: np.ndarray, res_deg: float = 0.5) -> "GeoGridIndex":
        cells = GeoGridIndex.cell_of(lat, lng, res_deg)
        order = np.argsort(cells, kind="stable")
        sorted_cells = cells[order]
        uniq, starts = np.unique(sorted_cells, return_index=True)
        offsets = np.append(starts, len(cells)).astype(np.uint64)
        return GeoGridIndex(res_deg, uniq.astype(np.int64), offsets,
                            order.astype(np.uint32))

    def candidate_docs(self, lat: float, lng: float, radius_m: float) -> np.ndarray:
        """Docs in cells intersecting the circle (superset of matches)."""
        deg_lat = np.degrees(radius_m / EARTH_RADIUS_M)
        cos = max(0.01, np.cos(np.radians(lat)))
        deg_lng = deg_lat / cos
        r = np.int64(np.ceil(360.0 / self.res_deg))
        n_lat = int(np.ceil(180.0 / self.res_deg))
        la_lo = int(np.floor((lat - deg_lat + 90.0) / self.res_deg))
        la_hi = int(np.floor((lat + deg_lat + 90.0) / self.res_deg))
        pole_clip = la_lo < 0 or la_hi >= n_lat  # circle reaches a pole
        la_lo, la_hi = max(la_lo, 0), min(la_hi, n_lat - 1)
        lo_lo = int(np.floor((lng - deg_lng + 180.0) / self.res_deg))
        lo_hi = int(np.floor((lng + deg_lng + 180.0) / self.res_deg))
        if pole_clip or lo_hi - lo_lo + 1 >= int(r):
            lo_cols = np.arange(r, dtype=np.int64)  # all longitudes
        else:
            # wrap modulo grid width so circles crossing ±180° keep their
            # candidate cells instead of walking off the linear range
            lo_cols = np.arange(lo_lo, lo_hi + 1, dtype=np.int64) % r
        wanted = []
        for la in range(la_lo, la_hi + 1):
            base = np.int64(la) * r
            wanted.append(base + lo_cols)
        wanted = np.concatenate(wanted)
        idx = np.searchsorted(self.cell_ids, wanted)
        idx = idx[(idx < len(self.cell_ids))]
        hit = idx[np.isin(self.cell_ids[idx], wanted)]
        if not len(hit):
            return np.empty(0, dtype=np.uint32)
        return np.concatenate([self.docs[self.offsets[i]:self.offsets[i + 1]]
                               for i in np.unique(hit)])


def serialize_geo_index(idx: GeoGridIndex) -> list[tuple[str, np.ndarray]]:
    hdr = np.asarray([idx.res_deg], dtype=np.float64)
    return [("geo.hdr", hdr), ("geo.cells", idx.cell_ids),
            ("geo.off", idx.offsets), ("geo.docs", idx.docs)]


def deserialize_geo_index(bufs: dict[str, np.ndarray]) -> GeoGridIndex:
    return GeoGridIndex(float(bufs["geo.hdr"].view(np.float64)[0]),
                        bufs["geo.cells"].view(np.int64),
                        bufs["geo.off"].view(np.uint64),
                        bufs["geo.docs"].view(np.uint32))


# ---------------------------------------------------------------------------
# Vector index: exact cosine top-K (MXU matmul) + IVF pruning
# ---------------------------------------------------------------------------


@dataclass
class VectorIndex:
    """Top-K cosine similarity over a (n, dim) float32 matrix.

    Reference: the Lucene HNSW vector index (pinot-segment-local/.../
    creator/impl/vector/lucene99/, VectorSimilarityFilterOperator). The
    TPU-first design inverts the approach: instead of a pointer-chasing
    graph (hostile to the MXU), store L2-normalized vectors densely and
    compute exact similarity as ONE (n,dim)x(dim,) matmul on device —
    at OLAP segment sizes the matmul is faster than graph traversal on
    accelerators. An IVF coarse quantizer (k-means centroids) optionally
    prunes to nprobe clusters for very large segments."""

    vectors: np.ndarray  # (n, dim) float32, L2-normalized rows
    centroids: np.ndarray = None  # (nlist, dim) or None
    assignments: np.ndarray = None  # (n,) int32 cluster of each row

    @staticmethod
    def build(vectors: np.ndarray, nlist: int = 0) -> "VectorIndex":
        v = np.ascontiguousarray(vectors, dtype=np.float32)
        norms = np.linalg.norm(v, axis=1, keepdims=True)
        norms[norms == 0] = 1.0
        v = v / norms
        centroids = assignments = None
        n = len(v)
        if nlist == 0 and n >= 4096:
            nlist = int(np.sqrt(n))
        if nlist > 1 and n > nlist:
            centroids, assignments = _kmeans(v, nlist)
        return VectorIndex(v, centroids, assignments)

    def top_k(self, query: np.ndarray, k: int, nprobe: int = 8):
        """(doc_ids, similarities) of the k nearest by cosine."""
        q = np.asarray(query, dtype=np.float32)
        qn = np.linalg.norm(q)
        if qn > 0:
            q = q / qn
        if self.centroids is not None and nprobe < len(self.centroids):
            cscore = self.centroids @ q
            probe = np.argpartition(cscore, -nprobe)[-nprobe:]
            cand = np.nonzero(np.isin(self.assignments, probe))[0]
            if len(cand) < k:  # under-probed: fall back to exact
                cand = np.arange(len(self.vectors))
        else:
            cand = np.arange(len(self.vectors))
        sims = self.vectors[cand] @ q
        k = min(k, len(cand))
        if k == 0:
            return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.float32)
        top = np.argpartition(sims, -k)[-k:]
        order = np.argsort(-sims[top], kind="stable")
        sel = top[order]
        return cand[sel].astype(np.int64), sims[sel]

    def mask_top_k(self, query: np.ndarray, k: int, num_docs: int) -> np.ndarray:
        docs, _ = self.top_k(query, k)
        mask = np.zeros(num_docs, dtype=bool)
        mask[docs[docs < num_docs]] = True
        return mask


def _kmeans(v: np.ndarray, nlist: int, iters: int = 8):
    """Small k-means on normalized vectors (IVF coarse quantizer)."""
    rng = np.random.default_rng(0)
    centroids = v[rng.choice(len(v), nlist, replace=False)].copy()
    assign = np.zeros(len(v), dtype=np.int32)
    for _ in range(iters):
        assign = np.argmax(v @ centroids.T, axis=1).astype(np.int32)
        for c in range(nlist):
            members = v[assign == c]
            if len(members):
                m = members.mean(axis=0)
                norm = np.linalg.norm(m)
                centroids[c] = m / norm if norm > 0 else m
    return centroids, assign


def serialize_vector_index(idx: VectorIndex) -> list[tuple[str, np.ndarray]]:
    n, dim = idx.vectors.shape
    nlist = 0 if idx.centroids is None else len(idx.centroids)
    hdr = np.asarray([n, dim, nlist], dtype=np.int64)
    out = [("vec.hdr", hdr), ("vec.data", idx.vectors.reshape(-1))]
    if nlist:
        out.append(("vec.centroids", idx.centroids.reshape(-1)))
        out.append(("vec.assign", idx.assignments))
    return out


def deserialize_vector_index(bufs: dict[str, np.ndarray]) -> VectorIndex:
    n, dim, nlist = (int(x) for x in bufs["vec.hdr"].view(np.int64))
    vecs = bufs["vec.data"].view(np.float32).reshape(n, dim)
    if nlist:
        return VectorIndex(vecs,
                           bufs["vec.centroids"].view(np.float32).reshape(nlist, dim),
                           bufs["vec.assign"].view(np.int32))
    return VectorIndex(vecs)
