"""Immutable segment loader.

Reference: pinot-segment-local/.../indexsegment/immutable/
ImmutableSegmentLoader.java:67 — loads a segment directory, mmaps buffers, and
exposes per-column data sources. Here `data.bin` is np.memmap'd (the analogue
of PinotDataBuffer.mapFile, pinot-segment-spi/.../memory/PinotDataBuffer.java:272)
and columns decode lazily into host int32/float planes, cached, ready for a
single DMA to HBM via device_cache.SegmentDeviceCache.
"""

from __future__ import annotations

import os
import zlib
from pathlib import Path
from typing import Optional

import numpy as np

from ..spi.data_types import DataType
from . import bitpack
from .dictionary import Dictionary, deserialize_dictionary
from .format import DATA_FILE, ColumnMetadata, SegmentMetadata, read_metadata

# load-time verifications performed (pinned by the integrity perf guard:
# verification is LOAD-time only — warm queries must never move this)
VERIFY_CALLS = 0


def verify_enabled() -> bool:
    """CRC verification on load is ON unless PINOT_TPU_VERIFY_CRC opts out."""
    return os.environ.get("PINOT_TPU_VERIFY_CRC", "true").lower() \
        not in ("false", "0", "off", "no")


class SegmentIntegrityError(RuntimeError):
    """A loaded segment's bytes do not match its build-time checksums
    (bit rot, truncation, torn copy). Carries enough structure for the
    server to quarantine the replica and name the damaged columns."""

    def __init__(self, segment_name: str, directory, reason: str,
                 columns: Optional[list] = None):
        detail = f" (columns: {', '.join(columns)})" if columns else ""
        super().__init__(
            f"segment {segment_name} failed integrity check: "
            f"{reason}{detail} [{directory}]")
        self.segment_name = segment_name
        self.directory = str(directory)
        self.reason = reason
        self.columns = columns or []


class ImmutableSegment:
    """A loaded immutable segment: metadata + lazily decoded column planes."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.metadata: SegmentMetadata = read_metadata(self.directory)
        self._data = np.memmap(self.directory / DATA_FILE, dtype=np.uint8, mode="r")
        self._dictionaries: dict[str, Dictionary] = {}
        self._decompressed: dict[str, np.ndarray] = {}
        self._dict_ids: dict[str, np.ndarray] = {}
        self._raw: dict[str, np.ndarray] = {}
        self._nulls: dict[str, Optional[np.ndarray]] = {}
        self._mv_offsets: dict[str, np.ndarray] = {}
        self._indexes: dict[tuple, object] = {}

    # -- integrity ----------------------------------------------------------
    def verify_integrity(self) -> None:
        """Recompute checksums over data.bin and compare with the ones the
        builder stamped into metadata.json; raise SegmentIntegrityError on
        any mismatch, naming the damaged column(s) when the per-buffer
        crcs localize it. One full sequential pass at load time — nothing
        on the query path re-verifies (the memmap pages it touches are the
        ones queries would fault in anyway)."""
        global VERIFY_CALLS
        VERIFY_CALLS += 1
        meta = self.metadata
        expected_end = max(
            (off + size for off, size, *_ in meta.buffers.values()),
            default=0)
        if len(self._data) < expected_end:
            self._integrity_failure(
                f"data.bin truncated: {len(self._data)} bytes, "
                f"buffers extend to {expected_end}",
                self._damaged_columns())
        if meta.crc is not None:
            crc = zlib.crc32(self._data[:expected_end])
            if format(crc, "08x") != meta.crc:
                self._integrity_failure(
                    f"segment crc mismatch: computed {format(crc, '08x')}, "
                    f"metadata {meta.crc}", self._damaged_columns())
        elif meta.buffer_crcs:
            # no whole-segment crc (older metadata) but per-buffer crcs
            # present: verify buffer by buffer
            bad = self._damaged_columns()
            if bad:
                self._integrity_failure("buffer crc mismatch", bad)

    def _damaged_columns(self) -> list:
        """Per-buffer re-check to localize damage: returns the owning
        column names (or raw buffer names) whose stored crc disagrees."""
        meta = self.metadata
        columns = sorted(meta.columns, key=len, reverse=True)
        bad = []
        for name, want in meta.buffer_crcs.items():
            entry = meta.buffers.get(name)
            if entry is None:
                continue
            off, size = entry[0], entry[1]
            chunk = self._data[off:off + size]
            if len(chunk) != size or format(zlib.crc32(chunk), "08x") != want:
                owner = next((c for c in columns
                              if name == c or name.startswith(c + ".")),
                             name)
                if owner not in bad:
                    bad.append(owner)
        return bad

    def _integrity_failure(self, reason: str, columns: list):
        from ..spi.metrics import SERVER_METRICS, ServerMeter

        SERVER_METRICS.add_meter(ServerMeter.SEGMENT_CRC_MISMATCH)
        raise SegmentIntegrityError(self.metadata.segment_name,
                                    self.directory, reason, columns)

    # -- schema evolution ---------------------------------------------------
    def apply_schema(self, schema) -> None:
        """Backfill columns the schema has but this segment predates as
        virtual default-value columns (reference:
        SegmentPreProcessor.updateDefaultColumns on load,
        ImmutableSegmentLoader.java:67-101 — schema evolution without
        rewriting old segments). Virtual columns are dict-encoded with one
        value (the field's default), so every engine path — predicates,
        group keys, projections — works unchanged."""
        from .dictionary import Dictionary

        for name in schema.column_names():
            if name in self.metadata.columns:
                continue
            spec = schema.field_spec(name)
            if not spec.single_value:
                continue  # MV virtual columns: not needed yet
            default = spec.default_null_value
            dt = spec.data_type
            n = self.num_docs
            meta = ColumnMetadata(
                name=name, data_type=dt.value, field_type=spec.field_type.value,
                encoding="DICT", cardinality=1, bits_per_value=1,
                min_value=default, max_value=default, is_sorted=True,
                total_number_of_entries=n)
            self.metadata.columns[name] = meta
            if dt.value in ("STRING", "JSON", "BYTES"):
                values = np.asarray([default], dtype=object)
            else:
                values = np.asarray([default], dtype=dt.numpy_dtype)
            self._dictionaries[name] = Dictionary(dt, values)
            self._dict_ids[name] = np.zeros(n, dtype=np.int32)
            self._nulls[name] = None

    def backfill_indexes(self, indexing) -> list[str]:
        """Build indexes the table config requests but this segment was
        written without (reference: SegmentPreProcessor's index backfill on
        load, ImmutableSegmentLoader.java:67-101 — adding an index to the
        config takes effect on old segments without a rewrite). Built
        in-memory and cached; returns the list of indexes created."""
        from . import indexes as ix

        built = []

        def have(key):
            return self._indexes.get(key) is not None

        for col in indexing.inverted_index_columns:
            if not self.has_column(col) or have(("inv", col)):
                continue
            if self.get_inverted_index(col) is None:
                m = self.column_metadata(col)
                if m.encoding == "DICT" and m.single_value:
                    self._indexes[("inv", col)] = ix.InvertedIndex.build(
                        self.get_dict_ids(col), m.cardinality)
                    built.append(f"inverted:{col}")
        for col in indexing.range_index_columns:
            if not self.has_column(col):
                continue
            m = self.column_metadata(col)
            if m.encoding == "DICT":
                # dict range queries ride the CSR inverted index (same
                # choice the builder makes for rangeIndexColumns)
                if (m.single_value and not have(("inv", col))
                        and self.get_inverted_index(col) is None):
                    self._indexes[("inv", col)] = ix.InvertedIndex.build(
                        self.get_dict_ids(col), m.cardinality)
                    built.append(f"range(inv):{col}")
            elif not have(("rng", col)) and self.get_range_index(col) is None:
                if m.encoding == "RAW" and DataType(m.data_type).is_fixed_width:
                    self._indexes[("rng", col)] = ix.RawRangeIndex.build(
                        self.get_raw(col))
                    built.append(f"range:{col}")
        for col in indexing.bloom_filter_columns:
            if not self.has_column(col) or have(("bloom", col)):
                continue
            if self.get_bloom_filter(col) is None:
                m = self.column_metadata(col)
                values = (self.get_dictionary(col).values
                          if m.encoding == "DICT" else self.get_raw(col))
                self._indexes[("bloom", col)] = ix.BloomFilter.build(values)
                built.append(f"bloom:{col}")
        for col in indexing.json_index_columns:
            if self.has_column(col) and self.get_json_index(col) is None:
                self.get_json_index(col, or_build=True)
                built.append(f"json:{col}")
        for col in indexing.text_index_columns:
            if self.has_column(col) and self.get_text_index(col) is None:
                self.get_text_index(col, or_build=True)
                built.append(f"text:{col}")
        for col in indexing.vector_index_columns:
            if self.has_column(col) and self.get_vector_index(col) is None:
                self.get_vector_index(col, or_build=True)
                built.append(f"vector:{col}")
        for cfg in indexing.geo_index_configs:
            lat, lng = cfg.get("latColumn"), cfg.get("lngColumn")
            if lat and lng and self.has_column(lat) and self.has_column(lng) \
                    and self.get_geo_index(lat, lng) is None:
                self.get_geo_index(lat, lng, or_build=True)
                built.append(f"geo:{lat},{lng}")
        return built

    # -- identity ----------------------------------------------------------
    @property
    def name(self) -> str:
        return self.metadata.segment_name

    @property
    def num_docs(self) -> int:
        return self.metadata.num_docs

    def column_metadata(self, column: str) -> ColumnMetadata:
        return self.metadata.columns[column]

    def has_column(self, column: str) -> bool:
        return column in self.metadata.columns

    def columns(self) -> list[str]:
        return list(self.metadata.columns)

    # -- buffers -----------------------------------------------------------
    def _buffer(self, name: str) -> np.ndarray:
        entry = self.metadata.buffers[name]
        if len(entry) == 3:  # [offset, size, codec]: PTCC-compressed buffer
            if name not in self._decompressed:
                from .compression import decompress_buffer

                off, size, _codec = entry
                self._decompressed[name] = np.frombuffer(
                    decompress_buffer(self._data[off:off + size]),
                    dtype=np.uint8)
            return self._decompressed[name]
        off, size = entry
        return self._data[off : off + size]

    def buffer_array(self, name: str) -> np.ndarray:
        """Raw uint8 view of a stored buffer (custom index SPI surface)."""
        return self._buffer(name)

    def get_custom_index(self, column: str, type_name: str):
        """Lazily deserialize a custom index built through the index SPI
        (segment/index_spi.py); None if this segment carries none."""
        key = ("custom", column, type_name)
        if key not in self._indexes:
            from .index_spi import load_custom_index

            self._indexes[key] = load_custom_index(self, column, type_name)
        return self._indexes[key]

    def get_map_index(self, column: str):
        """Dense per-key planes for a MAP column (segment/map_index.py);
        None when this segment has no map index for the column."""
        return self.get_custom_index(column, "map")

    def get_dictionary(self, column: str) -> Dictionary:
        if column not in self._dictionaries:
            m = self.column_metadata(column)
            assert m.encoding == "DICT", f"{column} has no dictionary"
            raw = bytes(self._buffer(f"{column}.dict"))
            self._dictionaries[column] = deserialize_dictionary(raw, DataType(m.data_type), m.cardinality)
        return self._dictionaries[column]

    def get_dict_ids(self, column: str) -> np.ndarray:
        """Decoded int32 dict-id plane (SV) or flat MV dict-id stream."""
        if column not in self._dict_ids:
            m = self.column_metadata(column)
            assert m.encoding == "DICT"
            count = m.total_number_of_entries
            self._dict_ids[column] = bitpack.unpack(self._buffer(f"{column}.fwd"), m.bits_per_value, count)
        return self._dict_ids[column]

    def get_mv_offsets(self, column: str) -> np.ndarray:
        if column not in self._mv_offsets:
            self._mv_offsets[column] = np.frombuffer(
                self._buffer(f"{column}.mvoff"), dtype=np.uint32, count=self.num_docs + 1
            ).astype(np.int64)
        return self._mv_offsets[column]

    def get_mv_dict_id_matrix(self, column: str) -> np.ndarray:
        """(num_docs, max_mv) int32 matrix padded with `cardinality` sentinel.

        The pad id is out of dictionary range so every predicate evaluates
        false on pad slots; device MV predicates reduce with any() across the
        MV axis.
        """
        m = self.column_metadata(column)
        ids = self.get_dict_ids(column)
        offsets = self.get_mv_offsets(column)
        max_mv = max(1, m.max_number_of_multi_values)
        out = np.full((self.num_docs, max_mv), m.cardinality, dtype=np.int32)
        lens = np.diff(offsets)
        col_idx = np.arange(max_mv)[None, :]
        mask = col_idx < lens[:, None]
        out[mask] = ids
        return out

    def get_raw(self, column: str) -> np.ndarray:
        if column not in self._raw:
            m = self.column_metadata(column)
            if m.encoding == "CLP":
                # log-structured column: decode templates + variables back
                # to the exact original strings (reference CLP forward
                # index reader), cached like any other raw plane
                from .clp import deserialize_clp

                col = deserialize_clp(bytes(self._buffer(f"{column}.fwd")))
                self._raw[column] = col.decode_all()
                return self._raw[column]
            assert m.encoding == "RAW"
            dtype = DataType(m.data_type)
            if not dtype.is_fixed_width:
                # var-byte raw column: value stream + u64 offsets
                # (reference VarByteChunkForwardIndexReaderV4)
                blob = self._buffer(f"{column}.fwd").tobytes()
                offs = np.frombuffer(self._buffer(f"{column}.voff"),
                                     dtype=np.uint64, count=self.num_docs + 1)
                out = np.empty(self.num_docs, dtype=object)
                decode = dtype.value != "BYTES"
                for i in range(self.num_docs):
                    piece = blob[int(offs[i]):int(offs[i + 1])]
                    out[i] = piece.decode("utf-8") if decode else piece
                self._raw[column] = out
            else:
                self._raw[column] = np.frombuffer(
                    self._buffer(f"{column}.fwd"),
                    dtype=dtype.numpy_dtype, count=self.num_docs)
        return self._raw[column]

    def get_null_bitmap(self, column: str) -> Optional[np.ndarray]:
        """Boolean null vector, or None when the column has no nulls
        (reference NullValueVectorReaderImpl)."""
        if column not in self._nulls:
            m = self.column_metadata(column)
            if not m.has_nulls:
                self._nulls[column] = None
            else:
                self._nulls[column] = bitpack.unpack_bitmap(self._buffer(f"{column}.nulls"), self.num_docs)
        return self._nulls[column]

    # -- auxiliary indexes (segment/indexes.py) -----------------------------
    def _has_buffer(self, name: str) -> bool:
        return name in self.metadata.buffers

    def get_inverted_index(self, column: str):
        """CSR inverted index if built, else None (reference
        BitmapInvertedIndexReader; doubles as the dict range index here)."""
        key = ("inv", column)
        if key not in self._indexes:
            if self._has_buffer(f"{column}.inv.off"):
                from .indexes import deserialize_inverted

                self._indexes[key] = deserialize_inverted(
                    np.frombuffer(self._buffer(f"{column}.inv.off"), dtype=np.uint32),
                    np.frombuffer(self._buffer(f"{column}.inv.docs"), dtype=np.uint32),
                )
            else:
                self._indexes[key] = None
        return self._indexes[key]

    def get_sorted_index(self, column: str):
        """Derived sorted index for sorted dict columns (no stored buffer —
        reference SortedIndexReader reads the forward index directly)."""
        key = ("sorted", column)
        if key not in self._indexes:
            m = self.column_metadata(column)
            if m.encoding == "DICT" and m.single_value and m.is_sorted:
                from .indexes import SortedIndex

                self._indexes[key] = SortedIndex.build(
                    self.get_dict_ids(column), m.cardinality)
            else:
                self._indexes[key] = None
        return self._indexes[key]

    def get_range_index(self, column: str):
        """Raw-column range index (sorted values + permutation), else None."""
        key = ("rng", column)
        if key not in self._indexes:
            if self._has_buffer(f"{column}.rng.perm"):
                from .indexes import RawRangeIndex

                m = self.column_metadata(column)
                dt = DataType(m.data_type).numpy_dtype
                self._indexes[key] = RawRangeIndex(
                    np.frombuffer(self._buffer(f"{column}.rng.sorted"), dtype=dt),
                    np.frombuffer(self._buffer(f"{column}.rng.perm"), dtype=np.uint32),
                )
            else:
                self._indexes[key] = None
        return self._indexes[key]

    def get_bloom_filter(self, column: str):
        key = ("bloom", column)
        if key not in self._indexes:
            if self._has_buffer(f"{column}.bloom.hdr"):
                from .indexes import deserialize_bloom

                self._indexes[key] = deserialize_bloom(
                    np.frombuffer(self._buffer(f"{column}.bloom.hdr"), dtype=np.int64),
                    np.frombuffer(self._buffer(f"{column}.bloom.bits"), dtype=np.uint8),
                )
            else:
                self._indexes[key] = None
        return self._indexes[key]

    def get_json_index(self, column: str, or_build: bool = False):
        """Persisted JSON index, or (or_build=True) a transient one built
        from column values and cached — so repeated JSON_MATCH queries on an
        unindexed column parse the JSON corpus once, not per query."""
        key = ("json", column)
        if key not in self._indexes:
            if self._has_buffer(f"{column}.json.keys.names"):
                from .indexes import deserialize_json_index

                bufs = {
                    suffix: np.frombuffer(self._buffer(f"{column}.{suffix}"), dtype=np.uint8)
                    for suffix in (
                        "json.keys.names", "json.keys.off", "json.keys.docs",
                        "json.paths.names", "json.paths.off", "json.paths.docs",
                    )
                }
                self._indexes[key] = deserialize_json_index(bufs)
            else:
                self._indexes[key] = None
        if self._indexes[key] is None and or_build:
            from .indexes import JsonIndex

            self._indexes[key] = JsonIndex.build(self.get_values(column))
        return self._indexes[key]

    def get_text_index(self, column: str, or_build: bool = False):
        key = ("text", column)
        if key not in self._indexes:
            if self._has_buffer(f"{column}.text.terms"):
                from .indexes import deserialize_text_index

                bufs = {s: np.frombuffer(self._buffer(f"{column}.text.{s2}"),
                                         dtype=np.uint8)
                        for s, s2 in (("text.terms", "terms"), ("text.off", "off"),
                                      ("text.docs", "docs"), ("text.pos", "pos"))}
                self._indexes[key] = deserialize_text_index(bufs)
            else:
                self._indexes[key] = None
        if self._indexes[key] is None and or_build:
            from .indexes import TextIndex

            self._indexes[key] = TextIndex.build(self.get_values(column))
        return self._indexes[key]

    def get_vector_index(self, column: str, or_build: bool = False):
        key = ("vector", column)
        if key not in self._indexes:
            if self._has_buffer(f"{column}.vec.hdr"):
                from .indexes import deserialize_vector_index

                bufs = {s: np.frombuffer(self._buffer(f"{column}.{s}"),
                                         dtype=np.uint8)
                        for s in ("vec.hdr", "vec.data")}
                for opt in ("vec.centroids", "vec.assign"):
                    if self._has_buffer(f"{column}.{opt}"):
                        bufs[opt] = np.frombuffer(
                            self._buffer(f"{column}.{opt}"), dtype=np.uint8)
                self._indexes[key] = deserialize_vector_index(bufs)
            else:
                self._indexes[key] = None
        if self._indexes[key] is None and or_build:
            from .indexes import VectorIndex

            vecs = np.stack([np.asarray(v, dtype=np.float32)
                             for v in self.get_mv_values(column)])
            self._indexes[key] = VectorIndex.build(vecs)
        return self._indexes[key]

    def get_geo_index(self, lat_col: str, lng_col: str, or_build: bool = False):
        key = ("geo", lat_col, lng_col)
        pair = f"{lat_col}__{lng_col}"
        if key not in self._indexes:
            if self._has_buffer(f"{pair}.geo.hdr"):
                from .indexes import deserialize_geo_index

                bufs = {s: np.frombuffer(self._buffer(f"{pair}.{s}"), dtype=np.uint8)
                        for s in ("geo.hdr", "geo.cells", "geo.off", "geo.docs")}
                self._indexes[key] = deserialize_geo_index(bufs)
            else:
                self._indexes[key] = None
        if self._indexes[key] is None and or_build:
            from .indexes import GeoGridIndex

            self._indexes[key] = GeoGridIndex.build(
                np.asarray(self.get_values(lat_col), dtype=np.float64),
                np.asarray(self.get_values(lng_col), dtype=np.float64))
        return self._indexes[key]

    def star_trees(self):
        """Loaded StarTreeViews (pre-aggregated pseudo-segments), cached."""
        key = ("startree", "*")
        if key not in self._indexes:
            from .startree import StarTreeView

            self._indexes[key] = [
                StarTreeView(self, m) for m in self.metadata.star_trees]
        return self._indexes[key]

    # -- materialized values (host path / test oracle) ---------------------
    def get_values(self, column: str) -> np.ndarray:
        """Fully materialized value array (SV) — used by the CPU oracle path."""
        m = self.column_metadata(column)
        if m.encoding in ("RAW", "CLP"):
            return self.get_raw(column)
        if not m.single_value:
            raise ValueError(f"{column} is MV; use get_mv_values")
        return self.get_dictionary(column).take(self.get_dict_ids(column))

    def get_mv_values(self, column: str) -> list[np.ndarray]:
        d = self.get_dictionary(column)
        ids = self.get_dict_ids(column)
        offsets = self.get_mv_offsets(column)
        return [d.take(ids[offsets[i] : offsets[i + 1]]) for i in range(self.num_docs)]

    def read_cell(self, column: str, doc_id: int):
        """Single-cell point read (partial upsert reads the previous row
        version at ingestion rate; decoded id planes are cached, so this is
        O(1) after the first read of a column)."""
        m = self.column_metadata(column)
        if m.encoding in ("RAW", "CLP"):
            v = self.get_raw(column)[doc_id]
            return v.item() if isinstance(v, np.generic) else v
        d = self.get_dictionary(column)
        if m.single_value:
            return d.get(int(self.get_dict_ids(column)[doc_id]))
        offsets = self.get_mv_offsets(column)
        ids = self.get_dict_ids(column)[offsets[doc_id]:offsets[doc_id + 1]]
        return [d.get(int(i)) for i in ids]

    def destroy(self) -> None:
        """Release all decoded planes and the data.bin mapping.

        The segment is unusable afterwards (reference
        ImmutableSegmentImpl.destroy semantics — called on segment drop)."""
        self._dict_ids.clear()
        self._raw.clear()
        self._dictionaries.clear()
        self._decompressed.clear()
        self._nulls.clear()
        self._mv_offsets.clear()
        self._data = None


def load_segment(directory: str | Path,
                 verify: Optional[bool] = None,
                 expected_crc: Optional[str] = None) -> ImmutableSegment:
    """Load (and by default verify) a segment directory. ``verify=None``
    follows PINOT_TPU_VERIFY_CRC (default on); verification happens ONCE
    here — load/reload time — never per query.

    ``expected_crc`` cross-checks the loaded segment against the crc the
    catalog (/SEGMENTS metadata) advertises: a tiered-storage cold fetch
    that pulls a stale or swapped deep-store copy fails here instead of
    silently serving different bytes than the catalog promised."""
    seg = ImmutableSegment(directory)
    if verify if verify is not None else verify_enabled():
        seg.verify_integrity()
    if expected_crc is not None and seg.metadata.crc is not None \
            and str(expected_crc) != str(seg.metadata.crc):
        raise SegmentIntegrityError(
            seg.metadata.segment_name, directory,
            f"crc {seg.metadata.crc} does not match catalog crc "
            f"{expected_crc}")
    return seg
