"""Map index: dense per-key planes for MAP-typed columns.

Reference analogue: the map index
(pinot-segment-spi/.../index/StandardIndexes.java:89-146 MAP_ID;
pinot-segment-local/.../segment/index/map/MapIndexType.java and
ImmutableMapIndexReader) — a MAP column's frequent keys are stored as
dense per-key forward columns so ``mapCol['key']`` never walks per-row
map entries.

TPU-first redesign: each dense key becomes a flat float64 value plane plus
a presence plane — exactly the whole-segment column layout every other
plane uses, so an indexed key is filterable with plain vector algebra (and
HBM-residable like any column plane). Non-numeric or rare keys fall back
to the row-wise ``mapvalue`` transform (query/transforms.py), matching the
reference's dynamically-typed fallback reader.

The column itself stores maps as JSON strings (or dict objects on the
mutable path) — the same object-column representation the JSON index uses.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass

import numpy as np

from .index_spi import IndexType, register_index_type

DEFAULT_MAX_KEYS = 64


def _parse_map(x):
    if isinstance(x, dict):
        return x
    if isinstance(x, (str, bytes)):
        try:
            obj = json.loads(x)
            return obj if isinstance(obj, dict) else None
        except (json.JSONDecodeError, TypeError):
            return None
    return None


@dataclass
class MapIndex:
    """Dense planes for the indexed keys of one MAP column."""

    dense_keys: list[str]
    values: dict[str, np.ndarray]  # key → (n,) float64 (0 where absent)
    present: dict[str, np.ndarray]  # key → (n,) bool

    @staticmethod
    def build(col_values, config: dict | None = None) -> "MapIndex":
        config = config or {}
        n = len(col_values)
        maps = [_parse_map(x) for x in col_values]
        wanted = config.get("denseKeys")
        if wanted is None:
            freq: Counter = Counter()
            for m in maps:
                if m:
                    freq.update(m.keys())
            max_keys = int(config.get("maxKeys", DEFAULT_MAX_KEYS))
            # deterministic: by descending frequency then name
            wanted = [k for k, _ in sorted(
                freq.items(), key=lambda kv: (-kv[1], kv[0]))[:max_keys]]
        values: dict[str, np.ndarray] = {}
        present: dict[str, np.ndarray] = {}
        for key in wanted:
            v = np.zeros(n, dtype=np.float64)
            pr = np.zeros(n, dtype=bool)
            for i, m in enumerate(maps):
                if not m or key not in m:
                    continue
                x = m[key]
                if isinstance(x, bool):
                    v[i] = float(x)
                elif isinstance(x, (int, float)) and np.isfinite(x):
                    v[i] = float(x)
                else:
                    continue  # non-numeric value: not densifiable
                pr[i] = True
            values[key] = v
            present[key] = pr
        return MapIndex(list(wanted), values, present)

    def has_key(self, key: str) -> bool:
        return key in self.values

    def value_plane(self, key: str):
        """(values float64, present bool) — absent rows carry 0/False."""
        return self.values[key], self.present[key]

    # -- persistence (index SPI buffers) ----------------------------------
    def serialize(self):
        out = [("meta", np.frombuffer(
            json.dumps(self.dense_keys).encode("utf-8"), dtype=np.uint8))]
        for i, key in enumerate(self.dense_keys):
            out.append((f"v{i}", self.values[key]))
            out.append((f"p{i}", self.present[key].astype(np.uint8)))
        return out

    @staticmethod
    def deserialize(bufs: dict) -> "MapIndex":
        keys = json.loads(bytes(bufs["meta"]).decode("utf-8"))
        # stored buffers surface as raw uint8 (index SPI contract): view
        # the value planes back as float64, presence as one byte per doc
        values = {k: np.frombuffer(np.asarray(bufs[f"v{i}"]).tobytes(),
                                   dtype=np.float64)
                  for i, k in enumerate(keys)}
        present = {k: np.asarray(bufs[f"p{i}"]).astype(bool)
                   for i, k in enumerate(keys)}
        return MapIndex(keys, values, present)


register_index_type(IndexType(
    name="map",
    build=lambda values, cfg: MapIndex.build(values, cfg),
    serialize=lambda idx: idx.serialize(),
    deserialize=MapIndex.deserialize,
))


def map_value_args(expr):
    """(column, key, default|None) when ``expr`` is mapvalue(col, 'key') /
    item(col, 'key') with literal key — else None. Shared by both engines'
    predicate fast paths."""
    if not getattr(expr, "is_function", False):
        return None
    fn = expr.function
    if fn.name not in ("mapvalue", "item", "map_value"):
        return None
    args = fn.arguments
    if len(args) < 2 or not args[0].is_identifier or not args[1].is_literal:
        return None
    default = None
    if len(args) > 2 and args[2].is_literal:
        default = args[2].literal
    return args[0].identifier, str(args[1].literal), default
