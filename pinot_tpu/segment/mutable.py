"""Mutable (consuming) segment: in-memory columnar store built row-at-a-time.

Reference: MutableSegmentImpl (pinot-segment-local/.../indexsegment/mutable/
MutableSegmentImpl.java:126, index():515) + the realtime mutable dictionary /
forward index impls (.../realtime/impl/). Design differences, TPU-first:

- Columns are append-only python/numpy buffers on host. Consuming segments
  execute on the HOST engine (duck-typing the ImmutableSegment read API);
  the device executes committed (immutable, sorted-dictionary) segments —
  mirroring how the reference's realtime segments are slower scan-heavy
  segments until conversion.
- Mutable dictionaries are insertion-ordered (no sorted invariant), so the
  planner refuses mutable segments (``is_mutable``) and the auto backend
  falls back to host; on commit RealtimeSegmentConverter re-encodes with
  sorted dictionaries for full device execution.
- Readers see a consistent prefix: ``index()`` appends then publishes the new
  row count last (single-writer, many-reader snapshot isolation — same
  guarantee MutableSegmentImpl gives via its volatile numDocsIndexed).
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from typing import Any, Optional

import numpy as np

from ..spi.data_types import DataType, FieldSpec, Schema, coerce_value
from .format import ColumnMetadata

_NUMERIC_NP = {
    DataType.INT: np.int32,
    DataType.LONG: np.int64,
    DataType.FLOAT: np.float32,
    DataType.DOUBLE: np.float64,
    DataType.BOOLEAN: np.int8,
    DataType.TIMESTAMP: np.int64,
}


class MutableDictionary:
    """Insertion-ordered value↔id map (reference realtime mutable
    dictionaries). ``values`` materializes for host predicate evaluation."""

    def __init__(self):
        self._index: dict = {}
        self._values: list = []

    def index_of(self, value) -> int:
        return self._index.get(value, -1)

    def upsert(self, value) -> int:
        did = self._index.get(value)
        if did is None:
            did = len(self._values)
            self._index[value] = did
            self._values.append(value)
        return did

    def get(self, dict_id: int):
        return self._values[dict_id]

    @property
    def values(self) -> np.ndarray:
        return np.asarray(self._values)

    def __len__(self) -> int:
        return len(self._values)


class SnapshotDictionary:
    """Read-only view of a MutableDictionary pinned at a cardinality.

    The live dictionary is insertion-ordered and append-only, so its first
    ``card`` entries never change — pinning the cardinality makes every
    lookup deterministic for one snapshot even while ingestion keeps
    inserting new values. Values indexed after the pin report -1 (absent),
    which is consistent: no row inside the snapshot prefix can reference
    them."""

    def __init__(self, live: MutableDictionary, card: int):
        self._live = live
        self._card = card

    def index_of(self, value) -> int:
        did = self._live.index_of(value)
        return did if 0 <= did < self._card else -1

    def get(self, dict_id: int):
        return self._live.get(dict_id)

    @property
    def values(self) -> np.ndarray:
        return np.asarray(self._live._values[: self._card])

    def __len__(self) -> int:
        return self._card


class _MutableColumn:
    def __init__(self, spec: FieldSpec):
        self.spec = spec
        self.single_value = spec.single_value
        dt = DataType(spec.data_type)
        self.data_type = dt
        # dimensions dict-encode (strings MUST); metrics store raw
        self.dict_encoded = spec.field_type.value != "METRIC" or not dt.is_numeric
        self.dictionary = MutableDictionary() if self.dict_encoded else None
        self.dict_ids: list = []      # SV dict ids | raw values
        self.mv_ids: list = []        # MV rows: list[list]
        self.null_docs: list[int] = []
        self.min_value = None
        self.max_value = None
        self.total_values = 0
        self.max_mv = 0

    def _observe(self, v):
        if self.min_value is None or v < self.min_value:
            self.min_value = v
        if self.max_value is None or v > self.max_value:
            self.max_value = v

    def add(self, value, doc_id: int):
        if value is None:
            self.null_docs.append(doc_id)
            value = (list(self.spec.default_null_value)
                     if not self.single_value and isinstance(
                         self.spec.default_null_value, (list, tuple))
                     else self.spec.default_null_value)
            if not self.single_value and not isinstance(value, (list, tuple)):
                value = [value]
        if self.single_value:
            value = self._coerce(value)
            self._observe(value)
            self.total_values += 1
            if self.dict_encoded:
                self.dict_ids.append(self.dictionary.upsert(value))
            else:
                self.dict_ids.append(value)
        else:
            vals = [self._coerce(v) for v in (value if isinstance(value, (list, tuple, np.ndarray)) else [value])]
            for v in vals:
                self._observe(v)
            self.total_values += len(vals)
            self.max_mv = max(self.max_mv, len(vals))
            if self.dict_encoded:
                self.mv_ids.append([self.dictionary.upsert(v) for v in vals])
            else:
                self.mv_ids.append(vals)

    def _coerce(self, v):
        return coerce_value(v, self.data_type)

    def metadata(self, num_docs: int) -> ColumnMetadata:
        card = len(self.dictionary) if self.dict_encoded else 0
        return ColumnMetadata(
            name=self.spec.name,
            data_type=self.data_type.value,
            field_type=self.spec.field_type.value,
            encoding="DICT" if self.dict_encoded else "RAW",
            single_value=self.single_value,
            cardinality=card,
            min_value=self.min_value,
            max_value=self.max_value,
            is_sorted=False,
            has_nulls=bool(self.null_docs),
            total_number_of_entries=self.total_values,
            max_number_of_multi_values=self.max_mv,
        )

    def values_snapshot(self, n: int) -> np.ndarray:
        if not self.single_value:
            raise ValueError(f"{self.spec.name} is MV")
        if self.dict_encoded:
            vals = self.dictionary.values
            ids = np.asarray(self.dict_ids[:n], dtype=np.int64)
            if len(vals) == 0:
                return np.empty(0, dtype=object)
            return vals[ids]
        dtype = _NUMERIC_NP.get(self.data_type, object)
        return np.asarray(self.dict_ids[:n], dtype=dtype)

    def mv_snapshot(self, n: int) -> list[np.ndarray]:
        if self.dict_encoded:
            vals = self.dictionary.values
            return [np.asarray([vals[i] for i in row]) for row in self.mv_ids[:n]]
        return [np.asarray(row) for row in self.mv_ids[:n]]

    # -- device-plane delta reads (realtime/device_plane.py) ---------------
    # Rows below any published num_docs are immutable, so slicing [a, b)
    # with b <= num_docs is race-free against the consumer thread.

    def ids_slice(self, a: int, b: int) -> np.ndarray:
        """SV dict-id rows [a, b) as int32 (unpacked device ids plane)."""
        return np.asarray(self.dict_ids[a:b], dtype=np.int32)

    def raw_slice(self, a: int, b: int) -> np.ndarray:
        """Raw (non-dict) SV metric rows [a, b) at the column's np dtype."""
        dtype = _NUMERIC_NP.get(self.data_type)
        if dtype is None:
            raise ValueError(f"{self.spec.name}: non-numeric raw plane")
        return np.asarray(self.dict_ids[a:b], dtype=dtype)

    def null_slice(self, a: int, b: int) -> np.ndarray:
        """Null bitmap for rows [a, b). null_docs is monotonic (appended in
        doc order) so a bisected window is exact."""
        out = np.zeros(b - a, dtype=bool)
        nd = self.null_docs
        lo = bisect.bisect_left(nd, a)
        hi = bisect.bisect_left(nd, b)
        for d in nd[lo:hi]:
            out[d - a] = True
        return out

    def dict_values_numeric(self, a: int, b: int) -> np.ndarray:
        """Dictionary values [a, b) at the column's np dtype — delta feed
        for the device dict-values plane (append-only, stable prefix)."""
        dtype = _NUMERIC_NP.get(self.data_type)
        if dtype is None:
            raise ValueError(f"{self.spec.name}: non-numeric dict plane")
        return np.asarray(self.dictionary._values[a:b], dtype=dtype)


class MutableSegment:
    """Duck-types the ImmutableSegment read API (segment/loader.py) over
    append-only buffers; queried by the host engine while consuming."""

    is_mutable = True
    valid_doc_ids = None  # upsert validity plane (upsert/manager.py)

    def __init__(self, schema: Schema, segment_name: str):
        self.schema = schema
        self.segment_name = segment_name
        self._columns: dict[str, _MutableColumn] = {
            name: _MutableColumn(spec) for name, spec in schema.fields.items()}
        self._num_docs = 0
        self._lock = threading.Lock()
        self.creation_time_ms = int(time.time() * 1000)

    # -- write path --------------------------------------------------------
    def index(self, row: dict) -> int:
        """Add one transformed row; returns its doc id (reference
        MutableSegmentImpl.index:515 — single consumer thread)."""
        doc_id = self._num_docs
        for name, col in self._columns.items():
            col.add(row.get(name), doc_id)
        # publish AFTER the row is fully written (reader snapshot isolation)
        self._num_docs = doc_id + 1
        return doc_id

    # -- read API (ImmutableSegment duck type) -----------------------------
    @property
    def name(self) -> str:
        return self.segment_name

    @property
    def num_docs(self) -> int:
        return self._num_docs

    def columns(self) -> list[str]:
        return list(self._columns)

    def has_column(self, column: str) -> bool:
        return column in self._columns

    def column_metadata(self, column: str) -> ColumnMetadata:
        return self._columns[column].metadata(self._num_docs)

    def column(self, name: str) -> _MutableColumn:
        """Raw column buffer access for the realtime device-plane reader."""
        return self._columns[name]

    def get_dictionary(self, column: str) -> MutableDictionary:
        return self._columns[column].dictionary

    def get_values(self, column: str) -> np.ndarray:
        return self._columns[column].values_snapshot(self._num_docs)

    def get_mv_values(self, column: str) -> list[np.ndarray]:
        return self._columns[column].mv_snapshot(self._num_docs)

    def read_cell(self, column: str, doc_id: int):
        """Single-cell point read without materializing the column (partial
        upsert reads the previous row version at ingestion rate)."""
        col = self._columns[column]
        if not col.single_value:
            row = col.mv_ids[doc_id]
            if col.dict_encoded:
                return [col.dictionary.get(i) for i in row]
            return list(row)
        v = col.dict_ids[doc_id]
        return col.dictionary.get(v) if col.dict_encoded else v

    def get_null_bitmap(self, column: str) -> Optional[np.ndarray]:
        col = self._columns[column]
        if not col.null_docs:
            return None
        m = np.zeros(self._num_docs, dtype=bool)
        docs = [d for d in col.null_docs if d < self._num_docs]
        m[docs] = True
        return m

    # consuming segments carry no persisted indexes — host engine scans
    def get_inverted_index(self, column: str):
        return None

    def get_sorted_index(self, column: str):
        return None

    def get_range_index(self, column: str):
        return None

    def get_bloom_filter(self, column: str):
        return None

    def get_json_index(self, column: str, or_build: bool = False):
        return None

    def get_text_index(self, column: str, or_build: bool = False):
        return None

    def get_vector_index(self, column: str, or_build: bool = False):
        return None

    def get_geo_index(self, lat_col: str, lng_col: str, or_build: bool = False):
        return None

    @property
    def star_trees(self):
        return []

    # -- conversion support ------------------------------------------------
    def to_columns(self) -> dict[str, list]:
        """Column-major snapshot for RealtimeSegmentConverter → SegmentBuilder."""
        n = self._num_docs
        out: dict[str, Any] = {}
        for name, col in self._columns.items():
            if col.single_value:
                vals: list = list(col.values_snapshot(n))
            else:
                vals = [list(r) for r in col.mv_snapshot(n)]
            # restore None so the builder re-derives the null vector
            for d in col.null_docs:
                if d < n:
                    vals[d] = None
            out[name] = vals
        return out

    def null_docs(self) -> dict[str, list[int]]:
        return {name: [d for d in col.null_docs if d < self._num_docs]
                for name, col in self._columns.items() if col.null_docs}

    def destroy(self) -> None:
        self._columns.clear()
        self._num_docs = 0

    def snapshot_view(self) -> "MutableSegmentView":
        """Pin the row count for one query: every column reads the same
        prefix even while the consumer thread keeps appending (reference:
        MutableSegmentImpl readers bound by numDocsIndexed at acquire)."""
        return MutableSegmentView(self)


class _PinnedValidity:
    """Immutable upsert-validity snapshot, duck-typing ValidDocIds reads so
    the host filter and the device mask param see the exact same bits."""

    def __init__(self, mask: np.ndarray):
        self._mask = mask

    def mask(self, n: int) -> np.ndarray:
        out = np.zeros(n, dtype=bool)
        m = min(n, len(self._mask))
        out[:m] = self._mask[:m]
        return out

    def num_valid(self, n: Optional[int] = None) -> int:
        m = self._mask if n is None else self._mask[:n]
        return int(m.sum())


class MutableSegmentView:
    """Read-only consistent-prefix view over a MutableSegment.

    Beyond the row count, the view pins everything a query plan can
    observe: per-column dictionary cardinality (SnapshotDictionary), the
    upsert validity plane, and column metadata — so the host path, the
    device plane path, and every cache key derived from this view agree on
    one immutable snapshot identified by ``snapshot_generation``."""

    is_mutable = True

    def __init__(self, segment: MutableSegment):
        self._seg = segment
        self._n = segment._num_docs
        vd = segment.valid_doc_ids
        if vd is not None and hasattr(vd, "snapshot"):
            mask, ugen = vd.snapshot(self._n)
            self._valid: Optional[_PinnedValidity] = _PinnedValidity(mask)
            self._upsert_gen = ugen
        else:
            self._valid = None
            self._upsert_gen = 0
        # card read AFTER _num_docs: every dict id in the prefix is < card
        self._cards = {
            name: (len(col.dictionary) if col.dict_encoded else 0)
            for name, col in segment._columns.items()}
        self._dicts: dict[str, Optional[SnapshotDictionary]] = {}
        self._meta: dict[str, ColumnMetadata] = {}

    @property
    def valid_doc_ids(self):
        return self._valid

    @property
    def snapshot_generation(self) -> tuple:
        """Stable identity of this snapshot's contents: the row prefix plus
        the upsert validity generation. Two views with equal generations
        answer every query identically."""
        return (self._n, self._upsert_gen)

    def pinned_cardinality(self, column: str) -> int:
        return self._cards[column]

    def read_cell(self, column: str, doc_id: int):
        return self._seg.read_cell(column, doc_id)

    @property
    def name(self) -> str:
        return self._seg.segment_name

    @property
    def schema(self):
        return self._seg.schema

    @property
    def num_docs(self) -> int:
        return self._n

    def columns(self) -> list[str]:
        return self._seg.columns()

    def has_column(self, column: str) -> bool:
        return self._seg.has_column(column)

    def column_metadata(self, column: str) -> ColumnMetadata:
        md = self._meta.get(column)
        if md is None:
            col = self._seg._columns[column]
            md = col.metadata(self._n)
            if col.dict_encoded:
                md = dataclasses.replace(md, cardinality=self._cards[column])
            self._meta[column] = md
        return md

    def get_dictionary(self, column: str):
        if column not in self._dicts:
            live = self._seg._columns[column].dictionary
            self._dicts[column] = (
                SnapshotDictionary(live, self._cards[column])
                if live is not None else None)
        return self._dicts[column]

    def get_values(self, column: str) -> np.ndarray:
        return self._seg._columns[column].values_snapshot(self._n)

    def get_mv_values(self, column: str) -> list[np.ndarray]:
        return self._seg._columns[column].mv_snapshot(self._n)

    def get_null_bitmap(self, column: str) -> Optional[np.ndarray]:
        col = self._seg._columns[column]
        if not col.null_docs:
            return None
        m = np.zeros(self._n, dtype=bool)
        m[[d for d in col.null_docs if d < self._n]] = True
        return m

    def get_inverted_index(self, column: str):
        return None

    def get_sorted_index(self, column: str):
        return None

    def get_range_index(self, column: str):
        return None

    def get_bloom_filter(self, column: str):
        return None

    def get_json_index(self, column: str, or_build: bool = False):
        return None

    def get_text_index(self, column: str, or_build: bool = False):
        return None

    def get_vector_index(self, column: str, or_build: bool = False):
        return None

    def get_geo_index(self, lat_col: str, lng_col: str, or_build: bool = False):
        return None

    @property
    def star_trees(self):
        return []
