"""ctypes bridge to the native host library (native/pinot_native.cpp).

Builds the shared library on first use with g++ -O3 (cached beside the
source); every entry point degrades to the numpy implementation when the
toolchain or library is unavailable, so the native layer is a pure
accelerator. The reference's equivalent machinery is the hand-unrolled
Java in SURVEY.md §2.9 (FixedBitIntReader etc.).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).resolve().parents[2] / "native" / "pinot_native.cpp"
_SO = _SRC.with_suffix(".so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             str(_SRC), "-o", str(_SO)],
            check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None when unavailable.
    Set PINOT_TPU_DISABLE_NATIVE=1 to force the numpy paths."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PINOT_TPU_DISABLE_NATIVE"):
            return None
        if not _SRC.exists():
            return None
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError:
            return None
        u8 = ctypes.POINTER(ctypes.c_uint8)
        i32 = ctypes.POINTER(ctypes.c_int32)
        i64 = ctypes.POINTER(ctypes.c_int64)
        f64 = ctypes.POINTER(ctypes.c_double)
        u32 = ctypes.POINTER(ctypes.c_uint32)
        lib.unpack_bits.argtypes = [u8, ctypes.c_int, ctypes.c_int64, i32,
                                    ctypes.c_int]
        lib.pack_bits.argtypes = [u32, ctypes.c_int64, ctypes.c_int, u8]
        lib.pack_bitmap.argtypes = [u8, ctypes.c_int64, u8]
        lib.unpack_bitmap.argtypes = [u8, ctypes.c_int64, u8]
        lib.factorize_i64.argtypes = [i64, ctypes.c_int64, i64, i64]
        lib.factorize_i64.restype = ctypes.c_int64
        lib.group_agg_f64.argtypes = [i64, f64, ctypes.c_int64,
                                      ctypes.c_int64, f64, i64, f64, f64]
        for fn in ("lz4_compress", "lz4_decompress",
                   "snappy_compress", "snappy_decompress"):
            f = getattr(lib, fn)
            f.argtypes = [u8, ctypes.c_int64, u8, ctypes.c_int64]
            f.restype = ctypes.c_int64
        _lib = lib
        return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def unpack_bits(data: np.ndarray, num_bits: int, count: int,
                dtype=np.int32) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None or count == 0:
        return None
    data = np.ascontiguousarray(data, dtype=np.uint8)
    # the fast path reads an 8-byte window at the last value's byte offset
    needed = (count * num_bits + 7) // 8
    padded = 1 if len(data) >= needed + 8 else 0
    out = np.empty(count, dtype=np.int32)
    lib.unpack_bits(_ptr(data, ctypes.c_uint8), num_bits, count,
                    _ptr(out, ctypes.c_int32), padded)
    if dtype == np.int32:
        return out
    if num_bits == 32:
        # full-width values are unsigned in the bitstream: widen without
        # sign extension (matches the numpy path's uint32 view)
        return out.view(np.uint32).astype(dtype)
    return out.astype(dtype)


def pack_bits(values: np.ndarray, num_bits: int) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.uint32)
    n = len(values)
    out = np.zeros((n * num_bits + 7) // 8, dtype=np.uint8)
    lib.pack_bits(_ptr(values, ctypes.c_uint32), n, num_bits,
                  _ptr(out, ctypes.c_uint8))
    return out


def unpack_bitmap(data: np.ndarray, count: int) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.uint8)
    out = np.empty(count, dtype=np.uint8)
    lib.unpack_bitmap(_ptr(data, ctypes.c_uint8), count,
                      _ptr(out, ctypes.c_uint8))
    return out.view(bool)


def factorize_i64(keys: np.ndarray):
    """(codes, uniques) in first-occurrence order, or None without the lib."""
    lib = get_lib()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    n = len(keys)
    codes = np.empty(n, dtype=np.int64)
    uniques = np.empty(n, dtype=np.int64)
    num = lib.factorize_i64(_ptr(keys, ctypes.c_int64), n,
                            _ptr(codes, ctypes.c_int64),
                            _ptr(uniques, ctypes.c_int64))
    return codes, uniques[:num]


def group_agg_f64(codes: np.ndarray, vals: np.ndarray, num_groups: int):
    """(sums, counts, mins, maxs) per group, or None without the lib."""
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.int64)
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    sums = np.empty(num_groups, dtype=np.float64)
    counts = np.empty(num_groups, dtype=np.int64)
    mins = np.empty(num_groups, dtype=np.float64)
    maxs = np.empty(num_groups, dtype=np.float64)
    lib.group_agg_f64(_ptr(codes, ctypes.c_int64), _ptr(vals, ctypes.c_double),
                      len(codes), num_groups, _ptr(sums, ctypes.c_double),
                      _ptr(counts, ctypes.c_int64), _ptr(mins, ctypes.c_double),
                      _ptr(maxs, ctypes.c_double))
    return sums, counts, mins, maxs


def _codec_call(fn_name: str, src: bytes, dst_cap: int) -> Optional[bytes]:
    lib = get_lib()
    if lib is None:
        return None
    src_arr = np.frombuffer(src, dtype=np.uint8) if src else np.empty(0, np.uint8)
    src_arr = np.ascontiguousarray(src_arr)
    dst = np.empty(max(1, dst_cap), dtype=np.uint8)
    n = getattr(lib, fn_name)(_ptr(src_arr, ctypes.c_uint8), len(src),
                              _ptr(dst, ctypes.c_uint8), dst_cap)
    if n < 0:
        raise ValueError(f"{fn_name}: corrupt or oversized stream")
    return dst[:n].tobytes()


def lz4_compress(data: bytes) -> Optional[bytes]:
    return _codec_call("lz4_compress", data, len(data) + len(data) // 255 + 16)


def lz4_decompress(blob: bytes, raw_size: int) -> Optional[bytes]:
    return _codec_call("lz4_decompress", blob, raw_size)


def snappy_compress(data: bytes) -> Optional[bytes]:
    return _codec_call("snappy_compress", data, 32 + len(data) + len(data) // 6)


def snappy_decompress(blob: bytes, raw_size: int) -> Optional[bytes]:
    return _codec_call("snappy_decompress", blob, raw_size)
